"""Declarative training-health rules over rolling metric windows.

The PS receives one MetricUpdate per job epoch (control/ps.py
`_h_metrics`); this module keeps a bounded rolling window of those
updates per job and evaluates a small, declarative rule set into a
verdict the `GET /health?id=` endpoint serves and `kubeml top` renders:

    {"id": ..., "state": "healthy|warning|critical|unknown",
     "reasons": [{"rule": ..., "severity": ..., "detail": ...}, ...],
     "latest": {...last epoch's stats...}}

Rules look only at the window — no wall clock reads inside checks — so
tests drive them with a fake clock (`HealthEvaluator(clock=...)`), the
same determinism discipline as ps._scan_heartbeats(now).

Rule set (thresholds chosen for the repo's CPU-scale models; all
overridable per-evaluator):

  worker_divergence  critical  the non-finite guard dropped or
                               quarantined workers this epoch — the
                               alert-layer annotation over the existing
                               quarantine counters (fired by faults.py
                               nan plans in tier-1 tests)
  grad_explosion     critical  a worker's RMS grad norm exceeds the
                               absolute ceiling, or blew up relative to
                               the window median (shape of divergence
                               even at small scale)
  loss_divergence    warning   cross-worker loss spread is large
                               relative to the train loss — workers are
                               no longer fitting the same function
  update_stall       warning   every worker's update/param ratio has
                               been ~0 for several epochs — the
                               optimizer stopped moving (lr underflow,
                               frozen params, dead schedule)
  straggler          warning   the slowest round dispatch is many times
                               the epoch median (faults.py slow plans)
"""

from __future__ import annotations

import dataclasses
import statistics
import time
from typing import Any, Callable, Dict, List, Optional

# verdict states, ordered by severity (prom.HEALTH_STATES mirrors this)
STATES = ("healthy", "warning", "critical", "unknown")


@dataclasses.dataclass
class HealthRule:
    """One declarative check over a job's metric window.

    `check(window)` sees the job's sample list (oldest first; each a
    dict of the MetricUpdate's health fields) and returns a
    human-readable detail string when firing, else None."""

    name: str
    severity: str              # 'warning' | 'critical'
    description: str
    check: Callable[[List[dict]], Optional[str]]


def _latest(window: List[dict]) -> dict:
    return window[-1] if window else {}


def _rule_worker_divergence(window: List[dict]) -> Optional[str]:
    m = _latest(window)
    dropped = float(m.get("dropped_workers", 0.0))
    quarantined = int(m.get("quarantined_workers", 0))
    if dropped > 0 or quarantined > 0:
        return (f"non-finite guard dropped {dropped:g} worker update(s), "
                f"{quarantined} worker(s) quarantined in the last epoch")
    return None


def _make_grad_explosion(abs_limit: float, rel_limit: float):
    def check(window: List[dict]) -> Optional[str]:
        m = _latest(window)
        norms = [float(x) for x in m.get("grad_norms", []) if x > 0]
        if not norms:
            return None
        worst = max(norms)
        if worst > abs_limit:
            return (f"grad norm {worst:.3g} exceeds the absolute limit "
                    f"{abs_limit:g}")
        history = [max((float(x) for x in s.get("grad_norms", [])
                        if x > 0), default=0.0) for s in window[:-1]]
        history = [h for h in history if h > 0]
        if len(history) >= 2:
            base = statistics.median(history)
            if base > 0 and worst > rel_limit * base:
                return (f"grad norm {worst:.3g} is {worst / base:.0f}x "
                        f"the window median {base:.3g}")
        return None
    return check


def _make_loss_divergence(rel_limit: float):
    def check(window: List[dict]) -> Optional[str]:
        m = _latest(window)
        spread = float(m.get("loss_spread", 0.0))
        loss = abs(float(m.get("train_loss", 0.0)))
        if spread > rel_limit * max(loss, 1e-6):
            return (f"cross-worker loss spread {spread:.3g} vs train "
                    f"loss {loss:.3g} — workers are diverging")
        return None
    return check


def _make_update_stall(ratio_floor: float, min_epochs: int):
    def check(window: List[dict]) -> Optional[str]:
        if len(window) < min_epochs:
            return None
        recent = window[-min_epochs:]
        for s in recent:
            ratios = [float(x) for x in s.get("update_ratios", [])]
            if not ratios or max(ratios) >= ratio_floor:
                return None
        return (f"update/param ratio below {ratio_floor:g} on every "
                f"worker for {min_epochs} epochs — optimizer stalled")
    return check


def _make_straggler(rel_limit: float, min_rounds: int):
    def check(window: List[dict]) -> Optional[str]:
        m = _latest(window)
        times = [float(t) for t in
                 (m.get("phase_times") or {}).get("dispatch", [])]
        if len(times) < min_rounds:
            return None
        med = statistics.median(times)
        worst = max(times)
        if med > 0 and worst > rel_limit * med:
            return (f"slowest round dispatch {worst:.3g}s is "
                    f"{worst / med:.0f}x the epoch median {med:.3g}s")
        return None
    return check


def _make_serve_saturation():
    """Serving plane (serve/service.py snapshots): warn when admission
    starts costing clients — the queue is at cap, or requests were shed
    with 429 since the previous snapshot. Training samples carry none
    of the serve_* fields, so this never fires for them."""
    def check(window: List[dict]) -> Optional[str]:
        m = _latest(window)
        cap = m.get("serve_queue_cap")
        if cap is None:
            return None
        depth = float(m.get("serve_queue_depth", 0.0))
        rej = float(m.get("serve_rejected_total", 0.0))
        prev = next((s for s in reversed(window[:-1])
                     if s.get("serve_queue_cap") is not None), None)
        rej_prev = float(prev.get("serve_rejected_total", 0.0)) \
            if prev else 0.0
        if rej > rej_prev:
            return (f"{rej - rej_prev:g} request(s) shed with 429 since "
                    f"the last snapshot (queue {depth:g}/{cap:g})")
        if float(cap) > 0 and depth >= float(cap):
            return (f"admission queue full ({depth:g}/{cap:g}); the next "
                    f"request will be shed")
        return None
    return check


def _make_queue_starvation(wait_limit_s: float):
    """Cluster allocator (control/cluster.py snapshots under the
    `cluster` pseudo job id): warn when a parked job has waited past
    the limit — either aging is disabled/too slow, or quotas have
    wedged the queue behind a full pool. Training/serving samples
    carry no cluster_* fields, so this never fires for them."""
    def check(window: List[dict]) -> Optional[str]:
        m = _latest(window)
        lanes = m.get("cluster_pool_lanes")
        if lanes is None:
            return None
        depth = float(m.get("cluster_queue_depth", 0.0))
        wait = float(m.get("cluster_oldest_wait_s", 0.0))
        if depth > 0 and wait > wait_limit_s:
            in_use = float(m.get("cluster_lanes_in_use", 0.0))
            return (f"oldest parked job has waited {wait:.0f}s "
                    f"(> {wait_limit_s:g}s) with {depth:g} job(s) "
                    f"queued and {in_use:g}/{float(lanes):g} lanes "
                    f"leased — queue is starving")
        return None
    return check


def _make_data_staleness(lag_limit: int):
    """Continual plane (train/job.py sliding-window passes): warn when
    the dataset registry is more than `lag_limit` generations ahead of
    what the job has trained — appends are outrunning training, so the
    served model is drifting stale. Non-continual jobs publish
    data_lag_generations = -1 (the wire default) and older samples omit
    the field entirely, so this never fires for them."""
    def check(window: List[dict]) -> Optional[str]:
        m = _latest(window)
        lag = m.get("data_lag_generations")
        if lag is None or int(lag) < 0:
            return None
        if int(lag) > lag_limit:
            return (f"dataset registry is {int(lag)} generation(s) ahead "
                    f"of the last trained generation "
                    f"{int(m.get('dataset_generation', 0))} "
                    f"(limit {lag_limit}) — training is falling behind "
                    f"appends")
        return None
    return check


def _make_serve_crash_loop(restart_limit: int = 2):
    """Serving plane: critical when the supervisor rebuilt the engine
    `restart_limit`+ times within the sample window — one restart is
    recovery working, repeated restarts are a crash loop (a fault the
    supervisor keeps resurrecting into). Delta across the window, like
    serve_saturation's 429 accounting, so old restarts age out."""
    def check(window: List[dict]) -> Optional[str]:
        m = _latest(window)
        latest = m.get("serve_engine_restarts")
        if latest is None:
            return None
        first = next((s.get("serve_engine_restarts") for s in window
                      if s.get("serve_engine_restarts") is not None),
                     None)
        delta = float(latest) - float(first if first is not None else 0)
        if delta >= restart_limit:
            return (f"serving engine restarted {delta:g} time(s) within "
                    f"the sample window (limit {restart_limit}) — the "
                    f"supervisor is crash-looping")
        return None
    return check


def _make_fleet_degraded():
    """Serving fleet: warn when the supervisor ejected a replica within
    the sample window — the fleet is serving, but degraded: a pool
    member died or crash-looped, its streams were live-migrated, and a
    probation replica is earning its vnodes back. Delta across the
    window like serve_crash_loop, so old ejections age out; solo-serve
    samples carry no fleet_* fields and never fire this."""
    def check(window: List[dict]) -> Optional[str]:
        m = _latest(window)
        latest = m.get("fleet_ejections_total")
        if latest is None:
            return None
        first = next((s.get("fleet_ejections_total") for s in window
                      if s.get("fleet_ejections_total") is not None),
                     None)
        delta = float(latest) - float(first if first is not None else 0)
        if delta >= 1:
            migrated = m.get("fleet_migrated_streams_total", 0)
            probation = m.get("fleet_probation", 0)
            return (f"{delta:g} replica(s) ejected within the sample "
                    f"window ({float(migrated):g} stream(s) "
                    f"live-migrated, {float(probation):g} replica(s) in "
                    f"probation) — fleet is degraded")
        return None
    return check


def _make_control_flapping(recover_limit: int = 2):
    """Durable control plane (PR 17): critical when the allocator's
    journaled recovery counter climbed `recover_limit`+ times within
    the sample window — one recovery is the durability layer doing its
    job, repeated recoveries mean the control plane is crash-looping
    ("flapping") and every restart is re-running the adoption sweep.
    Delta across the window like serve_crash_loop, so a single old
    recovery ages out; non-cluster samples carry no cluster_* fields
    and never fire this."""
    def check(window: List[dict]) -> Optional[str]:
        m = _latest(window)
        latest = m.get("cluster_recoveries_total")
        if latest is None:
            return None
        first = next((s.get("cluster_recoveries_total") for s in window
                      if s.get("cluster_recoveries_total") is not None),
                     None)
        delta = float(latest) - float(first if first is not None else 0)
        if delta >= recover_limit:
            epoch = m.get("cluster_fencing_epoch", 0)
            return (f"control plane recovered {delta:g} time(s) within "
                    f"the sample window (limit {recover_limit}, fencing "
                    f"epoch now {float(epoch):g}) — the control plane "
                    f"is flapping")
        return None
    return check


def _make_serve_ttft_slo(slo_s: float):
    def check(window: List[dict]) -> Optional[str]:
        m = _latest(window)
        p99 = m.get("serve_ttft_p99")
        if p99 is None or float(p99) <= slo_s:
            return None
        return (f"p99 time-to-first-token {float(p99):.3g}s exceeds the "
                f"{slo_s:g}s SLO (p50 "
                f"{float(m.get('serve_ttft_p50', 0.0)):.3g}s)")
    return check


def _make_slo_burn():
    """Serving SLO plane (serve/slo.py): warn when BOTH burn windows
    exceed 1.0 — the multi-window rule, so a single bad tick (fast
    spike, slow still fine) never pages and a long-ago incident (slow
    elevated, fast recovered) clears. Latest-value check: the burn
    rates are already windowed by the SLO engine itself; solo-serve
    and training samples carry no serve_slo_* fields and never fire."""
    def check(window: List[dict]) -> Optional[str]:
        m = _latest(window)
        fast = m.get("serve_slo_burn_fast")
        slow = m.get("serve_slo_burn_slow")
        if fast is None or slow is None:
            return None
        if float(fast) > 1.0 and float(slow) > 1.0:
            att = m.get("serve_slo_attainment", 1.0)
            target = m.get("serve_slo_target", 0.0)
            return (f"SLO error budget burning in both windows (fast "
                    f"{float(fast):.3g}x, slow {float(slow):.3g}x): "
                    f"attainment {float(att):.4g} vs target "
                    f"{float(target):g}")
        return None
    return check


def default_rules(grad_abs: float = 1e4, grad_rel: float = 50.0,
                  spread_rel: float = 0.75, stall_floor: float = 1e-7,
                  stall_epochs: int = 3, straggler_rel: float = 5.0,
                  straggler_min_rounds: int = 4,
                  serve_ttft_slo_s: float = 2.0,
                  queue_starvation_s: float = 120.0,
                  data_lag_limit: int = 2) -> List[HealthRule]:
    return [
        HealthRule("worker_divergence", "critical",
                   "non-finite guard dropped or quarantined workers",
                   _rule_worker_divergence),
        HealthRule("grad_explosion", "critical",
                   "gradient norm exceeded absolute or relative limits",
                   _make_grad_explosion(grad_abs, grad_rel)),
        HealthRule("loss_divergence", "warning",
                   "cross-worker loss spread large vs train loss",
                   _make_loss_divergence(spread_rel)),
        HealthRule("update_stall", "warning",
                   "update/param ratio ~0 across workers for epochs",
                   _make_update_stall(stall_floor, stall_epochs)),
        HealthRule("straggler", "warning",
                   "one round dispatch far slower than the epoch median",
                   _make_straggler(straggler_rel, straggler_min_rounds)),
        HealthRule("serve_saturation", "warning",
                   "inference admission queue at cap or shedding 429s",
                   _make_serve_saturation()),
        HealthRule("serve_ttft_slo", "warning",
                   "serving p99 time-to-first-token above the SLO",
                   _make_serve_ttft_slo(serve_ttft_slo_s)),
        HealthRule("slo_burn", "warning",
                   "SLO burn rate above 1.0 in both fast and slow windows",
                   _make_slo_burn()),
        HealthRule("serve_crash_loop", "critical",
                   "serving engine restarted repeatedly in the window",
                   _make_serve_crash_loop()),
        HealthRule("fleet_degraded", "warning",
                   "fleet supervisor ejected a replica in the window",
                   _make_fleet_degraded()),
        HealthRule("queue_starvation", "warning",
                   "a cluster-parked job has waited past the limit",
                   _make_queue_starvation(queue_starvation_s)),
        HealthRule("data_staleness", "warning",
                   "continual job's trained generation lags the registry",
                   _make_data_staleness(data_lag_limit)),
        HealthRule("control_flapping", "critical",
                   "control plane recovered repeatedly in the window",
                   _make_control_flapping()),
    ]


# the MetricUpdate fields a window sample keeps (copied out so the
# evaluator never holds live wire objects)
_SAMPLE_FIELDS = ("train_loss", "validation_loss", "accuracy",
                  "parallelism", "epoch_duration", "dropped_workers",
                  "quarantined_workers", "grad_norms", "update_ratios",
                  "worker_losses", "loss_spread", "jit_compiles",
                  "hbm_peak_bytes", "hbm_in_use_bytes", "phase_times",
                  # serving-plane snapshots (serve/service.py) ride the
                  # same pipeline under the serve:<model> pseudo job id
                  "serve_active_slots", "serve_slot_cap",
                  "serve_queue_depth", "serve_queue_cap",
                  "serve_kv_page_utilization", "serve_rejected_total",
                  "serve_ttft_p50", "serve_ttft_p99",
                  # additive TTFT attribution (queue + prefill +
                  # interleave == TTFT) — `kubeml top` breakdown line
                  "serve_ttft_queue_s", "serve_ttft_prefill_s",
                  "serve_ttft_interleave_s",
                  "serve_prefill_backlog_tokens", "serve_prefix_hit_pct",
                  "serve_weight_generation", "serve_active_generations",
                  # fault-tolerance telemetry (PR 12): restarts feed the
                  # serve_crash_loop rule, the rest the top faults line
                  "serve_engine_restarts", "serve_poisoned_total",
                  "serve_deadline_total",
                  # decode bandwidth (PR 15): KV storage mode + the
                  # deterministic bytes-per-token proxy for the top
                  # "decode bw" line
                  "serve_kv_dtype", "serve_kv_bytes_per_token",
                  # decode amortization (PR 16): deterministic
                  # dispatch-count proxies for multi-step / speculative
                  # decode — the top "decode amortization" line
                  "serve_dispatches_per_token",
                  "serve_accepted_per_dispatch",
                  # SLO plane (PR 18, serve/slo.py): burn rates feed the
                  # slo_burn rule, attainment/target the top "slo" line
                  "serve_slo_target", "serve_slo_attainment",
                  "serve_slo_burn_fast", "serve_slo_burn_slow",
                  "serve_slo_good_total", "serve_slo_bad_total",
                  "serve_slo_alerts_total",
                  # serving-fleet telemetry (serve/fleet.py): replica
                  # count + router/autoscaler counters ride the merged
                  # serve:<model> sample; the per-replica prefix
                  # hit/miss deltas make routing-quality regressions
                  # visible per replica (the cache LRU is per-replica)
                  "fleet_replicas", "fleet_replicas_min",
                  "fleet_replicas_max", "fleet_draining",
                  "fleet_cold_starts_total", "fleet_spills_total",
                  "fleet_router_retries_total", "fleet_grows_total",
                  "fleet_shrinks_total", "fleet_scale_to_zero_total",
                  "fleet_replica_prefix_hits",
                  "fleet_replica_prefix_misses",
                  # fleet failure domains (PR 14): ejections feed the
                  # fleet_degraded rule, the rest the top fleet-faults
                  # line
                  "fleet_probation", "fleet_ejections_total",
                  "fleet_failovers_total",
                  "fleet_migrated_streams_total",
                  "fleet_probes_total", "fleet_hedges_total",
                  # continual-plane freshness (train/job.py sliding
                  # window); lag -1 = not a continual job
                  "dataset_generation", "data_lag_generations",
                  # cluster-allocator snapshots (control/cluster.py)
                  # ride the same pipeline under the `cluster` pseudo
                  # job id; `kubeml top --id cluster` renders them
                  "cluster_pool_lanes", "cluster_lanes_in_use",
                  "cluster_running_jobs", "cluster_serving_jobs",
                  "cluster_serving_lanes", "cluster_queue_depth",
                  "cluster_queue_by_priority", "cluster_oldest_wait_s",
                  "cluster_tenant_lanes", "cluster_tenant_quota",
                  "cluster_tenant_weight",
                  "cluster_gang_placements_total",
                  "cluster_preemptions_total",
                  "cluster_aged_grants_total",
                  "cluster_quota_clamps_total",
                  # durable control plane (PR 17): journaled recovery /
                  # fencing counters survive restarts with the journal;
                  # recoveries feed the control_flapping rule, the rest
                  # the top control line
                  "cluster_recoveries_total", "cluster_fencing_epoch",
                  "cluster_fencing_rejections_total",
                  "cluster_journal_records_total",
                  "cluster_journal_compactions_total",
                  "cluster_journal_torn_drops_total",
                  # analytic cost ledger (PR 20, metrics/ledger.py):
                  # cumulative per-program cost snapshots ride the
                  # sample so `kubeml top` can render the attributed
                  # flops/bytes per sample (train) and per token (serve)
                  "cost_programs", "serve_cost_programs")


class HealthEvaluator:
    """Per-job rolling windows + rule evaluation.

    `observe(m)` ingests a MetricUpdate (or any object with its health
    fields), re-evaluates the rules, and returns the list of NEWLY
    firing rules (deduped against the job's already-active set) so the
    PS can bump `kubeml_health_alerts_total` once per onset instead of
    once per epoch. `verdict(job_id)` returns the machine-readable
    verdict served by `GET /health?id=`.
    """

    def __init__(self, clock: Callable[[], float] = time.time,
                 window_s: float = 600.0, max_samples: int = 32,
                 rules: Optional[List[HealthRule]] = None):
        self.clock = clock
        self.window_s = window_s
        self.max_samples = max_samples
        self.rules = default_rules() if rules is None else rules
        self._windows: Dict[str, List] = {}     # job -> [(t, sample)]
        self._active: Dict[str, Dict[str, dict]] = {}  # job -> rule -> reason

    def _sample(self, m: Any) -> dict:
        s = {}
        for f in _SAMPLE_FIELDS:
            v = getattr(m, f, None) if not isinstance(m, dict) \
                else m.get(f)
            if v is not None:
                s[f] = v
        return s

    def _prune(self, entries: List, now: float) -> List:
        entries = [e for e in entries if now - e[0] <= self.window_s]
        return entries[-self.max_samples:]

    def observe(self, m: Any) -> List[dict]:
        """Ingest one epoch update; returns newly-fired reasons."""
        job_id = m["job_id"] if isinstance(m, dict) else m.job_id
        now = self.clock()
        entries = self._prune(self._windows.get(job_id, []), now)
        entries.append((now, self._sample(m)))
        self._windows[job_id] = entries
        window = [s for _, s in entries]
        firing: Dict[str, dict] = {}
        for rule in self.rules:
            detail = rule.check(window)
            if detail:
                firing[rule.name] = {"rule": rule.name,
                                     "severity": rule.severity,
                                     "detail": detail}
        previous = self._active.get(job_id, {})
        new = [r for name, r in firing.items() if name not in previous]
        self._active[job_id] = firing
        return new

    def verdict(self, job_id: str) -> dict:
        """The served health document. `state` is the worst severity of
        the currently-firing rules; a job with no samples (never
        reported, or window expired) is `unknown`."""
        now = self.clock()
        entries = self._prune(self._windows.get(job_id, []), now)
        self._windows[job_id] = entries
        if not entries:
            return {"id": job_id, "state": "unknown", "reasons": [],
                    "latest": {}}
        reasons = sorted(self._active.get(job_id, {}).values(),
                         key=lambda r: (r["severity"] != "critical",
                                        r["rule"]))
        if any(r["severity"] == "critical" for r in reasons):
            state = "critical"
        elif reasons:
            state = "warning"
        else:
            state = "healthy"
        return {"id": job_id, "state": state, "reasons": reasons,
                "latest": dict(entries[-1][1])}

    def clear(self, job_id: str) -> None:
        self._windows.pop(job_id, None)
        self._active.pop(job_id, None)
