"""Cluster allocator — gang placement, priority preemption, fair sharing.

The per-job `ThroughputBasedPolicy` (control/policy.py) sizes ONE job's
parallelism from its own epoch times, exactly as the reference KubeML
did. Under multi-tenant load many jobs contend for one shared device
mesh, so this module adds the cluster-level layer the reference never
had — in the spirit of Gandiva/DRF-style GPU-cluster schedulers, built
on the repo's preemption grace (SIGTERM → drain → round-granular
checkpoint → budget-free reschedule, docs/architecture.md):

  - gang placement: a job's N worker lanes place atomically or not at
    all — never a partially placed job. The scheduler's 503-defer path
    is reserved for true pool exhaustion; an arrival the pool cannot
    hold YET simply queues here until lanes free.
  - priority preemption: a strictly-higher-priority arrival that cannot
    place selects the cheapest-to-displace running victims (lowest
    priority, then fewest lanes, then least sunk time). The scheduler
    SIGTERMs each victim, which drains its in-flight round, checkpoints,
    and requeues WITHOUT consuming `max_restarts`.
  - weighted fair sharing with per-tenant quotas: deficit-tracked
    shares decide which tenant grows when the pool frees; a tenant at
    its quota is clamped (its jobs wait on its OWN lanes) before any
    under-quota tenant is held back. Aging raises a parked job's
    effective priority over time so sustained higher-priority load can
    never starve it.

The allocator is PURE LOGIC: no HTTP, no threads of its own, and an
injectable clock (the HealthEvaluator/_scan_heartbeats determinism
discipline), so every decision path is unit-testable and the bench.py
cluster arm can drive it with a fake clock. Decisions are explicit
`Decision` records whose `path` names one of DECISION_PATHS below;
tools/check_sched_invariants.py fails the build unless each named path
has a quoted-name test in tests/.

DURABILITY (docs/architecture.md "Control-plane durability"): attach a
DecisionJournal (control/journal.py) and every mutating operation —
submit / release / resize / regrant / fence rejection / the recovery
marker itself — is appended as an OP record (op name, args, the clock
reading it ran under, the decisions it produced, the fencing epoch)
before the decisions reach the caller. `ClusterAllocator.recover()`
replays snapshot+tail by RE-EXECUTING each op under its recorded clock
reading, so the reconstructed `snapshot()` is exactly equal to the
pre-crash state at every journaled index; a replayed op whose decisions
diverge from the journaled ones raises JournalCorruptError rather than
silently forking history. Every lane grant carries a monotone fencing
epoch: a recovered allocator bumps the epoch (`mark_recovered`), so a
stale pre-crash worker presenting an old grant is rejected with a 409
(`fence_check` → StaleGrantError) instead of double-booking lanes.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

from kubeml_tpu.api.errors import StaleGrantError
from kubeml_tpu.control.journal import DecisionJournal, JournalCorruptError

# Pseudo job id under which the scheduler feeds allocator snapshots
# through the PS health pipeline (the serve:<model> idiom), so
# `kubeml top --id cluster` renders the cluster pane from GET /health.
CLUSTER_JOB_ID = "cluster"

# Named decision paths. Every entry must be exercised by a test that
# names it (quoted, in an assertion) — tools/check_sched_invariants.py
# walks this literal and lints tests/ for coverage.
DECISION_PATHS = {
    "gang-atomicity": "a job's N lanes place atomically or not at all",
    "no-starvation": "aging raises a parked job's effective priority "
                     "until it places under sustained load",
    "quota-clamp": "an over-quota tenant is clamped before any "
                   "under-quota tenant is held back",
    "preempt-cheapest": "a higher-priority arrival displaces the "
                        "cheapest-to-displace lower-priority victims",
    "serve-elastic": "a serving-replica gang (kind='serving') grows or "
                     "shrinks elastically through the shared pool",
}

DEFAULT_TENANT = "default"
# seconds of queue wait per +1 effective priority (0 disables aging)
DEFAULT_AGING_S = 30.0


@dataclasses.dataclass(frozen=True)
class Decision:
    """One explicit allocator decision, for the scheduler to apply.

    action: 'place'   — start job_id with `lanes` workers (atomic gang)
            'queue'   — job_id stays parked until lanes free
            'preempt' — SIGTERM `victim` to make room for job_id
            'resize'  — running job_id's next-epoch width is `lanes`
    path names the DECISION_PATHS entry that drove the choice.
    epoch is the fencing epoch the grant is valid under ('place' /
    'resize' of a pool member); a worker must present it back on
    re-parallelization and is 409-rejected when it is stale."""

    action: str
    job_id: str
    lanes: int = 0
    victim: str = ""
    path: str = ""
    detail: str = ""
    epoch: int = 0


@dataclasses.dataclass
class _Pending:
    job_id: str
    tenant: str
    priority: int
    lanes: int          # requested gang size (clamped to the pool)
    enqueued_at: float
    kind: str = "train"  # 'train' (worker gang) | 'serving' (replicas)


@dataclasses.dataclass
class _Running:
    job_id: str
    tenant: str
    priority: int
    lanes: int
    placed_at: float
    preempting: bool = False  # victim selected; lanes free on release
    kind: str = "train"


def parse_tenant_spec(spec: str) -> Tuple[str, float, Optional[int]]:
    """Parse a CLI tenant spec ``name=weight[:quota]`` — e.g.
    ``teamA=2:4`` (weight 2, at most 4 lanes) or ``teamB=1`` (weight 1,
    quota = whole pool)."""
    name, _, rest = spec.partition("=")
    name = name.strip()
    if not name or not rest:
        raise ValueError(f"bad tenant spec {spec!r}; want name=weight[:quota]")
    weight_s, _, quota_s = rest.partition(":")
    weight = float(weight_s)
    if weight <= 0:
        raise ValueError(f"tenant {name!r}: weight must be > 0")
    quota = None
    if quota_s:
        quota = int(quota_s)
        if quota < 1:
            raise ValueError(f"tenant {name!r}: quota must be >= 1 lane")
    return name, weight, quota


class ClusterAllocator:
    """Owns the shared pool of worker lanes between the scheduler and
    the PS. All methods are synchronous, deterministic given `clock`,
    and safe to call from the scheduler loop and its HTTP handlers."""

    def __init__(self, pool_lanes: int,
                 tenant_weights: Optional[Dict[str, float]] = None,
                 tenant_quotas: Optional[Dict[str, int]] = None,
                 clock: Callable[[], float] = time.monotonic,
                 aging_s: float = DEFAULT_AGING_S,
                 journal: Optional[DecisionJournal] = None,
                 compact_every: int = 0):
        if pool_lanes < 1:
            raise ValueError("pool must have at least one lane")
        self.pool_lanes = int(pool_lanes)
        self.tenant_weights = dict(tenant_weights or {})
        self.tenant_quotas = dict(tenant_quotas or {})
        self.clock = clock
        self.aging_s = float(aging_s)
        self._running: Dict[str, _Running] = {}
        self._pending: List[_Pending] = []
        # weighted-fair deficit per tenant: accrues (weight-shared) as
        # lanes free, spends as that tenant's jobs place — the
        # tie-break among equal effective priorities, so the tenant the
        # pool has shortchanged longest grows first
        self._deficit: Dict[str, float] = {}
        # lifetime counters (cumulative; snapshot() exports them and
        # metrics/prom.py turns deltas into Prometheus counters)
        self.gang_placements = 0
        self.preemptions = 0
        self.aged_grants = 0
        self.quota_clamps = 0
        # --- durability / fencing state (journaled; survives restart)
        self.fencing_epoch = 1
        self.fencing_rejections = 0
        self.recoveries = 0
        self.journal_records = 0
        self.journal_compactions = 0
        self._grant_epochs: Dict[str, int] = {}
        self._journal = journal
        self.compact_every = int(compact_every)
        self._since_compact = 0
        # replay machinery: when set, mutators run under the RECORDED
        # clock reading instead of self.clock (exact reconstruction)
        self._replaying = False
        self._replay_now: Optional[float] = None
        self._last_now: Optional[float] = None
        self._lock = threading.Lock()

    # ------------------------------------------------------------ internals

    def _now(self) -> float:
        return self._replay_now if self._replay_now is not None \
            else self.clock()

    def _free(self) -> int:
        return self.pool_lanes - sum(r.lanes
                                     for r in self._running.values())

    def _in_use(self, tenant: str) -> int:
        return sum(r.lanes for r in self._running.values()
                   if r.tenant == tenant)

    def _quota(self, tenant: str) -> int:
        return int(self.tenant_quotas.get(tenant, self.pool_lanes))

    def _weight(self, tenant: str) -> float:
        return float(self.tenant_weights.get(tenant, 1.0))

    def _eff_priority(self, p: _Pending, now: float) -> int:
        if self.aging_s <= 0:
            return p.priority
        return p.priority + int((now - p.enqueued_at) // self.aging_s)

    def _accrue_deficit(self, freed: int) -> None:
        """Freed lanes accrue deficit to every tenant with parked work,
        split by weight — the DRR quantum. Bounded to one pool so an
        idle tenant can't bank unbounded future claim."""
        tenants = {p.tenant for p in self._pending}
        if not tenants or freed <= 0:
            return
        total_w = sum(self._weight(t) for t in tenants)
        for t in tenants:
            d = self._deficit.get(t, 0.0) \
                + freed * self._weight(t) / total_w
            self._deficit[t] = min(d, float(self.pool_lanes))

    def _grants(self, now: float) -> List[Decision]:
        """Head-of-line gang placement over the parked queue, ordered by
        effective priority (aging included), then tenant deficit, then
        FIFO. A quota-blocked head is SKIPPED (it waits on its own
        tenant's lanes, and must not hold back under-quota tenants —
        the quota-clamp ordering); a size-blocked head HOLDS the line
        (no backfill behind it, so a wide gang is never starved by a
        stream of narrow ones — aging alone then guarantees it runs)."""
        decisions: List[Decision] = []
        progressed = True
        while progressed and self._pending:
            progressed = False
            order = sorted(
                self._pending,
                key=lambda p: (-self._eff_priority(p, now),
                               -self._deficit.get(p.tenant, 0.0),
                               p.enqueued_at, p.job_id))
            for p in order:
                room = self._quota(p.tenant) - self._in_use(p.tenant)
                if room < 1:
                    continue  # over-quota tenant: never holds the line
                # only an EXPLICIT quota clamps the gang below its ask;
                # the default quota (= the whole pool) must not, or any
                # wide gang would silently shrink to whatever is free
                lanes = min(p.lanes, room) \
                    if p.tenant in self.tenant_quotas else p.lanes
                if lanes > self._free():
                    break  # size-blocked head holds the line: no backfill
                self._pending.remove(p)
                self._running[p.job_id] = _Running(
                    p.job_id, p.tenant, p.priority, lanes, placed_at=now,
                    kind=p.kind)
                self.gang_placements += 1
                aged = self._eff_priority(p, now) > p.priority
                clamped = lanes < p.lanes
                if aged:
                    self.aged_grants += 1
                if clamped:
                    self.quota_clamps += 1
                self._deficit[p.tenant] = \
                    self._deficit.get(p.tenant, 0.0) - lanes
                self._grant_epochs[p.job_id] = self.fencing_epoch
                if aged:
                    path = "no-starvation"
                    detail = (f"placed after aging to effective priority "
                              f"{self._eff_priority(p, now)} "
                              f"(base {p.priority})")
                elif clamped:
                    path = "quota-clamp"
                    detail = (f"gang clamped {p.lanes}->{lanes}: tenant "
                              f"{p.tenant} quota "
                              f"{self._quota(p.tenant)} lanes")
                else:
                    path = "gang-atomicity"
                    detail = f"all {lanes} lanes placed atomically"
                decisions.append(Decision("place", p.job_id, lanes=lanes,
                                          path=path, detail=detail,
                                          epoch=self.fencing_epoch))
                progressed = True
                break  # state changed: re-rank before the next grant
        return decisions

    def _preempt_for(self, p: _Pending, now: float) -> List[Decision]:
        """Greedy cheapest-first victim selection for a parked arrival
        that outranks running work: candidates are strictly-lower-RAW-
        priority running jobs (aging confers ordering, never the right
        to displace), cheapest = lowest priority, then fewest lanes,
        then least sunk time. Victims only marked — their lanes free
        when the drained process actually exits and release() runs."""
        # as in _grants: only an EXPLICIT quota bounds how many lanes
        # the arrival may claim — the default quota equals the pool and
        # would otherwise collapse `need` to 1 whenever the pool is
        # full, displacing too few victims to ever seat the gang
        if p.tenant in self.tenant_quotas:
            need = min(p.lanes,
                       max(1, self._quota(p.tenant)
                           - self._in_use(p.tenant)))
        else:
            need = p.lanes
        avail = self._free() + sum(r.lanes
                                   for r in self._running.values()
                                   if r.preempting)
        if need <= avail:
            return []  # enough already freeing; wait for release()
        cands = sorted(
            (r for r in self._running.values()
             if not r.preempting and r.priority < p.priority),
            key=lambda r: (r.priority, r.lanes, -r.placed_at, r.job_id))
        victims: List[_Running] = []
        for r in cands:
            if avail >= need:
                break
            victims.append(r)
            avail += r.lanes
        if avail < need:
            return []  # even preempting every candidate won't fit: wait
        decisions = []
        for v in victims:
            v.preempting = True
            self.preemptions += 1
            decisions.append(Decision(
                "preempt", p.job_id, victim=v.job_id,
                path="preempt-cheapest",
                detail=(f"priority {p.priority} arrival needs {need} "
                        f"lane(s); displacing {v.job_id} (priority "
                        f"{v.priority}, {v.lanes} lane(s))")))
        return decisions

    # ----------------------------------------------------------- durability

    def _record(self, op: str, args: dict, now: float,
                decisions: List[Decision]) -> None:
        """Journal one completed op (no-op without a journal; during
        replay the disk write is skipped but the counters advance
        identically, so replayed state matches recorded state). Called
        with the lock held, AFTER the op mutated state — the decisions
        are already final when the frame hits disk."""
        self._last_now = now
        if self._journal is None:
            return
        self.journal_records += 1
        if self._replaying:
            return
        self._journal.append({
            "op": op, "args": args, "now": now,
            "epoch": self.fencing_epoch,
            "decisions": [dataclasses.asdict(d) for d in decisions],
        })
        self._since_compact += 1
        if self.compact_every and self._since_compact >= self.compact_every:
            self._since_compact = 0
            self.journal_compactions += 1
            self._journal.compact(self._state_dict())

    def _state_dict(self) -> dict:
        """Complete dynamic state, deterministically ordered, for the
        compaction snapshot. Pool/tenant CONFIG is included for
        recovery-time validation, not restored (config belongs to the
        deployment, not the journal)."""
        return {
            "pool_lanes": self.pool_lanes,
            "tenant_weights": {t: self.tenant_weights[t]
                               for t in sorted(self.tenant_weights)},
            "tenant_quotas": {t: self.tenant_quotas[t]
                              for t in sorted(self.tenant_quotas)},
            "aging_s": self.aging_s,
            "running": [dataclasses.asdict(self._running[j])
                        for j in sorted(self._running)],
            "pending": [dataclasses.asdict(p) for p in self._pending],
            "deficit": {t: self._deficit[t]
                        for t in sorted(self._deficit)},
            "counters": {
                "gang_placements": self.gang_placements,
                "preemptions": self.preemptions,
                "aged_grants": self.aged_grants,
                "quota_clamps": self.quota_clamps,
                "fencing_rejections": self.fencing_rejections,
                "recoveries": self.recoveries,
                "journal_records": self.journal_records,
                "journal_compactions": self.journal_compactions,
            },
            "fencing_epoch": self.fencing_epoch,
            "grant_epochs": {j: self._grant_epochs[j]
                             for j in sorted(self._grant_epochs)},
            "last_now": self._last_now,
        }

    def _restore_state(self, state: dict) -> None:
        if int(state["pool_lanes"]) != self.pool_lanes:
            raise ValueError(
                f"journal snapshot was taken under pool_lanes="
                f"{state['pool_lanes']}, recovering allocator has "
                f"{self.pool_lanes}; refusing to mix incarnations")
        self._running = {r["job_id"]: _Running(**r)
                         for r in state["running"]}
        self._pending = [_Pending(**p) for p in state["pending"]]
        self._deficit = dict(state["deficit"])
        c = state["counters"]
        self.gang_placements = int(c["gang_placements"])
        self.preemptions = int(c["preemptions"])
        self.aged_grants = int(c["aged_grants"])
        self.quota_clamps = int(c["quota_clamps"])
        self.fencing_rejections = int(c["fencing_rejections"])
        self.recoveries = int(c["recoveries"])
        self.journal_records = int(c["journal_records"])
        self.journal_compactions = int(c["journal_compactions"])
        self.fencing_epoch = int(state["fencing_epoch"])
        self._grant_epochs = {j: int(e)
                              for j, e in state["grant_epochs"].items()}
        self._last_now = state["last_now"]

    def _apply_record(self, rec: dict) -> None:
        """Re-execute one journaled op under its recorded clock reading
        and verify it reproduces the journaled decisions — divergence
        means the journal and the code disagree about history, which
        must never be papered over."""
        self._replay_now = float(rec["now"])
        op, args = rec["op"], rec["args"]
        try:
            if op == "submit":
                got = self.submit(**args)
            elif op == "release":
                got = self.release(**args)
            elif op == "resize":
                got = self.resize(**args)
            elif op == "regrant":
                self.regrant(**args)
                got = []
            elif op == "fence_reject":
                try:
                    self.fence_check(**args)
                except StaleGrantError:
                    pass
                got = []
            elif op == "recover":
                self.mark_recovered(**args)
                got = []
            else:
                raise JournalCorruptError(
                    f"journal record {rec.get('i')}: unknown op {op!r}")
        finally:
            self._replay_now = None
        want = rec.get("decisions", [])
        if [dataclasses.asdict(d) for d in got] != want:
            raise JournalCorruptError(
                f"journal record {rec.get('i')} ({op}) replayed to "
                f"different decisions than were journaled — refusing "
                f"to fork history")

    @classmethod
    def recover(cls, journal: DecisionJournal, pool_lanes: int,
                tenant_weights: Optional[Dict[str, float]] = None,
                tenant_quotas: Optional[Dict[str, int]] = None,
                clock: Callable[[], float] = time.monotonic,
                aging_s: float = DEFAULT_AGING_S,
                compact_every: int = 0) -> "ClusterAllocator":
        """Reconstruct an allocator from its journal: restore the
        compaction snapshot, then re-execute the tail ops under their
        recorded clock readings. The result's `snapshot()` equals the
        pre-crash allocator's at the last durable index — the
        crash-at-every-index sweep in tests/test_control_durability.py
        asserts exactly that. Call `mark_recovered()` afterwards to
        bump the fencing epoch (kept separate so the sweep can compare
        the PURE reconstruction first)."""
        state, tail = journal.replay()
        alloc = cls(pool_lanes, tenant_weights, tenant_quotas,
                    clock=clock, aging_s=aging_s, journal=journal,
                    compact_every=compact_every)
        if state is not None:
            alloc._restore_state(state)
        alloc._replaying = True
        try:
            for rec in tail:
                alloc._apply_record(rec)
        finally:
            alloc._replaying = False
        return alloc

    def mark_recovered(self, delta: Optional[float] = None) -> int:
        """The recovered control plane is live again: bump the fencing
        epoch (all pre-crash grants become stale) and rebase the queue/
        placement timestamps onto this incarnation's clock, preserving
        each job's accrued age (the old process's monotonic readings
        are meaningless here). Journaled as its own op so a second
        crash replays the bump too. Returns the new epoch."""
        with self._lock:
            now = self._now()
            if delta is None:
                delta = 0.0 if self._last_now is None \
                    else now - self._last_now
            for p in self._pending:
                p.enqueued_at += delta
            for r in self._running.values():
                r.placed_at += delta
            self.fencing_epoch += 1
            self.recoveries += 1
            self._record("recover", {"delta": delta}, now, [])
            return self.fencing_epoch

    def fence_check(self, job_id: str, epoch: int) -> None:
        """Validate a worker's grant epoch. A mismatch (or a grant the
        allocator no longer holds) is the split-brain signature — a
        worker from a previous control-plane incarnation whose lanes
        may have been given away. Rejections are journaled (they bump a
        counter that must survive restart) and raise StaleGrantError
        (409)."""
        with self._lock:
            current = self._grant_epochs.get(job_id, 0)
            if int(epoch) == current and current > 0:
                return
            now = self._now()
            self.fencing_rejections += 1
            self._record("fence_reject",
                         {"job_id": job_id, "epoch": int(epoch)}, now, [])
            raise StaleGrantError(job_id, int(epoch), current)

    def regrant(self, job_id: str) -> Optional[Tuple[int, int]]:
        """Re-adopt a surviving pre-crash job: stamp its grant with the
        CURRENT fencing epoch at its journaled width. Returns (lanes,
        epoch), or None when the job is not a running pool member (the
        scheduler then requeues it instead)."""
        with self._lock:
            rec = self._running.get(job_id)
            if rec is None:
                return None
            now = self._now()
            self._grant_epochs[job_id] = self.fencing_epoch
            self._record("regrant", {"job_id": job_id}, now, [])
            return rec.lanes, self.fencing_epoch

    def grant_epoch(self, job_id: str) -> int:
        """Current fencing epoch of `job_id`'s grant (0 = no grant)."""
        with self._lock:
            return self._grant_epochs.get(job_id, 0)

    def running_jobs(self) -> Dict[str, int]:
        """{job_id: lanes} of current pool members, sorted by job id —
        the scheduler's recovery sweep walks this to decide re-adopt
        vs. requeue."""
        with self._lock:
            return {j: self._running[j].lanes
                    for j in sorted(self._running)}

    def pending_jobs(self) -> List[str]:
        """Parked job ids in queue order."""
        with self._lock:
            return [p.job_id for p in self._pending]

    # -------------------------------------------------------------- surface

    def submit(self, job_id: str, tenant: str = DEFAULT_TENANT,
               priority: int = 0, lanes: int = 1,
               kind: str = "train") -> List[Decision]:
        """Admit one job's gang request. Returns the decisions to apply:
        an immediate atomic 'place', or 'queue' (possibly alongside
        'preempt' decisions naming the victims making room). `kind` is
        the gang kind: 'train' worker gangs and 'serving' replica gangs
        (serve/fleet.py via the scheduler's /serve/resize) share the
        one pool and the same placement/preemption machinery."""
        with self._lock:
            now = self._now()
            lanes = max(1, min(int(lanes), self.pool_lanes))
            tenant = tenant or DEFAULT_TENANT
            if job_id in self._running \
                    or any(p.job_id == job_id for p in self._pending):
                raise ValueError(f"job {job_id} already admitted")
            p = _Pending(job_id, tenant, int(priority), lanes,
                         enqueued_at=now, kind=str(kind))
            self._pending.append(p)
            decisions = self._grants(now)
            if any(p.job_id == job_id for p in self._pending):
                decisions += self._preempt_for(p, now)
                decisions.append(Decision(
                    "queue", job_id, lanes=lanes,
                    detail=f"parked: {self._free()} free lane(s), "
                           f"gang wants {lanes}"))
            self._record("submit",
                         {"job_id": job_id, "tenant": tenant,
                          "priority": int(priority), "lanes": lanes,
                          "kind": str(kind)}, now, decisions)
            return decisions

    def release(self, job_id: str) -> List[Decision]:
        """A job left the pool (finished, failed, or a preempted victim
        exited after its drain) or abandoned the queue. Frees its
        lanes, accrues the weighted-fair deficit, and returns any
        'place' grants the freed lanes unlock."""
        with self._lock:
            now = self._now()
            rec = self._running.pop(job_id, None)
            self._grant_epochs.pop(job_id, None)
            if rec is None:
                self._pending = [p for p in self._pending
                                 if p.job_id != job_id]
                self._record("release", {"job_id": job_id}, now, [])
                return []
            self._accrue_deficit(rec.lanes)
            decisions = self._grants(now)
            self._record("release", {"job_id": job_id}, now, decisions)
            return decisions

    def resize(self, job_id: str, requested: int) -> List[Decision]:
        """The per-job advisor (ThroughputBasedPolicy) asked for a new
        width. Shrinks always land (frees lanes → may grant parked
        work); grows are clamped by free lanes, the tenant quota, and
        parked equal-or-higher-priority work (freed lanes go to the
        queue first). First decision is always the 'resize' answer."""
        with self._lock:
            now = self._now()
            requested = max(1, int(requested))
            rec = self._running.get(job_id)
            if rec is None:
                decisions = [Decision("resize", job_id, lanes=requested,
                                      detail="job not pool-managed; "
                                             "advisor width passes "
                                             "through")]
                self._record("resize", {"job_id": job_id,
                                        "requested": requested},
                             now, decisions)
                return decisions
            quota_cap = self._quota(rec.tenant) \
                - self._in_use(rec.tenant) + rec.lanes \
                if rec.tenant in self.tenant_quotas else self.pool_lanes
            allowed = min(requested, quota_cap)
            if allowed > rec.lanes:
                grow_cap = rec.lanes + self._free()
                if any(self._eff_priority(p, now) >= rec.priority
                       for p in self._pending):
                    grow_cap = rec.lanes  # parked peers claim frees first
                allowed = min(allowed, grow_cap)
            allowed = max(1, allowed)
            path = detail = ""
            if allowed < min(requested, quota_cap):
                detail = (f"grow {rec.lanes}->{requested} clamped to "
                          f"{allowed}: free lanes/parked work")
            if quota_cap < requested:
                path = "quota-clamp"
                self.quota_clamps += 1
                detail = (f"advisor asked {requested}, tenant "
                          f"{rec.tenant} quota {self._quota(rec.tenant)} "
                          f"lane(s) allows {allowed}")
            if rec.kind == "serving" and not path:
                # the second gang kind's signature decision: a serving
                # fleet's replica count flexes through the shared pool
                path = "serve-elastic"
                if not detail:
                    detail = (f"serving gang resized {rec.lanes}->"
                              f"{allowed} lane(s) elastically")
            decisions = [Decision("resize", job_id, lanes=allowed,
                                  path=path, detail=detail,
                                  epoch=self._grant_epochs.get(job_id, 0))]
            if allowed != rec.lanes:
                freed = rec.lanes - allowed
                rec.lanes = allowed
                if freed > 0:
                    self._accrue_deficit(freed)
                    decisions += self._grants(now)
            self._record("resize", {"job_id": job_id,
                                    "requested": requested},
                         now, decisions)
            return decisions

    def running_lanes(self, job_id: str) -> Optional[int]:
        """Lanes currently held by `job_id`, or None when it is not a
        running pool member (the scheduler's /serve/resize uses this to
        pick submit-vs-resize for a serving gang)."""
        with self._lock:
            rec = self._running.get(job_id)
            return None if rec is None else rec.lanes

    # ------------------------------------------------------------ telemetry

    def snapshot(self, now: Optional[float] = None) -> dict:
        """The cluster telemetry sample: fed to the PS (POST /cluster)
        for the Prometheus gauges, and through the health pipeline
        under CLUSTER_JOB_ID for the queue-starvation rule and the
        `kubeml top` cluster pane.

        Deterministically ordered — tenants, priorities, gangs and
        counters all sort — so two allocators with equal state produce
        byte-equal JSON and the replay-equality sweep compares
        canonical forms. `now` pins the clock reading (replay-equality
        comparisons across two allocator instances)."""
        with self._lock:
            if now is None:
                now = self.clock()
            in_use = self.pool_lanes - self._free()
            by_prio: Dict[str, int] = {}
            for prio in sorted({p.priority for p in self._pending}):
                by_prio[str(prio)] = sum(1 for p in self._pending
                                         if p.priority == prio)
            tenants = sorted(set(self.tenant_weights)
                             | set(self.tenant_quotas)
                             | {r.tenant for r in self._running.values()}
                             | {p.tenant for p in self._pending})
            oldest = max((now - p.enqueued_at for p in self._pending),
                         default=0.0)
            return {
                "job_id": CLUSTER_JOB_ID,
                "cluster_pool_lanes": self.pool_lanes,
                "cluster_lanes_in_use": in_use,
                "cluster_running_jobs": len(self._running),
                "cluster_queue_depth": len(self._pending),
                "cluster_queue_by_priority": by_prio,
                "cluster_oldest_wait_s": oldest,
                "cluster_tenant_lanes": {
                    t: self._in_use(t) for t in tenants},
                "cluster_tenant_quota": {
                    t: self._quota(t) for t in tenants},
                "cluster_tenant_weight": {
                    t: self._weight(t) for t in tenants},
                "cluster_serving_jobs": sum(
                    1 for r in self._running.values()
                    if r.kind == "serving"),
                "cluster_serving_lanes": sum(
                    r.lanes for r in self._running.values()
                    if r.kind == "serving"),
                "cluster_running_gangs": [
                    {"job_id": j, "lanes": self._running[j].lanes,
                     "kind": self._running[j].kind,
                     "epoch": self._grant_epochs.get(j, 0)}
                    for j in sorted(self._running)],
                "cluster_gang_placements_total": self.gang_placements,
                "cluster_preemptions_total": self.preemptions,
                "cluster_aged_grants_total": self.aged_grants,
                "cluster_quota_clamps_total": self.quota_clamps,
                "cluster_fencing_epoch": self.fencing_epoch,
                "cluster_fencing_rejections_total":
                    self.fencing_rejections,
                "cluster_recoveries_total": self.recoveries,
                "cluster_journal_records_total": self.journal_records,
                "cluster_journal_compactions_total":
                    self.journal_compactions,
                "cluster_journal_torn_drops_total":
                    self._journal.torn_drops
                    if self._journal is not None else 0,
            }


def verify_journal_roundtrip(alloc: ClusterAllocator) -> dict:
    """Round-trip check: replay `alloc`'s journal into a twin and
    assert the twin's snapshot equals the live one at the same pinned
    clock reading. Raises JournalCorruptError on divergence, returns
    the canonical snapshot. Used by the durability tests after every
    workload and by Scheduler.recover() as a post-recovery self-check —
    a recovery that cannot reproduce itself must fail loudly, not
    serve traffic from a forked history."""
    if alloc._journal is None:
        raise ValueError("allocator has no journal to verify against")
    now = alloc.clock()
    live = alloc.snapshot(now=now)
    twin_journal = DecisionJournal(alloc._journal.dir)
    twin_journal.journal_path = alloc._journal.journal_path
    twin_journal.snapshot_path = alloc._journal.snapshot_path
    twin = ClusterAllocator.recover(
        twin_journal, alloc.pool_lanes,
        tenant_weights=alloc.tenant_weights,
        tenant_quotas=alloc.tenant_quotas,
        clock=alloc.clock, aging_s=alloc.aging_s)
    replayed = twin.snapshot(now=now)
    # torn drops are a property of each PROCESS's journal handle (what
    # it repaired at its own boot), not of the journaled history — the
    # twin reads an already-repaired file and legitimately sees zero
    for s in (live, replayed):
        s.pop("cluster_journal_torn_drops_total", None)
    if replayed != live:
        diff = {k for k in set(live) | set(replayed)
                if live.get(k) != replayed.get(k)}
        raise JournalCorruptError(
            f"journal replay diverged from live state on key(s) "
            f"{sorted(diff)}")
    return live
