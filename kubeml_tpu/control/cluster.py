"""Cluster allocator — gang placement, priority preemption, fair sharing.

The per-job `ThroughputBasedPolicy` (control/policy.py) sizes ONE job's
parallelism from its own epoch times, exactly as the reference KubeML
did. Under multi-tenant load many jobs contend for one shared device
mesh, so this module adds the cluster-level layer the reference never
had — in the spirit of Gandiva/DRF-style GPU-cluster schedulers, built
on the repo's preemption grace (SIGTERM → drain → round-granular
checkpoint → budget-free reschedule, docs/architecture.md):

  - gang placement: a job's N worker lanes place atomically or not at
    all — never a partially placed job. The scheduler's 503-defer path
    is reserved for true pool exhaustion; an arrival the pool cannot
    hold YET simply queues here until lanes free.
  - priority preemption: a strictly-higher-priority arrival that cannot
    place selects the cheapest-to-displace running victims (lowest
    priority, then fewest lanes, then least sunk time). The scheduler
    SIGTERMs each victim, which drains its in-flight round, checkpoints,
    and requeues WITHOUT consuming `max_restarts`.
  - weighted fair sharing with per-tenant quotas: deficit-tracked
    shares decide which tenant grows when the pool frees; a tenant at
    its quota is clamped (its jobs wait on its OWN lanes) before any
    under-quota tenant is held back. Aging raises a parked job's
    effective priority over time so sustained higher-priority load can
    never starve it.

The allocator is PURE LOGIC: no HTTP, no threads of its own, and an
injectable clock (the HealthEvaluator/_scan_heartbeats determinism
discipline), so every decision path is unit-testable and the bench.py
cluster arm can drive it with a fake clock. Decisions are explicit
`Decision` records whose `path` names one of DECISION_PATHS below;
tools/check_sched_invariants.py fails the build unless each named path
has a quoted-name test in tests/.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

# Pseudo job id under which the scheduler feeds allocator snapshots
# through the PS health pipeline (the serve:<model> idiom), so
# `kubeml top --id cluster` renders the cluster pane from GET /health.
CLUSTER_JOB_ID = "cluster"

# Named decision paths. Every entry must be exercised by a test that
# names it (quoted, in an assertion) — tools/check_sched_invariants.py
# walks this literal and lints tests/ for coverage.
DECISION_PATHS = {
    "gang-atomicity": "a job's N lanes place atomically or not at all",
    "no-starvation": "aging raises a parked job's effective priority "
                     "until it places under sustained load",
    "quota-clamp": "an over-quota tenant is clamped before any "
                   "under-quota tenant is held back",
    "preempt-cheapest": "a higher-priority arrival displaces the "
                        "cheapest-to-displace lower-priority victims",
    "serve-elastic": "a serving-replica gang (kind='serving') grows or "
                     "shrinks elastically through the shared pool",
}

DEFAULT_TENANT = "default"
# seconds of queue wait per +1 effective priority (0 disables aging)
DEFAULT_AGING_S = 30.0


@dataclasses.dataclass(frozen=True)
class Decision:
    """One explicit allocator decision, for the scheduler to apply.

    action: 'place'   — start job_id with `lanes` workers (atomic gang)
            'queue'   — job_id stays parked until lanes free
            'preempt' — SIGTERM `victim` to make room for job_id
            'resize'  — running job_id's next-epoch width is `lanes`
    path names the DECISION_PATHS entry that drove the choice."""

    action: str
    job_id: str
    lanes: int = 0
    victim: str = ""
    path: str = ""
    detail: str = ""


@dataclasses.dataclass
class _Pending:
    job_id: str
    tenant: str
    priority: int
    lanes: int          # requested gang size (clamped to the pool)
    enqueued_at: float
    kind: str = "train"  # 'train' (worker gang) | 'serving' (replicas)


@dataclasses.dataclass
class _Running:
    job_id: str
    tenant: str
    priority: int
    lanes: int
    placed_at: float
    preempting: bool = False  # victim selected; lanes free on release
    kind: str = "train"


def parse_tenant_spec(spec: str) -> Tuple[str, float, Optional[int]]:
    """Parse a CLI tenant spec ``name=weight[:quota]`` — e.g.
    ``teamA=2:4`` (weight 2, at most 4 lanes) or ``teamB=1`` (weight 1,
    quota = whole pool)."""
    name, _, rest = spec.partition("=")
    name = name.strip()
    if not name or not rest:
        raise ValueError(f"bad tenant spec {spec!r}; want name=weight[:quota]")
    weight_s, _, quota_s = rest.partition(":")
    weight = float(weight_s)
    if weight <= 0:
        raise ValueError(f"tenant {name!r}: weight must be > 0")
    quota = None
    if quota_s:
        quota = int(quota_s)
        if quota < 1:
            raise ValueError(f"tenant {name!r}: quota must be >= 1 lane")
    return name, weight, quota


class ClusterAllocator:
    """Owns the shared pool of worker lanes between the scheduler and
    the PS. All methods are synchronous, deterministic given `clock`,
    and safe to call from the scheduler loop and its HTTP handlers."""

    def __init__(self, pool_lanes: int,
                 tenant_weights: Optional[Dict[str, float]] = None,
                 tenant_quotas: Optional[Dict[str, int]] = None,
                 clock: Callable[[], float] = time.monotonic,
                 aging_s: float = DEFAULT_AGING_S):
        if pool_lanes < 1:
            raise ValueError("pool must have at least one lane")
        self.pool_lanes = int(pool_lanes)
        self.tenant_weights = dict(tenant_weights or {})
        self.tenant_quotas = dict(tenant_quotas or {})
        self.clock = clock
        self.aging_s = float(aging_s)
        self._running: Dict[str, _Running] = {}
        self._pending: List[_Pending] = []
        # weighted-fair deficit per tenant: accrues (weight-shared) as
        # lanes free, spends as that tenant's jobs place — the
        # tie-break among equal effective priorities, so the tenant the
        # pool has shortchanged longest grows first
        self._deficit: Dict[str, float] = {}
        # lifetime counters (cumulative; snapshot() exports them and
        # metrics/prom.py turns deltas into Prometheus counters)
        self.gang_placements = 0
        self.preemptions = 0
        self.aged_grants = 0
        self.quota_clamps = 0
        self._lock = threading.Lock()

    # ------------------------------------------------------------ internals

    def _free(self) -> int:
        return self.pool_lanes - sum(r.lanes
                                     for r in self._running.values())

    def _in_use(self, tenant: str) -> int:
        return sum(r.lanes for r in self._running.values()
                   if r.tenant == tenant)

    def _quota(self, tenant: str) -> int:
        return int(self.tenant_quotas.get(tenant, self.pool_lanes))

    def _weight(self, tenant: str) -> float:
        return float(self.tenant_weights.get(tenant, 1.0))

    def _eff_priority(self, p: _Pending, now: float) -> int:
        if self.aging_s <= 0:
            return p.priority
        return p.priority + int((now - p.enqueued_at) // self.aging_s)

    def _accrue_deficit(self, freed: int) -> None:
        """Freed lanes accrue deficit to every tenant with parked work,
        split by weight — the DRR quantum. Bounded to one pool so an
        idle tenant can't bank unbounded future claim."""
        tenants = {p.tenant for p in self._pending}
        if not tenants or freed <= 0:
            return
        total_w = sum(self._weight(t) for t in tenants)
        for t in tenants:
            d = self._deficit.get(t, 0.0) \
                + freed * self._weight(t) / total_w
            self._deficit[t] = min(d, float(self.pool_lanes))

    def _grants(self, now: float) -> List[Decision]:
        """Head-of-line gang placement over the parked queue, ordered by
        effective priority (aging included), then tenant deficit, then
        FIFO. A quota-blocked head is SKIPPED (it waits on its own
        tenant's lanes, and must not hold back under-quota tenants —
        the quota-clamp ordering); a size-blocked head HOLDS the line
        (no backfill behind it, so a wide gang is never starved by a
        stream of narrow ones — aging alone then guarantees it runs)."""
        decisions: List[Decision] = []
        progressed = True
        while progressed and self._pending:
            progressed = False
            order = sorted(
                self._pending,
                key=lambda p: (-self._eff_priority(p, now),
                               -self._deficit.get(p.tenant, 0.0),
                               p.enqueued_at, p.job_id))
            for p in order:
                room = self._quota(p.tenant) - self._in_use(p.tenant)
                if room < 1:
                    continue  # over-quota tenant: never holds the line
                # only an EXPLICIT quota clamps the gang below its ask;
                # the default quota (= the whole pool) must not, or any
                # wide gang would silently shrink to whatever is free
                lanes = min(p.lanes, room) \
                    if p.tenant in self.tenant_quotas else p.lanes
                if lanes > self._free():
                    break  # size-blocked head holds the line: no backfill
                self._pending.remove(p)
                self._running[p.job_id] = _Running(
                    p.job_id, p.tenant, p.priority, lanes, placed_at=now,
                    kind=p.kind)
                self.gang_placements += 1
                aged = self._eff_priority(p, now) > p.priority
                clamped = lanes < p.lanes
                if aged:
                    self.aged_grants += 1
                if clamped:
                    self.quota_clamps += 1
                self._deficit[p.tenant] = \
                    self._deficit.get(p.tenant, 0.0) - lanes
                if aged:
                    path = "no-starvation"
                    detail = (f"placed after aging to effective priority "
                              f"{self._eff_priority(p, now)} "
                              f"(base {p.priority})")
                elif clamped:
                    path = "quota-clamp"
                    detail = (f"gang clamped {p.lanes}->{lanes}: tenant "
                              f"{p.tenant} quota "
                              f"{self._quota(p.tenant)} lanes")
                else:
                    path = "gang-atomicity"
                    detail = f"all {lanes} lanes placed atomically"
                decisions.append(Decision("place", p.job_id, lanes=lanes,
                                          path=path, detail=detail))
                progressed = True
                break  # state changed: re-rank before the next grant
        return decisions

    def _preempt_for(self, p: _Pending, now: float) -> List[Decision]:
        """Greedy cheapest-first victim selection for a parked arrival
        that outranks running work: candidates are strictly-lower-RAW-
        priority running jobs (aging confers ordering, never the right
        to displace), cheapest = lowest priority, then fewest lanes,
        then least sunk time. Victims only marked — their lanes free
        when the drained process actually exits and release() runs."""
        # as in _grants: only an EXPLICIT quota bounds how many lanes
        # the arrival may claim — the default quota equals the pool and
        # would otherwise collapse `need` to 1 whenever the pool is
        # full, displacing too few victims to ever seat the gang
        if p.tenant in self.tenant_quotas:
            need = min(p.lanes,
                       max(1, self._quota(p.tenant)
                           - self._in_use(p.tenant)))
        else:
            need = p.lanes
        avail = self._free() + sum(r.lanes
                                   for r in self._running.values()
                                   if r.preempting)
        if need <= avail:
            return []  # enough already freeing; wait for release()
        cands = sorted(
            (r for r in self._running.values()
             if not r.preempting and r.priority < p.priority),
            key=lambda r: (r.priority, r.lanes, -r.placed_at, r.job_id))
        victims: List[_Running] = []
        for r in cands:
            if avail >= need:
                break
            victims.append(r)
            avail += r.lanes
        if avail < need:
            return []  # even preempting every candidate won't fit: wait
        decisions = []
        for v in victims:
            v.preempting = True
            self.preemptions += 1
            decisions.append(Decision(
                "preempt", p.job_id, victim=v.job_id,
                path="preempt-cheapest",
                detail=(f"priority {p.priority} arrival needs {need} "
                        f"lane(s); displacing {v.job_id} (priority "
                        f"{v.priority}, {v.lanes} lane(s))")))
        return decisions

    # -------------------------------------------------------------- surface

    def submit(self, job_id: str, tenant: str = DEFAULT_TENANT,
               priority: int = 0, lanes: int = 1,
               kind: str = "train") -> List[Decision]:
        """Admit one job's gang request. Returns the decisions to apply:
        an immediate atomic 'place', or 'queue' (possibly alongside
        'preempt' decisions naming the victims making room). `kind` is
        the gang kind: 'train' worker gangs and 'serving' replica gangs
        (serve/fleet.py via the scheduler's /serve/resize) share the
        one pool and the same placement/preemption machinery."""
        with self._lock:
            now = self.clock()
            lanes = max(1, min(int(lanes), self.pool_lanes))
            tenant = tenant or DEFAULT_TENANT
            if job_id in self._running \
                    or any(p.job_id == job_id for p in self._pending):
                raise ValueError(f"job {job_id} already admitted")
            p = _Pending(job_id, tenant, int(priority), lanes,
                         enqueued_at=now, kind=str(kind))
            self._pending.append(p)
            decisions = self._grants(now)
            if any(p.job_id == job_id for p in self._pending):
                decisions += self._preempt_for(p, now)
                decisions.append(Decision(
                    "queue", job_id, lanes=lanes,
                    detail=f"parked: {self._free()} free lane(s), "
                           f"gang wants {lanes}"))
            return decisions

    def release(self, job_id: str) -> List[Decision]:
        """A job left the pool (finished, failed, or a preempted victim
        exited after its drain) or abandoned the queue. Frees its
        lanes, accrues the weighted-fair deficit, and returns any
        'place' grants the freed lanes unlock."""
        with self._lock:
            now = self.clock()
            rec = self._running.pop(job_id, None)
            if rec is None:
                self._pending = [p for p in self._pending
                                 if p.job_id != job_id]
                return []
            self._accrue_deficit(rec.lanes)
            return self._grants(now)

    def resize(self, job_id: str, requested: int) -> List[Decision]:
        """The per-job advisor (ThroughputBasedPolicy) asked for a new
        width. Shrinks always land (frees lanes → may grant parked
        work); grows are clamped by free lanes, the tenant quota, and
        parked equal-or-higher-priority work (freed lanes go to the
        queue first). First decision is always the 'resize' answer."""
        with self._lock:
            now = self.clock()
            requested = max(1, int(requested))
            rec = self._running.get(job_id)
            if rec is None:
                return [Decision("resize", job_id, lanes=requested,
                                 detail="job not pool-managed; advisor "
                                        "width passes through")]
            quota_cap = self._quota(rec.tenant) \
                - self._in_use(rec.tenant) + rec.lanes \
                if rec.tenant in self.tenant_quotas else self.pool_lanes
            allowed = min(requested, quota_cap)
            if allowed > rec.lanes:
                grow_cap = rec.lanes + self._free()
                if any(self._eff_priority(p, now) >= rec.priority
                       for p in self._pending):
                    grow_cap = rec.lanes  # parked peers claim frees first
                allowed = min(allowed, grow_cap)
            allowed = max(1, allowed)
            path = detail = ""
            if allowed < min(requested, quota_cap):
                detail = (f"grow {rec.lanes}->{requested} clamped to "
                          f"{allowed}: free lanes/parked work")
            if quota_cap < requested:
                path = "quota-clamp"
                self.quota_clamps += 1
                detail = (f"advisor asked {requested}, tenant "
                          f"{rec.tenant} quota {self._quota(rec.tenant)} "
                          f"lane(s) allows {allowed}")
            if rec.kind == "serving" and not path:
                # the second gang kind's signature decision: a serving
                # fleet's replica count flexes through the shared pool
                path = "serve-elastic"
                if not detail:
                    detail = (f"serving gang resized {rec.lanes}->"
                              f"{allowed} lane(s) elastically")
            decisions = [Decision("resize", job_id, lanes=allowed,
                                  path=path, detail=detail)]
            if allowed != rec.lanes:
                freed = rec.lanes - allowed
                rec.lanes = allowed
                if freed > 0:
                    self._accrue_deficit(freed)
                    decisions += self._grants(now)
            return decisions

    def running_lanes(self, job_id: str) -> Optional[int]:
        """Lanes currently held by `job_id`, or None when it is not a
        running pool member (the scheduler's /serve/resize uses this to
        pick submit-vs-resize for a serving gang)."""
        with self._lock:
            rec = self._running.get(job_id)
            return None if rec is None else rec.lanes

    # ------------------------------------------------------------ telemetry

    def snapshot(self) -> dict:
        """The cluster telemetry sample: fed to the PS (POST /cluster)
        for the Prometheus gauges, and through the health pipeline
        under CLUSTER_JOB_ID for the queue-starvation rule and the
        `kubeml top` cluster pane."""
        with self._lock:
            now = self.clock()
            in_use = self.pool_lanes - self._free()
            by_prio: Dict[str, int] = {}
            for p in self._pending:
                key = str(p.priority)
                by_prio[key] = by_prio.get(key, 0) + 1
            tenants = sorted(set(self.tenant_weights)
                             | set(self.tenant_quotas)
                             | {r.tenant for r in self._running.values()}
                             | {p.tenant for p in self._pending})
            oldest = max((now - p.enqueued_at for p in self._pending),
                         default=0.0)
            return {
                "job_id": CLUSTER_JOB_ID,
                "cluster_pool_lanes": self.pool_lanes,
                "cluster_lanes_in_use": in_use,
                "cluster_running_jobs": len(self._running),
                "cluster_queue_depth": len(self._pending),
                "cluster_queue_by_priority": by_prio,
                "cluster_oldest_wait_s": oldest,
                "cluster_tenant_lanes": {
                    t: self._in_use(t) for t in tenants},
                "cluster_tenant_quota": {
                    t: self._quota(t) for t in tenants},
                "cluster_tenant_weight": {
                    t: self._weight(t) for t in tenants},
                "cluster_serving_jobs": sum(
                    1 for r in self._running.values()
                    if r.kind == "serving"),
                "cluster_serving_lanes": sum(
                    r.lanes for r in self._running.values()
                    if r.kind == "serving"),
                "cluster_gang_placements_total": self.gang_placements,
                "cluster_preemptions_total": self.preemptions,
                "cluster_aged_grants_total": self.aged_grants,
                "cluster_quota_clamps_total": self.quota_clamps,
            }
