"""Parameter Server manager — per-job lifecycle + metrics.

Parity with ml/pkg/ps/ (parameter_server.go, api.go): tracks a job index,
starts jobs, relays scheduler updates, receives metric updates and finish
signals, exports Prometheus gauges, serves the task list.

REST surface (ml/pkg/ps/api.go:335-345):
    POST   /start            start a task (body: TrainTask)
    POST   /update/{jobId}   apply a new parallelism for the next epoch
    POST   /metrics/{jobId}  metric update push (body: MetricUpdate)
    POST   /finish/{jobId}   job finished notification
    DELETE /stop/{jobId}     stop a running job
    GET    /tasks            running-task list
    GET    /metrics          Prometheus exposition (metrics.go:19)
    POST   /infer            run inference on a checkpointed model (our
                             addition: the reference scheduler invokes the
                             live function instead — scheduler/api.go:140 —
                             which only works while the job's tensors exist;
                             checkpoints fix that, SURVEY.md §3.3)

Job execution has the reference's two modes (STANDALONE_JOBS env,
ml/cmd/ml/main.go:115-133):

  - threaded (default): the job runs as a thread of this process, sharing
    the device mesh — the natural mode on a TPU host, where one process
    owns the chips (reference threaded mode, ml/pkg/ps/api.go:211-217);
  - standalone (STANDALONE_JOBS=true): one child PROCESS per job running
    `python -m kubeml_tpu.train.jobserver`, spoken to over the same
    per-job REST surface as the reference's job pod (creation + readiness
    wait + retried /start mirror ml/pkg/ps/job_pod.go:18-62 and
    ml/pkg/ps/api.go:192-207). Use when jobs should be isolated (CPU
    hosts, or TPU hosts where each job is pinned to a distinct device
    subset via JAX visible-devices env vars).
"""

from __future__ import annotations

import collections
import json
import logging
import os
import random
import shutil
import signal
import subprocess
import sys
import tempfile
import threading
import time
from typing import Dict, List, Optional

import numpy as np

from kubeml_tpu.api.errors import (InvalidArgsError, JobNotFoundError,
                                   KubeMLException)
from kubeml_tpu.api.types import MetricUpdate, TrainTask
from kubeml_tpu.control.health import HealthEvaluator
from kubeml_tpu.control.httpd import (JsonService, Raw, Request, Stream,
                                      http_json)
from kubeml_tpu.control.journal import atomic_write_json, read_json
from kubeml_tpu.data.registry import DatasetRegistry
from kubeml_tpu.metrics.ledger import attributed_from_snapshot
from kubeml_tpu.metrics.prom import MetricsRegistry
from kubeml_tpu.models.base import InferenceInputError, KubeDataset
from kubeml_tpu.parallel.distributed import CLUSTER_ENV_VARS
from kubeml_tpu.parallel.mesh import make_mesh
from kubeml_tpu.train.checkpoint import (checkpoint_saved_at,
                                         load_checkpoint)
from kubeml_tpu.train.functionlib import FunctionRegistry
from kubeml_tpu.train.history import HistoryStore
from kubeml_tpu.train.job import JobCallbacks, TrainJob
from kubeml_tpu.utils.trace import (TRACE_HEADER, TraceSink, Tracer,
                                    get_trace_context, make_trace_id,
                                    merge_job_trace)

logger = logging.getLogger("kubeml_tpu.ps")


class _InferSlot:
    __slots__ = ("arr", "event", "result", "error")

    def __init__(self, arr):
        self.arr = arr
        self.event = threading.Event()
        self.result = None
        self.error = None


class InferBatcher:
    """Micro-batches concurrent /infer requests into one device call.

    Serving depth the reference never had (its /infer is a single-shot
    function invocation — scheduler/api.go:119-162): on TPU a
    single-request stream leaves the chip idle between tiny dispatches,
    so requests that arrive within `window_s` for the same
    (model, sample-shape) group are stacked along the batch dim and
    served by ONE model.infer call, then scattered back — the classic
    leader/follower micro-batcher. The leader pays the window (a few
    ms — small against any model call) of extra latency; followers
    ride free. Stacked batches pad to the next power of two (repeating
    the last row) so jitted inference paths see a handful of bucket
    shapes instead of one program per concurrency level. Oversized
    collections are served in max_batch chunks by the same leader.

    Disable with KUBEML_INFER_BATCH=0 (requests then run unbatched)."""

    def __init__(self, window_s: float = 0.003, max_batch: int = 64,
                 timeout_s: float = 60.0):
        self.window_s = window_s
        self.max_batch = max_batch
        self.timeout_s = timeout_s
        self._lock = threading.Lock()
        self._groups: Dict[tuple, list] = {}
        self._last_arrival: Dict[tuple, float] = {}
        self._next_evict = 0.0

    def _evict_stale(self, now: float) -> None:
        """Drop `_last_arrival` entries idle past the dense-traffic
        horizon (call with `_lock` held). The detector only reads back
        8 windows, so anything older is dead weight — without eviction
        a long-lived PS serving many (model, shape) groups grows this
        dict one entry per key it ever saw, forever. Amortized: one
        sweep per ~4 horizons, not per request."""
        horizon = 8 * self.window_s
        if now < self._next_evict:
            return
        self._next_evict = now + 4 * horizon
        cutoff = now - horizon
        for key in [k for k, t in self._last_arrival.items()
                    if t < cutoff]:
            del self._last_arrival[key]

    @staticmethod
    def enabled() -> bool:
        return os.environ.get("KUBEML_INFER_BATCH", "").lower() not in (
            "0", "false", "no")

    def submit(self, key: tuple, arr, run):
        """run(stacked_batch) -> stacked predictions; returns this
        request's slice. Exceptions from the batched call propagate to
        every member."""
        slot = _InferSlot(arr)
        now = time.monotonic()
        with self._lock:
            grp = self._groups.get(key)
            leader = grp is None
            if leader:
                grp = self._groups[key] = []
            grp.append(slot)
            # dense-traffic detector: a leader only pays the collection
            # window when another request for this key arrived recently
            # (within 8 windows); sparse/single-stream traffic serves
            # immediately — no latency tax when there is nothing to
            # batch with
            dense = (now - self._last_arrival.get(key, 0.0)
                     < 8 * self.window_s)
            self._last_arrival[key] = now
            self._evict_stale(now)
        if not leader:
            # follower: the leader serves us (bounded wait: a crashed
            # leader must not hang the request forever)
            if not slot.event.wait(timeout=self.timeout_s):
                # CANCEL before giving up: our row must leave the
                # pending bucket, or a later flush of this key would
                # scatter a result into a slot nobody is waiting on
                # (and mis-align every row after ours). The group may
                # already be gone (leader popped it and is about to set
                # our event) — then removal no-ops and the result is
                # simply dropped.
                with self._lock:
                    grp = self._groups.get(key)
                    if grp is not None and slot in grp:
                        grp.remove(slot)
                        if not grp:
                            del self._groups[key]
                raise KubeMLException("batched inference timed out", 500)
            if slot.error is not None:
                raise slot.error
            return slot.result
        if dense:
            time.sleep(self.window_s)  # collection window
        with self._lock:
            collected = self._groups.pop(key)
        for i in range(0, len(collected), self.max_batch):
            batch = collected[i:i + self.max_batch]
            try:
                lens = [len(s.arr) for s in batch]
                stacked = (batch[0].arr if len(batch) == 1
                           else np.concatenate([s.arr for s in batch]))
                total = len(stacked)
                padded = 1 << (total - 1).bit_length()  # next pow2 bucket
                if padded > total:
                    stacked = np.concatenate(
                        [stacked, np.repeat(stacked[-1:], padded - total,
                                            axis=0)])
                preds = np.asarray(run(stacked))[:total]
                off = 0
                for s, n in zip(batch, lens):
                    s.result = preds[off:off + n]
                    off += n
                for s in batch:
                    s.event.set()
            except BaseException as e:
                # later chunks still get served — a bad first chunk
                # must not strand their followers in the 60 s wait
                for s in batch:
                    s.error = e
                    s.event.set()
        own = collected[0]
        if own.error is not None:
            raise own.error
        return own.result


class _JobRecord:
    """A running job: either a thread of this process (job + thread set)
    or a standalone child process (proc + url set)."""

    def __init__(self, task: TrainTask, job: Optional[TrainJob] = None,
                 thread: Optional[threading.Thread] = None,
                 proc: Optional[subprocess.Popen] = None,
                 url: Optional[str] = None):
        self.task = task
        self.job = job
        self.thread = thread
        self.proc = proc
        self.url = url
        self.partition: Optional[int] = None  # device-partition slot
        self.next_parallelism: Optional[int] = None
        self.update_event = threading.Event()
        # lifecycle counters seed from the task so an allocator requeue
        # (task handed back to the scheduler and re-/start-ed as a new
        # record) carries the job's cumulative history forward
        self.restarts = task.restarts  # crash restarts consumed
        self.restarting = False  # watchdog respawn claimed, in progress
        self.preempted = False  # child announced a graceful preemption
        self.preemptions = task.preemptions  # reschedules consumed (do
        #                       NOT count as restarts: preemption is the
        #                       platform's doing, not the job's, so it
        #                       must not eat the max_restarts crash budget)
        self.requeue_on_exit = False  # cluster-allocator preemption: on
        #                       exit, hand the task BACK to the scheduler
        #                       queue (freeing the lanes/partition) instead
        #                       of respawning in place
        self.last_heartbeat: Optional[float] = None  # monotonic stamp
        self.heartbeat_progress = (0, 0)  # (epoch, round) last reported
        # pid of a child RE-ADOPTED from a previous PS incarnation
        # (control-plane recovery): there is no Popen handle to wait()
        # on or terminate(), so preemption and the adopted watchdog go
        # through this pid instead
        self.adopted_pid: Optional[int] = None

    def push_update(self, parallelism: int,
                    grant_epoch: Optional[int] = None):
        # standalone-ness is `job is None`, NOT `proc is not None`: a
        # crash-restarting record has proc/url transiently None and must
        # answer the 503 retry signal, not silently bank the update in
        # the threaded-mode field nothing reads for it
        if self.job is None and self.url is None:
            raise KubeMLException(
                f"job {self.task.job_id} still starting", 503)
        if grant_epoch is not None:
            # a recovered scheduler re-fenced the grant: the child must
            # present the NEW epoch on its next /job ask or be 409'd
            self.task.grant_epoch = int(grant_epoch)
        if self.url is not None:
            body = {"parallelism": parallelism}
            if grant_epoch is not None:
                body["grant_epoch"] = int(grant_epoch)
            http_json("POST", f"{self.url}/update", body)
        else:
            self.next_parallelism = parallelism
            self.update_event.set()

    def request_stop(self):
        if self.url is not None:
            http_json("DELETE", f"{self.url}/stop")
        elif self.job is not None:
            self.job.stop()
        else:
            raise KubeMLException(
                f"job {self.task.job_id} still starting", 503)



class ParameterServer(JsonService):
    name = "ps"

    def __init__(self, mesh=None, port: int = 0,
                 scheduler_url: Optional[str] = None,
                 standalone_jobs: Optional[bool] = None,
                 job_env: Optional[Dict[str, str]] = None,
                 job_partitions: Optional[List[Dict[str, str]]] = None,
                 infer_cache_size: Optional[int] = None,
                 serve_slots: Optional[int] = None,
                 serve_queue_depth: Optional[int] = None,
                 serve_page_tokens: Optional[int] = None,
                 serve_hbm_budget_mb: Optional[float] = None,
                 serve_prefill_chunk: Optional[int] = None,
                 serve_kv_dtype: Optional[str] = None,
                 serve_decode_steps: Optional[int] = None,
                 serve_draft_model: Optional[str] = None,
                 serve_prefix_cache: Optional[bool] = None,
                 serve_drain_grace_s: Optional[float] = None,
                 serve_replicas_min: Optional[int] = None,
                 serve_replicas_max: Optional[int] = None,
                 serve_scale_to_zero_s: Optional[float] = None,
                 serve_replica_restart_budget: Optional[int] = None,
                 serve_probe_requests: Optional[int] = None,
                 serve_hedge_after_s: Optional[float] = None,
                 serve_slo_ttft_ms: Optional[float] = None,
                 serve_slo_tpot_ms: Optional[float] = None,
                 serve_slo_target: Optional[float] = None,
                 state_dir: Optional[str] = None):
        super().__init__(port=port)
        # Lazy mesh: in standalone mode the PARENT must not initialize the
        # accelerator backend (on TPU, libtpu is single-process-exclusive —
        # the chips belong to the job processes). The mesh is only built
        # when a threaded job actually needs it.
        self._mesh = mesh
        self.scheduler_url = scheduler_url
        if standalone_jobs is None:  # reference env toggle, main.go:115-133
            standalone_jobs = os.environ.get(
                "STANDALONE_JOBS", "").lower() in ("1", "true", "yes")
        self.standalone_jobs = standalone_jobs
        # extra env for standalone job processes (e.g. per-job TPU
        # visible-devices pinning)
        self.job_env = job_env or {}
        # device-partition slots for CONCURRENT standalone jobs: each
        # entry is an env dict pinning one job process to a device
        # subset (e.g. {"TPU_VISIBLE_DEVICES": "0,1"}). A starting job
        # leases the first free slot and holds it until its process
        # exits; with every slot busy, /start answers 503 (the
        # scheduler's queue keeps the task until capacity frees). None =
        # no partitioning, jobs share whatever the env exposes.
        self.job_partitions = job_partitions
        self._busy_partitions: set = set()
        self.jobs: Dict[str, _JobRecord] = {}
        self._jobs_lock = threading.RLock()
        self._stopping = False  # set by stop(); gates spawns/restarts
        self._infer_cache: "collections.OrderedDict" = \
            collections.OrderedDict()
        self._infer_cache_lock = threading.Lock()
        # checkpoint-LRU sizing (satellite of the serving plane): entry
        # cap via flag/env, plus a shared HBM budget — deserialized
        # checkpoints and the serving KV slabs draw from the same
        # device memory, so cached entries yield to live KV pages
        self.infer_cache_size = max(1, int(
            infer_cache_size if infer_cache_size is not None
            else os.environ.get("KUBEML_INFER_CACHE_SIZE", "4")))
        self.serve_hbm_budget_bytes = int(float(
            serve_hbm_budget_mb if serve_hbm_budget_mb is not None
            else os.environ.get("KUBEML_SERVE_HBM_BUDGET_MB", "512"))
            * (1 << 20))
        # serving-plane knobs (serve/): slot pool width, admission queue
        # cap, KV page size in tokens
        self.serve_slots = int(
            serve_slots if serve_slots is not None
            else os.environ.get("KUBEML_SERVE_SLOTS", "8"))
        self.serve_queue_depth = int(
            serve_queue_depth if serve_queue_depth is not None
            else os.environ.get("KUBEML_SERVE_QUEUE", "16"))
        self.serve_page_tokens = int(
            serve_page_tokens if serve_page_tokens is not None
            else os.environ.get("KUBEML_SERVE_PAGE_TOKENS", "16"))
        # chunked prefill + prefix cache (PR 8): prompt tokens per
        # prefill dispatch (0 = token-by-token), and whether full
        # prompt pages are shared across requests by content hash
        self.serve_prefill_chunk = int(
            serve_prefill_chunk if serve_prefill_chunk is not None
            else os.environ.get("KUBEML_SERVE_PREFILL_CHUNK", "16"))
        # decode bandwidth (PR 15): KV page storage mode — "f32" keeps
        # the model dtype (bit-identity baseline), "int8" quantizes
        # pages on write with per-page scales (engine/pager validate)
        self.serve_kv_dtype = str(
            serve_kv_dtype if serve_kv_dtype is not None
            else os.environ.get("KUBEML_SERVE_KV_DTYPE", "f32"))
        # decode latency (PR 16): K fused decode steps per dispatch
        # (1 = single-step), and an optional draft model id enabling
        # speculative decoding (engine builds the verify program)
        self.serve_decode_steps = int(
            serve_decode_steps if serve_decode_steps is not None
            else os.environ.get("KUBEML_SERVE_DECODE_STEPS", "1"))
        self.serve_draft_model = str(
            serve_draft_model if serve_draft_model is not None
            else os.environ.get("KUBEML_SERVE_DRAFT_MODEL", ""))
        if serve_prefix_cache is None:
            serve_prefix_cache = os.environ.get(
                "KUBEML_SERVE_PREFIX_CACHE", "on").lower() \
                not in ("0", "off", "false", "no")
        self.serve_prefix_cache = bool(serve_prefix_cache)
        # graceful drain budget on stop(): 0 = hard stop (the default
        # keeps test teardown instant); >0 closes admission with 503s
        # and lets in-flight streams finish for that many seconds
        self.serve_drain_grace_s = float(
            serve_drain_grace_s if serve_drain_grace_s is not None
            else os.environ.get("KUBEML_SERVE_DRAIN_GRACE_S", "0"))
        # fleet knobs (serve/fleet.py): replica floor/ceiling per model
        # and the idle budget before the fleet scales to zero (0 =
        # never). Defaults keep the single-replica behavior exactly.
        self.serve_replicas_min = int(
            serve_replicas_min if serve_replicas_min is not None
            else os.environ.get("KUBEML_SERVE_REPLICAS_MIN", "1"))
        self.serve_replicas_max = int(
            serve_replicas_max if serve_replicas_max is not None
            else os.environ.get("KUBEML_SERVE_REPLICAS_MAX", "1"))
        self.serve_scale_to_zero_s = float(
            serve_scale_to_zero_s if serve_scale_to_zero_s is not None
            else os.environ.get("KUBEML_SERVE_SCALE_TO_ZERO_S", "0"))
        # fleet failure-domain knobs (serve/fleet.py supervise_once):
        # crash-loop restart budget per replica, half-open probes to
        # rejoin after ejection, hedge age for gray failures (0 = off)
        self.serve_replica_restart_budget = int(
            serve_replica_restart_budget
            if serve_replica_restart_budget is not None
            else os.environ.get(
                "KUBEML_SERVE_REPLICA_RESTART_BUDGET", "2"))
        self.serve_probe_requests = int(
            serve_probe_requests if serve_probe_requests is not None
            else os.environ.get("KUBEML_SERVE_PROBE_REQUESTS", "2"))
        self.serve_hedge_after_s = float(
            serve_hedge_after_s if serve_hedge_after_s is not None
            else os.environ.get("KUBEML_SERVE_HEDGE_AFTER_S", "0"))
        # SLO plane (serve/slo.py): per-model latency objectives in ms
        # (0 TTFT = inherit the health-rule ttft SLO; 0 TPOT = no TPOT
        # objective) and the availability target the burn rate is
        # measured against
        self.serve_slo_ttft_ms = float(
            serve_slo_ttft_ms if serve_slo_ttft_ms is not None
            else os.environ.get("KUBEML_SERVE_SLO_TTFT_MS", "0"))
        self.serve_slo_tpot_ms = float(
            serve_slo_tpot_ms if serve_slo_tpot_ms is not None
            else os.environ.get("KUBEML_SERVE_SLO_TPOT_MS", "0"))
        self.serve_slo_target = float(
            serve_slo_target if serve_slo_target is not None
            else os.environ.get("KUBEML_SERVE_SLO_TARGET", "0.99"))
        self._serve: Dict[str, tuple] = {}   # model_id -> (stamp, fleet)
        self._serve_lock = threading.Lock()
        # latest analytic cost-ledger snapshot per TRAIN job (pushed
        # cumulatively on every MetricUpdate; serve-plane cost is read
        # live from the service/fleet at request time). Plain dict —
        # whole-value assignment per job id, reads tolerate staleness.
        self._cost: Dict[str, dict] = {}
        # durable control plane (opt-in): standalone-job and fleet
        # manifests mirrored under state_dir so recover() can re-adopt
        # surviving children and rebuild serving fleets after a crash
        self.state_dir = state_dir
        self._jobs_manifest_path = (
            os.path.join(state_dir, "ps.jobs.json") if state_dir else None)
        self._fleet_manifest_path = (
            os.path.join(state_dir, "ps.fleets.json") if state_dir
            else None)
        if state_dir:
            os.makedirs(state_dir, exist_ok=True)
        self.recoveries = 0
        self.last_recovery_s: Optional[float] = None
        self._infer_batcher = InferBatcher() if InferBatcher.enabled() \
            else None
        self.metrics = MetricsRegistry()
        # training-health verdicts over rolling MetricUpdate windows
        # (control/health.py); served on GET /health?id=
        self.health = HealthEvaluator()
        self.fn_registry = FunctionRegistry()
        self.ds_registry = DatasetRegistry()
        self.history_store = HistoryStore()

        # liveness reaper config: a standalone child that stops posting
        # heartbeats for interval * miss_budget seconds is declared
        # wedged and killed into the checkpoint-restart path. 0 disables.
        self.heartbeat_timeout = (
            float(os.environ.get("KUBEML_HEARTBEAT_INTERVAL", "10"))
            * float(os.environ.get("KUBEML_HEARTBEAT_MISS_BUDGET", "6")))
        self._reaper_stop = threading.Event()
        self._reaper_thread: Optional[threading.Thread] = None

        self.route("POST", "/start", self._h_start)
        self.route("POST", "/update/{jobId}", self._h_update)
        self.route("POST", "/metrics/{jobId}", self._h_metrics)
        self.route("POST", "/finish/{jobId}", self._h_finish)
        self.route("POST", "/preempted/{jobId}", self._h_preempted)
        self.route("POST", "/preempt/{jobId}", self._h_preempt)
        self.route("POST", "/cluster", self._h_cluster)
        self.route("POST", "/heartbeat/{jobId}", self._h_heartbeat)
        self.route("DELETE", "/stop/{jobId}", self._h_stop)
        self.route("GET", "/tasks", self._h_tasks)
        self.route("GET", "/metrics", self._h_prom)
        self.route("GET", "/trace", self._h_trace)
        self.route("GET", "/cost", self._h_cost)
        self.route("GET", "/flight", self._h_flight)
        # replaces the base liveness route: without ?id= it still
        # answers {"ok": true}, with ?id=<jobId> it serves the job's
        # health verdict
        self.route("GET", "/health", self._h_health)
        self.route("POST", "/infer", self._h_infer)
        self.route("POST", "/generate", self._h_generate)

    @property
    def mesh(self):
        if self._mesh is None:
            self._mesh = make_mesh()
        return self._mesh

    def start(self):
        port = super().start()
        if self.standalone_jobs and self.heartbeat_timeout > 0:
            self._reaper_thread = threading.Thread(
                target=self._reaper_loop, name="heartbeat-reaper",
                daemon=True)
            self._reaper_thread.start()
        return port

    # -------------------------------------------------- liveness reaper

    def _reaper_loop(self):
        period = max(1.0, self.heartbeat_timeout / 4)
        while not self._reaper_stop.wait(timeout=period):
            try:
                self._scan_heartbeats(time.monotonic())
            except Exception:
                logger.exception("heartbeat sweep failed")

    def _scan_heartbeats(self, now: float) -> List[str]:
        """One liveness sweep (pure given `now` — unit-testable without
        wall-clock waits): kill standalone children whose last progress
        heartbeat is older than the miss budget. The crash watchdog is
        the exit-code path for a DEAD child; this covers the
        alive-but-wedged one (deadlocked collective, hung IO) whose
        process never exits. Killing it routes recovery through that
        same watchdog: proc.wait() returns and the job restarts from
        its round-granular checkpoint. A child that never heartbeated
        is never reaped — liveness starts at its first beat, which
        covers slow starts and heartbeat-disabled children."""
        if self.heartbeat_timeout <= 0:
            return []
        reaped: List[str] = []
        with self._jobs_lock:
            stale = []
            for job_id, rec in self.jobs.items():
                if (rec.proc is None or rec.last_heartbeat is None
                        or rec.task.state == "stopping"):
                    continue
                age = now - rec.last_heartbeat
                if age >= self.heartbeat_timeout:
                    rec.last_heartbeat = None  # one kill per silence
                    stale.append((job_id, rec, age))
        for job_id, rec, age in stale:
            logger.error(
                "job %s: no heartbeat for %.0fs (budget %.0fs) at "
                "epoch %d round %d — declaring wedged; killing pid %s "
                "for checkpoint restart", job_id, age,
                self.heartbeat_timeout, rec.heartbeat_progress[0],
                rec.heartbeat_progress[1],
                rec.proc.pid if rec.proc else "?")
            self.metrics.note_wedged(job_id)
            try:
                rec.proc.kill()
            except OSError:
                pass
            reaped.append(job_id)
        return reaped

    # ------------------------------------------------------------- handlers

    def _h_start(self, req: Request):
        task = TrainTask.from_dict(req.body)
        # adopt the propagated trace id (header context when the task
        # predates the trace_id field) and leave the PS's own mark on
        # the job timeline — one span covering launch, flushed to the
        # per-job trace dir for merge_job_trace
        if not task.trace_id:
            task.trace_id = get_trace_context() or make_trace_id()
        tracer = Tracer(trace_id=task.trace_id)
        with tracer.span("ps.start_task", job_id=task.job_id,
                         mode="standalone" if self.standalone_jobs
                         else "threaded"):
            self.start_task(task)
        try:
            TraceSink(task.job_id, "ps").write(tracer)
        except OSError:
            logger.exception("ps: trace flush failed for %s", task.job_id)
        return {"job_id": task.job_id}

    def _h_update(self, req: Request):
        job_id = req.params["jobId"]
        with self._jobs_lock:
            rec = self.jobs.get(job_id)
        if rec is None:
            raise JobNotFoundError(job_id)
        epoch = req.body.get("grant_epoch") \
            if isinstance(req.body, dict) else None
        rec.push_update(int(req.body["parallelism"]),
                        grant_epoch=None if epoch is None else int(epoch))
        return {"ok": True}

    def _h_metrics(self, req: Request):
        m = MetricUpdate.from_dict(req.body)
        self.metrics.update_job(m)
        if m.cost_programs:
            self._cost[m.job_id] = m.cost_programs
        self._observe_health(m)
        return {"ok": True}

    def _observe_health(self, m) -> None:
        """Feed one update through the health rules: bump the alert
        counter once per rule ONSET (the evaluator dedupes against
        already-active rules) and publish the verdict gauge. Accepts a
        MetricUpdate (training epochs) or a plain snapshot dict (the
        serving loop's serve:<model> pseudo-job samples)."""
        job_id = m["job_id"] if isinstance(m, dict) else m.job_id
        for reason in self.health.observe(m):
            self.metrics.note_health_alert(job_id, reason["rule"])
            logger.warning("job %s health alert [%s/%s]: %s", job_id,
                           reason["severity"], reason["rule"],
                           reason["detail"])
            if job_id.startswith("serve:"):
                # SLO-breach onset on the serving plane: freeze the
                # evidence — dump the engine flight ring into the trace
                self._serve_flight_snapshot(job_id[len("serve:"):],
                                            reason["rule"])
        self.metrics.set_health(
            job_id, self.health.verdict(job_id)["state"])

    def _serve_flight_snapshot(self, model_id: str, rule: str) -> None:
        """Auto-snapshot the model's flight recorder into its serve
        trace on a health-rule onset. Reads the serve registry WITHOUT
        _serve_lock: this runs on the serving-loop thread (health_cb),
        which can hold the service condition variable — taking
        _serve_lock here would invert against _serve_service's
        install_weights (service cv acquired under _serve_lock) and
        deadlock. A bare dict read is safe in CPython and staleness is
        harmless (a just-swapped service simply snapshots nothing)."""
        cur = self._serve.get(model_id)
        if cur is None:
            return
        try:
            cur[1].flight_snapshot(f"health:{rule}")
        except Exception:
            logger.exception("flight snapshot failed for serve:%s",
                             model_id)

    def _h_health(self, req: Request):
        """Bare GET /health keeps the liveness contract every service
        answers; ?id=<jobId> serves that job's training-health verdict
        (state + machine-readable reasons + the latest epoch's stats)."""
        job_id = req.query.get("id", "")
        if not job_id:
            return {"ok": True}
        return self.health.verdict(job_id)

    def _h_finish(self, req: Request):
        self._finish(req.params["jobId"], req.body.get("error")
                     if isinstance(req.body, dict) else None)
        return {"ok": True}

    def _h_stop(self, req: Request):
        job_id = req.params["jobId"]
        with self._jobs_lock:
            rec = self.jobs.get(job_id)
        if rec is None:
            raise JobNotFoundError(job_id)
        rec.request_stop()
        rec.task.state = "stopping"
        return {"ok": True}

    def _h_preempted(self, req: Request):
        """A standalone child drained, checkpointed at the round cursor
        and is about to exit: mark its record so the watchdog reschedules
        it (without consuming the crash-restart budget)."""
        job_id = req.params["jobId"]
        body = req.body if isinstance(req.body, dict) else {}
        with self._jobs_lock:
            rec = self.jobs.get(job_id)
            if rec is None:
                raise JobNotFoundError(job_id)
            rec.preempted = True
            rec.preemptions += 1
        logger.warning("job %s preempted at epoch %s round %s; will "
                       "reschedule from its round checkpoint", job_id,
                       body.get("epoch"), body.get("round"))
        self.metrics.note_preemption(job_id)
        return {"ok": True}

    def _h_preempt(self, req: Request):
        """Cluster-allocator preemption (control/cluster.py): SIGTERM
        the victim's standalone child so it drains its in-flight round,
        checkpoints at the round cursor, posts /preempted and exits —
        then the watchdog hands its task BACK to the scheduler queue
        (requeue_on_exit) instead of respawning in place, so the freed
        lanes go to the higher-priority arrival. No restart budget is
        consumed anywhere on this path.

        A ``serve:<model>`` victim is the second gang kind: its fleet
        drains to zero (in-flight streams get the grace budget, then
        the replicas stop) and the model cold-starts again on its next
        request — the serverless analogue of drain + requeue."""
        job_id = req.params["jobId"]
        if job_id.startswith("serve:"):
            model_id = job_id[len("serve:"):]
            with self._serve_lock:
                cur = self._serve.get(model_id)
            if cur is None:
                raise JobNotFoundError(job_id)
            logger.warning("serving fleet %s: allocator preemption — "
                           "draining to zero", model_id)
            cur[1].scale_to_zero("allocator preemption")
            self._persist_fleets()
            return {"ok": True}
        with self._jobs_lock:
            rec = self.jobs.get(job_id)
            if rec is None:
                raise JobNotFoundError(job_id)
            if rec.proc is None and rec.adopted_pid is None:
                # threaded jobs share one process — there is no SIGTERM
                # grace path to drain them individually
                raise KubeMLException(
                    f"job {job_id} is not a standalone child; "
                    "allocator preemption requires standalone job mode",
                    503)
            rec.requeue_on_exit = True
            proc = rec.proc
            pid = rec.adopted_pid
        logger.warning("job %s: allocator preemption — sending SIGTERM "
                       "for drain + checkpoint + requeue", job_id)
        if proc is not None:
            proc.terminate()
        else:
            # re-adopted child (control-plane recovery): no Popen
            # handle, terminate by pid
            try:
                os.kill(pid, signal.SIGTERM)
            except OSError:
                pass
        return {"ok": True}

    def _h_cluster(self, req: Request):
        """Cluster-allocator telemetry push from the scheduler: the
        snapshot lands on the Prometheus cluster families and rides the
        health pipeline under the `cluster` pseudo job id (the
        serve:<model> idiom), so the queue-starvation rule and the
        `kubeml top` cluster pane see it via GET /health?id=cluster."""
        snap = req.body if isinstance(req.body, dict) else {}
        if not snap.get("job_id"):
            raise InvalidArgsError("cluster snapshot requires job_id")
        self.metrics.update_cluster(snap)
        self._observe_health(snap)
        return {"ok": True}

    def _h_heartbeat(self, req: Request):
        """Progress heartbeat from a standalone child (epoch + round
        cursor). Feeds the liveness reaper: silence past the miss budget
        means alive-but-wedged, and the child is killed into the
        ordinary checkpoint-restart path."""
        job_id = req.params["jobId"]
        body = req.body if isinstance(req.body, dict) else {}
        progress = (int(body.get("epoch", 0)), int(body.get("round", 0)))
        with self._jobs_lock:
            rec = self.jobs.get(job_id)
            if rec is None:
                raise JobNotFoundError(job_id)
            rec.last_heartbeat = time.monotonic()
            rec.heartbeat_progress = progress
        self.metrics.note_heartbeat(job_id, *progress)
        return {"ok": True}

    def _h_tasks(self, req: Request):
        with self._jobs_lock:
            out = []
            for r in self.jobs.values():
                # stamp the PS-side lifecycle counters onto the listing:
                # each child incarnation only knows its own lifetime
                r.task.restarts = r.restarts
                r.task.preemptions = r.preemptions
                out.append(r.task.to_dict())
            return out

    def _h_prom(self, req: Request):
        # job families plus this service's HTTP middleware series, one
        # scrape target
        text = self.metrics.exposition() + self.http_metrics.exposition()
        return Raw(text.encode(), "text/plain; version=0.0.4")

    def _h_trace(self, req: Request):
        """Merged Chrome trace for a job (?id=<jobId>): every process's
        TraceSink file plus any xla_profile capture, one Perfetto-
        loadable document."""
        job_id = req.query.get("id", "")
        if not job_id:
            raise InvalidArgsError("id query parameter required")
        if job_id.startswith("serve:"):
            # live serving tracers batch their unforced flushes; push
            # the tail out so the merge sees up-to-the-request state
            with self._serve_lock:
                cur = self._serve.get(job_id[len("serve:"):])
            if cur is not None:
                cur[1].flush_trace()
        try:
            return merge_job_trace(job_id)
        except FileNotFoundError:
            raise JobNotFoundError(f"{job_id} (no trace recorded)")

    def _h_cost(self, req: Request):
        """Per-program analytic cost for a job (?id=<jobId> or
        ?id=serve:<model>): the ledger snapshot (flat per-program
        record + attributed totals) plus the per-plane attribution
        (flops/bytes per sample and per token). Train jobs serve the
        latest MetricUpdate snapshot; serving models read the live
        service/fleet snapshot, merged fleet-wide like /trace."""
        job_id = req.query.get("id", "")
        if not job_id:
            raise InvalidArgsError("id query parameter required")
        if job_id.startswith("serve:"):
            with self._serve_lock:
                cur = self._serve.get(job_id[len("serve:"):])
            if cur is None:
                raise JobNotFoundError(
                    f"{job_id} (no serving service running)")
            programs = cur[1].snapshot().get("serve_cost_programs") or {}
        else:
            programs = self._cost.get(job_id)
            if programs is None:
                raise JobNotFoundError(f"{job_id} (no cost recorded)")
        return {"id": job_id, "programs": programs,
                "attributed": attributed_from_snapshot(programs)}

    def _h_flight(self, req: Request):
        """Drain the serving engine's flight recorder
        (?id=serve:<model> or bare ?id=<model>): the last N loop-step
        records, oldest first — the always-on black box the trace
        auto-snapshots are cut from. Live state, not a file: shows what
        the loop was doing RIGHT NOW even with no incident yet."""
        job_id = req.query.get("id", "")
        if not job_id:
            raise InvalidArgsError("id query parameter required")
        model_id = (job_id[len("serve:"):]
                    if job_id.startswith("serve:") else job_id)
        with self._serve_lock:
            cur = self._serve.get(model_id)
        if cur is None:
            raise JobNotFoundError(
                f"serve:{model_id} (no serving service running)")
        # fleet mode: one merged document over every replica's ring,
        # each record stamped with the replica index it came from
        capacity = total = 0
        records: list = []
        replicas: list = []
        for idx, engine in cur[1].engines():
            fl = getattr(engine, "flight", None)
            if fl is None:
                continue
            replicas.append(idx)
            capacity += fl.capacity
            total += fl.total
            for rec in fl.snapshot():
                if isinstance(rec, dict):
                    rec = dict(rec)
                    rec["replica"] = idx
                records.append(rec)
        return {"id": f"serve:{model_id}", "model": model_id,
                "capacity": capacity, "total_steps": total,
                "replicas": replicas, "records": records}

    def _h_infer(self, req: Request):
        model_id = req.body.get("model_id")
        if not model_id:
            raise InvalidArgsError("model_id required")
        data = req.body.get("data")
        if data is None:
            raise InvalidArgsError("data required")
        try:
            arr = np.asarray(data)
        except ValueError as e:  # ragged/inhomogeneous client payload
            raise InvalidArgsError(f"malformed inference payload: {e}") \
                from e
        model, variables = self._load_for_infer(model_id)
        try:
            if self._infer_batcher is not None and arr.ndim >= 1 \
                    and len(arr) > 0:
                # concurrent requests for the same (model, sample
                # shape) stack into one device call — the leader's
                # model/variables serve the whole group (same model_id
                # + the LRU's saved_at freshness keying)
                key = (model_id, arr.shape[1:], str(arr.dtype))
                preds = self._infer_batcher.submit(
                    key, np.asarray(arr),
                    lambda stacked: model.infer(variables, stacked))
            else:
                preds = model.infer(variables, arr)
        except InferenceInputError as e:
            # model-library input rejections (e.g. prompt/sequence longer
            # than max_len) are client errors, not server faults:
            # translate to the 4xx envelope instead of the generic 500.
            # Other exceptions (broken checkpoint shapes, internal jax
            # errors) stay on the 500 path
            raise InvalidArgsError(str(e)) from e
        return {"predictions": np.asarray(preds).tolist()}

    def _load_for_infer(self, model_id: str):
        """Checkpoint load with a small LRU keyed on the manifest's
        saved_at stamp (checkpoint.checkpoint_saved_at — immune to
        filesystem mtime granularity), so repeated inference against one
        model doesn't re-read the weights from disk per request (the
        reference reads live RedisAI tensors — scheduler/api.go:140)."""
        saved_at = checkpoint_saved_at(model_id)
        if saved_at is not None:  # unreadable manifests never hit the cache
            with self._infer_cache_lock:
                hit = self._infer_cache.get(model_id)
                if hit is not None and hit[0] == saved_at:
                    self._infer_cache.move_to_end(model_id)
                    self.metrics.note_infer_cache(True)
                    return hit[1], hit[2]
        self.metrics.note_infer_cache(False)
        variables, manifest = load_checkpoint(model_id)
        model_cls, _ = self.fn_registry.resolve(
            manifest.get("function") or manifest.get("model"))
        model = model_cls()
        # key on the LOADED manifest's stamp so the (stamp, weights) pair
        # is consistent even if a save raced the probe above
        key = manifest.get("saved_at")
        if key is not None:
            with self._infer_cache_lock:
                self._infer_cache[model_id] = (key, model, variables)
                self._infer_cache.move_to_end(model_id)
                self._evict_infer_cache_locked()
                self.metrics.set_infer_cache_entries(
                    len(self._infer_cache))
        return model, variables

    @staticmethod
    def _variables_nbytes(variables) -> int:
        import jax
        return int(sum(getattr(leaf, "nbytes", 0)
                       for leaf in jax.tree_util.tree_leaves(variables)))

    def _evict_infer_cache_locked(self) -> None:
        """LRU eviction under two pressures (cache lock held): the entry
        cap (--infer-cache-size), and the serving HBM budget — the KV
        slabs of live decode services and cached checkpoint weights
        share device memory, so cached entries yield until the combined
        footprint fits. The freshest entry always survives (the request
        that just loaded it is about to use it)."""
        while len(self._infer_cache) > self.infer_cache_size:
            self._infer_cache.popitem(last=False)
        budget = self.serve_hbm_budget_bytes - self._serve_hbm_bytes()
        while len(self._infer_cache) > 1 \
                and sum(self._variables_nbytes(e[2])
                        for e in self._infer_cache.values()) > budget:
            self._infer_cache.popitem(last=False)

    def _serve_hbm_bytes(self) -> int:
        with self._serve_lock:
            return sum(fleet.hbm_bytes
                       for _, fleet in self._serve.values())

    # -------------------------------------------------------- serving plane

    def _serve_replica_factory(self, model_id: str):
        """Replica builder for the model's fleet (serve/fleet.py): one
        call builds one UNSTARTED ServeService over a fresh DecodeEngine
        — the exact documented program inventory per replica (decode,
        prefill, plus multi-step and/or verify when those knobs are
        set). Called at fleet start, on autoscaler grows, and on cold
        starts from zero, so it re-reads the checkpoint cache each time
        (a replica born after a hot-swap starts on the newest
        weights)."""
        from kubeml_tpu.serve.engine import DecodeEngine
        from kubeml_tpu.serve.pager import PageGeometry
        from kubeml_tpu.serve.service import ServeService

        def factory(index: int) -> ServeService:
            model, variables = self._load_for_infer(model_id)
            module = getattr(model, "module", None)
            try:
                draft_module = draft_variables = None
                if self.serve_draft_model:
                    # a missing/broken draft checkpoint or an
                    # incompatible draft trunk is a client error like
                    # any other bad serve knob, hence inside this try
                    draft, draft_variables = self._load_for_infer(
                        self.serve_draft_model)
                    draft_module = getattr(draft, "module", None)
                engine = DecodeEngine(
                    module, variables,
                    geom=PageGeometry.for_module(
                        slots=self.serve_slots,
                        page=self.serve_page_tokens,
                        max_len=module.max_len),
                    prefill_chunk=self.serve_prefill_chunk,
                    kv_dtype=self.serve_kv_dtype,
                    decode_steps=self.serve_decode_steps,
                    draft_module=draft_module,
                    draft_variables=draft_variables,
                    prefix_cache=self.serve_prefix_cache,
                    # production posture: a pager invariant violation
                    # is logged and counted
                    # (kubeml_serve_page_leaks_total), never an
                    # AssertionError that kills the serving loop
                    # mid-stream — tests run strict
                    strict_pager=False)
            except (ValueError, TypeError, AttributeError) as e:
                # non-GPT modules (no paged decode step) and invalid
                # serve knobs (e.g. a negative prefill chunk) are
                # client errors
                raise InvalidArgsError(
                    f"model {model_id} does not support streaming "
                    f"decode with the configured serve knobs: {e}") \
                    from e
            # serving observability is always on in the product path:
            # the tracer shares the service clock (perf_counter), and
            # each replica sinks under the serve:<model> pseudo-job id
            # with its own process name so GET /trace?id=serve:<model>
            # renders the whole fleet on one timeline
            return ServeService(model_id, engine,
                                max_queue=self.serve_queue_depth,
                                metrics=self.metrics,
                                tracer=Tracer(clock=time.perf_counter),
                                trace_sink=TraceSink(
                                    f"serve:{model_id}",
                                    f"serve-r{index}"))
        return factory

    def _serve_resize_cb(self, model_id: str):
        """The fleet's bridge to the cluster pool: every autoscale
        decision is offered to the scheduler (POST /serve/resize →
        ClusterAllocator, gang kind 'serving') so replicas and training
        lanes contend for one pool. Fails OPEN — a standalone PS or an
        unreachable scheduler must not stall serving elasticity."""
        def resize_cb(replicas: int) -> int:
            # every autoscale decision also refreshes the durable fleet
            # manifest — replica-count changes from inside the fleet
            # (grow/shrink/scale-to-zero) all pass through here
            self._persist_fleets()
            if not self.scheduler_url:
                return replicas
            try:
                resp = http_json(
                    "POST", f"{self.scheduler_url}/serve/resize",
                    {"model_id": model_id, "replicas": int(replicas)})
                return int(resp.get("granted", replicas))
            except Exception:
                logger.exception("serve resize offer failed for %s; "
                                 "failing open", model_id)
                return replicas
        return resize_cb

    def _serve_service(self, model_id: str):
        """The model's serving FLEET (serve/fleet.py): N continuous-
        batching replicas behind the prefix-affinity router. The FIRST
        request builds it; when the checkpoint stamp later changes (a
        continual job published on its --publish-every-rounds cadence,
        or a retrain finished), the new weights are INSTALLED into every
        live replica as a new generation — in-flight streams finish on
        the weights they attached under, new admissions decode the new
        generation, and nothing is stopped or shed (the zero-downtime
        hot-swap; the old build-new-service-and-stop path failed every
        in-flight stream with 'serving loop stopped')."""
        from kubeml_tpu.serve.fleet import ServeFleet
        model, variables = self._load_for_infer(model_id)
        stamp = checkpoint_saved_at(model_id)
        with self._serve_lock:
            cur = self._serve.get(model_id)
            if cur is not None:
                if cur[0] != stamp:
                    # zero-downtime swap: queue the install for every
                    # replica's serving-loop thread; requests admitted
                    # from here on attach to the new generation
                    cur[1].install_weights(variables, stamp)
                    self._serve[model_id] = (stamp, cur[1])
                    self._persist_fleets_async()
                return cur[1]
        fleet = ServeFleet(
            model_id, self._serve_replica_factory(model_id),
            replicas_min=self.serve_replicas_min,
            replicas_max=self.serve_replicas_max,
            scale_to_zero_s=self.serve_scale_to_zero_s,
            # the shrink/scale-to-zero grace: the stop() knob defaults
            # to 0 for instant teardown, but an autoscaler retire must
            # always give in-flight streams a real budget
            drain_grace_s=self.serve_drain_grace_s or 5.0,
            page_tokens=self.serve_page_tokens,
            metrics=self.metrics,
            health_cb=self._observe_health,
            resize_cb=self._serve_resize_cb(model_id),
            replica_restart_budget=self.serve_replica_restart_budget,
            probe_requests=self.serve_probe_requests,
            hedge_after_s=self.serve_hedge_after_s,
            # fleet-level spans (routing, migration, hedging) sink as
            # their own process in the serve:<model> trace dir, so the
            # merged document stitches one tree per request across the
            # router and every replica it touched
            tracer=Tracer(clock=time.perf_counter),
            trace_sink=TraceSink(f"serve:{model_id}", "fleet"),
            slo_ttft_s=self.serve_slo_ttft_ms / 1000.0,
            slo_tpot_s=self.serve_slo_tpot_ms / 1000.0,
            slo_target=self.serve_slo_target).start()
        old = None
        with self._serve_lock:
            cur = self._serve.get(model_id)
            if cur is not None:  # lost the build race; ours is unused
                old, fleet = fleet, cur[1]
            else:
                self._serve[model_id] = (stamp, fleet)
        if old is not None:
            old.stop()
        self._persist_fleets()
        return fleet

    def _h_generate(self, req: Request):
        """Streaming continuous-batching generation. Body:
        {model_id, prompt: [token ids], max_new_tokens, temperature,
        seed, eos_id, deadline_ms, stream} — stream=true (default)
        answers ndjson chunks ({"token": id} per token, then
        {"done": ..., "tokens": [...]}) as the decode loop produces
        them; stream=false blocks and answers one JSON document.
        Saturation answers 429 with Retry-After (admission control,
        never unbounded queueing); an infeasible deadline_ms also 429s
        at admission; a draining service answers 503 + Retry-After so
        the client's retry lands on another replica."""
        from kubeml_tpu.serve.slots import ServeDraining, ServeSaturated
        body = req.body if isinstance(req.body, dict) else {}
        model_id = body.get("model_id")
        if not model_id:
            raise InvalidArgsError("model_id required")
        prompt = body.get("prompt")
        if prompt is None:
            raise InvalidArgsError("prompt required (list of token ids)")
        try:
            prompt = [int(t) for t in prompt]
        except (TypeError, ValueError) as e:
            raise InvalidArgsError(
                f"prompt must be a list of token ids: {e}") from e
        svc = self._serve_service(model_id)
        # distributed tracing: adopt the client's X-KubeML-Trace-Id
        # (bound to this thread by the httpd middleware) or mint one.
        # Every response path echoes it back as a header — body shapes
        # are part of the streaming contract and stay untouched — so
        # the client can pull GET /trace?id=serve:<model> and find its
        # own span tree by trace_id
        trace_id = get_trace_context() or make_trace_id()
        hdrs = {TRACE_HEADER: trace_id}
        try:
            r = svc.submit(
                prompt,
                max_new_tokens=int(body.get("max_new_tokens", 32)),
                temperature=float(body.get("temperature", 0.0)),
                seed=int(body.get("seed", 0)),
                eos_id=body.get("eos_id"),
                trace_id=trace_id,
                deadline_ms=body.get("deadline_ms"),
                session=body.get("session"))
        except InferenceInputError as e:
            raise InvalidArgsError(str(e)) from e
        except (ServeSaturated, ServeDraining) as e:
            retry = max(1, int(round(e.retry_after_s)))
            return Raw(e.to_json().encode(), "application/json",
                       status=e.status_code,
                       headers={"Retry-After": str(retry), **hdrs})
        if body.get("stream", True):
            return Stream(self._generate_chunks(svc, r), headers=hdrs)
        if not r.wait(timeout=600.0):
            svc.cancel(r)
            raise KubeMLException("generation timed out", 504)
        if r.outcome == "ok":
            return Raw(json.dumps({"tokens": r.tokens}).encode(),
                       "application/json", headers=hdrs)
        raise KubeMLException(r.error or f"generation {r.outcome}", 500)

    def _generate_chunks(self, svc, r):
        """ndjson producer for one stream; generator close() (client
        disconnect — httpd Stream contract) cancels the request so its
        slot and KV pages free immediately."""
        try:
            for ev in r.events_iter():
                yield (json.dumps(ev) + "\n").encode()
        finally:
            if not r.done:
                svc.cancel(r)
            # producer-side stream lifetime (submit -> generator close),
            # including cancelled streams. The HTTP duration histogram
            # is NOT redundant with this: the middleware observes after
            # the full chunked body is written to the socket, so it
            # times the server-side write path — docs/observability.md
            # spells out which covers what
            if r.submitted_at is not None:
                self.metrics.observe_serve_stream(
                    svc.model_id, svc.clock() - r.submitted_at)

    # ----------------------------------------------- durable control plane

    def _persist_jobs(self) -> None:
        """Mirror the standalone-job registry to the durable manifest
        (atomic tmp+rename). Threaded jobs are deliberately absent:
        they are threads of THIS process and cannot outlive it."""
        if self._jobs_manifest_path is None:
            return
        with self._jobs_lock:
            doc = {}
            for job_id in sorted(self.jobs):
                rec = self.jobs[job_id]
                if rec.job is not None:
                    continue
                rec.task.restarts = rec.restarts
                rec.task.preemptions = rec.preemptions
                pid = rec.proc.pid if rec.proc is not None \
                    else rec.adopted_pid
                doc[job_id] = {"task": rec.task.to_dict(),
                               "url": rec.url, "pid": pid,
                               "partition": rec.partition}
        atomic_write_json(self._jobs_manifest_path, {"jobs": doc})

    def _persist_fleets(self) -> None:
        """Mirror the serving registry — checkpoint stamp + live
        replica count per model — so recover() can rebuild each fleet
        at its pre-crash width with the last published weights."""
        if self._fleet_manifest_path is None:
            return
        with self._serve_lock:
            items = sorted(self._serve.items())
        doc = {m: {"stamp": stamp, "replicas": fleet.replica_count}
               for m, (stamp, fleet) in items}
        atomic_write_json(self._fleet_manifest_path, {"fleets": doc})

    def _persist_fleets_async(self) -> None:
        """_persist_fleets for callers already holding _serve_lock
        (a plain, non-reentrant Lock): defer to a short-lived thread
        that takes the lock itself."""
        if self._fleet_manifest_path is None:
            return
        threading.Thread(target=self._persist_fleets,
                         name="persist-fleets", daemon=True).start()

    def recover(self) -> dict:
        """Rebuild a restarted PS from its durable manifests.

        Standalone children that survived the control-plane crash are
        RE-ADOPTED: probed over their recorded URL, reinstated in the
        job registry (partition lease re-claimed, counters restored)
        and watched by a pid-poll watchdog — never double-started.
        Children that died with the control plane are dropped here; the
        scheduler's own recovery sweep requeues them budget-free from
        their checkpoints. Serving fleets are rebuilt at their recorded
        replica counts via the ordinary build path, which re-installs
        the last published checkpoint stamp — streams then resume
        through the re-prefill path bit-identically."""
        t0 = time.monotonic()
        summary: dict = {"adopted": [], "dropped": [], "fleets": {}}
        jobs_doc = (read_json(self._jobs_manifest_path)
                    if self._jobs_manifest_path else None) or {}
        for job_id, ent in sorted(jobs_doc.get("jobs", {}).items()):
            task = TrainTask.from_dict(ent["task"])
            url = ent.get("url")
            alive = False
            if url:
                try:
                    http_json("GET", f"{url}/health")
                    alive = True
                except Exception:
                    alive = False
            if not alive:
                summary["dropped"].append(job_id)
                logger.warning("ps recovery: job %s child is gone; "
                               "leaving the requeue to the scheduler "
                               "sweep", job_id)
                continue
            rec = _JobRecord(task, url=url)
            rec.partition = ent.get("partition")
            rec.adopted_pid = ent.get("pid")
            with self._jobs_lock:
                if job_id in self.jobs:
                    continue
                self.jobs[job_id] = rec
                if rec.partition is not None and self.job_partitions:
                    self._busy_partitions.add(rec.partition)
            self.metrics.running_total.inc("train")
            threading.Thread(target=self._watch_adopted,
                             args=(job_id, rec, rec.adopted_pid),
                             name=f"watch-{job_id}",
                             daemon=True).start()
            summary["adopted"].append(job_id)
            logger.warning("ps recovery: re-adopted live child %s at "
                           "%s (pid %s)", job_id, url, rec.adopted_pid)
        fleets_doc = (read_json(self._fleet_manifest_path)
                      if self._fleet_manifest_path else None) or {}
        for model_id, ent in sorted(fleets_doc.get("fleets", {}).items()):
            replicas = int(ent.get("replicas", 0))
            if replicas <= 0:
                continue  # was at zero; the next request cold-starts it
            try:
                fleet = self._serve_service(model_id)
                live = fleet.ensure_replicas(replicas)
                summary["fleets"][model_id] = live
                logger.warning("ps recovery: fleet %s rebuilt at %d "
                               "replica(s) (stamp %s)", model_id, live,
                               ent.get("stamp"))
            except Exception:
                logger.exception("ps recovery: fleet %s rebuild failed",
                                 model_id)
        self.last_recovery_s = time.monotonic() - t0
        self.recoveries += 1
        self.metrics.note_control_recovery("ps", self.last_recovery_s)
        self._persist_jobs()
        self._persist_fleets()
        summary["recovery_s"] = self.last_recovery_s
        logger.warning("ps recovered in %.3fs: %d job(s) adopted, %d "
                       "dropped, %d fleet(s) rebuilt",
                       self.last_recovery_s, len(summary["adopted"]),
                       len(summary["dropped"]), len(summary["fleets"]))
        return summary

    # ------------------------------------------------------------- job mgmt

    def start_task(self, task: TrainTask) -> None:
        """Launch the job: as a child process in standalone mode
        (ps/api.go:139-222, pod -> process) or as a thread otherwise
        (ps/api.go:211-217)."""
        if self.standalone_jobs:
            self._start_standalone(task)
            return
        fn_name = task.parameters.function_name or task.parameters.model_type
        model_cls, dataset_cls = self.fn_registry.resolve(fn_name)
        model = model_cls()
        dataset = (dataset_cls(task.parameters.dataset) if dataset_cls
                   else KubeDataset(task.parameters.dataset))

        from kubeml_tpu.api.const import kubeml_home
        import os
        job = TrainJob(task, model, dataset, self.mesh,
                       registry=self.ds_registry,
                       history_store=self.history_store,
                       callbacks=JobCallbacks(
                           request_parallelism=self._request_parallelism,
                           publish_metrics=self._publish_metrics,
                           on_finish=self._finish),
                       log_file=os.path.join(kubeml_home(), "logs",
                                             f"{task.job_id}.log"))
        thread = threading.Thread(target=self._run_job, args=(job,),
                                  name=f"job-{task.job_id}", daemon=True)
        with self._jobs_lock:
            if task.job_id in self.jobs:
                raise InvalidArgsError(f"job {task.job_id} already exists")
            self.jobs[task.job_id] = _JobRecord(task, job, thread)
        self.metrics.running_total.inc("train")
        task.state = "running"
        thread.start()

    def _run_job(self, job: TrainJob):
        try:
            job.train()
        except Exception:
            logger.exception("job %s thread failed", job.task.job_id)

    # ------------------------------------------------------- standalone mode

    def _start_standalone(self, task: TrainTask) -> None:
        """Spawn the per-job server process and hand it the task — the
        reference's pod creation + readiness wait + retried StartTask
        (ps/job_pod.go:18-62, ps/api.go:192-207), process-shaped.

        The job id is reserved in the index BEFORE spawning, so duplicate
        submissions are rejected up front and an immediately-failing child
        whose /finish races this method still finds its record. The parent
        deliberately makes no JAX calls here: on TPU the chips belong to
        the job processes (each can be pinned to a device subset via
        JAX/TPU visible-devices env vars passed through `job_env`)."""
        rec = _JobRecord(task)
        with self._jobs_lock:
            if task.job_id in self.jobs:
                raise InvalidArgsError(f"job {task.job_id} already exists")
            if self.job_partitions is not None:
                free = [i for i in range(len(self.job_partitions))
                        if i not in self._busy_partitions]
                if not free:
                    raise KubeMLException(
                        "all device partitions are leased to running "
                        "jobs; retry when one finishes", 503)
                rec.partition = free[0]
                self._busy_partitions.add(free[0])
            self.jobs[task.job_id] = rec
        self.metrics.running_total.inc("train")
        try:
            self._spawn_standalone(rec)
        except Exception:
            with self._jobs_lock:
                popped = self.jobs.pop(task.job_id, None)
            if popped is not None:  # not already finished via /finish
                self.metrics.running_total.inc("train", -1.0)
            if rec.proc is not None:
                # reap off-thread; the partition frees only once the
                # terminated child is GONE (chips stay held until exit)
                threading.Thread(target=self._reap, args=(rec,),
                                 name=f"reap-{task.job_id}",
                                 daemon=True).start()
            else:
                self._release_partition(rec)
            raise

    def _spawn_standalone(self, rec: _JobRecord) -> None:
        """Spawn the per-job child process, wait for readiness, push the
        task, and arm the crash watchdog. Shared by the first start and
        the watchdog's checkpoint-based restart; a failed spawn cleans up
        its own child process, while record/partition bookkeeping stays
        with the caller."""
        task = rec.task
        task.state = "starting"
        tmp_dir = tempfile.mkdtemp(prefix=f"kubeml-job-{task.job_id}-")
        port_file = os.path.join(tmp_dir, "port")
        cmd = [sys.executable, "-m", "kubeml_tpu.train.jobserver",
               "--job-id", task.job_id, "--ps-url", self.url,
               "--port-file", port_file]
        if task.trace_id:
            # argv (not just the /start task payload) so the child's
            # spans correlate even for rounds logged before the task
            # arrives, and across watchdog restarts
            cmd += ["--trace-id", task.trace_id]
        mirror_cpu = 0
        if self._mesh is not None:
            # explicit mesh: size hint + (tests) mirror a virtual-CPU view
            from kubeml_tpu.parallel.mesh import data_axis_size
            cmd += ["--mesh-data", str(data_axis_size(self._mesh))]
            devs = self._mesh.devices.ravel()
            if devs[0].platform == "cpu":
                mirror_cpu = len(devs)
                cmd += ["--virtual-cpu-devices", str(mirror_cpu)]
        if self.scheduler_url:
            cmd += ["--scheduler-url", self.scheduler_url]
        env = dict(os.environ)
        if mirror_cpu:
            # a CPU-mirrored child must be CPU-targeted AT INTERPRETER
            # START, not merely retargeted after import: the container
            # sitecustomize eagerly initializes the accelerator backend
            # first, which (a) on a TPU host would transiently steal the
            # single-process-exclusive chip from a real TPU job and (b)
            # blocks indefinitely when the relay is still reaping a
            # SIGKILLed sibling's session — observed as chaos-test
            # children stuck in backend init with the watchdog's restart
            # then failing on the readiness timeout
            from kubeml_tpu.testing import virtual_cpu_env
            env.update(virtual_cpu_env(mirror_cpu))
        # the job child must NOT inherit the parent's jax.distributed
        # rank: on multi-host serve these vars hold the PARENT's
        # coordinator/rank, and a child re-joining as that rank hangs
        # the cluster (at best a 300s rendezvous timeout). This covers
        # every family jobserver's initialize()/jax auto-detect triggers
        # on, not just our own vars. Multi-host job processes get their
        # own topology via job_env/partition env when wanted.
        for var in CLUSTER_ENV_VARS:
            env.pop(var, None)
        env.update(self.job_env)
        if rec.partition is not None:
            env.update(self.job_partitions[rec.partition])
            logger.info("job %s leased device partition %d (%s)",
                        task.job_id, rec.partition,
                        self.job_partitions[rec.partition])
        repo_root = os.path.dirname(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))))
        env["PYTHONPATH"] = repo_root + os.pathsep + env.get("PYTHONPATH", "")
        try:
            rec.proc = subprocess.Popen(cmd, env=env)
            rec.url = self._wait_job_ready(rec.proc, port_file)
            # retried start push, parity ps/api.go:192-207 (10x backoff)
            delay = 0.1
            for attempt in range(10):
                try:
                    http_json("POST", f"{rec.url}/start", task.to_dict())
                    break
                except KubeMLException:
                    if attempt == 9:
                        raise
                    time.sleep(delay)
                    delay = min(delay * 2, 5.0)
        except Exception:
            # terminate only; the CALLER owns reap/partition bookkeeping
            # (a single reap path — double-reaping the same record could
            # double-release its partition around a concurrent re-lease)
            if rec.proc is not None:
                rec.proc.terminate()
            raise
        finally:
            shutil.rmtree(tmp_dir, ignore_errors=True)
        task.state = "running"
        # a stop() that raced this spawn cleared the job index while the
        # child was coming up: the child now holds a task nobody tracks —
        # terminate (and properly reap) it instead of leaking an orphan
        # that trains to completion against a dead endpoint. Keyed on
        # _stopping ONLY: a merely-absent record is the documented
        # fast-/finish race (an immediately-finishing child popped its
        # own record) and must not fail a job that actually ran.
        with self._jobs_lock:
            raced_stop = self._stopping
        if raced_stop:
            rec.proc.terminate()
            threading.Thread(target=self._reap, args=(rec,),
                             name=f"reap-{task.job_id}",
                             daemon=True).start()
            raise KubeMLException(
                "parameter server is shutting down", 503)
        # watchdog: a child that dies WITHOUT posting /finish (OOM-kill,
        # segfault) must not pin its record — or its device partition —
        # forever. proc.wait() here races the normal finish path safely:
        # _finish pops the record exactly once, so whichever side loses
        # the pop becomes a no-op.
        threading.Thread(target=self._watch_standalone,
                         args=(task.job_id, rec),
                         name=f"watch-{task.job_id}", daemon=True).start()
        self._persist_jobs()

    def _watch_standalone(self, job_id: str, rec: _JobRecord):
        proc = rec.proc
        proc.wait()
        self._on_child_exit(job_id, rec, proc.returncode)

    def _watch_adopted(self, job_id: str, rec: _JobRecord,
                       pid: Optional[int]) -> None:
        """Watchdog for a child RE-ADOPTED from a previous PS
        incarnation (control-plane recovery): no Popen handle exists to
        wait() on, so poll pid liveness (falling back to the child's
        /health endpoint without one) and route its death through the
        same exit logic as a spawn-watched child."""
        while True:
            if self._stopping:
                return
            with self._jobs_lock:
                if self.jobs.get(job_id) is not rec:
                    return  # deregistered normally via /finish
            if rec.proc is not None:
                return      # a restart respawned it; its own watchdog owns it
            if pid is not None:
                try:
                    os.kill(pid, 0)
                    alive = True
                except OSError:
                    alive = False
            else:
                try:
                    http_json("GET", f"{rec.url}/health")
                    alive = True
                except Exception:
                    alive = False
            if not alive:
                break
            time.sleep(0.5)
        self._on_child_exit(job_id, rec, None)

    def _on_child_exit(self, job_id: str, rec: _JobRecord,
                       rc: Optional[int]) -> None:
        # checkpoint-based recovery: a crashed job process (OOM-kill,
        # segfault — the pod-death analogue of the reference's
        # merge-with-survivors tolerance, util.go:144-166) restarts from
        # its OWN latest checkpoint with history/epoch/parallelism
        # restored (train/job.py resume-from-self), up to
        # options.max_restarts times. Not eligible: an acknowledged
        # /stop (a restart would undo the user's decision) or no
        # checkpoint (nothing to resume) — those fail as before. The
        # claim happens UNDER the jobs lock so a concurrent /finish
        # observes either the dead incarnation or the respawn claim,
        # never a half-restarted record.
        opts = rec.task.parameters.options
        # probe the checkpoint BEFORE taking the jobs lock: the probe is
        # filesystem IO (manifest open + parse) and every control-plane
        # handler contends on this lock — a slow/hung filesystem must
        # not stall /start, /finish, /update and metrics for all jobs.
        # The probe result can only go stale in the benign direction (a
        # checkpoint appearing between probe and claim), and the cheap
        # in-memory conditions are re-evaluated under the lock.
        has_checkpoint = checkpoint_saved_at(job_id) is not None
        with self._jobs_lock:
            if self.jobs.get(job_id) is not rec:
                return  # already deregistered via /finish
            # a preempted exit is the PLATFORM's doing: always eligible
            # for reschedule (given a checkpoint) and exempt from the
            # max_restarts crash budget
            preempted, rec.preempted = rec.preempted, False
            # cluster-allocator preemption (POST /preempt): the task
            # goes BACK to the scheduler queue so the freed lanes serve
            # the higher-priority arrival — instead of respawning here.
            # Covers a child that crashed DURING the drain too (the
            # eviction was the platform's doing either way, so neither
            # path consumes max_restarts); without a checkpoint there
            # is nothing to requeue and the exit fails as before.
            requeue = (rec.requeue_on_exit
                       and self.scheduler_url is not None
                       and not self._stopping
                       and rec.task.state != "stopping"
                       and has_checkpoint)
            if requeue:
                self.jobs.pop(job_id, None)
            eligible = (not requeue
                        and not self._stopping
                        and rec.task.state != "stopping"
                        and (preempted or rec.restarts < opts.max_restarts)
                        and has_checkpoint)
            if eligible:
                if not preempted:
                    rec.restarts += 1
                rec.proc = None
                rec.url = None
                rec.adopted_pid = None
                rec.restarting = True
                rec.last_heartbeat = None  # fresh liveness window
                rec.task.parameters.resume_from = job_id
        if requeue:
            self._requeue_preempted(job_id, rec)
            return
        if not preempted:
            logger.warning("job %s process exited without finishing "
                           "(rc=%s)", job_id, rc)
        if not eligible:
            self._finish(job_id,
                         error=f"job process exited unexpectedly (rc={rc})")
            return
        if preempted:
            logger.warning("job %s: rescheduling after preemption "
                           "(%d so far) from its round checkpoint",
                           job_id, rec.preemptions)
        else:
            logger.warning("job %s: restarting from its checkpoint "
                           "(restart %d/%d)", job_id, rec.restarts,
                           opts.max_restarts)
            # surface the restart on /metrics: per-job gauge (cleared at
            # finish like every job series) + the PS-lifetime total
            self.metrics.note_restart(job_id)
        try:
            self._spawn_standalone(rec)  # re-arms the watchdog
        except Exception as e:
            rec.restarting = False
            self._finish(job_id,
                         error=f"job process crashed (rc={rc}) and "
                               f"checkpoint restart failed: {e}")
            return
        rec.restarting = False

    def _requeue_preempted(self, job_id: str, rec: _JobRecord) -> None:
        """Hand an allocator-preempted task back to the scheduler queue
        (the record is already popped; the child process has exited, so
        its device partition frees immediately). The task carries the
        cumulative restart/preemption counters and resumes from its own
        round-granular checkpoint when the allocator re-places it."""
        self._release_partition(rec)
        self.metrics.running_total.inc("train", -1.0)
        task = rec.task
        task.state = "queued"
        task.elapsed_time_s = -1.0
        task.parameters.resume_from = job_id
        task.restarts = rec.restarts
        task.preemptions = rec.preemptions
        logger.warning("job %s: handing preempted task back to the "
                       "scheduler queue (preemptions=%d, restarts=%d)",
                       job_id, rec.preemptions, rec.restarts)
        self._persist_jobs()
        # bounded retry with jittered backoff: the scheduler may be
        # mid-restart (control-plane recovery window) — one failed POST
        # must not strand the job forever
        delay = 0.1
        for attempt in range(5):
            try:
                http_json("POST", f"{self.scheduler_url}/requeue",
                          task.to_dict(), trace_id=task.trace_id or None)
                return
            except KubeMLException as e:
                if attempt == 4:
                    logger.error("requeue of preempted job %s failed "
                                 "after %d attempts: %s — the job is "
                                 "stranded until resubmitted", job_id,
                                 attempt + 1, e.message)
                    return
                logger.warning("requeue of %s failed (attempt %d/5): "
                               "%s — retrying", job_id, attempt + 1,
                               e.message)
                time.sleep(delay * (0.5 + random.random() / 2))
                delay = min(delay * 2, 2.0)

    def _wait_job_ready(self, proc: subprocess.Popen, port_file: str,
                        timeout: Optional[float] = None) -> str:
        """Poll for the child's bound port, then its /health — the
        reference's waitForPodRunning loop (job_pod.go:18-62; longer
        timeout here because the child pays JAX import + backend init).
        KUBEML_JOB_START_TIMEOUT overrides the 120 s default — hosts
        under heavy CPU load (or cold container caches) can push a
        child's JAX init past it, which would fail the start (and
        consume a crash-restart attempt) spuriously."""
        if timeout is None:
            timeout = float(os.environ.get("KUBEML_JOB_START_TIMEOUT",
                                           120.0))
        deadline = time.monotonic() + timeout
        while not os.path.exists(port_file):
            if proc.poll() is not None:
                raise KubeMLException(
                    f"job process exited with {proc.returncode} "
                    "before binding", 500)
            if time.monotonic() > deadline:
                proc.terminate()
                raise KubeMLException("job process start timed out", 500)
            time.sleep(0.1)
        with open(port_file) as f:
            url = f"http://127.0.0.1:{int(f.read())}"
        while True:
            try:
                http_json("GET", f"{url}/health")
                return url
            except KubeMLException:
                if proc.poll() is not None:
                    raise KubeMLException(
                        f"job process exited with {proc.returncode} "
                        "before becoming healthy", 500)
                if time.monotonic() > deadline:
                    proc.terminate()
                    raise
                time.sleep(0.2)

    def _request_parallelism(self, task: TrainTask) -> Optional[int]:
        """Between-epoch parallelism negotiation (job.go:196-215)."""
        if self.scheduler_url is None:
            return None
        with self._jobs_lock:
            rec = self.jobs.get(task.job_id)
        if rec is None:
            return None
        # drop any stale answer from a previous timed-out round so the wait
        # below only observes the response to THIS request
        rec.update_event.clear()
        try:
            http_json("POST", f"{self.scheduler_url}/job", task.to_dict())
        except KubeMLException as e:
            logger.warning("scheduler unreachable for %s: %s", task.job_id,
                           e.message)
            return None
        # reference-shaped async path: the scheduler processes the request
        # from its queue and pushes POST /update/{jobId} to us
        if not rec.update_event.wait(timeout=60.0):
            logger.warning("no parallelism update for %s within 60s",
                           task.job_id)
            return None
        rec.update_event.clear()
        return rec.next_parallelism

    def _publish_metrics(self, m: MetricUpdate):
        # in-process twin of _h_metrics: thread jobs publish here
        # instead of POST /metrics/{jobId}, so /cost has to stash the
        # ledger snapshot on this path too
        self.metrics.update_job(m)
        if m.cost_programs:
            self._cost[m.job_id] = m.cost_programs
        self._observe_health(m)

    def _finish(self, job_id: str, error: Optional[str] = None):
        """Clear per-job series + notify the scheduler
        (ps/api.go:266-327)."""
        with self._jobs_lock:
            rec = self.jobs.get(job_id)
            if rec is not None and rec.restarting:
                # a finish racing the watchdog's respawn claim can only
                # be the DEAD incarnation's last message (the respawned
                # child does not exist yet): the restart owns the
                # record. A genuinely-finished job's checkpoint is
                # stamped completed, so the respawn resumes straight
                # into completion and re-delivers its finish.
                return
            rec = self.jobs.pop(job_id, None)
        if rec is None:
            return
        if rec.restarts or rec.preemptions:
            # stamp the watchdog restart/preemption counts into the
            # finished History record — the job process cannot know them
            # (each incarnation sees only its own lifetime); a failed job
            # that never saved a record simply has nothing to stamp
            try:
                h = self.history_store.get(job_id)
                h.data.restarts = rec.restarts
                h.data.preemptions = rec.preemptions
                self.history_store.save(h)
            except JobNotFoundError:
                pass
        if rec.proc is not None:
            # the job process exits after its finish notification; reap it
            # off-thread so this handler (called BY that process) returns
            threading.Thread(target=self._reap, args=(rec,),
                             name=f"reap-{job_id}", daemon=True).start()
        else:
            self._release_partition(rec)
        self.metrics.clear_job(job_id)
        self.health.clear(job_id)
        self.metrics.running_total.inc("train", -1.0)
        self._persist_jobs()
        if error:
            logger.warning("job %s exited with error: %s", job_id, error)
        if self.scheduler_url is not None:
            try:
                http_json("DELETE", f"{self.scheduler_url}/finish/{job_id}")
            except KubeMLException as e:
                logger.warning("could not notify scheduler finish: %s",
                               e.message)

    def _reap(self, rec: _JobRecord):
        proc = rec.proc
        try:
            proc.wait(30.0)
        except subprocess.TimeoutExpired:
            logger.warning("job process %d did not exit; killing", proc.pid)
            proc.kill()
            proc.wait()
        finally:
            # the device partition frees only once the process is GONE —
            # on TPU the chips stay held until exit
            self._release_partition(rec)

    def _release_partition(self, rec: _JobRecord):
        # atomic take-and-clear: concurrent releases (reaper + finish)
        # must free the slot exactly once, or a second release could
        # free a slot already re-leased to another job
        with self._jobs_lock:
            slot, rec.partition = rec.partition, None
            if slot is not None:
                self._busy_partitions.discard(slot)

    def stop(self):
        """Shut the HTTP server down AND terminate standalone job
        children — a dying PS must not leak orphan job processes (they
        outlive the deployment, keep retrying metric pushes against a
        dead endpoint, and hold inherited stdio pipes open, which
        blocks any parent waiting on those streams). The reference's
        analogue is pod garbage collection on PS teardown."""
        super().stop()
        self._reaper_stop.set()
        # stop the serving loops first: they fail their in-flight
        # streams with terminal events, so blocked /generate threads
        # unwind instead of waiting out their stream timeout. With a
        # drain grace budget, admission 503s first and in-flight
        # streams get that long to finish cleanly before the hard stop
        with self._serve_lock:
            serves = [svc for _, svc in self._serve.values()]
            self._serve.clear()
        for svc in serves:
            svc.stop(grace_s=self.serve_drain_grace_s)
        with self._jobs_lock:
            self._stopping = True  # no further spawns or crash-restarts
            recs = list(self.jobs.values())
            self.jobs.clear()
        for rec in recs:
            if rec.proc is not None and rec.proc.poll() is None:
                rec.proc.terminate()
            elif rec.job is not None:
                # threaded-mode jobs must stop too: the record is gone
                # from the index, so without the signal the in-process
                # training thread would keep dispatching rounds (and
                # writing checkpoints) against a stopped PS
                rec.job.stop()
        for rec in recs:
            if rec.proc is not None:
                try:
                    rec.proc.wait(10.0)
                except subprocess.TimeoutExpired:
                    rec.proc.kill()
                    rec.proc.wait()
            elif rec.thread is not None and rec.thread.is_alive():
                # bounded: the stop event is checked per-epoch, so a
                # long epoch may outlive this join — daemon threads
                # can't block interpreter exit either way
                rec.thread.join(10.0)
            self._release_partition(rec)

    def wait_for_job(self, job_id: str, timeout: Optional[float] = None
                     ) -> bool:
        """Test/experiment helper: wait until the job is done.

        Polls the job index rather than joining one process/thread
        handle: a crashed-and-restarting record keeps its registration
        across incarnations (rec.proc is transiently None mid-restart),
        so deregistration — not any single child's exit — is the "job
        finished" signal."""
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            with self._jobs_lock:
                if job_id not in self.jobs:
                    return True
            if deadline is not None and time.monotonic() > deadline:
                return False
            time.sleep(0.05)
