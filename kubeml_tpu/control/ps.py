"""Parameter Server manager — per-job lifecycle + metrics.

Parity with ml/pkg/ps/ (parameter_server.go, api.go): tracks a job index,
starts jobs, relays scheduler updates, receives metric updates and finish
signals, exports Prometheus gauges, serves the task list.

REST surface (ml/pkg/ps/api.go:335-345):
    POST   /start            start a task (body: TrainTask)
    POST   /update/{jobId}   apply a new parallelism for the next epoch
    POST   /metrics/{jobId}  metric update push (body: MetricUpdate)
    POST   /finish/{jobId}   job finished notification
    DELETE /stop/{jobId}     stop a running job
    GET    /tasks            running-task list
    GET    /metrics          Prometheus exposition (metrics.go:19)
    POST   /infer            run inference on a checkpointed model (our
                             addition: the reference scheduler invokes the
                             live function instead — scheduler/api.go:140 —
                             which only works while the job's tensors exist;
                             checkpoints fix that, SURVEY.md §3.3)

Jobs run as threads in this process — the reference's "threaded mode"
(STANDALONE_JOBS=false, ml/pkg/ps/api.go:211-217). The pod-per-job mode
maps to process-per-job on a TPU host and can be layered on later; the mesh
is shared either way since all chips belong to this host's slice.
"""

from __future__ import annotations

import logging
import threading
from typing import Dict, Optional

import numpy as np

from kubeml_tpu.api.errors import (InvalidArgsError, JobNotFoundError,
                                   KubeMLException)
from kubeml_tpu.api.types import MetricUpdate, TrainTask
from kubeml_tpu.control.httpd import JsonService, Raw, Request, http_json
from kubeml_tpu.data.registry import DatasetRegistry
from kubeml_tpu.metrics.prom import MetricsRegistry
from kubeml_tpu.models.base import KubeDataset
from kubeml_tpu.parallel.mesh import make_mesh
from kubeml_tpu.train.checkpoint import load_checkpoint
from kubeml_tpu.train.functionlib import FunctionRegistry
from kubeml_tpu.train.history import HistoryStore
from kubeml_tpu.train.job import JobCallbacks, TrainJob

logger = logging.getLogger("kubeml_tpu.ps")


class _JobRecord:
    def __init__(self, task: TrainTask, job: TrainJob,
                 thread: threading.Thread):
        self.task = task
        self.job = job
        self.thread = thread
        self.next_parallelism: Optional[int] = None
        self.update_event = threading.Event()


class ParameterServer(JsonService):
    name = "ps"

    def __init__(self, mesh=None, port: int = 0,
                 scheduler_url: Optional[str] = None):
        super().__init__(port=port)
        self.mesh = mesh if mesh is not None else make_mesh()
        self.scheduler_url = scheduler_url
        self.jobs: Dict[str, _JobRecord] = {}
        self._jobs_lock = threading.RLock()
        self.metrics = MetricsRegistry()
        self.fn_registry = FunctionRegistry()
        self.ds_registry = DatasetRegistry()
        self.history_store = HistoryStore()

        self.route("POST", "/start", self._h_start)
        self.route("POST", "/update/{jobId}", self._h_update)
        self.route("POST", "/metrics/{jobId}", self._h_metrics)
        self.route("POST", "/finish/{jobId}", self._h_finish)
        self.route("DELETE", "/stop/{jobId}", self._h_stop)
        self.route("GET", "/tasks", self._h_tasks)
        self.route("GET", "/metrics", self._h_prom)
        self.route("POST", "/infer", self._h_infer)

    # ------------------------------------------------------------- handlers

    def _h_start(self, req: Request):
        task = TrainTask.from_dict(req.body)
        self.start_task(task)
        return {"job_id": task.job_id}

    def _h_update(self, req: Request):
        job_id = req.params["jobId"]
        with self._jobs_lock:
            rec = self.jobs.get(job_id)
        if rec is None:
            raise JobNotFoundError(job_id)
        rec.next_parallelism = int(req.body["parallelism"])
        rec.update_event.set()
        return {"ok": True}

    def _h_metrics(self, req: Request):
        self.metrics.update_job(MetricUpdate.from_dict(req.body))
        return {"ok": True}

    def _h_finish(self, req: Request):
        self._finish(req.params["jobId"], req.body.get("error")
                     if isinstance(req.body, dict) else None)
        return {"ok": True}

    def _h_stop(self, req: Request):
        job_id = req.params["jobId"]
        with self._jobs_lock:
            rec = self.jobs.get(job_id)
        if rec is None:
            raise JobNotFoundError(job_id)
        rec.job.stop()
        rec.task.state = "stopping"
        return {"ok": True}

    def _h_tasks(self, req: Request):
        with self._jobs_lock:
            return [r.task.to_dict() for r in self.jobs.values()]

    def _h_prom(self, req: Request):
        return Raw(self.metrics.exposition().encode(),
                   "text/plain; version=0.0.4")

    def _h_infer(self, req: Request):
        model_id = req.body.get("model_id")
        if not model_id:
            raise InvalidArgsError("model_id required")
        variables, manifest = load_checkpoint(model_id)
        model_cls, _ = self.fn_registry.resolve(
            manifest.get("function") or manifest.get("model"))
        model = model_cls()
        preds = model.infer(variables, np.asarray(req.body.get("data")))
        return {"predictions": np.asarray(preds).tolist()}

    # ------------------------------------------------------------- job mgmt

    def start_task(self, task: TrainTask) -> None:
        """Instantiate model/dataset from the function registry and launch
        the job thread (ps/api.go:139-222 without the pod machinery)."""
        fn_name = task.parameters.function_name or task.parameters.model_type
        model_cls, dataset_cls = self.fn_registry.resolve(fn_name)
        model = model_cls()
        dataset = (dataset_cls(task.parameters.dataset) if dataset_cls
                   else KubeDataset(task.parameters.dataset))

        from kubeml_tpu.api.const import kubeml_home
        import os
        job = TrainJob(task, model, dataset, self.mesh,
                       registry=self.ds_registry,
                       history_store=self.history_store,
                       callbacks=JobCallbacks(
                           request_parallelism=self._request_parallelism,
                           publish_metrics=self._publish_metrics,
                           on_finish=self._finish),
                       log_file=os.path.join(kubeml_home(), "logs",
                                             f"{task.job_id}.log"))
        thread = threading.Thread(target=self._run_job, args=(job,),
                                  name=f"job-{task.job_id}", daemon=True)
        with self._jobs_lock:
            if task.job_id in self.jobs:
                raise InvalidArgsError(f"job {task.job_id} already exists")
            self.jobs[task.job_id] = _JobRecord(task, job, thread)
        self.metrics.running_total.inc("train")
        task.state = "running"
        thread.start()

    def _run_job(self, job: TrainJob):
        try:
            job.train()
        except Exception:
            logger.exception("job %s thread failed", job.task.job_id)

    def _request_parallelism(self, task: TrainTask) -> Optional[int]:
        """Between-epoch parallelism negotiation (job.go:196-215)."""
        if self.scheduler_url is None:
            return None
        with self._jobs_lock:
            rec = self.jobs.get(task.job_id)
        if rec is None:
            return None
        # drop any stale answer from a previous timed-out round so the wait
        # below only observes the response to THIS request
        rec.update_event.clear()
        try:
            http_json("POST", f"{self.scheduler_url}/job", task.to_dict())
        except KubeMLException as e:
            logger.warning("scheduler unreachable for %s: %s", task.job_id,
                           e.message)
            return None
        # reference-shaped async path: the scheduler processes the request
        # from its queue and pushes POST /update/{jobId} to us
        if not rec.update_event.wait(timeout=60.0):
            logger.warning("no parallelism update for %s within 60s",
                           task.job_id)
            return None
        rec.update_event.clear()
        return rec.next_parallelism

    def _publish_metrics(self, m: MetricUpdate):
        self.metrics.update_job(m)

    def _finish(self, job_id: str, error: Optional[str] = None):
        """Clear per-job series + notify the scheduler
        (ps/api.go:266-327)."""
        with self._jobs_lock:
            rec = self.jobs.pop(job_id, None)
        if rec is None:
            return
        self.metrics.clear_job(job_id)
        self.metrics.running_total.inc("train", -1.0)
        if error:
            logger.warning("job %s exited with error: %s", job_id, error)
        if self.scheduler_url is not None:
            try:
                http_json("DELETE", f"{self.scheduler_url}/finish/{job_id}")
            except KubeMLException as e:
                logger.warning("could not notify scheduler finish: %s",
                               e.message)

    def wait_for_job(self, job_id: str, timeout: Optional[float] = None
                     ) -> bool:
        """Test/experiment helper: join a job thread."""
        with self._jobs_lock:
            rec = self.jobs.get(job_id)
        if rec is None:
            return True
        rec.thread.join(timeout)
        return not rec.thread.is_alive()
