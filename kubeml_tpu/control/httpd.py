"""Minimal JSON-over-HTTP service framework (stdlib only).

The reference's control plane is JSON/HTTP via gorilla-mux (SURVEY.md §2b);
this is the equivalent on a TPU host: a ThreadingHTTPServer with pattern
routes, the shared error envelope (ml/pkg/error/error.go), and JSON helpers.
Kept deliberately tiny — the control plane was never the hot path.
"""

from __future__ import annotations

import http.client
import json
import logging
import re
import threading
import time
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Callable, Dict, List, Optional, Tuple

from kubeml_tpu.api.errors import KubeMLException, check_error
from kubeml_tpu.metrics.prom import HttpMetrics
from kubeml_tpu.utils.trace import (TRACE_HEADER, get_trace_context,
                                    set_trace_context)

logger = logging.getLogger("kubeml_tpu.http")


class Raw:
    """Non-JSON response (e.g. Prometheus text exposition).

    `headers` adds extra response headers — e.g. the serving plane's
    429s carry Retry-After so shed clients back off by contract."""

    def __init__(self, payload: bytes, content_type: str = "text/plain",
                 status: int = 200,
                 headers: Optional[Dict[str, str]] = None):
        self.payload = payload
        self.content_type = content_type
        self.status = status
        self.headers = headers


class Stream:
    """Chunked (streaming) response: `chunks` is an iterable of bytes,
    written as HTTP/1.1 chunked transfer encoding as they are produced —
    the serving plane's per-token /generate lines.

    If the client disconnects mid-stream the iterator is close()d (a
    generator sees GeneratorExit), which is the handler's cancellation
    hook — wrap the body in try/finally to release the stream's slot."""

    def __init__(self, chunks, content_type: str = "application/x-ndjson",
                 status: int = 200,
                 headers: Optional[Dict[str, str]] = None):
        self.chunks = chunks
        self.content_type = content_type
        self.status = status
        self.headers = headers


class Route:
    def __init__(self, method: str, pattern: str, handler: Callable):
        self.method = method
        self.pattern = pattern
        # '/train/{jobId}' -> ^/train/(?P<jobId>[^/]+)$
        regex = re.sub(r"\{(\w+)\}", r"(?P<\1>[^/]+)", pattern)
        self.regex = re.compile(f"^{regex}$")
        self.handler = handler


class JsonService:
    """Base class: subclasses call .route() then .start().

    Every request goes through a small middleware layer: the
    X-KubeML-Trace-Id header (if present) is bound to the handler thread
    so any `http_json` call the handler makes propagates it downstream,
    and request latency/status are recorded per endpoint *pattern* in
    `self.http_metrics` (exposed on GET /metrics; subclasses with their
    own /metrics route fold `http_metrics.exposition()` in themselves).
    The clock is injectable for deterministic latency tests.
    """

    name = "service"

    def __init__(self, port: int = 0, host: str = "127.0.0.1",
                 clock: Optional[Callable[[], float]] = None):
        self._routes: List[Route] = []
        self._host = host
        self._port = port
        self._server: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None
        self._clock = clock or time.perf_counter
        self.http_metrics = HttpMetrics(self.name)
        self.route("GET", "/health", lambda req: {"ok": True})

    def route(self, method: str, pattern: str, handler: Callable):
        # re-registering a (method, pattern) replaces the earlier route
        # (matching is first-wins), so a subclass can extend a base
        # route — e.g. the PS folds a job-health verdict into /health
        # while keeping the bare-liveness behavior
        self._routes = [r for r in self._routes
                        if not (r.method == method
                                and r.pattern == pattern)]
        self._routes.append(Route(method, pattern, handler))

    def _h_default_metrics(self, req):
        return Raw(self.http_metrics.exposition().encode(),
                   "text/plain; version=0.0.4")

    # ------------------------------------------------------------ lifecycle

    def start(self) -> int:
        service = self
        # default /metrics (HTTP middleware series only) unless the
        # subclass registered its own — deferred to start() so a
        # subclass route wins even though __init__ runs first
        if not any(r.method == "GET" and r.pattern == "/metrics"
                   for r in self._routes):
            self.route("GET", "/metrics", self._h_default_metrics)

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, fmt, *args):
                logger.debug("%s %s", service.name, fmt % args)

            def _dispatch(self, method):
                t0 = service._clock()
                self._status = 0
                self._endpoint = "<unmatched>"
                trace_id = self.headers.get(TRACE_HEADER)
                prev_trace = get_trace_context()
                if trace_id:
                    set_trace_context(trace_id)
                try:
                    self._handle(method)
                finally:
                    if trace_id:
                        set_trace_context(prev_trace)
                    try:
                        service.http_metrics.observe(
                            method, self._endpoint, self._status,
                            service._clock() - t0)
                    except Exception:
                        logger.exception("%s: http metrics observe failed",
                                         service.name)

            def _handle(self, method):
                path = self.path.split("?")[0]
                query = {}
                if "?" in self.path:
                    from urllib.parse import parse_qsl
                    query = dict(parse_qsl(self.path.split("?", 1)[1]))
                body = None
                length = int(self.headers.get("Content-Length") or 0)
                raw = self.rfile.read(length) if length else b""
                if raw:
                    try:
                        body = json.loads(raw)
                    except ValueError:
                        body = raw
                for r in service._routes:
                    if r.method != method:
                        continue
                    m = r.regex.match(path)
                    if not m:
                        continue
                    self._endpoint = r.pattern
                    try:
                        req = Request(path=path, params=m.groupdict(),
                                      query=query, body=body, raw=raw,
                                      headers=dict(self.headers))
                        out = r.handler(req)
                        if isinstance(out, Stream):
                            self._reply_stream(out)
                        elif isinstance(out, Raw):
                            self._reply(out.status, out.payload,
                                        out.content_type, out.headers)
                        else:
                            payload = json.dumps(out if out is not None
                                                 else {}).encode()
                            self._reply(200, payload)
                    except KubeMLException as e:
                        self._reply(e.status_code, e.to_json().encode())
                    except Exception as e:  # 500 envelope
                        logger.exception("%s %s %s failed", service.name,
                                         method, path)
                        self._reply(500, json.dumps(
                            {"code": 500, "error": str(e)}).encode())
                    return
                self._reply(404, json.dumps(
                    {"code": 404, "error": f"no route {method} {path}"}
                ).encode())

            def _reply(self, code, payload: bytes,
                       content_type: str = "application/json",
                       headers: Optional[Dict[str, str]] = None):
                self._status = code
                self.send_response(code)
                self.send_header("Content-Type", content_type)
                self.send_header("Content-Length", str(len(payload)))
                for key, value in (headers or {}).items():
                    self.send_header(key, str(value))
                self.end_headers()
                self.wfile.write(payload)

            def _reply_stream(self, out: "Stream"):
                """Write a Stream as chunked transfer encoding. Once the
                status line is on the wire nothing can turn a mid-stream
                failure into a 500, so errors here only close the
                connection; handler-side errors must surface as in-band
                stream items instead."""
                self._status = out.status
                self.send_response(out.status)
                self.send_header("Content-Type", out.content_type)
                for key, value in (out.headers or {}).items():
                    self.send_header(key, str(value))
                self.send_header("Transfer-Encoding", "chunked")
                self.end_headers()
                try:
                    for chunk in out.chunks:
                        if not chunk:
                            continue
                        self.wfile.write(b"%x\r\n" % len(chunk)
                                         + chunk + b"\r\n")
                        self.wfile.flush()
                    self.wfile.write(b"0\r\n\r\n")
                except OSError:
                    # client went away mid-stream: the finally clause
                    # close()s the producer (its cancellation hook) and
                    # this connection cannot be reused
                    self.close_connection = True
                except Exception:
                    logger.exception("%s: stream producer failed",
                                     service.name)
                    self.close_connection = True
                finally:
                    close = getattr(out.chunks, "close", None)
                    if close is not None:
                        try:
                            close()
                        except Exception:
                            logger.exception("%s: stream close failed",
                                             service.name)

            def do_GET(self):
                self._dispatch("GET")

            def do_POST(self):
                self._dispatch("POST")

            def do_DELETE(self):
                self._dispatch("DELETE")

            def do_PUT(self):
                self._dispatch("PUT")

        self._server = ThreadingHTTPServer((self._host, self._port), Handler)
        self._server.daemon_threads = True
        self._port = self._server.server_address[1]
        self._thread = threading.Thread(
            target=self._server.serve_forever, name=f"{self.name}-http",
            daemon=True)
        self._thread.start()
        logger.info("%s listening on %s:%d", self.name, self._host,
                    self._port)
        return self._port

    def stop(self):
        if self._server:
            self._server.shutdown()
            self._server.server_close()
            self._server = None

    @property
    def url(self) -> str:
        return f"http://{self._host}:{self._port}"

    @property
    def port(self) -> int:
        return self._port


class Request:
    def __init__(self, path: str, params: Dict[str, str],
                 query: Dict[str, str], body: Any, raw: bytes,
                 headers: Optional[Dict[str, str]] = None):
        self.path = path
        self.params = params
        self.query = query
        self.body = body
        self.raw = raw
        self.headers = headers or {}


# ------------------------------------------------------------------ client

def http_json(method: str, url: str, body: Any = None,
              timeout: float = 300.0, raw_body: Optional[bytes] = None,
              content_type: Optional[str] = None,
              trace_id: Optional[str] = None) -> Any:
    """JSON request helper with the shared error envelope.

    Pass raw_body/content_type instead of body for opaque payloads (e.g.
    multipart uploads); the response is still parsed as JSON.

    The thread's trace context (or an explicit trace_id) is attached as
    the X-KubeML-Trace-Id header, so a request handled inside a traced
    server thread propagates the id downstream without every call site
    knowing about tracing.
    """
    headers = {}
    trace_id = trace_id or get_trace_context()
    if trace_id:
        headers[TRACE_HEADER] = trace_id
    if raw_body is not None:
        data = raw_body
        if content_type:
            headers["Content-Type"] = content_type
    elif body is not None:
        data = json.dumps(body).encode()
        headers["Content-Type"] = "application/json"
    else:
        data = None
    req = urllib.request.Request(url, data=data, method=method,
                                 headers=headers)
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            payload = resp.read()
            return json.loads(payload) if payload else None
    except urllib.error.HTTPError as e:
        check_error(e.code, e.read())
    except urllib.error.URLError as e:
        raise KubeMLException(f"cannot reach {url}: {e.reason}", 503)
    except (http.client.HTTPException, OSError) as e:
        # transport-level failures urllib does not wrap (e.g.
        # RemoteDisconnected when the peer dies mid-request) must map to
        # the same retryable 503 envelope as unreachable hosts — the
        # PS's retried /start push (and every other caller with retry
        # logic) keys on KubeMLException, and a raw exception here would
        # escape those loops and fail the operation on one hiccup
        raise KubeMLException(f"cannot reach {url}: {e}", 503)
