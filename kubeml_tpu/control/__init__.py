from kubeml_tpu.control.policy import ThroughputBasedPolicy
from kubeml_tpu.control.scheduler import Scheduler, SchedulerQueue
from kubeml_tpu.control.ps import ParameterServer
from kubeml_tpu.control.controller import Controller
from kubeml_tpu.control.storage import StorageService
from kubeml_tpu.control.client import KubemlClient
from kubeml_tpu.control.deployment import Deployment, start_deployment

__all__ = ["ThroughputBasedPolicy", "Scheduler", "SchedulerQueue",
           "ParameterServer", "Controller", "StorageService", "KubemlClient",
           "Deployment", "start_deployment"]
