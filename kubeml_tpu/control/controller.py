"""Controller — the public API gateway.

Parity with ml/pkg/controller/api.go:16-42:
    POST   /train              -> scheduler /train
    POST   /infer              -> scheduler /infer
    GET    /dataset            -> dataset summaries (storageApi.go:70-189)
    POST   /dataset/{name}     -> proxied to the storage service
                                  (storageApi.go:35-67 ReverseProxy)
    DELETE /dataset/{name}     -> storage service delete
    GET    /dataset/{name}     -> single summary
    GET    /tasks              -> PS task list (tasksApi.go:10-36)
    DELETE /tasks/{jobId}      -> PS stop
    GET    /history            -> all histories (historyApi.go:14-111)
    GET    /history/{taskId}   -> one history
    DELETE /history/{taskId}   -> delete one
    DELETE /history            -> prune all
    GET    /health
    GET    /health/{jobId}     -> PS training-health verdict
"""

from __future__ import annotations

import logging
from typing import Optional

from kubeml_tpu.api.errors import KubeMLException
from kubeml_tpu.control.httpd import JsonService, Request, http_json
from kubeml_tpu.data.registry import DatasetRegistry
from kubeml_tpu.train.history import HistoryStore

logger = logging.getLogger("kubeml_tpu.controller")


class Controller(JsonService):
    name = "controller"

    def __init__(self, scheduler_url: Optional[str] = None,
                 ps_url: Optional[str] = None,
                 storage_url: Optional[str] = None, port: int = 0,
                 registry: Optional[DatasetRegistry] = None,
                 history_store: Optional[HistoryStore] = None):
        super().__init__(port=port)
        self.scheduler_url = scheduler_url
        self.ps_url = ps_url
        self.storage_url = storage_url
        self.registry = registry or DatasetRegistry()
        self.history_store = history_store or HistoryStore()

        self.route("POST", "/train", self._h_train)
        self.route("POST", "/infer", self._h_infer)
        self.route("GET", "/dataset", self._h_dataset_list)
        self.route("GET", "/dataset/{name}", self._h_dataset_get)
        self.route("POST", "/dataset/{name}", self._h_dataset_create)
        self.route("POST", "/dataset/{name}/append", self._h_dataset_append)
        self.route("DELETE", "/dataset/{name}", self._h_dataset_delete)
        self.route("GET", "/tasks", self._h_tasks)
        self.route("DELETE", "/tasks/{jobId}", self._h_task_stop)
        self.route("GET", "/cluster", self._h_cluster)
        self.route("GET", "/trace/{jobId}", self._h_trace)
        self.route("GET", "/cost/{jobId}", self._h_cost)
        # /health stays the gateway's own liveness probe; the job-health
        # verdict gets its own path segment
        self.route("GET", "/health/{jobId}", self._h_job_health)
        self.route("GET", "/history", self._h_history_list)
        self.route("GET", "/history/{taskId}", self._h_history_get)
        self.route("DELETE", "/history/{taskId}", self._h_history_delete)
        self.route("DELETE", "/history", self._h_history_prune)
        # function registry routes (net-new surface: the reference CLI talks
        # to the Fission CRD API directly for these, cmd/function.go:96-128;
        # here the registry lives on the serving host so the API covers it)
        self.route("GET", "/functions", self._h_fn_list)
        self.route("GET", "/functions/{name}", self._h_fn_get)
        self.route("POST", "/functions/{name}", self._h_fn_create)
        self.route("DELETE", "/functions/{name}", self._h_fn_delete)

    def _need(self, url, what):
        if url is None:
            raise KubeMLException(f"no {what} configured", 503)
        return url

    # ------------------------------------------------------------ train/infer

    def _h_train(self, req: Request):
        return http_json("POST",
                         f"{self._need(self.scheduler_url, 'scheduler')}/train",
                         req.body)

    def _h_infer(self, req: Request):
        return http_json("POST",
                         f"{self._need(self.scheduler_url, 'scheduler')}/infer",
                         req.body)

    # -------------------------------------------------------------- datasets

    def _h_dataset_list(self, req: Request):
        return [s.to_dict() for s in self.registry.list()]

    def _h_dataset_get(self, req: Request):
        return self.registry.get(req.params["name"]).summary().to_dict()

    def _h_dataset_create(self, req: Request):
        """Reverse-proxy the multipart upload to the storage service
        (storageApi.go:35-67)."""
        url = f"{self._need(self.storage_url, 'storage service')}" \
              f"/dataset/{req.params['name']}"
        return http_json("POST", url, raw_body=req.raw,
                         content_type=req.headers.get("Content-Type", ""),
                         timeout=600)

    def _h_dataset_append(self, req: Request):
        """Reverse-proxy a generation-tagged append, preserving the
        ?generation=/?retention= query the storage service validates."""
        from urllib.parse import urlencode
        url = f"{self._need(self.storage_url, 'storage service')}" \
              f"/dataset/{req.params['name']}/append"
        if req.query:
            url += "?" + urlencode(req.query)
        return http_json("POST", url, raw_body=req.raw,
                         content_type=req.headers.get("Content-Type", ""),
                         timeout=600)

    def _h_dataset_delete(self, req: Request):
        return http_json(
            "DELETE",
            f"{self._need(self.storage_url, 'storage service')}"
            f"/dataset/{req.params['name']}")

    # ----------------------------------------------------------------- tasks

    def _h_tasks(self, req: Request):
        return http_json("GET", f"{self._need(self.ps_url, 'PS')}/tasks")

    def _h_task_stop(self, req: Request):
        return http_json(
            "DELETE",
            f"{self._need(self.ps_url, 'PS')}/stop/{req.params['jobId']}")

    def _h_cluster(self, req: Request):
        """Cluster-allocator snapshot (pool, queues, tenant shares), proxied
        to the scheduler which owns the allocator; 503 when the deployment
        runs without cluster mode."""
        return http_json(
            "GET",
            f"{self._need(self.scheduler_url, 'scheduler')}/cluster")

    def _h_trace(self, req: Request):
        """Merged job timeline, proxied to the PS (which owns the trace
        directory) so `kubeml trace --id` needs only the gateway URL."""
        return http_json(
            "GET",
            f"{self._need(self.ps_url, 'PS')}/trace"
            f"?id={req.params['jobId']}")

    def _h_cost(self, req: Request):
        """Per-program analytic cost attribution, proxied to the PS
        (which holds the latest ledger snapshots) so `kubeml cost --id`
        needs only the gateway URL."""
        return http_json(
            "GET",
            f"{self._need(self.ps_url, 'PS')}/cost"
            f"?id={req.params['jobId']}")

    def _h_job_health(self, req: Request):
        """Training-health verdict, proxied to the PS (which owns the
        rolling metric windows) so `kubeml health/top --id` need only
        the gateway URL."""
        return http_json(
            "GET",
            f"{self._need(self.ps_url, 'PS')}/health"
            f"?id={req.params['jobId']}")

    # --------------------------------------------------------------- history

    def _h_history_list(self, req: Request):
        return [h.to_dict() for h in self.history_store.list()]

    def _h_history_get(self, req: Request):
        return self.history_store.get(req.params["taskId"]).to_dict()

    def _h_history_delete(self, req: Request):
        self.history_store.delete(req.params["taskId"])
        return {"ok": True}

    def _h_history_prune(self, req: Request):
        return {"deleted": self.history_store.prune()}

    # ------------------------------------------------------------- functions

    @property
    def _fn_registry(self):
        from kubeml_tpu.train.functionlib import FunctionRegistry
        return FunctionRegistry()

    def _h_fn_list(self, req: Request):
        from kubeml_tpu.models import builtin_names
        reg = self._fn_registry
        return ([{"name": n, "kind": "user"} for n in reg.list()]
                + [{"name": n, "kind": "builtin"} for n in builtin_names()])

    def _h_fn_get(self, req: Request):
        self._fn_registry.resolve(req.params["name"])  # raises 404 if absent
        return {"name": req.params["name"]}

    def _h_fn_create(self, req: Request):
        import tempfile
        with tempfile.NamedTemporaryFile("wb", suffix=".py") as f:
            f.write(req.raw)
            f.flush()
            self._fn_registry.create(req.params["name"], f.name)
        return {"name": req.params["name"]}

    def _h_fn_delete(self, req: Request):
        self._fn_registry.delete(req.params["name"])
        return {"ok": True}
