"""Durable decision journal for the control plane.

The data plane survives anything (round checkpoints, serve re-prefill,
fleet failure domains) but the orchestrator's own state — allocator
gangs/quotas/deficits, the scheduler queue, the PS registries — lived
only in process memory. This module is the persistence primitive that
fixes that: a CRC-framed write-ahead journal plus an atomically-written
compaction snapshot, both under ``$KUBEML_HOME/control/``.

Frame format (append-only file ``<name>.journal``):

    [u32 payload_len][u32 crc32(payload)][payload: canonical JSON]

Every payload carries its own monotone record index ``"i"`` so replay
composes with compaction: ``compact(state)`` first writes
``<name>.snapshot.json`` = ``{"index": last, "state": ...}`` via
tmp+rename, then truncates the journal — a crash BETWEEN the two steps
leaves stale records behind, and replay simply skips any record with
``i <= snapshot.index``. No ordering between snapshot and journal is
ever load-bearing beyond that.

Corruption policy (the load-bearing distinction):

  - a torn/truncated TAIL — short header, short payload, or a bad CRC on
    the final frame — is the expected signature of a crash mid-append.
    Replay drops it, repairs the file by truncating at the last valid
    frame, and counts ``torn_drops``. Never mis-replayed.
  - a corrupt record MID-FILE (bad CRC with valid bytes after it) means
    the journal itself is damaged. Replay raises
    :class:`JournalCorruptError` loudly — silently skipping past valid
    records would resurrect a state the allocator never held.

Fault injection: an optional ``ControlFaultPlan`` (faults.py) fires
``control_crash`` (die after a durable append), ``control_torn_write``
(die mid-append leaving a partial frame), and ``control_slow_recover``
(dilate replay) at named record indices, raising
:class:`kubeml_tpu.faults.ControlCrash` so tests and the bench's
``control_chaos`` arm can kill the control plane at exact coordinates.
"""

from __future__ import annotations

import json
import logging
import os
import struct
import zlib
from typing import Any, List, Optional, Tuple

logger = logging.getLogger("kubeml_tpu.journal")

_HEADER = struct.Struct("<II")   # payload length, crc32(payload)


class JournalCorruptError(RuntimeError):
    """A complete journal frame failed its CRC (or decoded to garbage)
    with valid records after it — the journal is damaged, not torn.
    Recovery must fail loudly; replaying around the hole would
    reconstruct a state the allocator never held."""


def atomic_write_json(path: str, doc: Any) -> None:
    """Write ``doc`` as JSON via tmp+rename so readers (and a recovery
    after a crash mid-write) never observe a partial file."""
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(doc, f, sort_keys=True)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


def read_json(path: str) -> Optional[Any]:
    """Load a JSON state file; None when absent. A half-written file
    cannot exist (atomic_write_json), so a parse error here is real
    corruption and propagates."""
    try:
        with open(path) as f:
            return json.load(f)
    except FileNotFoundError:
        return None


class DecisionJournal:
    """CRC-framed write-ahead journal + compaction snapshot for one
    control-plane role. Synchronous and deterministic: no threads, no
    wall clock — callers decide when to append and when to compact."""

    def __init__(self, directory: str, name: str = "allocator",
                 fault_plan: Optional[Any] = None):
        self.dir = directory
        os.makedirs(directory, exist_ok=True)
        self.journal_path = os.path.join(directory, f"{name}.journal")
        self.snapshot_path = os.path.join(directory,
                                          f"{name}.snapshot.json")
        self.fault_plan = fault_plan
        self._fh = None
        # None until the first append or replay fixes it from disk
        self.next_index: Optional[int] = None
        # lifetime-of-this-process counters (cumulative totals that must
        # survive restart ride the OWNER's journaled state instead)
        self.records_appended = 0
        self.compactions = 0
        self.torn_drops = 0

    # ------------------------------------------------------------- internals

    def _handle(self):
        if self._fh is None:
            self._fh = open(self.journal_path, "ab")
        return self._fh

    def close(self) -> None:
        if self._fh is not None:
            self._fh.close()
            self._fh = None

    def _read_frames(self) -> Tuple[List[dict], int]:
        """All complete, CRC-valid frames plus the byte offset of the
        first bad/torn one (== file size when clean). Raises
        JournalCorruptError on a bad frame that is NOT the tail."""
        try:
            with open(self.journal_path, "rb") as f:
                data = f.read()
        except FileNotFoundError:
            return [], 0
        frames: List[dict] = []
        off, n = 0, len(data)
        while off < n:
            if n - off < _HEADER.size:
                break                                # torn header at EOF
            length, crc = _HEADER.unpack_from(data, off)
            end = off + _HEADER.size + length
            if end > n:
                break                                # torn payload at EOF
            payload = data[off + _HEADER.size:end]
            if zlib.crc32(payload) != crc:
                if end == n:
                    break                            # torn final frame
                raise JournalCorruptError(
                    f"{self.journal_path}: CRC mismatch at byte {off} "
                    f"with {n - end} valid byte(s) after it — journal "
                    f"is corrupt, refusing to replay around the hole")
            try:
                frames.append(json.loads(payload))
            except ValueError as e:
                raise JournalCorruptError(
                    f"{self.journal_path}: frame at byte {off} passed "
                    f"CRC but is not JSON: {e}") from None
            off = end
        return frames, off

    def _repair_tail(self, valid_bytes: int) -> None:
        """Truncate the journal at the last valid frame so future
        appends extend a clean file, not a garbage tail."""
        try:
            size = os.path.getsize(self.journal_path)
        except FileNotFoundError:
            return
        if size <= valid_bytes:
            return
        self.close()
        with open(self.journal_path, "r+b") as f:
            f.truncate(valid_bytes)
        self.torn_drops += 1
        logger.warning("journal %s: dropped torn tail (%d byte(s) after "
                       "offset %d)", self.journal_path,
                       size - valid_bytes, valid_bytes)

    # --------------------------------------------------------------- surface

    def replay(self) -> Tuple[Optional[dict], List[dict]]:
        """(snapshot state or None, tail records after the snapshot).

        Repairs a torn tail in place, raises JournalCorruptError on
        mid-file damage, fires control_slow_recover, and leaves
        ``next_index`` pointing one past the last durable record."""
        if self.fault_plan is not None:
            self.fault_plan.sleep_recover()
        snap = read_json(self.snapshot_path)
        snap_index = -1
        state = None
        if snap is not None:
            snap_index = int(snap["index"])
            state = snap["state"]
        frames, valid_bytes = self._read_frames()
        self._repair_tail(valid_bytes)
        tail = [r for r in frames if int(r["i"]) > snap_index]
        last = tail[-1]["i"] if tail else snap_index
        self.next_index = int(last) + 1
        return state, tail

    def append(self, record: dict) -> int:
        """Durably append one record; returns its index. The record
        gains an ``"i"`` key. Fault hooks: control_torn_write writes a
        partial frame then raises ControlCrash; control_crash raises
        AFTER the full frame is flushed (death-after-durable)."""
        if self.next_index is None:
            frames, valid_bytes = self._read_frames()
            self._repair_tail(valid_bytes)
            snap = read_json(self.snapshot_path)
            last = frames[-1]["i"] if frames else \
                (int(snap["index"]) if snap is not None else -1)
            self.next_index = int(last) + 1
        index = self.next_index
        record = dict(record)
        record["i"] = index
        payload = json.dumps(record, sort_keys=True).encode()
        frame = _HEADER.pack(len(payload), zlib.crc32(payload)) + payload
        fh = self._handle()
        plan = self.fault_plan
        if plan is not None and plan.torn_at(index):
            # die mid-write: a strict prefix of the frame reaches disk
            fh.write(frame[:max(1, len(frame) - 7)])
            fh.flush()
            self.close()
            from kubeml_tpu.faults import ControlCrash
            raise ControlCrash(
                f"injected control_torn_write at journal index {index}")
        fh.write(frame)
        fh.flush()
        self.next_index = index + 1
        self.records_appended += 1
        if plan is not None and plan.crash_at(index):
            self.close()
            from kubeml_tpu.faults import ControlCrash
            raise ControlCrash(
                f"injected control_crash after journal index {index}")
        return index

    def compact(self, state: dict) -> None:
        """Fold everything up to the last appended record into the
        snapshot, then truncate the journal. Each step is individually
        atomic; replay's ``i <= snapshot.index`` skip makes the pair
        crash-safe without any cross-file transaction."""
        if self.next_index is None:
            self.replay()
        atomic_write_json(self.snapshot_path,
                          {"index": self.next_index - 1, "state": state})
        self.close()
        with open(self.journal_path, "wb"):
            pass
        self.compactions += 1
