"""System constants.

Parity with the reference's hardcoded constants (ml/pkg/api/const.go:4-30 and
the performance-bounding constants catalogued in BASELINE.md), re-homed for a
single-host / multi-host TPU deployment: service addresses default to
localhost ports instead of Kubernetes cluster DNS.
"""

import os

# --- parallelism (ml/pkg/api/const.go:16,25) -------------------------------
DEFAULT_PARALLELISM = 5
DEBUG_PARALLELISM = 2

# --- storage granularity (ml/pkg/controller/storageApi.go:20,
#     python/kubeml/kubeml/util.py:10, python/storage/api.py:135) -----------
STORAGE_SUBSET_SIZE = 64

# --- CLI validation bound (ml/pkg/kubeml-cli/cmd/train.go:15) --------------
MAX_BATCH_SIZE = 1024

# --- scheduler throughput policy thresholds (ml/pkg/scheduler/policy.go:9-12)
POLICY_UPPER_BOUND = 1.2   # epoch slowed >= 20%  -> parallelism -1
POLICY_LOWER_BOUND = 1.05  # epoch within 5%      -> parallelism +1

# --- service ports (reference uses k8s DNS, ml/pkg/api/const.go:4-14;
#     we use localhost ports, overridable via env) --------------------------
CONTROLLER_PORT = int(os.environ.get("KUBEML_CONTROLLER_PORT", "9673"))
SCHEDULER_PORT = int(os.environ.get("KUBEML_SCHEDULER_PORT", "9674"))
PS_PORT = int(os.environ.get("KUBEML_PS_PORT", "9675"))
STORAGE_PORT = int(os.environ.get("KUBEML_STORAGE_PORT", "9676"))
METRICS_PORT = int(os.environ.get("KUBEML_METRICS_PORT", "9677"))

CONTROLLER_URL = os.environ.get("KUBEML_CONTROLLER_URL", f"http://127.0.0.1:{CONTROLLER_PORT}")
SCHEDULER_URL = os.environ.get("KUBEML_SCHEDULER_URL", f"http://127.0.0.1:{SCHEDULER_PORT}")
PS_URL = os.environ.get("KUBEML_PS_URL", f"http://127.0.0.1:{PS_PORT}")
STORAGE_URL = os.environ.get("KUBEML_STORAGE_URL", f"http://127.0.0.1:{STORAGE_PORT}")


def kubeml_home() -> str:
    """Root directory for the on-disk data/model/history planes.

    Replaces the reference's MongoDB + RedisAI deployments (SURVEY.md L0) with
    a host-filesystem layout suitable for TPU VM hosts.
    """
    return os.environ.get("KUBEML_TPU_HOME", os.path.expanduser("~/.kubeml_tpu"))
