"""Wire types shared by every component.

Parity with ml/pkg/api/types.go:9-112 — same field set, same JSON key names
(snake/camel kept as the reference serializes them), so histories and train
requests are drop-in compatible for users of the reference system.
"""

from __future__ import annotations

import dataclasses
import json
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional


def _asdict(obj) -> dict:
    return dataclasses.asdict(obj)


@dataclass
class TrainOptions:
    """Tunable training options (ml/pkg/api/types.go:24-34)."""

    default_parallelism: int = 5
    static_parallelism: bool = False
    validate_every: int = 1
    k: int = 1                     # K-step local SGD period; -1 => once per epoch
    goal_accuracy: float = 100.0   # early-stop accuracy target (percent)
    # net-new vs the reference (which has no checkpointing, SURVEY.md §5):
    # checkpoint cadence in epochs. N > 0 = every N epochs; 0 (default) =
    # auto — snapshot whenever the job validates, so a running job is
    # inferable mid-run by default (the reference serves inference on a
    # live job's weights, scheduler/api.go:119-162 — our equivalent needs
    # a checkpoint on disk); -1 = final checkpoint only
    checkpoint_every: int = 0
    # net-new: training engine — 'kavg' is the reference's K-step local
    # SGD with weight averaging; 'syncdp' is per-step gradient averaging
    # with persistent optimizer state (parallel/syncdp.py; K is ignored)
    engine: str = "kavg"
    # net-new: reshuffle the epoch's document order each epoch. The
    # reference never shuffles (network.py:283 constructs its DataLoader
    # without shuffle), so False is parity; real-data convergence sweeps
    # want True
    shuffle: bool = False
    # net-new: inner mesh axes, per job (the reference's only axis is
    # data parallelism, SURVEY.md §2a). n_model > 1 = Megatron tensor
    # parallelism (model must publish tp_rules); n_seq > 1 = ring/ulysses
    # sequence parallelism (model must support enable_seq_parallel). The
    # job carves its mesh as data x model x seq from the deployment's
    # devices; data-axis size = devices / (n_model * n_seq).
    n_model: int = 1
    n_seq: int = 1
    # net-new: expert parallelism for MoE functions. Inside a manual
    # round (with n_seq > 1 or n_stage > 1) experts shard over the mesh
    # expert axis via parallel/manual.py ep_partial_ffn; standalone
    # (plain DP x EP) the GSPMD ep_mesh path shards them with XLA-
    # inserted token all-to-alls (parallel/ep.moe_apply).
    n_expert: int = 1
    # net-new: GPipe pipeline parallelism — the decoder trunk splits
    # into n_stage groups of consecutive layers over the mesh stage
    # axis, microbatches ppermuting along the ICI ring (parallel/pp.py
    # pipeline_lane inside the fully-manual round). Transformer
    # families (GPT incl. MoE, BERT).
    n_stage: int = 1
    # microbatch count for the pipeline (0 = auto: 2 * n_stage); must
    # divide the per-worker batch size
    pp_microbatches: int = 0
    # net-new: FSDP (ZeRO-3) for the syncdp engine — parameters AND
    # optimizer state shard over the data axis (each chip stores 1/D of
    # the model; GSPMD all-gathers a layer's weights at its use site and
    # reduce-scatters the grads back — parallel/syncdp.py). Requires
    # engine='syncdp'; the kavg engine's semantics (per-round weight
    # average of full replicas) preclude parameter sharding.
    fsdp: bool = False
    # net-new: sync rounds executed per engine dispatch
    # (KAvgEngine.train_rounds — identical math, merges preserved);
    # > 1 amortizes per-round submission overhead, measured worth ~2-3%
    # headline throughput on tunneled backends
    # (results/round_probe_v5e.jsonl). Ignored (treated as 1) when
    # per-round host control is required: chaos hooks, multi-process
    # clusters, sequence-parallel batches.
    rounds_per_dispatch: int = 1
    seq_impl: str = "ring"         # 'ring' | 'ulysses'
    # TP execution strategy: 'gspmd' (NamedSharding placement, XLA
    # inserts the collectives — parallel/tp.py) or 'manual' (explicit
    # Megatron psums inside a fully-manual round — parallel/manual.py).
    # TP+SP combined always runs manual (GSPMD cannot ride the
    # fully-manual SP round); this flag picks the path for TP-only jobs.
    tp_impl: str = "gspmd"         # 'gspmd' | 'manual'
    # net-new guard: cap on scheduler-driven parallelism growth. The
    # reference's throughput policy only floor-clamps at 1
    # (policy.go:75-90), so a long dynamic job monotonically accretes
    # workers and re-lowers its round program at every change; 0 keeps
    # that parity behavior, N > 0 stops growth at N
    max_parallelism: int = 0
    # net-new recovery: how many times the PS restarts a standalone job
    # whose process dies without finishing (OOM-kill, segfault, host
    # eviction), resuming from the job's own latest checkpoint with its
    # history and topology restored. 0 disables (a dead process fails
    # the job, the pre-r4 behavior). The reference survives pod death
    # only within a single merge (util.go:144-166) and loses the job if
    # its TrainJob pod dies; checkpoint-based restart closes that gap.
    max_restarts: int = 1
    # net-new (on-device round assembly, data/device_cache.py): keep the
    # train split resident in HBM and feed rounds [W, S, B] int32 gather
    # indices instead of materialized batches. 'auto' enables it when
    # the job is structurally eligible (single process, no seq/pipeline/
    # manual-TP round, identity transform_train or a
    # transform_train_device hook) AND the per-chip footprint fits
    # device_cache_mb; 'on' forces it for eligible jobs regardless of
    # the budget (ineligible jobs get a 400); 'off' keeps host staging.
    device_cache: str = "auto"
    # per-chip HBM budget (MB) for the cached split under
    # device_cache='auto'; above it the job falls back to host staging
    device_cache_mb: int = 512
    # net-new fault tolerance (the merge guard itself is always on —
    # parallel/kavg.py drops non-finite workers from every merge):
    # quarantine_after = N > 0 masks a worker out for the REST OF THE
    # EPOCH once the guard drops it N consecutive rounds (host-side mask
    # edit between dispatches, no retrace); 0 disables. Enabling it (or
    # abort_after) costs a tiny per-round [W] readback, so both default
    # off to preserve the fully-async dispatch pipeline.
    quarantine_after: int = 0
    # abort_after = N > 0 fails the job with a diagnostic when EVERY
    # contributing worker is non-finite for N consecutive rounds —
    # instead of silently "training" on frozen weights; 0 disables
    abort_after: int = 0
    # net-new: deterministic fault-injection plan (kubeml_tpu/faults.py)
    # — a JSON spec of events at named (epoch, round, worker)
    # coordinates: NaN bursts, worker dropouts, a process crash,
    # checkpoint corruption, artificial slow rounds. Empty = no faults.
    fault_plan: str = ""
    # net-new elastic degraded mode (round-granular resume): N > 0
    # checkpoints every N sync rounds WITH a train_state cursor (epoch,
    # round, guard masks, partial accumulators) so a crash/preemption
    # restart resumes at the failed round instead of the epoch start.
    # kavg only (it re-derives optimizer state each round, so the
    # weights + cursor fully determine the resumed trajectory); forces
    # rounds_per_dispatch=1. 0 disables (epoch-granular checkpoints).
    checkpoint_every_rounds: int = 0
    # net-new elastic degraded mode (mid-epoch work reassignment): when
    # the non-finite guard quarantines a worker mid-epoch, re-deal its
    # undispatched sample indices to the surviving workers as extra
    # makeup rounds at the end of the epoch, so every index still trains
    # exactly once per epoch. Requires quarantine_after > 0; counts land
    # in History.reassigned_batches and kubeml_job_reassigned_batches.
    reassign_on_quarantine: bool = False
    # net-new training-health telemetry: compute per-worker grad-norm /
    # update-ratio / loss-spread stat lanes inside the jitted round
    # programs (parallel/kavg.py, parallel/syncdp.py). The lanes are
    # pure extra outputs accumulated lazily on device — weights are
    # bit-identical with the flag on or off and no mid-epoch host syncs
    # are added — so they default ON; turn off to shave the (small)
    # extra FLOPs and HBM of the stat outputs.
    train_stats: bool = True
    # net-new sync-round comm levers (parallel/merge.py; docs/
    # performance.md "Merge overlap & compression"):
    # merge_dtype = '' keeps full-f32 merge payloads; 'bf16' halves the
    # cross-slice wire bytes by casting the payload (NO error feedback —
    # each round independently rounds to bf16). Kavg engine only.
    merge_dtype: str = ""
    # merge_compress = 'none' | 'bf16' | 'int8': error-feedback
    # compressed merge payloads — the per-lane quantization error is
    # carried as a persistent residual and added back into the next
    # round's payload, so the quantization bias cancels over rounds.
    # int8 adds a shared per-bucket scale (4 B/bucket). Mutually
    # exclusive with merge_dtype. Residuals are zeroed for lanes the
    # non-finite guard drops, so quarantine semantics survive.
    merge_compress: str = "none"
    # merge_bucket_mb > 0 splits the merge into consecutive-leaf buckets
    # of at most this many MB (f32 accounting) and issues each bucket's
    # collective independently, so early buckets overlap the rest of the
    # round's compute; 0 keeps the monolithic per-leaf merge. Bucketing
    # is bit-identical to the monolithic merge (tests/test_merge.py).
    merge_bucket_mb: float = 0.0
    # net-new continual-training plane: continual=True makes the job
    # sliding-window — `epochs` becomes a per-pass cap and the job loops
    # passes forever (until stopped/preempted), re-polling the dataset
    # registry for new generations between passes. window_generations
    # caps how many newest generations the pass trains over (0 = all
    # retained). publish_every_rounds > 0 publishes a stamped checkpoint
    # every N sync rounds so the serving plane can hot-swap mid-stream
    # (kavg only, forces rounds_per_dispatch=1 like
    # checkpoint_every_rounds).
    continual: bool = False
    window_generations: int = 0
    publish_every_rounds: int = 0

    def to_dict(self) -> dict:
        return {
            "default_parallelism": self.default_parallelism,
            "static_parallelism": self.static_parallelism,
            "validate_every": self.validate_every,
            "K": self.k,
            "goal_accuracy": self.goal_accuracy,
            "checkpoint_every": self.checkpoint_every,
            "engine": self.engine,
            "shuffle": self.shuffle,
            "n_model": self.n_model,
            "n_seq": self.n_seq,
            "n_expert": self.n_expert,
            "n_stage": self.n_stage,
            "pp_microbatches": self.pp_microbatches,
            "fsdp": self.fsdp,
            "rounds_per_dispatch": self.rounds_per_dispatch,
            "seq_impl": self.seq_impl,
            "tp_impl": self.tp_impl,
            "max_parallelism": self.max_parallelism,
            "max_restarts": self.max_restarts,
            "device_cache": self.device_cache,
            "device_cache_mb": self.device_cache_mb,
            "quarantine_after": self.quarantine_after,
            "abort_after": self.abort_after,
            "fault_plan": self.fault_plan,
            "checkpoint_every_rounds": self.checkpoint_every_rounds,
            "reassign_on_quarantine": self.reassign_on_quarantine,
            "train_stats": self.train_stats,
            "merge_dtype": self.merge_dtype,
            "merge_compress": self.merge_compress,
            "merge_bucket_mb": self.merge_bucket_mb,
            "continual": self.continual,
            "window_generations": self.window_generations,
            "publish_every_rounds": self.publish_every_rounds,
        }

    @classmethod
    def from_dict(cls, d: dict) -> "TrainOptions":
        return cls(
            default_parallelism=d.get("default_parallelism", 5),
            static_parallelism=d.get("static_parallelism", False),
            validate_every=d.get("validate_every", 1),
            k=d.get("K", d.get("k", 1)),
            goal_accuracy=d.get("goal_accuracy", 100.0),
            checkpoint_every=d.get("checkpoint_every", 0),
            engine=d.get("engine", "kavg"),
            shuffle=d.get("shuffle", False),
            n_model=int(d.get("n_model", 1)),
            n_seq=int(d.get("n_seq", 1)),
            n_expert=int(d.get("n_expert", 1)),
            n_stage=int(d.get("n_stage", 1)),
            pp_microbatches=int(d.get("pp_microbatches", 0)),
            fsdp=bool(d.get("fsdp", False)),
            rounds_per_dispatch=int(d.get("rounds_per_dispatch", 1)),
            seq_impl=d.get("seq_impl", "ring"),
            tp_impl=d.get("tp_impl", "gspmd"),
            max_parallelism=int(d.get("max_parallelism", 0)),
            max_restarts=int(d.get("max_restarts", 1)),
            device_cache=d.get("device_cache", "auto"),
            device_cache_mb=int(d.get("device_cache_mb", 512)),
            quarantine_after=int(d.get("quarantine_after", 0)),
            abort_after=int(d.get("abort_after", 0)),
            fault_plan=d.get("fault_plan", ""),
            checkpoint_every_rounds=int(d.get("checkpoint_every_rounds", 0)),
            reassign_on_quarantine=bool(d.get("reassign_on_quarantine",
                                              False)),
            train_stats=bool(d.get("train_stats", True)),
            merge_dtype=d.get("merge_dtype", ""),
            merge_compress=d.get("merge_compress", "none"),
            merge_bucket_mb=float(d.get("merge_bucket_mb", 0.0)),
            continual=bool(d.get("continual", False)),
            window_generations=int(d.get("window_generations", 0)),
            publish_every_rounds=int(d.get("publish_every_rounds", 0)),
        )


@dataclass
class TrainRequest:
    """A train submission (ml/pkg/api/types.go:9-22)."""

    model_type: str        # registered function/model name
    batch_size: int
    epochs: int
    dataset: str
    lr: float
    function_name: str = ""
    options: TrainOptions = field(default_factory=TrainOptions)
    # warm-start from another job's checkpoint (net-new: the reference
    # deletes weights at job end and has no resume path, SURVEY.md §5)
    resume_from: str = ""
    # cluster-allocator admission (control/cluster.py; defaults keep old
    # clients/manifests parsing): higher priority places first and may
    # preempt strictly-lower-priority work; the tenant keys quota and
    # weighted-fair-share accounting
    priority: int = 0
    tenant: str = ""

    def to_dict(self) -> dict:
        return {
            "model_type": self.model_type,
            "batch_size": self.batch_size,
            "epochs": self.epochs,
            "dataset": self.dataset,
            "lr": self.lr,
            "function_name": self.function_name or self.model_type,
            "options": self.options.to_dict(),
            "resume_from": self.resume_from,
            "priority": self.priority,
            "tenant": self.tenant,
        }

    @classmethod
    def from_dict(cls, d: dict) -> "TrainRequest":
        return cls(
            model_type=d.get("model_type", d.get("function_name", "")),
            batch_size=int(d["batch_size"]),
            epochs=int(d["epochs"]),
            dataset=d["dataset"],
            lr=float(d["lr"]),
            function_name=d.get("function_name", ""),
            options=TrainOptions.from_dict(d.get("options", {})),
            resume_from=d.get("resume_from", ""),
            priority=int(d.get("priority", 0)),
            tenant=d.get("tenant", ""),
        )


@dataclass
class TrainTask:
    """A scheduled job (ml/pkg/api/types.go:44-58)."""

    job_id: str
    parameters: TrainRequest
    parallelism: int = 0
    elapsed_time_s: float = -1.0   # last epoch duration fed back to the policy
    state: str = "queued"          # queued | starting | running | finished | failed | stopped
    # client-minted trace id; rides the task across the scheduler queue
    # (thread-locals don't survive the hop) into the PS and from there
    # to the standalone job process, so spans from every process in the
    # chain correlate (utils/trace.py)
    trace_id: str = ""
    # degraded-mode visibility (stamped by the PS on /tasks listings so
    # `kubeml task list` shows them without scraping /metrics): watchdog
    # restarts consumed and graceful preemption handoffs survived
    restarts: int = 0
    preemptions: int = 0
    # cluster-allocator admission keys, copied off the request at
    # enqueue so the scheduler/PS wire carries them without reparsing
    # parameters (control/cluster.py; defaults keep old payloads valid)
    priority: int = 0
    tenant: str = ""
    # fencing epoch of the lane grant this task runs under
    # (control/cluster.py). Stamped by the scheduler at dispatch and
    # echoed back on every /job re-parallelize ask; a recovered
    # allocator rejects stale epochs with 409 so a pre-crash worker can
    # never double-book lanes. 0 = unfenced (legacy / non-cluster mode)
    grant_epoch: int = 0

    def to_dict(self) -> dict:
        return {
            "job_id": self.job_id,
            "parameters": self.parameters.to_dict(),
            "parallelism": self.parallelism,
            "elapsed_time_s": self.elapsed_time_s,
            "state": self.state,
            "trace_id": self.trace_id,
            "restarts": self.restarts,
            "preemptions": self.preemptions,
            "priority": self.priority,
            "tenant": self.tenant,
            "grant_epoch": self.grant_epoch,
        }

    @classmethod
    def from_dict(cls, d: dict) -> "TrainTask":
        return cls(
            job_id=d["job_id"],
            parameters=TrainRequest.from_dict(d["parameters"]),
            parallelism=d.get("parallelism", 0),
            elapsed_time_s=d.get("elapsed_time_s", -1.0),
            state=d.get("state", "queued"),
            trace_id=d.get("trace_id", ""),
            restarts=int(d.get("restarts", 0)),
            preemptions=int(d.get("preemptions", 0)),
            priority=int(d.get("priority", 0)),
            tenant=d.get("tenant", ""),
            grant_epoch=int(d.get("grant_epoch", 0)),
        )


@dataclass
class JobHistory:
    """Per-epoch metric arrays (ml/pkg/api/types.go:75-81)."""

    validation_loss: List[float] = field(default_factory=list)
    accuracy: List[float] = field(default_factory=list)
    train_loss: List[float] = field(default_factory=list)
    parallelism: List[int] = field(default_factory=list)
    epoch_duration: List[float] = field(default_factory=list)
    # net-new fault-tolerance observability (defaults keep old manifests
    # and histories loadable): per-epoch worker-round drops by the
    # non-finite merge guard (kavg; sync-DP counts skipped steps) and
    # workers under quarantine at epoch end
    dropped_workers: List[float] = field(default_factory=list)
    quarantined_workers: List[int] = field(default_factory=list)
    # net-new elastic degraded mode: per-epoch minibatch steps re-dealt
    # from quarantined workers to survivors (makeup rounds)
    reassigned_batches: List[int] = field(default_factory=list)
    # net-new training-health telemetry (on-device stat lanes,
    # parallel/kavg.py): per-epoch [min, mean, max] across workers of
    # the RMS global grad norm and of the update/param norm ratio, plus
    # the mean cross-worker loss spread. Empty when train_stats was off.
    grad_norm_summary: List[List[float]] = field(default_factory=list)
    update_ratio_summary: List[List[float]] = field(default_factory=list)
    loss_spread: List[float] = field(default_factory=list)
    # checkpoint-based watchdog restarts consumed by the job (stamped by
    # the PS at finish — control/ps.py)
    restarts: int = 0
    # SIGTERM/preempt-fault graceful handoffs survived (restart from a
    # round-granular checkpoint; does not consume the restart budget)
    preemptions: int = 0

    def to_dict(self) -> dict:
        return _asdict(self)

    @classmethod
    def from_dict(cls, d: dict) -> "JobHistory":
        return cls(
            validation_loss=list(d.get("validation_loss", [])),
            accuracy=list(d.get("accuracy", [])),
            train_loss=list(d.get("train_loss", [])),
            parallelism=list(d.get("parallelism", [])),
            epoch_duration=list(d.get("epoch_duration", [])),
            dropped_workers=list(d.get("dropped_workers", [])),
            quarantined_workers=list(d.get("quarantined_workers", [])),
            reassigned_batches=list(d.get("reassigned_batches", [])),
            grad_norm_summary=[list(x) for x in
                               d.get("grad_norm_summary", [])],
            update_ratio_summary=[list(x) for x in
                                  d.get("update_ratio_summary", [])],
            loss_spread=list(d.get("loss_spread", [])),
            restarts=int(d.get("restarts", 0)),
            preemptions=int(d.get("preemptions", 0)),
        )


@dataclass
class History:
    """A persisted training history record (ml/pkg/api/types.go:84-100)."""

    id: str
    task: TrainRequest
    data: JobHistory

    def to_dict(self) -> dict:
        return {"_id": self.id, "task": self.task.to_dict(), "data": self.data.to_dict()}

    @classmethod
    def from_dict(cls, d: dict) -> "History":
        return cls(
            id=d.get("_id", d.get("id", "")),
            task=TrainRequest.from_dict(d["task"]),
            data=JobHistory.from_dict(d["data"]),
        )


@dataclass
class MetricUpdate:
    """A per-epoch metric push from a job to the PS (ml/pkg/api/types.go:103-112)."""

    job_id: str
    validation_loss: float
    accuracy: float
    train_loss: float
    parallelism: int
    epoch_duration: float
    # fault-tolerance counters for the epoch (optional on the wire so
    # updates from older jobs still parse)
    dropped_workers: float = 0.0
    quarantined_workers: int = 0
    # minibatch steps re-dealt from quarantined workers this epoch
    # (elastic degraded mode; optional on the wire)
    reassigned_batches: int = 0
    # async checkpoint saves coalesced because the writer fell behind
    # (cumulative over the job's life; optional on the wire)
    checkpoint_drops: int = 0
    # per-phase span durations for the epoch (tracer name -> seconds per
    # round), feeding the PS latency histograms; optional on the wire
    phase_times: Dict[str, List[float]] = field(default_factory=dict)
    # training-health stat lanes for the epoch (optional on the wire —
    # empty when the job ran with train_stats off): per-worker RMS
    # global grad norm, update/param norm ratio, and mean per-step loss,
    # plus the mean cross-worker loss spread (on-device population std
    # of the merged workers' per-round mean losses)
    grad_norms: List[float] = field(default_factory=list)
    update_ratios: List[float] = field(default_factory=list)
    worker_losses: List[float] = field(default_factory=list)
    loss_spread: float = 0.0
    # runtime introspection (metrics/runtime.py; cumulative over the
    # job's life): engine-program jit compiles and the device-memory
    # watermark at epoch end
    jit_compiles: int = 0
    hbm_peak_bytes: int = 0
    hbm_in_use_bytes: int = 0
    # tracer events dropped at the ring cap so far (utils/trace.py)
    trace_events_dropped: int = 0
    # continual-plane freshness (optional on the wire; only continual
    # jobs publish them): the dataset generation this pass trained over,
    # and how many generations the registry is ahead of it
    dataset_generation: int = 0
    data_lag_generations: int = -1
    # analytic cost ledger snapshot (metrics/ledger.py; optional on the
    # wire): one flat dict per program (per-dispatch record fields +
    # attributed totals), cumulative over the job's life — the PS
    # stores the latest and delta-advances the kubeml_cost_* counters
    cost_programs: Dict[str, dict] = field(default_factory=dict)

    def to_dict(self) -> dict:
        return _asdict(self)

    @classmethod
    def from_dict(cls, d: dict) -> "MetricUpdate":
        return cls(**{k: d[k] for k in
                      ("job_id", "validation_loss", "accuracy", "train_loss",
                       "parallelism", "epoch_duration")},
                   dropped_workers=float(d.get("dropped_workers", 0.0)),
                   quarantined_workers=int(d.get("quarantined_workers", 0)),
                   reassigned_batches=int(d.get("reassigned_batches", 0)),
                   checkpoint_drops=int(d.get("checkpoint_drops", 0)),
                   phase_times={str(k): [float(x) for x in v]
                                for k, v in (d.get("phase_times")
                                             or {}).items()},
                   grad_norms=[float(x) for x in d.get("grad_norms", [])],
                   update_ratios=[float(x) for x in
                                  d.get("update_ratios", [])],
                   worker_losses=[float(x) for x in
                                  d.get("worker_losses", [])],
                   loss_spread=float(d.get("loss_spread", 0.0)),
                   jit_compiles=int(d.get("jit_compiles", 0)),
                   hbm_peak_bytes=int(d.get("hbm_peak_bytes", 0)),
                   hbm_in_use_bytes=int(d.get("hbm_in_use_bytes", 0)),
                   trace_events_dropped=int(d.get("trace_events_dropped",
                                                  0)),
                   dataset_generation=int(d.get("dataset_generation", 0)),
                   data_lag_generations=int(d.get("data_lag_generations",
                                                  -1)),
                   cost_programs=dict(d.get("cost_programs") or {}))


@dataclass
class InferRequest:
    """Inference request (ml/pkg/api/types.go:37-41)."""

    model_id: str          # jobId of the trained model
    data: Any = None       # opaque JSON payload handed to the user's infer()

    def to_dict(self) -> dict:
        return {"model_id": self.model_id, "data": self.data}

    @classmethod
    def from_dict(cls, d: dict) -> "InferRequest":
        return cls(model_id=d["model_id"], data=d.get("data"))


@dataclass
class DatasetSummary:
    """Dataset listing entry (ml/pkg/api/types.go:66-72)."""

    name: str
    train_set_size: int
    test_set_size: int

    def to_dict(self) -> dict:
        return _asdict(self)

    @classmethod
    def from_dict(cls, d: dict) -> "DatasetSummary":
        return cls(name=d["name"],
                   train_set_size=d.get("train_set_size", 0),
                   test_set_size=d.get("test_set_size", 0))


def dumps(obj) -> str:
    """Serialize any wire type (or list of them) to JSON."""
    if isinstance(obj, list):
        return json.dumps([o.to_dict() if hasattr(o, "to_dict") else o for o in obj])
    return json.dumps(obj.to_dict() if hasattr(obj, "to_dict") else obj)
