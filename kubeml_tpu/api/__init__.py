from kubeml_tpu.api import const, errors, types

__all__ = ["const", "errors", "types"]
