"""Shared JSON error envelope + exception hierarchy.

Parity: the reference shares a `{code, error}` JSON envelope between its Go
components (ml/pkg/error/error.go:13-87) and Python functions
(python/kubeml/kubeml/exceptions.py:1-48). We keep the same wire shape and
exception names so user code and clients translate directly.
"""

from __future__ import annotations

import json


class KubeMLException(Exception):
    """Base exception carrying an HTTP-style status code.

    Mirrors python/kubeml/kubeml/exceptions.py:5-17.
    """

    def __init__(self, message: str, status_code: int = 500):
        super().__init__(message)
        self.message = message
        self.status_code = status_code

    def to_dict(self) -> dict:
        return {"code": self.status_code, "error": self.message}

    def to_json(self) -> str:
        return json.dumps(self.to_dict())


class MergeError(KubeMLException):
    def __init__(self, message: str = "Error merging model"):
        super().__init__(message, 500)


class DataError(KubeMLException):
    def __init__(self, message: str = "Error loading data"):
        super().__init__(message, 500)


class InvalidFormatError(KubeMLException):
    def __init__(self, message: str = "Invalid request format"):
        super().__init__(message, 400)


class StorageError(KubeMLException):
    def __init__(self, message: str = "Error accessing storage"):
        super().__init__(message, 500)


class DatasetNotFoundError(KubeMLException):
    def __init__(self, name: str = ""):
        super().__init__(f"Dataset not found{': ' + name if name else ''}", 404)


class InvalidArgsError(KubeMLException):
    def __init__(self, message: str = "Invalid arguments"):
        super().__init__(message, 400)


class JobNotFoundError(KubeMLException):
    def __init__(self, job_id: str = ""):
        super().__init__(f"Job not found{': ' + job_id if job_id else ''}", 404)


class FunctionNotFoundError(KubeMLException):
    def __init__(self, name: str = ""):
        super().__init__(f"Function not found{': ' + name if name else ''}", 404)


class StaleGrantError(KubeMLException):
    """409: the caller presented a lane grant whose fencing epoch
    predates the current allocator incarnation — a pre-crash worker
    that outlived the control plane that granted it. The recovered
    allocator may have given those lanes away; honoring the stale grant
    would double-book them (split-brain). The worker must requeue."""

    def __init__(self, job_id: str = "", presented: int = 0,
                 current: int = 0):
        super().__init__(
            f"stale grant for job {job_id}: fencing epoch {presented} "
            f"predates current epoch {current}", 409)
        self.job_id = job_id
        self.presented = presented
        self.current = current


class JobPreemptedError(KubeMLException):
    """Control-flow signal: the job drained and checkpointed mid-epoch in
    response to a preemption notice (SIGTERM or a `preempt` fault event)
    and expects the PS to reschedule it from the round-granular
    checkpoint. Not a failure — train() re-raises it without reporting
    on_finish so the PS job record stays alive for the watchdog."""

    def __init__(self, job_id: str = "", epoch: int = 0, round_: int = 0):
        super().__init__(
            f"job {job_id} preempted at epoch {epoch} round {round_}", 503)
        self.job_id = job_id
        self.epoch = epoch
        self.round = round_


def check_error(status_code: int, body: bytes) -> None:
    """Raise a KubeMLException from an error-envelope HTTP response.

    Parity with CheckFunctionError (ml/pkg/error/error.go:36-59): parse the
    `{code, error}` envelope if present, otherwise synthesize from status.
    """
    if status_code < 400:
        return
    try:
        payload = json.loads(body.decode("utf-8"))
        raise KubeMLException(payload.get("error", "unknown error"),
                              payload.get("code", status_code))
    except (ValueError, AttributeError, UnicodeDecodeError):
        raise KubeMLException(body.decode("utf-8", "replace") or "unknown error",
                              status_code) from None
