"""Expert parallelism — GShard-style mixture-of-experts over the mesh
`expert` axis.

Net-new relative to the reference (SURVEY.md §2a: "Absent: ... expert
parallelism"). TPU-first design: routing is expressed as dense one-hot
einsum dispatch/combine (the GShard/Mesh-TensorFlow formulation) rather
than gather/scatter — static shapes, MXU-friendly, and the expert-major
intermediates are annotated with `with_sharding_constraint` over the
`expert` axis so XLA's SPMD partitioner inserts the all-to-alls on ICI.
No manual collective code is needed; the same program runs on one chip
(expert axis size 1) or a full slice.

Capacity semantics: each expert processes at most C = ceil(T/E *
capacity_factor) tokens per call; overflow tokens are dropped from that
expert (their combine weight is zero, so they pass through the residual
path in `MoEBlock`-style use). Auxiliary load-balancing loss follows
Shazeer et al.: E * sum_e(fraction_routed_e * mean_prob_e).
"""

from __future__ import annotations

import math
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from kubeml_tpu.parallel.mesh import EXPERT_AXIS

PyTree = Any


def init_moe_params(rng: jax.Array, d_model: int, d_ff: int,
                    n_experts: int) -> Dict[str, jax.Array]:
    """Router + stacked expert-FFN parameters.

    Leaves carry the expert dim leading so `EP_RULES`-style placement (or
    the constraints inside `moe_apply`) shard them over the expert axis.
    """
    kr, ki, ko = jax.random.split(rng, 3)
    scale_in = 1.0 / jnp.sqrt(d_model)
    scale_out = 1.0 / jnp.sqrt(d_ff)
    return {
        "router": jax.random.normal(kr, (d_model, n_experts)) * scale_in,
        "wi": jax.random.normal(ki, (n_experts, d_model, d_ff)) * scale_in,
        "bi": jnp.zeros((n_experts, d_ff)),
        "wo": jax.random.normal(ko, (n_experts, d_ff, d_model)) * scale_out,
        "bo": jnp.zeros((n_experts, d_model)),
    }


def make_dispatch(logits: jax.Array, capacity: int, k: int = 2,
                  token_mask: Optional[jax.Array] = None
                  ) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Top-k routing with per-expert capacity.

    logits: [T, E]. Returns (dispatch [T, E, C] 0/1, combine [T, E, C]
    float, aux_loss scalar). A token contributes to at most k experts;
    within an expert, slots fill in token order (GShard's cumsum position
    assignment) and overflow is dropped.

    token_mask [T] (1 = real): masked tokens are excluded from routing
    entirely — they claim no capacity slots (so padding can never
    displace real tokens from an expert) and do not enter the
    load-balance statistics.
    """
    t, e = logits.shape
    k = min(k, e)
    probs = jax.nn.softmax(logits, axis=-1)
    valid = (jnp.ones((t,), logits.dtype) if token_mask is None
             else token_mask.astype(logits.dtype))

    dispatch = jnp.zeros((t, e, capacity), logits.dtype)
    combine = jnp.zeros((t, e, capacity), logits.dtype)
    masked = probs * valid[:, None]
    # Slot tokens expert-by-expert for each of the k choices. Loop bound k
    # is a static Python int — unrolled at trace time, XLA-friendly.
    fill = jnp.zeros((e,), jnp.int32)  # slots already used per expert
    for _ in range(k):
        choice = jnp.argmax(masked, axis=-1)                      # [T]
        onehot = jax.nn.one_hot(choice, e, dtype=logits.dtype) \
            * valid[:, None]                                      # [T, E]
        pos = (jnp.cumsum(onehot, axis=0) - 1) * onehot           # [T, E]
        pos = pos + fill[None, :] * onehot
        keep = onehot * (pos < capacity)
        slot = jax.nn.one_hot(pos.astype(jnp.int32), capacity,
                              dtype=logits.dtype)                 # [T, E, C]
        d = keep[..., None] * slot
        dispatch = dispatch + d
        combine = combine + d * probs[..., None]
        fill = fill + keep.sum(axis=0).astype(jnp.int32)
        masked = masked * (1.0 - onehot)  # next choice excludes this expert

    # Load-balance auxiliary loss over the FIRST choice distribution,
    # statistics taken over REAL tokens only.
    n_valid = jnp.maximum(valid.sum(), 1.0)
    first = jax.nn.one_hot(jnp.argmax(probs, axis=-1), e,
                           dtype=logits.dtype) * valid[:, None]
    aux = e * jnp.sum((first.sum(axis=0) / n_valid)
                      * ((probs * valid[:, None]).sum(axis=0) / n_valid))
    return dispatch, combine, aux


def route_tokens(router: jax.Array, x: jax.Array, *, k: int = 2,
                 capacity_factor: float = 1.25,
                 token_mask: Optional[jax.Array] = None
                 ) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """THE routing preamble — f32 router matmul, capacity formula, and
    GShard dispatch — shared by `moe_apply` (GSPMD path) and the manual
    expert path (models/gpt.py MoEFFN with ep_axis), so the two
    execution strategies can never drift in routing semantics.

    x: [T, d_model]; router: [d_model, E]. Returns (dispatch [T, E, C],
    combine [T, E, C], aux_loss)."""
    t = x.shape[0]
    e = router.shape[1]
    capacity = max(1, math.ceil((t / e) * capacity_factor))
    logits = x.astype(jnp.float32) @ router.astype(jnp.float32)
    return make_dispatch(logits, capacity, k, token_mask=token_mask)


def moe_apply(params: Dict[str, jax.Array], x: jax.Array,
              mesh: Optional[Mesh] = None, *, k: int = 2,
              capacity_factor: float = 1.25,
              token_mask: Optional[jax.Array] = None
              ) -> Tuple[jax.Array, jax.Array]:
    """Apply the expert layer to tokens x [T, d_model].

    Returns (y [T, d_model], aux_loss). With a mesh, expert-major
    intermediates are constrained to the `expert` axis so the SPMD
    partitioner materializes dispatch/return as all-to-alls.

    Routing/softmax/aux statistics run in f32; the expert matmuls (the
    dominant FLOPs) run in x.dtype — bf16 activations keep the MXU on
    its fast path, with biases/params cast to match. token_mask [T]
    excludes padding from routing and capacity (see make_dispatch).
    """
    def on_expert_axis(arr):
        if mesh is None or mesh.shape[EXPERT_AXIS] == 1:
            return arr
        return jax.lax.with_sharding_constraint(
            arr, NamedSharding(mesh, P(EXPERT_AXIS)))

    dispatch, combine, aux = route_tokens(
        params["router"], x, k=k, capacity_factor=capacity_factor,
        token_mask=token_mask)

    cdt = x.dtype
    expert_in = on_expert_axis(
        jnp.einsum("tec,td->ecd", dispatch.astype(cdt), x))
    h = jax.nn.gelu(
        jnp.einsum("ecd,edf->ecf", expert_in, params["wi"].astype(cdt))
        + params["bi"].astype(cdt)[:, None, :])
    # Empty slots get the bias too, but combine is zero there — harmless.
    out = on_expert_axis(
        jnp.einsum("ecf,efd->ecd", h, params["wo"].astype(cdt))
        + params["bo"].astype(cdt)[:, None, :])
    y = jnp.einsum("tec,ecd->td", combine.astype(cdt), out)
    return y, aux


# Placement rules for `tp.shard_variables`-style use: expert-stacked
# leaves shard their leading dim over the expert axis.
EP_RULES = [
    (r".*/(wi|wo|bi|bo)$", P(EXPERT_AXIS)),
]
