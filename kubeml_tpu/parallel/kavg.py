"""K-step local SGD with masked weight averaging — the core sync engine.

This is the TPU-native re-design of the reference's entire data plane: the N
Fission function replicas, the RedisAI weight blackboard, and the Go merge
barrier (ml/pkg/train/job.go:368-451 + ml/pkg/model/parallelSGD.go:26-54)
collapse into ONE jit-compiled "sync round":

    round(variables, batches) =
        for each data-parallel lane (shard_map over the mesh `data` axis):
            start from the shared (averaged) variables,
            run K masked local optimizer steps (lax.scan),
        then average the resulting *weights* (not gradients) with a masked
        lax.psum, dividing by the number of contributing workers.

Semantics preserved exactly from the reference:
  - weights are averaged, not gradients (ml/pkg/model/model.go:286-296 sums
    weights; job.go:398 divides by reporter count);
  - optimizer state is re-initialized at every sync round
    (python/kubeml/kubeml/network.py:208-217 `_reset_optimizer_state`);
  - the average is taken over the workers that actually contributed
    ("merge with whoever reported", straggler/failure tolerance of
    ml/pkg/train/util.go:144-166) — here a 0/1 worker mask, ANDed
    on-device with a per-worker all-leaves-finite flag: a worker whose
    K local steps produced NaN/Inf weights or loss is dropped from the
    merge exactly as if its mask bit had been 0 (the numerical analogue
    of the survivor-merge, per-worker skip-step a la mixed-precision
    training), and the drop is reported via RoundStats.dropped_device;
  - integer leaves (e.g. a BatchNorm step counter) are averaged in float
    and truncated back, matching ParallelSGD.Average's int64 handling
    (ml/pkg/model/parallelSGD.go:40-52);
  - ragged shards (short final chunks, partial batches) contribute only
    their real samples, via step and sample masks.

Virtual workers: logical parallelism N may exceed the mesh's data-axis size
D. Workers are laid out [W] with W = ceil(N/D)*D; each lane processes W/D
virtual workers sequentially, all starting from the same round params (this
is exact: in the reference, every function's chunk starts from the same
averaged model). N < W is expressed through the worker mask.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable, Dict, Optional, Tuple

import jax
from kubeml_tpu import compat
import jax.numpy as jnp
import numpy as np
import optax
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from kubeml_tpu.metrics.ledger import CostLedger
from kubeml_tpu.parallel import merge as merge_lib
from kubeml_tpu.parallel.mesh import DATA_AXIS, SEQ_AXIS

PyTree = Any

# loss_fn(variables, batch, rng, train=True)
#   -> (per_example_loss [B], new_model_state)
LossFn = Callable[[PyTree, PyTree, jax.Array], Tuple[jax.Array, PyTree]]
# metrics_fn(variables, batch) -> {name: per_example_values [B]}
MetricsFn = Callable[[PyTree, PyTree], Dict[str, jax.Array]]
# tx_factory(lr, epoch) -> optax.GradientTransformation (lr/epoch may be traced)
TxFactory = Callable[[jax.Array, jax.Array], optax.GradientTransformation]


class RoundStats:
    """Host-side view of one sync round's outcome.

    `loss_sum` and `dropped` materialize LAZILY: reading either blocks on
    the round and costs a device->host readback (tens of ms on tunneled
    backends), so dispatch loops should accumulate `loss_sum_device` /
    `dropped_device` on device and read back once per epoch; a loop that
    only wants an opportunistic progress number must use the
    non-blocking `peek()` instead. `step_count` and `sample_count` are
    host-derived from the masks (free). `contributors` counts the
    workers that actually MERGED: the host mask sum minus the on-device
    non-finite drops, so reading it also synchronizes whenever a
    `dropped_device` is attached.

    When the engine runs with `collect_stats=True`, `stat_device` holds
    the [W, 3] (or [R, W, 3]) per-worker health-stat accumulators —
    columns are the step-masked sums of squared global grad norm,
    squared update norm, and squared param norm — and `spread_device`
    the per-round cross-worker loss-spread scalar. Both follow the same
    lazy discipline as `loss_sum_device`.
    """

    def __init__(self, loss_sum_device: jax.Array, step_count: np.ndarray,
                 sample_count: np.ndarray, contributors: float,
                 compiled: bool = False,
                 dropped_device: Optional[jax.Array] = None,
                 stat_device: Optional[jax.Array] = None,
                 spread_device: Optional[jax.Array] = None):
        self.loss_sum_device = loss_sum_device    # [W] device array
        self.step_count = step_count              # [W] real local steps
        self.sample_count = sample_count          # [W] real samples
        self.planned_contributors = contributors  # host mask sum
        # [W] (or [R, W]) device array of 0/1 flags: 1 = the worker was
        # masked in but produced a non-finite update and was dropped from
        # the merge by the on-device guard
        self.dropped_device = dropped_device
        # True when this dispatch built (traced + XLA-compiled) a new
        # round program — the job subtracts such rounds from the epoch
        # duration it reports to the throughput policy, so compile time
        # is never read as throughput signal (policy.go:50-94 assumes
        # epoch time ~= steady state; on TPU only non-compile rounds are)
        self.compiled = compiled
        # on-device health-stat lanes (engine collect_stats=True only)
        self.stat_device = stat_device
        self.spread_device = spread_device
        self._loss_sum: Optional[np.ndarray] = None
        self._dropped: Optional[np.ndarray] = None

    def peek(self) -> Optional[np.ndarray]:
        """Non-blocking view of the [W] loss sums: the array if the
        round has already drained on device, else None.

        WARNING: the `loss_sum`/`dropped`/`contributors` properties
        SYNCHRONIZE — reading any of them mid-dispatch blocks the host
        on the in-flight round and serializes the dispatch pipeline.
        Anything that wants a merely opportunistic number (heartbeats,
        a live `kubeml top` sampler) must go through peek(); the
        dispatch loop in train/job.py accumulates `loss_sum_device` and
        reads back once per epoch for exactly this reason."""
        if self._loss_sum is not None:
            return self._loss_sum
        ready = getattr(self.loss_sum_device, "is_ready", None)
        if callable(ready) and not ready():
            return None
        self._loss_sum = np.asarray(self.loss_sum_device)
        return self._loss_sum

    @property
    def loss_sum(self) -> np.ndarray:
        """[W] masked sum of per-step mean losses (synchronizing)."""
        if self._loss_sum is None:
            self._loss_sum = np.asarray(self.loss_sum_device)
        return self._loss_sum

    @property
    def dropped(self) -> np.ndarray:
        """[W] (or [R, W]) non-finite drop flags (synchronizing)."""
        if self._dropped is None:
            if self.dropped_device is None:
                self._dropped = np.zeros_like(
                    np.asarray(self.step_count, dtype=np.float32))
            else:
                self._dropped = np.asarray(self.dropped_device)
        return self._dropped

    @property
    def contributors(self) -> float:
        """Workers merged = planned (mask sum) - non-finite drops."""
        if self.dropped_device is None:
            return self.planned_contributors
        return float(self.planned_contributors - self.dropped.sum())

    def __repr__(self):
        return (f"RoundStats(steps={self.step_count.sum():.0f}, "
                f"samples={self.sample_count.sum():.0f}, "
                f"contributors={self.contributors:.0f})")


def seq_batch_spec(key: str, seq_dims: Optional[Dict[str, int]]) -> P:
    """THE PartitionSpec for a [W, S, B, ...] round-batch leaf: sharded
    over `data` on dim 0, and — for sequence-carrying keys — over `seq`
    on per-example dim d (full dim 3+d). One definition shared by the
    engine's shard_map in_specs and the job's staging shardings, so
    staged batches can never silently reshard on round entry."""
    if seq_dims and key in seq_dims:
        return P(DATA_AXIS, *([None] * (2 + seq_dims[key])), SEQ_AXIS)
    return P(DATA_AXIS)


def _select_tree(mask: jax.Array, new: PyTree, old: PyTree) -> PyTree:
    """Elementwise tree select: mask==1 -> new, else old (masked step)."""
    return jax.tree_util.tree_map(
        lambda n, o: jnp.where(mask.astype(jnp.bool_), n, o), new, old)


def tree_all_finite(tree: PyTree) -> jax.Array:
    """Scalar bool: every floating leaf of `tree` is finite.

    Integer leaves (e.g. BatchNorm step counters) cannot go non-finite
    and are skipped. Shared by the kavg merge guard and the sync-DP
    skip-step so "worker went non-finite" means the same thing in both
    engines."""
    ok = jnp.bool_(True)
    for leaf in jax.tree_util.tree_leaves(tree):
        if jnp.issubdtype(jnp.asarray(leaf).dtype, jnp.inexact):
            ok = jnp.logical_and(ok, jnp.isfinite(leaf).all())
    return ok


def tree_sq_norm(tree: PyTree) -> jax.Array:
    """Scalar f32: sum of squares over every floating leaf of `tree`
    (the square of the global L2 norm). Integer leaves are skipped,
    mirroring tree_all_finite — a BatchNorm counter is not a gradient.
    Shared by both engines' stat lanes so "grad norm" means the same
    thing under kavg and syncdp."""
    total = jnp.float32(0.0)
    for leaf in jax.tree_util.tree_leaves(tree):
        if jnp.issubdtype(jnp.asarray(leaf).dtype, jnp.inexact):
            total = total + jnp.sum(
                jnp.square(leaf.astype(jnp.float32)))
    return total


def drain_round(variables: PyTree) -> PyTree:
    """Block until every leaf of `variables` is materialized on device.

    JAX dispatch is asynchronous: when the training loop acts on a
    preemption notice, the just-"completed" round's merged weights may
    still be queued behind the dispatch. The preemption grace path calls
    this before the synchronous round-granular checkpoint so "drain the
    in-flight round" is a real barrier — and so resume-latency numbers
    (bench.py preempted arm) measure checkpoint IO, not queued device
    work. Returns the same tree for call-site chaining."""
    for leaf in jax.tree_util.tree_leaves(variables):
        if hasattr(leaf, "block_until_ready"):
            leaf.block_until_ready()
    return variables


def masked_scalar_loss(loss_fn: LossFn, model_state: PyTree, batch: PyTree,
                       rng: jax.Array, smask: jax.Array):
    """params -> (masked-mean loss, new model state) — THE per-step loss
    definition shared by every training engine (K-avg and sync-DP), so
    the masked-mean semantics (padded examples excluded, zero-sample
    guard) cannot silently diverge between them."""

    def scalar(params):
        per_ex, new_state = loss_fn(
            {"params": params, **model_state}, batch,
            jax.random.wrap_key_data(rng), smask)
        denom = jnp.maximum(smask.sum(), 1.0)
        return (per_ex * smask).sum() / denom, new_state

    return scalar


class KAvgEngine:
    """Builds and caches the jitted sync-round and eval-round programs.

    One engine per job. Programs are cached per round shape
    (W, S, ...) — a parallelism change re-lowers, matching the reference's
    behavior of re-sharding between epochs (job.go:196-215).
    """

    def __init__(self, mesh: Mesh, loss_fn: LossFn, metrics_fn: MetricsFn,
                 tx_factory: TxFactory, donate: bool = True,
                 merge_dtype: Any = None, unroll: int = 8,
                 batch_seq_dims: Optional[Dict[str, int]] = None,
                 manual_inner: bool = False,
                 collect_stats: bool = False,
                 merge_bucket_mb: float = 0.0,
                 merge_compress: str = "none",
                 merge_fused: Optional[bool] = None):
        """donate=True donates the input variables buffer to each
        train_round (frees a full model copy of HBM) — the caller must then
        always continue from the *returned* variables, never reuse the
        argument. Pass donate=False for interactive/experimental use.

        merge_dtype compresses the merge collective: the summed weight
        contributions are cast to this dtype (e.g. jnp.bfloat16) before
        the cross-lane psum, halving the all-reduce bytes on ICI — and,
        on multislice meshes, on the much slower DCN phase. None (default)
        keeps the reduction in float32. This is the TPU-native analog of
        the gradient-compression family the reference lacks entirely
        (SURVEY.md §2a "Absent: ... gradient compression"): lossy
        compression applied exactly at the communication boundary, with
        local math still in f32.

        unroll: CAP on the lax.scan unroll factor for the K local steps
        (actual factor = min(unroll, K)). Fully unrolling the K=8
        headline round measures ~4% faster than unroll=2 on v5e
        (scheduling slack across step boundaries, no scan bookkeeping);
        the cap bounds compile time for large-K (sparse-averaging)
        rounds where S can reach the whole-shard step count.

        batch_seq_dims: sequence-parallel TRAINING. Maps top-level batch
        keys to the dim (within the per-example shape) that carries the
        sequence, e.g. {"x": 0} for [B, T] token ids. When the mesh seq
        axis is > 1 and this is set, those leaves are sharded over `seq`
        and the round runs with BOTH data and seq manual, under
        check_vma=True — vma tracking is what makes grads w.r.t. the
        replicated params come out correct (the backward inserts the
        seq-axis psums at the invariant->varying boundaries; with
        check_vma=False those grads are silently wrong, measured up to
        4x off on a 4-way seq mesh). The loss_fn must be seq-aware: its
        per-example loss must be invariant over `seq` (models do this
        with an internal psum — bert.py pools over the ring, gpt.py
        reduces its token loss over the axis).

        manual_inner: run the round with ALL mesh axes manual +
        check_vma=True even without seq-parallel batch sharding — the
        mode for models executing MANUAL tensor parallelism
        (parallel/manual.py: the model's own psums over the `model`
        axis, vma inserting the gradient psums at the invariant
        boundaries). Composes with batch_seq_dims (TP+SP in one round)
        and with merge_dtype (a fully-manual sub-f32 psum is safe; only
        the partial-manual one miscompiles).

        collect_stats: compile the round with the on-device HEALTH STAT
        LANES: per worker per round, the step-masked sums of squared
        global grad norm, squared update norm, and squared param norm,
        plus the cross-worker loss-spread scalar. The stats are pure
        EXTRA OUTPUTS computed from values the update dataflow already
        produces (grads, updates, round-start params) — nothing feeds
        back into the optimizer chain, so the merged weights are
        bit-identical with stats on or off (tests/test_health.py proves
        it), and like the loss they accumulate lazily on device (zero
        extra host syncs mid-epoch).

        merge_bucket_mb > 0 splits the merge into size-capped flat
        buckets, each reduced with ONE collective (parallel/merge.py):
        fewer, larger psums whose independence lets XLA overlap early
        buckets' collectives with the round's scan tail. The f32
        bucketed merge is bit-identical to the monolithic one.

        merge_compress in {"bf16", "int8"} turns on error-feedback
        compressed merges: per-lane quantized payloads with persistent
        residuals carried as extra (donated) round state, zeroed for
        lanes whose workers were all masked/quarantined/NaN-dropped.
        Mutually exclusive with merge_dtype (EF owns the wire dtype);
        implies bucketing (merge.DEFAULT_EF_BUCKET_MB cap when
        merge_bucket_mb is unset).

        merge_fused: force the fused merge-apply Pallas kernel
        (ops/pallas/fused_merge.py) on (True) or off (False) for the
        bucketed strategies; None auto-selects it on TPU backends where
        a Mosaic kernel may be emitted, falling back to the
        bit-identical lax chain elsewhere (always the fallback under
        JAX_PLATFORMS=cpu)."""
        self.mesh = mesh
        self.loss_fn = loss_fn
        self.metrics_fn = metrics_fn
        self.tx_factory = tx_factory
        self.donate = donate
        self.merge_dtype = merge_dtype
        self.unroll = max(1, int(unroll))
        self.n_lanes = mesh.shape[DATA_AXIS]
        self.collect_stats = bool(collect_stats)
        self.batch_seq_dims = dict(batch_seq_dims or {})
        self._seq_train = (mesh.shape[SEQ_AXIS] > 1
                           and bool(self.batch_seq_dims))
        self._full_manual = self._seq_train or bool(manual_inner)
        # sub-f32 wires on meshes with Auto inner axes must ride the
        # ppermute ring: a sub-f32 lax.psum fatally miscompiles in the
        # partially-manual partitioner (parallel/collectives.py). Fully-
        # manual rounds (seq-parallel / manual-TP) psum directly.
        self._wire_ring = (mesh.size != self.n_lanes
                           and not self._full_manual)
        self._compressed_ring = (merge_dtype is not None
                                 and self._wire_ring)
        if merge_dtype is not None:
            if not jnp.issubdtype(jnp.dtype(merge_dtype), jnp.floating):
                raise ValueError(
                    f"merge_dtype must be a floating dtype, got "
                    f"{jnp.dtype(merge_dtype)}")
        self.merge_bucket_mb = float(merge_bucket_mb)
        self.merge_compress = str(merge_compress or "none")
        self._merge = merge_lib.make_strategy(
            merge_dtype=merge_dtype, bucket_mb=self.merge_bucket_mb,
            compress=self.merge_compress, use_ring=self._wire_ring,
            fused=merge_fused)
        self._ef = self._merge.needs_residual
        # per-lane EF residuals: dict of flat [D * L_bucket] f32 arrays
        # sharded over `data`, threaded through (and donated to) every
        # train dispatch; None until the first compressed round
        self._ef_state: Optional[Dict[str, jax.Array]] = None
        self._train_cache: Dict[Any, Callable] = {}
        self._eval_cache: Dict[Any, Callable] = {}
        # analytic cost ledger (metrics/ledger.py): every round program
        # gets a ProgramCost captured AOT at compile time, dispatches
        # attribute flops/sample + bytes/sample, and the merge wire
        # plan is registered as an exact analytic kernel record
        self.ledger = CostLedger()

    @property
    def merge_strategy(self) -> str:
        """Registered name of the active merge strategy
        (parallel/merge.py MERGE_STRATEGIES)."""
        return self._merge.name

    @property
    def programs_compiled(self) -> int:
        """Distinct train-round programs built by this engine — the
        bench comm-proxy's compiled-program count."""
        return len(self._train_cache)

    def merge_comm_proxy(self, variables: PyTree) -> Dict[str, int]:
        """Deterministic per-round wire numbers for this engine's merge
        strategy over `variables` (see merge.MergeStrategy.comm_proxy)."""
        out = self._merge.comm_proxy(variables)
        out["strategy"] = self._merge.name
        return out

    def reset_merge_residuals(self) -> None:
        """Drop the EF residual state (membership/shape changes, or a
        cold restart where carrying stale error would be wrong)."""
        self._ef_state = None

    def _ef_residuals(self, variables: PyTree) -> Dict[str, jax.Array]:
        """Current per-lane EF residuals, zero-initialized on first use."""
        sizes = self._merge.residual_sizes(variables)
        if (self._ef_state is not None
                and set(self._ef_state) == set(sizes)
                and all(self._ef_state[k].shape[0] == self.n_lanes * n
                        for k, n in sizes.items())):
            return self._ef_state
        sh = NamedSharding(self.mesh, P(DATA_AXIS))
        self._ef_state = {
            k: jax.device_put(np.zeros(self.n_lanes * n, np.float32), sh)
            for k, n in sizes.items()}
        return self._ef_state

    def _shmap_manual_kwargs(self) -> Dict[str, Any]:
        """shard_map manual-axes kwargs shared by the train and eval
        builders (they must partition identically).

        Default: only the data axis is manual (the masked-psum merge);
        all inner axes (model/seq/stage/expert) stay AUTO, so variables
        sharded over them — e.g. Megatron TP rules via parallel.tp —
        train as-is: GSPMD inserts the model-axis collectives inside
        each DP lane while the weight average still psums over `data`
        only. Pure-DP meshes (all inner axes size 1) go FULL manual
        ({}): leaving size-1 axes Auto blocks pallas kernels inside the
        round ("Mosaic kernels cannot be automatically partitioned"),
        which would silently cost transformer models their flash
        attention. Compressed merges pick their collective accordingly:
        direct sub-f32 psum when full-manual, the ppermute ring when
        inner axes stay Auto (a partially-manual sub-f32 psum fatally
        miscompiles — parallel/collectives.py).
        """
        if self.mesh.size == self.mesh.shape[DATA_AXIS]:
            return {}
        if self._full_manual:
            # seq-parallel and/or manual-TP training: ALL axes manual
            # (leaving the unused axes Auto trips the same partial-manual
            # partitioner bug as merge_dtype: "Invalid binary instruction
            # opcode copy") and vma tracking ON — required for correct
            # grads w.r.t. the replicated params (see __init__
            # docstring). GSPMD TP cannot ride a fully-manual round; the
            # job layer picks manual TP (parallel/manual.py) there.
            return dict(check_vma=True)
        return dict(axis_names={DATA_AXIS})

    def _shmap_kwargs(self) -> Dict[str, Any]:
        """Full shard_map kwargs: manual axes + the vma flag (default
        off — masked-psum merges and pallas calls predate vma tracking;
        seq-parallel training overrides it on)."""
        kw = dict(check_vma=False)
        kw.update(self._shmap_manual_kwargs())
        return kw

    def _batch_in_specs(self, batch: PyTree):
        """Per-leaf PartitionSpecs for a [W, S, B, ...] round batch:
        everything shards over `data` on dim 0; sequence-carrying keys
        additionally shard their sequence dim over `seq`."""
        if not self._seq_train:
            return P(DATA_AXIS)
        if not isinstance(batch, dict):
            raise ValueError("sequence-parallel training requires a dict "
                             "batch (keys matched against batch_seq_dims)")
        return {k: seq_batch_spec(k, self.batch_seq_dims) for k in batch}

    # ---------------------------------------------------------------- train

    def _make_lane_fn(self, w_per_lane: int):
        """Build the per-lane sync-round body shared by the one-round
        and R-round programs: K masked local steps per virtual worker
        (lax.scan) followed by the masked-psum merge; elastic N, chaos
        hooks, and the seq/manual variants all flow through this one
        body."""
        mesh = self.mesh
        loss_fn = self.loss_fn
        tx_factory = self.tx_factory
        full_manual = self._full_manual
        collect = self.collect_stats

        def run_chunk(variables, chunk, lr, epoch):
            """K masked local steps for one virtual worker.

            chunk: dict with batch [S, B, ...] pytree under 'batch',
            sample_mask [S, B], step_mask [S], rngs [S, 2].
            """
            tx = tx_factory(lr, epoch)
            params = variables["params"]
            model_state = {k: v for k, v in variables.items() if k != "params"}
            opt_state = tx.init(params)  # fresh optimizer per sync round
            if full_manual:
                # vma: the scan carry becomes data-varying after step 1
                # (local steps genuinely diverge per lane), so the
                # invariant round-start params must be pcast to varying
                # for the carry types to match. Values stay seq-INVARIANT
                # throughout — that is what vma's backward enforces.
                params, model_state, opt_state = jax.tree_util.tree_map(
                    lambda x: compat.pcast(x, DATA_AXIS, to="varying"),
                    (params, model_state, opt_state))

            def step(carry, xs):
                params, model_state, opt_state = carry
                batch, smask, stmask, rng = xs
                (loss, new_state), grads = jax.value_and_grad(
                    masked_scalar_loss(loss_fn, model_state, batch, rng,
                                       smask), has_aux=True)(params)
                updates, new_opt = tx.update(grads, opt_state, params)
                new_params = optax.apply_updates(params, updates)
                out = loss * stmask
                if collect:
                    # health-stat lane: squared global grad/update/param
                    # norms from the values the update chain already
                    # computed. Masked steps contribute zero (stmask
                    # multiply is safe here: a non-finite worker's stats
                    # are SELECTed out lane-side, not multiplied).
                    out = (out, stmask * jnp.stack([
                        tree_sq_norm(grads), tree_sq_norm(updates),
                        tree_sq_norm(params)]))
                # note: compiling an unmasked variant for all-real rounds
                # was tried in round 3 and measured WITHIN NOISE on the
                # v5e headline config — XLA fuses these selects into the
                # optimizer-update chain, so they are effectively free;
                # keep the single masked program
                params = _select_tree(stmask, new_params, params)
                model_state = _select_tree(stmask, new_state, model_state)
                opt_state = _select_tree(stmask, new_opt, opt_state)
                return (params, model_state, opt_state), out

            (params, model_state, _), out = lax.scan(
                step, (params, model_state, opt_state),
                (chunk["batch"], chunk["sample_mask"], chunk["step_mask"],
                 chunk["rngs"]),
                unroll=min(self.unroll, chunk["step_mask"].shape[0]))
            new_vars = {"params": params, **model_state}
            if collect:
                losses, stat_steps = out
                return new_vars, losses.sum(), stat_steps.sum(axis=0)
            return new_vars, out.sum(), None

        def lane_fn(variables, batch, sample_mask, step_mask, worker_mask,
                    rngs, lr, epoch, resid=None):
            # per-lane shapes: batch [W/D, S, B, ...], masks likewise, all
            # already sliced by shard_map over the data axis.
            contrib = jax.tree_util.tree_map(
                lambda x: jnp.zeros_like(x, dtype=jnp.float32), variables)
            loss_sums = []
            dropped = []
            stat_rows = []
            spread_m1 = jnp.float32(0.0)  # masked sums of per-worker mean
            spread_m2 = jnp.float32(0.0)  # loss and its square (for var)
            eff_count = jnp.float32(0.0)
            for v in range(w_per_lane):  # static unroll, w_per_lane is tiny
                chunk = {
                    "batch": jax.tree_util.tree_map(lambda x: x[v], batch),
                    "sample_mask": sample_mask[v],
                    "step_mask": step_mask[v],
                    "rngs": rngs[v],
                }
                new_vars, loss_sum, stat_sum = run_chunk(
                    variables, chunk, lr, epoch)
                wm = worker_mask[v]
                # merge guard: a worker whose K local steps produced ANY
                # non-finite weight (or a non-finite loss) is dropped from
                # the merge exactly as if its mask bit had been 0 — the
                # TPU-native "merge with whoever reported". The drop must
                # be a jnp.where SELECT, not a multiply: NaN * 0 == NaN,
                # so masking by multiplication would poison the psum for
                # every worker (the exact failure this guard exists for).
                ok = jnp.logical_and(tree_all_finite(new_vars),
                                     jnp.isfinite(loss_sum))
                okf = ok.astype(jnp.float32)
                contrib = jax.tree_util.tree_map(
                    lambda c, n: c + jnp.where(ok, n, 0).astype(jnp.float32)
                    * wm, contrib, new_vars)
                loss_sums.append(jnp.where(ok, loss_sum, 0.0) * wm)
                dropped.append(wm * (1.0 - okf))
                eff_count = eff_count + wm * okf
                if collect:
                    # stat rows ride the same SELECT-not-multiply guard
                    # as the loss: a dropped worker's NaN grads must not
                    # poison the epoch accumulators
                    stat_rows.append(
                        jnp.where(ok, stat_sum, jnp.zeros_like(stat_sum))
                        * wm)
                    mean_v = loss_sum / jnp.maximum(
                        chunk["step_mask"].sum(), 1.0)
                    w_ok = wm * okf
                    safe = jnp.where(ok, mean_v, 0.0)
                    spread_m1 = spread_m1 + w_ok * safe
                    spread_m2 = spread_m2 + w_ok * safe * safe

            raw_count = lax.psum(eff_count, DATA_AXIS)
            count = jnp.maximum(raw_count, 1.0)  # guard 0-contributor divide
            # the strategy object (parallel/merge.py, selected at engine
            # construction) owns the cross-lane wire: per-leaf psums
            # (monolithic), flat size-capped buckets (bucketed, one
            # collective each), or EF-compressed buckets with per-lane
            # residual carry. All variants preserve the all-dropped
            # carry-forward (raw_count == 0 returns `variables`) and the
            # SELECT-not-multiply drop guard applied to `contrib` above.
            avg, new_resid = self._merge.lane_merge(
                contrib, variables, raw_count, count,
                lane_alive=eff_count > 0, residual=resid)
            if collect:
                # cross-worker loss spread: population std of the merged
                # workers' per-step mean losses, computed with two psums
                # over moments already on device (no extra readback)
                m1 = lax.psum(spread_m1, DATA_AXIS) / count
                m2 = lax.psum(spread_m2, DATA_AXIS) / count
                spread = jnp.sqrt(jnp.maximum(m2 - m1 * m1, 0.0))
                outs = (jnp.stack(loss_sums), jnp.stack(dropped),
                        jnp.stack(stat_rows), spread)
            else:
                outs = (jnp.stack(loss_sums), jnp.stack(dropped))
            if self._ef:
                return avg, outs, new_resid
            return avg, outs

        return lane_fn

    def _stat_out_specs(self, lift=None):
        """out_specs tail for the collect_stats extras: the [W, 3] stat
        matrix shards over data like the loss sums; the spread scalar is
        replicated (it is a cross-lane psum result)."""
        if not self.collect_stats:
            return ()
        if lift is None:
            return (P(DATA_AXIS), P())
        return (lift(P(DATA_AXIS)), P(None))

    def _ef_specs(self) -> tuple:
        """Extra in/out spec tail for the EF residual dict: per-lane
        flat buckets live as [D * L] arrays sharded over `data` (the
        spec is a pytree prefix over the dict). Empty when the strategy
        carries no residual."""
        return (P(DATA_AXIS),) if self._ef else ()

    def _donate(self, resid_arg: int) -> tuple:
        """Donated argnums: the variables buffer plus — for EF
        strategies — the residual carry at position `resid_arg` (both
        are replaced by the round's outputs)."""
        if not self.donate:
            return ()
        return (0, resid_arg) if self._ef else (0,)

    def _build_train_round(self, w_per_lane: int, batch_template=None):
        """Compile the sync-round program: one sync round per dispatch."""
        sharded = compat.shard_map(
            self._make_lane_fn(w_per_lane), mesh=self.mesh,
            in_specs=(P(), self._batch_in_specs(batch_template),
                      P(DATA_AXIS), P(DATA_AXIS),
                      P(DATA_AXIS), P(DATA_AXIS), P(), P())
            + self._ef_specs(),
            out_specs=(P(), (P(DATA_AXIS), P(DATA_AXIS))
                       + self._stat_out_specs()) + self._ef_specs(),
            **self._shmap_kwargs())
        return jax.jit(sharded, donate_argnums=self._donate(8))

    def _build_train_rounds(self, w_per_lane: int, batch_template=None):
        """Compile the R-round program: a lax.scan of the SAME per-lane
        round body, R sync rounds (merges between them preserved) in ONE
        dispatch. Identical math to R single-round dispatches; what it
        buys is R x fewer submissions — on tunneled/high-latency
        backends per-round dispatch costs host work + wire latency that
        a ~50 ms round cannot fully hide (experiments/round_probe.py
        quantifies it). R is baked into the program via the leading axis
        of every non-variables input."""
        lane_fn = self._make_lane_fn(w_per_lane)
        ef = self._ef

        def multi_lane(variables, batch, sample_mask, step_mask,
                       worker_mask, rngs, lr, epoch, *resid):
            # EF residuals ride the round scan as part of the carry:
            # round r+1's payload re-injects round r's cast error.
            def one(carry, xs):
                vars_, rs = carry
                b, sm, stm, wm, rg = xs
                out = lane_fn(vars_, b, sm, stm, wm, rg, lr, epoch, rs)
                if ef:
                    avg, outs, new_rs = out
                    return (avg, new_rs), outs
                avg, outs = out
                return (avg, None), outs

            (vars_, rs), outs = lax.scan(
                one, (variables, resid[0] if ef else None),
                (batch, sample_mask, step_mask, worker_mask, rngs))
            if ef:
                return vars_, outs, rs
            return vars_, outs

        def lift(spec: P) -> P:
            return P(None, *spec)

        batch_specs = self._batch_in_specs(batch_template)
        batch_specs = (jax.tree_util.tree_map(lift, batch_specs)
                       if isinstance(batch_specs, dict)
                       else lift(batch_specs))
        sharded = compat.shard_map(
            multi_lane, mesh=self.mesh,
            in_specs=(P(), batch_specs,
                      lift(P(DATA_AXIS)), lift(P(DATA_AXIS)),
                      lift(P(DATA_AXIS)), lift(P(DATA_AXIS)), P(), P())
            + self._ef_specs(),
            out_specs=(P(), (lift(P(DATA_AXIS)), lift(P(DATA_AXIS)))
                       + self._stat_out_specs(lift)) + self._ef_specs(),
            **self._shmap_kwargs())
        return jax.jit(sharded, donate_argnums=self._donate(8))

    def _cost_fallback(self, variables: PyTree, samples: int) -> dict:
        """Closed-form per-dispatch estimate for backends without XLA
        cost analysis: ~6 flops per weight per sample (dense fwd+bwd+
        update rule of thumb) over params read/written plus the merge
        wire payload."""
        nbytes = sum(int(getattr(a, "nbytes", 0))
                     for a in jax.tree_util.tree_leaves(variables))
        payload = self._merge.comm_proxy(variables)["merge_payload_bytes"]
        return {"flops": 6.0 * (nbytes / 4.0) * max(samples, 1),
                "hbm_bytes": float(3 * nbytes + payload)}

    def _dispatch(self, fn: Callable, variables: PyTree, *args,
                  program: str = "", compiled: bool = False,
                  samples: int = 0):
        """Invoke a compiled round program, threading (and re-stashing)
        the EF residual carry when the strategy keeps one. On a compile
        the program's ProgramCost is captured AOT first (aval-only
        lowering over the exact args about to dispatch — donation-safe,
        jit-cache-invisible), then every dispatch attributes its sample
        count to the ledger."""
        full = (variables, *args)
        if self._ef:
            resid = self._ef_residuals(variables)
            full = full + (resid,)
        if compiled and program:
            self.ledger.capture(
                program, "train", fn, *full,
                fallback=self._cost_fallback(variables, samples))
            merge_lib.register_strategy_cost(self.ledger, self._merge,
                                             variables)
        if program:
            self.ledger.note_dispatch(program, samples=samples)
        if self._ef:
            avg, outs, new_resid = fn(*full)
            self._ef_state = new_resid
            return avg, outs
        return fn(*full)

    def train_rounds(self, variables: PyTree, batch: PyTree,
                     sample_mask: np.ndarray, step_mask: np.ndarray,
                     worker_mask: np.ndarray, rngs: np.ndarray,
                     lr: float, epoch: int) -> Tuple[PyTree, RoundStats]:
        """Execute R consecutive sync rounds in ONE dispatch.

        Same contract as train_round with a leading round axis R on
        every array: batch leaves [R, W, S, B, ...], sample_mask
        [R, W, S, B], step_mask [R, W, S], worker_mask [R, W], rngs
        [R, W, S, 2]. Merges run between rounds exactly as in R
        single-round dispatches. Stats come back per round:
        loss_sum_device [R, W], step_count/sample_count [R, W]."""
        R, W = int(step_mask.shape[0]), int(step_mask.shape[1])
        if W % self.n_lanes:
            raise ValueError(f"W={W} not a multiple of lanes={self.n_lanes}")
        w_per_lane = W // self.n_lanes
        lead = jax.tree_util.tree_leaves(batch)[0]
        key = ("multi", R, w_per_lane, tuple(lead.shape[2:4]),
               jax.tree_util.tree_structure(batch), self.collect_stats)
        compiled = key not in self._train_cache
        if compiled:
            self._train_cache[key] = self._build_train_rounds(
                w_per_lane, batch_template=batch)
        avg, (loss_sums, dropped, *extra) = self._dispatch(
            self._train_cache[key], variables, batch,
            jnp.asarray(sample_mask, jnp.float32),
            jnp.asarray(step_mask, jnp.float32),
            jnp.asarray(worker_mask, jnp.float32),
            jnp.asarray(rngs, jnp.uint32),
            jnp.float32(lr), jnp.int32(epoch),
            program="kavg.train_multi", compiled=compiled,
            samples=int(np.asarray(sample_mask).sum()))
        stats = RoundStats(
            loss_sum_device=loss_sums,
            step_count=np.asarray(step_mask).sum(axis=2),
            sample_count=np.asarray(sample_mask).sum(axis=(2, 3)),
            contributors=float(np.asarray(worker_mask).sum()),
            compiled=compiled,
            dropped_device=dropped,
            stat_device=extra[0] if extra else None,
            spread_device=extra[1] if extra else None,
        )
        return avg, stats

    def train_round(self, variables: PyTree, batch: PyTree,
                    sample_mask: np.ndarray, step_mask: np.ndarray,
                    worker_mask: np.ndarray, rngs: np.ndarray,
                    lr: float, epoch: int) -> Tuple[PyTree, RoundStats]:
        """Execute one sync round.

        batch leaves: [W, S, B, ...]; sample_mask [W, S, B]; step_mask [W, S];
        worker_mask [W]; rngs [W, S, 2] uint32 key data. W must be a multiple
        of the mesh data-axis size.
        """
        W = int(step_mask.shape[0])
        if W % self.n_lanes:
            raise ValueError(f"W={W} not a multiple of lanes={self.n_lanes}")
        w_per_lane = W // self.n_lanes
        lead = jax.tree_util.tree_leaves(batch)[0]
        key = (w_per_lane, tuple(lead.shape[1:3]),
               jax.tree_util.tree_structure(batch), self.collect_stats)
        compiled = key not in self._train_cache
        if compiled:
            self._train_cache[key] = self._build_train_round(
                w_per_lane, batch_template=batch)

        # shard_map slices dim 0 contiguously: lane d owns virtual workers
        # [d*W/D, (d+1)*W/D) — matching the reference's contiguous doc shards.
        avg, (loss_sums, dropped, *extra) = self._dispatch(
            self._train_cache[key], variables, batch,
            jnp.asarray(sample_mask, jnp.float32),
            jnp.asarray(step_mask, jnp.float32),
            jnp.asarray(worker_mask, jnp.float32),
            jnp.asarray(rngs, jnp.uint32),
            jnp.float32(lr), jnp.int32(epoch),
            program="kavg.train", compiled=compiled,
            samples=int(np.asarray(sample_mask).sum()))
        stats = RoundStats(
            loss_sum_device=loss_sums,
            step_count=np.asarray(step_mask).sum(axis=1),
            sample_count=np.asarray(sample_mask).sum(axis=(1, 2)),
            contributors=float(np.asarray(worker_mask).sum()),
            compiled=compiled,
            dropped_device=dropped,
            stat_device=extra[0] if extra else None,
            spread_device=extra[1] if extra else None,
        )
        return avg, stats

    # ------------------------------------------------------ index-fed train

    def _indexed_lane_fn(self, w_per_lane: int, cache):
        """Per-lane body for INDEX-FED rounds (data/device_cache.py):
        gather the lane's samples from the device-resident dataset
        slab, then run the exact same round body as the host-staged
        path. The gather is the only addition — masks, local steps,
        and the merge are byte-for-byte the same lane_fn, which is what
        makes index-fed rounds bit-identical to host-staged ones (the
        gathered values match what the host would have shipped; padded
        slots gather sample 0 instead of zeros but are fully masked)."""
        lane_fn = self._make_lane_fn(w_per_lane)
        lane_sharded = cache.layout == "sharded"
        device_transform = cache.device_transform

        def indexed_lane(variables, cache_arrays, idx, sample_mask,
                         step_mask, worker_mask, rngs, lr, epoch,
                         resid=None):
            # sharded layout: the [D, L, ...] slab arrives per-lane as
            # [1, L, ...]; indices are lane-local into that slab.
            # replicated layout: the full [n, ...] split, global indices.
            src = {k: (v[0] if lane_sharded else v)
                   for k, v in cache_arrays.items()}
            if device_transform is not None:
                batch = device_transform(src["x"][idx], src["y"][idx])
            else:
                batch = {k: v[idx] for k, v in src.items()}
            return lane_fn(variables, batch, sample_mask, step_mask,
                           worker_mask, rngs, lr, epoch, resid)

        return indexed_lane

    def _cache_in_specs(self, cache):
        return {k: (P(DATA_AXIS) if cache.layout == "sharded" else P())
                for k in cache.arrays}

    def _build_train_round_indexed(self, w_per_lane: int, cache):
        sharded = compat.shard_map(
            self._indexed_lane_fn(w_per_lane, cache), mesh=self.mesh,
            in_specs=(P(), self._cache_in_specs(cache),
                      P(DATA_AXIS), P(DATA_AXIS), P(DATA_AXIS),
                      P(DATA_AXIS), P(DATA_AXIS), P(), P())
            + self._ef_specs(),
            out_specs=(P(), (P(DATA_AXIS), P(DATA_AXIS))
                       + self._stat_out_specs()) + self._ef_specs(),
            **self._shmap_kwargs())
        # donate only the variables (and the EF residual carry) — the
        # cache (arg 1) must outlive every round of the job
        return jax.jit(sharded, donate_argnums=self._donate(9))

    def _build_train_rounds_indexed(self, w_per_lane: int, cache):
        indexed = self._indexed_lane_fn(w_per_lane, cache)
        ef = self._ef

        def multi_lane(variables, cache_arrays, idx, sample_mask,
                       step_mask, worker_mask, rngs, lr, epoch, *resid):
            def one(carry, xs):
                vars_, rs = carry
                ix, sm, stm, wm, rg = xs
                out = indexed(vars_, cache_arrays, ix, sm, stm, wm, rg,
                              lr, epoch, rs)
                if ef:
                    avg, outs, new_rs = out
                    return (avg, new_rs), outs
                avg, outs = out
                return (avg, None), outs

            # the cache rides the scan as a closed-over constant: R
            # rounds of indices scan over it without it ever moving
            (vars_, rs), outs = lax.scan(
                one, (variables, resid[0] if ef else None),
                (idx, sample_mask, step_mask, worker_mask, rngs))
            if ef:
                return vars_, outs, rs
            return vars_, outs

        def lift(spec: P) -> P:
            return P(None, *spec)

        sharded = compat.shard_map(
            multi_lane, mesh=self.mesh,
            in_specs=(P(), self._cache_in_specs(cache),
                      lift(P(DATA_AXIS)), lift(P(DATA_AXIS)),
                      lift(P(DATA_AXIS)), lift(P(DATA_AXIS)),
                      lift(P(DATA_AXIS)), P(), P()) + self._ef_specs(),
            out_specs=(P(), (lift(P(DATA_AXIS)), lift(P(DATA_AXIS)))
                       + self._stat_out_specs(lift)) + self._ef_specs(),
            **self._shmap_kwargs())
        return jax.jit(sharded, donate_argnums=self._donate(9))

    def train_round_indexed(self, variables: PyTree, cache,
                            idx: np.ndarray, sample_mask: np.ndarray,
                            step_mask: np.ndarray, worker_mask: np.ndarray,
                            rngs: np.ndarray, lr: float, epoch: int
                            ) -> Tuple[PyTree, RoundStats]:
        """Execute one sync round against the device-resident dataset
        cache: same contract and results as train_round, but the
        dispatch carries only `idx` [W, S, B] int32 gather indices
        (lane-local for sharded caches, global for replicated) instead
        of materialized batch leaves."""
        if self._seq_train:
            raise ValueError("index-fed rounds do not support "
                             "sequence-parallel batch sharding")
        W = int(step_mask.shape[0])
        if W % self.n_lanes:
            raise ValueError(f"W={W} not a multiple of lanes={self.n_lanes}")
        w_per_lane = W // self.n_lanes
        key = ("idx", w_per_lane, tuple(np.shape(idx)[1:3]),
               cache.signature, self.collect_stats)
        compiled = key not in self._train_cache
        if compiled:
            self._train_cache[key] = self._build_train_round_indexed(
                w_per_lane, cache)
        avg, (loss_sums, dropped, *extra) = self._dispatch(
            self._train_cache[key], variables, cache.arrays,
            jnp.asarray(idx, jnp.int32),
            jnp.asarray(sample_mask, jnp.float32),
            jnp.asarray(step_mask, jnp.float32),
            jnp.asarray(worker_mask, jnp.float32),
            jnp.asarray(rngs, jnp.uint32),
            jnp.float32(lr), jnp.int32(epoch),
            program="kavg.train_indexed", compiled=compiled,
            samples=int(np.asarray(sample_mask).sum()))
        stats = RoundStats(
            loss_sum_device=loss_sums,
            step_count=np.asarray(step_mask).sum(axis=1),
            sample_count=np.asarray(sample_mask).sum(axis=(1, 2)),
            contributors=float(np.asarray(worker_mask).sum()),
            compiled=compiled,
            dropped_device=dropped,
            stat_device=extra[0] if extra else None,
            spread_device=extra[1] if extra else None,
        )
        return avg, stats

    def train_rounds_indexed(self, variables: PyTree, cache,
                             idx: np.ndarray, sample_mask: np.ndarray,
                             step_mask: np.ndarray, worker_mask: np.ndarray,
                             rngs: np.ndarray, lr: float, epoch: int
                             ) -> Tuple[PyTree, RoundStats]:
        """R index-fed sync rounds in ONE dispatch (train_rounds with
        `idx` [R, W, S, B] instead of batch leaves — the dispatch
        payload a grouped round ships shrinks by the same factor)."""
        if self._seq_train:
            raise ValueError("index-fed rounds do not support "
                             "sequence-parallel batch sharding")
        R, W = int(step_mask.shape[0]), int(step_mask.shape[1])
        if W % self.n_lanes:
            raise ValueError(f"W={W} not a multiple of lanes={self.n_lanes}")
        w_per_lane = W // self.n_lanes
        key = ("idx-multi", R, w_per_lane, tuple(np.shape(idx)[2:4]),
               cache.signature, self.collect_stats)
        compiled = key not in self._train_cache
        if compiled:
            self._train_cache[key] = self._build_train_rounds_indexed(
                w_per_lane, cache)
        avg, (loss_sums, dropped, *extra) = self._dispatch(
            self._train_cache[key], variables, cache.arrays,
            jnp.asarray(idx, jnp.int32),
            jnp.asarray(sample_mask, jnp.float32),
            jnp.asarray(step_mask, jnp.float32),
            jnp.asarray(worker_mask, jnp.float32),
            jnp.asarray(rngs, jnp.uint32),
            jnp.float32(lr), jnp.int32(epoch),
            program="kavg.train_multi_indexed", compiled=compiled,
            samples=int(np.asarray(sample_mask).sum()))
        stats = RoundStats(
            loss_sum_device=loss_sums,
            step_count=np.asarray(step_mask).sum(axis=2),
            sample_count=np.asarray(sample_mask).sum(axis=(2, 3)),
            contributors=float(np.asarray(worker_mask).sum()),
            compiled=compiled,
            dropped_device=dropped,
            stat_device=extra[0] if extra else None,
            spread_device=extra[1] if extra else None,
        )
        return avg, stats

    # ----------------------------------------------------------------- eval

    def _build_eval_round(self, w_per_lane: int, metric_names: Tuple[str, ...],
                          batch_template=None):
        mesh = self.mesh
        metrics_fn = self.metrics_fn

        def lane_fn(variables, batch, sample_mask):
            sums = {name: jnp.float32(0.0) for name in metric_names}
            n = jnp.float32(0.0)
            for v in range(w_per_lane):
                b = jax.tree_util.tree_map(lambda x: x[v], batch)
                sm = sample_mask[v]  # [S, B]

                def eval_step(_, xs):
                    mb, m = xs
                    vals = metrics_fn(variables, mb)
                    return None, {k: (v_ * m).sum() for k, v_ in vals.items()}

                _, per_step = lax.scan(eval_step, None, (b, sm))
                for name in metric_names:
                    sums[name] = sums[name] + per_step[name].sum()
                n = n + sm.sum()
            total_n = jnp.maximum(lax.psum(n, DATA_AXIS), 1.0)
            totals = {k: lax.psum(v, DATA_AXIS) for k, v in sums.items()}
            return totals, total_n

        sharded = compat.shard_map(
            lane_fn, mesh=mesh,
            in_specs=(P(), self._batch_in_specs(batch_template),
                      P(DATA_AXIS)),
            out_specs=(P(), P()),
            **self._shmap_kwargs())
        return jax.jit(sharded)

    def eval_round(self, variables: PyTree, batch: PyTree,
                   sample_mask: np.ndarray,
                   metric_names: Tuple[str, ...] = ("loss", "accuracy")
                   ) -> Dict[str, float]:
        """Datapoint-weighted evaluation over all workers.

        Parity with the reference's weighted validation aggregation
        (ml/pkg/train/util.go:100-122): metric = sum(per-example) / n.
        """
        W = int(jax.tree_util.tree_leaves(batch)[0].shape[0])
        if W % self.n_lanes:
            raise ValueError(f"W={W} not a multiple of lanes={self.n_lanes}")
        w_per_lane = W // self.n_lanes
        lead = jax.tree_util.tree_leaves(batch)[0]
        # tree structure is part of the key: the compiled program bakes
        # in per-key in_specs from the batch template (same as train)
        key = (w_per_lane, tuple(lead.shape[1:3]), metric_names,
               jax.tree_util.tree_structure(batch))
        eval_compiled = key not in self._eval_cache
        if eval_compiled:
            self._eval_cache[key] = self._build_eval_round(
                w_per_lane, metric_names, batch_template=batch)
        eval_args = (variables, batch,
                     jnp.asarray(sample_mask, jnp.float32))
        if eval_compiled:
            self.ledger.capture(
                "kavg.eval", "train", self._eval_cache[key], *eval_args,
                fallback=self._cost_fallback(
                    variables, int(np.asarray(sample_mask).sum())))
        self.ledger.note_dispatch(
            "kavg.eval", samples=int(np.asarray(sample_mask).sum()))
        totals, n = self._eval_cache[key](*eval_args)
        n = float(n)
        return {k: float(v) / n for k, v in totals.items()} | {"n": n}
