"""Multi-host / multi-slice distributed runtime.

The reference scales out with NCCL-free HTTP fan-out over Kubernetes pods
(SURVEY.md §2b: RedisAI blackboard data plane + JSON control plane). The
TPU-native equivalent is JAX's multi-controller runtime: every host runs
the same program, `jax.distributed` forms the cluster, and XLA collectives
ride ICI within a slice and DCN across slices. Nothing else in the
framework changes — the mesh abstracts the transport, so the same
KAvgEngine/TP/SP/PP/EP code paths run single-chip, single-slice, and
multi-slice.

Two entry points:

  initialize(...)        — join (or bootstrap) the multi-host cluster.
                           On Cloud TPU pods all arguments are discovered
                           from the metadata environment; off-TPU the
                           caller passes coordinator/num_processes/
                           process_id explicitly.
  make_multislice_mesh() — a (data, model, seq, stage, expert) mesh whose
                           device order is SLICE-MAJOR on the data axis:
                           lanes that differ only within a slice are
                           ICI-adjacent, and the data-parallel psum
                           decomposes into per-slice reduce (ICI) + a
                           small cross-slice phase (DCN) — the layout the
                           XLA multi-slice all-reduce pass expects.
                           Inner (model/seq/stage/expert) axes never
                           cross a slice boundary, keeping the
                           latency-sensitive TP/ring/pipeline collectives
                           on ICI.

The data-parallel semantics over DCN are identical to single-slice: the
K-avg weight average is one masked psum over the full `data` axis
(parallel/kavg.py), regardless of how many slices that axis spans.
"""

from __future__ import annotations

import collections
import logging
import os
from typing import Dict, List, Optional, Sequence

import jax
from jax.sharding import Mesh

from kubeml_tpu.parallel.mesh import make_mesh

logger = logging.getLogger("kubeml_tpu.distributed")

# Every env-var family that can make a process believe it belongs to a
# jax.distributed cluster — our own launcher vars plus everything
# initialize()/_cluster_env_present auto-detects (jax / megascale /
# TPU-pod / SLURM / OpenMPI). Kept HERE, next to the detection logic,
# so detection and scrubbing (control/ps.py strips these from
# standalone-job child envs) evolve together: a child inheriting its
# parent's rank re-joins the parent's cluster and hangs it.
CLUSTER_ENV_VARS = (
    "KUBEML_COORDINATOR_ADDRESS", "KUBEML_NUM_PROCESSES",
    "KUBEML_PROCESS_ID",
    "JAX_COORDINATOR_ADDRESS", "JAX_NUM_PROCESSES", "JAX_PROCESS_ID",
    "MEGASCALE_COORDINATOR_ADDRESS", "MEGASCALE_NUM_SLICES",
    "MEGASCALE_SLICE_ID",
    "TPU_WORKER_HOSTNAMES", "TPU_WORKER_ID",
    "SLURM_NTASKS", "SLURM_PROCID", "SLURM_JOB_ID",
    "OMPI_COMM_WORLD_SIZE", "OMPI_COMM_WORLD_RANK",
)


def _cluster_env_present() -> bool:
    """True when the environment indicates a MULTI-host cluster
    (jax.distributed auto-detects from these families). If so, a failed
    join must raise — proceeding single-process would train N independent
    model copies and report wrong results. Single-host values (e.g.
    TPU_WORKER_HOSTNAMES=localhost on a 1-host slice) don't count."""
    if os.environ.get("JAX_COORDINATOR_ADDRESS") \
            or os.environ.get("MEGASCALE_COORDINATOR_ADDRESS") \
            or os.environ.get("KUBEML_COORDINATOR_ADDRESS"):
        return True
    hosts = os.environ.get("TPU_WORKER_HOSTNAMES", "")
    if len([h for h in hosts.split(",") if h.strip()]) > 1:
        return True
    for var in ("SLURM_NTASKS", "OMPI_COMM_WORLD_SIZE"):
        try:
            if int(os.environ.get(var, "1")) > 1:
                return True
        except ValueError:
            pass
    return False


def initialize(coordinator_address: Optional[str] = None,
               num_processes: Optional[int] = None,
               process_id: Optional[int] = None) -> None:
    """Join the multi-host JAX cluster (idempotent).

    On Cloud TPU pod slices, call with no arguments — JAX discovers the
    coordinator and process topology from the TPU metadata environment.
    For DCN-connected CPU/GPU hosts or manual bring-up, pass all three.
    Must be the FIRST JAX call in the process (jax.distributed's own
    contract): touching the backend first makes joining impossible, so
    this function deliberately makes no other JAX calls before the join.

    With explicit arguments a rendezvous failure raises — silently
    training N independent model copies would be wrong results, not
    degraded service. With no arguments and no environment to discover
    from, this is a single-process run and returns quietly.

    This replaces the reference's Kubernetes Service discovery + HTTP
    rendezvous (ml/pkg/api/const.go:4-14, ml/pkg/ps/job_pod.go:96-137):
    after initialize(), `jax.devices()` spans every chip in the cluster
    and collectives over any mesh built from them ride ICI/DCN.
    """
    is_init = getattr(jax.distributed, "is_initialized", None)
    if is_init is not None and is_init():
        return  # already part of a cluster
    # env-driven bring-up (tools/launch_distributed.py and manual
    # multi-host runs set these; explicit arguments win)
    if coordinator_address is None:
        coordinator_address = os.environ.get("KUBEML_COORDINATOR_ADDRESS")
    if num_processes is None and os.environ.get("KUBEML_NUM_PROCESSES"):
        num_processes = int(os.environ["KUBEML_NUM_PROCESSES"])
    if process_id is None and os.environ.get("KUBEML_PROCESS_ID"):
        process_id = int(os.environ["KUBEML_PROCESS_ID"])
    kwargs = {}
    if coordinator_address is not None:
        kwargs["coordinator_address"] = coordinator_address
    if num_processes is not None:
        kwargs["num_processes"] = num_processes
    if process_id is not None:
        kwargs["process_id"] = process_id
    try:
        jax.distributed.initialize(**kwargs)
        logger.info("joined cluster: process %d/%d, %d devices",
                    jax.process_index(), jax.process_count(),
                    len(jax.devices()))
    except (RuntimeError, ValueError) as e:
        if kwargs or _cluster_env_present():
            raise  # a real cluster must not silently degrade to 1 process
        logger.info("single-process run (jax.distributed unavailable: %s)",
                    e)


def group_by_slice(devices: Sequence,
                   n_slices: Optional[int] = None) -> List[List]:
    """Partition devices into ICI-connected groups (slices).

    Real TPU devices carry `slice_index`; hosts without it (CPU tests,
    single-slice) fall back to process_index, and `n_slices` forces an
    even contiguous split for emulating multi-slice layouts on virtual
    devices.
    """
    devices = list(devices)
    if n_slices is not None:
        if len(devices) % n_slices:
            raise ValueError(
                f"{len(devices)} devices not divisible into {n_slices} "
                "slices")
        per = len(devices) // n_slices
        return [devices[i * per:(i + 1) * per] for i in range(n_slices)]
    groups: Dict[int, List] = collections.defaultdict(list)
    for d in devices:
        sid = getattr(d, "slice_index", None)
        if sid is None:
            sid = getattr(d, "process_index", 0)
        groups[sid].append(d)
    sizes = {len(g) for g in groups.values()}
    if len(sizes) > 1:
        raise ValueError(f"uneven slices: {sorted(sizes)}")
    return [sorted(groups[sid], key=lambda d: d.id)
            for sid in sorted(groups)]


def make_multislice_mesh(n_model: int = 1, n_seq: int = 1, n_stage: int = 1,
                         n_expert: int = 1,
                         devices: Optional[Sequence] = None,
                         n_slices: Optional[int] = None) -> Mesh:
    """Build the standard 5-axis mesh over a multi-slice cluster.

    The full `data` axis spans all slices, slice-major: data lane
    d = s * data_per_slice + i maps to slice s, in-slice data lane i
    (data_per_slice = slice size / product of inner axes). Inner axes are
    filled within a slice (they must divide the slice size), so
    model/seq/stage/expert collectives never touch DCN.

    Degenerates to exactly `make_mesh(...)` ordering on one slice, so
    callers can use it unconditionally.
    """
    if devices is None:
        devices = jax.devices()
    slices = group_by_slice(devices, n_slices=n_slices)
    per_slice = len(slices[0])
    inner = n_model * n_seq * n_stage * n_expert
    if per_slice % inner:
        raise ValueError(
            f"slice size {per_slice} not divisible by inner axes product "
            f"{inner} ({n_model}x{n_seq}x{n_stage}x{n_expert}) — inner "
            "axes must not cross a slice boundary")
    data_per_slice = per_slice // inner
    return make_mesh(n_data=len(slices) * data_per_slice, n_model=n_model,
                     n_seq=n_seq, n_stage=n_stage, n_expert=n_expert,
                     devices=[d for s in slices for d in s])


def is_coordinator() -> bool:
    """True on the process that should run the control plane (serve the
    REST API, write history/checkpoints). Mirrors the reference's single
    controller deployment (SURVEY.md §1 L5) in the multi-controller
    runtime: exactly one process, the others only execute collectives."""
    return jax.process_index() == 0
