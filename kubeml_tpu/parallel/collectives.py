"""Hand-rolled collectives for cases XLA's builtins cannot serve.

`ring_psum` exists for one reason: the SPMD partitioner miscompiles a
sub-f32 `lax.psum` inside a PARTIALLY-manual shard_map (data manual,
model/... Auto) — a fatal "Invalid binary instruction opcode copy"
check failure while partitioning the AllReduce (reproduced minimally on
jax 0.9 / CPU and TPU; `lax.psum_scatter` in bf16 dies the same way).
That crash is why round 1's bf16 merge compression was boxed into
pure-DP meshes. `lax.ppermute` (CollectivePermute) takes a different
partitioner path and is unaffected, so a classic ring all-reduce built
on it delivers the compressed wire on exactly the meshes the builtin
cannot:

  reduce-scatter phase:  D-1 ppermute steps, each moving one 1/D chunk
                         in `wire_dtype`, accumulating in f32;
  all-gather phase:      D-1 ppermute steps circulating the reduced
                         chunks, still in `wire_dtype`.

Wire bytes per device ≈ 2·M·sizeof(wire) — for bf16, HALF of the f32
psum's ≈ 2·M·4, the same 2x saving the direct bf16 psum gives on
pure-DP meshes. Error model matches the direct path: one downcast per
hop plus f32 accumulation, so worst case grows ~D·2^-8 relative —
acceptable for weight averaging, never used for integer leaves
(parallel/kavg.py skips them).

On TPU the D-1 neighbor steps ride adjacent-chip ICI links. The
latency cost vs one fused AllReduce is real but secondary: compression
exists for the bandwidth-bound regime (large models, DCN phases), where
wire bytes dominate.
"""

from __future__ import annotations

import jax
from kubeml_tpu import compat
import jax.numpy as jnp
from jax import lax


def ring_psum(x: jax.Array, axis_name: str, wire_dtype) -> jax.Array:
    """All-reduce sum over `axis_name` with the wire in `wire_dtype`.

    Call inside shard_map (manual over `axis_name`). Equals
    `lax.psum(x, axis_name)` up to wire_dtype rounding; f32 wire is
    exact up to reduction order. Works on partially-manual meshes where
    a sub-f32 `lax.psum` crashes the partitioner (module docstring).
    """
    D = compat.axis_size(axis_name)
    if D == 1:
        return x
    if not compat.HAS_NATIVE_SHARD_MAP:
        # Legacy JAX (0.4.x): the partitioner bug this ring dodges does
        # not exist there — a direct sub-f32 psum partitions fine even
        # under partial-manual meshes — while the ring itself cannot
        # build: its lax.axis_index lowers to a PartitionId instruction
        # the legacy partial-manual partitioner rejects. Same wire
        # compression; accumulation rides the wire dtype instead of
        # f32-with-wire-hops (same ~D·2^-8 worst-case error model).
        return lax.psum(x.astype(wire_dtype), axis_name).astype(x.dtype)
    r = lax.axis_index(axis_name)
    shape, n = x.shape, x.size
    pad = (-n) % D
    chunks = jnp.pad(x.astype(jnp.float32).reshape(-1),
                     (0, pad)).reshape(D, -1)          # [D, C] f32
    perm = [(i, (i + 1) % D) for i in range(D)]

    # reduce-scatter: at step s every rank forwards the chunk it last
    # accumulated — (r - s) mod D — and folds the incoming chunk
    # (r - s - 1) mod D into its local copy. After D-1 steps rank r
    # holds the fully-reduced chunk (r + 1) mod D.
    def rs_step(s, chunks):
        send = lax.dynamic_index_in_dim(chunks, (r - s) % D, 0,
                                        keepdims=False)
        recv = lax.ppermute(send.astype(wire_dtype), axis_name, perm)
        i = (r - s - 1) % D
        mine = lax.dynamic_index_in_dim(chunks, i, 0, keepdims=False)
        return lax.dynamic_update_index_in_dim(
            chunks, mine + recv.astype(jnp.float32), i, 0)

    chunks = lax.fori_loop(0, D - 1, rs_step, chunks)

    # Lane identity: receivers will see every reduced chunk through one
    # wire_dtype round-trip, so the owner must hold the same rounded
    # value — otherwise the "replicated" output differs across lanes on
    # the 1/D of elements each rank owns.
    own = (r + 1) % D
    owned = lax.dynamic_index_in_dim(chunks, own, 0, keepdims=False)
    chunks = lax.dynamic_update_index_in_dim(
        chunks, owned.astype(wire_dtype).astype(jnp.float32), own, 0)

    # all-gather: circulate the reduced chunks; at step s rank r sends
    # chunk (r + 1 - s) mod D (its reduced chunk at s=0, thereafter the
    # one it just received) and stores incoming chunk (r - s) mod D.
    def ag_step(s, chunks):
        send = lax.dynamic_index_in_dim(chunks, (r + 1 - s) % D, 0,
                                        keepdims=False)
        recv = lax.ppermute(send.astype(wire_dtype), axis_name, perm)
        return lax.dynamic_update_index_in_dim(
            chunks, recv.astype(jnp.float32), (r - s) % D, 0)

    chunks = lax.fori_loop(0, D - 1, ag_step, chunks)
    return chunks.reshape(-1)[:n].reshape(shape).astype(x.dtype)
