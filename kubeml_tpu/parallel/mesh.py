"""Device-mesh construction.

The reference's "cluster" is a fleet of Fission function pods coordinated
over HTTP (SURVEY.md §2b). Here the cluster is a `jax.sharding.Mesh`:
the `data` axis carries the data-parallel lanes that replace function
replicas; `model`/`seq`/`stage`/`expert` axes carry tensor, sequence,
pipeline, and expert parallelism (all net-new relative to the reference,
which has none — SURVEY.md §2a).

Collectives ride ICI within a slice; multi-slice meshes extend over DCN via
jax.distributed (same code path — the mesh abstracts the transport).
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh

DATA_AXIS = "data"
MODEL_AXIS = "model"
SEQ_AXIS = "seq"
STAGE_AXIS = "stage"
EXPERT_AXIS = "expert"


def make_mesh(n_data: Optional[int] = None, n_model: int = 1,
              n_seq: int = 1, n_stage: int = 1, n_expert: int = 1,
              devices: Optional[Sequence] = None) -> Mesh:
    """Create a (data, model, seq, stage, expert) mesh.

    n_data defaults to `len(devices) // (n_model * n_seq * n_stage *
    n_expert)`. All five axes are always present (size 1 when unused) so
    the same PartitionSpecs work for pure-DP, DP x TP, DP x SP, PP, and
    EP programs without recompiling call sites. Axis order puts `data`
    outermost: on real slices, adjacent devices (fast ICI hops) land on
    the inner axes, which carry the latency-sensitive TP/ring/pipeline/
    all-to-all collectives.
    """
    if devices is None:
        devices = jax.devices()
    devices = list(devices)
    inner = n_model * n_seq * n_stage * n_expert
    if n_data is None:
        if len(devices) % inner:
            raise ValueError(
                f"{len(devices)} devices not divisible by "
                f"{n_model}x{n_seq}x{n_stage}x{n_expert}")
        n_data = len(devices) // inner
    need = n_data * inner
    if need > len(devices):
        raise ValueError(
            f"mesh {n_data}x{n_model}x{n_seq}x{n_stage}x{n_expert} needs "
            f"{need} devices, have {len(devices)}")
    arr = np.array(devices[:need]).reshape(
        n_data, n_model, n_seq, n_stage, n_expert)
    return Mesh(arr, (DATA_AXIS, MODEL_AXIS, SEQ_AXIS, STAGE_AXIS,
                      EXPERT_AXIS))


def data_axis_size(mesh: Mesh) -> int:
    return mesh.shape[DATA_AXIS]
