"""Device-mesh construction.

The reference's "cluster" is a fleet of Fission function pods coordinated
over HTTP (SURVEY.md §2b). Here the cluster is a `jax.sharding.Mesh`:
the `data` axis carries the data-parallel lanes that replace function
replicas, and an optional `model` axis carries tensor/sequence parallelism
(net-new relative to the reference, which has none — SURVEY.md §2a).

Collectives ride ICI within a slice; multi-slice meshes extend over DCN via
jax.distributed (same code path — the mesh abstracts the transport).
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh

DATA_AXIS = "data"
MODEL_AXIS = "model"


def make_mesh(n_data: Optional[int] = None, n_model: int = 1,
              devices: Optional[Sequence] = None) -> Mesh:
    """Create a (data, model) mesh.

    n_data defaults to `len(devices) // n_model`. A 1-sized model axis is
    always present so the same PartitionSpecs work for pure-DP and DP x TP
    programs without recompiling call sites.
    """
    if devices is None:
        devices = jax.devices()
    devices = list(devices)
    if n_data is None:
        if len(devices) % n_model:
            raise ValueError(
                f"{len(devices)} devices not divisible by model axis {n_model}")
        n_data = len(devices) // n_model
    need = n_data * n_model
    if need > len(devices):
        raise ValueError(f"mesh {n_data}x{n_model} needs {need} devices, "
                         f"have {len(devices)}")
    arr = np.array(devices[:need]).reshape(n_data, n_model)
    return Mesh(arr, (DATA_AXIS, MODEL_AXIS))


def data_axis_size(mesh: Mesh) -> int:
    return mesh.shape[DATA_AXIS]
