"""Pipeline parallelism — GPipe-style microbatch pipelining over the mesh
`stage` axis.

Net-new relative to the reference (SURVEY.md §2a: "Absent: ... pipeline
parallelism"). TPU-first design: instead of per-stage processes passing
activations over a network (the GPU-framework pattern), ONE SPMD program
runs on all stages under `shard_map`. Stage parameters are stacked on a
leading [P] dim and sharded over the `stage` axis; at every clock tick
each stage applies its block to its current activation and `ppermute`s
the result one hop along the ICI ring to its successor. M microbatches
drain in M + P - 1 ticks — the (P-1)-tick fill/drain bubble is the
standard GPipe cost, amortized by choosing M >> P.

The whole pipeline is differentiable end-to-end: `ppermute` and `scan`
have transposes, so `jax.grad` through `pipeline_apply` yields correct
stage-parameter gradients, with the reverse activation transfers riding
the same ICI ring in the opposite direction.

Restriction (by construction of the SPMD formulation): every stage maps
activations of one fixed shape to the same shape. Embed/head layers that
change shape run outside the pipelined trunk (see `models/`).

Stages may also emit a scalar auxiliary output (`has_aux=True` —
stage_fn returns `(activation, aux)`): aux values from REAL ticks are
summed across microbatches and stages (fill/drain ticks, whose inputs
are clipped garbage, are masked out). This is what lets MoE blocks ride
the pipeline — their sown load-balance losses accumulate exactly as in
the sequential reference.
"""

from __future__ import annotations

from typing import Any, Callable, Sequence

import jax
from kubeml_tpu import compat
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from kubeml_tpu.parallel.mesh import STAGE_AXIS

PyTree = Any
# stage_fn(stage_params, activation [B, ...]) -> activation [B, ...]
#   (or -> (activation, aux_scalar) with has_aux=True)
StageFn = Callable[[PyTree, jax.Array], jax.Array]


def stack_stage_params(params_list: Sequence[PyTree]) -> PyTree:
    """Stack per-stage param pytrees on a new leading [P] dim.

    The stacked tree is what `pipeline_apply` shards over the stage axis.
    All stages must share one tree structure and leaf shapes (uniform
    blocks — the transformer/MLP-trunk case).
    """
    return jax.tree_util.tree_map(
        lambda *xs: jnp.stack(xs, axis=0), *params_list)


def pipeline_lane(stage_fn: StageFn, local_params: PyTree, xs: jax.Array,
                  axis_name: str = STAGE_AXIS, has_aux: bool = False,
                  consts: PyTree = None, vma: bool = False):
    """The per-stage GPipe body, callable INSIDE an existing manual
    region (an engine's all-axes-manual training round) as well as from
    `pipeline_apply`'s own shard_map below.

    local_params: THIS stage's parameters (already sliced — via
        shard_map in_specs, or `manual.axis_slice` over the stacked
        layer dim when the caller keeps params replicated).
    xs: [M, B, ...] microbatches, replicated across stages.
    consts: optional pytree of per-microbatch constants with leading
        [M] (pad masks, rng key data); stage s at tick t receives the
        slice for the microbatch it is chewing (t - s, clipped) and
        stage_fn is called as stage_fn(params, act, const).
    vma: True inside check_vma=True rounds — the stage-invariant inputs
        are pcast to varying so the tick scan's carry types line up;
        the final psums return stage-INVARIANT outputs either way,
        which is exactly what the vma-checked round requires of a loss.

    Returns (outputs [M, B, ...], aux_sum) — both replicated over the
    stage axis; aux_sum is 0.0 unless has_aux.
    """
    n_stage = compat.axis_size(axis_name)
    sid = lax.axis_index(axis_name)
    m = xs.shape[0]
    if vma:
        xs = compat.pcast(xs, axis_name, to="varying")
        if consts is not None:
            consts = jax.tree_util.tree_map(
                lambda c: compat.pcast(c, axis_name, to="varying"), consts)
    perm = [(i, (i + 1) % n_stage) for i in range(n_stage)]
    # scalar zero derived from xs so its vma matches the varying aux
    # accumulated into it (a literal 0.0 would be invariant and fail
    # the scan's carry-type check under check_vma=True)
    zero = (xs.ravel()[0].astype(jnp.float32) * 0.0)

    def tick(carry, t):
        act, aux_sum = carry
        # Stage 0 injects microbatch t (clipped during drain ticks —
        # those outputs never reach the collected window); others
        # consume the activation ppermuted in on the previous tick.
        inp = jnp.where(sid == 0,
                        lax.dynamic_index_in_dim(
                            xs, jnp.clip(t, 0, m - 1), keepdims=False),
                        act)
        mb = jnp.clip(t - sid, 0, m - 1)  # microbatch this stage chews
        if consts is not None:
            const = jax.tree_util.tree_map(
                lambda c: lax.dynamic_index_in_dim(c, mb, keepdims=False),
                consts)
            out = stage_fn(local_params, inp, const)
        else:
            out = stage_fn(local_params, inp)
        if has_aux:
            out, aux = out
            # stage s processes microbatch (t - s): real iff it is
            # in [0, m) — fill/drain ticks chew clipped garbage whose
            # aux must not pollute the sum
            real = ((t >= sid) & (t - sid < m)).astype(jnp.float32)
            aux_sum = aux_sum + aux.astype(jnp.float32) * real
        nxt = lax.ppermute(out, axis_name, perm)
        return (nxt, aux_sum), out

    (_, aux_sum), outs = lax.scan(
        tick, (jnp.zeros_like(xs[0]), zero),
        jnp.arange(m + n_stage - 1))
    # Microbatch j finishes on the last stage at tick j + P - 1.
    ys = outs[n_stage - 1:]
    # Zero everywhere but the last stage, then psum-broadcast so the
    # result is replicated across stages.
    ys = jnp.where(sid == n_stage - 1, ys, jnp.zeros_like(ys))
    return lax.psum(ys, axis_name), lax.psum(aux_sum, axis_name)


def pipeline_apply(stage_fn: StageFn, stage_params: PyTree, x: jax.Array,
                   mesh: Mesh, has_aux: bool = False):
    """Run x through P pipeline stages with microbatch pipelining.

    stage_params: pytree with leading dim [P] on every leaf (see
        `stack_stage_params`), laid out over the mesh `stage` axis.
    x: [M, B, ...] — M microbatches. More microbatches = smaller bubble
        fraction (bubble = (P-1)/(M+P-1) of ticks).
    has_aux: stage_fn returns (activation, aux_scalar); the call then
        returns (outputs, aux_sum) with aux summed over every REAL
        (stage, microbatch) pair — fill/drain ticks masked out.
    Returns [M, B, ...] outputs, replicated over the stage axis
    (plus the aux scalar when has_aux).
    """
    n_stage = mesh.shape[STAGE_AXIS]
    for leaf in jax.tree_util.tree_leaves(stage_params):
        if leaf.shape[0] != n_stage:
            raise ValueError(
                f"stage_params stack {leaf.shape[0]} stages but the mesh "
                f"stage axis is {n_stage}; they must match")

    def lane(params, xs):
        # params leaves arrive sliced to [1, ...] for this stage.
        params = jax.tree_util.tree_map(lambda p: p[0], params)
        return pipeline_lane(stage_fn, params, xs, STAGE_AXIS,
                             has_aux=has_aux)

    sharded = compat.shard_map(
        lane, mesh=mesh,
        in_specs=(P(STAGE_AXIS), P()),
        out_specs=(P(), P()),
        check_vma=False)
    ys, aux = sharded(stage_params, x)
    return (ys, aux) if has_aux else ys


def sequential_apply(stage_fn: StageFn, stage_params: PyTree,
                     x: jax.Array, has_aux: bool = False):
    """Reference semantics: the same chain with no pipelining.

    stage_params leaves [P, ...]; x [M, B, ...]. Used by tests and as the
    single-device fallback when the mesh has no stage axis.
    """
    n_stage = jax.tree_util.tree_leaves(stage_params)[0].shape[0]

    def one(mb):
        act, aux_sum = mb, jnp.float32(0.0)
        for s in range(n_stage):
            p = jax.tree_util.tree_map(lambda q: q[s], stage_params)
            act = stage_fn(p, act)
            if has_aux:
                act, aux = act
                aux_sum = aux_sum + aux.astype(jnp.float32)
        return act, aux_sum

    ys, auxes = jax.vmap(one)(x)
    return (ys, auxes.sum()) if has_aux else ys
