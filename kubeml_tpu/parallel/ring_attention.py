"""Ring attention — sequence/context parallelism over the mesh `seq` axis.

Net-new capability relative to the reference, which has no long-context
support of any kind (SURVEY.md §5 "long-context / sequence parallelism:
absent entirely"); required of this framework as a first-class subsystem.

Design (blockwise ring attention, Liu et al.-style, built from JAX
primitives — NOT a port of any reference code):

  - the sequence dimension is sharded over the mesh `seq` axis: each
    device holds a Q block and a KV block of T/n tokens;
  - devices rotate KV blocks around the ring with `lax.ppermute` (on TPU
    this lowers to neighbor ICI transfers) while accumulating their Q
    block's attention with a numerically-stable online softmax
    (running max m, denominator l, numerator acc — the flash-attention
    recurrence), so no device ever materializes the [T, T] score matrix;
  - padding and causality are expressed through rotating per-token
    metadata (kv position ids + kv keep-mask), so the result is exactly
    equal to full attention with the equivalent additive bias.

The inner block computation is `_block_attn`, deliberately isolated so the
pallas flash kernel (ops/pallas) can replace it without touching the ring.
"""

from __future__ import annotations

import functools

import jax
from kubeml_tpu import compat
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from kubeml_tpu.ops.attention import NEG_INF
from kubeml_tpu.parallel.mesh import SEQ_AXIS

__all__ = ["ring_attention", "ring_self_attention", "RingLayoutError"]


class RingLayoutError(ValueError):
    """A causal flash ring call's positions violate the contiguous shard
    layout (shard s must hold global positions [s*T/n, (s+1)*T/n)).

    Raised at the HOST by entry points whose positions are known before
    trace time (`ring_self_attention`); the raw shard_map-body
    `ring_attention` cannot see positions until runtime and falls back
    to NaN-poisoning its output instead (see its docstring)."""


def _block_attn(q, k, v, bias):
    """One Q-block x KV-block step of the online-softmax recurrence.

    q [B, Tq, H, D]; k/v [B, Tk, H, D]; bias [B, H, Tq, Tk] additive.
    Returns (numerator [B, Tq, H, D] f32, row max [B, H, Tq] f32,
    row denom [B, H, Tq] f32) for this block only.
    """
    d = q.shape[-1]
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                   preferred_element_type=jnp.float32)
    s = s * (1.0 / jnp.sqrt(jnp.float32(d))) + bias
    m = s.max(axis=-1)                          # [B, H, Tq]
    p = jnp.exp(s - m[..., None])               # [B, H, Tq, Tk]
    l = p.sum(axis=-1)                          # [B, H, Tq]
    acc = jnp.einsum("bhqk,bkhd->bqhd", p.astype(v.dtype), v)
    return acc.astype(jnp.float32), m, l


def _block_attn_flash(q, k, v, kv_mask, causal, interpret):
    """The same per-block partials, computed by the pallas flash kernel
    (ops/pallas) — O(block) VMEM and MXU-saturating tiles instead of the
    materialized [Tq, Tk] score tensor. The kernel's saved row stats
    reconstruct the un-normalized numerator: num = out * l.

    kv_mask [B, Tk] (1 = attendable); causal applies the ALIGNED
    diagonal mask (used for the local block only — ring off-diagonal
    blocks express causality through kv_mask instead).
    """
    from kubeml_tpu.ops.pallas.flash_attention import (DEFAULT_BLOCK_K,
                                                       DEFAULT_BLOCK_Q,
                                                       _fa_forward)

    B, T, H, D = q.shape
    out, m_rows, l_rows = _fa_forward(
        q, k, v, kv_mask.astype(jnp.float32), causal,
        DEFAULT_BLOCK_Q, DEFAULT_BLOCK_K, interpret)
    m = m_rows.reshape(B, H, T)
    l = l_rows.reshape(B, H, T)
    num = out.astype(jnp.float32) * l.transpose(0, 2, 1)[..., None]
    return num, m, l


def _merge_partials(acc, m, l, a_blk, m_blk, l_blk):
    """Fold one block's (num, max, denom) into the running online-softmax
    state — THE merge rule shared by the dense and flash block paths."""
    new_m = jnp.maximum(m, m_blk)
    old_scale = jnp.exp(m - new_m)              # [B, H, Tq]
    blk_scale = jnp.exp(m_blk - new_m)
    l = l * old_scale + l_blk * blk_scale
    # scales are [B, H, Tq]; acc is [B, Tq, H, D]
    acc = acc * old_scale.transpose(0, 2, 1)[..., None] + \
        a_blk * blk_scale.transpose(0, 2, 1)[..., None]
    return acc, new_m, l


# --------------------------------------------- differentiable flash ring
#
# The flash ring is a jax.custom_vjp: per-block pallas partials merged
# across ring steps in the forward, and a BACKWARD ring that reuses the
# flash backward kernels per block. The key identity making this exact:
# the forward saves the GLOBAL per-row softmax stats (max m, normalizer
# l, merged over all ring steps), and the global probability of any
# (q row i, kv block j) entry is p_ij = exp(s_ij - m_i) / l_i — so the
# per-block backward kernels, fed global stats instead of block-local
# ones, produce exactly the global dQ/dK/dV contributions of that block,
# and contributions just sum. dK/dV accumulators travel WITH their kv
# block around the ring (picking up each device's contribution) and one
# final ppermute returns them home; dQ accumulates locally.


def _causal_step_mask(maskb, causal, sid, s, n):
    """Visibility of the visiting kv block at ring step s — THE rule the
    forward and backward rings must share (a divergence makes gradients
    silently stop matching the forward). After s rotations this device
    holds shard (sid - s)'s block: under the contiguous layout it is
    fully visible iff it sits strictly before this device's shard (the
    diagonal was step 0); a dropped block's all-masked partials carry
    m = NEG_INF and merge (or backprop) with weight zero."""
    if not causal:
        return maskb
    j = (sid - s) % n
    return maskb * (j < sid).astype(maskb.dtype)


def _ring_flash_core(q, k, v, kv_mask, causal, axis_name, interpret):
    """Flash forward ring: returns (normalized out f32, m, l) with m/l
    the GLOBAL row stats [B, H, Tq] the backward needs."""
    n = compat.axis_size(axis_name)
    sid = lax.axis_index(axis_name)
    perm = [(i, (i + 1) % n) for i in range(n)]
    acc0, m0, l0 = _block_attn_flash(q, k, v, kv_mask, causal, interpret)

    def step(carry, s):
        acc, m, l, kb, vb, maskb = carry
        kb, vb, maskb = [lax.ppermute(t, axis_name, perm)
                         for t in (kb, vb, maskb)]
        eff_mask = _causal_step_mask(maskb, causal, sid, s, n)
        a_blk, m_blk, l_blk = _block_attn_flash(q, kb, vb, eff_mask,
                                                False, interpret)
        acc, m, l = _merge_partials(acc, m, l, a_blk, m_blk, l_blk)
        return (acc, m, l, kb, vb, maskb), None

    (acc, m, l, *_), _ = lax.scan(step, (acc0, m0, l0, k, v, kv_mask),
                                  jnp.arange(1, n))
    out = acc / jnp.maximum(l, 1e-30).transpose(0, 2, 1)[..., None]
    return out, m, l


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6))
def _ring_flash(q, k, v, kv_mask, causal, axis_name, interpret):
    out, _, _ = _ring_flash_core(q, k, v, kv_mask, causal, axis_name,
                                 interpret)
    return out.astype(q.dtype)


def _ring_flash_fwd(q, k, v, kv_mask, causal, axis_name, interpret):
    out, m, l = _ring_flash_core(q, k, v, kv_mask, causal, axis_name,
                                 interpret)
    out = out.astype(q.dtype)
    return out, (q, k, v, kv_mask, out, m, l)


def _ring_flash_bwd(causal, axis_name, interpret, res, g):
    from kubeml_tpu.ops.pallas.flash_attention import (DEFAULT_BLOCK_K,
                                                       DEFAULT_BLOCK_Q,
                                                       _fa_backward)

    q, k, v, kv_mask, out, m, l = res
    n = compat.axis_size(axis_name)
    sid = lax.axis_index(axis_name)
    perm = [(i, (i + 1) % n) for i in range(n)]
    B, T, H, D = q.shape
    # the kernels' [BH, 1, T] row-stat layout, from the merged stats
    m_rows = m.reshape(B * H, 1, T)
    l_rows = l.reshape(B * H, 1, T)

    def block_bwd(kb, vb, maskb, blk_causal):
        # global-stats flash backward for ONE (local q, visiting kv)
        # pair: delta is recomputed per call from (g, out) — cheap
        # elementwise next to the kernels' matmuls
        return _fa_backward(q, kb, vb, maskb, out, m_rows, l_rows, g,
                            blk_causal, DEFAULT_BLOCK_Q, DEFAULT_BLOCK_K,
                            interpret)

    # diagonal (local) block first, mirroring the forward's step 0
    dq0, dk0, dv0 = block_bwd(k, v, kv_mask, causal)
    f32 = jnp.float32

    def step(carry, s):
        dq, kb, vb, maskb, dkb, dvb = carry
        # dk/dv accumulators travel WITH their kv block
        kb, vb, maskb, dkb, dvb = [
            lax.ppermute(t, axis_name, perm)
            for t in (kb, vb, maskb, dkb, dvb)]
        eff_mask = _causal_step_mask(maskb, causal, sid, s, n)
        dq_c, dk_c, dv_c = block_bwd(kb, vb, eff_mask, False)
        return (dq + dq_c.astype(f32), kb, vb, maskb,
                dkb + dk_c.astype(f32), dvb + dv_c.astype(f32)), None

    carry = (dq0.astype(f32), k, v, kv_mask,
             dk0.astype(f32), dv0.astype(f32))
    (dq, _, _, _, dkb, dvb), _ = lax.scan(step, carry, jnp.arange(1, n))
    # after n-1 rotations each kv block's accumulator sits one hop short
    # of home: a final ppermute returns it to its owner
    dkb = lax.ppermute(dkb, axis_name, perm)
    dvb = lax.ppermute(dvb, axis_name, perm)
    return (dq.astype(q.dtype), dkb.astype(k.dtype),
            dvb.astype(v.dtype), jnp.zeros_like(kv_mask))


_ring_flash.defvjp(_ring_flash_fwd, _ring_flash_bwd)


def ring_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                   q_pos: jax.Array, kv_pos: jax.Array,
                   kv_mask: jax.Array, causal: bool = False,
                   axis_name: str = SEQ_AXIS,
                   use_flash: bool = False,
                   interpret: bool = False) -> jax.Array:
    """Sequence-parallel attention body (call inside shard_map/jit).

    Per-device shapes: q/k/v [B, T_local, H, D]; q_pos/kv_pos [T_local]
    global token positions; kv_mask [B, T_local] 1 = real token. Returns
    the attention output for the local Q block, [B, T_local, H, D], equal
    to full attention over the global sequence.

    use_flash swaps the per-block computation for the pallas flash
    kernel and is fully DIFFERENTIABLE (since round 4): the forward
    merges per-block kernel partials across ring steps, and a custom
    backward ring feeds the merged global row stats to the flash
    backward kernels per block (see _ring_flash), so long-context
    TRAINING gets the kernel too. The flash path assumes the STANDARD
    contiguous shard layout (shard s holds global positions
    [s*T_local, (s+1)*T_local) — what ring_self_attention and the model
    modules construct): causality then reduces to an aligned-diagonal
    mask on the local block plus a whole-block keep/drop per ring step,
    so arbitrary q_pos/kv_pos are not consulted. A causal flash call
    whose positions VIOLATE that layout poisons its output with NaN
    rather than silently computing wrong attention (non-causal flash is
    layout-independent: softmax is permutation-invariant over the
    masked key set). interpret runs the kernel in the pallas
    interpreter (CPU tests).
    """
    n = compat.axis_size(axis_name)
    perm = [(i, (i + 1) % n) for i in range(n)]

    if use_flash:
        if causal:
            # the causal keep/drop inside the flash ring assumes the
            # contiguous layout; a violating caller must get a LOUD
            # failure (NaN), not silently wrong attention
            sid = lax.axis_index(axis_name)
            expected = sid * q.shape[1] + jnp.arange(q.shape[1])
            layout_ok = jnp.logical_and((q_pos == expected).all(),
                                        (kv_pos == expected).all())
        else:
            layout_ok = jnp.bool_(True)
        out = _ring_flash(q, k, v, kv_mask.astype(jnp.float32), causal,
                          axis_name, interpret)
        return jnp.where(layout_ok, out, jnp.nan).astype(q.dtype)

    def bias_for(kv_pos_blk, kv_mask_blk):
        bias = (1.0 - kv_mask_blk.astype(jnp.float32)) * NEG_INF
        bias = bias[:, None, None, :]           # [B, 1, 1, Tk]
        if causal:
            allowed = q_pos[:, None] >= kv_pos_blk[None, :]  # [Tq, Tk]
            bias = bias + jnp.where(allowed, 0.0, NEG_INF)[None, None]
        return bias

    # local KV block first, then n-1 rotate-and-accumulate steps — no
    # wasted final ppermute (each rotation's result is always consumed)
    acc0, m0, l0 = _block_attn(q, k, v, bias_for(kv_pos, kv_mask))

    def step(carry, s):
        acc, m, l, kb, vb, posb, maskb = carry
        kb, vb, posb, maskb = [
            lax.ppermute(t, axis_name, perm) for t in (kb, vb, posb, maskb)]
        a_blk, m_blk, l_blk = _block_attn(q, kb, vb,
                                          bias_for(posb, maskb))
        acc, m, l = _merge_partials(acc, m, l, a_blk, m_blk, l_blk)
        return (acc, m, l, kb, vb, posb, maskb), None

    (acc, m, l, *_), _ = lax.scan(
        step, (acc0, m0, l0, k, v, kv_pos, kv_mask), jnp.arange(1, n))
    # rows with zero real keys (all-pad) have l ~ n*exp(0)=0? No: fully
    # masked rows keep m = NEG_INF and l from exp(0)=1 terms per block, so
    # the division is finite; still guard for safety.
    out = acc / jnp.maximum(l, 1e-30).transpose(0, 2, 1)[..., None]
    return out.astype(q.dtype)


def ring_self_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                        pad_mask: jax.Array, mesh: Mesh,
                        causal: bool = False,
                        use_flash: bool = False,
                        interpret: bool = False,
                        positions=None) -> jax.Array:
    """Host-callable wrapper: shards [B, T, H, D] tensors over the mesh
    `seq` axis and runs ring_attention. T must divide by the seq-axis size.
    use_flash routes each ring block through the pallas flash kernel,
    forward AND backward (see ring_attention / _ring_flash).

    positions: optional [T] global position ids (default arange(T)).
    Causal flash requires the contiguous shard layout (shard s holds
    positions [s*T/n, (s+1)*T/n)); because positions are HOST-known
    here, a violating layout raises `RingLayoutError` at call time —
    the loud-but-late NaN poisoning remains only for the raw shard_map
    body `ring_attention`, whose positions are runtime values.
    """
    import numpy as np

    n = mesh.shape[SEQ_AXIS]
    B, T, H, D = q.shape
    if T % n:
        raise ValueError(f"sequence length {T} not divisible by seq={n}")
    if positions is None:
        positions = jnp.arange(T, dtype=jnp.int32)
    else:
        host_pos = np.asarray(positions)
        if host_pos.shape != (T,):
            raise RingLayoutError(
                f"positions must be [{T}] global ids, got "
                f"{host_pos.shape}")
        if causal and use_flash and not np.array_equal(
                host_pos, np.arange(T)):
            raise RingLayoutError(
                "causal flash ring attention requires the contiguous "
                "shard layout: positions must be arange(T) so shard s "
                f"holds [s*{T // n}, (s+1)*{T // n}); got a "
                "non-contiguous layout. Use the dense (use_flash="
                "False) ring for custom position layouts, or call the "
                "raw ring_attention body (which NaN-poisons on "
                "violation) if you know what you are doing")
        positions = jnp.asarray(host_pos, jnp.int32)

    def body(q, k, v, q_pos, kv_pos, kv_mask):
        return ring_attention(q, k, v, q_pos[0], kv_pos[0], kv_mask,
                              causal=causal, use_flash=use_flash,
                              interpret=interpret)

    seq_spec = P(None, SEQ_AXIS, None, None)
    sharded = compat.shard_map(
        body, mesh=mesh,
        in_specs=(seq_spec, seq_spec, seq_spec,
                  P(None, SEQ_AXIS), P(None, SEQ_AXIS), P(None, SEQ_AXIS)),
        out_specs=seq_spec, check_vma=False)
    # positions get a leading broadcast dim so shard_map can slice dim 1
    pos2d = positions[None, :]
    return sharded(q, k, v, pos2d, pos2d, pad_mask)
