"""Ring attention — sequence/context parallelism over the mesh `seq` axis.

Net-new capability relative to the reference, which has no long-context
support of any kind (SURVEY.md §5 "long-context / sequence parallelism:
absent entirely"); required of this framework as a first-class subsystem.

Design (blockwise ring attention, Liu et al.-style, built from JAX
primitives — NOT a port of any reference code):

  - the sequence dimension is sharded over the mesh `seq` axis: each
    device holds a Q block and a KV block of T/n tokens;
  - devices rotate KV blocks around the ring with `lax.ppermute` (on TPU
    this lowers to neighbor ICI transfers) while accumulating their Q
    block's attention with a numerically-stable online softmax
    (running max m, denominator l, numerator acc — the flash-attention
    recurrence), so no device ever materializes the [T, T] score matrix;
  - padding and causality are expressed through rotating per-token
    metadata (kv position ids + kv keep-mask), so the result is exactly
    equal to full attention with the equivalent additive bias.

The inner block computation is `_block_attn`, deliberately isolated so the
pallas flash kernel (ops/pallas) can replace it without touching the ring.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from kubeml_tpu.ops.attention import NEG_INF
from kubeml_tpu.parallel.mesh import SEQ_AXIS

__all__ = ["ring_attention", "ring_self_attention"]


def _block_attn(q, k, v, bias):
    """One Q-block x KV-block step of the online-softmax recurrence.

    q [B, Tq, H, D]; k/v [B, Tk, H, D]; bias [B, H, Tq, Tk] additive.
    Returns (numerator [B, Tq, H, D] f32, row max [B, H, Tq] f32,
    row denom [B, H, Tq] f32) for this block only.
    """
    d = q.shape[-1]
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                   preferred_element_type=jnp.float32)
    s = s * (1.0 / jnp.sqrt(jnp.float32(d))) + bias
    m = s.max(axis=-1)                          # [B, H, Tq]
    p = jnp.exp(s - m[..., None])               # [B, H, Tq, Tk]
    l = p.sum(axis=-1)                          # [B, H, Tq]
    acc = jnp.einsum("bhqk,bkhd->bqhd", p.astype(v.dtype), v)
    return acc.astype(jnp.float32), m, l


def ring_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                   q_pos: jax.Array, kv_pos: jax.Array,
                   kv_mask: jax.Array, causal: bool = False,
                   axis_name: str = SEQ_AXIS) -> jax.Array:
    """Sequence-parallel attention body (call inside shard_map/jit).

    Per-device shapes: q/k/v [B, T_local, H, D]; q_pos/kv_pos [T_local]
    global token positions; kv_mask [B, T_local] 1 = real token. Returns
    the attention output for the local Q block, [B, T_local, H, D], equal
    to full attention over the global sequence.
    """
    n = lax.axis_size(axis_name)
    perm = [(i, (i + 1) % n) for i in range(n)]

    def bias_for(kv_pos_blk, kv_mask_blk):
        bias = (1.0 - kv_mask_blk.astype(jnp.float32)) * NEG_INF
        bias = bias[:, None, None, :]           # [B, 1, 1, Tk]
        if causal:
            allowed = q_pos[:, None] >= kv_pos_blk[None, :]  # [Tq, Tk]
            bias = bias + jnp.where(allowed, 0.0, NEG_INF)[None, None]
        return bias

    # local KV block first, then n-1 rotate-and-accumulate steps — no
    # wasted final ppermute (each rotation's result is always consumed)
    acc0, m0, l0 = _block_attn(q, k, v, bias_for(kv_pos, kv_mask))

    def step(carry, _):
        acc, m, l, kb, vb, posb, maskb = carry
        kb, vb, posb, maskb = [
            lax.ppermute(t, axis_name, perm) for t in (kb, vb, posb, maskb)]
        a_blk, m_blk, l_blk = _block_attn(q, kb, vb, bias_for(posb, maskb))
        new_m = jnp.maximum(m, m_blk)
        old_scale = jnp.exp(m - new_m)          # [B, H, Tq]
        blk_scale = jnp.exp(m_blk - new_m)
        l = l * old_scale + l_blk * blk_scale
        # scales are [B, H, Tq]; acc is [B, Tq, H, D]
        acc = acc * old_scale.transpose(0, 2, 1)[..., None] + \
            a_blk * blk_scale.transpose(0, 2, 1)[..., None]
        return (acc, new_m, l, kb, vb, posb, maskb), None

    (acc, m, l, *_), _ = lax.scan(
        step, (acc0, m0, l0, k, v, kv_pos, kv_mask), None, length=n - 1)
    # rows with zero real keys (all-pad) have l ~ n*exp(0)=0? No: fully
    # masked rows keep m = NEG_INF and l from exp(0)=1 terms per block, so
    # the division is finite; still guard for safety.
    out = acc / jnp.maximum(l, 1e-30).transpose(0, 2, 1)[..., None]
    return out.astype(q.dtype)


def ring_self_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                        pad_mask: jax.Array, mesh: Mesh,
                        causal: bool = False) -> jax.Array:
    """Host-callable wrapper: shards [B, T, H, D] tensors over the mesh
    `seq` axis and runs ring_attention. T must divide by the seq-axis size.
    """
    n = mesh.shape[SEQ_AXIS]
    B, T, H, D = q.shape
    if T % n:
        raise ValueError(f"sequence length {T} not divisible by seq={n}")
    positions = jnp.arange(T, dtype=jnp.int32)

    def body(q, k, v, q_pos, kv_pos, kv_mask):
        return ring_attention(q, k, v, q_pos[0], kv_pos[0], kv_mask,
                              causal=causal)

    seq_spec = P(None, SEQ_AXIS, None, None)
    sharded = jax.shard_map(
        body, mesh=mesh,
        in_specs=(seq_spec, seq_spec, seq_spec,
                  P(None, SEQ_AXIS), P(None, SEQ_AXIS), P(None, SEQ_AXIS)),
        out_specs=seq_spec, check_vma=False)
    # positions get a leading broadcast dim so shard_map can slice dim 1
    pos2d = positions[None, :]
    return sharded(q, k, v, pos2d, pos2d, pad_mask)
