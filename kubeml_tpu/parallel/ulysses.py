"""Ulysses-style all-to-all sequence/context parallelism.

Net-new capability relative to the reference, which has no long-context
support of any kind (SURVEY.md §5 "long-context / sequence parallelism:
absent entirely"). This is the second of the framework's two
sequence-parallel strategies, complementing the ppermute ring
(parallel/ring_attention.py):

  - **ring**: KV blocks rotate around the seq axis; per-device memory is
    O(T_local^2) scores and communication is n-1 neighbor hops of the
    local KV block. Best when T is huge and heads are few.
  - **ulysses** (this module): two `lax.all_to_all` collectives re-shard
    the activations from sequence-sharded [B, T/n, H, D] to head-sharded
    [B, T, H/n, D], each device runs ordinary full attention over the
    GLOBAL sequence for its head group, and a second all-to-all restores
    sequence sharding. Communication is 2 all-to-alls of the activation
    tensor (O(B·T·H·D/n) per device, bandwidth-optimal on a TPU torus),
    and the local attention is the stock `masked_attention` — so the
    pallas flash kernel applies unchanged. Requires H % n == 0.

Both strategies are exact: outputs equal full attention over the global
sequence with the equivalent additive bias (ops.attention.composed_bias
is the shared semantics definition).

Design from JAX primitives (`lax.all_to_all`, `lax.all_gather`) — the
reference has nothing to port here; the decomposition follows the
published DeepSpeed-Ulysses scheme (PAPERS.md) re-expressed for
shard_map over a named mesh axis.
"""

from __future__ import annotations

import jax
from kubeml_tpu import compat
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from kubeml_tpu.ops.attention import masked_attention
from kubeml_tpu.parallel.mesh import SEQ_AXIS

__all__ = ["ulysses_attention", "ulysses_self_attention"]


def ulysses_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                      kv_mask: jax.Array, causal: bool = False,
                      axis_name: str = SEQ_AXIS,
                      impl: str = "auto",
                      interpret: bool = False) -> jax.Array:
    """Sequence-parallel attention body (call inside shard_map/jit).

    Per-device shapes: q/k/v [B, T_local, H, D] (the local block of a
    sequence sharded over `axis_name`); kv_mask [B, T_local] 1 = real
    token. H must be divisible by the axis size. Returns the attention
    output for the local sequence block, [B, T_local, H, D], equal to
    full attention over the global sequence.

    impl is forwarded to ops.masked_attention ('auto' picks the pallas
    flash kernel on TPU when the global T tiles cleanly).
    """
    n = compat.axis_size(axis_name)
    H = q.shape[2]
    if H % n:
        raise ValueError(
            f"ulysses needs heads % seq-axis == 0, got H={H}, n={n}")

    def seq_to_heads(x):
        # [B, T/n, H, D] -> [B, T, H/n, D]: device i keeps head group i,
        # gathers every device's sequence block along the T dim
        return lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1,
                              tiled=True)

    def heads_to_seq(x):
        return lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2,
                              tiled=True)

    qg, kg, vg = seq_to_heads(q), seq_to_heads(k), seq_to_heads(v)
    # the full-sequence keep-mask is tiny ([B, T]); gather it outright
    mask_g = lax.all_gather(kv_mask, axis_name, axis=1, tiled=True)
    out = masked_attention(qg, kg, vg, mask_g, causal=causal, impl=impl,
                           interpret=interpret)
    return heads_to_seq(out)


def ulysses_self_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                           pad_mask: jax.Array, mesh: Mesh,
                           causal: bool = False) -> jax.Array:
    """Host-callable wrapper: shards [B, T, H, D] tensors over the mesh
    `seq` axis and runs ulysses_attention. T and H must divide by the
    seq-axis size.
    """
    n = mesh.shape[SEQ_AXIS]
    B, T, H, D = q.shape
    if T % n:
        raise ValueError(f"sequence length {T} not divisible by seq={n}")
    if H % n:
        raise ValueError(f"head count {H} not divisible by seq={n}")

    def body(q, k, v, kv_mask):
        return ulysses_attention(q, k, v, kv_mask, causal=causal)

    seq_spec = P(None, SEQ_AXIS, None, None)
    sharded = compat.shard_map(
        body, mesh=mesh,
        in_specs=(seq_spec, seq_spec, seq_spec, P(None, SEQ_AXIS)),
        out_specs=seq_spec, check_vma=False)
    return sharded(q, k, v, pad_mask)
