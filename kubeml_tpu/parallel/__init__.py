from kubeml_tpu.parallel.mesh import make_mesh, data_axis_size
from kubeml_tpu.parallel.kavg import KAvgEngine, RoundStats

__all__ = ["make_mesh", "data_axis_size", "KAvgEngine", "RoundStats"]
