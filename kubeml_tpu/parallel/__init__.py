from kubeml_tpu.parallel.mesh import make_mesh, data_axis_size
from kubeml_tpu.parallel.kavg import KAvgEngine, RoundStats
from kubeml_tpu.parallel.pp import (pipeline_apply, sequential_apply,
                                    stack_stage_params)
from kubeml_tpu.parallel.ep import init_moe_params, moe_apply
from kubeml_tpu.parallel.distributed import (initialize, is_coordinator,
                                             make_multislice_mesh)
from kubeml_tpu.parallel.syncdp import SyncDPEngine

__all__ = ["make_mesh", "data_axis_size", "KAvgEngine", "RoundStats",
           "pipeline_apply", "sequential_apply", "stack_stage_params",
           "init_moe_params", "moe_apply", "initialize", "is_coordinator",
           "make_multislice_mesh", "SyncDPEngine"]
