"""Fully-manual tensor parallelism — Megatron collectives placed by hand.

Round 2 shipped TP as GSPMD placement (parallel/tp.py): parameters carry
NamedShardings over the mesh `model` axis and XLA's SPMD partitioner
inserts the collectives. That path works alone but cannot live inside the
engine's fully-manual rounds: sequence-parallel training runs shard_map
with ALL axes manual + check_vma=True (partial-manual meshes trip a fatal
partitioner miscompile — parallel/collectives.py), and a manual region
cannot host GSPMD-partitioned sub-programs. Hence round 2's exclusion
matrix: no TP+SP in one job.

This module clears it the way the reference clears nothing (TP is
net-new; SURVEY.md §2a): the Megatron column/row-parallel matmuls are
written out explicitly for execution INSIDE a manual shard_map over the
`model` axis, with `lax.psum` placed by hand at the row-parallel
boundaries.

Design (differs from classic Megatron deliberately):
  - Parameters stay FULL-SIZED and replicated across model lanes; each
    lane dynamic-slices its own shard (heads / FFN columns) at trace
    time via `lax.axis_index`. Tree paths and shapes are IDENTICAL to
    the dense modules ("q/kernel", "Dense_0/kernel", ...), so
    checkpoints, the K-avg weight merge, and the GSPMD rule table all
    apply unchanged — a TP job can resume a dense checkpoint and vice
    versa. The cost: TP shards FLOPs and activation memory, not
    parameter memory (parameter/optimizer sharding is syncdp's ZeRO-1
    job).
  - Gradient assembly is automatic through vma tracking: under
    `check_vma=True` the params are model-axis-INVARIANT while the
    sliced compute is varying; JAX's backward inserts the model-axis
    psums at those boundaries, so every lane receives the full summed
    gradient and applies an identical optimizer update — params remain
    replicated across model lanes with no explicit all-reduce code.
    (Correctness is pinned by tests/test_manual_tp.py against the dense
    forward/grads; with check_vma=False these grads would be silently
    wrong, same failure mode as seq-parallel training.)

Composability this buys (the round-3 matrix):
  - TP x SP in ONE job: attention runs on H/n_model local heads while
    the KV ring rotates over the `seq` axis — the two axes never touch.
  - TP x compressed merge: the engine's full-manual round may psum in
    bf16 directly (the miscompile is partial-manual-only).
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import flax.linen as nn
import jax
from kubeml_tpu import compat
import jax.numpy as jnp
import numpy as np
from jax import lax


def axis_slice(arr: jax.Array, axis_name: str, dim: int) -> jax.Array:
    """This lane's contiguous shard of `arr` along `dim` over the manual
    mesh axis `axis_name`. The dimension must divide evenly (callers
    validate with a readable error at module level)."""
    n = compat.axis_size(axis_name)
    size = arr.shape[dim] // n
    start = lax.axis_index(axis_name) * size
    return lax.dynamic_slice_in_dim(arr, start, size, axis=dim)


def _dense_general_init(kernel_init, n_in: int):
    """Replicates flax DenseGeneral's kernel init semantics: the variance
    scaling is computed on the (prod(in), prod(out)) flattened 2-D shape,
    then reshaped — so manual-TP modules initialize from the same
    distribution as the nn.DenseGeneral they mirror."""

    def init(rng, shape, dtype=jnp.float32):
        flat = (int(np.prod(shape[:n_in])), int(np.prod(shape[n_in:])))
        return kernel_init(rng, flat, dtype).reshape(shape)

    return init


class TPHeadsDense(nn.Module):
    """Column-parallel mirror of `nn.DenseGeneral((heads, head_dim))`.

    Params: kernel [hidden, heads, head_dim], bias [heads, head_dim] —
    same tree paths/shapes as the dense module. Each model lane computes
    only its heads // n_model local heads: [B, T, H, D] -> [B, T, H/n, D].
    """

    heads: int
    head_dim: int
    axis_name: str
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x):
        hidden = x.shape[-1]
        kernel = self.param(
            "kernel",
            _dense_general_init(nn.initializers.lecun_normal(), 1),
            (hidden, self.heads, self.head_dim), jnp.float32)
        bias = self.param("bias", nn.initializers.zeros,
                          (self.heads, self.head_dim), jnp.float32)
        kl = axis_slice(kernel, self.axis_name, 1).astype(self.dtype)
        bl = axis_slice(bias, self.axis_name, 0).astype(self.dtype)
        return jnp.einsum("...d,dhk->...hk", x.astype(self.dtype), kl) + bl


class TPOutDense(nn.Module):
    """Row-parallel mirror of `nn.DenseGeneral(hidden, axis=(-2, -1))` —
    the attention output projection. Consumes LOCAL heads [B, T, H/n, D],
    contracts against this lane's kernel rows, and psums the partial
    products over the model axis; the bias is added once, after the sum.

    Params: kernel [heads, head_dim, hidden], bias [hidden].
    """

    heads: int
    head_dim: int
    hidden: int
    axis_name: str
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, attn_local):
        kernel = self.param(
            "kernel",
            _dense_general_init(nn.initializers.lecun_normal(), 2),
            (self.heads, self.head_dim, self.hidden), jnp.float32)
        bias = self.param("bias", nn.initializers.zeros,
                          (self.hidden,), jnp.float32)
        kl = axis_slice(kernel, self.axis_name, 0).astype(self.dtype)
        # partials accumulate and psum in f32 (the dense matmul's own
        # accumulation precision), rounding to the compute dtype ONCE
        # after the sum — keeps manual-TP outputs within one bf16 ulp of
        # the dense path instead of one ulp per lane
        part = jnp.einsum("...hk,hkd->...d", attn_local.astype(self.dtype),
                          kl, preferred_element_type=jnp.float32)
        y = lax.psum(part, self.axis_name) + bias
        return y.astype(self.dtype)


class TPColumnDense(nn.Module):
    """Column-parallel mirror of `nn.Dense(features)`: output columns
    shard over the model axis, [..., in] -> [..., features/n] local.

    Params: kernel [in, features], bias [features].
    """

    features: int
    axis_name: str
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x):
        kernel = self.param("kernel", nn.initializers.lecun_normal(),
                            (x.shape[-1], self.features), jnp.float32)
        bias = self.param("bias", nn.initializers.zeros,
                          (self.features,), jnp.float32)
        kl = axis_slice(kernel, self.axis_name, 1).astype(self.dtype)
        bl = axis_slice(bias, self.axis_name, 0).astype(self.dtype)
        return x.astype(self.dtype) @ kl + bl


class TPRowDense(nn.Module):
    """Row-parallel mirror of `nn.Dense(features)`: consumes the LOCAL
    column block [..., in/n], contracts against this lane's kernel rows,
    psums partials over the model axis, bias added once after.

    Params: kernel [in, features], bias [features].
    """

    features: int
    in_features: int
    axis_name: str
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x_local):
        kernel = self.param("kernel", nn.initializers.lecun_normal(),
                            (self.in_features, self.features), jnp.float32)
        bias = self.param("bias", nn.initializers.zeros,
                          (self.features,), jnp.float32)
        kl = axis_slice(kernel, self.axis_name, 0).astype(self.dtype)
        # f32 partial accumulation + single rounding, as in TPOutDense
        part = jnp.einsum("...f,fd->...d", x_local.astype(self.dtype), kl,
                          preferred_element_type=jnp.float32)
        y = lax.psum(part, self.axis_name) + bias
        return y.astype(self.dtype)


def validate_tp_geometry(heads: int, ffn: int, n_model: int) -> None:
    """Readable trace-time rejection for indivisible TP factors."""
    if heads % n_model:
        raise ValueError(
            f"{heads} attention heads do not divide over a "
            f"{n_model}-way model axis")
    if ffn % n_model:
        raise ValueError(
            f"FFN width {ffn} does not divide over a "
            f"{n_model}-way model axis")


def ep_partial_ffn(params_wi, params_bi, params_wo, params_bo,
                   dispatch, combine, x, axis_name: str,
                   dtype=jnp.bfloat16) -> jax.Array:
    """Expert-sharded GShard FFN for a manual `expert` axis.

    All arguments are FULL-sized (router/dispatch computed identically on
    every lane from replicated tokens); each lane slices its E/n local
    experts, runs only their FFNs, combines only their slots, and the
    psum over the expert axis assembles the full output — expert FLOPs
    shard, tokens stay replicated (correct and bandwidth-fine at the
    per-stage activation sizes the pipelined MoE trunk carries; the
    token-sharded scale-up path is ep_alltoall_ffn below).

    dispatch/combine: [T, E, C] from parallel.ep.make_dispatch.
    x: [T, d_model]. Returns y [T, d_model] (model-axis invariant).
    """
    wi = axis_slice(params_wi, axis_name, 0).astype(dtype)
    bi = axis_slice(params_bi, axis_name, 0).astype(dtype)
    wo = axis_slice(params_wo, axis_name, 0).astype(dtype)
    bo = axis_slice(params_bo, axis_name, 0).astype(dtype)
    disp = axis_slice(dispatch, axis_name, 1).astype(dtype)
    comb = axis_slice(combine, axis_name, 1).astype(dtype)

    expert_in = jnp.einsum("tec,td->ecd", disp, x.astype(dtype))
    h = jax.nn.gelu(jnp.einsum("ecd,edf->ecf", expert_in, wi)
                    + bi[:, None, :])
    out = jnp.einsum("ecf,efd->ecd", h, wo) + bo[:, None, :]
    y_part = jnp.einsum("tec,ecd->td", comb, out)
    return lax.psum(y_part, axis_name)


def ep_alltoall_ffn(params_wi, params_bi, params_wo, params_bo,
                    dispatch, combine, x, axis_name: str,
                    dtype=jnp.bfloat16) -> jax.Array:
    """Token-SHARDED expert-parallel GShard FFN — the scale-up path
    ep_partial_ffn documents (and production MoE's standard form).

    Each lane holds a [T_local, d] token shard routed LOCALLY
    (dispatch/combine [T_local, E, C_local] over the FULL expert set
    with per-shard capacity) and its E/n local experts' weights. Two
    tiled `lax.all_to_all` exchanges move token slot payloads to their
    experts' lanes and back, so tokens, router math, and expert FLOPs
    ALL shard n-fold — no replicated-token psum, and the wire cost is
    2 x [E, C_local, d] slot traffic instead of a full [T, d]
    all-reduce. Per-shard routing equals global routing whenever no
    expert overflows (the same grouping semantics as sequence-parallel
    MoE, models/gpt.py — under overflow the drop PATTERN differs, not
    correctness).

    Returns y_local [T_local, d]: the lane's own tokens, fully
    combined (each token's slots all returned home — no psum needed).
    """
    wi = axis_slice(params_wi, axis_name, 0).astype(dtype)   # [E/n, d, f]
    bi = axis_slice(params_bi, axis_name, 0).astype(dtype)
    wo = axis_slice(params_wo, axis_name, 0).astype(dtype)
    bo = axis_slice(params_bo, axis_name, 0).astype(dtype)
    disp = dispatch.astype(dtype)                            # [Tl, E, Cl]
    comb = combine.astype(dtype)

    # this lane's slot payloads for EVERY expert
    expert_in = jnp.einsum("tec,td->ecd", disp, x.astype(dtype))
    # exchange 1: send expert block j to lane j; receive every lane's
    # slots for OUR E/n experts, stacked along capacity -> [E/n, n*Cl, d]
    # (tiled all_to_all places peer j's piece at block j of the concat
    # axis, so capacity block j = lane j's slots)
    recv = lax.all_to_all(expert_in, axis_name, split_axis=0,
                          concat_axis=1, tiled=True)
    h = jax.nn.gelu(jnp.einsum("ecd,edf->ecf", recv, wi) + bi[:, None, :])
    out = jnp.einsum("ecf,efd->ecd", h, wo) + bo[:, None, :]
    # exchange 2 (inverse): capacity block j returns to lane j; expert
    # blocks re-stack in lane-major = global-expert order -> [E, Cl, d]
    back = lax.all_to_all(out, axis_name, split_axis=1, concat_axis=0,
                          tiled=True)
    return jnp.einsum("tec,ecd->td", comb, back)
