"""Synchronous data parallelism with ZeRO-1 optimizer-state sharding.

Beyond-parity engine: the reference's only training mode is K-step local
SGD with weight averaging (SURVEY.md §2a — served here by
parallel/kavg.py). This module adds the classic alternative — per-step
gradient all-reduce with PERSISTENT optimizer state — for workloads where
exact synchronous SGD semantics matter more than the reference's
communication-saving K-AVG, plus ZeRO-1 sharding of that state so adaptive
optimizers (adam's m/v are 2x the model in f32) stop costing replicated
HBM.

TPU-native design — the whole engine is sharding annotations, no manual
collectives:

  - the global batch is sharded over the mesh `data` axis
    (`P(None, DATA_AXIS)` on the [S, B, ...] leaves); params stay
    replicated (`P()`). `value_and_grad` of the batch-mean loss then
    makes XLA's SPMD partitioner insert the gradient all-reduce itself —
    the `psum` the reference's RedisAI blackboard approximated is never
    written down;
  - ZeRO-1: optimizer-state leaves are laid out sharded over `data`
    (dim 0 when it divides the axis), so each chip stores 1/D of m/v and
    computes 1/D of the update; GSPMD all-gathers the updates into the
    replicated params. A `with_sharding_constraint` inside the scan body
    pins the layout so it persists across steps instead of decaying to
    whatever the partitioner prefers;
  - FSDP (ZeRO-3): `fsdp=True` extends the same layout rule to the
    PARAMETERS — each chip stores 1/D of the model; GSPMD all-gathers a
    layer's weights at its use site in forward/backward and
    reduce-scatters the grads back to the shards. Zero model code
    changes: FSDP here is literally a different `PartitionSpec` on the
    same program;
  - S steps run as one `lax.scan` under a single jit — one dispatch per
    round, same async-dispatch discipline as the K-avg engine.

The two engines share the model contract (KubeModel.loss /
configure_optimizers) and differ only in sync semantics:

    KAvgEngine:   merge every K steps, average WEIGHTS, reset opt state
                  (reference parity, network.py:208-217)
    SyncDPEngine: merge every step, average GRADIENTS, keep opt state
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from kubeml_tpu.parallel.kavg import (_select_tree, masked_scalar_loss,
                                      tree_all_finite, tree_sq_norm)
from kubeml_tpu.parallel.mesh import DATA_AXIS

PyTree = Any


class SyncDPEngine:
    """Per-step gradient-averaging trainer over the mesh `data` axis.

    loss_fn / tx_factory follow the KAvgEngine contract
    (KubeModel.loss / KubeModel.configure_optimizers).
    """

    def __init__(self, mesh: Mesh, loss_fn: Callable, tx_factory: Callable,
                 zero1: bool = True, fsdp: bool = False,
                 donate: bool = True, collect_stats: bool = False):
        """zero1=True shards optimizer state over the data axis (ZeRO-1);
        fsdp=True additionally shards the PARAMETERS over the data axis
        (ZeRO-3 / FSDP: each chip stores 1/D of the model and GSPMD
        all-gathers each layer at use, reduce-scattering the grads), for
        models too large to replicate per chip. fsdp implies zero1.
        donate=True donates the carried state to each train_steps call —
        thread the returned state, never reuse the argument.
        collect_stats=True adds per-step health-stat outputs (squared
        global grad / update / param norms, see `last_stats_device`) to
        the scan — pure EXTRA outputs computed from values the step
        already produces, so trained weights are bit-identical with the
        flag on or off, and they stay on device until the job's
        epoch-end drain (no mid-epoch host syncs)."""
        self.mesh = mesh
        self.loss_fn = loss_fn
        self.tx_factory = tx_factory
        self.zero1 = zero1 or fsdp
        self.fsdp = fsdp
        self.donate = donate
        self.collect_stats = bool(collect_stats)
        self.n_lanes = mesh.shape[DATA_AXIS]
        self._cache: Dict[Any, Callable] = {}
        self._opt_specs: Optional[PyTree] = None
        self._param_specs: Optional[PyTree] = None
        # mirrors RoundStats.compiled (parallel/kavg.py): True when the
        # most recent train_steps built a new program — the job excludes
        # such rounds from the duration the throughput policy sees
        self.last_compiled = False
        # [S] device array of 0/1 flags from the most recent train_steps:
        # 1 = the global gradient went non-finite and the optimizer update
        # was SKIPPED (params/opt state carried forward unchanged — the
        # skip-step practice of mixed-precision training). Kept on device;
        # accumulate and read back once per epoch like RoundStats.
        self.last_skipped_device: Optional[jax.Array] = None
        # [S, 3] device array from the most recent train_steps when
        # collect_stats: per-step (sq global grad norm, sq update norm,
        # sq param norm), zeroed for masked/skipped steps. Same lazy
        # discipline as last_skipped_device — keep on device, reduce at
        # epoch end. None when collect_stats is off.
        self.last_stats_device: Optional[jax.Array] = None

    # ----------------------------------------------------------------- state

    def _opt_spec_for(self, leaf) -> P:
        """ZeRO layout rule: shard dim 0 over `data` when it divides the
        axis; scalars/indivisible leaves (optax step counts, small biases)
        replicate."""
        if (self.zero1 and hasattr(leaf, "ndim") and leaf.ndim >= 1
                and leaf.shape[0] % self.n_lanes == 0 and leaf.shape[0] > 0):
            return P(DATA_AXIS)
        return P()

    def init_state(self, variables: PyTree, lr: float = 0.0,
                   epoch: int = 0) -> PyTree:
        """Build {params, model_state, opt_state} with opt_state (and,
        with fsdp, params) laid out per the ZeRO rule. lr/epoch only
        parameterize schedules whose state shape depends on them (none of
        the stock optax ones do)."""
        tx = self.tx_factory(jnp.float32(lr), jnp.int32(epoch))
        params = variables["params"]
        self._param_specs = jax.tree_util.tree_map(
            self._opt_spec_for if self.fsdp else (lambda _: P()), params)
        params = jax.tree_util.tree_map(
            lambda x, spec: jax.device_put(x, NamedSharding(self.mesh,
                                                            spec)),
            params, self._param_specs)
        opt_state = jax.eval_shape(tx.init, params)
        self._opt_specs = jax.tree_util.tree_map(self._opt_spec_for,
                                                 opt_state)
        shardings = jax.tree_util.tree_map(
            lambda spec: NamedSharding(self.mesh, spec), self._opt_specs)
        opt_state = jax.jit(tx.init, out_shardings=shardings)(params)
        return {
            "params": params,
            "model_state": {k: v for k, v in variables.items()
                            if k != "params"},
            "opt_state": opt_state,
        }

    def variables(self, state: PyTree) -> PyTree:
        """Flax-style variable dict view (for eval/checkpoint/serving)."""
        return {"params": state["params"], **state["model_state"]}

    # ----------------------------------------------------------------- train

    def _build(self, opt_specs, param_specs):
        mesh = self.mesh
        loss_fn = self.loss_fn
        tx_factory = self.tx_factory
        collect = self.collect_stats

        def run(state, batch, sample_mask, rngs, lr, epoch):
            tx = tx_factory(lr, epoch)

            def step(carry, xs):
                params, model_state, opt_state = carry
                mb, smask, rng = xs
                (loss, new_state), grads = jax.value_and_grad(
                    masked_scalar_loss(loss_fn, model_state, mb, rng,
                                       smask), has_aux=True)(params)
                updates, new_opt = tx.update(grads, opt_state, params)
                new_params = optax.apply_updates(params, updates)
                # skip-step guard: when the GLOBAL (all-reduced) gradient
                # or the loss is non-finite, the whole step is a no-op —
                # params and optimizer state carry forward unchanged, the
                # sync-DP analogue of the kavg merge guard. The select
                # already isolates the poisoned new_params, so no NaN
                # escapes into the carry.
                grads_ok = jnp.logical_and(tree_all_finite(grads),
                                           jnp.isfinite(loss))
                real = (smask.sum() > 0).astype(jnp.float32)
                # an all-masked step (ragged epoch tail) must be a true
                # no-op: zero grads alone would still move adam's momentum
                stmask = real * grads_ok.astype(jnp.float32)
                new_params = _select_tree(stmask, new_params, params)
                new_state = _select_tree(stmask, new_state, model_state)
                new_opt = _select_tree(stmask, new_opt, opt_state)
                # pin the ZeRO/FSDP layouts so they survive the scan carry
                new_opt = jax.tree_util.tree_map(
                    lambda x, spec: lax.with_sharding_constraint(
                        x, NamedSharding(mesh, spec)),
                    new_opt, opt_specs)
                new_params = jax.tree_util.tree_map(
                    lambda x, spec: lax.with_sharding_constraint(
                        x, NamedSharding(mesh, spec)),
                    new_params, param_specs)
                # a skipped step reports loss 0 (a NaN entry would poison
                # the epoch's on-device loss accumulation) and flags
                # itself; only REAL steps can be "skipped"
                loss_out = jnp.where(grads_ok, loss, 0.0) * real
                skipped = real * (1.0 - grads_ok.astype(jnp.float32))
                outs = (loss_out, skipped)
                if collect:
                    # health-stat lane: pure extra outputs from values the
                    # step already computed — nothing feeds back into the
                    # carry, so weights are bit-identical stats on/off.
                    # where-select, not multiply: NaN * 0 == NaN would
                    # leak a poisoned step's grads into the epoch sums.
                    stat = jnp.where(
                        stmask > 0,
                        jnp.stack([tree_sq_norm(grads),
                                   tree_sq_norm(updates),
                                   tree_sq_norm(new_params)]),
                        jnp.zeros((3,), jnp.float32))
                    outs = outs + (stat,)
                return (new_params, new_state, new_opt), outs

            (params, model_state, opt_state), outs = lax.scan(
                step, (state["params"], state["model_state"],
                       state["opt_state"]),
                (batch, sample_mask, rngs))
            losses, skipped = outs[0], outs[1]
            new_state = {"params": params, "model_state": model_state,
                         "opt_state": opt_state}
            if collect:
                return new_state, losses, skipped, outs[2]
            return new_state, losses, skipped

        return run

    def train_steps(self, state: PyTree, batch: PyTree,
                    sample_mask: np.ndarray, rngs: np.ndarray,
                    lr: float, epoch: int) -> Tuple[PyTree, jax.Array]:
        """Run S synchronous steps; one jitted dispatch.

        batch leaves [S, B, ...] with B the GLOBAL batch (B % data-axis
        == 0); sample_mask [S, B] 1 = real example; rngs [S, 2] uint32 key
        data. Returns (new state, per-step mean losses [S], a device
        array — read back lazily). Steps whose global gradient went
        non-finite are no-ops (loss reported 0); their flags land in
        `last_skipped_device`."""
        if self._opt_specs is None:
            raise ValueError("call init_state() first")
        lead = jax.tree_util.tree_leaves(batch)[0]
        if lead.shape[1] % self.n_lanes:
            raise ValueError(
                f"global batch {lead.shape[1]} not divisible by the "
                f"data-axis size {self.n_lanes}")
        key = (tuple(lead.shape[:2]),
               jax.tree_util.tree_structure(batch), self.collect_stats)
        self.last_compiled = key not in self._cache
        if self.last_compiled:
            batch_sh = jax.tree_util.tree_map(
                lambda _: NamedSharding(self.mesh, P(None, DATA_AXIS)),
                batch)
            state_sh = {
                "params": jax.tree_util.tree_map(
                    lambda spec: NamedSharding(self.mesh, spec),
                    self._param_specs),
                "model_state": jax.tree_util.tree_map(
                    lambda _: NamedSharding(self.mesh, P()),
                    state["model_state"]),
                "opt_state": jax.tree_util.tree_map(
                    lambda spec: NamedSharding(self.mesh, spec),
                    self._opt_specs),
            }
            rep = NamedSharding(self.mesh, P())
            mask_sh = NamedSharding(self.mesh, P(None, DATA_AXIS))
            self._cache[key] = jax.jit(
                self._build(self._opt_specs, self._param_specs),
                in_shardings=(state_sh, batch_sh, mask_sh, rep, rep, rep),
                # pin outputs to the input layout: without this GSPMD may
                # return params/opt leaves in whatever sharding propagation
                # settled on, and the NEXT dispatch's in_shardings mismatch
                out_shardings=(state_sh, rep, rep)
                + ((rep,) if self.collect_stats else ()),
                donate_argnums=(0,) if self.donate else ())
        state, losses, skipped, *extra = self._cache[key](
            state, batch, jnp.asarray(sample_mask, jnp.float32),
            jnp.asarray(rngs, jnp.uint32), jnp.float32(lr),
            jnp.int32(epoch))
        self.last_skipped_device = skipped
        self.last_stats_device = extra[0] if extra else None
        return state, losses

    # ------------------------------------------------------ index-fed train

    def _build_indexed(self, opt_specs, param_specs, cache):
        """Index-fed wrapper around the same scan body: gather the
        [S, G] global-batch samples from the replicated device cache,
        then run the exact _build program on the gathered leaves —
        identical math, so results are bit-identical to a host-staged
        dispatch of the same samples."""
        run = self._build(opt_specs, param_specs)
        device_transform = cache.device_transform

        def run_indexed(state, cache_arrays, idx, sample_mask, rngs, lr,
                        epoch):
            if device_transform is not None:
                batch = device_transform(cache_arrays["x"][idx],
                                         cache_arrays["y"][idx])
            else:
                batch = {k: v[idx] for k, v in cache_arrays.items()}
            return run(state, batch, sample_mask, rngs, lr, epoch)

        return run_indexed

    def train_steps_indexed(self, state: PyTree, cache, idx: np.ndarray,
                            sample_mask: np.ndarray, rngs: np.ndarray,
                            lr: float, epoch: int
                            ) -> Tuple[PyTree, jax.Array]:
        """train_steps against a device-resident dataset cache
        (data/device_cache.py): the dispatch carries `idx` [S, G] int32
        GLOBAL sample indices instead of the materialized [S, G, ...]
        batch leaves. Requires a replicated cache — the sync-DP global
        batch interleaves every worker's samples across the data axis,
        so a lane's gather set is never a contiguous slab."""
        if cache.layout != "replicated":
            raise ValueError("sync-DP index-fed rounds need a replicated "
                             f"cache, got layout={cache.layout!r}")
        if self._opt_specs is None:
            raise ValueError("call init_state() first")
        S, G = int(np.shape(idx)[0]), int(np.shape(idx)[1])
        if G % self.n_lanes:
            raise ValueError(
                f"global batch {G} not divisible by the "
                f"data-axis size {self.n_lanes}")
        key = ("idx", (S, G), cache.signature, self.collect_stats)
        self.last_compiled = key not in self._cache
        if self.last_compiled:
            state_sh = {
                "params": jax.tree_util.tree_map(
                    lambda spec: NamedSharding(self.mesh, spec),
                    self._param_specs),
                "model_state": jax.tree_util.tree_map(
                    lambda _: NamedSharding(self.mesh, P()),
                    state["model_state"]),
                "opt_state": jax.tree_util.tree_map(
                    lambda spec: NamedSharding(self.mesh, spec),
                    self._opt_specs),
            }
            rep = NamedSharding(self.mesh, P())
            cache_sh = jax.tree_util.tree_map(lambda _: rep, cache.arrays)
            idx_sh = NamedSharding(self.mesh, P(None, DATA_AXIS))
            mask_sh = NamedSharding(self.mesh, P(None, DATA_AXIS))
            self._cache[key] = jax.jit(
                self._build_indexed(self._opt_specs, self._param_specs,
                                    cache),
                in_shardings=(state_sh, cache_sh, idx_sh, mask_sh, rep,
                              rep, rep),
                out_shardings=(state_sh, rep, rep)
                + ((rep,) if self.collect_stats else ()),
                # donate only the state; the cache must outlive the job
                donate_argnums=(0,) if self.donate else ())
        state, losses, skipped, *extra = self._cache[key](
            state, cache.arrays, jnp.asarray(idx, jnp.int32),
            jnp.asarray(sample_mask, jnp.float32),
            jnp.asarray(rngs, jnp.uint32), jnp.float32(lr),
            jnp.int32(epoch))
        self.last_skipped_device = skipped
        self.last_stats_device = extra[0] if extra else None
        return state, losses
