"""Synchronous data parallelism with ZeRO-1 optimizer-state sharding.

Beyond-parity engine: the reference's only training mode is K-step local
SGD with weight averaging (SURVEY.md §2a — served here by
parallel/kavg.py). This module adds the classic alternative — per-step
gradient all-reduce with PERSISTENT optimizer state — for workloads where
exact synchronous SGD semantics matter more than the reference's
communication-saving K-AVG, plus ZeRO-1 sharding of that state so adaptive
optimizers (adam's m/v are 2x the model in f32) stop costing replicated
HBM.

TPU-native design — the whole engine is sharding annotations, no manual
collectives:

  - the global batch is sharded over the mesh `data` axis
    (`P(None, DATA_AXIS)` on the [S, B, ...] leaves); params stay
    replicated (`P()`). `value_and_grad` of the batch-mean loss then
    makes XLA's SPMD partitioner insert the gradient all-reduce itself —
    the `psum` the reference's RedisAI blackboard approximated is never
    written down;
  - ZeRO-1: optimizer-state leaves are laid out sharded over `data`
    (dim 0 when it divides the axis), so each chip stores 1/D of m/v and
    computes 1/D of the update; GSPMD all-gathers the updates into the
    replicated params. A `with_sharding_constraint` inside the scan body
    pins the layout so it persists across steps instead of decaying to
    whatever the partitioner prefers;
  - FSDP (ZeRO-3): `fsdp=True` extends the same layout rule to the
    PARAMETERS — each chip stores 1/D of the model; GSPMD all-gathers a
    layer's weights at its use site in forward/backward and
    reduce-scatters the grads back to the shards. Zero model code
    changes: FSDP here is literally a different `PartitionSpec` on the
    same program;
  - S steps run as one `lax.scan` under a single jit — one dispatch per
    round, same async-dispatch discipline as the K-avg engine.

The two engines share the model contract (KubeModel.loss /
configure_optimizers) and differ only in sync semantics:

    KAvgEngine:   merge every K steps, average WEIGHTS, reset opt state
                  (reference parity, network.py:208-217)
    SyncDPEngine: merge every step, average GRADIENTS, keep opt state
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from kubeml_tpu import compat
from kubeml_tpu.metrics.ledger import CostLedger
from kubeml_tpu.parallel import merge as merge_lib
from kubeml_tpu.parallel.kavg import (_select_tree, masked_scalar_loss,
                                      tree_all_finite, tree_sq_norm)
from kubeml_tpu.parallel.mesh import DATA_AXIS

PyTree = Any


class SyncDPEngine:
    """Per-step gradient-averaging trainer over the mesh `data` axis.

    loss_fn / tx_factory follow the KAvgEngine contract
    (KubeModel.loss / KubeModel.configure_optimizers).
    """

    def __init__(self, mesh: Mesh, loss_fn: Callable, tx_factory: Callable,
                 zero1: bool = True, fsdp: bool = False,
                 donate: bool = True, collect_stats: bool = False,
                 merge_strategy: Optional[str] = None,
                 merge_bucket_mb: float = 0.0,
                 merge_fused: Optional[bool] = None):
        """zero1=True shards optimizer state over the data axis (ZeRO-1);
        fsdp=True additionally shards the PARAMETERS over the data axis
        (ZeRO-3 / FSDP: each chip stores 1/D of the model and GSPMD
        all-gathers each layer at use, reduce-scattering the grads), for
        models too large to replicate per chip. fsdp implies zero1.
        donate=True donates the carried state to each train_steps call —
        thread the returned state, never reuse the argument.
        collect_stats=True adds per-step health-stat outputs (squared
        global grad / update / param norms, see `last_stats_device`) to
        the scan — pure EXTRA outputs computed from values the step
        already produces, so trained weights are bit-identical with the
        flag on or off, and they stay on device until the job's
        epoch-end drain (no mid-epoch host syncs).

        merge_strategy selects an EXPLICIT gradient merge through the
        shared strategy objects of parallel/merge.py instead of the
        implicit GSPMD all-reduce: per-lane gradient sums computed under
        a shard_map over `data`, reduced by the named strategy
        ("monolithic" | "bucketed" | "ef_bf16" | "ef_int8", with
        merge_bucket_mb sizing the flat buckets), then normalized by the
        global real-sample count — the same masked-mean semantics as
        the implicit path, so skip-step guards and stat lanes carry
        over unchanged. "bucketed" is bit-identical to "monolithic";
        EF strategies keep per-lane residual state inside the carried
        train state (key "merge_resid", zeroed on skipped steps and for
        fully-masked lanes). Model-state float leaves (batch stats)
        come back as the cross-lane mean — per-lane statistics, the
        DDP convention — where the implicit path computes global-batch
        statistics; stick to the implicit path when that distinction
        matters. Incompatible with fsdp (sharded params need GSPMD's
        reduce-scatter). merge_fused forwards to the bucketed apply
        kernel (ops/pallas/fused_merge.py)."""
        self.mesh = mesh
        self.loss_fn = loss_fn
        self.tx_factory = tx_factory
        self.zero1 = zero1 or fsdp
        self.fsdp = fsdp
        self.donate = donate
        self.collect_stats = bool(collect_stats)
        self.n_lanes = mesh.shape[DATA_AXIS]
        if merge_strategy is not None and fsdp:
            raise ValueError("explicit merge strategies are incompatible "
                             "with fsdp (sharded params rely on GSPMD's "
                             "gradient reduce-scatter)")
        self._merge = (merge_lib.strategy_by_name(
            merge_strategy, bucket_mb=merge_bucket_mb,
            use_ring=mesh.size != self.n_lanes, fused=merge_fused)
            if merge_strategy is not None else None)
        self._ef = self._merge is not None and self._merge.needs_residual
        self._cache: Dict[Any, Callable] = {}
        # analytic cost ledger (metrics/ledger.py): per-program
        # ProgramCost captured AOT at compile, dispatches attributed
        self.ledger = CostLedger()
        self._opt_specs: Optional[PyTree] = None
        self._param_specs: Optional[PyTree] = None
        # mirrors RoundStats.compiled (parallel/kavg.py): True when the
        # most recent train_steps built a new program — the job excludes
        # such rounds from the duration the throughput policy sees
        self.last_compiled = False
        # [S] device array of 0/1 flags from the most recent train_steps:
        # 1 = the global gradient went non-finite and the optimizer update
        # was SKIPPED (params/opt state carried forward unchanged — the
        # skip-step practice of mixed-precision training). Kept on device;
        # accumulate and read back once per epoch like RoundStats.
        self.last_skipped_device: Optional[jax.Array] = None
        # [S, 3] device array from the most recent train_steps when
        # collect_stats: per-step (sq global grad norm, sq update norm,
        # sq param norm), zeroed for masked/skipped steps. Same lazy
        # discipline as last_skipped_device — keep on device, reduce at
        # epoch end. None when collect_stats is off.
        self.last_stats_device: Optional[jax.Array] = None

    @property
    def merge_strategy(self) -> Optional[str]:
        """Registered name of the explicit merge strategy, or None when
        the implicit GSPMD all-reduce is in charge."""
        return self._merge.name if self._merge is not None else None

    @property
    def programs_compiled(self) -> int:
        """Distinct train programs built by this engine."""
        return len(self._cache)

    def merge_comm_proxy(self, variables: PyTree) -> Dict[str, int]:
        """Deterministic per-step gradient-merge wire numbers. The
        implicit GSPMD path is reported as the monolithic strategy over
        the params (one full-f32 all-reduce of the gradient tree)."""
        strategy = self._merge or merge_lib.MERGE_STRATEGIES["monolithic"]()
        out = strategy.comm_proxy(variables["params"]
                                  if "params" in variables else variables)
        out["strategy"] = (self._merge.name if self._merge is not None
                           else "monolithic")
        return out

    # ----------------------------------------------------------------- state

    def _opt_spec_for(self, leaf) -> P:
        """ZeRO layout rule: shard dim 0 over `data` when it divides the
        axis; scalars/indivisible leaves (optax step counts, small biases)
        replicate."""
        if (self.zero1 and hasattr(leaf, "ndim") and leaf.ndim >= 1
                and leaf.shape[0] % self.n_lanes == 0 and leaf.shape[0] > 0):
            return P(DATA_AXIS)
        return P()

    def init_state(self, variables: PyTree, lr: float = 0.0,
                   epoch: int = 0) -> PyTree:
        """Build {params, model_state, opt_state} with opt_state (and,
        with fsdp, params) laid out per the ZeRO rule. lr/epoch only
        parameterize schedules whose state shape depends on them (none of
        the stock optax ones do)."""
        tx = self.tx_factory(jnp.float32(lr), jnp.int32(epoch))
        params = variables["params"]
        self._param_specs = jax.tree_util.tree_map(
            self._opt_spec_for if self.fsdp else (lambda _: P()), params)
        params = jax.tree_util.tree_map(
            lambda x, spec: jax.device_put(x, NamedSharding(self.mesh,
                                                            spec)),
            params, self._param_specs)
        opt_state = jax.eval_shape(tx.init, params)
        self._opt_specs = jax.tree_util.tree_map(self._opt_spec_for,
                                                 opt_state)
        shardings = jax.tree_util.tree_map(
            lambda spec: NamedSharding(self.mesh, spec), self._opt_specs)
        opt_state = jax.jit(tx.init, out_shardings=shardings)(params)
        state = {
            "params": params,
            "model_state": {k: v for k, v in variables.items()
                            if k != "params"},
            "opt_state": opt_state,
        }
        if self._ef:
            # per-lane EF residuals live INSIDE the carried train state
            # (donated and threaded like opt_state): flat [D * L_bucket]
            # f32 per bucket, sharded over `data` so each lane owns its
            # slice. Zero-initialized — a fresh state carries no error.
            sizes = self._merge.residual_sizes(state["params"])
            sh = NamedSharding(self.mesh, P(DATA_AXIS))
            state["merge_resid"] = {
                k: jax.device_put(np.zeros(self.n_lanes * n, np.float32),
                                  sh)
                for k, n in sizes.items()}
        return state

    def variables(self, state: PyTree) -> PyTree:
        """Flax-style variable dict view (for eval/checkpoint/serving)."""
        return {"params": state["params"], **state["model_state"]}

    def _state_shardings(self, state: PyTree) -> PyTree:
        """NamedSharding tree for the carried train state (jit in/out
        shardings): params/opt per the ZeRO rule, model_state
        replicated, EF residuals lane-sharded over `data`."""
        sh = {
            "params": jax.tree_util.tree_map(
                lambda spec: NamedSharding(self.mesh, spec),
                self._param_specs),
            "model_state": jax.tree_util.tree_map(
                lambda _: NamedSharding(self.mesh, P()),
                state["model_state"]),
            "opt_state": jax.tree_util.tree_map(
                lambda spec: NamedSharding(self.mesh, spec),
                self._opt_specs),
        }
        if self._ef:
            sh["merge_resid"] = jax.tree_util.tree_map(
                lambda _: NamedSharding(self.mesh, P(DATA_AXIS)),
                state["merge_resid"])
        return sh

    # ----------------------------------------------------------------- train

    def _lane_grad_fn(self):
        """shard_map'd per-lane gradient + strategy merge for the
        EXPLICIT merge path: each lane computes the gradient of its
        UNNORMALIZED masked loss sum over its batch shard, the strategy
        object reduces the per-lane sums (one bucketed/compressed
        collective set instead of GSPMD's implicit all-reduce), and the
        caller divides by the psum'd real-sample count — algebraically
        the same masked-mean gradient as the implicit path."""
        loss_fn = self.loss_fn
        strategy = self._merge
        ef = self._ef
        n_lanes = self.n_lanes

        def lane(params, model_state, mb, smask, rng, *resid):
            def local_sum(p):
                per_ex, new_state = loss_fn(
                    {"params": p, **model_state}, mb,
                    jax.random.wrap_key_data(rng), smask)
                return (per_ex * smask).sum(), new_state

            (lsum, new_state), g = jax.value_and_grad(
                local_sum, has_aux=True)(params)
            lane_n = smask.sum()
            denom = lax.psum(lane_n, DATA_AXIS)
            # a lane whose local grads went non-finite poisons the step
            # for everyone (skip-step semantics, same as the implicit
            # path) — but EF payload masking below would HIDE its NaN
            # from the merged grads, so the bad-lane count travels
            # explicitly and the caller folds it into grads_ok.
            lane_finite = jnp.logical_and(tree_all_finite(g),
                                          jnp.isfinite(lsum))
            bad = lax.psum(1.0 - lane_finite.astype(jnp.float32),
                           DATA_AXIS)
            alive = jnp.logical_and(lane_n > 0, lane_finite)
            raw = lax.psum(alive.astype(jnp.float32), DATA_AXIS)
            # SUM the per-lane grads (count=1; normalization by the
            # global sample count happens outside): ref is a zero tree,
            # so an all-dead step merges to zero grads.
            zeros = jax.tree_util.tree_map(
                lambda x: jnp.zeros_like(x, jnp.float32), g)
            gsum, new_resid = strategy.lane_merge(
                g, zeros, raw, jnp.float32(1.0),
                lane_alive=alive, residual=resid[0] if ef else None)
            loss_tot = lax.psum(jnp.where(lane_finite, lsum, 0.0),
                                DATA_AXIS)
            # model_state: float leaves (batch stats) come back as the
            # cross-lane mean (per-lane statistics, DDP convention);
            # integer leaves (step counters) advance identically on
            # every lane and pass through.
            new_state = jax.tree_util.tree_map(
                lambda l: ((lax.psum(l.astype(jnp.float32), DATA_AXIS)
                            / n_lanes).astype(l.dtype)
                           if jnp.issubdtype(l.dtype, jnp.inexact)
                           else l),
                new_state)
            out = (gsum, loss_tot, denom, bad, new_state)
            return out + ((new_resid,) if ef else ())

        kw = dict(check_vma=False)
        if self.mesh.size != self.n_lanes:
            kw["axis_names"] = {DATA_AXIS}
        ef_specs = (P(DATA_AXIS),) if ef else ()
        return compat.shard_map(
            lane, mesh=self.mesh,
            in_specs=(P(), P(), P(DATA_AXIS), P(DATA_AXIS), P())
            + ef_specs,
            out_specs=(P(), P(), P(), P(), P()) + ef_specs,
            **kw)

    def _build(self, opt_specs, param_specs):
        mesh = self.mesh
        loss_fn = self.loss_fn
        tx_factory = self.tx_factory
        collect = self.collect_stats
        explicit = self._merge is not None
        ef = self._ef
        lane_grads = self._lane_grad_fn() if explicit else None

        def run(state, batch, sample_mask, rngs, lr, epoch):
            tx = tx_factory(lr, epoch)

            def step(carry, xs):
                if ef:
                    params, model_state, opt_state, resid = carry
                else:
                    params, model_state, opt_state = carry
                    resid = None
                mb, smask, rng = xs
                if explicit:
                    out = lane_grads(params, model_state, mb, smask, rng,
                                     *((resid,) if ef else ()))
                    gsum, loss_tot, denom, bad, new_state = out[:5]
                    dn = jnp.maximum(denom, 1.0)
                    grads = jax.tree_util.tree_map(lambda x: x / dn, gsum)
                    loss = loss_tot / dn
                else:
                    (loss, new_state), grads = jax.value_and_grad(
                        masked_scalar_loss(loss_fn, model_state, mb, rng,
                                           smask), has_aux=True)(params)
                updates, new_opt = tx.update(grads, opt_state, params)
                new_params = optax.apply_updates(params, updates)
                # skip-step guard: when the GLOBAL (all-reduced) gradient
                # or the loss is non-finite, the whole step is a no-op —
                # params and optimizer state carry forward unchanged, the
                # sync-DP analogue of the kavg merge guard. The select
                # already isolates the poisoned new_params, so no NaN
                # escapes into the carry.
                grads_ok = jnp.logical_and(tree_all_finite(grads),
                                           jnp.isfinite(loss))
                if explicit:
                    # EF payload masking hides a poisoned lane's NaN from
                    # the merged grads; the explicit bad-lane count keeps
                    # skip-step semantics identical to the implicit path
                    grads_ok = jnp.logical_and(grads_ok, bad == 0)
                real = (smask.sum() > 0).astype(jnp.float32)
                # an all-masked step (ragged epoch tail) must be a true
                # no-op: zero grads alone would still move adam's momentum
                stmask = real * grads_ok.astype(jnp.float32)
                new_params = _select_tree(stmask, new_params, params)
                new_state = _select_tree(stmask, new_state, model_state)
                new_opt = _select_tree(stmask, new_opt, opt_state)
                # pin the ZeRO/FSDP layouts so they survive the scan carry
                new_opt = jax.tree_util.tree_map(
                    lambda x, spec: lax.with_sharding_constraint(
                        x, NamedSharding(mesh, spec)),
                    new_opt, opt_specs)
                new_params = jax.tree_util.tree_map(
                    lambda x, spec: lax.with_sharding_constraint(
                        x, NamedSharding(mesh, spec)),
                    new_params, param_specs)
                # a skipped step reports loss 0 (a NaN entry would poison
                # the epoch's on-device loss accumulation) and flags
                # itself; only REAL steps can be "skipped"
                loss_out = jnp.where(grads_ok, loss, 0.0) * real
                skipped = real * (1.0 - grads_ok.astype(jnp.float32))
                outs = (loss_out, skipped)
                if collect:
                    # health-stat lane: pure extra outputs from values the
                    # step already computed — nothing feeds back into the
                    # carry, so weights are bit-identical stats on/off.
                    # where-select, not multiply: NaN * 0 == NaN would
                    # leak a poisoned step's grads into the epoch sums.
                    stat = jnp.where(
                        stmask > 0,
                        jnp.stack([tree_sq_norm(grads),
                                   tree_sq_norm(updates),
                                   tree_sq_norm(new_params)]),
                        jnp.zeros((3,), jnp.float32))
                    outs = outs + (stat,)
                if ef:
                    # EF residual bookkeeping across the skip-step guard:
                    # applied step -> keep the strategy's residual;
                    # skipped (non-finite) step -> ZERO it (its payload
                    # was wasted and may descend from poisoned values);
                    # all-masked step (pure no-op) -> carry the old
                    # residual, as if the step never happened.
                    nr = out[5]
                    new_resid = {
                        k: jnp.where(stmask > 0, nr[k],
                                     jnp.where(real > 0,
                                               jnp.zeros_like(nr[k]),
                                               resid[k]))
                        for k in nr}
                    new_resid = jax.tree_util.tree_map(
                        lambda x: lax.with_sharding_constraint(
                            x, NamedSharding(mesh, P(DATA_AXIS))),
                        new_resid)
                    return (new_params, new_state, new_opt,
                            new_resid), outs
                return (new_params, new_state, new_opt), outs

            carry0 = (state["params"], state["model_state"],
                      state["opt_state"])
            if ef:
                carry0 = carry0 + (state["merge_resid"],)
            carry, outs = lax.scan(step, carry0,
                                   (batch, sample_mask, rngs))
            params, model_state, opt_state = carry[:3]
            losses, skipped = outs[0], outs[1]
            new_state = {"params": params, "model_state": model_state,
                         "opt_state": opt_state}
            if ef:
                new_state["merge_resid"] = carry[3]
            if collect:
                return new_state, losses, skipped, outs[2]
            return new_state, losses, skipped

        return run

    def train_steps(self, state: PyTree, batch: PyTree,
                    sample_mask: np.ndarray, rngs: np.ndarray,
                    lr: float, epoch: int) -> Tuple[PyTree, jax.Array]:
        """Run S synchronous steps; one jitted dispatch.

        batch leaves [S, B, ...] with B the GLOBAL batch (B % data-axis
        == 0); sample_mask [S, B] 1 = real example; rngs [S, 2] uint32 key
        data. Returns (new state, per-step mean losses [S], a device
        array — read back lazily). Steps whose global gradient went
        non-finite are no-ops (loss reported 0); their flags land in
        `last_skipped_device`."""
        if self._opt_specs is None:
            raise ValueError("call init_state() first")
        lead = jax.tree_util.tree_leaves(batch)[0]
        if lead.shape[1] % self.n_lanes:
            raise ValueError(
                f"global batch {lead.shape[1]} not divisible by the "
                f"data-axis size {self.n_lanes}")
        key = (tuple(lead.shape[:2]),
               jax.tree_util.tree_structure(batch), self.collect_stats)
        self.last_compiled = key not in self._cache
        if self.last_compiled:
            batch_sh = jax.tree_util.tree_map(
                lambda _: NamedSharding(self.mesh, P(None, DATA_AXIS)),
                batch)
            state_sh = self._state_shardings(state)
            rep = NamedSharding(self.mesh, P())
            mask_sh = NamedSharding(self.mesh, P(None, DATA_AXIS))
            self._cache[key] = jax.jit(
                self._build(self._opt_specs, self._param_specs),
                in_shardings=(state_sh, batch_sh, mask_sh, rep, rep, rep),
                # pin outputs to the input layout: without this GSPMD may
                # return params/opt leaves in whatever sharding propagation
                # settled on, and the NEXT dispatch's in_shardings mismatch
                out_shardings=(state_sh, rep, rep)
                + ((rep,) if self.collect_stats else ()),
                donate_argnums=(0,) if self.donate else ())
        dispatch_args = (
            state, batch, jnp.asarray(sample_mask, jnp.float32),
            jnp.asarray(rngs, jnp.uint32), jnp.float32(lr),
            jnp.int32(epoch))
        self._ledger_note("syncdp.train", self._cache[key],
                          dispatch_args, sample_mask)
        state, losses, skipped, *extra = self._cache[key](*dispatch_args)
        self.last_skipped_device = skipped
        self.last_stats_device = extra[0] if extra else None
        return state, losses

    def _ledger_note(self, program, fn, dispatch_args,
                     sample_mask) -> None:
        """Capture the program's ProgramCost on compile (AOT aval-only
        lowering over the exact dispatch args — donation-safe) and
        attribute this dispatch's real sample count. The merge wire
        plan registers alongside as an exact analytic kernel record
        when the engine merges explicitly."""
        samples = int(np.asarray(sample_mask).sum())
        if self.last_compiled:
            params = dispatch_args[0]["params"]
            nbytes = sum(int(getattr(a, "nbytes", 0))
                         for a in jax.tree_util.tree_leaves(params))
            self.ledger.capture(
                program, "train", fn, *dispatch_args,
                fallback={"flops": 6.0 * (nbytes / 4.0) * max(samples, 1),
                          "hbm_bytes": float(3 * nbytes)})
            if self._merge is not None:
                merge_lib.register_strategy_cost(self.ledger, self._merge,
                                                 params)
        self.ledger.note_dispatch(program, samples=samples)

    # ------------------------------------------------------ index-fed train

    def _build_indexed(self, opt_specs, param_specs, cache):
        """Index-fed wrapper around the same scan body: gather the
        [S, G] global-batch samples from the replicated device cache,
        then run the exact _build program on the gathered leaves —
        identical math, so results are bit-identical to a host-staged
        dispatch of the same samples."""
        run = self._build(opt_specs, param_specs)
        device_transform = cache.device_transform

        def run_indexed(state, cache_arrays, idx, sample_mask, rngs, lr,
                        epoch):
            if device_transform is not None:
                batch = device_transform(cache_arrays["x"][idx],
                                         cache_arrays["y"][idx])
            else:
                batch = {k: v[idx] for k, v in cache_arrays.items()}
            return run(state, batch, sample_mask, rngs, lr, epoch)

        return run_indexed

    def train_steps_indexed(self, state: PyTree, cache, idx: np.ndarray,
                            sample_mask: np.ndarray, rngs: np.ndarray,
                            lr: float, epoch: int
                            ) -> Tuple[PyTree, jax.Array]:
        """train_steps against a device-resident dataset cache
        (data/device_cache.py): the dispatch carries `idx` [S, G] int32
        GLOBAL sample indices instead of the materialized [S, G, ...]
        batch leaves. Requires a replicated cache — the sync-DP global
        batch interleaves every worker's samples across the data axis,
        so a lane's gather set is never a contiguous slab."""
        if cache.layout != "replicated":
            raise ValueError("sync-DP index-fed rounds need a replicated "
                             f"cache, got layout={cache.layout!r}")
        if self._opt_specs is None:
            raise ValueError("call init_state() first")
        S, G = int(np.shape(idx)[0]), int(np.shape(idx)[1])
        if G % self.n_lanes:
            raise ValueError(
                f"global batch {G} not divisible by the "
                f"data-axis size {self.n_lanes}")
        key = ("idx", (S, G), cache.signature, self.collect_stats)
        self.last_compiled = key not in self._cache
        if self.last_compiled:
            state_sh = self._state_shardings(state)
            rep = NamedSharding(self.mesh, P())
            cache_sh = jax.tree_util.tree_map(lambda _: rep, cache.arrays)
            idx_sh = NamedSharding(self.mesh, P(None, DATA_AXIS))
            mask_sh = NamedSharding(self.mesh, P(None, DATA_AXIS))
            self._cache[key] = jax.jit(
                self._build_indexed(self._opt_specs, self._param_specs,
                                    cache),
                in_shardings=(state_sh, cache_sh, idx_sh, mask_sh, rep,
                              rep, rep),
                out_shardings=(state_sh, rep, rep)
                + ((rep,) if self.collect_stats else ()),
                # donate only the state; the cache must outlive the job
                donate_argnums=(0,) if self.donate else ())
        dispatch_args = (
            state, cache.arrays, jnp.asarray(idx, jnp.int32),
            jnp.asarray(sample_mask, jnp.float32),
            jnp.asarray(rngs, jnp.uint32), jnp.float32(lr),
            jnp.int32(epoch))
        self._ledger_note("syncdp.train_indexed", self._cache[key],
                          dispatch_args, sample_mask)
        state, losses, skipped, *extra = self._cache[key](*dispatch_args)
        self.last_skipped_device = skipped
        self.last_stats_device = extra[0] if extra else None
        return state, losses
