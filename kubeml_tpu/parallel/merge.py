"""Bucket planner + merge-strategy objects shared by both engines.

The sync round's communication step — mask-guarded cross-lane averaging
in the K-avg engine, the gradient all-reduce in the sync-DP engine —
used to live as a monolithic per-leaf `lax.psum` inline in each engine.
This module factors it into one place with two orthogonal levers:

  * BUCKETING (DDP-style): consecutive leaves are packed into size-capped
    flat f32 buckets and each bucket is reduced with ONE collective.
    Fewer, larger collectives amortize per-collective latency, and the
    independent per-bucket psums give XLA's latency-hiding scheduler
    freedom to overlap early buckets' collectives with the tail of the
    round's compute (the `lax.scan` of local steps) — the overlap model
    docs/performance.md describes. The f32 bucketed merge is BIT-IDENTICAL
    to the monolithic merge: a psum is elementwise over lanes, so
    psum(concat(a, b)) == concat(psum(a), psum(b)) exactly, and the
    guard-select/divide/cast chain applies the same IEEE ops per element.

  * ERROR-FEEDBACK COMPRESSION (1-bit-SGD / EF-SignSGD family): each
    lane quantizes payload = contribution + residual to bf16 (cast) or
    int8 (shared per-bucket scale from a cross-lane max), ships the
    quantized bucket, and keeps residual' = payload - decode(payload) for
    the next round, so quantization error is re-injected instead of lost.
    Residuals are per-lane persistent state threaded through the round
    programs as extra (donated) carry; they are ZEROED for lanes with no
    live contributor this round (quarantined / NaN-dropped workers), so
    the non-finite merge guard's semantics survive compression — a
    revived worker never replays a stale or poisoned residual.

Strategy registry: every variant is registered by name below and
`tools/check_merge_parity.py` lints that each registered name is covered
by a bit-identity or bounded-divergence test in tests/.

Wire-safety rules inherited from the engines (parallel/collectives.py):
a sub-f32 `lax.psum` fatally miscompiles in the partially-manual
partitioner, so compressed wires ride the ppermute ring on meshes with
Auto inner axes (`use_ring=True`) and psum directly only on fully-manual
rounds. The int8 strategy sidesteps the issue entirely: quantized values
are integer-valued f32 (exact in f32 psums up to 2^24), so its wire
collective is always a plain f32 psum of small integers plus one pmax
for the shared scale.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from kubeml_tpu.parallel.mesh import DATA_AXIS

PyTree = Any

# default size cap for EF-compressed buckets when the caller sets a
# compression scheme but no explicit merge_bucket_mb
DEFAULT_EF_BUCKET_MB = 4.0


def _leaf_elems(leaf) -> int:
    return int(np.prod(np.shape(leaf))) if np.ndim(leaf) else 1


def _leaf_float(leaf) -> bool:
    return jnp.issubdtype(jnp.asarray(leaf).dtype
                          if not hasattr(leaf, "dtype")
                          else jnp.dtype(leaf.dtype), jnp.floating)


@dataclass(frozen=True)
class Bucket:
    """One merge bucket: a run of consecutive tree leaves reduced with a
    single flat collective. `compressible` buckets hold only floating
    leaves (wire compression / EF may apply); exact buckets hold integer
    leaves (BatchNorm counters etc.) whose average-and-truncate contract
    requires a full-precision wire."""
    indices: Tuple[int, ...]     # leaf positions in tree_leaves order
    sizes: Tuple[int, ...]       # element count per leaf
    length: int                  # total elements in the bucket
    compressible: bool


@dataclass(frozen=True)
class BucketPlan:
    buckets: Tuple[Bucket, ...]
    n_leaves: int

    @property
    def n_buckets(self) -> int:
        return len(self.buckets)


def plan_buckets(leaves, bucket_mb: float) -> BucketPlan:
    """Pack consecutive leaves into size-capped buckets.

    Leaves keep their tree order (stable: jax's tree flatten sorts dict
    keys), consecutive float leaves pack greedily until the bucket would
    exceed `bucket_mb` MB of f32 payload (a single leaf larger than the
    cap gets its own bucket), and integer leaves never share a bucket
    with float ones so exact and compressible wires stay separable.
    bucket_mb <= 0 means "one bucket per kind" (no size cap).
    Accepts arrays or ShapeDtypeStructs — only shape/dtype are read."""
    cap_elems = int(bucket_mb * 1024 * 1024 / 4) if bucket_mb > 0 else 0
    buckets: List[Bucket] = []
    cur_idx: List[int] = []
    cur_sizes: List[int] = []
    cur_len = 0
    cur_float = True

    def flush():
        nonlocal cur_idx, cur_sizes, cur_len
        if cur_idx:
            buckets.append(Bucket(tuple(cur_idx), tuple(cur_sizes),
                                  cur_len, cur_float))
        cur_idx, cur_sizes, cur_len = [], [], 0

    for i, leaf in enumerate(leaves):
        n = _leaf_elems(leaf)
        is_float = _leaf_float(leaf)
        if cur_idx and (is_float != cur_float
                        or (cap_elems and cur_len + n > cap_elems)):
            flush()
        cur_float = is_float
        cur_idx.append(i)
        cur_sizes.append(n)
        cur_len += n
    flush()
    return BucketPlan(tuple(buckets), len(list(leaves)))


def _ring_psum(x, wire_dtype):
    from kubeml_tpu.parallel.collectives import ring_psum
    return ring_psum(x, DATA_AXIS, wire_dtype)


# --------------------------------------------------------------- registry

MERGE_STRATEGIES: Dict[str, Callable[..., "MergeStrategy"]] = {}


def _register(name: str):
    def deco(cls):
        MERGE_STRATEGIES[name] = cls
        cls.name = name
        return cls
    return deco


def make_strategy(merge_dtype: Any = None, bucket_mb: float = 0.0,
                  compress: str = "none", use_ring: bool = False,
                  fused: Optional[bool] = None) -> "MergeStrategy":
    """Map the engine knobs to a registered strategy instance.

    merge_dtype: legacy wire cast (no EF) applied to float payloads.
    bucket_mb > 0 selects the bucketed strategy; compress in
    {"bf16", "int8"} selects the EF strategies (implies bucketing, with
    a DEFAULT_EF_BUCKET_MB cap when bucket_mb is unset). merge_dtype
    and compress are mutually exclusive: EF already owns the wire."""
    compress = str(compress or "none")
    if compress not in ("none", "bf16", "int8"):
        raise ValueError(f"merge_compress must be none|bf16|int8, "
                         f"got {compress!r}")
    if compress != "none":
        if merge_dtype is not None:
            raise ValueError("merge_dtype and merge_compress are mutually "
                             "exclusive (EF compression owns the wire "
                             "dtype)")
        mb = bucket_mb if bucket_mb > 0 else DEFAULT_EF_BUCKET_MB
        cls = MERGE_STRATEGIES["ef_bf16" if compress == "bf16"
                               else "ef_int8"]
        return cls(bucket_mb=mb, use_ring=use_ring, fused=fused)
    if bucket_mb > 0:
        return MERGE_STRATEGIES["bucketed"](
            wire_dtype=merge_dtype, bucket_mb=bucket_mb, use_ring=use_ring,
            fused=fused)
    return MERGE_STRATEGIES["monolithic"](
        wire_dtype=merge_dtype, use_ring=use_ring)


class MergeStrategy:
    """One sync-round cross-lane merge, called INSIDE the engines'
    shard_map lane body.

    lane_merge(contrib, ref, raw_count, count, lane_alive, residual):
      contrib    per-lane f32 contribution tree (masked sums)
      ref        round-start variables tree (carry-forward + dtype source)
      raw_count  psum'd live-contributor count (0 => all dropped)
      count      max(raw_count, 1) — safe divisor
      lane_alive scalar bool: this lane shipped >= 1 live contribution
      residual   per-lane EF residual dict (needs_residual only)
    returns (avg_tree, new_residual_or_None). The all-dropped guard is
    part of the contract: raw_count == 0 must return `ref` unchanged."""

    name = "?"
    needs_residual = False

    def residual_sizes(self, variables: PyTree) -> Dict[str, int]:
        """Per-lane flat residual lengths keyed by bucket name ({} for
        strategies without EF state)."""
        return {}

    def lane_merge(self, contrib, ref, raw_count, count,
                   lane_alive=None, residual=None):
        raise NotImplementedError

    def comm_proxy(self, variables: PyTree) -> Dict[str, int]:
        """Deterministic CPU-tier communication proxy for one merge:
        wire payload bytes per lane per round and collective/bucket
        counts — computable from leaf shapes alone, so bench can assert
        them stable without an accelerator."""
        raise NotImplementedError


def _wire_bytes(dtype) -> int:
    return jnp.dtype(dtype).itemsize


@_register("monolithic")
class MonolithicMerge(MergeStrategy):
    """The pre-bucketing merge, verbatim: one masked psum per tree leaf,
    optional lossy wire cast on float leaves. The reference baseline all
    bit-identity tests anchor on."""

    def __init__(self, wire_dtype: Any = None, use_ring: bool = False,
                 **_):
        self.wire_dtype = wire_dtype
        self.use_ring = bool(use_ring) and wire_dtype is not None

    def lane_merge(self, contrib, ref, raw_count, count,
                   lane_alive=None, residual=None):
        merge_dtype = self.wire_dtype
        use_ring = self.use_ring

        def merge_leaf(c, r):
            # integer leaves (BatchNorm counters) stay uncompressed:
            # bf16's 8-bit mantissa would drift a counter > 256 even
            # when every worker agrees, breaking the exact average-
            # and-truncate contract
            if (merge_dtype is not None
                    and jnp.issubdtype(r.dtype, jnp.floating)):
                # compress at the communication boundary only: local
                # accumulation stays f32, the wire carries merge_dtype.
                # Error: ~2^-8 relative per cast PLUS the reduction
                # chain accumulating through bf16 hops (~D*2^-8 worst
                # case). Full-manual meshes psum the sub-f32 values
                # directly; Auto-inner meshes must take the ppermute
                # ring (a partial-manual sub-f32 psum is a fatal
                # partitioner miscompile — parallel/collectives.py).
                if use_ring:
                    s = _ring_psum(c, merge_dtype)
                else:
                    s = lax.psum(c.astype(merge_dtype), DATA_AXIS
                                 ).astype(jnp.float32)
                merged = (s / count).astype(r.dtype)
            else:
                merged = (lax.psum(c, DATA_AXIS) / count).astype(r.dtype)
            # every contributor dropped (all workers non-finite this
            # round): contrib is all-zero and dividing by the clamped
            # count would SILENTLY ZERO the weights. Carry the round-
            # start variables forward instead. For raw_count > 0 the
            # select picks the identical merged value, so the normal
            # path stays bit-identical.
            return jnp.where(raw_count > 0, merged, r)

        return (jax.tree_util.tree_map(merge_leaf, contrib, ref), None)

    def comm_proxy(self, variables):
        leaves = jax.tree_util.tree_leaves(variables)
        payload = 0
        for leaf in leaves:
            wb = (_wire_bytes(self.wire_dtype)
                  if self.wire_dtype is not None and _leaf_float(leaf)
                  else 4)
            payload += _leaf_elems(leaf) * wb
        return {"merge_payload_bytes": payload,
                "buckets_per_round": len(leaves),
                "collectives_per_round": len(leaves)}


class _BucketedBase(MergeStrategy):
    """Shared flat-bucket machinery: concat a bucket's leaves into one
    f32 vector, reduce it with one collective, apply avg+guard-select
    over the flat vector (via the fused Pallas kernel on TPU, a lax
    fallback elsewhere — bit-identical math), then split and cast back
    per leaf. Cast/select commute elementwise with the monolithic
    per-leaf chain, which is what makes the f32 variant bit-identical."""

    def __init__(self, bucket_mb: float, use_ring: bool = False,
                 fused: Optional[bool] = None, **_):
        self.bucket_mb = float(bucket_mb)
        self.use_ring = bool(use_ring)
        self.fused = fused

    def _flat(self, leaves, bucket: Bucket):
        parts = [leaves[i].reshape(-1).astype(jnp.float32)
                 for i in bucket.indices]
        return parts[0] if len(parts) == 1 else jnp.concatenate(parts)

    def _apply(self, s, ref_f32, raw_count, count):
        from kubeml_tpu.ops.pallas.fused_merge import fused_avg_select
        return fused_avg_select(s, ref_f32, count, raw_count,
                                fused=self.fused)

    def _split(self, merged_flat, ref_leaves, bucket: Bucket, out):
        off = 0
        for i, n in zip(bucket.indices, bucket.sizes):
            r = ref_leaves[i]
            out[i] = merged_flat[off:off + n].reshape(r.shape
                                                      ).astype(r.dtype)
            off += n

    def _reduce_bucket(self, flat_c, bucket: Bucket, lane_alive, residual):
        """Returns (summed_f32, new_residual_or_None) for one bucket."""
        raise NotImplementedError

    def lane_merge(self, contrib, ref, raw_count, count,
                   lane_alive=None, residual=None):
        leaves_c, treedef = jax.tree_util.tree_flatten(contrib)
        leaves_r = jax.tree_util.tree_leaves(ref)
        plan = plan_buckets(leaves_r, self.bucket_mb)
        merged: List[Any] = [None] * plan.n_leaves
        new_resid: Dict[str, Any] = {}
        for bi, bucket in enumerate(plan.buckets):
            flat_c = self._flat(leaves_c, bucket)
            ref_f32 = self._flat(leaves_r, bucket)
            r_in = (residual.get(f"b{bi}")
                    if self.needs_residual and residual is not None
                    else None)
            s, r_out = self._reduce_bucket(flat_c, bucket, lane_alive,
                                           r_in)
            if r_out is not None:
                new_resid[f"b{bi}"] = r_out
            m = self._apply(s, ref_f32, raw_count, count)
            self._split(m, leaves_r, bucket, merged)
        avg = jax.tree_util.tree_unflatten(treedef, merged)
        return avg, (new_resid if self.needs_residual else None)

    def residual_sizes(self, variables):
        if not self.needs_residual:
            return {}
        plan = plan_buckets(jax.tree_util.tree_leaves(variables),
                            self.bucket_mb)
        return {f"b{bi}": b.length
                for bi, b in enumerate(plan.buckets) if b.compressible}

    def _bucket_wire_bytes(self, bucket: Bucket) -> int:
        return bucket.length * 4

    def comm_proxy(self, variables):
        plan = plan_buckets(jax.tree_util.tree_leaves(variables),
                            self.bucket_mb)
        payload = sum(self._bucket_wire_bytes(b) for b in plan.buckets)
        return {"merge_payload_bytes": payload,
                "buckets_per_round": plan.n_buckets,
                "collectives_per_round": plan.n_buckets}


@_register("bucketed")
class BucketedMerge(_BucketedBase):
    """Size-capped flat-bucket merge. f32 wire (default) is bit-identical
    to the monolithic merge; an optional wire_dtype cast (legacy
    merge_dtype knob) compresses float buckets like the monolithic path
    does per leaf — bounded-divergence there, since ring chunking over
    the flat bucket rounds in a different order than per-leaf rings."""

    def __init__(self, wire_dtype: Any = None, bucket_mb: float = 0.0,
                 use_ring: bool = False, fused: Optional[bool] = None,
                 **_):
        super().__init__(bucket_mb, use_ring=use_ring, fused=fused)
        self.wire_dtype = wire_dtype

    def _reduce_bucket(self, flat_c, bucket, lane_alive, residual):
        if self.wire_dtype is not None and bucket.compressible:
            if self.use_ring:
                return _ring_psum(flat_c, self.wire_dtype), None
            return lax.psum(flat_c.astype(self.wire_dtype), DATA_AXIS
                            ).astype(jnp.float32), None
        return lax.psum(flat_c, DATA_AXIS), None

    def _bucket_wire_bytes(self, bucket):
        if self.wire_dtype is not None and bucket.compressible:
            return bucket.length * _wire_bytes(self.wire_dtype)
        return bucket.length * 4


@_register("ef_bf16")
class EFBf16Merge(_BucketedBase):
    """Error-feedback bf16 merge: payload = contribution + residual is
    cast to bf16 per lane, the bf16 values cross the wire (direct psum
    on fully-manual rounds, f32-accumulating ppermute ring with bf16
    hops on Auto-inner meshes), and residual' = payload - decode(payload)
    carries the cast error to the next round. Residuals for dead lanes
    (no live contributor: quarantined or NaN-dropped) are zeroed."""

    needs_residual = True

    def _reduce_bucket(self, flat_c, bucket, lane_alive, residual):
        if not bucket.compressible:
            return lax.psum(flat_c, DATA_AXIS), None
        p = jnp.where(lane_alive, flat_c + residual, 0.0)
        q = p.astype(jnp.bfloat16)
        decoded = q.astype(jnp.float32)
        new_r = jnp.where(lane_alive, p - decoded, 0.0)
        if self.use_ring:
            s = _ring_psum(decoded, jnp.bfloat16)
        else:
            s = lax.psum(q, DATA_AXIS).astype(jnp.float32)
        return s, new_r

    def _bucket_wire_bytes(self, bucket):
        return bucket.length * (2 if bucket.compressible else 4)


@_register("ef_int8")
class EFInt8Merge(_BucketedBase):
    """Error-feedback int8 merge with a SHARED per-bucket scale: one
    cross-lane pmax fixes scale = max|payload| / 127, every lane ships
    round(payload/scale) — integer-valued and exactly representable in
    f32, so the wire collective is an ordinary f32 psum (safe on every
    mesh, no ring needed) whose sum is exact; decode multiplies the
    summed integers by the shared scale. residual' = payload -
    round(payload/scale)*scale is exact per lane. Dead lanes ship zeros
    and zero their residual."""

    needs_residual = True

    def _reduce_bucket(self, flat_c, bucket, lane_alive, residual):
        if not bucket.compressible:
            return lax.psum(flat_c, DATA_AXIS), None
        p = jnp.where(lane_alive, flat_c + residual, 0.0)
        amax = lax.pmax(jnp.max(jnp.abs(p)), DATA_AXIS)
        scale = amax / 127.0
        safe = jnp.where(scale > 0, scale, 1.0)
        q = jnp.where(scale > 0, jnp.round(p / safe), 0.0)
        decoded = q * scale
        new_r = jnp.where(lane_alive, p - decoded, 0.0)
        s = lax.psum(q, DATA_AXIS) * scale
        return s, new_r

    def _bucket_wire_bytes(self, bucket):
        # 1 byte/element + one broadcast f32 scale per bucket
        if bucket.compressible:
            return bucket.length + 4
        return bucket.length * 4


def strategy_by_name(name: str, wire_dtype: Any = None,
                     bucket_mb: float = 0.0, use_ring: bool = False,
                     fused: Optional[bool] = None) -> "MergeStrategy":
    """Instantiate a registered strategy by name (the sync-DP engine's
    explicit-merge knob). EF strategies get the default bucket cap when
    bucket_mb is unset."""
    if name not in MERGE_STRATEGIES:
        raise ValueError(f"unknown merge strategy {name!r}; registered: "
                         f"{sorted(MERGE_STRATEGIES)}")
    cls = MERGE_STRATEGIES[name]
    if getattr(cls, "needs_residual", False) and bucket_mb <= 0:
        bucket_mb = DEFAULT_EF_BUCKET_MB
    return cls(wire_dtype=wire_dtype, bucket_mb=bucket_mb,
               use_ring=use_ring, fused=fused)


def merge_comm_proxy(variables: PyTree, merge_dtype: Any = None,
                     bucket_mb: float = 0.0, compress: str = "none"
                     ) -> Dict[str, int]:
    """Module-level comm proxy for bench/tests: build the strategy the
    engine would pick for these knobs and report its deterministic
    per-round wire numbers."""
    strategy = make_strategy(merge_dtype=merge_dtype, bucket_mb=bucket_mb,
                             compress=compress)
    out = strategy.comm_proxy(variables)
    out["strategy"] = strategy.name
    return out


def register_strategy_cost(ledger, strategy: "MergeStrategy",
                           variables: PyTree) -> Dict[str, int]:
    """Register a strategy's wire plan as an analytic cost-ledger
    record (`merge.<strategy>`, kernel plane) built from the SAME
    comm_proxy numbers bench reports, then reconcile the two EXACTLY
    (metrics/ledger.py): the pure-counter payload bytes must match
    bit-for-bit, so the proxy and the ledger can never drift apart.
    Returns the proxy dict so callers keep the bucket/collective
    counts."""
    proxy = strategy.comm_proxy(variables)
    proxy["strategy"] = strategy.name
    program = f"merge.{strategy.name}"
    ledger.capture_analytic(
        program, "kernel",
        hbm_bytes=float(proxy["merge_payload_bytes"]),
        # one collective per bucket: the wire both reads and writes the
        # payload once per round, and the bucket count rides along as
        # the output-side descriptor so budgets pin it too
        output_bytes=int(proxy["merge_payload_bytes"]),
        argument_bytes=int(proxy["buckets_per_round"]))
    ledger.reconcile(program, "hbm_bytes",
                     proxy["merge_payload_bytes"], tolerance=0.0)
    return proxy


def register_merge_cost(ledger, variables: PyTree, merge_dtype: Any = None,
                        bucket_mb: float = 0.0, compress: str = "none"
                        ) -> Dict[str, int]:
    """Knob-level twin of register_strategy_cost for callers (bench,
    the budget lint) that hold engine knobs rather than a strategy."""
    strategy = make_strategy(merge_dtype=merge_dtype, bucket_mb=bucket_mb,
                             compress=compress)
    return register_strategy_cost(ledger, strategy, variables)
