"""Tensor parallelism — GSPMD sharding rules over the mesh `model` axis.

Net-new relative to the reference (SURVEY.md §2a: "Absent: tensor
parallelism..."). The idiomatic TPU mechanism is NOT manual collectives:
parameters get `NamedSharding` annotations (Megatron-style column/row
split per transformer block) and XLA's SPMD partitioner inserts the
all-reduces on ICI. One rule table drives both placement
(`shard_variables`) and jit constraints.

Rule format: (path_regex, PartitionSpec). First match wins; default is
full replication. Paths are '/'-joined flax param paths, e.g.
"layer_0/q/kernel".
"""

from __future__ import annotations

import re
from typing import Any, List, Sequence, Tuple

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from kubeml_tpu.parallel.mesh import MODEL_AXIS

PyTree = Any
Rules = Sequence[Tuple[str, P]]

# Megatron split for the transformer blocks (models/bert.py EncoderBlock
# AND models/gpt.py DecoderBlock — both use the q/k/v/out DenseGeneral +
# Dense_0/Dense_1 FFN layout, so one rule table covers both):
#   q/k/v DenseGeneral kernels [hidden, heads, head_dim] -> shard heads;
#   attention out DenseGeneral  [heads, head_dim, hidden] -> shard heads
#     (row-parallel: XLA inserts one psum after it);
#   FFN Dense_0 [hidden, ffn] -> column split; Dense_1 [ffn, hidden] ->
#     row split (again one psum);
#   token/position embeddings -> vocab/hidden kept replicated (tiny at
#     this scale; shard via an extra rule when they dominate).
TRANSFORMER_TP_RULES: List[Tuple[str, P]] = [
    (r".*/(q|k|v)/kernel$", P(None, MODEL_AXIS, None)),
    (r".*/(q|k|v)/bias$", P(MODEL_AXIS, None)),
    (r".*/out/kernel$", P(MODEL_AXIS, None, None)),
    (r".*/Dense_0/kernel$", P(None, MODEL_AXIS)),
    (r".*/Dense_0/bias$", P(MODEL_AXIS)),
    (r".*/Dense_1/kernel$", P(MODEL_AXIS, None)),
]
BERT_TP_RULES = TRANSFORMER_TP_RULES  # back-compat alias
GPT_TP_RULES = TRANSFORMER_TP_RULES


def spec_for(path: str, rules: Rules) -> P:
    for pattern, spec in rules:
        if re.match(pattern, path):
            return spec
    return P()


def _paths(tree: PyTree):
    return jax.tree_util.tree_map_with_path(
        lambda kp, x: "/".join(
            str(getattr(k, "key", getattr(k, "idx", k))) for k in kp),
        tree)


def tree_specs(tree: PyTree, rules: Rules) -> PyTree:
    """PartitionSpec pytree matching `tree` under `rules`."""
    return jax.tree_util.tree_map(
        lambda path: spec_for(path, rules), _paths(tree))


def shard_variables(variables: PyTree, mesh: Mesh, rules: Rules) -> PyTree:
    """Place a variable pytree onto the mesh per the rule table.

    Unmatched leaves are replicated. Leaves whose matched spec doesn't
    divide the dimension fall back to replication (e.g. 2 heads on a
    4-way model axis) — a warning-free degradation matching GSPMD's
    behavior of preferring correctness over forced sharding.
    """
    specs = tree_specs(variables, rules)

    def place(x, spec):
        for dim, name in enumerate(spec):
            if name is None:
                continue
            if dim >= x.ndim or x.shape[dim] % mesh.shape[name]:
                spec = P()
                break
        return jax.device_put(x, NamedSharding(mesh, spec))

    return jax.tree_util.tree_map(place, variables, specs)
