"""Decoder-only transformer (GPT-style) for causal language modeling.

Net-new relative to the reference (SURVEY.md §2a lists transformer and
long-context workloads as absent; its largest models are ResNet-34/VGG-11):
this is the framework's generative/long-context flagship, built on the same
attention primitive stack as BERT-tiny:

  - causal attention goes through ops.masked_attention(causal=True) — bf16
    QK^T/PV matmuls on the MXU, f32 softmax — which auto-dispatches to the
    pallas flash kernel on TPU (KV-block streaming, no O(T^2) HBM);
  - long-context execution: the SAME module runs under shard_map over the
    mesh `seq` axis, with the causal KV ring (parallel/ring_attention.py)
    or the ulysses all-to-all head-sharded scheme (parallel/ulysses.py)
    swapped in at the attention call — no chip ever holds the full
    sequence (forward_seq_parallel below);
  - pre-LN blocks, GELU MLPs, learned positional embeddings, weight-tied
    LM head (Embed.attend);
  - LayerNorm params stay float32; all matmuls bfloat16.

Training plugs into the standard engines through the KubeModel contract:
`loss` returns one value per SEQUENCE (mean over its real next-token
positions), so the K-avg weight averaging and the datapoint-weighted
validation aggregation (ml/pkg/train/util.go:100-122) treat a sequence
exactly like the reference treats one sample.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Optional

import flax.linen as nn
import jax
from kubeml_tpu import compat
import jax.numpy as jnp
import numpy as np
import optax
from jax import lax

from kubeml_tpu.models import register_model
from kubeml_tpu.models.base import InferenceInputError, KubeModel
from kubeml_tpu.parallel.tp import TRANSFORMER_TP_RULES
from kubeml_tpu.ops.attention import masked_attention

PAD_ID = 0


class DecoderBlock(nn.Module):
    hidden: int
    heads: int
    ffn: int
    dropout: float
    dtype: jnp.dtype
    # set to the mesh seq-axis name for sequence parallelism (see
    # models/bert.py EncoderBlock — same contract, causal variant)
    seq_axis: Optional[str] = None
    seq_impl: str = "ring"
    # KV-cache length for incremental decoding (None = no cache path)
    cache_len: Optional[int] = None
    # mesh model-axis name for MANUAL tensor parallelism (Megatron
    # column/row matmuls with hand-placed psums — parallel/manual.py);
    # composes with seq_axis (ring impl)
    tp_axis: Optional[str] = None
    # > 0: replace the dense FFN with a mixture-of-experts layer
    n_experts: int = 0
    moe_k: int = 2
    capacity_factor: float = 1.25
    ep_mesh: Any = None
    # mesh expert-axis name for MANUAL expert parallelism (inside an
    # already-manual shard_map, e.g. the GPipe pipeline — the GSPMD
    # ep_mesh constraints cannot cross a manual region)
    ep_axis: Optional[str] = None
    ep_impl: str = "replicated"    # 'replicated' | 'alltoall' (MoEFFN)
    # attention implementation: 'auto' (pallas flash kernel on TPU when
    # the [local] sequence tiles, jnp reference otherwise — applies to
    # BOTH the dense path and the seq-parallel ring, which is
    # differentiable), 'flash', or 'reference'
    attn_impl: str = "auto"
    flash_interpret: bool = False  # pallas interpreter (CPU tests)

    def _cached_attention(self, q, k, v, bias, offset):
        """Incremental decode: append this call's K/V into the block's
        cache at `offset` and attend over the whole cache.

        The cache lives in the flax 'cache' collection ([B, cache_len,
        H, D] per block, created on first decode apply); `bias` is the
        module-level [B, 1, Tq, cache_len] causal+validity bias.
        """
        B, _, H, D = k.shape
        k_cache = self.variable(
            "cache", "cached_k",
            lambda: jnp.zeros((B, self.cache_len, H, D), self.dtype))
        v_cache = self.variable(
            "cache", "cached_v",
            lambda: jnp.zeros((B, self.cache_len, H, D), self.dtype))
        k_cache.value = lax.dynamic_update_slice(
            k_cache.value, k.astype(self.dtype), (0, offset, 0, 0))
        v_cache.value = lax.dynamic_update_slice(
            v_cache.value, v.astype(self.dtype), (0, offset, 0, 0))
        from kubeml_tpu.ops.attention import multi_head_attention
        return multi_head_attention(q, k_cache.value, v_cache.value, bias)

    @nn.compact
    def __call__(self, h, pad_mask, train: bool, pos=None,
                 decode_bias=None, decode_offset=None):
        head_dim = self.hidden // self.heads
        x = nn.LayerNorm(dtype=jnp.float32)(h)
        if self.tp_axis is not None:
            from kubeml_tpu.parallel.manual import (TPHeadsDense,
                                                    validate_tp_geometry)
            if decode_offset is not None:
                raise ValueError("manual TP does not run the KV-cache "
                                 "decode path; decode with the dense "
                                 "module (same variables)")
            validate_tp_geometry(self.heads, self.ffn,
                                 compat.axis_size(self.tp_axis))
            mk_qkv = partial(TPHeadsDense, self.heads, head_dim,
                             self.tp_axis, self.dtype)
        else:
            mk_qkv = partial(nn.DenseGeneral, (self.heads, head_dim),
                             dtype=self.dtype)
        q = mk_qkv(name="q")(x)
        k = mk_qkv(name="k")(x)
        v = mk_qkv(name="v")(x)
        if self.seq_impl not in ("ring", "ulysses"):  # static field
            raise ValueError(f"unknown seq_impl {self.seq_impl!r}; "
                             f"expected 'ring' or 'ulysses'")
        if self.tp_axis is not None and self.seq_axis is not None \
                and self.seq_impl == "ulysses":
            raise ValueError(
                "tensor parallelism composes with seq_impl='ring' only "
                "(ulysses re-shards the head axis the TP split owns)")
        if decode_offset is not None:
            attn = self._cached_attention(q, k, v, decode_bias,
                                          decode_offset)
        elif self.seq_axis is not None and self.seq_impl == "ulysses":
            from kubeml_tpu.parallel.ulysses import ulysses_attention
            attn = ulysses_attention(q, k, v, kv_mask=pad_mask,
                                     causal=True, axis_name=self.seq_axis,
                                     impl=self.attn_impl,
                                     interpret=self.flash_interpret)
        elif self.seq_axis is not None:
            # causal KV ring: blocks rotate with their positions, the
            # per-block bias keeps position ordering globally correct
            from kubeml_tpu.ops.attention import ring_flash_eligible
            from kubeml_tpu.parallel.ring_attention import ring_attention
            use_flash = (ring_flash_eligible(q.shape[1])
                         if self.attn_impl == "auto"
                         else self.attn_impl == "flash")
            attn = ring_attention(q, k, v, q_pos=pos, kv_pos=pos,
                                  kv_mask=pad_mask, causal=True,
                                  axis_name=self.seq_axis,
                                  use_flash=use_flash,
                                  interpret=self.flash_interpret)
        else:
            attn = masked_attention(q, k, v, pad_mask, causal=True,
                                    impl=self.attn_impl,
                                    interpret=self.flash_interpret)
        # one scaffolding path; only the Dense constructors differ per
        # execution mode (manual-TP mirrors share the dense param tree
        # paths — checkpoint/merge parity). MoE FFNs are their own path
        # (experts shard over ep_axis/ep_mesh, never the TP split).
        if self.tp_axis is not None:
            from kubeml_tpu.parallel.manual import (TPColumnDense,
                                                    TPOutDense, TPRowDense)
            mk_out = partial(TPOutDense, self.heads, head_dim,
                             self.hidden, self.tp_axis, self.dtype)
            mk_d0 = partial(TPColumnDense, self.ffn, self.tp_axis,
                            self.dtype)
            mk_d1 = partial(TPRowDense, self.hidden, self.ffn,
                            self.tp_axis, self.dtype)
        else:
            mk_out = partial(nn.DenseGeneral, self.hidden, axis=(-2, -1),
                             dtype=self.dtype)
            mk_d0 = partial(nn.Dense, self.ffn, dtype=self.dtype)
            mk_d1 = partial(nn.Dense, self.hidden, dtype=self.dtype)
        attn = mk_out(name="out")(attn)
        attn = nn.Dropout(self.dropout, deterministic=not train)(attn)
        h = h + attn
        x = nn.LayerNorm(dtype=jnp.float32)(h)
        if self.n_experts > 0:
            if self.tp_axis is not None:
                raise ValueError("manual TP does not apply to MoE blocks "
                                 "(experts shard over the expert axis "
                                 "instead — ep_axis)")
            x = MoEFFN(self.hidden, self.ffn, self.n_experts,
                       k=self.moe_k, capacity_factor=self.capacity_factor,
                       ep_mesh=self.ep_mesh, ep_axis=self.ep_axis,
                       ep_impl=self.ep_impl, name="moe")(x, pad_mask)
        else:
            x = mk_d0(name="Dense_0")(x)
            x = nn.gelu(x)
            x = mk_d1(name="Dense_1")(x)
        x = nn.Dropout(self.dropout, deterministic=not train)(x)
        return h + x


class MoEFFN(nn.Module):
    """Mixture-of-experts FFN: flax parameter wrapper over the GShard
    dispatch/combine formulation in parallel/ep.py (same math the EP
    tests pin). The auxiliary load-balance loss is sown into the
    'intermediates' collection; GPTMoEMini.loss collects it."""

    d_model: int
    d_ff: int
    n_experts: int
    k: int = 2
    capacity_factor: float = 1.25
    ep_mesh: Any = None  # jax Mesh: shard experts over its `expert` axis
    # manual expert axis (mutually exclusive with ep_mesh): experts
    # shard over an already-manual mesh axis with a hand-placed psum —
    # parallel/manual.py ep_partial_ffn. This is what lets MoE blocks
    # run expert-sharded INSIDE the GPipe pipeline's shard_map.
    ep_axis: Optional[str] = None
    # manual-axis execution strategy: 'replicated' routes all tokens on
    # every lane and psums partial outputs (ep_partial_ffn — simple,
    # bandwidth-fine at small activations); 'alltoall' shards tokens
    # and router math over the expert axis too, exchanging slot
    # payloads with two all_to_alls (ep_alltoall_ffn — the scale-up
    # path; per-shard routing capacity, the SP x MoE semantics)
    ep_impl: str = "replicated"

    @nn.compact
    def __call__(self, h, pad_mask):
        from kubeml_tpu.parallel.ep import moe_apply
        if self.ep_impl not in ("replicated", "alltoall"):
            # validated on EVERY path (incl. GSPMD/dense, which ignore
            # the field) so a typo surfaces where it was written
            raise ValueError(f"unknown ep_impl {self.ep_impl!r}; "
                             "expected 'replicated' or 'alltoall'")
        d, f, e = self.d_model, self.d_ff, self.n_experts
        scale_in = 1.0 / np.sqrt(d)
        scale_out = 1.0 / np.sqrt(f)
        params = {
            "router": self.param(
                "router", nn.initializers.normal(scale_in), (d, e)),
            "wi": self.param(
                "wi", nn.initializers.normal(scale_in), (e, d, f)),
            "bi": self.param("bi", nn.initializers.zeros, (e, f)),
            "wo": self.param(
                "wo", nn.initializers.normal(scale_out), (e, f, d)),
            "bo": self.param("bo", nn.initializers.zeros, (e, d)),
        }
        B, T, D = h.shape
        # pad tokens are excluded from routing and capacity entirely —
        # unlike the dense FFN (row-independent), an unmasked MoE would
        # let padding displace real tokens from expert slots
        if self.ep_axis is not None:
            if self.ep_mesh is not None:
                raise ValueError("ep_axis (manual) and ep_mesh (GSPMD) "
                                 "are mutually exclusive")
            if e % compat.axis_size(self.ep_axis):
                raise ValueError(
                    f"{e} experts do not divide over a "
                    f"{compat.axis_size(self.ep_axis)}-way expert axis")
            from kubeml_tpu.parallel.ep import route_tokens
            x = h.reshape(B * T, D)
            if self.ep_impl == "alltoall":
                # token-sharded scale-up path: each lane routes ITS
                # 1/n token slice (per-shard capacity), exchanges slot
                # payloads with its experts' lanes, and the final
                # all_gather restores the replicated activation the
                # surrounding (replicated-token) trunk expects
                from kubeml_tpu.parallel.manual import ep_alltoall_ffn
                nl = compat.axis_size(self.ep_axis)
                if (B * T) % nl:
                    raise ValueError(
                        f"{B * T} tokens do not divide over a "
                        f"{nl}-way expert axis (ep_impl='alltoall')")
                tl = (B * T) // nl
                start = lax.axis_index(self.ep_axis) * tl
                x_local = lax.dynamic_slice_in_dim(x, start, tl)
                mask_local = lax.dynamic_slice_in_dim(
                    pad_mask.reshape(B * T), start, tl)
                dispatch, combine, aux = route_tokens(
                    params["router"], x_local, k=self.k,
                    capacity_factor=self.capacity_factor,
                    token_mask=mask_local)
                # per-shard aux averaged over lanes: the loss must stay
                # expert-axis-invariant like the replicated path's
                aux = jax.tree_util.tree_map(
                    lambda a: lax.psum(a, self.ep_axis) / nl, aux)
                y_local = ep_alltoall_ffn(
                    params["wi"], params["bi"], params["wo"],
                    params["bo"], dispatch, combine, x_local,
                    self.ep_axis, dtype=h.dtype)
                y = lax.all_gather(y_local, self.ep_axis, axis=0,
                                   tiled=True)
            elif self.ep_impl == "replicated":
                from kubeml_tpu.parallel.manual import ep_partial_ffn
                # routing is the SHARED preamble
                # (parallel/ep.route_tokens), replicated on every
                # expert lane — tokens are replicated over the expert
                # axis in the pipeline; only the expert FFNs shard
                dispatch, combine, aux = route_tokens(
                    params["router"], x, k=self.k,
                    capacity_factor=self.capacity_factor,
                    token_mask=pad_mask.reshape(B * T))
                y = ep_partial_ffn(params["wi"], params["bi"],
                                   params["wo"], params["bo"], dispatch,
                                   combine, x, self.ep_axis,
                                   dtype=h.dtype)
            else:  # membership validated at the top of __call__
                raise AssertionError(self.ep_impl)
        else:
            y, aux = moe_apply(params, h.reshape(B * T, D),
                               mesh=self.ep_mesh, k=self.k,
                               capacity_factor=self.capacity_factor,
                               token_mask=pad_mask.reshape(B * T))
        self.sow("intermediates", "moe_aux", aux)
        return y.reshape(B, T, D).astype(h.dtype)


class GPTModule(nn.Module):
    vocab_size: int = 8192
    max_len: int = 512
    hidden: int = 256
    layers: int = 4
    heads: int = 4
    ffn: int = 1024
    dropout: float = 0.1
    dtype: jnp.dtype = jnp.bfloat16
    seq_axis: Optional[str] = None  # sequence-parallel mode
    seq_impl: str = "ring"          # 'ring' | 'ulysses'
    n_experts: int = 0              # > 0: MoE FFN in every block
    moe_k: int = 2
    capacity_factor: float = 1.25
    ep_mesh: Any = None             # mesh whose `expert` axis shards experts
    ep_axis: Optional[str] = None   # manual expert axis (see MoEFFN)
    ep_impl: str = "replicated"     # 'replicated' | 'alltoall' (MoEFFN)
    tp_axis: Optional[str] = None   # manual tensor-parallel mode
    attn_impl: str = "auto"         # 'auto' | 'flash' | 'reference'
    flash_interpret: bool = False   # pallas interpreter (CPU tests)

    @nn.compact
    def __call__(self, x, train: bool = False, decode: bool = False,
                 cache_len: Optional[int] = None):
        # x: int32 token ids [B, T], pad id 0. With seq_axis set this runs
        # inside shard_map on the LOCAL [B, T/n] block (positions offset by
        # the shard index) and returns the LOCAL logits block — the causal
        # ring/all-to-all reconstructs exactly the dense forward.
        #
        # decode=True is the incremental KV-cache path (apply with
        # mutable=['cache']): this call's tokens are appended at the
        # cache's current index, attention runs against all cached
        # positions, and positions/validity advance — O(cache_len) per
        # step instead of a full re-forward. cache_len (static) sizes the
        # cache on the first decode call.
        B, T = x.shape
        n_shards = 1 if self.seq_axis is None else compat.axis_size(self.seq_axis)
        if (not decode) and T * n_shards > self.max_len:
            # trace-time guard; InferenceInputError (a ValueError) so
            # client-supplied overlong sequences surface as 4xx in serving
            raise InferenceInputError(
                f"sequence length {T * n_shards} exceeds "
                f"max_len {self.max_len}")
        pad_mask = (x != PAD_ID).astype(jnp.float32)
        decode_bias = offset = None
        if decode:
            if train or self.seq_axis is not None:
                raise ValueError("decode mode is eval-only and dense-only")
            if cache_len is None or cache_len > self.max_len:
                raise ValueError(f"decode needs cache_len <= max_len "
                                 f"{self.max_len}, got {cache_len}")
            index = self.variable("cache", "index",
                                  lambda: jnp.zeros((), jnp.int32))
            valid = self.variable("cache", "valid",
                                  lambda: jnp.zeros((B, cache_len),
                                                    jnp.float32))
            offset = index.value
            valid.value = lax.dynamic_update_slice(
                valid.value, pad_mask, (0, offset))
            # kv position j is attendable by query t (window position
            # offset+t) iff j holds a real token and j <= offset+t
            q_pos = offset + jnp.arange(T)
            kv_pos = jnp.arange(cache_len)
            causal = (kv_pos[None, :] <= q_pos[:, None]).astype(jnp.float32)
            keep = valid.value[:, None, None, :] * causal[None, None]
            from kubeml_tpu.ops.attention import NEG_INF
            decode_bias = (1.0 - keep) * NEG_INF
            pos_ids = q_pos
            index.value = offset + T
        elif self.seq_axis is None:
            pos_ids = jnp.arange(T)
        else:
            pos_ids = lax.axis_index(self.seq_axis) * T + jnp.arange(T)
        embed = nn.Embed(self.vocab_size, self.hidden, dtype=self.dtype,
                         name="tok_embed")
        h = embed(x)
        pos = nn.Embed(self.max_len, self.hidden, dtype=self.dtype,
                       name="pos_embed")(pos_ids[None, :])
        h = h + pos
        h = nn.Dropout(self.dropout, deterministic=not train)(h)
        for i in range(self.layers):
            h = DecoderBlock(self.hidden, self.heads, self.ffn, self.dropout,
                             self.dtype, seq_axis=self.seq_axis,
                             seq_impl=self.seq_impl,
                             cache_len=cache_len,
                             n_experts=self.n_experts, moe_k=self.moe_k,
                             capacity_factor=self.capacity_factor,
                             ep_mesh=self.ep_mesh, ep_axis=self.ep_axis,
                             ep_impl=self.ep_impl,
                             tp_axis=self.tp_axis,
                             attn_impl=self.attn_impl,
                             flash_interpret=self.flash_interpret,
                             name=f"layer_{i}")(h, pad_mask, train,
                                                pos=pos_ids,
                                                decode_bias=decode_bias,
                                                decode_offset=offset)
        h = nn.LayerNorm(dtype=jnp.float32)(h)
        # weight-tied LM head: logits = h @ tok_embed^T
        logits = embed.attend(h.astype(self.dtype))
        return logits.astype(jnp.float32)


def _prompt_lengths(window: np.ndarray) -> np.ndarray:
    """Per-row count of prompt tokens: one past the LAST non-pad token
    (interior pads count as prompt), 0 for an all-pad row — the shared
    definition for both generation paths. Callers clamp to >= 1 when
    indexing the conditioning logits (an all-pad row conditions on
    position 0)."""
    real = window != PAD_ID
    Tp = window.shape[1]
    return np.where(real.any(axis=1),
                    Tp - np.argmax(real[:, ::-1], axis=1), 0)


# serving KV storage modes (mirrors serve/pager.py KV_DTYPES): "f32"
# keeps pages in the module dtype — the bit-identity baseline; "int8"
# quantizes pages on write with per-page symmetric scales
_KV_DTYPES = ("f32", "int8")


def _int8_write_decode(pages, scales, layer, rows, write_page, write_off):
    """Quantize-on-write for one layer's decode rows [S, H, Dh] (f32)
    into int8 pages with PER-PAGE symmetric scales (the PR-7 EFInt8
    convention from parallel/merge.py: scale = amax/127, value =
    q * scale, zero-amax rows quantize to 0).

    A page's scale is the running amax of its written rows: each write
    maxes the row's amax into the stored scale and REQUANTIZES the
    page's existing rows under the new scale (factor = old/new <= 1 —
    exact when the scale is unchanged, bounded rounding otherwise, at
    most G-1 rescales per page). write_off == 0 RESETS the scale first:
    pages always fill from row 0 (decode, prefill, and CoW all write
    monotonically; a CoW split never lands on offset 0), so offset 0
    means first-ever write — which is also what makes a reused
    (evicted/retired) page's stale scale vanish without any host-side
    device work. old == 0 makes the factor 0, wiping stale int8 bytes
    in the same pass. Inactive lanes point at null page 0 with offset
    0, so their garbage resets/requants land identical zeros there —
    order-free, deterministic, never attended."""
    old = scales[layer, write_page]
    old = jnp.where(write_off == 0, 0.0, old)
    amax = jnp.max(jnp.abs(rows), axis=(1, 2))
    new = jnp.maximum(old, amax / 127.0)
    safe = jnp.where(new > 0, new, 1.0)
    factor = jnp.where(new > 0, old / safe, 0.0)
    requant = jnp.round(pages[layer, write_page].astype(jnp.float32)
                        * factor[:, None, None, None])
    pages = pages.at[layer, write_page].set(requant.astype(jnp.int8))
    qrow = jnp.clip(jnp.round(rows / safe[:, None, None]), -127, 127)
    pages = pages.at[layer, write_page, write_off].set(qrow.astype(jnp.int8))
    scales = scales.at[layer, write_page].set(new)
    return pages, scales


def _int8_write_prefill(pages, scales, layer, rows, write_pages,
                        write_offs, in_chunk):
    """Chunked twin of _int8_write_decode: C rows [C, H, Dh] (f32)
    land across up to two pages per chunk. Per-page amaxes accumulate
    with scatter-max (duplicate page indices reduce associatively —
    deterministic); the reset rule is the same, applied per page when
    any row in the chunk writes its offset 0. The requant scatter
    writes IDENTICAL bytes for duplicate page indices (the factor is a
    function of the page alone), so it too is order-free."""
    base = scales[layer]
    reset = jnp.zeros_like(base).at[write_pages].max(
        (write_offs == 0).astype(jnp.float32) * in_chunk)
    base = jnp.where(reset > 0, 0.0, base)
    amax = jnp.max(jnp.abs(rows), axis=(1, 2)) * in_chunk
    new = base.at[write_pages].max(amax / 127.0)
    safe = jnp.where(new > 0, new, 1.0)
    factor = jnp.where(new > 0, base / safe, 0.0)
    requant = jnp.round(pages[layer, write_pages].astype(jnp.float32)
                        * factor[write_pages][:, None, None, None])
    pages = pages.at[layer, write_pages].set(requant.astype(jnp.int8))
    qrows = jnp.clip(jnp.round(rows / safe[write_pages][:, None, None]),
                     -127, 127)
    pages = pages.at[layer, write_pages, write_offs].set(
        qrows.astype(jnp.int8))
    scales = scales.at[layer].set(new)
    return pages, scales


def build_paged_decode_step(module: GPTModule, kv_dtype: str = "f32",
                            attn_impl: str = "auto",
                            attn_interpret: bool = False):
    """One-token-per-slot decode step over a PAGED KV cache — the
    serving plane's persistent program (serve/engine.py).

    The module's own decode path (decode=True above) grows one
    contiguous [B, cache_len] cache per batch and retraces per (B, Tp,
    n_new) shape — fine for offline generate(), wrong for serving where
    requests join and leave continuously. This builder re-expresses the
    SAME math (identical flax submodule kinds applied to the same
    parameter subtrees, the same NEG_INF bias convention, the same
    f32-softmax attention primitive) as a single fixed-shape step:

      step(params, k_pages, v_pages, k_scales, v_scales, valid_pages,
           tokens[S], pos[S], page_tables[S, Pmax],
           write_page[S], write_off[S], active[S], temps[S],
           key_data[S, 2], copy_src[S], copy_dst[S], poison[S])
        -> (next_tokens[S], bad[S], k_pages, v_pages, k_scales,
            v_scales, valid_pages)

    kv_dtype selects the page storage mode (serve/pager.py KV_DTYPES):
    "f32" keeps pages in the module dtype and the step is IEEE-identical
    to the pre-scale program (the scale lanes ride along untouched, so
    the step signature — and the two-compile pin — is uniform across
    modes); "int8" quantizes K/V rows on write with per-page symmetric
    scales (_int8_write_decode) and the attention dequantizes inside the
    kernel. attn_impl/attn_interpret forward to ops/pallas
    paged_attention — the context read streams pages through the page
    table on TPU instead of materializing a contiguous [S, C, H, D]
    gather, which is the decode bandwidth attack this builder exists
    for; the 'gather' fallback is the old chain verbatim.

    Every per-request quantity is DATA (the kavg worker-mask trick), so
    slot membership changes never recompile. Inactive slots compute
    garbage rows whose K/V scatter lands on the reserved null page 0
    with validity 0 — written but never attended. Each active slot
    consumes its token at position pos (prompt tokens one per step
    during its prefill phase, then its own previous output) and the
    returned row is its next-token pick: greedy at temps<=0, else
    categorical over logits/temp keyed by that slot's own key_data —
    per-(request, position) keys, so sampling is independent of which
    other requests happen to share the batch (bit-identity under
    continuous batching, proven in tests/test_serving.py).

    copy_src/copy_dst are the prefix cache's COPY-ON-WRITE lane: before
    anything else, page copy_src[s] is duplicated into page copy_dst[s]
    (K, V, and validity) for every slot. A slot about to write into a
    page it shares with other streams gets a private copy this way —
    inside the SAME dispatch as the write, so CoW costs zero extra
    programs and the compile count stays pinned at two (prefill +
    decode). Slots with nothing to split pass 0 -> 0, a no-op through
    the null page.

    bad[S] is the ON-DEVICE NON-FINITE GUARD (the kavg merge guard's
    serving twin): 1.0 for an active row whose logits went non-finite
    this step. The check runs BEFORE the never-emit-PAD mask (which
    puts a legitimate -inf in every row) and flagged rows are
    where-selected to zeros before sampling — per LANE, so one
    poisoned stream never perturbs its neighbours' math and the host
    can terminate just that slot. poison[S] is the fault-injection
    lane driving it deterministically (faults.py serve_nan_logits): a
    raised lane forces that row non-finite on device, through the same
    guard a genuinely poisoned checkpoint would trip.

    Slots are rows: no cross-slot reduction exists anywhere in the
    step, which is what makes concurrent decode bit-identical to
    running the same requests one at a time.
    """
    if module.n_experts or module.seq_axis is not None \
            or module.tp_axis is not None:
        raise ValueError(
            "paged decode serves dense GPT modules only (no MoE, "
            "sequence-parallel, or manual-TP variants)")
    if kv_dtype not in _KV_DTYPES:
        raise ValueError(
            f"kv_dtype must be one of {_KV_DTYPES}, got {kv_dtype!r}")
    quantized = kv_dtype == "int8"
    heads, hidden = module.heads, module.hidden
    head_dim = hidden // heads
    dtype = module.dtype
    from kubeml_tpu.ops.attention import NEG_INF
    from kubeml_tpu.ops.pallas.paged_attention import paged_attention
    tok_embed = nn.Embed(module.vocab_size, hidden, dtype=dtype)
    pos_embed = nn.Embed(module.max_len, hidden, dtype=dtype)
    ln = nn.LayerNorm(dtype=jnp.float32)
    qkv = nn.DenseGeneral((heads, head_dim), dtype=dtype)
    out_proj = nn.DenseGeneral(hidden, axis=(-2, -1), dtype=dtype)
    ffn_in = nn.Dense(module.ffn, dtype=dtype)
    ffn_out = nn.Dense(hidden, dtype=dtype)

    def step(params, k_pages, v_pages, k_scales, v_scales, valid_pages,
             tokens, pos, page_tables, write_page, write_off, active,
             temps, key_data, copy_src, copy_dst, poison):
        S = tokens.shape[0]
        G = valid_pages.shape[1]
        C = page_tables.shape[1] * G
        # copy-on-write splits first: the gather of copy_src pages
        # happens before any scatter in this dispatch (functional
        # update semantics), so splitting a page and reusing its id are
        # safe in the same step. 0 -> 0 rows are null-page no-ops.
        # Scales are page metadata and split with their page.
        k_pages = k_pages.at[:, copy_dst].set(k_pages[:, copy_src])
        v_pages = v_pages.at[:, copy_dst].set(v_pages[:, copy_src])
        k_scales = k_scales.at[:, copy_dst].set(k_scales[:, copy_src])
        v_scales = v_scales.at[:, copy_dst].set(v_scales[:, copy_src])
        valid_pages = valid_pages.at[copy_dst].set(valid_pages[copy_src])
        h = tok_embed.apply({"params": params["tok_embed"]}, tokens[:, None])
        h = h + pos_embed.apply({"params": params["pos_embed"]},
                                pos[:, None])
        # this token's validity, written BEFORE the gather so a slot's
        # first token attends to itself (offset-0 decode semantics of
        # the contiguous path). Inactive slots write 0 to the null page.
        tok_valid = active * (tokens != PAD_ID).astype(jnp.float32)
        valid_pages = valid_pages.at[write_page, write_off].set(tok_valid)
        ctx_valid = valid_pages[page_tables].reshape(S, C)
        causal = (jnp.arange(C)[None, :] <= pos[:, None]) \
            .astype(jnp.float32)
        bias = (1.0 - ctx_valid * causal)[:, None, None, :] * NEG_INF
        for i in range(module.layers):
            p = params[f"layer_{i}"]
            x = ln.apply({"params": p["LayerNorm_0"]}, h)
            q = qkv.apply({"params": p["q"]}, x)
            k = qkv.apply({"params": p["k"]}, x)
            v = qkv.apply({"params": p["v"]}, x)
            if quantized:
                k_pages, k_scales = _int8_write_decode(
                    k_pages, k_scales, i, k[:, 0].astype(jnp.float32),
                    write_page, write_off)
                v_pages, v_scales = _int8_write_decode(
                    v_pages, v_scales, i, v[:, 0].astype(jnp.float32),
                    write_page, write_off)
            else:
                k_pages = k_pages.at[i, write_page, write_off].set(
                    k[:, 0].astype(dtype))
                v_pages = v_pages.at[i, write_page, write_off].set(
                    v[:, 0].astype(dtype))
            attn = paged_attention(
                q, k_pages[i], v_pages[i], k_scales[i], v_scales[i],
                page_tables, bias, quantized=quantized,
                compute_dtype=dtype, impl=attn_impl,
                interpret=attn_interpret)
            attn = out_proj.apply({"params": p["out"]}, attn)
            h = h + attn
            x = ln.apply({"params": p["LayerNorm_1"]}, h)
            x = ffn_in.apply({"params": p["Dense_0"]}, x)
            x = nn.gelu(x)
            x = ffn_out.apply({"params": p["Dense_1"]}, x)
            h = h + x
        h = ln.apply({"params": params["LayerNorm_0"]}, h)
        logits = tok_embed.apply(
            {"params": params["tok_embed"]}, h.astype(dtype),
            method=tok_embed.attend).astype(jnp.float32)[:, 0]
        # fault lane: a raised poison row goes non-finite here, BEFORE
        # the guard — injection and genuine weight poison trip the same
        # path (where-select, never 0*NaN: that would stay NaN)
        logits = jnp.where(poison[:, None] > 0, jnp.nan, logits)
        # non-finite guard, per lane. Must run BEFORE the PAD mask
        # below writes a legitimate -inf into every row; flagged rows
        # are sanitized to zeros so argmax/categorical stay well-defined
        # (their pick is discarded by the host and forced to 0 anyway).
        bad = active * (1.0 - jnp.all(
            jnp.isfinite(logits), axis=-1).astype(jnp.float32))
        logits = jnp.where(bad[:, None] > 0,
                           jnp.zeros_like(logits), logits)
        logits = logits.at[:, PAD_ID].set(-jnp.inf)  # never emit PAD

        def pick_one(kd, lg, t):
            greedy = jnp.argmax(lg, axis=-1).astype(jnp.int32)
            safe_t = jnp.where(t > 0, t, 1.0)
            sampled = jax.random.categorical(
                jax.random.wrap_key_data(kd), lg / safe_t).astype(jnp.int32)
            return jnp.where(t > 0, sampled, greedy)

        nxt = jax.vmap(pick_one)(key_data, logits, temps)
        nxt = jnp.where(bad > 0, 0, nxt)
        return nxt, bad, k_pages, v_pages, k_scales, v_scales, valid_pages

    return step


def build_paged_prefill_step(module: GPTModule, chunk: int,
                             kv_dtype: str = "f32",
                             attn_impl: str = "auto",
                             attn_interpret: bool = False):
    """Chunked prefill over the paged KV cache: C prompt tokens for ONE
    slot per dispatch — the serving plane's second (and last) persistent
    program (serve/engine.py).

    Without this, prompts ride the decode step one token per dispatch: a
    512-token prompt costs ~512 full-batch dispatches before its first
    sampled token, and every co-resident stream pays the queueing. This
    program bulk-writes a fixed-size chunk of prompt KV instead:

      prefill(params, k_pages, v_pages, k_scales, v_scales, valid_pages,
              tokens[C], pos[C], page_table[Pmax],
              write_pages[C], write_offs[C], in_chunk[C])
        -> (k_pages, v_pages, k_scales, v_scales, valid_pages)

    kv_dtype / attn_impl / attn_interpret mirror
    build_paged_decode_step: "int8" quantizes chunk rows on write
    (_int8_write_prefill) and the paged-attention context read
    dequantizes them; "f32" leaves the scale lanes inert and the
    program IEEE-identical to the pre-scale one.

    The chunk size C is static (one compile, amortized forever); real
    chunk length is DATA — prompts shorter than C pad the tail with
    in_chunk = 0 rows whose writes land on the null page 0 with validity
    0, so prompt lengths never recompile. No logits, no sampling: the
    LAST prompt token always goes through the decode step (which samples
    the first output), keeping this program shape-free of the vocab and
    the emission path bit-identical to token-by-token prefill.

    Bit-identity with the decode-step prefill it replaces: queries are
    the chunk rows, context is the slot's whole page table, and the bias
    keeps kv position j for query position p iff valid[j] * (j <= p) —
    the same mask the decode step applies one row at a time. Chunk
    tokens' K/V (and validity) are written BEFORE the gather, exactly
    like the decode step's write-then-attend, so within-chunk causal
    attention sees the same bytes token-by-token dispatches would have
    produced; positions after p inside the chunk are excluded by the
    causal term just as they would not yet exist in the sequential
    schedule.
    """
    if chunk < 1:
        raise ValueError(f"prefill chunk must be >= 1, got {chunk}")
    if module.n_experts or module.seq_axis is not None \
            or module.tp_axis is not None:
        raise ValueError(
            "paged prefill serves dense GPT modules only (no MoE, "
            "sequence-parallel, or manual-TP variants)")
    if kv_dtype not in _KV_DTYPES:
        raise ValueError(
            f"kv_dtype must be one of {_KV_DTYPES}, got {kv_dtype!r}")
    quantized = kv_dtype == "int8"
    heads, hidden = module.heads, module.hidden
    head_dim = hidden // heads
    dtype = module.dtype
    from kubeml_tpu.ops.attention import NEG_INF
    from kubeml_tpu.ops.pallas.paged_attention import paged_attention
    tok_embed = nn.Embed(module.vocab_size, hidden, dtype=dtype)
    pos_embed = nn.Embed(module.max_len, hidden, dtype=dtype)
    ln = nn.LayerNorm(dtype=jnp.float32)
    qkv = nn.DenseGeneral((heads, head_dim), dtype=dtype)
    out_proj = nn.DenseGeneral(hidden, axis=(-2, -1), dtype=dtype)
    ffn_in = nn.Dense(module.ffn, dtype=dtype)
    ffn_out = nn.Dense(hidden, dtype=dtype)

    def prefill(params, k_pages, v_pages, k_scales, v_scales,
                valid_pages, tokens, pos, page_table, write_pages,
                write_offs, in_chunk):
        G = valid_pages.shape[1]
        C = page_table.shape[0] * G
        h = tok_embed.apply({"params": params["tok_embed"]}, tokens[None, :])
        h = h + pos_embed.apply({"params": params["pos_embed"]},
                                pos[None, :])
        # chunk validity lands before the gather (write-then-attend,
        # like the decode step); pad-tail rows write 0 to the null page
        tok_valid = in_chunk * (tokens != PAD_ID).astype(jnp.float32)
        valid_pages = valid_pages.at[write_pages, write_offs].set(tok_valid)
        ctx_valid = valid_pages[page_table].reshape(C)
        causal = (jnp.arange(C)[None, :] <= pos[:, None]) \
            .astype(jnp.float32)                      # [chunk, C]
        bias = (1.0 - ctx_valid[None, :] * causal)[None, None] * NEG_INF
        for i in range(module.layers):
            p = params[f"layer_{i}"]
            x = ln.apply({"params": p["LayerNorm_0"]}, h)
            q = qkv.apply({"params": p["q"]}, x)
            k = qkv.apply({"params": p["k"]}, x)
            v = qkv.apply({"params": p["v"]}, x)
            if quantized:
                k_pages, k_scales = _int8_write_prefill(
                    k_pages, k_scales, i, k[0].astype(jnp.float32),
                    write_pages, write_offs, in_chunk)
                v_pages, v_scales = _int8_write_prefill(
                    v_pages, v_scales, i, v[0].astype(jnp.float32),
                    write_pages, write_offs, in_chunk)
            else:
                k_pages = k_pages.at[i, write_pages, write_offs].set(
                    k[0].astype(dtype))
                v_pages = v_pages.at[i, write_pages, write_offs].set(
                    v[0].astype(dtype))
            attn = paged_attention(
                q, k_pages[i], v_pages[i], k_scales[i], v_scales[i],
                page_table[None], bias, quantized=quantized,
                compute_dtype=dtype, impl=attn_impl,
                interpret=attn_interpret)
            attn = out_proj.apply({"params": p["out"]}, attn)
            h = h + attn
            x = ln.apply({"params": p["LayerNorm_1"]}, h)
            x = ffn_in.apply({"params": p["Dense_0"]}, x)
            x = nn.gelu(x)
            x = ffn_out.apply({"params": p["Dense_1"]}, x)
            h = h + x
        return k_pages, v_pages, k_scales, v_scales, valid_pages

    return prefill


def build_paged_multi_step_decode(module: GPTModule, steps: int,
                                  kv_dtype: str = "f32",
                                  attn_impl: str = "auto",
                                  attn_interpret: bool = False):
    """K decode step bodies fused into ONE jitted dispatch — the
    serving plane's third persistent program (serve/engine.py), used
    only in the all-decode steady state.

    PR 15 cut decode HBM traffic; the dominant remaining per-token cost
    is the host round-trip per dispatch. This builder lax.scans the
    EXACT step function from build_paged_decode_step K times inside one
    program, so the steady state pays one dispatch per K tokens
    (dispatches_per_token == 1/K) with zero change to the math:

      multi(params, k_pages, v_pages, k_scales, v_scales, valid_pages,
            tokens[S], pos[S], page_tables[S, Pmax], live[S],
            temps[S], seeds[S], eos_ids[S], budgets[S])
        -> (out_tokens[K, S], out_bad[K, S], k_pages, v_pages,
            k_scales, v_scales, valid_pages)

    Bit-identity with K single dispatches is BY CONSTRUCTION, not by
    argument: each scan iteration calls the single-step function with
    inputs computed exactly as the engine's host loop computes them —
    write_page/write_off from the page table and position, the
    per-(seed, position) sampling key from seeds and the running pos —
    and a lane that finishes mid-window (EOS, token budget, or the
    non-finite guard) flips its `live` flag to 0, after which every
    subsequent iteration masks its row to the same all-zeros inputs an
    UNOCCUPIED slot presents (tokens 0, pos 0, null-page write, temp 0,
    zero key). Early exit is data, so K never recompiles per finish
    pattern. eos_ids carries -1 for requests without an EOS id;
    budgets carries each lane's REMAINING token budget. The host walks
    out_tokens row by row and stops at each lane's terminal condition
    — discarded tail picks are garbage-by-design, exactly like an
    inactive slot's pick in the single-step program.

    Joins, CoW splits, chunked-prefill progress, multi-generation
    drains, and fault injection all fall back to the single-step
    program in the scheduler — this program never sees them.
    """
    steps = int(steps)
    if steps < 2:
        raise ValueError(
            f"multi-step decode needs steps >= 2 (1 is the single-step "
            f"program), got {steps}")
    step = build_paged_decode_step(module, kv_dtype, attn_impl,
                                   attn_interpret)

    def multi(params, k_pages, v_pages, k_scales, v_scales, valid_pages,
              tokens, pos, page_tables, live, temps, seeds, eos_ids,
              budgets):
        S = tokens.shape[0]
        G = valid_pages.shape[1]
        Pmax = page_tables.shape[1]
        rows = jnp.arange(S)
        zero_i = jnp.zeros(S, jnp.int32)
        zero_f = jnp.zeros(S, jnp.float32)

        def body(carry, _):
            (tokens, pos, live, emitted,
             k_pages, v_pages, k_scales, v_scales, valid_pages) = carry
            on = live > 0
            pi = jnp.clip(pos // G, 0, Pmax - 1)
            write_page = jnp.where(on, page_tables[rows, pi], 0)
            write_off = jnp.where(on, pos % G, 0)
            # the engine's per-(request, position) key, built on device:
            # uint32(seed), uint32(pos) — byte-identical to the host's
            key_data = jnp.where(
                on[:, None],
                jnp.stack([seeds, pos.astype(jnp.uint32)], axis=1),
                jnp.zeros((S, 2), jnp.uint32))
            (nxt, bad, k_pages, v_pages, k_scales, v_scales,
             valid_pages) = step(
                params, k_pages, v_pages, k_scales, v_scales,
                valid_pages,
                jnp.where(on, tokens, 0), jnp.where(on, pos, 0),
                page_tables, write_page, write_off,
                live.astype(jnp.float32), jnp.where(on, temps, 0.0),
                key_data, zero_i, zero_i, zero_f)
            emitted = emitted + live
            done = (((nxt == eos_ids) & (eos_ids >= 0))
                    | (emitted >= budgets)
                    | (bad > 0)).astype(jnp.int32)
            tokens = jnp.where(on, nxt, tokens)
            pos = jnp.where(on, pos + 1, pos)
            live = live * (1 - done)
            return (tokens, pos, live, emitted, k_pages, v_pages,
                    k_scales, v_scales, valid_pages), (nxt, bad)

        init = (tokens, pos, live, jnp.zeros(S, jnp.int32),
                k_pages, v_pages, k_scales, v_scales, valid_pages)
        carry, (out_tokens, out_bad) = lax.scan(
            body, init, None, length=steps)
        (_, _, _, _, k_pages, v_pages, k_scales, v_scales,
         valid_pages) = carry
        return (out_tokens, out_bad, k_pages, v_pages, k_scales,
                v_scales, valid_pages)

    return multi


def build_paged_spec_verify_step(module: GPTModule,
                                 draft_module: GPTModule,
                                 steps: int, window: int,
                                 kv_dtype: str = "f32",
                                 attn_impl: str = "auto",
                                 attn_interpret: bool = False):
    """Draft-propose + target-verify + rollback-replay in ONE jitted
    dispatch — the serving plane's fourth (and last) persistent
    program (serve/engine.py).

    Speculative decoding (Leviathan et al. 2023): a small DRAFT model
    proposes K tokens per slot; the TARGET model scores all K+1
    positions teacher-forced (the chunked-prefill trick — proposed
    tokens are a chunk whose logits we keep); the accepted run is the
    longest prefix where the target's own pick matches the proposal,
    and the position after it gets the target's pick as a free bonus
    token. Emitted tokens are therefore ALWAYS the target's picks under
    the engine's per-(seed, position) keys — acceptance only decides
    how many survive per dispatch, so output is bit-identical to the
    non-speculative program at ANY temperature, not just greedy.

      verify(params, draft_params, k_pages, v_pages, k_scales,
             v_scales, valid_pages, window_toks[S, W], pos[S],
             page_tables[S, Pmax], live[S], temps[S], seeds[S],
             wlen[S])
        -> (picks[K+1, S], bads[K+1, S], accepted[S], k_pages,
            v_pages, k_scales, v_scales, valid_pages)

    window_toks[s, :pos[s]+1] is slot s's full context (prompt +
    emitted tokens); wlen[s] = min(K+1, remaining budget) caps how many
    verify steps lane s may run. The STATELESS draft re-forwards the
    whole window per proposed token (greedy, PAD masked) — no draft KV
    cache, so the draft needs no pager, no catch-up after rejection,
    and no fifth program.

    Rollback is DATA, in the same dispatch, as two passes over the same
    single-step function the decode program uses. Pass 1 teacher-forces
    all wlen steps from the INPUT slab, committing every write (within-
    window attention needs them) and keeping the picks; the resulting
    slab is discarded. Pass 2 re-scans from the input slab with the
    write mask narrowed to steps <= accepted — so the returned slab
    holds exactly the writes a never-proposed run would have made.
    Exactness (including int8 page scales, which requantize
    sequentially on write and so cannot be row-restored) follows
    because acceptance is a PREFIX and the causal mask hides positions
    beyond a step's own: every accepted step sees the identical context
    in both passes, hence writes identical bytes, hence the sequential
    int8 requant chain replays exactly. The 2x target compute on
    accepted steps is the price of bit-exact rollback; rejected steps'
    pass-2 lanes mask to the null page like unoccupied slots.
    """
    steps = int(steps)
    window = int(window)
    if steps < 1:
        raise ValueError(f"speculative decode needs steps >= 1, "
                         f"got {steps}")
    if draft_module.n_experts or draft_module.seq_axis is not None \
            or draft_module.tp_axis is not None:
        raise ValueError(
            "speculative draft must be a dense GPT module (no MoE, "
            "sequence-parallel, or manual-TP variants)")
    if draft_module.vocab_size != module.vocab_size:
        raise ValueError(
            f"draft vocab ({draft_module.vocab_size}) must match the "
            f"target vocab ({module.vocab_size})")
    if window < 2 or window > module.max_len \
            or window > draft_module.max_len:
        raise ValueError(
            f"verify window must be in [2, min(target max_len "
            f"{module.max_len}, draft max_len {draft_module.max_len})], "
            f"got {window}")
    step = build_paged_decode_step(module, kv_dtype, attn_impl,
                                   attn_interpret)

    def verify(params, draft_params, k_pages, v_pages, k_scales,
               v_scales, valid_pages, window_toks, pos, page_tables,
               live, temps, seeds, wlen):
        S, W = window_toks.shape
        G = valid_pages.shape[1]
        Pmax = page_tables.shape[1]
        rows = jnp.arange(S)
        zero_i = jnp.zeros(S, jnp.int32)
        zero_f = jnp.zeros(S, jnp.float32)

        # ---- draft proposes K tokens (greedy, full window re-forward
        # per token — stateless, so rejection needs no draft rollback)
        win = window_toks
        props = []
        for i in range(1, steps + 1):
            lg = draft_module.apply({"params": draft_params}, win)
            lg = lg[rows, jnp.clip(pos + i - 1, 0, W - 1)]
            lg = lg.at[:, PAD_ID].set(-jnp.inf)
            d = jnp.argmax(lg, axis=-1).astype(jnp.int32)
            win = win.at[rows, jnp.clip(pos + i, 0, W - 1)].set(d)
            props.append(d)
        proposals = jnp.stack(props)                       # [K, S]

        # teacher-forced inputs: the real token at pos, then proposals
        inputs = jnp.concatenate(
            [window_toks[rows, jnp.clip(pos, 0, W - 1)][None],
             proposals], axis=0)                           # [K+1, S]

        def run_pass(slabs, keep):
            # keep(j) -> int32 [S]: lane liveness at verify step j; a
            # masked lane presents the unoccupied-slot inputs, so its
            # writes land on the null page
            def body(carry, xs):
                (k_pages, v_pages, k_scales, v_scales,
                 valid_pages) = carry
                tok, j = xs
                on_i = keep(j)
                on = on_i > 0
                posj = pos + j
                pi = jnp.clip(posj // G, 0, Pmax - 1)
                write_page = jnp.where(on, page_tables[rows, pi], 0)
                write_off = jnp.where(on, posj % G, 0)
                key_data = jnp.where(
                    on[:, None],
                    jnp.stack([seeds, posj.astype(jnp.uint32)], axis=1),
                    jnp.zeros((S, 2), jnp.uint32))
                (nxt, bad, k_pages, v_pages, k_scales, v_scales,
                 valid_pages) = step(
                    params, k_pages, v_pages, k_scales, v_scales,
                    valid_pages,
                    jnp.where(on, tok, 0), jnp.where(on, posj, 0),
                    page_tables, write_page, write_off,
                    on_i.astype(jnp.float32),
                    jnp.where(on, temps, 0.0),
                    key_data, zero_i, zero_i, zero_f)
                return (k_pages, v_pages, k_scales, v_scales,
                        valid_pages), (nxt, bad)
            return lax.scan(body, slabs,
                            (inputs, jnp.arange(steps + 1)))

        slabs0 = (k_pages, v_pages, k_scales, v_scales, valid_pages)
        # pass 1: verify — all wlen steps write, picks kept, slab dropped
        _, (picks, bads) = run_pass(
            slabs0, lambda j: live * (j < wlen).astype(jnp.int32))

        ok = ((picks[:steps] == proposals)
              & (bads[:steps] == 0)).astype(jnp.int32)     # [K, S]
        accepted = jnp.cumprod(ok, axis=0).sum(axis=0)
        accepted = jnp.maximum(
            jnp.minimum(accepted, wlen - 1), 0) * live

        # pass 2: rollback-as-replay — only steps <= accepted write
        final, _ = run_pass(
            slabs0,
            lambda j: live * ((j < wlen)
                              & (j <= accepted)).astype(jnp.int32))
        k_pages, v_pages, k_scales, v_scales, valid_pages = final
        return (picks, bads, accepted, k_pages, v_pages, k_scales,
                v_scales, valid_pages)

    return verify


def _lm_per_example(logits: jax.Array, x: jax.Array) -> jax.Array:
    """Per-sequence mean next-token cross-entropy [B] — THE LM loss
    definition shared by the dense and MoE model classes."""
    targets, tok_mask = _shift_targets(x)
    per_tok = optax.softmax_cross_entropy_with_integer_labels(
        logits, targets)
    denom = jnp.maximum(tok_mask.sum(axis=1), 1.0)
    return (per_tok * tok_mask).sum(axis=1) / denom


def _shift_targets(x: jax.Array):
    """(targets, token_mask) for next-token prediction on [B, T] ids.

    Position t predicts x[:, t+1]; a position contributes iff both it and
    its target are real (non-pad) tokens. The last position never has a
    target inside the window.
    """
    targets = jnp.concatenate(
        [x[:, 1:], jnp.full((x.shape[0], 1), PAD_ID, x.dtype)], axis=1)
    mask = ((x != PAD_ID) & (targets != PAD_ID)).astype(jnp.float32)
    return targets, mask


def _shift_targets_sp(x_local: jax.Array, axis_name: str):
    """Seq-parallel _shift_targets: each shard holds a [B, T/n] block.

    The block's last position targets the NEXT shard's first token,
    fetched with one ppermute around the ring (the cross-boundary
    prediction a local shift would drop). The global last position (last
    shard's last column) keeps dense semantics — the ring wraps shard
    0's first token to it, so it is explicitly masked out.
    """
    n = compat.axis_size(axis_name)
    idx = lax.axis_index(axis_name)
    nxt_first = lax.ppermute(x_local[:, :1], axis_name,
                             perm=[((s + 1) % n, s) for s in range(n)])
    targets = jnp.concatenate([x_local[:, 1:], nxt_first], axis=1)
    mask = ((x_local != PAD_ID) & (targets != PAD_ID)).astype(jnp.float32)
    last_col = jnp.where(idx == n - 1, 0.0, 1.0)
    mask = mask.at[:, -1].mul(last_col)
    return targets, mask


def _lm_per_example_sp(logits: jax.Array, x_local: jax.Array,
                       axis_name: str) -> jax.Array:
    """Seq-parallel _lm_per_example: the per-sequence mean reduces over
    the WHOLE sequence via psums of the local token-loss sum and count,
    so the result is seq-invariant (equal on every shard and equal to
    the dense loss) — the invariance the engine's vma-checked round
    requires."""
    targets, tok_mask = _shift_targets_sp(x_local, axis_name)
    per_tok = optax.softmax_cross_entropy_with_integer_labels(
        logits, targets)
    num = lax.psum((per_tok * tok_mask).sum(axis=1), axis_name)
    den = lax.psum(tok_mask.sum(axis=1), axis_name)
    return num / jnp.maximum(den, 1.0)


@register_model("gpt-mini")
class GPTMini(KubeModel):
    """~6M-param decoder-only LM (4 layers x 256 hidden x 4 heads)."""

    name = "gpt-mini"

    def build(self):
        return GPTModule()

    def init_variables(self, rng, sample_batch):
        return self.init_module.init(rng, sample_batch["x"], train=False)

    def apply_train(self, variables, x, rng, extra_mutable=()):
        mutable = [k for k in variables if k != "params"] \
            + list(extra_mutable)
        if mutable:
            logits, new_state = self.module.apply(
                variables, x, train=True, mutable=mutable,
                rngs={"dropout": rng})
            return logits, dict(new_state)
        logits = self.module.apply(variables, x, train=True,
                                   rngs={"dropout": rng})
        return logits, {}

    def loss(self, variables, batch, rng, sample_mask):
        """Per-sequence mean next-token cross-entropy, [B].

        With the module in seq-parallel mode (inside the engine's
        vma-checked round) x is the LOCAL [B, T/n] block and the loss
        reduces over the ring — identical value on every shard, equal to
        the dense loss. In pipeline-parallel mode
        (enable_pipeline_parallel) the decoder trunk runs the GPipe
        body over the mesh `stage` axis instead."""
        x = batch["x"]
        if getattr(self, "_pp_microbatches", 0):
            per_ex, aux = self._pp_forward_loss(variables, x, rng)
            return per_ex, {}
        logits, new_state = self.apply_train(variables, x, rng)
        if self.module.seq_axis is not None:
            return _lm_per_example_sp(logits, x, self.module.seq_axis), \
                new_state
        return _lm_per_example(logits, x), new_state

    # --------------------------------------------- pipeline-parallel training

    def enable_pipeline_parallel(self, n_stage: int,
                                 microbatches: int = 0) -> None:
        """Route TRAINING through the GPipe pipeline body over the mesh
        `stage` axis (called by the job for --pipeline-parallel > 1).

        The module stays DENSE: the loss stacks the per-layer params
        in-trace and each stage dynamic-slices its L/P consecutive
        layers via `lax.axis_index` — tree paths/shapes identical to
        the dense model (the manual-TP design, parallel/manual.py), so
        checkpoints, the K-avg merge, and inference apply unchanged.
        Runs inside the engine's all-axes-manual vma-checked round; vma
        backward assembles the stage psums for the replicated stacked
        params. Composes with expert parallelism (the blocks' ep_axis
        path — MoE trunks pipeline with per-microbatch routing), not
        with --seq-parallel/--tensor-parallel."""
        if self.module.seq_axis is not None or \
                getattr(self.module, "tp_axis", None) is not None:
            raise ValueError(
                "pipeline parallelism composes with expert parallelism "
                "only (not --seq-parallel/--tensor-parallel)")
        L = self.module.layers
        if L % n_stage:
            raise ValueError(
                f"{L} layers do not split over a {n_stage}-stage axis")
        self._pp_microbatches = int(microbatches) or 2 * int(n_stage)

    def _pp_forward_loss(self, variables, x, rng):
        """Pipelined per-sequence loss: embed/head replicated on every
        stage (they change activation shape — parallel/pp.py docstring),
        the L decoder blocks pipelined as `stage`-axis groups of L/P
        consecutive layers, pad masks and per-microbatch dropout keys
        riding along as pipeline consts. Equal to the dense loss up to
        bf16 noise (MoE: per-microbatch routing capacity, the standard
        pipelined-MoE semantics of forward_pipelined)."""
        from kubeml_tpu.parallel.manual import axis_slice
        from kubeml_tpu.parallel.mesh import STAGE_AXIS
        from kubeml_tpu.parallel.pp import pipeline_lane

        module = self.module
        params = variables["params"]
        B, T = x.shape
        if T > module.max_len:
            raise InferenceInputError(
                f"sequence length {T} exceeds max_len {module.max_len}")
        n_stage = compat.axis_size(STAGE_AXIS)
        per = module.layers // n_stage
        M = self._pp_microbatches
        if B % M:
            raise ValueError(
                f"batch {B} not divisible by {M} microbatches")
        moe = bool(module.n_experts)
        pad_mask = (x != PAD_ID).astype(jnp.float32)
        emb = params["tok_embed"]["embedding"].astype(module.dtype)
        h = emb[x] + params["pos_embed"]["embedding"][
            jnp.arange(T)].astype(module.dtype)[None]
        k_embed, k_blocks = jax.random.split(rng)
        if module.dropout > 0.0:  # the dense path's post-embed dropout
            keep = jax.random.bernoulli(k_embed, 1.0 - module.dropout,
                                        h.shape)
            h = jnp.where(keep, h / (1.0 - module.dropout), 0.0).astype(
                module.dtype)

        block = DecoderBlock(module.hidden, module.heads, module.ffn,
                             module.dropout, module.dtype,
                             n_experts=module.n_experts,
                             moe_k=module.moe_k,
                             capacity_factor=module.capacity_factor,
                             ep_axis=module.ep_axis, ep_impl=module.ep_impl,
                             attn_impl=module.attn_impl,
                             flash_interpret=module.flash_interpret)

        def stage_fn(p, act, const):
            mask, kdata = const  # [B/M, T] pad mask, [2] key data
            key = jax.random.wrap_key_data(kdata)
            sid = lax.axis_index(STAGE_AXIS)
            # vma-matching zero: aux accumulates stage-varying values
            aux0 = (act.ravel()[0].astype(jnp.float32) * 0.0)

            def body(carry, xs_l):
                a, aux = carry
                pj, j = xs_l
                # dropout key unique per (microbatch, global layer)
                kj = jax.random.fold_in(key, sid * per + j)
                if moe:
                    out, st = block.apply(
                        {"params": pj}, a, mask, True,
                        rngs={"dropout": kj}, mutable=["intermediates"])
                    out = out.astype(a.dtype)
                    aux = aux + jnp.asarray(
                        sum(jax.tree_util.tree_leaves(st)), jnp.float32)
                else:
                    out = block.apply({"params": pj}, a, mask, True,
                                      rngs={"dropout": kj})
                return (out, aux), None

            (act, aux), _ = lax.scan(body, (act, aux0),
                                     (p, jnp.arange(per)))
            return (act, aux) if moe else act

        # [L, ...] stacked layer params; this stage slices its group —
        # replicated full-size params, exactly the manual-TP layout
        stacked = jax.tree_util.tree_map(
            lambda *xs: jnp.stack(xs, axis=0),
            *[params[f"layer_{i}"] for i in range(module.layers)])
        local = jax.tree_util.tree_map(
            lambda leaf: axis_slice(leaf, STAGE_AXIS, 0), stacked)

        keys = jax.random.key_data(jax.random.split(k_blocks, M))
        hm = h.reshape(M, B // M, T, module.hidden)
        masks = pad_mask.reshape(M, B // M, T)
        ys, aux = pipeline_lane(stage_fn, local, hm, STAGE_AXIS,
                                has_aux=moe, consts=(masks, keys),
                                vma=True)
        h = ys.reshape(B, T, module.hidden)
        h = nn.LayerNorm(dtype=jnp.float32).apply(
            {"params": params["LayerNorm_0"]}, h)
        logits = (h.astype(module.dtype) @ emb.T).astype(jnp.float32)
        per_ex = _lm_per_example(logits, x)
        if moe:
            # mean per layer per microbatch — the pipelined analog of
            # the dense loss's sum(sown)/layers (forward_pipelined)
            per_ex = per_ex + self.aux_coef * aux / (module.layers * M)
        return per_ex, aux

    def metrics(self, variables, batch):
        x = batch["x"]
        logits = self.module.apply(variables, x, train=False)
        if self.module.seq_axis is not None:
            axis = self.module.seq_axis
            targets, tok_mask = _shift_targets_sp(x, axis)
        else:
            targets, tok_mask = _shift_targets(x)
        per_tok = optax.softmax_cross_entropy_with_integer_labels(
            logits, targets)
        hit = (jnp.argmax(logits, axis=-1) == targets).astype(jnp.float32)
        num_l, num_h = (per_tok * tok_mask).sum(axis=1), \
            (hit * tok_mask).sum(axis=1)
        den = tok_mask.sum(axis=1)
        if self.module.seq_axis is not None:
            num_l = lax.psum(num_l, axis)
            num_h = lax.psum(num_h, axis)
            den = lax.psum(den, axis)
        denom = jnp.maximum(den, 1.0)
        return {"loss": num_l / denom, "accuracy": num_h / denom}

    # job-surface parallelism (same table/dims as the BERT family: the
    # decoder blocks share the q/k/v/out + Dense_0/Dense_1 param layout;
    # base enable_seq_parallel handles the module clone)
    seq_batch_dims = {"x": 0}
    tp_rules = TRANSFORMER_TP_RULES

    def configure_optimizers(self, lr, epoch):
        return optax.adamw(lr, weight_decay=0.01)

    # ------------------------------------------------------------ inference


    def infer(self, variables, data: np.ndarray,
              max_new_tokens: int = 32) -> np.ndarray:
        """Greedy continuation of prompt id rows [B, Tp] (0 = pad).

        Serving entry point (the controller's /infer path calls this).
        Full-length prompts — the common serving case — take the KV-cache
        scan decode (`generate`, ~100x faster on tunneled backends);
        ragged rows fall back to the per-token window re-forward below,
        whose continuation starts at each row's own last real token.
        Generated tokens are never PAD_ID.
        """
        prompts = np.asarray(data, np.int32)
        Tp = prompts.shape[1]
        if Tp > self.module.max_len:
            # same contract as the module forward: the serving path must
            # not hand back a silently truncated prompt with zero
            # generated tokens
            raise InferenceInputError(
                f"prompt length {Tp} exceeds max_len {self.module.max_len};"
                " window the prompt to its last max_len tokens before"
                " calling infer()")
        # width-0 prompts go to the re-forward path, which pads the
        # window and produces the unconditioned continuation
        if 0 < Tp < self.module.max_len and \
                bool((_prompt_lengths(prompts) == Tp).all()):
            return self.generate(variables, prompts, max_new_tokens)
        return self._infer_reforward(variables, prompts, max_new_tokens)

    def _infer_reforward(self, variables, prompts: np.ndarray,
                         max_new_tokens: int) -> np.ndarray:
        """Ragged-prompt-safe greedy path: one fixed-shape jitted forward
        over the padded [B, max_len] window, re-dispatched per generated
        token (same executable every step — no per-step recompiles)."""
        B, Tp = prompts.shape
        T = min(self.module.max_len, Tp + max_new_tokens)
        if not hasattr(self, "_gen_step"):
            module = self.module

            @jax.jit
            def gen_step(variables, window, lengths):
                logits = module.apply(variables, window, train=False)
                # logits at each row's last real position predict the next
                nxt = jnp.take_along_axis(
                    logits, (lengths - 1)[:, None, None], axis=1)[:, 0]
                # generation never emits the pad token — it would truncate
                # the row (everything after a 0 reads as padding)
                nxt = nxt.at[:, PAD_ID].set(-jnp.inf)
                return jnp.argmax(nxt, axis=-1).astype(jnp.int32)

            self._gen_step = gen_step
        window = np.zeros((B, T), np.int32)
        window[:, :Tp] = prompts[:, :T]
        # interior 0s stay part of the prompt (never overwritten);
        # all-pad rows produce unconditioned continuations from position 0
        lengths = _prompt_lengths(window)
        variables = jax.device_put(variables)  # once, not per token
        for _ in range(T - Tp):
            nxt = np.asarray(self._gen_step(
                variables, jnp.asarray(window),
                jnp.asarray(np.maximum(lengths, 1))))
            grow = lengths < T
            window[np.arange(B), np.minimum(lengths, T - 1)] = np.where(
                grow, nxt, window[np.arange(B), np.minimum(lengths, T - 1)])
            lengths = np.minimum(lengths + grow, T)
        return window

    def generate(self, variables, prompts: np.ndarray,
                 max_new_tokens: int = 32, temperature: float = 0.0,
                 seed: int = 0) -> np.ndarray:
        """KV-cache generation: prefill once, then ONE jitted
        lax.scan of single-token decode steps — O(cache_len) work per
        token instead of infer()'s full re-forward, and the whole
        continuation is a single device program (no per-token host
        round-trips).

        Positions follow the training convention (pads hold positions):
        the [B, Tp] window is the prompt — interior/trailing pads are
        masked context — and the continuation occupies window positions
        Tp, Tp+1, ... for every row. The first generated token conditions
        on each row's LAST REAL token (matching infer()); for full-length
        prompts greedy generate() equals infer() exactly.

        temperature 0 = greedy; > 0 samples from softmax(logits/T).
        Generated tokens are never PAD_ID.
        """
        module = self.module
        prompts = np.asarray(prompts, np.int32)
        B, Tp = prompts.shape
        if Tp == 0:
            raise ValueError(
                "generate() needs at least one prompt column; pass an "
                "all-pad column (or use infer()) for unconditioned "
                "continuations")
        n_new = min(max_new_tokens, module.max_len - Tp)
        if n_new <= 0:
            return prompts
        cache_len = Tp + n_new
        key = (B, Tp, n_new, temperature != 0.0)
        if not hasattr(self, "_decode_cache"):
            self._decode_cache = {}
        if key not in self._decode_cache:
            sample = temperature != 0.0

            @jax.jit
            def run(params, prompts, lengths, temp, rng_key):
                # ---- prefill: whole prompt in one pass, cache populated
                logits, state = module.apply(
                    {"params": params}, prompts, decode=True,
                    cache_len=cache_len, mutable=["cache"])
                cache = state["cache"]
                first = jnp.take_along_axis(
                    logits, (lengths - 1)[:, None, None], axis=1)[:, 0]

                def pick(logits, k):
                    logits = logits.at[:, PAD_ID].set(-jnp.inf)
                    if sample:
                        return jax.random.categorical(
                            k, logits / temp).astype(jnp.int32)
                    return jnp.argmax(logits, axis=-1).astype(jnp.int32)

                # n_new picks total: one from the prefill logits, then
                # n_new - 1 single-token decode steps
                keys = jax.random.split(rng_key, n_new)
                tok = pick(first, keys[0])

                def body(carry, k):
                    tok, cache = carry
                    logits, state = module.apply(
                        {"params": params, "cache": cache}, tok[:, None],
                        decode=True, cache_len=cache_len,
                        mutable=["cache"])
                    return (pick(logits[:, 0], k), state["cache"]), tok

                (last, _), toks = lax.scan(body, (tok, cache), keys[1:])
                return jnp.concatenate(
                    [toks.T, last[:, None]], axis=1)  # [B, n_new]

            self._decode_cache[key] = run
        lengths = _prompt_lengths(prompts)
        new = np.asarray(self._decode_cache[key](
            jax.device_put(variables["params"]), jnp.asarray(prompts),
            jnp.asarray(np.maximum(lengths, 1)), jnp.float32(temperature),
            jax.random.PRNGKey(seed)))
        return np.concatenate([prompts, new], axis=1)

    # ----------------------------------------------------- pipeline parallel

    def forward_pipelined(self, variables, x, mesh, microbatches: int = 4):
        """Causal forward with the decoder trunk pipelined over the mesh
        `stage` axis (GPipe microbatching, parallel/pp.py).

        The embedding and LM head run outside the pipelined trunk (they
        change activation shape); the L decoder blocks split into
        `stage`-axis groups of L/P consecutive layers, each stage
        scanning its group. x: [B, T] full-length (pad-free) token rows
        with B divisible by `microbatches`. Returns [B, T, vocab] logits
        equal to the dense forward up to bf16 noise.

        MoE trunks pipeline too (round 2): routing capacity is computed
        PER MICROBATCH — the standard pipelined-MoE semantics, equal to
        the per-microbatch sequential reference, NOT bit-equal to the
        full-batch dense forward — and the per-block load-balance
        losses accumulate across real ticks, so the call returns
        (logits, aux) with aux normalized like the dense loss
        (mean per layer per microbatch).

        PP x EP (round 3): when the mesh also carries an expert axis
        (> 1), each stage's expert FFNs shard over it with the MANUAL
        expert path (parallel/manual.py ep_partial_ffn) — the pipeline's
        shard_map is fully manual, so the hand-placed expert psum
        composes where GSPMD ep_mesh constraints cannot. Routing stays
        replicated per expert lane; only expert FLOPs shard. Requires
        n_experts % expert-axis == 0.
        """
        from kubeml_tpu.parallel.mesh import EXPERT_AXIS, STAGE_AXIS
        from kubeml_tpu.parallel.pp import (pipeline_apply,
                                            stack_stage_params)

        module = self.module
        if module.n_experts and module.ep_mesh is not None:
            raise ValueError(
                "pipelined MoE shards experts over the mesh expert axis "
                "(manual path); construct the model without ep_mesh "
                "(GSPMD constraints cannot cross the stage shard_map)")
        n_expert = mesh.shape[EXPERT_AXIS]
        if n_expert > 1 and not module.n_experts:
            raise ValueError("the mesh has an expert axis but the model "
                             "has no experts")
        n_stage = mesh.shape[STAGE_AXIS]
        L = module.layers
        if L % n_stage:
            raise ValueError(f"{L} layers do not split over a "
                             f"{n_stage}-stage axis")
        per = L // n_stage
        x = jnp.asarray(x)
        B, T = x.shape
        M = microbatches
        if B % M:
            raise ValueError(f"batch {B} not divisible by {M} microbatches")
        if T > module.max_len:  # same guard as the dense forward
            raise ValueError(f"sequence length {T} exceeds max_len "
                             f"{module.max_len}")
        # this is an eager host API (like forward_seq_parallel): enforce
        # the documented pad-free precondition rather than silently
        # diverging from the dense forward
        if bool((x == PAD_ID).any()):
            raise ValueError("forward_pipelined requires pad-free rows "
                             "(the pipelined trunk runs without a pad "
                             "mask); use the dense forward for padded "
                             "batches")

        moe = bool(module.n_experts)
        # the module is part of the key: a clone (ep_impl, attn_impl,
        # ...) must not silently reuse the previous configuration's
        # compiled program (flax modules hash by configuration)
        key = (module, mesh, M)
        if not hasattr(self, "_pp_cache"):
            self._pp_cache = {}
        if key not in self._pp_cache:
            block = DecoderBlock(module.hidden, module.heads, module.ffn,
                                 0.0, module.dtype,
                                 n_experts=module.n_experts,
                                 moe_k=module.moe_k,
                                 capacity_factor=module.capacity_factor,
                                 ep_axis=(EXPERT_AXIS if n_expert > 1
                                          else None),
                                 ep_impl=module.ep_impl,
                                 attn_impl=module.attn_impl,
                                 flash_interpret=module.flash_interpret)

            def stage_fn(p, act):
                ones = jnp.ones(act.shape[:2], jnp.float32)
                if not moe:
                    def body(a, pj):
                        return block.apply({"params": pj}, a, ones,
                                           False), None

                    act, _ = lax.scan(body, act, p)
                    return act

                # MoE: each block sows its load-balance aux; routing
                # capacity is computed PER MICROBATCH (the pipelined
                # semantics — documented in the docstring)
                def body(carry, pj):
                    a, aux = carry
                    out, st = block.apply({"params": pj}, a, ones, False,
                                          mutable=["intermediates"])
                    # the MoE combine returns f32; the pipeline carries
                    # activations in the module compute dtype
                    out = out.astype(a.dtype)
                    aux = aux + jnp.asarray(
                        sum(jax.tree_util.tree_leaves(st)), jnp.float32)
                    return (out, aux), None

                (act, aux), _ = lax.scan(
                    body, (act, jnp.float32(0.0)), p)
                return act, aux

            def fwd(variables, x):
                params = variables["params"]
                B, T = x.shape
                # [P, per, ...]: stage s scans layers [s*per, (s+1)*per)
                stage_params = stack_stage_params([
                    stack_stage_params(
                        [params[f"layer_{s * per + j}"] for j in range(per)])
                    for s in range(n_stage)])
                emb = params["tok_embed"]["embedding"].astype(module.dtype)
                h = emb[x] + params["pos_embed"]["embedding"][
                    jnp.arange(T)].astype(module.dtype)[None]
                h = h.reshape(M, B // M, T, module.hidden)
                out = pipeline_apply(stage_fn, stage_params, h, mesh,
                                     has_aux=moe)
                h, aux = out if moe else (out, None)
                h = h.reshape(B, T, module.hidden)
                ln = nn.LayerNorm(dtype=jnp.float32)
                h = ln.apply({"params": params["LayerNorm_0"]}, h)
                logits = (h.astype(module.dtype) @ emb.T).astype(
                    jnp.float32)
                if moe:
                    # mean per layer per microbatch — the pipelined
                    # analog of the dense loss's sum(sown)/layers
                    return logits, aux / (module.layers * M)
                return logits

            self._pp_cache[key] = jax.jit(fwd)
        return self._pp_cache[key](variables, x)

    # ----------------------------------------------------- sequence parallel

    def forward_seq_parallel(self, variables, x, mesh, impl="ring"):
        """Long-context causal forward over the mesh `seq` axis.

        x: [B, T] with T divisible by the seq-axis size. Returns the full
        [B, T, vocab] logits, numerically equal to the dense forward,
        while each chip only ever holds a [B, T/n] sequence block (and the
        flash/ring paths never materialize O(T^2) scores).
        """
        from jax.sharding import PartitionSpec as P

        from kubeml_tpu.parallel.mesh import SEQ_AXIS

        if impl not in ("ring", "ulysses"):
            raise ValueError(f"unknown seq-parallel impl {impl!r}; "
                             f"expected 'ring' or 'ulysses'")
        n_seq = mesh.shape[SEQ_AXIS]
        if x.shape[1] % n_seq:
            raise ValueError(f"sequence length {x.shape[1]} not divisible "
                             f"by the seq-axis size {n_seq}")
        # module in the key for the same reason as _pp_cache: clones
        # must not reuse a stale compiled program
        key = (self.module, mesh, x.shape[1] // n_seq, impl)
        if not hasattr(self, "_sp_cache"):
            self._sp_cache = {}
        if key not in self._sp_cache:
            sp_module = self.module.clone(seq_axis=SEQ_AXIS, seq_impl=impl)

            def fwd(variables, x_local):
                return sp_module.apply(variables, x_local, train=False)

            # logits come back seq-sharded: out spec reassembles [B, T, V]
            self._sp_cache[key] = jax.jit(compat.shard_map(
                fwd, mesh=mesh, in_specs=(P(), P(None, SEQ_AXIS)),
                out_specs=P(None, SEQ_AXIS), check_vma=False))
        return self._sp_cache[key](variables, x)


@register_model("gpt-nano")
class GPTNano(GPTMini):
    """~60k-param 2-layer LM for the CPU tier: serving smoke tests and
    the bench closed-loop arm need a module whose paged decode step
    compiles in seconds, not minutes. Same architecture/param tree as
    gpt-mini, so everything that serves gpt-mini serves this."""

    name = "gpt-nano"

    def build(self):
        return GPTModule(vocab_size=512, max_len=64, hidden=32, layers=2,
                         heads=2, ffn=64, dropout=0.0)


@register_model("gpt-moe-mini")
class GPTMoEMini(GPTMini):
    """MoE variant of gpt-mini: 8 experts x 512-wide FFN, top-2 routing
    (GShard dispatch/combine from parallel/ep.py), same attention stack.

    Expert parallelism at model level: construct with
    `GPTMoEMini(ep_mesh=mesh)` to shard the expert-stacked FFN weights
    and the dispatch/combine intermediates over the mesh `expert` axis —
    GSPMD then materializes the token all-to-alls on ICI (EP_RULES in
    parallel/ep.py give the parameter placements).

    The router's load-balance auxiliary loss (Shazeer et al.) is sown by
    each block and added to every sequence's loss with weight
    `aux_coef`, so the K-avg/syncdp engines and the reference's
    loss-aggregation semantics need no special-casing.
    """

    name = "gpt-moe-mini"
    aux_coef = 0.01
    # round 3 lifts round 2's SP x MoE exclusion: sequences shard over
    # the seq axis with PER-SHARD routing — each shard routes its local
    # T/n tokens with capacity ceil((T_local/E) * factor), the standard
    # distributed-MoE semantics (routing groups follow the device
    # layout, exactly like the pipelined trunk routes per microbatch).
    # Equal to the dense forward whenever no expert overflows; under
    # overflow the drop pattern differs by grouping, not by correctness.
    # GSPMD ep_mesh cannot cross the manual seq shard_map; round 4 adds
    # the MANUAL expert axis instead (enable_expert_parallel /
    # --expert-parallel): experts shard inside the same manual round
    # via ep_partial_ffn, exactly matching the replicated-expert round
    # (tests/test_parallel_pp_ep.py::test_kavg_sp_ep_round_matches_sp_only).
    seq_batch_dims = {"x": 0}
    # job-level TP stays rejected too: the Megatron table would shard
    # only the attention stack while the expert FFNs (the bulk of the
    # params, under 'moe') stay replicated — use ep_mesh expert
    # parallelism for this family instead
    tp_rules = None

    def __init__(self, ep_mesh=None):
        self.ep_mesh = ep_mesh

    def _require_replicated_experts(self) -> None:
        # check the MODULE's ep_mesh (what actually executes), not just
        # the constructor arg — they can diverge after build()
        if self.ep_mesh is not None or \
                getattr(self.module, "ep_mesh", None) is not None:
            raise ValueError(
                "sequence-parallel MoE requires replicated experts: "
                "GSPMD ep_mesh constraints cannot cross the manual "
                "seq-axis shard_map (construct without ep_mesh)")

    def enable_seq_parallel(self, impl: str = "ring") -> None:
        self._require_replicated_experts()
        super().enable_seq_parallel(impl)

    def enable_pipeline_parallel(self, n_stage: int,
                                 microbatches: int = 0) -> None:
        # same constraint as SP: GSPMD ep_mesh constraints cannot cross
        # the manual stage shard_map — PP x EP uses the manual expert
        # axis (enable_expert_parallel) instead
        if self.ep_mesh is not None or \
                getattr(self.module, "ep_mesh", None) is not None:
            raise ValueError(
                "pipelined MoE requires replicated or manual-axis "
                "experts: GSPMD ep_mesh constraints cannot cross the "
                "manual stage shard_map (construct without ep_mesh; "
                "combine --pipeline-parallel with --expert-parallel "
                "for expert sharding)")
        super().enable_pipeline_parallel(n_stage, microbatches)

    def enable_tensor_parallel(self) -> None:
        # the module HAS a tp_axis field (shared DecoderBlock), so the
        # base hasattr check would accept it and fail only at trace
        # time inside the first round; reject at the job surface with
        # the same rationale as tp_rules=None above
        raise ValueError(
            "gpt-moe-mini does not support tensor parallelism (the "
            "Megatron split would leave the expert FFNs — the bulk of "
            "the params — replicated); use expert parallelism "
            "(ep_mesh) for this family")

    def build(self):
        return GPTModule(ffn=512, n_experts=8, ep_mesh=self.ep_mesh)

    def loss(self, variables, batch, rng, sample_mask):
        x = batch["x"]
        if getattr(self, "_pp_microbatches", 0):
            # pipelined MoE trunk: _pp_forward_loss already folds the
            # aux_coef-weighted load-balance aux into per_ex
            per_ex, _ = self._pp_forward_loss(variables, x, rng)
            return per_ex, {}
        logits, new_state = self.apply_train(
            variables, x, rng, extra_mutable=("intermediates",))
        sown = new_state.pop("intermediates", {})
        aux = sum(jax.tree_util.tree_leaves(sown)) / max(
            1, self.module.layers)
        if self.module.seq_axis is not None:
            # per-shard aux statistics average over the ring so the
            # per-example loss is seq-INVARIANT (the vma-checked round's
            # contract — see KAvgEngine.batch_seq_dims)
            axis = self.module.seq_axis
            aux = lax.psum(aux, axis) / compat.axis_size(axis)
            per_ex = _lm_per_example_sp(logits, x, axis)
        else:
            per_ex = _lm_per_example(logits, x)
        return per_ex + self.aux_coef * aux, new_state

    def forward_seq_parallel(self, variables, x, mesh, impl="ring"):
        """Long-context MoE forward over the mesh `seq` axis with
        PER-SHARD routing (class docstring). Requires replicated
        experts; delegates to the dense family's ring/ulysses driver."""
        self._require_replicated_experts()
        return super().forward_seq_parallel(variables, x, mesh, impl)
