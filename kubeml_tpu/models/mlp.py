"""Small MLP classifier — test workhorse + simplest user-model template."""

import flax.linen as nn
import jax.numpy as jnp

from kubeml_tpu.models import register_model
from kubeml_tpu.models.base import ClassifierModel


class MLPModule(nn.Module):
    hidden: int = 32
    num_classes: int = 10
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = x.reshape((x.shape[0], -1)).astype(self.dtype)
        x = nn.relu(nn.Dense(self.hidden, dtype=self.dtype)(x))
        x = nn.Dense(self.num_classes, dtype=self.dtype)(x)
        return x.astype(jnp.float32)


@register_model("mlp")
class MLP(ClassifierModel):
    name = "mlp"

    def __init__(self, hidden: int = 32, num_classes: int = 10):
        self.hidden = hidden
        self.num_classes = num_classes

    def build(self):
        return MLPModule(hidden=self.hidden, num_classes=self.num_classes)
