"""BERT-tiny encoder for sequence classification (SST-2 — BASELINE config 5).

Net-new relative to the reference (no transformer exists there; SURVEY.md
§2a lists transformer workloads as absent). Geometry follows the public
"BERT-tiny" point: 2 layers, hidden 128, 2 heads, FFN 512.

TPU-first:
  - attention goes through ops.masked_attention (bf16 matmuls, f32
    softmax), which auto-dispatches to the pallas flash kernel on TPU and
    the jnp reference path elsewhere; the ring-attention sequence-parallel
    path swaps in at the same primitive;
  - LayerNorm params stay float32; all matmuls bfloat16 (MXU);
  - padding flows as a [B, T] keep-mask with static shapes; each
    implementation composes its own additive bias from it
    (ops.attention.composed_bias is the semantics definition).
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import flax.linen as nn
import jax
from kubeml_tpu import compat
import jax.numpy as jnp
import optax
from jax import lax

from kubeml_tpu.models import register_model
from kubeml_tpu.models.base import ClassifierModel, InferenceInputError
from kubeml_tpu.parallel.tp import TRANSFORMER_TP_RULES
from kubeml_tpu.ops.attention import masked_attention

PAD_ID = 0


class EncoderBlock(nn.Module):
    hidden: int
    heads: int
    ffn: int
    dropout: float
    dtype: jnp.dtype
    # set to the mesh seq-axis name for sequence parallelism: the block
    # then runs inside shard_map with [B, T_local, ...] activations and
    # attention becomes the ppermute ring (parallel/ring_attention.py)
    # or, with seq_impl="ulysses", the all-to-all head-sharded scheme
    # (parallel/ulysses.py — needs heads % seq-axis == 0)
    seq_axis: Optional[str] = None
    seq_impl: str = "ring"
    # set to the mesh model-axis name for MANUAL tensor parallelism: the
    # block then runs inside a fully-manual shard_map with Megatron
    # column/row-parallel matmuls and hand-placed psums
    # (parallel/manual.py). Composes with seq_axis (ring impl).
    tp_axis: Optional[str] = None
    # attention implementation: 'auto' (flash on TPU when tiling allows,
    # for BOTH the dense path and the differentiable seq-parallel ring),
    # 'flash', or 'reference'
    attn_impl: str = "auto"
    flash_interpret: bool = False  # pallas interpreter (CPU tests)

    @nn.compact
    def __call__(self, h, pad_mask, train: bool, pos=None):
        head_dim = self.hidden // self.heads
        x = nn.LayerNorm(dtype=jnp.float32)(h)
        if self.tp_axis is not None:
            from kubeml_tpu.parallel.manual import (TPHeadsDense,
                                                    validate_tp_geometry)
            validate_tp_geometry(self.heads, self.ffn,
                                 compat.axis_size(self.tp_axis))
            mk_qkv = partial(TPHeadsDense, self.heads, head_dim,
                             self.tp_axis, self.dtype)
        else:
            mk_qkv = partial(nn.DenseGeneral, (self.heads, head_dim),
                             dtype=self.dtype)
        q = mk_qkv(name="q")(x)
        k = mk_qkv(name="k")(x)
        v = mk_qkv(name="v")(x)
        if self.seq_impl not in ("ring", "ulysses"):  # static field
            raise ValueError(f"unknown seq_impl {self.seq_impl!r}; "
                             f"expected 'ring' or 'ulysses'")
        if self.tp_axis is not None and self.seq_axis is not None \
                and self.seq_impl == "ulysses":
            raise ValueError(
                "tensor parallelism composes with seq_impl='ring' only "
                "(ulysses re-shards the head axis the TP split owns)")
        if self.seq_axis is not None and self.seq_impl == "ulysses":
            # long-context path B: two all-to-alls re-shard seq->heads,
            # stock full attention per head group (flash-eligible)
            from kubeml_tpu.parallel.ulysses import ulysses_attention
            attn = ulysses_attention(q, k, v, kv_mask=pad_mask,
                                     causal=False,
                                     axis_name=self.seq_axis,
                                     impl=self.attn_impl,
                                     interpret=self.flash_interpret)
        elif self.seq_axis is not None:
            # long-context path A: KV blocks rotate around the seq ring;
            # O(block) HBM on the flash path, O(T_local^2) on reference
            from kubeml_tpu.ops.attention import ring_flash_eligible
            from kubeml_tpu.parallel.ring_attention import ring_attention
            use_flash = (ring_flash_eligible(q.shape[1])
                         if self.attn_impl == "auto"
                         else self.attn_impl == "flash")
            attn = ring_attention(q, k, v, q_pos=pos, kv_pos=pos,
                                  kv_mask=pad_mask, causal=False,
                                  axis_name=self.seq_axis,
                                  use_flash=use_flash,
                                  interpret=self.flash_interpret)
        else:
            # auto-dispatch: pallas flash kernel on TPU, jnp ref on CPU
            attn = masked_attention(q, k, v, pad_mask,
                                    impl=self.attn_impl,
                                    interpret=self.flash_interpret)
        # one scaffolding path for both execution modes: only the three
        # Dense constructors differ (manual-TP mirrors share the dense
        # modules' param tree paths — checkpoint/merge parity)
        if self.tp_axis is not None:
            from kubeml_tpu.parallel.manual import (TPColumnDense,
                                                    TPOutDense, TPRowDense)
            mk_out = partial(TPOutDense, self.heads, head_dim,
                             self.hidden, self.tp_axis, self.dtype)
            mk_d0 = partial(TPColumnDense, self.ffn, self.tp_axis,
                            self.dtype)
            mk_d1 = partial(TPRowDense, self.hidden, self.ffn,
                            self.tp_axis, self.dtype)
        else:
            mk_out = partial(nn.DenseGeneral, self.hidden, axis=(-2, -1),
                             dtype=self.dtype)
            mk_d0 = partial(nn.Dense, self.ffn, dtype=self.dtype)
            mk_d1 = partial(nn.Dense, self.hidden, dtype=self.dtype)
        attn = mk_out(name="out")(attn)
        attn = nn.Dropout(self.dropout, deterministic=not train)(attn)
        h = h + attn
        x = nn.LayerNorm(dtype=jnp.float32)(h)
        x = mk_d0(name="Dense_0")(x)
        x = nn.gelu(x)
        x = mk_d1(name="Dense_1")(x)
        x = nn.Dropout(self.dropout, deterministic=not train)(x)
        return h + x


class BertModule(nn.Module):
    vocab_size: int = 30522
    max_len: int = 128
    hidden: int = 128
    layers: int = 2
    heads: int = 2
    ffn: int = 512
    num_classes: int = 2
    dropout: float = 0.1
    dtype: jnp.dtype = jnp.bfloat16
    seq_axis: Optional[str] = None  # sequence-parallel mode (see below)
    seq_impl: str = "ring"          # 'ring' | 'ulysses'
    tp_axis: Optional[str] = None   # manual tensor-parallel mode
    attn_impl: str = "auto"         # 'auto' | 'flash' | 'reference'
    flash_interpret: bool = False   # pallas interpreter (CPU tests)

    @nn.compact
    def __call__(self, x, train: bool = False):
        # x: int32 token ids [B, T], T <= max_len, pad id 0.
        # With seq_axis set, this runs inside shard_map: x is the LOCAL
        # [B, T/n] sequence block, positions are offset by the shard
        # index, attention rides the ppermute ring, and the mean-pool
        # reduces over the seq axis — so the module computes exactly the
        # global-sequence forward while no chip ever holds the full T.
        B, T = x.shape
        n_shards = 1 if self.seq_axis is None else compat.axis_size(self.seq_axis)
        if T * n_shards > self.max_len:  # static trace-time guard.
            # InferenceInputError (a ValueError) so the serving layer
            # returns 4xx when the overlong sequence came from a client
            raise InferenceInputError(
                f"sequence length {T * n_shards} exceeds max_len "
                f"{self.max_len}")
        pad_mask = (x != PAD_ID).astype(jnp.float32)
        if self.seq_axis is None:
            pos_ids = jnp.arange(T)
        else:
            pos_ids = lax.axis_index(self.seq_axis) * T + jnp.arange(T)
        h = nn.Embed(self.vocab_size, self.hidden, dtype=self.dtype,
                     name="tok_embed")(x)
        pos = nn.Embed(self.max_len, self.hidden, dtype=self.dtype,
                       name="pos_embed")(pos_ids[None, :])
        h = h + pos
        h = nn.Dropout(self.dropout, deterministic=not train)(h)
        for i in range(self.layers):
            h = EncoderBlock(self.hidden, self.heads, self.ffn, self.dropout,
                             self.dtype, seq_axis=self.seq_axis,
                             seq_impl=self.seq_impl, tp_axis=self.tp_axis,
                             attn_impl=self.attn_impl,
                             flash_interpret=self.flash_interpret,
                             name=f"layer_{i}")(h, pad_mask, train,
                                                pos=pos_ids)
        h = nn.LayerNorm(dtype=jnp.float32)(h)
        # masked mean-pool (robust without a trained [CLS]); in
        # seq-parallel mode the pool is a psum over the seq ring, after
        # which the logits are replicated across shards
        num = (h * pad_mask[..., None]).sum(axis=1)
        den = pad_mask.sum(axis=1)
        if self.seq_axis is not None:
            num = lax.psum(num, self.seq_axis)
            den = lax.psum(den, self.seq_axis)
        pooled = num / jnp.maximum(den, 1.0)[..., None]
        out = nn.Dense(self.num_classes, dtype=self.dtype,
                       name="classifier")(pooled.astype(self.dtype))
        return out.astype(jnp.float32)


@register_model("bert-tiny")
class BertTiny(ClassifierModel):
    name = "bert-tiny"
    num_classes = 2

    # job-surface parallelism: Megatron TP over the encoder blocks, and
    # ring/ulysses SP over the token dim of 'x' (the base
    # enable_seq_parallel serves any model declaring seq_batch_dims).
    # The classifier's per-example loss is already seq-invariant (the
    # module psums its mean-pool over the ring), so the engine's
    # seq-parallel round needs no loss changes for this family.
    seq_batch_dims = {"x": 0}
    tp_rules = TRANSFORMER_TP_RULES

    def build(self):
        return BertModule(num_classes=self.num_classes)

    def configure_optimizers(self, lr, epoch):
        return optax.adamw(lr, weight_decay=0.01)

    # --------------------------------------------- pipeline-parallel training

    def enable_pipeline_parallel(self, n_stage: int,
                                 microbatches: int = 0) -> None:
        """Route TRAINING through the GPipe body over the mesh `stage`
        axis (--pipeline-parallel; same design as the GPT family,
        models/gpt.py): the encoder trunk splits into stage-axis groups
        of L/P consecutive blocks, the module stays DENSE (per-layer
        params stacked in-trace, each stage axis_slices its group —
        tree paths/shapes unchanged, so checkpoints/merge/inference
        apply as-is), and vma backward assembles the stage psums."""
        if self.module.seq_axis is not None or \
                getattr(self.module, "tp_axis", None) is not None:
            raise ValueError(
                "pipeline parallelism composes with expert parallelism "
                "only (not --seq-parallel/--tensor-parallel)")
        L = self.module.layers
        if L % n_stage:
            raise ValueError(
                f"{L} layers do not split over a {n_stage}-stage axis")
        self._pp_microbatches = int(microbatches) or 2 * int(n_stage)

    def loss(self, variables, batch, rng, sample_mask):
        if getattr(self, "_pp_microbatches", 0):
            return self._pp_forward_loss(variables, batch, rng)
        return super().loss(variables, batch, rng, sample_mask)

    def _pp_forward_loss(self, variables, batch, rng):
        """Pipelined classifier loss: embed + final LN/pool/head run
        replicated on every stage; the L encoder blocks pipeline with
        pad masks and per-microbatch dropout keys riding as consts.
        Equal to the dense loss up to bf16 noise (pinned by
        tests/test_job.py's PP-vs-dense BERT history parity)."""
        from kubeml_tpu.parallel.manual import axis_slice
        from kubeml_tpu.parallel.mesh import STAGE_AXIS
        from kubeml_tpu.parallel.pp import pipeline_lane

        module = self.module
        params = variables["params"]
        x = batch["x"]
        B, T = x.shape
        if T > module.max_len:
            raise InferenceInputError(
                f"sequence length {T} exceeds max_len {module.max_len}")
        n_stage = compat.axis_size(STAGE_AXIS)
        per = module.layers // n_stage
        M = self._pp_microbatches
        if B % M:
            raise ValueError(
                f"batch {B} not divisible by {M} microbatches")
        pad_mask = (x != PAD_ID).astype(jnp.float32)
        emb = params["tok_embed"]["embedding"].astype(module.dtype)
        h = emb[x] + params["pos_embed"]["embedding"][
            jnp.arange(T)].astype(module.dtype)[None]
        k_embed, k_blocks = jax.random.split(rng)
        if module.dropout > 0.0:  # the dense path's post-embed dropout
            keep = jax.random.bernoulli(k_embed, 1.0 - module.dropout,
                                        h.shape)
            h = jnp.where(keep, h / (1.0 - module.dropout), 0.0).astype(
                module.dtype)

        block = EncoderBlock(module.hidden, module.heads, module.ffn,
                             module.dropout, module.dtype,
                             attn_impl=module.attn_impl,
                             flash_interpret=module.flash_interpret)

        def stage_fn(p, act, const):
            mask, kdata = const  # [B/M, T] pad mask, [2] key data
            key = jax.random.wrap_key_data(kdata)
            sid = lax.axis_index(STAGE_AXIS)

            def body(a, xs_l):
                pj, j = xs_l
                kj = jax.random.fold_in(key, sid * per + j)
                return block.apply({"params": pj}, a, mask, True,
                                   rngs={"dropout": kj}), None

            act, _ = lax.scan(body, act, (p, jnp.arange(per)))
            return act

        stacked = jax.tree_util.tree_map(
            lambda *xs: jnp.stack(xs, axis=0),
            *[params[f"layer_{i}"] for i in range(module.layers)])
        local = jax.tree_util.tree_map(
            lambda leaf: axis_slice(leaf, STAGE_AXIS, 0), stacked)

        keys = jax.random.key_data(jax.random.split(k_blocks, M))
        hm = h.reshape(M, B // M, T, module.hidden)
        masks = pad_mask.reshape(M, B // M, T)
        ys, _ = pipeline_lane(stage_fn, local, hm, STAGE_AXIS,
                              consts=(masks, keys), vma=True)
        h = ys.reshape(B, T, module.hidden)
        h = nn.LayerNorm(dtype=jnp.float32).apply(
            {"params": params["LayerNorm_0"]}, h)
        # masked mean-pool + classifier head, replicated (dense parity)
        num = (h * pad_mask[..., None]).sum(axis=1)
        den = pad_mask.sum(axis=1)
        pooled = num / jnp.maximum(den, 1.0)[..., None]
        logits = nn.Dense(module.num_classes, dtype=module.dtype).apply(
            {"params": params["classifier"]},
            pooled.astype(module.dtype)).astype(jnp.float32)
        per_ex = optax.softmax_cross_entropy_with_integer_labels(
            logits, batch["y"])
        return per_ex, {}

    def forward_seq_parallel(self, variables, x, mesh, impl="ring"):
        """Long-context forward over the mesh `seq` axis.

        x: [B, T] with T divisible by the seq-axis size; the same
        variables as the dense module (shapes are identical, only the
        execution is sharded). Returns [B, num_classes] logits equal to
        the dense forward — no chip ever materializes the full sequence
        or an O(T^2) score tensor.

        impl: 'ring' (ppermute KV rotation) or 'ulysses' (all-to-all
        head sharding; needs heads % seq-axis == 0).
        """
        from jax.sharding import PartitionSpec as P

        from kubeml_tpu.parallel.mesh import SEQ_AXIS

        if impl not in ("ring", "ulysses"):
            raise ValueError(f"unknown seq-parallel impl {impl!r}; "
                             f"expected 'ring' or 'ulysses'")
        n_seq = mesh.shape[SEQ_AXIS]
        if x.shape[1] % n_seq:
            raise ValueError(
                f"sequence length {x.shape[1]} not divisible by the "
                f"seq-axis size {n_seq}")
        # module in the key: a clone (attn_impl, ...) must not silently
        # reuse the previous configuration's compiled program
        key = (self.module, mesh, x.shape[1] // n_seq, impl)
        if not hasattr(self, "_sp_cache"):
            self._sp_cache = {}
        if key not in self._sp_cache:
            # clone copies every dense-module field, overriding only the
            # execution mode — dense/seq-parallel parity by construction
            sp_module = self.module.clone(seq_axis=SEQ_AXIS, seq_impl=impl)

            def fwd(variables, x_local):
                return sp_module.apply(variables, x_local, train=False)

            self._sp_cache[key] = jax.jit(compat.shard_map(
                fwd, mesh=mesh, in_specs=(P(), P(None, SEQ_AXIS)),
                out_specs=P(), check_vma=False))
        return self._sp_cache[key](variables, x)
