"""BERT-tiny encoder for sequence classification (SST-2 — BASELINE config 5).

Net-new relative to the reference (no transformer exists there; SURVEY.md
§2a lists transformer workloads as absent). Geometry follows the public
"BERT-tiny" point: 2 layers, hidden 128, 2 heads, FFN 512.

TPU-first:
  - attention goes through ops.masked_attention (bf16 matmuls, f32
    softmax), which auto-dispatches to the pallas flash kernel on TPU and
    the jnp reference path elsewhere; the ring-attention sequence-parallel
    path swaps in at the same primitive;
  - LayerNorm params stay float32; all matmuls bfloat16 (MXU);
  - padding flows as a [B, T] keep-mask with static shapes; each
    implementation composes its own additive bias from it
    (ops.attention.composed_bias is the semantics definition).
"""

from __future__ import annotations

import flax.linen as nn
import jax.numpy as jnp
import optax

from kubeml_tpu.models import register_model
from kubeml_tpu.models.base import ClassifierModel
from kubeml_tpu.ops.attention import masked_attention

PAD_ID = 0


class EncoderBlock(nn.Module):
    hidden: int
    heads: int
    ffn: int
    dropout: float
    dtype: jnp.dtype

    @nn.compact
    def __call__(self, h, pad_mask, train: bool):
        head_dim = self.hidden // self.heads
        x = nn.LayerNorm(dtype=jnp.float32)(h)
        q = nn.DenseGeneral((self.heads, head_dim), dtype=self.dtype,
                            name="q")(x)
        k = nn.DenseGeneral((self.heads, head_dim), dtype=self.dtype,
                            name="k")(x)
        v = nn.DenseGeneral((self.heads, head_dim), dtype=self.dtype,
                            name="v")(x)
        # auto-dispatch: pallas flash kernel on TPU, jnp reference on CPU
        attn = masked_attention(q, k, v, pad_mask)
        attn = nn.DenseGeneral(self.hidden, axis=(-2, -1), dtype=self.dtype,
                               name="out")(attn)
        attn = nn.Dropout(self.dropout, deterministic=not train)(attn)
        h = h + attn
        x = nn.LayerNorm(dtype=jnp.float32)(h)
        x = nn.Dense(self.ffn, dtype=self.dtype)(x)
        x = nn.gelu(x)
        x = nn.Dense(self.hidden, dtype=self.dtype)(x)
        x = nn.Dropout(self.dropout, deterministic=not train)(x)
        return h + x


class BertModule(nn.Module):
    vocab_size: int = 30522
    max_len: int = 128
    hidden: int = 128
    layers: int = 2
    heads: int = 2
    ffn: int = 512
    num_classes: int = 2
    dropout: float = 0.1
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = False):
        # x: int32 token ids [B, T], T <= max_len, pad id 0
        B, T = x.shape
        if T > self.max_len:  # static shape: trace-time guard, not lax.cond
            raise ValueError(
                f"sequence length {T} exceeds max_len {self.max_len}")
        pad_mask = (x != PAD_ID).astype(jnp.float32)
        h = nn.Embed(self.vocab_size, self.hidden, dtype=self.dtype,
                     name="tok_embed")(x)
        pos = nn.Embed(self.max_len, self.hidden, dtype=self.dtype,
                       name="pos_embed")(jnp.arange(T)[None, :])
        h = h + pos
        h = nn.Dropout(self.dropout, deterministic=not train)(h)
        for i in range(self.layers):
            h = EncoderBlock(self.hidden, self.heads, self.ffn, self.dropout,
                             self.dtype, name=f"layer_{i}")(h, pad_mask,
                                                            train)
        h = nn.LayerNorm(dtype=jnp.float32)(h)
        # masked mean-pool (robust without a trained [CLS])
        pooled = (h * pad_mask[..., None]).sum(axis=1) / \
            jnp.maximum(pad_mask.sum(axis=1), 1.0)[..., None]
        out = nn.Dense(self.num_classes, dtype=self.dtype,
                       name="classifier")(pooled.astype(self.dtype))
        return out.astype(jnp.float32)


@register_model("bert-tiny")
class BertTiny(ClassifierModel):
    name = "bert-tiny"
    num_classes = 2

    def build(self):
        return BertModule(num_classes=self.num_classes)

    def configure_optimizers(self, lr, epoch):
        return optax.adamw(lr, weight_decay=0.01)
