"""VGG-11 for CIFAR-100.

Capability parity with the reference example function
ml/experiments/kubeml/function_vgg11.py (torchvision VGG-11 used in the
max-accuracy / TTA app experiments). TPU-first: NHWC, bfloat16 compute,
float32 params; the classifier head is sized from the pooled feature map
instead of hardcoding 224x224 geometry, so 32x32 CIFAR inputs work without
the reference's implicit upscaling.
"""

from __future__ import annotations

import flax.linen as nn
import jax.numpy as jnp
import optax

from kubeml_tpu.models import register_model
from kubeml_tpu.models.base import ClassifierModel

# VGG-11 ("A") configuration: conv widths with 'M' max-pools between
_VGG11 = (64, "M", 128, "M", 256, 256, "M", 512, 512, "M", 512, 512, "M")


class VGGModule(nn.Module):
    num_classes: int = 100
    hidden: int = 4096
    dropout: float = 0.5
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = x.astype(self.dtype)
        for v in _VGG11:
            if v == "M":
                x = nn.max_pool(x, (2, 2), strides=(2, 2))
            else:
                x = nn.Conv(v, (3, 3), padding="SAME", dtype=self.dtype)(x)
                x = nn.relu(x)
        x = x.reshape((x.shape[0], -1))
        x = nn.relu(nn.Dense(self.hidden, dtype=self.dtype)(x))
        x = nn.Dropout(self.dropout, deterministic=not train)(x)
        x = nn.relu(nn.Dense(self.hidden, dtype=self.dtype)(x))
        x = nn.Dropout(self.dropout, deterministic=not train)(x)
        x = nn.Dense(self.num_classes, dtype=self.dtype)(x)
        return x.astype(jnp.float32)


@register_model("vgg11")
class VGG11(ClassifierModel):
    name = "vgg11"
    num_classes = 100

    def build(self):
        return VGGModule(num_classes=self.num_classes)

    def configure_optimizers(self, lr, epoch):
        return optax.sgd(lr, momentum=0.9)
