"""ResNet family — CIFAR and ImageNet variants.

Capability parity with the reference example functions
ml/experiments/kubeml/function_resnet34.py (torchvision ResNet-34 on
CIFAR-10, SGD + epoch-stepped LR at lines 51-60) and
ml/experiments/kubeml/resnet32.py:186-198 (CIFAR-style ResNet-32), plus the
BASELINE.json configs ResNet-18/CIFAR-10 and ResNet-50/Imagenette.

TPU-first choices (not a port of torchvision):
  - NHWC layout end-to-end (XLA's native conv layout on TPU);
  - bfloat16 compute, float32 params and batch statistics — convs/matmuls
    hit the MXU at full tile rate, statistics stay numerically safe;
  - BatchNorm via flax with `batch_stats` as a mutable collection; the
    K-avg engine averages the statistics across workers exactly like the
    reference averages them through RedisAI (ml/pkg/model/parallelSGD.go:
    40-52 handles the int64 num_batches_tracked the same way our engine
    truncates integer leaves);
  - a `cifar_stem` switch (3x3/stride-1, no max-pool) so 32x32 inputs keep
    spatial resolution — what the reference gets implicitly by feeding
    CIFAR through torchvision's 7x7 stem at reduced fidelity, done right
    here.
"""

from __future__ import annotations

from functools import partial
from typing import Sequence, Type

import flax.linen as nn
import jax.numpy as jnp
import optax

from kubeml_tpu.models import register_model
from kubeml_tpu.models.base import ClassifierModel

ModuleDef = Type[nn.Module]


class BasicBlock(nn.Module):
    filters: int
    strides: int
    conv: ModuleDef
    norm: ModuleDef

    @nn.compact
    def __call__(self, x):
        residual = x
        y = self.conv(self.filters, (3, 3), (self.strides, self.strides))(x)
        y = self.norm()(y)
        y = nn.relu(y)
        y = self.conv(self.filters, (3, 3), (1, 1))(y)
        y = self.norm(scale_init=nn.initializers.zeros)(y)
        if residual.shape != y.shape:
            residual = self.conv(self.filters, (1, 1),
                                 (self.strides, self.strides),
                                 name="proj")(residual)
            residual = self.norm(name="proj_norm")(residual)
        return nn.relu(y + residual)


class BottleneckBlock(nn.Module):
    filters: int
    strides: int
    conv: ModuleDef
    norm: ModuleDef

    @nn.compact
    def __call__(self, x):
        residual = x
        y = self.conv(self.filters, (1, 1), (1, 1))(x)
        y = self.norm()(y)
        y = nn.relu(y)
        y = self.conv(self.filters, (3, 3), (self.strides, self.strides))(y)
        y = self.norm()(y)
        y = nn.relu(y)
        y = self.conv(self.filters * 4, (1, 1), (1, 1))(y)
        y = self.norm(scale_init=nn.initializers.zeros)(y)
        if residual.shape != y.shape:
            residual = self.conv(self.filters * 4, (1, 1),
                                 (self.strides, self.strides),
                                 name="proj")(residual)
            residual = self.norm(name="proj_norm")(residual)
        return nn.relu(y + residual)


class ResNetModule(nn.Module):
    """Stage-configurable ResNet over NHWC inputs."""

    stage_sizes: Sequence[int]
    block: Type[nn.Module] = BasicBlock
    num_classes: int = 10
    width: int = 64
    cifar_stem: bool = True
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = False):
        conv = partial(nn.Conv, use_bias=False, padding="SAME",
                       dtype=self.dtype)
        norm = partial(nn.BatchNorm, use_running_average=not train,
                       momentum=0.9, epsilon=1e-5, dtype=self.dtype,
                       param_dtype=jnp.float32)
        x = x.astype(self.dtype)
        if self.cifar_stem:
            x = conv(self.width, (3, 3), (1, 1), name="stem")(x)
        else:
            x = conv(self.width, (7, 7), (2, 2), name="stem")(x)
        x = norm(name="stem_norm")(x)
        x = nn.relu(x)
        if not self.cifar_stem:
            x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="SAME")
        for i, n_blocks in enumerate(self.stage_sizes):
            filters = self.width * (2 ** i)
            for j in range(n_blocks):
                strides = 2 if i > 0 and j == 0 else 1
                x = self.block(filters, strides, conv, norm)(x)
        x = jnp.mean(x, axis=(1, 2))
        x = nn.Dense(self.num_classes, dtype=self.dtype)(x)
        return x.astype(jnp.float32)


class _ResNetBase(ClassifierModel):
    """Shared training recipe: SGD + momentum with epoch-stepped LR decay —
    the reference's ResNet recipe (function_resnet34.py lines 51-60 steps
    the LR off self.epoch). Epoch is traced, so the schedule is a where()."""

    lr_decay_epochs = (15, 25)
    lr_decay_factor = 0.1
    weight_decay = 5e-4

    def configure_optimizers(self, lr, epoch):
        factor = jnp.float32(1.0)
        for boundary in self.lr_decay_epochs:
            factor = factor * jnp.where(epoch >= boundary,
                                        self.lr_decay_factor, 1.0)
        return optax.chain(
            optax.add_decayed_weights(self.weight_decay),
            optax.sgd(lr * factor, momentum=0.9),
        )


@register_model("resnet18")
class ResNet18(_ResNetBase):
    name = "resnet18"
    num_classes = 10

    def build(self):
        return ResNetModule(stage_sizes=(2, 2, 2, 2), block=BasicBlock,
                            num_classes=self.num_classes)


@register_model("resnet34")
class ResNet34(_ResNetBase):
    name = "resnet34"
    num_classes = 10

    def build(self):
        return ResNetModule(stage_sizes=(3, 4, 6, 3), block=BasicBlock,
                            num_classes=self.num_classes)


@register_model("resnet50")
class ResNet50(_ResNetBase):
    name = "resnet50"
    # Imagenette = 10-class ImageNet subset (BASELINE config 3)
    num_classes = 10

    def build(self):
        return ResNetModule(stage_sizes=(3, 4, 6, 3), block=BottleneckBlock,
                            num_classes=self.num_classes, cifar_stem=False)


@register_model("resnet32")
class ResNet32(_ResNetBase):
    """Classic CIFAR ResNet-32 (He et al. section 4.2): 3 stages of 5
    blocks, 16/32/64 channels (reference resnet32.py:186-198)."""

    name = "resnet32"
    num_classes = 10

    def build(self):
        return ResNetModule(stage_sizes=(5, 5, 5), block=BasicBlock,
                            num_classes=self.num_classes, width=16)
