"""2-layer LSTM text classifier (AG-News — BASELINE.json config 4).

The reference never ran a recurrent model (SURVEY.md §5: "long-context /
sequence parallelism: absent entirely"); this is the BASELINE config
"2-layer LSTM on AG-News (recurrent step under jit, sync PS)".

TPU-first: the recurrence is a `lax.scan` over time (via flax nn.RNN), so
the whole unrolled sequence is ONE compiled loop with static shapes — no
Python-level time stepping. Embedding/gate matmuls run in bfloat16 on the
MXU; padding (token id 0) is masked out of the mean-pool so ragged
sequences batch with static shapes.
"""

from __future__ import annotations

import flax.linen as nn
import jax.numpy as jnp
import optax

from kubeml_tpu.models import register_model
from kubeml_tpu.models.base import ClassifierModel

PAD_ID = 0


class LSTMClassifierModule(nn.Module):
    vocab_size: int = 32000
    embed_dim: int = 128
    hidden_dim: int = 256
    num_layers: int = 2
    num_classes: int = 4
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = False):
        # x: int32 token ids [B, T]. Mask/lengths stay float32/int32 —
        # bf16 can't count past 256 exactly (8-bit mantissa).
        mask = (x != PAD_ID).astype(jnp.float32)  # [B, T]
        h = nn.Embed(self.vocab_size, self.embed_dim,
                     dtype=self.dtype)(x)
        lengths = jnp.maximum(mask.sum(axis=1).astype(jnp.int32), 1)
        for i in range(self.num_layers):
            h = nn.RNN(nn.OptimizedLSTMCell(self.hidden_dim,
                                            dtype=self.dtype),
                       name=f"lstm_{i}")(h, seq_lengths=lengths)
        # masked mean-pool over real tokens
        pooled = (h * mask[..., None].astype(h.dtype)).sum(axis=1) / \
            jnp.maximum(mask.sum(axis=1), 1.0)[..., None].astype(h.dtype)
        out = nn.Dense(self.num_classes, dtype=self.dtype)(pooled)
        return out.astype(jnp.float32)


@register_model("lstm")
class LSTMClassifier(ClassifierModel):
    name = "lstm"
    num_classes = 4

    def build(self):
        return LSTMClassifierModule(num_classes=self.num_classes)

    def configure_optimizers(self, lr, epoch):
        return optax.adam(lr)
