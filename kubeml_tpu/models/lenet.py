"""LeNet-5 for MNIST.

Capability parity with the reference example function
ml/experiments/kubeml/function_lenet.py (conv 6/16 + fc 120/84/10, SGD),
expressed as a flax module. Runs in bfloat16 compute / float32 params so the
convs land on the MXU.
"""

import flax.linen as nn
import jax.numpy as jnp
import optax

from kubeml_tpu.models import register_model
from kubeml_tpu.models.base import ClassifierModel


class LeNetModule(nn.Module):
    num_classes: int = 10
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = x.astype(self.dtype)
        if x.ndim == 3:
            x = x[..., None]  # [B, 28, 28] -> NHWC
        x = nn.Conv(6, (5, 5), padding="SAME", dtype=self.dtype)(x)
        x = nn.relu(x)
        x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = nn.Conv(16, (5, 5), padding="VALID", dtype=self.dtype)(x)
        x = nn.relu(x)
        x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = x.reshape((x.shape[0], -1))
        x = nn.relu(nn.Dense(120, dtype=self.dtype)(x))
        x = nn.relu(nn.Dense(84, dtype=self.dtype)(x))
        x = nn.Dense(self.num_classes, dtype=self.dtype)(x)
        return x.astype(jnp.float32)


@register_model("lenet")
class LeNet(ClassifierModel):
    name = "lenet"

    def build(self):
        return LeNetModule()

    def configure_optimizers(self, lr, epoch):
        # reference function_lenet.py uses SGD with momentum-free lr
        return optax.sgd(lr)
