"""Built-in model zoo + user-model base classes.

The reference ships example "functions" (user model files) for LeNet/MNIST,
ResNet-34/CIFAR-10, VGG-11, ResNet-32 (ml/experiments/kubeml/*.py). Here the
equivalents are first-class built-ins, plus the BASELINE.json configs
(ResNet-18, ResNet-50, 2-layer LSTM, BERT-tiny).
"""

from kubeml_tpu.models.base import KubeModel, KubeDataset

_BUILTIN = {}


def register_model(name):
    def deco(cls):
        _BUILTIN[name] = cls
        return cls
    return deco


def _load_zoo():
    import importlib
    for mod in ("lenet", "resnet", "vgg", "lstm", "bert", "gpt", "mlp"):
        try:
            importlib.import_module(f"kubeml_tpu.models.{mod}")
        except ModuleNotFoundError:
            pass


def get_builtin(name):
    """Resolve a built-in model class by name (lazy import of the zoo)."""
    _load_zoo()
    return _BUILTIN.get(name)


def builtin_names():
    _load_zoo()
    return sorted(_BUILTIN)


__all__ = ["KubeModel", "KubeDataset", "register_model", "get_builtin",
           "builtin_names"]
