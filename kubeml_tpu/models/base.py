"""User-facing model/dataset abstractions.

Parity with the reference's `KubeModel`/`KubeDataset`
(python/kubeml/kubeml/network.py:463-476, dataset.py:81-227), translated to
functional JAX. The reference's imperative hooks map as:

    reference KubeModel.init(model)        -> KubeModel.init_variables (or
                                              the default flax init)
    reference KubeModel.train(batch, idx)  -> KubeModel.loss (pure: returns
                                              per-example loss; the engine
                                              differentiates and steps)
    reference KubeModel.validate(batch)    -> KubeModel.metrics (pure,
                                              per-example values; engine does
                                              the datapoint-weighted average,
                                              ml/pkg/train/util.go:100-122)
    reference KubeModel.infer(data)        -> KubeModel.infer
    reference configure_optimizers(...)    -> same name, returns an optax
                                              GradientTransformation; called
                                              with (lr, epoch) every sync
                                              round (the reference resets
                                              optimizer state each round —
                                              network.py:208-217 — so a fresh
                                              transform per round is exact)

Models carry a flax `nn.Module`; variables are the flax variable dict
({'params': ..., 'batch_stats': ...}). All computation must be jit-safe.
"""

from __future__ import annotations

import abc
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax

PyTree = Any


class InferenceInputError(ValueError):
    """A model rejected the caller-supplied inference payload (bad shape,
    overlong prompt, ...). Serving layers translate exactly this type to
    the 4xx error envelope; any other exception from infer() stays a
    server fault (5xx)."""


class KubeModel(abc.ABC):
    """Base class a user model subclasses (or a built-in provides)."""

    #: name under which the model registers (for CLI `fn`/train lookup)
    name: str = ""

    #: tensor-parallel sharding rules (parallel.tp rule table). None =
    #: the model does not support TP; a job requesting --tensor-parallel
    #: on it is rejected at start. Transformer families set this to the
    #: shared Megatron table.
    tp_rules = None

    #: sequence-parallel batch layout: {batch key: dim index within the
    #: per-example shape carrying the sequence}, e.g. {"x": 0} for
    #: [B, T] token ids. None = no sequence-parallel support.
    seq_batch_dims = None

    def enable_seq_parallel(self, impl: str = "ring") -> None:
        """Switch the model's module into sequence-parallel execution
        (called by the job when --seq-parallel > 1).

        The default implementation serves every family that declares
        seq_batch_dims and whose module takes seq_axis/seq_impl (the
        transformer families); models without seq support inherit the
        rejection, and special cases (e.g. MoE) override with a curated
        message."""
        if self.seq_batch_dims is None:
            raise ValueError(
                f"function {self.name or type(self).__name__!r} does not "
                "support sequence parallelism")
        if impl not in ("ring", "ulysses"):
            raise ValueError(f"unknown seq-parallel impl {impl!r}; "
                             "expected 'ring' or 'ulysses'")
        from kubeml_tpu.parallel.mesh import SEQ_AXIS
        self._module = self.module.clone(seq_axis=SEQ_AXIS, seq_impl=impl)

    def enable_tensor_parallel(self) -> None:
        """Switch the model's module into MANUAL tensor-parallel execution
        (called by the job for fully-manual rounds — combined TP+SP).

        Served by every family whose module takes a `tp_axis` field
        (the transformer families — parallel/manual.py); others reject.
        Distinct from `tp_rules` (GSPMD placement): manual TP runs inside
        fully-manual shard_map rounds where GSPMD cannot."""
        if not hasattr(self.module, "tp_axis"):
            raise ValueError(
                f"function {self.name or type(self).__name__!r} does not "
                "support manual tensor parallelism")
        from kubeml_tpu.parallel.mesh import MODEL_AXIS
        self._module = self.module.clone(tp_axis=MODEL_AXIS)

    def enable_pipeline_parallel(self, n_stage: int,
                                 microbatches: int = 0) -> None:
        """Route TRAINING through a GPipe pipeline over the mesh `stage`
        axis (called by the job when --pipeline-parallel > 1). Served by
        families with a uniform pipelineable trunk (the transformer
        families); everything else rejects with a clear message."""
        raise ValueError(
            f"function {self.name or type(self).__name__!r} does not "
            "support pipeline parallelism (requires a uniform "
            "pipelineable trunk — the transformer families: GPT, "
            "BERT)")

    def enable_expert_parallel(self) -> None:
        """Switch the model's module into MANUAL expert-parallel execution
        inside the engine's fully-manual round (called by the job when
        --expert-parallel > 1; composes with sequence parallelism).

        Only MoE families (a module with an `ep_axis` field AND experts)
        serve this; everything else rejects with a clear message."""
        if not getattr(self.module, "n_experts", 0) or \
                not hasattr(self.module, "ep_axis"):
            raise ValueError(
                f"function {self.name or type(self).__name__!r} has no "
                "experts to shard (expert parallelism applies to MoE "
                "families like gpt-moe-mini)")
        if getattr(self.module, "ep_mesh", None) is not None:
            raise ValueError(
                "manual expert parallelism (--expert-parallel) and GSPMD "
                "ep_mesh are mutually exclusive (construct without "
                "ep_mesh)")
        if getattr(self.module, "ep_impl", "replicated") != "replicated":
            # the vma-checked training round requires the loss to be
            # expert-axis-INVARIANT; only the replicated-token dispatch
            # (ep_partial_ffn's psum) provides that. 'alltoall' serves
            # the pipelined/forward paths — reject rather than silently
            # override the constructed configuration
            raise ValueError(
                "the expert-parallel training round requires "
                "ep_impl='replicated' (the expert psum keeps the loss "
                "expert-axis-invariant); ep_impl='alltoall' serves the "
                "pipelined and forward paths only")
        from kubeml_tpu.parallel.mesh import EXPERT_AXIS
        # 'replicated' dispatch (ep_partial_ffn): the psum over the
        # expert axis makes activations and loss expert-axis-INVARIANT,
        # which the vma-checked training round requires; the same vma
        # backward that assembles manual-TP gradients then psums each
        # lane's partial expert-weight grads, keeping replicated params
        # in lockstep (parallel/manual.py design notes)
        self._module = self.module.clone(ep_axis=EXPERT_AXIS)

    def enable_expert_parallel_gspmd(self, mesh) -> None:
        """GSPMD expert parallelism for rounds whose inner axes stay
        AUTO — plain DP x EP, no SP/PP (called by the job when
        --expert-parallel > 1 without a manual round). The module's
        ep_mesh sharding constraints lay the expert-major intermediates
        over the mesh `expert` axis and XLA's SPMD partitioner
        materializes the token all-to-alls inside each DP lane
        (parallel/ep.moe_apply); the K-avg weight merge still psums
        over `data` only."""
        if not getattr(self.module, "n_experts", 0) or \
                not hasattr(self.module, "ep_mesh"):
            raise ValueError(
                f"function {self.name or type(self).__name__!r} has no "
                "experts to shard (expert parallelism applies to MoE "
                "families like gpt-moe-mini)")
        if getattr(self.module, "ep_axis", None) is not None:
            raise ValueError(
                "manual expert parallelism (ep_axis) and GSPMD ep_mesh "
                "are mutually exclusive")
        self._module = self.module.clone(ep_mesh=mesh)

    @abc.abstractmethod
    def build(self):
        """Return the flax nn.Module."""

    @property
    def module(self):
        if not hasattr(self, "_module") or self._module is None:
            self._module = self.build()
        return self._module

    @property
    def init_module(self):
        """The module used for variable init: the DENSE clone when the
        model is in sequence- or tensor-parallel mode — the collectives
        only exist inside shard_map, while init runs outside it
        (variable shapes are identical either way)."""
        m = self.module
        overrides = {}
        if getattr(m, "seq_axis", None) is not None:
            overrides["seq_axis"] = None
        if getattr(m, "tp_axis", None) is not None:
            overrides["tp_axis"] = None
        if getattr(m, "ep_axis", None) is not None:
            overrides["ep_axis"] = None
        return m.clone(**overrides) if overrides else m

    # ------------------------------------------------------------- lifecycle

    def init_variables(self, rng: jax.Array, sample_batch: PyTree) -> PyTree:
        """Initialize the flax variable dict from one example batch.

        Default assumes classification-style batches {'x': ..., 'y': ...}.
        """
        return self.init_module.init(rng, sample_batch["x"], train=False)

    # ------------------------------------------------------------- training

    @abc.abstractmethod
    def loss(self, variables: PyTree, batch: PyTree, rng: jax.Array,
             sample_mask: jax.Array) -> Tuple[jax.Array, PyTree]:
        """Per-example loss [B] + updated mutable collections (may be {}).

        sample_mask [B] marks padded examples (0.0); implementations that
        update batch statistics may use it to exclude padding.
        """

    @abc.abstractmethod
    def metrics(self, variables: PyTree, batch: PyTree) -> Dict[str, jax.Array]:
        """Per-example metric values, each [B]; must include 'loss' and
        'accuracy' for history parity."""

    def configure_optimizers(self, lr: jax.Array, epoch: jax.Array
                             ) -> optax.GradientTransformation:
        """Default: plain SGD, the reference examples' optimizer."""
        return optax.sgd(lr)

    # ------------------------------------------------------------ inference

    def infer(self, variables: PyTree, data: np.ndarray) -> np.ndarray:
        """Default classification inference: argmax of logits.

        JITTED (cached per input shape): the eager apply this used to
        be pays one host->backend dispatch PER OP — measured ~150 ms
        for a LeNet batch on the tunneled v5e, which made serving
        latency dispatch-bound regardless of concurrency
        (results/infer-bench-v5e.jsonl). Program count stays bounded:
        the PS micro-batcher pads stacked requests to power-of-two
        buckets before calling here."""
        x = jnp.asarray(data)
        module = self.module
        if getattr(self, "_infer_jit_module", None) is not module:
            # keyed on the module instance: an enable_* clone after a
            # first infer must not silently serve the old configuration
            def run(variables, x):
                logits = module.apply(variables, x, train=False)
                if isinstance(logits, tuple):
                    logits = logits[0]
                return jnp.argmax(logits, axis=-1)

            self._infer_jit = jax.jit(run)
            self._infer_jit_module = module
        return np.asarray(self._infer_jit(variables, x))


class ClassifierModel(KubeModel):
    """Convenience base for softmax classifiers over {'x','y'} batches.

    Mirrors what every reference example function hand-writes
    (ml/experiments/kubeml/function_lenet.py etc.: cross-entropy forward/
    backward + accuracy validation) as reusable pure functions.
    """

    def apply_train(self, variables, x, rng):
        """Apply in train mode, returning (logits, new_model_state)."""
        mutable = [k for k in variables if k != "params"]
        if mutable:
            logits, new_state = self.module.apply(
                variables, x, train=True, mutable=mutable,
                rngs={"dropout": rng})
            return logits, dict(new_state)
        logits = self.module.apply(variables, x, train=True,
                                   rngs={"dropout": rng})
        return logits, {}

    def loss(self, variables, batch, rng, sample_mask):
        logits, new_state = self.apply_train(variables, batch["x"], rng)
        per_ex = optax.softmax_cross_entropy_with_integer_labels(
            logits, batch["y"])
        return per_ex, new_state

    def metrics(self, variables, batch):
        logits = self.module.apply(variables, batch["x"], train=False)
        per_ex_loss = optax.softmax_cross_entropy_with_integer_labels(
            logits, batch["y"])
        acc = (jnp.argmax(logits, axis=-1) == batch["y"]).astype(jnp.float32)
        return {"loss": per_ex_loss, "accuracy": acc}


class KubeDataset(abc.ABC):
    """Dataset-side user hooks.

    The reference KubeDataset pulls pickled 64-sample docs from MongoDB
    (dataset.py:184-223) and lets the user apply transforms per split. Here
    the storage plane is the on-disk registry (kubeml_tpu.data.registry);
    subclasses override the transforms. Transforms run on host numpy arrays,
    once per sync-round chunk, before device upload.
    """

    #: registry dataset name this model trains on
    dataset: str = ""

    #: optional DEVICE twin of transform_train for the index-fed cached
    #: path (data/device_cache.py): `f(x, y) -> {key: jnp.ndarray}`
    #: applied to the RAW gathered leaves inside the jitted round (e.g.
    #: u8 -> f32 normalize). A dataset whose host transform_train is not
    #: the identity must provide this for the device cache to be
    #: eligible — and the two must compute the same values, or cached
    #: and host-staged rounds diverge.
    transform_train_device = None

    def __init__(self, dataset_name: Optional[str] = None):
        if dataset_name:
            self.dataset = dataset_name

    def transform_train(self, data: np.ndarray, labels: np.ndarray) -> Dict[str, np.ndarray]:
        return {"x": data, "y": labels}

    def transform_test(self, data: np.ndarray, labels: np.ndarray) -> Dict[str, np.ndarray]:
        return {"x": data, "y": labels}
