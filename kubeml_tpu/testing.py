"""Test/dry-run utilities.

The container's sitecustomize may eagerly initialize a 1-chip accelerator
backend at interpreter start, which makes env vars like
``--xla_force_host_platform_device_count`` too late. The supported path to
a multi-device virtual mesh without hardware is to clear the initialized
backends and retarget JAX at N CPU devices — shared here so the test
conftest and the driver dry-run entry use one copy of the (unstable
extension API) recipe.
"""

from __future__ import annotations


def ensure_virtual_cpu_devices(n: int) -> None:
    """Make `jax.devices()` return at least n CPU devices (idempotent)."""
    import jax

    if len(jax.devices()) >= n and jax.devices()[0].platform == "cpu":
        return
    import jax.extend.backend
    jax.extend.backend.clear_backends()
    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_num_cpu_devices", n)
    assert len(jax.devices()) >= n, \
        f"failed to create {n} virtual CPU devices"
