"""Test/dry-run utilities.

The container's sitecustomize may eagerly initialize a 1-chip accelerator
backend at interpreter start, which makes env vars like
``--xla_force_host_platform_device_count`` too late. The supported path to
a multi-device virtual mesh without hardware is to clear the initialized
backends and retarget JAX at N CPU devices — shared here so the test
conftest and the driver dry-run entry use one copy of the (unstable
extension API) recipe.
"""

from __future__ import annotations


def virtual_cpu_env(n: int) -> dict:
    """Env vars that make a CHILD python process CPU-targeted at
    interpreter start (before its sitecustomize can eagerly grab the
    accelerator): the one copy of the recipe for every launcher that
    spawns CPU-emulated children (PS standalone spawns, the distributed
    launcher's --emulate-cpu, demo tools, test fixtures). JAX-free —
    safe to import from processes that must not initialize a backend."""
    return {"PALLAS_AXON_POOL_IPS": "", "JAX_PLATFORMS": "cpu",
            "JAX_NUM_CPU_DEVICES": str(n)}


def ensure_virtual_cpu_devices(n: int) -> None:
    """Make `jax.devices()` return at least n CPU devices (idempotent)."""
    import jax

    if len(jax.devices()) >= n and jax.devices()[0].platform == "cpu":
        return
    import jax.extend.backend
    jax.extend.backend.clear_backends()
    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_num_cpu_devices", n)
    assert len(jax.devices()) >= n, \
        f"failed to create {n} virtual CPU devices"
