"""Test/dry-run utilities.

The container's sitecustomize may eagerly initialize a 1-chip accelerator
backend at interpreter start, which makes env vars like
``--xla_force_host_platform_device_count`` too late. The supported path to
a multi-device virtual mesh without hardware is to clear the initialized
backends and retarget JAX at N CPU devices — shared here so the test
conftest and the driver dry-run entry use one copy of the (unstable
extension API) recipe.
"""

from __future__ import annotations

import os


def virtual_cpu_env(n: int) -> dict:
    """Env vars that make a CHILD python process CPU-targeted at
    interpreter start (before its sitecustomize can eagerly grab the
    accelerator): the one copy of the recipe for every launcher that
    spawns CPU-emulated children (PS standalone spawns, the distributed
    launcher's --emulate-cpu, demo tools, test fixtures). JAX-free —
    safe to import from processes that must not initialize a backend.

    Both device-count spellings are set: JAX_NUM_CPU_DEVICES for modern
    JAX and the XLA_FLAGS host-platform flag for 0.4.x (which ignores
    the former). Extending rather than clobbering an inherited XLA_FLAGS
    keeps any operator-set flags live in the child."""
    xla_flags = os.environ.get("XLA_FLAGS", "")
    flag = f"--xla_force_host_platform_device_count={n}"
    if flag not in xla_flags:
        xla_flags = (xla_flags + " " + flag).strip()
    return {"PALLAS_AXON_POOL_IPS": "", "JAX_PLATFORMS": "cpu",
            "JAX_NUM_CPU_DEVICES": str(n), "XLA_FLAGS": xla_flags}


def ensure_virtual_cpu_devices(n: int) -> None:
    """Make `jax.devices()` return at least n CPU devices (idempotent).

    The XLA host-platform flag must be in the environment BEFORE the
    first backend initialization: XLA parses XLA_FLAGS exactly once per
    process, so on JAX versions without the jax_num_cpu_devices config
    (<= 0.4.x) a post-init env change can never take effect — set it
    before the `jax.devices()` idempotence probe below, which is itself
    what triggers the first init in a fresh process."""
    flag = f"--xla_force_host_platform_device_count={n}"
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (flags + " " + flag).strip()

    import jax

    if len(jax.devices()) >= n and jax.devices()[0].platform == "cpu":
        return
    import jax.extend.backend
    jax.extend.backend.clear_backends()
    jax.config.update("jax_platforms", "cpu")
    try:
        jax.config.update("jax_num_cpu_devices", n)
    except AttributeError:
        # JAX 0.4.x: no such config — the XLA_FLAGS fallback set above
        # is honored when clear_backends() forces re-initialization
        # (provided no backend had initialized before this call; an
        # eagerly-initialized process already consumed XLA_FLAGS and
        # only the modern config path can retarget it).
        pass
    assert len(jax.devices()) >= n, \
        f"failed to create {n} virtual CPU devices"
