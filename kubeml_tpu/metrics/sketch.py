"""Mergeable windowed latency sketches (DDSketch-style, fixed gamma).

The serving fleet needs percentiles that (a) merge *exactly* across
replicas — so fleet p99 is the p99 of the pooled samples, not the
worst replica's — and (b) age out, so an idle fleet's window empties
instead of pinning a stale p99 forever (the autoscaler hack this
replaces lived in ``fleet.py`` as the ``inflight > 0`` guard).

Two pieces:

``QuantileSketch``
    Fixed-gamma log-bucket histogram (Masson et al., VLDB 2019).  A
    value ``v > 0`` lands in bucket ``ceil(log_gamma(v))``; the bucket
    midpoint ``2·gamma^k/(gamma+1)`` answers any quantile within
    relative error ``alpha`` where ``gamma = (1+alpha)/(1-alpha)``.
    Because the bucket boundaries are a pure function of ``alpha``,
    merging two sketches is bucket-count addition — associative,
    commutative, and *exactly* equal to sketching the pooled samples.
    ``state()``/``from_state()`` round-trip through plain JSON so a
    replica snapshot can carry its buckets to the fleet merge.

``WindowedSketch``
    A ring of sub-window sketches keyed by a tick counter derived from
    an injectable clock.  Samples land in the current sub-window;
    queries merge the live sub-windows and expired ticks are dropped
    deterministically — no wall-clock reads, so tests drive it with a
    fake clock tick by tick.

Everything here is host-side pure Python: no numpy, no hidden time
source.  ``QuantileSketch`` is single-threaded (callers serialize);
``WindowedSketch`` takes a small per-instance lock because it is the
object shared across threads in practice — the replica loop adds
samples while fleet snapshot threads merge the window.
"""

from __future__ import annotations

import math
import threading
from typing import Dict, Optional

__all__ = ["QuantileSketch", "WindowedSketch"]

DEFAULT_ALPHA = 0.01


class QuantileSketch:
    """Fixed-gamma log-bucket quantile sketch with exact merge."""

    __slots__ = ("alpha", "_gamma", "_log_gamma", "_buckets", "_zero")

    def __init__(self, alpha: float = DEFAULT_ALPHA):
        if not 0.0 < alpha < 1.0:
            raise ValueError(f"alpha must be in (0, 1), got {alpha}")
        self.alpha = float(alpha)
        self._gamma = (1.0 + alpha) / (1.0 - alpha)
        self._log_gamma = math.log(self._gamma)
        self._buckets: Dict[int, int] = {}
        self._zero = 0  # values <= 0 (clamped; latencies only)

    # ------------------------------------------------------------ insert

    def add(self, value: float, count: int = 1) -> None:
        if count <= 0:
            return
        if value <= 0.0:
            self._zero += count
            return
        key = math.ceil(math.log(value) / self._log_gamma)
        self._buckets[key] = self._buckets.get(key, 0) + count

    # ------------------------------------------------------------- merge

    def merge(self, other: "QuantileSketch") -> "QuantileSketch":
        """Fold ``other`` into self (bucket-count addition; exact)."""
        if abs(other.alpha - self.alpha) > 1e-12:
            raise ValueError(
                f"cannot merge sketches with different alpha "
                f"({self.alpha} vs {other.alpha})")
        self._zero += other._zero
        for key, n in other._buckets.items():
            self._buckets[key] = self._buckets.get(key, 0) + n
        return self

    # ------------------------------------------------------------ query

    @property
    def count(self) -> int:
        return self._zero + sum(self._buckets.values())

    def quantile(self, q: float) -> float:
        """Value at quantile ``q`` in [0, 1]; 0.0 on an empty sketch."""
        if not 0.0 <= q <= 1.0:
            raise ValueError(f"q must be in [0, 1], got {q}")
        total = self.count
        if total == 0:
            return 0.0
        rank = q * (total - 1)
        seen = self._zero
        if rank < seen:
            return 0.0
        for key in sorted(self._buckets):
            seen += self._buckets[key]
            if rank < seen:
                # midpoint of (gamma^(k-1), gamma^k]
                return 2.0 * self._gamma ** key / (self._gamma + 1.0)
        # unreachable unless float slop at q == 1.0
        key = max(self._buckets)
        return 2.0 * self._gamma ** key / (self._gamma + 1.0)

    # ------------------------------------------------------- state (JSON)

    def state(self) -> dict:
        """Plain-JSON snapshot: merge-able via ``from_state`` + ``merge``."""
        return {
            "alpha": self.alpha,
            "zero": self._zero,
            "buckets": {str(k): n for k, n in self._buckets.items()},
        }

    @classmethod
    def from_state(cls, st: dict) -> "QuantileSketch":
        sk = cls(alpha=float(st.get("alpha", DEFAULT_ALPHA)))
        sk._zero = int(st.get("zero", 0))
        sk._buckets = {int(k): int(n)
                       for k, n in dict(st.get("buckets", {})).items()}
        return sk

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return (f"QuantileSketch(alpha={self.alpha}, count={self.count}, "
                f"buckets={len(self._buckets)})")


class WindowedSketch:
    """Ring of sub-window sketches over an injectable clock.

    The window of ``window_s`` seconds is cut into ``subwindows`` equal
    ticks.  A sample lands in the sketch for the clock's current tick;
    queries merge every live tick and drop ticks older than the window.
    Expiry is a pure function of the clock reading — deterministic
    under a fake clock, and an idle window drains to empty (count 0)
    after ``window_s`` seconds with no samples.

    Thread-safe: ``add`` and the query paths hold a per-instance lock,
    since the replica loop inserts while fleet snapshot threads merge.
    """

    __slots__ = ("window_s", "subwindows", "alpha", "_clock", "_tick_s",
                 "_ring", "_lock")

    def __init__(self, window_s: float = 60.0, subwindows: int = 6,
                 alpha: float = DEFAULT_ALPHA, clock=None):
        if window_s <= 0.0:
            raise ValueError(f"window_s must be > 0, got {window_s}")
        if subwindows < 1:
            raise ValueError(f"subwindows must be >= 1, got {subwindows}")
        import time as _time
        self.window_s = float(window_s)
        self.subwindows = int(subwindows)
        self.alpha = float(alpha)
        self._clock = clock if clock is not None else _time.perf_counter
        self._tick_s = self.window_s / self.subwindows
        self._ring: Dict[int, QuantileSketch] = {}
        self._lock = threading.Lock()

    def _tick(self) -> int:
        return int(self._clock() // self._tick_s)

    def _expire(self, now_tick: int) -> None:
        floor = now_tick - self.subwindows
        for key in [k for k in self._ring if k <= floor]:
            del self._ring[key]

    # ------------------------------------------------------------ insert

    def add(self, value: float) -> None:
        with self._lock:
            tick = self._tick()
            self._expire(tick)
            sk = self._ring.get(tick)
            if sk is None:
                sk = self._ring[tick] = QuantileSketch(alpha=self.alpha)
            sk.add(value)

    # ------------------------------------------------------------- query

    def merged(self) -> QuantileSketch:
        """Exact merge of the live sub-windows (a fresh sketch)."""
        with self._lock:
            tick = self._tick()
            self._expire(tick)
            out = QuantileSketch(alpha=self.alpha)
            for sk in self._ring.values():
                out.merge(sk)
            return out

    def quantile(self, q: float) -> float:
        return self.merged().quantile(q)

    @property
    def count(self) -> int:
        return self.merged().count

    def state(self) -> dict:
        """JSON state of the merged live window (for fleet-side merge)."""
        return self.merged().state()

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return (f"WindowedSketch(window_s={self.window_s}, "
                f"subwindows={self.subwindows}, count={self.count})")
