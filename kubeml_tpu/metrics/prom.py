"""Prometheus text-format metrics (stdlib-only exposition).

Parity with ml/pkg/ps/metrics.go:33-81: the same gauge family names and
`jobid` label so existing dashboards (ml/dashboard/KubeML.json) work
unchanged against our /metrics endpoint:

    kubeml_job_validation_loss{jobid=...}
    kubeml_job_validation_accuracy{jobid=...}
    kubeml_job_train_loss{jobid=...}
    kubeml_job_parallelism{jobid=...}
    kubeml_job_epoch_duration_seconds{jobid=...}
    kubeml_job_running_total{type=...}

Per-job series are cleared when a job finishes (metrics.go:90-106).
"""

from __future__ import annotations

import math
import threading
from typing import Dict, Tuple


class Gauge:
    def __init__(self, name: str, help_: str, label: str):
        self.name = name
        self.help = help_
        self.label = label
        self._values: Dict[str, float] = {}
        self._lock = threading.Lock()

    def set(self, label_value: str, value: float):
        with self._lock:
            self._values[label_value] = value

    def inc(self, label_value: str, delta: float = 1.0):
        with self._lock:
            self._values[label_value] = self._values.get(label_value, 0.0) + delta

    def clear(self, label_value: str):
        with self._lock:
            self._values.pop(label_value, None)

    def collect(self) -> str:
        lines = [f"# HELP {self.name} {self.help}",
                 f"# TYPE {self.name} gauge"]
        with self._lock:
            for lv, v in sorted(self._values.items()):
                if isinstance(v, float) and math.isnan(v):
                    v = "NaN"
                lines.append(f'{self.name}{{{self.label}="{lv}"}} {v}')
        return "\n".join(lines)


class MetricsRegistry:
    """The PS metric set (ml/pkg/ps/metrics.go)."""

    def __init__(self):
        self.validation_loss = Gauge(
            "kubeml_job_validation_loss", "Validation loss of a job", "jobid")
        self.validation_accuracy = Gauge(
            "kubeml_job_validation_accuracy", "Validation accuracy of a job",
            "jobid")
        self.train_loss = Gauge(
            "kubeml_job_train_loss", "Train loss of a job", "jobid")
        self.parallelism = Gauge(
            "kubeml_job_parallelism", "Parallelism of a job", "jobid")
        self.epoch_duration = Gauge(
            "kubeml_job_epoch_duration_seconds", "Epoch duration of a job",
            "jobid")
        self.running_total = Gauge(
            "kubeml_job_running_total", "Number of running tasks by type",
            "type")
        # fault-tolerance series (net-new vs metrics.go): per-job
        # non-finite drops / quarantines from the guarded merge, and the
        # watchdog restart counters — per-job (cleared at finish like
        # every job series) plus a PS-lifetime total that persists
        self.dropped_workers = Gauge(
            "kubeml_job_dropped_workers",
            "Worker updates dropped for non-finite values in the last "
            "epoch of a job", "jobid")
        self.quarantined_workers = Gauge(
            "kubeml_job_quarantined_workers",
            "Workers quarantined for repeated non-finite updates in the "
            "last epoch of a job", "jobid")
        self.restarts = Gauge(
            "kubeml_job_restarts",
            "Watchdog restarts of a job's standalone process", "jobid")
        self.restarts_total = Gauge(
            "kubeml_ps_restarts_total",
            "Total watchdog restarts since the PS started", "type")
        self._job_gauges = [self.validation_loss, self.validation_accuracy,
                            self.train_loss, self.parallelism,
                            self.epoch_duration, self.dropped_workers,
                            self.quarantined_workers, self.restarts]

    def update_job(self, m) -> None:
        """Apply a MetricUpdate (ml/pkg/ps/metrics.go:90-99)."""
        self.validation_loss.set(m.job_id, m.validation_loss)
        self.validation_accuracy.set(m.job_id, m.accuracy)
        self.train_loss.set(m.job_id, m.train_loss)
        self.parallelism.set(m.job_id, m.parallelism)
        self.epoch_duration.set(m.job_id, m.epoch_duration)
        self.dropped_workers.set(m.job_id, m.dropped_workers)
        self.quarantined_workers.set(m.job_id, m.quarantined_workers)

    def note_restart(self, job_id: str) -> None:
        """One watchdog restart: bump the per-job gauge and the
        PS-lifetime total (the total survives clear_job, so a crashy
        job's history stays visible after it finishes)."""
        self.restarts.inc(job_id)
        self.restarts_total.inc("standalone")

    def clear_job(self, job_id: str) -> None:
        for g in self._job_gauges:
            g.clear(job_id)

    def exposition(self) -> str:
        gauges = self._job_gauges + [self.running_total,
                                     self.restarts_total]
        return "\n".join(g.collect() for g in gauges) + "\n"
