"""Prometheus text-format metrics (stdlib-only exposition).

Parity with ml/pkg/ps/metrics.go:33-81: the same gauge family names and
`jobid` label so existing dashboards (ml/dashboard/KubeML.json) work
unchanged against our /metrics endpoint:

    kubeml_job_validation_loss{jobid=...}
    kubeml_job_validation_accuracy{jobid=...}
    kubeml_job_train_loss{jobid=...}
    kubeml_job_parallelism{jobid=...}
    kubeml_job_epoch_duration_seconds{jobid=...}
    kubeml_job_running_total{type=...}

Per-job series are cleared when a job finishes (metrics.go:90-106).

Beyond the gauge parity set, this module now carries proper counter and
histogram families (exposition format 0.0.4: cumulative monotone
``_bucket`` series ending in ``le="+Inf"``, plus ``_sum``/``_count``):
per-job round phase latencies (dispatch / data-wait / merge) fed from
the job's tracer via MetricUpdate.phase_times, per-endpoint HTTP
request duration + status counters recorded by the JsonService
middleware (`HttpMetrics`), and the watchdog restart total — which was
previously (wrongly) exposed as a gauge although it is monotone.
tools/check_metrics.py lints the combined exposition.
"""

from __future__ import annotations

import math
import threading
from typing import Dict, Iterable, List, Sequence, Tuple, Union

LabelValues = Union[str, Sequence[str]]

# Latency buckets: 1ms..60s, roughly log-spaced.  Host-side round phases
# on CPU tier-1 land mid-range; real TPU dispatches land in the low
# buckets; stragglers and cold compiles still resolve above 1s instead
# of all collapsing into +Inf.
DEFAULT_TIME_BUCKETS = (0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
                        0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0)


def _escape(value: str) -> str:
    return (str(value).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def _fmt_labels(names: Sequence[str], values: Sequence[str],
                extra: Tuple[str, str] = None) -> str:
    pairs = [f'{n}="{_escape(v)}"' for n, v in zip(names, values)]
    if extra is not None:
        pairs.append(f'{extra[0]}="{extra[1]}"')
    return "{" + ",".join(pairs) + "}" if pairs else ""


def _fmt_value(v) -> str:
    if isinstance(v, float) and math.isnan(v):
        return "NaN"
    return str(v)


def _key(labels: Sequence[str], values: LabelValues) -> Tuple[str, ...]:
    if isinstance(values, str):
        values = (values,)
    values = tuple(str(v) for v in values)
    if len(values) != len(labels):
        raise ValueError(
            f"expected {len(labels)} label values {tuple(labels)}, "
            f"got {values}")
    return values


class Gauge:
    def __init__(self, name: str, help_: str, label: str):
        self.name = name
        self.help = help_
        self.label = label
        self._values: Dict[str, float] = {}
        self._lock = threading.Lock()

    def set(self, label_value: str, value: float):
        with self._lock:
            self._values[label_value] = value

    def inc(self, label_value: str, delta: float = 1.0):
        with self._lock:
            self._values[label_value] = self._values.get(label_value, 0.0) + delta

    def clear(self, label_value: str):
        with self._lock:
            self._values.pop(label_value, None)

    def collect(self) -> str:
        lines = [f"# HELP {self.name} {self.help}",
                 f"# TYPE {self.name} gauge"]
        with self._lock:
            for lv, v in sorted(self._values.items()):
                lines.append(
                    f'{self.name}{{{self.label}="{_escape(lv)}"}} '
                    f'{_fmt_value(v)}')
        return "\n".join(lines)


class MultiGauge:
    """Gauge family with an arbitrary label tuple (the single-label
    Gauge above predates it and stays for the reference-parity
    families). Used where one job fans out into several series —
    per-worker health stats (`worker` label) and the HBM watermark
    (`kind=peak|in_use`) — so per-worker data rides LABELS, never
    family-name suffixes (the cardinality rule tools/check_metrics.py
    enforces)."""

    def __init__(self, name: str, help_: str, labels: LabelValues):
        self.name = name
        self.help = help_
        self.labels = (labels,) if isinstance(labels, str) else tuple(labels)
        self._values: Dict[Tuple[str, ...], float] = {}
        self._lock = threading.Lock()

    def set(self, label_values: LabelValues, value: float):
        key = _key(self.labels, label_values)
        with self._lock:
            self._values[key] = value

    def value(self, label_values: LabelValues) -> float:
        key = _key(self.labels, label_values)
        with self._lock:
            return self._values.get(key, 0.0)

    def clear_prefix(self, first_label_value: str):
        """Drop every series whose FIRST label equals the value — the
        job-finish cleanup for jobid-leading families."""
        with self._lock:
            for key in [k for k in self._values
                        if k[0] == str(first_label_value)]:
                del self._values[key]

    def collect(self) -> str:
        lines = [f"# HELP {self.name} {self.help}",
                 f"# TYPE {self.name} gauge"]
        with self._lock:
            for key, v in sorted(self._values.items()):
                lines.append(
                    f"{self.name}{_fmt_labels(self.labels, key)} "
                    f"{_fmt_value(v)}")
        return "\n".join(lines)


class Counter:
    """Monotone counter family; name must end in ``_total`` by
    convention (enforced by tools/check_metrics.py)."""

    def __init__(self, name: str, help_: str, labels: LabelValues):
        self.name = name
        self.help = help_
        self.labels = (labels,) if isinstance(labels, str) else tuple(labels)
        self._values: Dict[Tuple[str, ...], float] = {}
        self._lock = threading.Lock()

    def inc(self, label_values: LabelValues, delta: float = 1.0):
        if delta < 0:
            raise ValueError("counters only go up")
        key = _key(self.labels, label_values)
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + delta

    def value(self, label_values: LabelValues) -> float:
        key = _key(self.labels, label_values)
        with self._lock:
            return self._values.get(key, 0.0)

    def clear_prefix(self, first_label_value: str):
        """Drop series whose FIRST label equals the value. Only for
        jobid-leading counters whose cardinality must not grow without
        bound across the PS's life — dropping a finished job's series
        is the documented reset (scrapers see a fresh start, as after
        any process restart)."""
        with self._lock:
            for key in [k for k in self._values
                        if k[0] == str(first_label_value)]:
                del self._values[key]

    def collect(self) -> str:
        lines = [f"# HELP {self.name} {self.help}",
                 f"# TYPE {self.name} counter"]
        with self._lock:
            for key, v in sorted(self._values.items()):
                lines.append(
                    f"{self.name}{_fmt_labels(self.labels, key)} "
                    f"{_fmt_value(v)}")
        return "\n".join(lines)


class Histogram:
    """Cumulative histogram family (exposition format 0.0.4).

    Per labelset: ``name_bucket{...,le="b"}`` for each upper bound plus
    ``le="+Inf"``, then ``name_sum`` and ``name_count``.  Buckets are
    cumulative and monotone by construction; bounds must be strictly
    increasing.
    """

    def __init__(self, name: str, help_: str, labels: LabelValues,
                 buckets: Sequence[float] = DEFAULT_TIME_BUCKETS):
        self.name = name
        self.help = help_
        self.labels = (labels,) if isinstance(labels, str) else tuple(labels)
        buckets = tuple(float(b) for b in buckets)
        if not buckets:
            raise ValueError("histogram needs at least one bucket bound")
        if any(b2 <= b1 for b1, b2 in zip(buckets, buckets[1:])):
            raise ValueError(f"bucket bounds must strictly increase: "
                             f"{buckets}")
        self.buckets = buckets
        # per labelset: [per-bound counts..., +Inf count], sum
        self._data: Dict[Tuple[str, ...], List] = {}
        self._lock = threading.Lock()

    def observe(self, label_values: LabelValues, value: float):
        key = _key(self.labels, label_values)
        value = float(value)
        with self._lock:
            entry = self._data.get(key)
            if entry is None:
                entry = [[0] * (len(self.buckets) + 1), 0.0]
                self._data[key] = entry
            counts, _ = entry
            for i, bound in enumerate(self.buckets):
                if value <= bound:
                    counts[i] += 1
                    break
            else:
                counts[len(self.buckets)] += 1
            entry[1] += value

    def clear(self, label_values: LabelValues):
        with self._lock:
            self._data.pop(_key(self.labels, label_values), None)

    @staticmethod
    def _fmt_bound(b: float) -> str:
        s = repr(b)
        return s[:-2] if s.endswith(".0") else s

    def collect(self) -> str:
        lines = [f"# HELP {self.name} {self.help}",
                 f"# TYPE {self.name} histogram"]
        with self._lock:
            for key, (counts, total) in sorted(self._data.items()):
                cum = 0
                for bound, n in zip(self.buckets, counts):
                    cum += n
                    labels = _fmt_labels(self.labels, key,
                                         ("le", self._fmt_bound(bound)))
                    lines.append(f"{self.name}_bucket{labels} {cum}")
                cum += counts[-1]
                labels = _fmt_labels(self.labels, key, ("le", "+Inf"))
                lines.append(f"{self.name}_bucket{labels} {cum}")
                plain = _fmt_labels(self.labels, key)
                lines.append(f"{self.name}_sum{plain} {_fmt_value(total)}")
                lines.append(f"{self.name}_count{plain} {cum}")
        return "\n".join(lines)


class HttpMetrics:
    """Per-endpoint HTTP request counters + duration histogram, recorded
    by the JsonService middleware on every service (PS, scheduler,
    controller, jobserver).  The endpoint label is the registered route
    *pattern* (``/update/{jobId}``), never the raw path, so cardinality
    stays bounded."""

    # HTTP handlers are quick JSON hops; sub-ms matters more than the
    # multi-second tail, so shift the default bucket grid down.
    BUCKETS = (0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
               0.25, 0.5, 1.0, 2.5, 10.0)

    def __init__(self, service: str):
        self.service = service
        self.requests = Counter(
            "kubeml_http_requests_total",
            "HTTP requests handled, by service/method/endpoint/status",
            ("service", "method", "endpoint", "status"))
        self.duration = Histogram(
            "kubeml_http_request_duration_seconds",
            "HTTP request handling latency, by service/method/endpoint",
            ("service", "method", "endpoint"), buckets=self.BUCKETS)

    def observe(self, method: str, endpoint: str, status: int,
                seconds: float):
        self.requests.inc((self.service, method, endpoint, str(status)))
        self.duration.observe((self.service, method, endpoint), seconds)

    def exposition(self) -> str:
        return (self.requests.collect() + "\n"
                + self.duration.collect() + "\n")


# Tracer span name -> histogram attribute for the phase latencies
# pushed per epoch via MetricUpdate.phase_times.  The merge cost splits
# into two spans: merge_wait is the BLOCKING portion (the epoch-end
# drain where the host actually waits on outstanding merges and the
# merged-loss readback), merge_overlap is merge-adjacent host
# bookkeeping done while the next dispatch is already executing on
# device — time the overlap pipeline hides.  device_drain is the
# pre-split name for the blocking portion; it stays mapped so traces
# from older processes (and the bench harness's drain spans) keep
# landing in kubeml_job_merge_seconds.
PHASE_HISTOGRAMS = {
    "dispatch": "dispatch_seconds",
    "data_wait": "data_wait_seconds",
    "device_drain": "merge_seconds",
    "merge_wait": "merge_seconds",
    "merge_overlap": "merge_overlap_seconds",
}


# the complete job-health state space (control/health.py verdicts);
# kubeml_job_health exposes one 0/1 series per state so dashboards can
# alert on `kubeml_job_health{state="critical"} == 1` without regexes
HEALTH_STATES = ("healthy", "warning", "critical", "unknown")


class MetricsRegistry:
    """The PS metric set (ml/pkg/ps/metrics.go)."""

    def __init__(self):
        self.validation_loss = Gauge(
            "kubeml_job_validation_loss", "Validation loss of a job", "jobid")
        self.validation_accuracy = Gauge(
            "kubeml_job_validation_accuracy", "Validation accuracy of a job",
            "jobid")
        self.train_loss = Gauge(
            "kubeml_job_train_loss", "Train loss of a job", "jobid")
        self.parallelism = Gauge(
            "kubeml_job_parallelism", "Parallelism of a job", "jobid")
        self.epoch_duration = Gauge(
            "kubeml_job_epoch_duration_seconds", "Epoch duration of a job",
            "jobid")
        self.running_total = Gauge(
            "kubeml_job_running_total", "Number of running tasks by type",
            "type")
        # fault-tolerance series (net-new vs metrics.go): per-job
        # non-finite drops / quarantines from the guarded merge, and the
        # watchdog restart counters — per-job (cleared at finish like
        # every job series) plus a PS-lifetime total that persists
        self.dropped_workers = Gauge(
            "kubeml_job_dropped_workers",
            "Worker updates dropped for non-finite values in the last "
            "epoch of a job", "jobid")
        self.quarantined_workers = Gauge(
            "kubeml_job_quarantined_workers",
            "Workers quarantined for repeated non-finite updates in the "
            "last epoch of a job", "jobid")
        self.restarts = Gauge(
            "kubeml_job_restarts",
            "Watchdog restarts of a job's standalone process", "jobid")
        self.restarts_total = Counter(
            "kubeml_ps_restarts_total",
            "Total watchdog restarts since the PS started", "type")
        # elastic degraded mode: mid-epoch reassignment volume, graceful
        # preemptions, coalesced checkpoint saves, and the heartbeat
        # cursor the liveness reaper watches
        self.reassigned_batches = Gauge(
            "kubeml_job_reassigned_batches",
            "Minibatch steps re-dealt from quarantined workers to "
            "survivors in the last epoch of a job", "jobid")
        self.preemptions = Gauge(
            "kubeml_job_preemptions",
            "Graceful preemption reschedules of a job's standalone "
            "process", "jobid")
        self.checkpoint_drops = Gauge(
            "kubeml_job_checkpoint_drops",
            "Async checkpoint saves coalesced into a newer snapshot "
            "because the writer fell behind", "jobid")
        self.heartbeat_epoch = Gauge(
            "kubeml_job_heartbeat_epoch",
            "Epoch cursor of a job's last progress heartbeat", "jobid")
        self.heartbeat_round = Gauge(
            "kubeml_job_heartbeat_round",
            "Round cursor of a job's last progress heartbeat", "jobid")
        self.preemptions_total = Counter(
            "kubeml_ps_preemptions_total",
            "Total graceful preemption reschedules since the PS started",
            "type")
        self.wedged_total = Counter(
            "kubeml_ps_wedged_kills_total",
            "Standalone children killed by the heartbeat reaper for "
            "missing the liveness budget", "type")
        # round-phase latency distributions, fed from the job tracer's
        # per-epoch durations (MetricUpdate.phase_times)
        self.dispatch_seconds = Histogram(
            "kubeml_job_dispatch_seconds",
            "Round dispatch latency (device step calls) of a job", "jobid")
        self.data_wait_seconds = Histogram(
            "kubeml_job_data_wait_seconds",
            "Time a job's round loop blocked waiting for input data",
            "jobid")
        self.merge_seconds = Histogram(
            "kubeml_job_merge_seconds",
            "Merged-result readback (device drain) latency of a job",
            "jobid")
        self.merge_overlap_seconds = Histogram(
            "kubeml_job_merge_overlap_seconds",
            "Merge-adjacent host bookkeeping of a job overlapped with "
            "device execution (hidden by the dispatch pipeline)", "jobid")
        # training-health telemetry (on-device stat lanes riding
        # MetricUpdate + control/health.py rule verdicts): per-worker
        # stats carry the worker as a LABEL (cardinality rule), the
        # verdict fans out one 0/1 series per state
        self.job_health = MultiGauge(
            "kubeml_job_health",
            "Health verdict of a job: 1 on the active state's series",
            ("jobid", "state"))
        self.worker_grad_norm = MultiGauge(
            "kubeml_job_worker_grad_norm",
            "Per-worker RMS global gradient norm in the last epoch of a "
            "job", ("jobid", "worker"))
        self.worker_update_ratio = MultiGauge(
            "kubeml_job_worker_update_ratio",
            "Per-worker update-norm/param-norm ratio in the last epoch "
            "of a job", ("jobid", "worker"))
        self.loss_spread = Gauge(
            "kubeml_job_loss_spread",
            "Cross-worker std of per-round mean losses in the last epoch "
            "of a job", "jobid")
        self.hbm_bytes = MultiGauge(
            "kubeml_device_hbm_bytes",
            "Device memory watermark of a job's process, by kind "
            "(peak|in_use)", ("jobid", "kind"))
        self.health_alerts_total = Counter(
            "kubeml_health_alerts_total",
            "Health-rule alerts fired for a job, by rule",
            ("jobid", "rule"))
        self.jit_compiles_total = Counter(
            "kubeml_jit_compiles_total",
            "Engine round-program jit compiles of a job", "jobid")
        self.trace_dropped_total = Counter(
            "kubeml_trace_events_dropped_total",
            "Tracer events dropped at the per-process ring cap for a job",
            "jobid")
        # serving plane (serve/): per-model SLO latency distributions
        # (TTFT = submit -> first token, TPOT = decode cadence after it,
        # e2e = submit -> done) + the occupancy/queue/KV gauges the
        # serve health rules and `kubeml top` read, keyed by MODEL, not
        # job — serving outlives any one job and survives clear_job
        self.serve_ttft_seconds = Histogram(
            "kubeml_serve_ttft_seconds",
            "Time to first generated token of a /generate request, by "
            "served model", "model")
        self.serve_tpot_seconds = Histogram(
            "kubeml_serve_tpot_seconds",
            "Mean per-output-token decode latency of a /generate "
            "request after its first token, by served model", "model")
        self.serve_e2e_seconds = Histogram(
            "kubeml_serve_e2e_seconds",
            "End-to-end latency of a /generate request, by served model",
            "model")
        # TTFT attribution (PR 11): the same TTFT decomposed into
        # additive components — queue (submit -> slot attach), prefill
        # (wall time of the dispatches that computed the prompt), and
        # interleave (scheduler delay between them; the remainder, so
        # the three sum to TTFT per request)
        self.serve_ttft_breakdown_seconds = Histogram(
            "kubeml_serve_ttft_breakdown_seconds",
            "Additive TTFT components of a /generate request "
            "(queue|prefill|interleave; they sum to the TTFT), by "
            "served model", ("model", "component"))
        # producer-side stream lifetime, recorded when the ndjson
        # generator CLOSES (incl. client disconnects that cancel the
        # request). kubeml_http_request_duration_seconds already covers
        # the full server-side write — the middleware observes after the
        # chunked body is written — but it only sees streams whose
        # connection the server finished with; this one is per-model
        # and counts cancelled/abandoned streams' real lifetimes too.
        self.serve_stream_duration_seconds = Histogram(
            "kubeml_serve_stream_duration_seconds",
            "Lifetime of a streaming /generate response from submit to "
            "producer close, by served model", "model")
        self.serve_active_slots = Gauge(
            "kubeml_serve_active_slots",
            "Decode slots occupied by in-flight streams of a served "
            "model", "model")
        self.serve_queue_depth = Gauge(
            "kubeml_serve_queue_depth",
            "Admitted /generate requests waiting for a decode slot, by "
            "served model", "model")
        self.serve_kv_utilization = Gauge(
            "kubeml_serve_kv_page_utilization",
            "Fraction of a served model's KV cache pages in use", "model")
        self.serve_requests_total = Counter(
            "kubeml_serve_requests_total",
            "Finished /generate requests by served model and outcome "
            "(ok|rejected|cancelled|deadline|error)", ("model", "outcome"))
        self.serve_tokens_total = Counter(
            "kubeml_serve_tokens_total",
            "Tokens generated by a served model", "model")
        # chunked prefill + prefix cache (PR 8): where prompt tokens are
        # spent (bulk prefill vs per-token decode), how often full
        # prompt pages are served from the content-hash cache, and the
        # prompt work queued ahead of any new request's first token
        self.serve_prefill_tokens_total = Counter(
            "kubeml_serve_prefill_tokens_total",
            "Prompt tokens bulk-loaded through the chunked-prefill "
            "program, by served model", "model")
        self.serve_decode_tokens_total = Counter(
            "kubeml_serve_decode_tokens_total",
            "Tokens advanced through the decode program across all "
            "slots, by served model", "model")
        self.serve_prefix_hits_total = Counter(
            "kubeml_serve_prefix_cache_hits_total",
            "Prompt pages attached from the shared prefix cache instead "
            "of being re-prefilled, by served model", "model")
        self.serve_prefix_misses_total = Counter(
            "kubeml_serve_prefix_cache_misses_total",
            "Prompt prefix-cache lookups that found no resident page, "
            "by served model", "model")
        self.serve_prefill_backlog = Gauge(
            "kubeml_serve_prefill_backlog_tokens",
            "Prompt tokens admitted but not yet prefilled, by served "
            "model", "model")
        # fault tolerance (PR 12): supervisor rebuilds, poisoned-stream
        # quarantines, and KV pager invariant violations (production
        # engines log-and-count instead of crashing; any nonzero value
        # is a bug to chase)
        self.serve_engine_restarts_total = Counter(
            "kubeml_serve_engine_restarts_total",
            "Supervisor engine rebuilds after a dead or wedged serving "
            "loop, by served model", "model")
        self.serve_poisoned_total = Counter(
            "kubeml_serve_poisoned_requests_total",
            "Requests quarantined for poisoning the decode step "
            "(non-finite logits or step exceptions isolated by "
            "bisection), by served model", "model")
        self.serve_page_leaks_total = Counter(
            "kubeml_serve_page_leaks_total",
            "KV pager invariant violations detected on release or "
            "recovery, by served model", "model")
        # decode bandwidth (PR 15): deterministic HBM bytes the decode
        # program moved through the paged KV cache (page geometry x
        # storage dtype per decoded token — a comm proxy, not a timer),
        # the observable the int8-KV mode exists to shrink
        self.serve_kv_bytes_total = Counter(
            "kubeml_serve_kv_bytes_total",
            "KV-cache bytes moved by decode dispatches (deterministic "
            "geometry-based proxy), by served model", "model")
        # decode latency (PR 16): speculative-decoding token flow —
        # draft proposals in, verifier-accepted tokens out (accepted
        # prefix + bonus pick per dispatch), proposals rolled back.
        # Counters, never timers: accepted/verify_dispatches is the
        # accepted_tokens_per_dispatch proxy the bench pins.
        self.serve_draft_tokens_total = Counter(
            "kubeml_serve_draft_tokens_total",
            "Tokens proposed by the speculative draft model, by served "
            "model", "model")
        self.serve_accepted_tokens_total = Counter(
            "kubeml_serve_accepted_tokens_total",
            "Speculative tokens kept per verify dispatch (accepted "
            "prefix plus the bonus target pick), by served model",
            "model")
        self.serve_rejected_tokens_total = Counter(
            "kubeml_serve_rejected_tokens_total",
            "Draft proposals rejected by the verifier and rolled back "
            "as data, by served model", "model")
        # continual plane (PR 10): the weight generation new admissions
        # attach to (advances on every zero-downtime hot-swap), and the
        # continual job's data freshness — dataset generation trained
        # vs. how many generations the registry is ahead
        self.serve_weight_generation = Gauge(
            "kubeml_serve_weight_generation",
            "Weight generation new admissions of a served model attach "
            "to (advances on hot-swap)", "model")
        self.dataset_generation = Gauge(
            "kubeml_dataset_generation",
            "Dataset generation a continual job last trained over",
            "jobid")
        self.data_lag_generations = Gauge(
            "kubeml_data_lag_generations",
            "Generations the dataset registry is ahead of what a "
            "continual job has trained", "jobid")
        # checkpoint-LRU (infer cache) instrumentation: entries resident
        # plus hit/miss traffic, labelled by cache in case more
        # deserialization caches grow later
        self.infer_cache_entries = Gauge(
            "kubeml_infer_cache_entries",
            "Deserialized checkpoints resident in an inference cache",
            "cache")
        self.infer_cache_hits_total = Counter(
            "kubeml_infer_cache_hits_total",
            "Inference-cache lookups served without touching storage",
            "cache")
        self.infer_cache_misses_total = Counter(
            "kubeml_infer_cache_misses_total",
            "Inference-cache lookups that deserialized a checkpoint",
            "cache")
        # serving fleet (serve/fleet.py), fed by the fleet's merged
        # snapshot (update_fleet): live replica count, router traffic
        # (spills off the affine replica, shed retries, cold starts),
        # autoscaler decisions by action, and per-replica prefix-cache
        # traffic — per-replica series ride a `replica` LABEL, never
        # family-name suffixes (the check_metrics.py cardinality rule)
        self.serve_fleet_replicas = Gauge(
            "kubeml_serve_fleet_replicas",
            "Live decode replicas behind the model's fleet router",
            "model")
        self.serve_fleet_spills_total = Counter(
            "kubeml_serve_fleet_spills_total",
            "Requests routed off their affine replica to a peer",
            "model")
        self.serve_fleet_router_retries_total = Counter(
            "kubeml_serve_fleet_router_retries_total",
            "Replica sheds the router retried against a peer", "model")
        self.serve_fleet_cold_starts_total = Counter(
            "kubeml_serve_fleet_cold_starts_total",
            "Replicas built from zero by a first request", "model")
        self.serve_fleet_scale_events_total = Counter(
            "kubeml_serve_fleet_scale_events_total",
            "Fleet autoscaler decisions applied, by action",
            ("model", "action"))
        self.serve_fleet_replica_prefix_hits_total = Counter(
            "kubeml_serve_fleet_replica_prefix_hits_total",
            "Prefix-cache hits per decode replica",
            ("model", "replica"))
        self.serve_fleet_replica_prefix_misses_total = Counter(
            "kubeml_serve_fleet_replica_prefix_misses_total",
            "Prefix-cache misses per decode replica",
            ("model", "replica"))
        # fleet failure domains (serve/fleet.py supervise_once):
        # ejections (replica removed from the ring), failovers
        # (ejections that moved >= 1 in-flight stream), migrated
        # streams (failover + hedge moves), half-open probes, hedges
        self.serve_fleet_ejections_total = Counter(
            "kubeml_serve_fleet_ejections_total",
            "Replicas ejected from the ring (dead or crash-looping)",
            "model")
        self.serve_fleet_failovers_total = Counter(
            "kubeml_serve_fleet_failovers_total",
            "Ejections that live-migrated at least one stream", "model")
        self.serve_fleet_migrated_streams_total = Counter(
            "kubeml_serve_fleet_migrated_streams_total",
            "In-flight streams resumed on another replica", "model")
        self.serve_fleet_probes_total = Counter(
            "kubeml_serve_fleet_probes_total",
            "Half-open probe requests routed to probation replicas",
            "model")
        self.serve_fleet_hedges_total = Counter(
            "kubeml_serve_fleet_hedges_total",
            "Queued streams re-issued off a straggler replica", "model")
        # serving SLO plane (serve/slo.py), fed by the fleet's merged
        # snapshot: attainment and the fast/slow burn-rate windows as
        # gauges (window rides a LABEL, not a family suffix), the
        # good/bad classification and burn-alert onsets as counters
        self.serve_slo_attainment = Gauge(
            "kubeml_serve_slo_attainment",
            "Fraction of finished requests meeting the model's latency "
            "SLO over the slow burn window", "model")
        self.serve_slo_burn_rate = MultiGauge(
            "kubeml_serve_slo_burn_rate",
            "SLO error-budget burn rate (bad fraction over 1-target), "
            "by window (fast|slow)", ("model", "window"))
        self.serve_slo_good_total = Counter(
            "kubeml_serve_slo_good_total",
            "Finished requests that met the model's latency SLO",
            "model")
        self.serve_slo_bad_total = Counter(
            "kubeml_serve_slo_bad_total",
            "Finished requests that missed the model's latency SLO "
            "(slow, errored, or deadline-expired)", "model")
        self.serve_slo_burn_alerts_total = Counter(
            "kubeml_serve_slo_burn_alerts_total",
            "Multi-window SLO burn alert onsets (fast AND slow burn "
            "above 1.0)", "model")
        # cluster allocator (control/cluster.py), fed by the scheduler's
        # snapshot pushes (POST /cluster): pool occupancy, queue depth
        # by priority, per-tenant lanes vs quota/weighted share, and
        # the lifetime decision counters (placements/preemptions/aged
        # grants/quota clamps — snapshots carry cumulative values, the
        # counters advance by delta like jit_compiles_total)
        self.cluster_pool_lanes = Gauge(
            "kubeml_cluster_pool_lanes",
            "Worker lanes in the shared cluster pool", "pool")
        self.cluster_lanes_in_use = Gauge(
            "kubeml_cluster_lanes_in_use",
            "Worker lanes currently leased to placed jobs", "pool")
        self.cluster_running_jobs = Gauge(
            "kubeml_cluster_running_jobs",
            "Jobs holding lanes in the shared pool", "pool")
        self.cluster_queue_depth = Gauge(
            "kubeml_cluster_queue_depth",
            "Jobs parked by the cluster allocator, by priority",
            "priority")
        self.cluster_oldest_wait = Gauge(
            "kubeml_cluster_oldest_wait_seconds",
            "Queue wait of the longest-parked job", "pool")
        self.cluster_tenant_lanes = Gauge(
            "kubeml_cluster_tenant_lanes",
            "Worker lanes leased to a tenant's running jobs", "tenant")
        self.cluster_tenant_quota = Gauge(
            "kubeml_cluster_tenant_quota_lanes",
            "Lane quota of a tenant (hard cap)", "tenant")
        self.cluster_tenant_share = Gauge(
            "kubeml_cluster_tenant_share",
            "Fraction of the pool a tenant's running jobs hold",
            "tenant")
        self.cluster_gang_placements_total = Counter(
            "kubeml_cluster_gang_placements_total",
            "Atomic gang placements by the cluster allocator", "pool")
        self.cluster_preemptions_total = Counter(
            "kubeml_cluster_preemptions_total",
            "Victims displaced by higher-priority arrivals", "pool")
        self.cluster_aged_grants_total = Counter(
            "kubeml_cluster_aged_grants_total",
            "Placements that needed aging to outrank newer arrivals",
            "pool")
        self.cluster_quota_clamps_total = Counter(
            "kubeml_cluster_quota_clamps_total",
            "Gang or resize asks clamped to a tenant quota", "pool")
        # analytic cost ledger (metrics/ledger.py): deterministic
        # per-program cost attribution — FLOPs / HBM bytes / dispatch
        # counts keyed by compiled program name and plane
        # (train|serve|kernel). Values come from XLA cost_analysis at
        # compile capture (or the closed-form fallback) times the
        # dispatch count, so they are model-derived, never timers;
        # cardinality is bounded by the fixed program registry.
        self.cost_flops_total = Counter(
            "kubeml_cost_flops_total",
            "Analytic-ledger FLOPs dispatched, by compiled program and "
            "plane", ("program", "plane"))
        self.cost_hbm_bytes_total = Counter(
            "kubeml_cost_hbm_bytes_total",
            "Analytic-ledger HBM bytes moved, by compiled program and "
            "plane", ("program", "plane"))
        self.cost_dispatches_total = Counter(
            "kubeml_cost_dispatches_total",
            "Device dispatches counted by the analytic cost ledger, by "
            "program and plane", ("program", "plane"))
        # durable control plane (control/journal.py): recovery counts
        # and latency per role, decision-journal activity, and stale
        # grants rejected by the fencing epoch — the split-brain signal
        self.control_recoveries_total = Counter(
            "kubeml_control_recoveries_total",
            "Control-plane crash recoveries completed", "role")
        self.control_journal_records_total = Counter(
            "kubeml_control_journal_records_total",
            "Decision-journal records appended", "role")
        self.control_journal_compactions_total = Counter(
            "kubeml_control_journal_compactions_total",
            "Decision-journal snapshot compactions", "role")
        self.control_fencing_rejections_total = Counter(
            "kubeml_control_fencing_rejections_total",
            "Stale lane grants rejected by their fencing epoch", "role")
        self.control_recovery_seconds = Histogram(
            "kubeml_control_recovery_seconds",
            "Wall seconds one control-plane role took to recover",
            "role")
        self.control_fencing_epoch = Gauge(
            "kubeml_control_fencing_epoch",
            "Current fencing epoch of the lane-grant allocator "
            "(bumped on every recovery)", "pool")
        # MetricUpdate carries these as cumulative-over-the-job values;
        # the counters advance by delta so they stay monotone even when
        # an update is replayed after a job restart
        self._jit_seen: Dict[str, float] = {}
        self._trace_seen: Dict[str, float] = {}
        self._job_gauges = [self.validation_loss, self.validation_accuracy,
                            self.train_loss, self.parallelism,
                            self.epoch_duration, self.dropped_workers,
                            self.quarantined_workers, self.restarts,
                            self.reassigned_batches, self.preemptions,
                            self.checkpoint_drops, self.heartbeat_epoch,
                            self.heartbeat_round, self.loss_spread,
                            self.dataset_generation,
                            self.data_lag_generations]
        self._job_hists = [self.dispatch_seconds, self.data_wait_seconds,
                           self.merge_seconds, self.merge_overlap_seconds]
        self._job_multi = [self.job_health, self.worker_grad_norm,
                           self.worker_update_ratio, self.hbm_bytes]
        self._job_counters = [self.health_alerts_total,
                              self.jit_compiles_total,
                              self.trace_dropped_total]
        self._serve_gauges = [self.serve_active_slots,
                              self.serve_queue_depth,
                              self.serve_kv_utilization,
                              self.serve_prefill_backlog,
                              self.serve_weight_generation,
                              self.serve_fleet_replicas,
                              self.serve_slo_attainment,
                              self.infer_cache_entries]
        # (model, window)-labelled: cleared per window in clear_serve,
        # so it stays out of the single-label _serve_gauges clear loop
        self._serve_multi_gauges = [self.serve_slo_burn_rate]
        self._serve_hists = [self.serve_ttft_seconds,
                             self.serve_tpot_seconds,
                             self.serve_e2e_seconds,
                             self.serve_stream_duration_seconds]
        # (model, component)-labelled: cleared per component, so it
        # stays out of the single-label _serve_hists clear loop
        self._serve_multi_hists = [self.serve_ttft_breakdown_seconds]
        self._serve_counters = [self.serve_requests_total,
                                self.serve_tokens_total,
                                self.serve_prefill_tokens_total,
                                self.serve_decode_tokens_total,
                                self.serve_prefix_hits_total,
                                self.serve_prefix_misses_total,
                                self.serve_engine_restarts_total,
                                self.serve_poisoned_total,
                                self.serve_page_leaks_total,
                                self.serve_kv_bytes_total,
                                self.serve_draft_tokens_total,
                                self.serve_accepted_tokens_total,
                                self.serve_rejected_tokens_total,
                                self.serve_fleet_spills_total,
                                self.serve_fleet_router_retries_total,
                                self.serve_fleet_cold_starts_total,
                                self.serve_fleet_scale_events_total,
                                self.serve_fleet_replica_prefix_hits_total,
                                self.serve_fleet_replica_prefix_misses_total,
                                self.serve_fleet_ejections_total,
                                self.serve_fleet_failovers_total,
                                self.serve_fleet_migrated_streams_total,
                                self.serve_fleet_probes_total,
                                self.serve_fleet_hedges_total,
                                self.serve_slo_good_total,
                                self.serve_slo_bad_total,
                                self.serve_slo_burn_alerts_total,
                                self.infer_cache_hits_total,
                                self.infer_cache_misses_total]
        self._cluster_gauges = [self.cluster_pool_lanes,
                                self.cluster_lanes_in_use,
                                self.cluster_running_jobs,
                                self.cluster_queue_depth,
                                self.cluster_oldest_wait,
                                self.cluster_tenant_lanes,
                                self.cluster_tenant_quota,
                                self.cluster_tenant_share,
                                self.control_fencing_epoch]
        self._cluster_counters = [self.cluster_gang_placements_total,
                                  self.cluster_preemptions_total,
                                  self.cluster_aged_grants_total,
                                  self.cluster_quota_clamps_total,
                                  self.control_recoveries_total,
                                  self.control_journal_records_total,
                                  self.control_journal_compactions_total,
                                  self.control_fencing_rejections_total]
        # cumulative counter values seen per snapshot field, for the
        # delta advance in update_cluster
        self._cluster_seen: Dict[str, float] = {}
        # (model, field) -> cumulative seen, for update_fleet's deltas
        self._fleet_seen: Dict[tuple, float] = {}
        # (owner, program, field) -> cumulative seen, for update_cost's
        # deltas; owner is a train job id or serve:<model> so two
        # sources sharing a program name stay independently monotone
        self._cost_seen: Dict[tuple, float] = {}

    def update_job(self, m) -> None:
        """Apply a MetricUpdate (ml/pkg/ps/metrics.go:90-99)."""
        self.validation_loss.set(m.job_id, m.validation_loss)
        self.validation_accuracy.set(m.job_id, m.accuracy)
        self.train_loss.set(m.job_id, m.train_loss)
        self.parallelism.set(m.job_id, m.parallelism)
        self.epoch_duration.set(m.job_id, m.epoch_duration)
        self.dropped_workers.set(m.job_id, m.dropped_workers)
        self.quarantined_workers.set(m.job_id, m.quarantined_workers)
        self.reassigned_batches.set(
            m.job_id, getattr(m, "reassigned_batches", 0))
        self.checkpoint_drops.set(
            m.job_id, getattr(m, "checkpoint_drops", 0))
        for span, attr in PHASE_HISTOGRAMS.items():
            hist = getattr(self, attr)
            for seconds in getattr(m, "phase_times", {}).get(span, ()):
                hist.observe(m.job_id, seconds)
        # training-health stat lanes: re-key the per-worker series each
        # epoch so a parallelism shrink doesn't leave stale workers
        grad_norms = getattr(m, "grad_norms", None) or []
        update_ratios = getattr(m, "update_ratios", None) or []
        if grad_norms or update_ratios:
            self.worker_grad_norm.clear_prefix(m.job_id)
            self.worker_update_ratio.clear_prefix(m.job_id)
            for i, gn in enumerate(grad_norms):
                self.worker_grad_norm.set((m.job_id, str(i)), gn)
            for i, ur in enumerate(update_ratios):
                self.worker_update_ratio.set((m.job_id, str(i)), ur)
            self.loss_spread.set(m.job_id, getattr(m, "loss_spread", 0.0))
        peak = getattr(m, "hbm_peak_bytes", 0)
        if peak:
            self.hbm_bytes.set((m.job_id, "peak"), peak)
            self.hbm_bytes.set((m.job_id, "in_use"),
                               getattr(m, "hbm_in_use_bytes", 0))
        for cum, seen, counter in (
                (getattr(m, "jit_compiles", 0), self._jit_seen,
                 self.jit_compiles_total),
                (getattr(m, "trace_events_dropped", 0), self._trace_seen,
                 self.trace_dropped_total)):
            if cum > seen.get(m.job_id, 0):
                counter.inc(m.job_id, cum - seen.get(m.job_id, 0))
                seen[m.job_id] = cum
        # continual-plane freshness: lag < 0 marks a non-continual job
        # (the field's wire default), which publishes neither gauge
        lag = getattr(m, "data_lag_generations", -1)
        if lag is not None and lag >= 0:
            self.dataset_generation.set(
                m.job_id, getattr(m, "dataset_generation", 0))
            self.data_lag_generations.set(m.job_id, lag)
        self.update_cost(m.job_id, getattr(m, "cost_programs", None))

    def update_cost(self, owner: str, cost_programs) -> None:
        """Advance the kubeml_cost_* counters from one cumulative
        ledger snapshot (CostLedger.snapshot(): one flat dict per
        program carrying the per-dispatch record plus attributed
        totals). `owner` scopes the seen-dict (a train job id or
        serve:<model>) so replayed snapshots and restarts stay
        monotone per source, while the exposed series aggregate by
        (program, plane) only — program names are the identity, the
        same decode program costs the same wherever it runs."""
        for program, entry in (cost_programs or {}).items():
            plane = str(entry.get("plane", "train"))
            for field, counter in (
                    ("flops_total", self.cost_flops_total),
                    ("hbm_bytes_total", self.cost_hbm_bytes_total),
                    ("dispatches", self.cost_dispatches_total)):
                cum = float(entry.get(field, 0))
                seen = self._cost_seen.get((owner, program, field), 0.0)
                if cum > seen:
                    counter.inc((program, plane), cum - seen)
                    self._cost_seen[(owner, program, field)] = cum

    def note_restart(self, job_id: str) -> None:
        """One watchdog restart: bump the per-job gauge and the
        PS-lifetime total (the total survives clear_job, so a crashy
        job's history stays visible after it finishes)."""
        self.restarts.inc(job_id)
        self.restarts_total.inc("standalone")

    def note_preemption(self, job_id: str) -> None:
        """One graceful preemption reschedule (same per-job gauge +
        lifetime total split as restarts)."""
        self.preemptions.inc(job_id)
        self.preemptions_total.inc("standalone")

    def note_heartbeat(self, job_id: str, epoch: int, rnd: int) -> None:
        self.heartbeat_epoch.set(job_id, epoch)
        self.heartbeat_round.set(job_id, rnd)

    def note_wedged(self, job_id: str) -> None:
        """Heartbeat reaper kill; the restart itself is counted by the
        watchdog path the kill routes into."""
        self.wedged_total.inc("standalone")

    def set_health(self, job_id: str, state: str) -> None:
        """Publish a job's health verdict: 1 on the active state's
        series, 0 on the rest (so a state change flips atomically for
        scrapers instead of briefly showing two active states)."""
        for s in HEALTH_STATES:
            self.job_health.set((job_id, s), 1.0 if s == state else 0.0)

    def note_health_alert(self, job_id: str, rule: str) -> None:
        self.health_alerts_total.inc((job_id, rule))

    # ------------------------------------------------------- serving plane

    def observe_serve_request(self, model: str, outcome: str) -> None:
        self.serve_requests_total.inc((model, outcome))

    def observe_serve_latency(self, model: str, ttft: float = None,
                              tpot: float = None,
                              e2e: float = None) -> None:
        if ttft is not None:
            self.serve_ttft_seconds.observe(model, ttft)
        if tpot is not None:
            self.serve_tpot_seconds.observe(model, tpot)
        if e2e is not None:
            self.serve_e2e_seconds.observe(model, e2e)

    def set_serve_state(self, model: str, active_slots: float,
                        queue_depth: float, kv_utilization: float,
                        prefill_backlog: float = 0.0) -> None:
        self.serve_active_slots.set(model, active_slots)
        self.serve_queue_depth.set(model, queue_depth)
        self.serve_kv_utilization.set(model, kv_utilization)
        self.serve_prefill_backlog.set(model, prefill_backlog)

    def set_serve_weight_generation(self, model: str, gen: int) -> None:
        self.serve_weight_generation.set(model, float(gen))

    def note_serve_tokens(self, model: str, n: int) -> None:
        self.serve_tokens_total.inc(model, n)

    def note_serve_prefill(self, model: str, n: int) -> None:
        self.serve_prefill_tokens_total.inc(model, n)

    def note_serve_decode(self, model: str, n: int) -> None:
        self.serve_decode_tokens_total.inc(model, n)

    def note_serve_prefix_hits(self, model: str, n: int) -> None:
        self.serve_prefix_hits_total.inc(model, n)

    def note_serve_prefix_misses(self, model: str, n: int) -> None:
        self.serve_prefix_misses_total.inc(model, n)

    def note_serve_engine_restart(self, model: str) -> None:
        self.serve_engine_restarts_total.inc(model)

    def note_serve_poisoned(self, model: str) -> None:
        self.serve_poisoned_total.inc(model)

    def note_serve_page_leaks(self, model: str, n: int) -> None:
        self.serve_page_leaks_total.inc(model, n)

    def note_serve_kv_bytes(self, model: str, n: int) -> None:
        self.serve_kv_bytes_total.inc(model, n)

    def note_serve_draft_tokens(self, model: str, n: int) -> None:
        self.serve_draft_tokens_total.inc(model, n)

    def note_serve_accepted_tokens(self, model: str, n: int) -> None:
        self.serve_accepted_tokens_total.inc(model, n)

    def note_serve_rejected_tokens(self, model: str, n: int) -> None:
        self.serve_rejected_tokens_total.inc(model, n)

    def observe_serve_ttft_breakdown(self, model: str, queue: float,
                                     prefill: float,
                                     interleave: float) -> None:
        self.serve_ttft_breakdown_seconds.observe((model, "queue"), queue)
        self.serve_ttft_breakdown_seconds.observe((model, "prefill"),
                                                  prefill)
        self.serve_ttft_breakdown_seconds.observe((model, "interleave"),
                                                  interleave)

    def observe_serve_stream(self, model: str, seconds: float) -> None:
        self.serve_stream_duration_seconds.observe(model, seconds)

    def note_serve_trace_dropped(self, model: str, cum: int) -> None:
        """Advance kubeml_trace_events_dropped_total for a serving
        sink's drops, under the serve:<model> pseudo-job id — the value
        is cumulative over the service's life (Tracer.dropped_events),
        the counter advances by delta like the training-plane path in
        update_job."""
        job_id = f"serve:{model}"
        seen = self._trace_seen.get(job_id, 0)
        if cum > seen:
            self.trace_dropped_total.inc(job_id, cum - seen)
            self._trace_seen[job_id] = cum

    def update_fleet(self, model: str, snap: dict) -> None:
        """Apply one merged fleet snapshot (serve/fleet.py). The gauge
        mirrors the live replica count; lifetime counters advance by
        delta against the snapshot's cumulative values (the
        update_cluster discipline, so republished snapshots stay
        monotone); the per-replica prefix hit/miss fields are already
        deltas and feed their counters directly."""
        self.serve_fleet_replicas.set(
            model, float(snap.get("fleet_replicas", 0)))
        # SLO plane: attainment + burn windows mirror the snapshot
        # (gauges), classification counters advance by delta
        self.serve_slo_attainment.set(
            model, float(snap.get("serve_slo_attainment", 1.0)))
        self.serve_slo_burn_rate.set(
            (model, "fast"), float(snap.get("serve_slo_burn_fast", 0.0)))
        self.serve_slo_burn_rate.set(
            (model, "slow"), float(snap.get("serve_slo_burn_slow", 0.0)))
        for field, counter in (
                ("fleet_spills_total", self.serve_fleet_spills_total),
                ("fleet_router_retries_total",
                 self.serve_fleet_router_retries_total),
                ("fleet_cold_starts_total",
                 self.serve_fleet_cold_starts_total),
                ("fleet_ejections_total",
                 self.serve_fleet_ejections_total),
                ("fleet_failovers_total",
                 self.serve_fleet_failovers_total),
                ("fleet_migrated_streams_total",
                 self.serve_fleet_migrated_streams_total),
                ("fleet_probes_total", self.serve_fleet_probes_total),
                ("fleet_hedges_total", self.serve_fleet_hedges_total),
                ("serve_slo_good_total", self.serve_slo_good_total),
                ("serve_slo_bad_total", self.serve_slo_bad_total),
                ("serve_slo_alerts_total",
                 self.serve_slo_burn_alerts_total)):
            cum = float(snap.get(field, 0))
            seen = self._fleet_seen.get((model, field), 0.0)
            if cum > seen:
                counter.inc(model, cum - seen)
                self._fleet_seen[(model, field)] = cum
        for field, action in (("fleet_grows_total", "grow"),
                              ("fleet_shrinks_total", "shrink"),
                              ("fleet_scale_to_zero_total",
                               "scale_to_zero")):
            cum = float(snap.get(field, 0))
            seen = self._fleet_seen.get((model, field), 0.0)
            if cum > seen:
                self.serve_fleet_scale_events_total.inc(
                    (model, action), cum - seen)
                self._fleet_seen[(model, field)] = cum
        for counter, field in (
                (self.serve_fleet_replica_prefix_hits_total,
                 "fleet_replica_prefix_hits"),
                (self.serve_fleet_replica_prefix_misses_total,
                 "fleet_replica_prefix_misses")):
            for replica, n in (snap.get(field) or {}).items():
                if n > 0:
                    counter.inc((model, str(replica)), float(n))
        self.update_cost(f"serve:{model}",
                         snap.get("serve_cost_programs"))

    def clear_serve(self, model: str) -> None:
        for g in (self.serve_active_slots, self.serve_queue_depth,
                  self.serve_kv_utilization, self.serve_prefill_backlog,
                  self.serve_weight_generation,
                  self.serve_fleet_replicas,
                  self.serve_slo_attainment):
            g.clear(model)
        self.serve_slo_burn_rate.clear_prefix(model)
        for h in self._serve_hists:
            h.clear(model)
        for comp in ("queue", "prefill", "interleave"):
            self.serve_ttft_breakdown_seconds.clear((model, comp))
        for c in (self.serve_requests_total, self.serve_tokens_total,
                  self.serve_prefill_tokens_total,
                  self.serve_decode_tokens_total,
                  self.serve_prefix_hits_total,
                  self.serve_prefix_misses_total,
                  self.serve_engine_restarts_total,
                  self.serve_poisoned_total,
                  self.serve_page_leaks_total,
                  self.serve_kv_bytes_total,
                  self.serve_draft_tokens_total,
                  self.serve_accepted_tokens_total,
                  self.serve_rejected_tokens_total,
                  self.serve_fleet_spills_total,
                  self.serve_fleet_router_retries_total,
                  self.serve_fleet_cold_starts_total,
                  self.serve_fleet_scale_events_total,
                  self.serve_fleet_replica_prefix_hits_total,
                  self.serve_fleet_replica_prefix_misses_total,
                  self.serve_fleet_ejections_total,
                  self.serve_fleet_failovers_total,
                  self.serve_fleet_migrated_streams_total,
                  self.serve_fleet_probes_total,
                  self.serve_fleet_hedges_total,
                  self.serve_slo_good_total,
                  self.serve_slo_bad_total,
                  self.serve_slo_burn_alerts_total):
            c.clear_prefix(model)
        self.trace_dropped_total.clear_prefix(f"serve:{model}")
        self._trace_seen.pop(f"serve:{model}", None)
        for key in [k for k in self._fleet_seen if k[0] == model]:
            del self._fleet_seen[key]
        for key in [k for k in self._cost_seen
                    if k[0] == f"serve:{model}"]:
            del self._cost_seen[key]

    # ---------------------------------------------------- cluster allocator

    def update_cluster(self, snap: dict) -> None:
        """Apply one allocator snapshot (control/cluster.py
        ClusterAllocator.snapshot(), pushed by the scheduler). Gauges
        mirror the snapshot; per-priority/per-tenant series absent from
        it zero out (a drained priority level must not linger at its
        last depth); lifetime counters advance by delta so replays
        after a scheduler restart stay monotone."""
        self.cluster_pool_lanes.set(
            "shared", float(snap.get("cluster_pool_lanes", 0)))
        self.cluster_lanes_in_use.set(
            "shared", float(snap.get("cluster_lanes_in_use", 0)))
        self.cluster_running_jobs.set(
            "shared", float(snap.get("cluster_running_jobs", 0)))
        self.cluster_oldest_wait.set(
            "shared", float(snap.get("cluster_oldest_wait_s", 0.0)))
        by_prio = snap.get("cluster_queue_by_priority") or {}
        with self.cluster_queue_depth._lock:
            stale = [k for k in self.cluster_queue_depth._values
                     if k not in by_prio]
        for k in stale:
            self.cluster_queue_depth.set(k, 0.0)
        for prio, depth in by_prio.items():
            self.cluster_queue_depth.set(str(prio), float(depth))
        pool = float(snap.get("cluster_pool_lanes", 0)) or 1.0
        lanes = snap.get("cluster_tenant_lanes") or {}
        quotas = snap.get("cluster_tenant_quota") or {}
        for t, n in lanes.items():
            self.cluster_tenant_lanes.set(t, float(n))
            self.cluster_tenant_share.set(t, float(n) / pool)
        for t, q in quotas.items():
            self.cluster_tenant_quota.set(t, float(q))
        for field, counter in (
                ("cluster_gang_placements_total",
                 self.cluster_gang_placements_total),
                ("cluster_preemptions_total",
                 self.cluster_preemptions_total),
                ("cluster_aged_grants_total",
                 self.cluster_aged_grants_total),
                ("cluster_quota_clamps_total",
                 self.cluster_quota_clamps_total)):
            cum = float(snap.get(field, 0))
            seen = self._cluster_seen.get(field, 0.0)
            if cum > seen:
                counter.inc("shared", cum - seen)
                self._cluster_seen[field] = cum
        # durable control plane: the allocator's journaled lifetime
        # counters (they survive restart, so deltas stay monotone
        # across control-plane incarnations)
        self.control_fencing_epoch.set(
            "shared", float(snap.get("cluster_fencing_epoch", 0)))
        for field, counter, role in (
                ("cluster_recoveries_total",
                 self.control_recoveries_total, "allocator"),
                ("cluster_journal_records_total",
                 self.control_journal_records_total, "allocator"),
                ("cluster_journal_compactions_total",
                 self.control_journal_compactions_total, "allocator"),
                ("cluster_fencing_rejections_total",
                 self.control_fencing_rejections_total, "allocator")):
            cum = float(snap.get(field, 0))
            seen = self._cluster_seen.get(field, 0.0)
            if cum > seen:
                counter.inc(role, cum - seen)
                self._cluster_seen[field] = cum
        # a just-recovered scheduler stamps its recovery duration onto
        # its first snapshot push
        rs = snap.get("control_recovery_s")
        if rs is not None:
            self.note_control_recovery(
                str(snap.get("control_role", "scheduler")), float(rs))

    def note_control_recovery(self, role: str, seconds: float) -> None:
        """One completed control-plane recovery for `role` (scheduler /
        ps / allocator): lifetime count + wall-seconds histogram."""
        self.control_recoveries_total.inc(role)
        self.control_recovery_seconds.observe(role, seconds)

    def note_infer_cache(self, hit: bool, cache: str = "checkpoints") -> None:
        (self.infer_cache_hits_total if hit
         else self.infer_cache_misses_total).inc(cache)

    def set_infer_cache_entries(self, n: int,
                                cache: str = "checkpoints") -> None:
        self.infer_cache_entries.set(cache, n)

    def clear_job(self, job_id: str) -> None:
        for g in self._job_gauges:
            g.clear(job_id)
        for h in self._job_hists:
            h.clear(job_id)
        for mg in self._job_multi:
            mg.clear_prefix(job_id)
        for c in self._job_counters:
            c.clear_prefix(job_id)
        self._jit_seen.pop(job_id, None)
        self._trace_seen.pop(job_id, None)
        # the (program, plane) cost series are PS-lifetime aggregates,
        # not job series — only the per-owner seen baseline is dropped
        for key in [k for k in self._cost_seen if k[0] == job_id]:
            del self._cost_seen[key]

    def exposition(self) -> str:
        families = (self._job_gauges + [self.running_total,
                                        self.restarts_total,
                                        self.preemptions_total,
                                        self.wedged_total,
                                        self.health_alerts_total,
                                        self.jit_compiles_total,
                                        self.trace_dropped_total]
                    + self._job_multi + self._job_hists
                    + self._serve_gauges + self._serve_multi_gauges
                    + self._serve_counters
                    + self._serve_hists + self._serve_multi_hists
                    + self._cluster_gauges + self._cluster_counters
                    + [self.cost_flops_total, self.cost_hbm_bytes_total,
                       self.cost_dispatches_total,
                       self.control_recovery_seconds])
        return "\n".join(f.collect() for f in families) + "\n"
