"""Analytic cost ledger: per-program HLO cost attribution.

Every jitted program the system runs — the kavg/syncdp round programs,
the serve inventory {decode, prefill, multi-step, spec-verify}, and the
closed-form kernel proxies (fused merge wire plan, paged-attention KV
traffic) — gets one deterministic `ProgramCost` record: flops, HBM
bytes accessed, transcendentals, peak temp memory.  Records come from
XLA's own cost model (`lowered.compile().cost_analysis()` /
`memory_analysis()` — the same numbers XProf attributes on hardware)
captured AOT at first compile, with a caller-supplied closed-form
fallback when a backend exposes no cost analysis.  The AOT path
(`jitfn.lower(*args).compile()`) reads only avals, so donated buffers
are safe, and it does NOT populate the jit fast-path cache — the
compile-count-pinned tests stay exact (verified: `_cache_size()` is
unchanged by an AOT lower+compile).

The ledger then counts dispatches so cost is *attributed*, not just
cataloged: flops/sample and bytes/sample on the train plane (samples
merged across lanes by the engines), flops/token and bytes/token on the
serve plane.  Attribution is the roofline question made assertable
(Williams et al., CACM 2009): arithmetic intensity = flops / HBM bytes
per program, a hardware-independent position that CI can gate on
(tools/check_cost_budgets.py) because identical HLO yields bit-identical
analysis on every run.

Reconciliation is the anti-drift contract: the hand-derived proxies
that predate the ledger (merge.py `comm_proxy`, pager.py
`decode_bytes_per_token`, the bench arms' inline recomputations) are
cross-checked against ledger records via `reconcile()` — exact for
pure-counter fields, ±tolerance for XLA-derived fields — and a mismatch
raises `CostReconciliationError` instead of silently drifting.

Totals accumulate incrementally (`note_dispatch` adds the CURRENT
record's per-dispatch cost), so with stable shapes the invariant
`totals == dispatches x per-dispatch cost` replays exactly; a
mid-run recapture (shape change) bumps `recaptures` so the replay
check knows when the invariant is per-segment rather than global.

Everything here is host-side bookkeeping: capture costs one extra AOT
compile per program per process (disable with KUBEML_COST_LEDGER=0),
`note_dispatch` is a few dict adds on the host, and nothing touches
the device dispatch path.
"""

from __future__ import annotations

import json
import os
import threading
from dataclasses import asdict, dataclass
from typing import Dict, Iterable, List, Optional

PLANES = ("train", "serve", "kernel")

# cost-analysis sources, strongest first: "xla" = HLO cost model,
# "analytic" = exact closed-form counter (pure host arithmetic over
# shapes — deterministic by construction), "fallback" = closed-form
# estimate used because the backend exposed no cost analysis
SOURCES = ("xla", "analytic", "fallback")

# documented tolerance for reconciling an XLA-derived byte count
# against a closed-form proxy: XLA counts every operand's traffic
# (params, masks, indices) on top of the proxy's modeled payload, and
# fusion can remove intermediate traffic the proxy counts, so the two
# agree in magnitude, not bit-for-bit.  Pure-counter reconciliations
# pass tol=0.0 and must match exactly.
XLA_PROXY_TOLERANCE = 0.50


def _enabled() -> bool:
    return os.environ.get("KUBEML_COST_LEDGER", "1") != "0"


class CostReconciliationError(AssertionError):
    """A ledger record disagrees with the proxy it must reconcile with.

    Raised loudly (not logged-and-ignored): the whole point of the
    ledger is that the closed-form proxies and the HLO cost model can
    never drift apart silently again."""


@dataclass(frozen=True)
class ProgramCost:
    """One compiled program's deterministic per-dispatch cost record."""

    program: str            # registry name, e.g. "kavg.train"
    plane: str              # "train" | "serve" | "kernel"
    flops: float            # HLO cost model flop count per dispatch
    hbm_bytes: float        # total bytes accessed per dispatch
    transcendentals: float  # exp/log/tanh… op count per dispatch
    peak_temp_bytes: int    # XLA temp allocation high-water mark
    argument_bytes: int = 0
    output_bytes: int = 0
    source: str = "xla"

    def __post_init__(self):
        if self.plane not in PLANES:
            raise ValueError(f"unknown plane {self.plane!r}")
        if self.source not in SOURCES:
            raise ValueError(f"unknown source {self.source!r}")

    @property
    def arithmetic_intensity(self) -> float:
        """Roofline x-coordinate: flops per HBM byte accessed."""
        return self.flops / self.hbm_bytes if self.hbm_bytes else 0.0

    def to_dict(self) -> dict:
        return asdict(self)

    @classmethod
    def from_dict(cls, d: dict) -> "ProgramCost":
        return cls(program=str(d["program"]), plane=str(d["plane"]),
                   flops=float(d["flops"]),
                   hbm_bytes=float(d["hbm_bytes"]),
                   transcendentals=float(d.get("transcendentals", 0.0)),
                   peak_temp_bytes=int(d.get("peak_temp_bytes", 0)),
                   argument_bytes=int(d.get("argument_bytes", 0)),
                   output_bytes=int(d.get("output_bytes", 0)),
                   source=str(d.get("source", "xla")))


def extract_xla_cost(jitfn, *args, **kwargs) -> Optional[dict]:
    """AOT-lower a jitted callable and read XLA's cost + memory
    analysis. Returns the raw field dict, or None when the backend
    exposes no usable analysis (the caller falls back to closed form).

    `.lower()` reads only avals — safe to call with buffers the real
    dispatch will donate — and the resulting executable is thrown away
    (it never enters the jit fast-path cache)."""
    try:
        compiled = jitfn.lower(*args, **kwargs).compile()
        ca = compiled.cost_analysis()
        if isinstance(ca, (list, tuple)):
            ca = ca[0] if ca else None
        if not isinstance(ca, dict) or "flops" not in ca:
            return None
        fields = {
            "flops": float(ca.get("flops", 0.0)),
            "hbm_bytes": float(ca.get("bytes accessed", 0.0)),
            "transcendentals": float(ca.get("transcendentals", 0.0)),
        }
        try:
            mem = compiled.memory_analysis()
            fields["peak_temp_bytes"] = int(
                getattr(mem, "temp_size_in_bytes", 0))
            fields["argument_bytes"] = int(
                getattr(mem, "argument_size_in_bytes", 0))
            fields["output_bytes"] = int(
                getattr(mem, "output_size_in_bytes", 0))
        except Exception:
            fields.update(peak_temp_bytes=0, argument_bytes=0,
                          output_bytes=0)
        return fields
    except Exception:
        return None


def _zero_totals() -> dict:
    return {"dispatches": 0, "flops_total": 0.0, "hbm_bytes_total": 0.0,
            "transcendentals_total": 0.0, "samples": 0, "tokens": 0,
            "recaptures": 0}


class CostLedger:
    """Per-process program registry + dispatch-attributed cost totals.

    Thread-safe: serve engines note dispatches from their loop thread
    while the PS snapshots from HTTP handlers."""

    def __init__(self, capture_enabled: Optional[bool] = None):
        # capture_enabled pins the XLA-capture decision for this ledger
        # regardless of KUBEML_COST_LEDGER (None = follow the env); the
        # canonical budget inventory uses True so the gate's numbers
        # never depend on ambient environment
        self._lock = threading.Lock()
        self._capture_enabled = capture_enabled
        self._programs: Dict[str, ProgramCost] = {}
        self._totals: Dict[str, dict] = {}

    # ------------------------------------------------------------ capture

    def capture(self, program: str, plane: str, jitfn, *args,
                fallback: Optional[dict] = None, **kwargs) -> ProgramCost:
        """Record `program`'s per-dispatch cost from XLA's analysis of
        the jitted callable at the given example args (call at first
        compile, with the concrete args about to be dispatched).  When
        the backend has no cost analysis — or KUBEML_COST_LEDGER=0
        skips the extra AOT compile — the closed-form `fallback` dict
        ({"flops":, "hbm_bytes":, "transcendentals":}) stands in with
        source="fallback".  Re-capturing an already-known program
        (shape change → recompile) replaces the record and bumps
        `recaptures` so replay checks can tell."""
        enabled = self._capture_enabled \
            if self._capture_enabled is not None else _enabled()
        fields = extract_xla_cost(jitfn, *args, **kwargs) \
            if enabled else None
        if fields is not None:
            rec = ProgramCost(program=program, plane=plane,
                              source="xla", **fields)
        else:
            fb = fallback or {}
            rec = ProgramCost(
                program=program, plane=plane,
                flops=float(fb.get("flops", 0.0)),
                hbm_bytes=float(fb.get("hbm_bytes", 0.0)),
                transcendentals=float(fb.get("transcendentals", 0.0)),
                peak_temp_bytes=int(fb.get("peak_temp_bytes", 0)),
                argument_bytes=int(fb.get("argument_bytes", 0)),
                output_bytes=int(fb.get("output_bytes", 0)),
                source="fallback")
        self._install(rec)
        return rec

    def capture_analytic(self, program: str, plane: str, *,
                         flops: float = 0.0, hbm_bytes: float = 0.0,
                         transcendentals: float = 0.0,
                         peak_temp_bytes: int = 0,
                         argument_bytes: int = 0,
                         output_bytes: int = 0) -> ProgramCost:
        """Record a pure-counter program: exact closed-form host
        arithmetic over shapes (merge wire plans, KV page traffic).
        These reconcile exactly (tol=0) and budget exactly."""
        rec = ProgramCost(program=program, plane=plane, flops=flops,
                          hbm_bytes=hbm_bytes,
                          transcendentals=transcendentals,
                          peak_temp_bytes=peak_temp_bytes,
                          argument_bytes=argument_bytes,
                          output_bytes=output_bytes, source="analytic")
        self._install(rec)
        return rec

    def _install(self, rec: ProgramCost) -> None:
        with self._lock:
            known = rec.program in self._programs
            self._programs[rec.program] = rec
            tot = self._totals.setdefault(rec.program, _zero_totals())
            if known:
                tot["recaptures"] += 1

    # ----------------------------------------------------------- dispatch

    def note_dispatch(self, program: str, n: int = 1, *,
                      samples: int = 0, tokens: int = 0) -> None:
        """Attribute `n` dispatches of `program` (and the samples /
        tokens they produced) at the program's CURRENT per-dispatch
        cost.  Unknown programs accumulate dispatch counts only — the
        record may arrive later (fallback capture after first use)."""
        if n <= 0 and samples <= 0 and tokens <= 0:
            return
        with self._lock:
            rec = self._programs.get(program)
            tot = self._totals.setdefault(program, _zero_totals())
            tot["dispatches"] += int(n)
            tot["samples"] += int(samples)
            tot["tokens"] += int(tokens)
            if rec is not None and n > 0:
                tot["flops_total"] += n * rec.flops
                tot["hbm_bytes_total"] += n * rec.hbm_bytes
                tot["transcendentals_total"] += n * rec.transcendentals

    # ------------------------------------------------------------- access

    def programs(self) -> List[str]:
        """Registry of known program names (JitCompileTracker keys its
        per-program recompile windows on these)."""
        with self._lock:
            return sorted(self._programs)

    def record(self, program: str) -> Optional[ProgramCost]:
        with self._lock:
            return self._programs.get(program)

    def totals(self, program: str) -> dict:
        with self._lock:
            return dict(self._totals.get(program, _zero_totals()))

    # -------------------------------------------------------- reconcile

    def reconcile(self, program: str, field: str, expected: float,
                  tolerance: float = 0.0) -> None:
        """Assert a record field against an independent proxy value.
        tol=0.0 → exact equality (pure-counter fields); tol>0 →
        relative |rec - expected| <= tol * max(|expected|, 1).  A miss
        raises CostReconciliationError — loud by design."""
        rec = self.record(program)
        if rec is None:
            raise CostReconciliationError(
                f"cost ledger has no record for {program!r} "
                f"(reconciling {field})")
        got = float(getattr(rec, field))
        expected = float(expected)
        if tolerance <= 0.0:
            ok = got == expected
        else:
            ok = abs(got - expected) <= tolerance * max(abs(expected), 1.0)
        if not ok:
            raise CostReconciliationError(
                f"cost ledger {program}.{field}={got!r} does not "
                f"reconcile with proxy value {expected!r} "
                f"(tolerance {tolerance:g}, source={rec.source})")

    # ------------------------------------------------------ attribution

    def attributed(self) -> dict:
        """Per-plane attributed cost: train flops/sample + bytes/sample
        (across both engines, lanes already merged into `samples` by
        the callers), serve flops/token + bytes/token."""
        with self._lock:
            planes: Dict[str, dict] = {}
            for name, rec in self._programs.items():
                tot = self._totals.get(name, _zero_totals())
                agg = planes.setdefault(rec.plane, {
                    "flops_total": 0.0, "hbm_bytes_total": 0.0,
                    "dispatches": 0, "samples": 0, "tokens": 0})
                agg["flops_total"] += tot["flops_total"]
                agg["hbm_bytes_total"] += tot["hbm_bytes_total"]
                agg["dispatches"] += tot["dispatches"]
                agg["samples"] += tot["samples"]
                agg["tokens"] += tot["tokens"]
        out = {}
        for plane, agg in planes.items():
            entry = dict(agg)
            if agg["samples"]:
                entry["flops_per_sample"] = agg["flops_total"] / agg["samples"]
                entry["bytes_per_sample"] = (
                    agg["hbm_bytes_total"] / agg["samples"])
            if agg["tokens"]:
                entry["flops_per_token"] = agg["flops_total"] / agg["tokens"]
                entry["bytes_per_token"] = (
                    agg["hbm_bytes_total"] / agg["tokens"])
            out[plane] = entry
        return out

    # --------------------------------------------------------- snapshot

    def snapshot(self) -> dict:
        """JSON-safe mergeable state: one entry per program carrying
        the record AND the attributed totals. This is what rides the
        MetricUpdate wire, the serve snapshot, and `GET /cost`."""
        with self._lock:
            return {name: {**rec.to_dict(),
                           **self._totals.get(name, _zero_totals())}
                    for name, rec in self._programs.items()}

    def replay_check(self) -> None:
        """Assert the ledger invariant `totals == dispatches x
        per-dispatch cost` for every stable (recapture-free) program —
        the bench arms run this before stamping their cost block."""
        snap = self.snapshot()
        for name, e in snap.items():
            if e["recaptures"]:
                continue
            for total_f, per_f in (("flops_total", "flops"),
                                   ("hbm_bytes_total", "hbm_bytes")):
                want = e["dispatches"] * e[per_f]
                if e[total_f] != want:
                    raise CostReconciliationError(
                        f"cost ledger replay mismatch for {name}: "
                        f"{total_f}={e[total_f]!r} != dispatches "
                        f"({e['dispatches']}) x {per_f} ({e[per_f]!r})")


def merge_cost_snapshots(snaps: Iterable[dict]) -> dict:
    """Merge per-process/per-replica ledger snapshots the way the fleet
    merges serve counters: totals SUM (a busy replica weighs more), the
    per-dispatch record comes from the first snapshot that has one (one
    engine config per fleet, so records agree across replicas)."""
    merged: Dict[str, dict] = {}
    for snap in snaps:
        for name, entry in (snap or {}).items():
            if name not in merged:
                merged[name] = dict(entry)
                continue
            m = merged[name]
            for k in ("dispatches", "samples", "tokens", "recaptures",
                      "flops_total", "hbm_bytes_total",
                      "transcendentals_total"):
                m[k] = m.get(k, 0) + entry.get(k, 0)
    return merged


def attributed_from_snapshot(snap: dict) -> dict:
    """Per-plane attribution over a (possibly merged) snapshot dict —
    the endpoint/CLI-side twin of CostLedger.attributed()."""
    planes: Dict[str, dict] = {}
    for entry in (snap or {}).values():
        agg = planes.setdefault(entry.get("plane", "kernel"), {
            "flops_total": 0.0, "hbm_bytes_total": 0.0,
            "dispatches": 0, "samples": 0, "tokens": 0})
        agg["flops_total"] += float(entry.get("flops_total", 0.0))
        agg["hbm_bytes_total"] += float(entry.get("hbm_bytes_total", 0.0))
        agg["dispatches"] += int(entry.get("dispatches", 0))
        agg["samples"] += int(entry.get("samples", 0))
        agg["tokens"] += int(entry.get("tokens", 0))
    out = {}
    for plane, agg in planes.items():
        entry = dict(agg)
        if agg["samples"]:
            entry["flops_per_sample"] = agg["flops_total"] / agg["samples"]
            entry["bytes_per_sample"] = agg["hbm_bytes_total"] / agg["samples"]
        if agg["tokens"]:
            entry["flops_per_token"] = agg["flops_total"] / agg["tokens"]
            entry["bytes_per_token"] = agg["hbm_bytes_total"] / agg["tokens"]
        out[plane] = entry
    return out


def snapshot_to_json(snap: dict) -> str:
    """Canonical serialization (sorted keys) so two processes that
    captured the same HLO produce byte-identical documents — the
    determinism contract tests/test_cost_ledger.py pins."""
    return json.dumps(snap, sort_keys=True)
