from kubeml_tpu.metrics.prom import (Counter, Gauge, Histogram,
                                     HttpMetrics, MetricsRegistry)

__all__ = ["Counter", "Gauge", "Histogram", "HttpMetrics",
           "MetricsRegistry"]
