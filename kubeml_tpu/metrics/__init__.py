from kubeml_tpu.metrics.ledger import (CostLedger, CostReconciliationError,
                                       ProgramCost, attributed_from_snapshot,
                                       merge_cost_snapshots)
from kubeml_tpu.metrics.prom import (Counter, Gauge, Histogram,
                                     HttpMetrics, MetricsRegistry)

__all__ = ["Counter", "Gauge", "Histogram", "HttpMetrics",
           "MetricsRegistry", "CostLedger", "CostReconciliationError",
           "ProgramCost", "attributed_from_snapshot",
           "merge_cost_snapshots"]
