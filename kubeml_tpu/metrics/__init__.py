from kubeml_tpu.metrics.prom import Gauge, MetricsRegistry

__all__ = ["Gauge", "MetricsRegistry"]
