"""Device-runtime introspection: jit-compile tracking and HBM watermarks.

Two perf regressions dominate TPU training postmortems and neither is
visible in loss curves: RECOMPILE STORMS (a drifting batch/round shape
makes every dispatch re-trace, so the job spends its epoch in XLA, not
on device) and HBM creep (a leaked reference or an unexpectedly
replicated layout walks peak memory up until allocation fails). Both
engines already know when they compiled (`RoundStats.compiled`,
`SyncDPEngine.last_compiled`) — this module turns those signals plus
`device.memory_stats()` into counters/gauges the job publishes per
epoch (`kubeml_jit_compiles_total`, `kubeml_device_hbm_bytes`).

Everything here is host-side bookkeeping — nothing touches the dispatch
path, and sampling memory_stats() is a cheap C++ call (no device sync).
"""

from __future__ import annotations

import logging
from typing import Dict, List, Optional, Tuple

logger = logging.getLogger("kubeml_tpu.metrics.runtime")

# a storm is many compiles CLOSE TOGETHER: this many compiles within the
# trailing window of notes flags it (tunable per tracker)
STORM_COMPILES = 3
STORM_WINDOW = 8


class JitCompileTracker:
    """Counts engine-program compiles and flags recompile storms.

    The job calls `note(compiled, duration_s, program=...)` once per
    dispatch with the engine's compile flag and the wall time of that
    dispatch (which, on a compile, is dominated by tracing+XLA). A
    healthy job compiles a handful of programs up front (one per
    distinct round shape) and never again; `storm` goes True when >=
    `storm_compiles` of the trailing `storm_window` dispatches compiled
    — the signature of shape drift (e.g. a ragged tail round shape
    changing every epoch, or batch-size churn defeating the program
    cache).

    Storm windows are PER PROGRAM, keyed on the cost ledger's registry
    names (metrics/ledger.py: "kavg.train", "serve.decode", …).  The
    old single global window mixed unrelated programs: with several
    jitted programs live in one process (a serve engine's four-program
    inventory, or an engine plus an eval round), each program's
    legitimate first compile landed in the same window and three
    healthy one-time compiles read as a storm — while a real storm in
    one program could hide behind a flood of healthy dispatches from
    another.  Keying the window on the program name makes detection
    exact and lets the storm log name the guilty program.  Un-named
    notes share the "" window, preserving the old behaviour for
    callers that predate program attribution.
    """

    def __init__(self, storm_compiles: int = STORM_COMPILES,
                 storm_window: int = STORM_WINDOW):
        self.storm_compiles = storm_compiles
        self.storm_window = storm_window
        self.compiles = 0
        self.dispatches = 0
        self.compile_seconds = 0.0
        self.storms = 0
        self.storm = False          # any program currently in storm
        self._recent: Dict[str, List[bool]] = {}
        self._storming: Dict[str, bool] = {}
        self.storms_by_program: Dict[str, int] = {}

    def note(self, compiled: bool, duration_s: float = 0.0,
             program: str = "") -> None:
        """Record one dispatch of `program`; duration only accumulates
        on compiles."""
        self.dispatches += 1
        recent = self._recent.setdefault(program, [])
        recent.append(bool(compiled))
        if len(recent) > self.storm_window:
            recent.pop(0)
        if compiled:
            self.compiles += 1
            self.compile_seconds += float(duration_s)
        in_storm = sum(recent) >= self.storm_compiles
        if in_storm and not self._storming.get(program, False):
            self.storms += 1
            self.storms_by_program[program] = \
                self.storms_by_program.get(program, 0) + 1
            logger.warning(
                "recompile storm in program %r: %d of the last %d "
                "dispatches compiled (%d compiles total) — check for "
                "round-shape drift", program or "<unattributed>",
                sum(recent), len(recent), self.compiles)
        self._storming[program] = in_storm
        self.storm = any(self._storming.values())

    def snapshot(self) -> Dict[str, float]:
        return {
            "jit_compiles": self.compiles,
            "jit_dispatches": self.dispatches,
            "jit_compile_seconds": round(self.compile_seconds, 6),
            "jit_storms": self.storms,
        }


def device_memory_stats(device=None) -> Optional[Tuple[int, int]]:
    """(peak_bytes, in_use_bytes) from the backend allocator, or None.

    TPU/GPU backends expose `device.memory_stats()` with
    `peak_bytes_in_use` / `bytes_in_use`; the CPU backend returns None
    (or lacks the method entirely), in which case callers fall back to
    `live_arrays_bytes` via HbmWatermark."""
    try:
        import jax
        if device is None:
            device = jax.devices()[0]
        stats = device.memory_stats()
    except Exception:
        return None
    if not stats:
        return None
    in_use = int(stats.get("bytes_in_use", 0))
    peak = int(stats.get("peak_bytes_in_use", in_use))
    return peak, in_use


def live_arrays_bytes() -> int:
    """Sum of nbytes over all live jax.Arrays — the CPU-backend stand-in
    for bytes_in_use (no allocator watermark exists there, so
    HbmWatermark tracks the running peak across samples instead)."""
    try:
        import jax
        return int(sum(getattr(a, "nbytes", 0) for a in jax.live_arrays()))
    except Exception:
        return 0


class HbmWatermark:
    """Peak / in-use device-memory sampler.

    `sample()` is called at natural sync points (epoch end, bench arm
    end); on real accelerators it reads the allocator's own watermark,
    on CPU it approximates with live-array bytes and keeps the max seen
    across samples as the peak. Either way the result feeds
    `kubeml_device_hbm_bytes{kind=peak|in_use}`.
    """

    def __init__(self, device=None):
        self.device = device
        self.peak_bytes = 0
        self.in_use_bytes = 0
        self.samples = 0

    def sample(self) -> Tuple[int, int]:
        stats = device_memory_stats(self.device)
        if stats is not None:
            peak, in_use = stats
            self.peak_bytes = max(self.peak_bytes, peak)
        else:
            in_use = live_arrays_bytes()
            self.peak_bytes = max(self.peak_bytes, in_use)
        self.in_use_bytes = in_use
        self.samples += 1
        return self.peak_bytes, self.in_use_bytes

    def snapshot(self) -> Dict[str, int]:
        return {
            "hbm_peak_bytes": self.peak_bytes,
            "hbm_in_use_bytes": self.in_use_bytes,
        }
