"""Installed-JAX compatibility shim.

The codebase is written against the modern shard_map surface —
``jax.shard_map`` with ``check_vma``/``axis_names`` kwargs,
``lax.pcast``, ``jax.typeof(x).vma``, ``jax.sharding.AxisType`` — while
the container may ship an older JAX (0.4.x) where shard_map lives in
``jax.experimental.shard_map`` with ``(check_rep, auto)`` kwargs and
varying-manual-axes (vma) tracking does not exist at all. This module is
the ONE translation layer: every call site imports from here and stays
written against the modern API, and the mapping to the legacy surface
lives in exactly one place.

Legacy mapping:

- ``check_vma`` has no legacy equivalent (vma tracking doesn't exist);
  it is dropped, and ``check_rep`` is forced False — the legacy
  replication check predates the masked-psum merge/pallas idioms used
  here and rejects valid programs.
- ``axis_names={...}`` (manual axes) becomes the complement:
  ``auto = frozenset(mesh.axis_names) - axis_names``.
- ``lax.pcast(x, axis, to='varying')`` is an identity on legacy JAX:
  without vma tracking there is no invariant/varying type distinction
  for the cast to mediate, so the scan-carry types it fixes up already
  match.
"""

from __future__ import annotations

from typing import Any, Optional, Set

import jax

HAS_NATIVE_SHARD_MAP = hasattr(jax, "shard_map")
HAS_PCAST = hasattr(jax.lax, "pcast")
HAS_VMA = hasattr(jax, "typeof")


def shard_map(f, *, mesh, in_specs, out_specs,
              check_vma: Optional[bool] = None,
              axis_names: Optional[Set[Any]] = None):
    """``jax.shard_map`` with modern kwargs on any installed JAX.

    ``axis_names`` is the MANUAL axis set (modern convention); omitted
    means all mesh axes are manual.
    """
    if HAS_NATIVE_SHARD_MAP:
        kw = {}
        if check_vma is not None:
            kw["check_vma"] = check_vma
        if axis_names is not None:
            kw["axis_names"] = set(axis_names)
        return jax.shard_map(f, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, **kw)
    from jax.experimental.shard_map import shard_map as _legacy
    kw = {"check_rep": False}
    if axis_names is not None:
        kw["auto"] = frozenset(set(mesh.axis_names) - set(axis_names))
    return _legacy(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                   **kw)


def axis_size(axis_name) -> int:
    """``lax.axis_size`` on modern JAX; on legacy JAX ``psum(1, axis)``,
    which constant-folds to the same static int inside any manual-axis
    body (the only place either spelling is legal)."""
    if hasattr(jax.lax, "axis_size"):
        return jax.lax.axis_size(axis_name)
    return jax.lax.psum(1, axis_name)


def pcast(x, axis_name, *, to: str):
    """``lax.pcast`` when the installed JAX tracks vma; identity when it
    does not (there is no type distinction to cast between)."""
    if HAS_PCAST:
        return jax.lax.pcast(x, axis_name, to=to)
    return x


def typeof_vma(x) -> frozenset:
    """``jax.typeof(x).vma`` — the varying-manual-axes of a traced value
    — or ``frozenset()`` on vma-less JAX (equivalent to 'not varying',
    which matches the legacy semantics where everything is untyped)."""
    if HAS_VMA:
        return jax.typeof(x).vma
    return frozenset()


def shape_dtype_struct(shape, dtype, *, vma=frozenset()):
    """``jax.ShapeDtypeStruct`` with a ``vma`` annotation where the
    installed JAX supports one (pallas ``out_shape`` under a
    check_vma=True shard_map requires it); dropped on vma-less JAX."""
    if HAS_VMA:
        return jax.ShapeDtypeStruct(shape, dtype, vma=vma)
    return jax.ShapeDtypeStruct(shape, dtype)


def flash_safe_context() -> bool:
    """Whether a pallas (Mosaic) kernel may be emitted here: fully-manual
    shard_map bodies and plain jit with no surrounding mesh are safe;
    any Auto (GSPMD-managed) axis in scope is not ("Mosaic kernels
    cannot be automatically partitioned").

    Modern JAX exposes the abstract mesh's per-axis types directly. On
    legacy JAX there is no equivalent introspection, so fall back to the
    physical mesh context: no surrounding `with mesh:` context means
    plain jit (safe); under a mesh context, require every mesh axis to
    be bound as a manual axis frame (fully-manual shard_map body).
    Anything unintrospectable answers False — the cost is a reference-
    path fallback, never a miscompile.
    """
    try:
        from jax.sharding import AxisType, get_abstract_mesh
        am = get_abstract_mesh()
        return am.empty or all(t == AxisType.Manual for t in am.axis_types)
    except ImportError:
        pass
    try:
        from jax._src.mesh import thread_resources
        phys = thread_resources.env.physical_mesh
        if phys.empty:
            return True
        from jax._src import core as _core
        frames = _core.thread_local_state.trace_state.axis_env
        manual = {getattr(fr, "name", None) for fr in frames}
        return all(a in manual for a in phys.axis_names)
    except Exception:
        return False
