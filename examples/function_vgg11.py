"""A KubeML function to train VGG-11 on CIFAR-100.

Equivalent of the reference example ml/experiments/kubeml/
function_vgg11.py (used in its max-accuracy/TTA app experiments).

    kubeml fn create -n vgg11-example --code examples/function_vgg11.py
    kubeml train -f vgg11-example -d cifar100 -e 30 -b 128 --lr 0.05 -p 8
"""

import numpy as np
import optax

from kubeml_tpu import ClassifierModel, KubeDataset
from kubeml_tpu.models.vgg import VGGModule

CIFAR_MEAN = np.array([0.5071, 0.4866, 0.4409], np.float32)
CIFAR_STD = np.array([0.2673, 0.2564, 0.2762], np.float32)


class KubeVGG11(ClassifierModel):
    name = "vgg11-example"
    num_classes = 100

    def build(self):
        return VGGModule(num_classes=self.num_classes)

    def configure_optimizers(self, lr, epoch):
        return optax.chain(optax.add_decayed_weights(5e-4),
                           optax.sgd(lr, momentum=0.9))


class Cifar100Dataset(KubeDataset):
    dataset = "cifar100"

    def _normalize(self, data):
        x = data.astype(np.float32)
        if x.max() > 1.5:
            x = x / 255.0
        return (x - CIFAR_MEAN) / CIFAR_STD

    def transform_train(self, data, labels):
        return {"x": self._normalize(data), "y": labels.astype(np.int32)}

    def transform_test(self, data, labels):
        return {"x": self._normalize(data), "y": labels.astype(np.int32)}
