"""A KubeML function to train LeNet-5 on MNIST.

The kubeml_tpu equivalent of the reference example
(ml/experiments/kubeml/function_lenet.py): one self-contained file with a
KubeModel subclass + a KubeDataset subclass, deployed with

    kubeml fn create -n lenet-example --code examples/function_lenet.py
    kubeml train -f lenet-example -d mnist -e 10 -b 64 --lr 0.01 -p 4 -K 16

Where the reference file hand-writes the torch train loop, optimizer
stepping, and weight save/load, here the user supplies only pure pieces:
a flax module, an optax factory, and numpy transforms — the engine
differentiates, steps, and merges.
"""

import flax.linen as nn
import jax.numpy as jnp
import numpy as np
import optax

from kubeml_tpu import ClassifierModel, KubeDataset

# MNIST channel statistics (the reference normalizes identically through
# torchvision.transforms.Normalize)
MNIST_MEAN, MNIST_STD = 0.1307, 0.3081


class LeNetModule(nn.Module):
    """LeNet-5 geometry (1998 paper), NHWC, bf16 compute."""

    num_classes: int = 10
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = x.astype(self.dtype)
        x = nn.Conv(6, (5, 5), padding="SAME", dtype=self.dtype)(x)
        x = nn.relu(x)
        x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = nn.Conv(16, (5, 5), padding="VALID", dtype=self.dtype)(x)
        x = nn.relu(x)
        x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = x.reshape((x.shape[0], -1))
        x = nn.relu(nn.Dense(120, dtype=self.dtype)(x))
        x = nn.relu(nn.Dense(84, dtype=self.dtype)(x))
        x = nn.Dense(self.num_classes, dtype=self.dtype)(x)
        return x.astype(jnp.float32)


class KubeLeNet(ClassifierModel):
    name = "lenet-example"
    num_classes = 10

    def build(self):
        return LeNetModule(num_classes=self.num_classes)

    def configure_optimizers(self, lr, epoch):
        # the reference example uses SGD momentum 0.9 on every function
        return optax.sgd(lr, momentum=0.9)


class MnistDataset(KubeDataset):
    dataset = "mnist"

    def _normalize(self, data):
        x = data.astype(np.float32)
        if x.ndim == 3:  # [N, 28, 28] -> NHWC
            x = x[..., None]
        if x.max() > 1.5:  # raw 0..255 uploads
            x = x / 255.0
        return (x - MNIST_MEAN) / MNIST_STD

    def transform_train(self, data, labels):
        return {"x": self._normalize(data), "y": labels.astype(np.int32)}

    def transform_test(self, data, labels):
        return {"x": self._normalize(data), "y": labels.astype(np.int32)}
