"""A KubeML function to train a byte-level GPT language model.

Net-new relative to the reference's example functions (all CNN
classifiers, ml/experiments/kubeml/*.py): the same single-file deploy
shape, but the task is causal language modeling on token windows.

    kubeml fn create -n gpt-example --code examples/function_gpt.py
    kubeml train -f gpt-example -d mytext -e 5 -b 32 --lr 0.003 -p 4 -K 8

Dataset format: x-train / x-test are int arrays [N, T] of token-id
windows (0 is reserved for padding; byte-level corpora should store
byte+1). The label files are required by the ingest API's 4-file
multipart shape (python/storage/api.py:58-142) but a causal LM derives
its targets by shifting the inputs, so upload zeros and they are
dropped here.
"""

import numpy as np

from kubeml_tpu import KubeDataset
from kubeml_tpu.models.gpt import GPTMini, GPTModule


class KubeGPT(GPTMini):
    name = "gpt-example"

    def build(self):
        # byte-level vocab: 256 byte values shifted by +1 for the pad id
        return GPTModule(vocab_size=257, max_len=128, hidden=128, layers=4,
                         heads=4, ffn=512)


class TextWindows(KubeDataset):
    dataset = "text"

    def _windows(self, data):
        x = np.asarray(data)
        if x.ndim != 2:
            raise ValueError(f"expected [N, T] token windows, got {x.shape}")
        return x.astype(np.int32)

    def transform_train(self, data, labels):
        # labels are a placeholder (see module docstring): targets are the
        # inputs shifted by one position, computed inside the model's loss
        return {"x": self._windows(data)}

    def transform_test(self, data, labels):
        return {"x": self._windows(data)}
