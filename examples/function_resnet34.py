"""A KubeML function to train ResNet-34 on CIFAR-10.

Equivalent of the reference example ml/experiments/kubeml/
function_resnet34.py: torchvision ResNet-34 with an LR stepped off
`self.epoch` (its lines 51-60) and CIFAR-10 normalization. Here the
epoch-stepped schedule is expressed inside `configure_optimizers(lr,
epoch)` — epoch arrives traced, so the steps are `jnp.where` boundaries
and the whole schedule compiles into the sync round.

    kubeml fn create -n resnet34-example --code examples/function_resnet34.py
    kubeml train -f resnet34-example -d cifar10 -e 30 -b 128 --lr 0.1 -p 8 --sparse-avg
"""

import jax.numpy as jnp
import numpy as np
import optax

from kubeml_tpu import ClassifierModel, KubeDataset
from kubeml_tpu.models.resnet import BasicBlock, ResNetModule

CIFAR_MEAN = np.array([0.4914, 0.4822, 0.4465], np.float32)
CIFAR_STD = np.array([0.2470, 0.2435, 0.2616], np.float32)


class KubeResNet34(ClassifierModel):
    name = "resnet34-example"
    num_classes = 10

    def build(self):
        return ResNetModule(stage_sizes=(3, 4, 6, 3), block=BasicBlock,
                            num_classes=self.num_classes)

    def configure_optimizers(self, lr, epoch):
        # step schedule off the epoch, like the reference's
        # lr * 0.1 at epochs 15 and 25 (function_resnet34.py:51-60)
        factor = jnp.float32(1.0)
        for boundary in (15, 25):
            factor = factor * jnp.where(epoch >= boundary, 0.1, 1.0)
        return optax.chain(optax.add_decayed_weights(5e-4),
                           optax.sgd(lr * factor, momentum=0.9))


class Cifar10Dataset(KubeDataset):
    dataset = "cifar10"

    def __init__(self, dataset_name=None, seed: int = 0):
        super().__init__(dataset_name)
        # own seeded generator: transforms run in the loader's prefetch
        # thread, so the global np.random would race across concurrent
        # jobs and break seed-reproducibility
        self._rng = np.random.default_rng(seed)

    def _normalize(self, data):
        x = data.astype(np.float32)
        if x.max() > 1.5:
            x = x / 255.0
        return (x - CIFAR_MEAN) / CIFAR_STD

    def transform_train(self, data, labels):
        x = self._normalize(data)
        # reference augmentation: random horizontal flip
        flip = self._rng.random(len(x)) < 0.5
        x[flip] = x[flip, :, ::-1]
        return {"x": x, "y": labels.astype(np.int32)}

    def transform_test(self, data, labels):
        return {"x": self._normalize(data), "y": labels.astype(np.int32)}
