"""Durable control plane: decision journaling, crash recovery at every
decision index, torn-write repair, grant fencing, and the scheduler/PS
re-adoption sweeps (kubeml_tpu/control/journal.py, cluster.py,
scheduler.py, ps.py — docs/architecture.md "Control-plane durability").

The load-bearing test is the crash-at-every-index sweep: after EVERY
journaled allocator decision, a twin recovered from snapshot+journal
must reproduce `snapshot()` exactly. Torn tails and fencing rejections
each get a dedicated test, the ControlFaultPlan kinds are asserted by
quoted name (tools/check_fault_tests.py lints that), and the bench's
self-asserting control_chaos arm is pinned here too.

Everything is fake-clock / coordinate-driven — no wall-clock sleeps,
no unseeded randomness, no TPU.
"""

from __future__ import annotations

import json
import os
import random
import struct
import types
import zlib

import pytest

from kubeml_tpu.api.errors import KubeMLException, StaleGrantError
from kubeml_tpu.api.types import TrainOptions, TrainRequest, TrainTask
from kubeml_tpu.control.cluster import (CLUSTER_JOB_ID, ClusterAllocator,
                                        verify_journal_roundtrip)
from kubeml_tpu.control.httpd import JsonService, Request
from kubeml_tpu.control.journal import (DecisionJournal,
                                        JournalCorruptError,
                                        atomic_write_json, read_json)
from kubeml_tpu.control.scheduler import Scheduler
from kubeml_tpu.faults import CONTROL_KINDS, ControlCrash, ControlFaultPlan

pytestmark = pytest.mark.chaos

_HEADER = struct.Struct("<II")


class FakeClock:
    def __init__(self, t: float = 0.0):
        self.t = t

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> None:
        self.t += dt


def _task(job_id: str, restarts: int = 0) -> TrainTask:
    return TrainTask(
        job_id=job_id,
        parameters=TrainRequest(model_type="mlp", batch_size=16, epochs=1,
                                dataset="blobs", lr=0.1,
                                options=TrainOptions(default_parallelism=2)),
        restarts=restarts)


def _req(body: dict) -> Request:
    return Request(path="/job", params={}, query={}, body=body, raw=b"")


def _snap_no_torn(alloc: ClusterAllocator, now: float) -> dict:
    """snapshot() minus the torn-drop counter, which is a per-process
    journal-handle stat rather than journaled history (the twin reads
    an already-repaired file and legitimately reports zero)."""
    s = alloc.snapshot(now=now)
    s.pop("cluster_journal_torn_drops_total", None)
    return s


def _journaled(tmp_path, clock, compact_every=0, fault_plan=None,
               pool=8):
    journal = DecisionJournal(str(tmp_path), fault_plan=fault_plan)
    alloc = ClusterAllocator(pool, tenant_weights={"a": 1.0, "b": 3.0},
                             tenant_quotas={"a": 6}, clock=clock,
                             journal=journal, compact_every=compact_every)
    return alloc, journal


def _twin(tmp_path, clock, pool=8) -> ClusterAllocator:
    return ClusterAllocator.recover(
        DecisionJournal(str(tmp_path)), pool,
        tenant_weights={"a": 1.0, "b": 3.0}, tenant_quotas={"a": 6},
        clock=clock)


# -------------------------------------------------- journal primitives


def test_journal_append_replay_roundtrip(tmp_path):
    """Frames come back in order with their monotone indices, and a
    fresh handle picks up next_index from disk."""
    j = DecisionJournal(str(tmp_path))
    assert [j.append({"op": n}) for n in ("a", "b", "c")] == [0, 1, 2]
    j.close()

    j2 = DecisionJournal(str(tmp_path))
    state, tail = j2.replay()
    assert state is None
    assert [r["op"] for r in tail] == ["a", "b", "c"]
    assert [r["i"] for r in tail] == [0, 1, 2]
    assert j2.next_index == 3
    assert j2.append({"op": "d"}) == 3


def test_torn_tail_is_dropped_and_repaired(tmp_path):
    """A truncated final frame (crash mid-append) is dropped, counted,
    and physically truncated so the next append extends a clean file —
    never mis-replayed as a record."""
    j = DecisionJournal(str(tmp_path))
    j.append({"op": "a"})
    j.append({"op": "b"})
    j.close()
    size = os.path.getsize(j.journal_path)
    with open(j.journal_path, "r+b") as f:
        f.truncate(size - 5)          # tear the tail of frame "b"

    j2 = DecisionJournal(str(tmp_path))
    state, tail = j2.replay()
    assert [r["op"] for r in tail] == ["a"]
    assert j2.torn_drops == 1
    assert j2.next_index == 1
    # the file was repaired: a third handle sees a clean journal
    j3 = DecisionJournal(str(tmp_path))
    _, tail3 = j3.replay()
    assert [r["op"] for r in tail3] == ["a"] and j3.torn_drops == 0


def test_midfile_corruption_fails_loudly(tmp_path):
    """A bad CRC (or CRC-valid garbage) with valid frames AFTER it is
    damage, not a torn tail: replay must raise, not skip the hole."""
    j = DecisionJournal(str(tmp_path))
    for n in ("a", "b", "c"):
        j.append({"op": n})
    j.close()
    with open(j.journal_path, "r+b") as f:
        f.seek(_HEADER.size + 2)      # inside frame 0's payload
        f.write(b"\xff")
    with pytest.raises(JournalCorruptError):
        DecisionJournal(str(tmp_path)).replay()

    # CRC-valid but non-JSON payload mid-file fails loudly too
    garbage = b"not json"
    good = json.dumps({"op": "z", "i": 0}, sort_keys=True).encode()
    with open(j.journal_path, "wb") as f:
        f.write(_HEADER.pack(len(garbage), zlib.crc32(garbage)) + garbage)
        f.write(_HEADER.pack(len(good), zlib.crc32(good)) + good)
    with pytest.raises(JournalCorruptError):
        DecisionJournal(str(tmp_path)).replay()


def test_compaction_snapshot_plus_tail(tmp_path):
    """compact() folds history into the snapshot; replay returns the
    snapshot state plus only the records after it."""
    j = DecisionJournal(str(tmp_path))
    j.append({"op": "a"})
    j.append({"op": "b"})
    j.compact({"folded": 2})
    j.append({"op": "c"})
    j.close()

    state, tail = DecisionJournal(str(tmp_path)).replay()
    assert state == {"folded": 2}
    assert [r["op"] for r in tail] == ["c"] and tail[0]["i"] == 2


def test_compaction_racing_append_skips_stale_records(tmp_path):
    """A crash BETWEEN snapshot write and journal truncate leaves stale
    pre-compaction records in the journal; replay must skip every
    record with i <= snapshot.index instead of double-applying it."""
    j = DecisionJournal(str(tmp_path))
    j.append({"op": "a"})
    j.append({"op": "b"})
    j.close()
    with open(j.journal_path, "rb") as f:
        stale = f.read()
    j.compact({"folded": 2})
    # simulate the truncate never reaching disk
    with open(j.journal_path, "wb") as f:
        f.write(stale)
    j.append({"op": "c"})
    j.close()

    state, tail = DecisionJournal(str(tmp_path)).replay()
    assert state == {"folded": 2}
    assert [r["op"] for r in tail] == ["c"]


def test_atomic_write_json_roundtrip(tmp_path):
    path = str(tmp_path / "doc.json")
    atomic_write_json(path, {"k": [1, 2]})
    assert read_json(path) == {"k": [1, 2]}
    assert read_json(str(tmp_path / "missing.json")) is None
    assert not os.path.exists(path + ".tmp")


# ------------------------------------------- crash-at-every-index sweep


def test_crash_recovery_at_every_decision_index(tmp_path):
    """THE durability contract: after EVERY journaled decision —
    placements, queues, preemptions, resizes, releases, a recovery
    bump, a fencing rejection, and across a compaction boundary — an
    allocator recovered from snapshot+journal reproduces `snapshot()`
    exactly."""
    clock = FakeClock(100.0)
    alloc, _ = _journaled(tmp_path, clock, compact_every=4)

    def stale_probe():
        with pytest.raises(StaleGrantError):
            alloc.fence_check("j1", 999)

    ops = [
        lambda: alloc.submit("j1", tenant="a", lanes=3),
        lambda: alloc.submit("j2", tenant="b", lanes=4),
        lambda: alloc.submit("j3", tenant="a", lanes=3),           # parks
        lambda: alloc.submit("hi", tenant="b", priority=5, lanes=4),
        lambda: alloc.resize("j1", 1),
        lambda: alloc.release("j2"),
        lambda: alloc.submit("sv", tenant="b", lanes=2, kind="serving"),
        lambda: alloc.mark_recovered(),
        stale_probe,
        lambda: alloc.release("j1"),
        lambda: alloc.resize("sv", 3),
        lambda: alloc.release("hi"),
    ]
    checked = 0
    for op in ops:
        clock.advance(1.0)
        op()
        twin = _twin(tmp_path, clock)
        assert _snap_no_torn(twin, clock.t) == \
            _snap_no_torn(alloc, clock.t)
        # and the library's own round-trip helper agrees
        verify_journal_roundtrip(alloc)
        checked += 1
    assert checked == len(ops)
    snap = alloc.snapshot(now=clock.t)
    assert snap["cluster_journal_records_total"] >= len(ops)
    assert snap["cluster_journal_compactions_total"] >= 2
    assert snap["cluster_recoveries_total"] == 1
    assert snap["cluster_fencing_rejections_total"] == 1
    assert snap["cluster_fencing_epoch"] == 2


def test_snapshot_only_recovery(tmp_path):
    """Recovery from a compaction snapshot with an EMPTY journal tail
    (compaction ran, then a clean crash) reconstructs exactly."""
    clock = FakeClock(5.0)
    alloc, journal = _journaled(tmp_path, clock)
    alloc.submit("j1", tenant="a", lanes=3)
    clock.advance(1.0)
    alloc.submit("j2", tenant="b", lanes=4)
    journal.compact(alloc._state_dict())
    assert os.path.getsize(journal.journal_path) == 0

    twin = _twin(tmp_path, clock)
    assert _snap_no_torn(twin, clock.t) == _snap_no_torn(alloc, clock.t)
    assert twin.running_jobs() == {"j1": 3, "j2": 4}


# ---------------------------------------------- injected control faults


def test_torn_write_fault_loses_op_but_never_misreplays(tmp_path):
    """control_torn_write kills the allocator MID-append: a partial
    frame reaches disk and the op is LOST. Recovery drops the torn
    tail (counted once) and reconstructs the pre-op state exactly —
    then appends extend the repaired file cleanly."""
    clock = FakeClock(0.0)
    plan = ControlFaultPlan.parse(
        [{"kind": "control_torn_write", "index": 2}])
    alloc, _ = _journaled(tmp_path, clock, fault_plan=plan)
    alloc.submit("j1", tenant="a", lanes=3)
    clock.advance(1.0)
    alloc.submit("j2", tenant="b", lanes=4)
    pre = _snap_no_torn(alloc, clock.t)
    with pytest.raises(ControlCrash):
        alloc.submit("j3", tenant="a", lanes=1)
    assert plan.injected["control_torn_write"] == 1

    twin = _twin(tmp_path, clock)
    assert twin._journal.torn_drops == 1
    assert _snap_no_torn(twin, clock.t) == pre
    assert "j3" not in twin.running_jobs()
    assert "j3" not in twin.pending_jobs()
    # the repaired file keeps working: resubmit lands at a fresh index
    twin.submit("j3", tenant="a", lanes=1)
    verify_journal_roundtrip(twin)


def test_crash_after_durable_append_keeps_the_op(tmp_path):
    """control_crash kills the allocator AFTER the frame is flushed:
    the op is durable and MUST survive into the recovered state (the
    landed/lost distinction the bench arm's retry logic rests on)."""
    clock = FakeClock(0.0)
    plan = ControlFaultPlan.parse([{"kind": "control_crash", "index": 1}])
    alloc, _ = _journaled(tmp_path, clock, fault_plan=plan)
    alloc.submit("j1", tenant="a", lanes=3)
    clock.advance(1.0)
    with pytest.raises(ControlCrash):
        alloc.submit("j2", tenant="b", lanes=4)
    assert plan.injected["control_crash"] == 1

    twin = _twin(tmp_path, clock)
    assert twin.running_jobs() == {"j1": 3, "j2": 4}
    assert twin._journal.torn_drops == 0


def test_slow_recover_dilates_replay_once(tmp_path):
    """control_slow_recover fires at the top of replay(), exactly once
    per event — a second replay does not re-fire it."""
    j = DecisionJournal(str(tmp_path))
    j.append({"op": "a"})
    j.close()
    plan = ControlFaultPlan.parse(
        [{"kind": "control_slow_recover", "duration_s": 0.0}])
    j2 = DecisionJournal(str(tmp_path), fault_plan=plan)
    j2.replay()
    assert plan.injected["control_slow_recover"] == 1
    j2.replay()
    assert plan.injected["control_slow_recover"] == 1

    with pytest.raises(ValueError):
        ControlFaultPlan.parse([{"kind": "bogus_kind"}])


# --------------------------------------------------------- grant fencing


def test_fencing_rejects_stale_epoch_with_409(tmp_path):
    """Split-brain: a pre-crash worker presenting its old fencing epoch
    after a recovery+regrant is rejected 409, the rejection is
    journaled (the counter survives ANOTHER restart), and the current
    epoch keeps working."""
    clock = FakeClock(0.0)
    alloc, _ = _journaled(tmp_path, clock)
    alloc.submit("j1", tenant="a", lanes=2)
    assert alloc.grant_epoch("j1") == 1
    alloc.fence_check("j1", 1)                 # current epoch: fine

    recovered = _twin(tmp_path, clock)
    assert recovered.mark_recovered() == 2
    assert recovered.regrant("j1") == (2, 2)
    with pytest.raises(StaleGrantError) as exc:
        recovered.fence_check("j1", 1)         # pre-crash epoch: stale
    assert exc.value.status_code == 409
    assert (exc.value.presented, exc.value.current) == (1, 2)
    recovered.fence_check("j1", 2)             # re-granted epoch: fine
    with pytest.raises(StaleGrantError):
        recovered.fence_check("ghost", 1)      # no grant at all

    snap = recovered.snapshot(now=clock.t)
    assert snap["cluster_fencing_rejections_total"] == 2
    # the rejection history itself is durable
    twin = _twin(tmp_path, clock)
    assert twin.snapshot(now=clock.t)[
        "cluster_fencing_rejections_total"] == 2
    assert recovered.regrant("ghost") is None


# --------------------------------------------------- scheduler recovery


def test_scheduler_recovery_adopts_requeues_and_reparks(tmp_path):
    """A restarted scheduler rebuilt from its state file + the replayed
    journal: a granted job whose child survived is RE-ADOPTED at its
    journaled width under the new epoch (no double /start); a granted
    job whose child died is released and requeued WITHOUT consuming its
    restart budget; parked and queued tasks resume their phases; and a
    stale pre-crash epoch on /job is fenced 409."""
    clock = FakeClock(50.0)
    jdir, sdir = str(tmp_path / "control"), str(tmp_path / "sched")
    journal = DecisionJournal(jdir)
    alloc1 = ClusterAllocator(4, clock=clock, journal=journal)
    alloc1.submit("aaaa0001", lanes=2)
    alloc1.submit("bbbb0002", lanes=2)
    alloc1.submit("cccc0003", lanes=4)         # parks: pool full
    sched1 = Scheduler(ps_url=None, allocator=alloc1, state_dir=sdir,
                       rng=random.Random(7))
    task_a, task_b = _task("aaaa0001"), _task("bbbb0002", restarts=1)
    task_c, task_d = _task("cccc0003"), _task("dddd0004")
    sched1._track(task_a, "granted", 2, 1)
    sched1._track(task_b, "granted", 2, 1)
    sched1._track(task_c, "parked")
    sched1._track(task_d, "queued")
    journal.close()

    # ---- crash: a new incarnation replays the journal + state file
    alloc2 = ClusterAllocator.recover(DecisionJournal(jdir), 4,
                                      clock=clock)
    assert alloc2.running_jobs() == {"aaaa0001": 2, "bbbb0002": 2}
    sched2 = Scheduler(ps_url=None, allocator=alloc2, state_dir=sdir,
                       rng=random.Random(7))
    summary = sched2.recover(ps_tasks=[{"job_id": "aaaa0001"}])

    assert summary["adopted"] == ["aaaa0001"]
    assert summary["requeued"] == ["bbbb0002"]
    assert summary["parked"] == ["cccc0003"]
    assert summary["queued"] == ["dddd0004"]
    assert summary["fencing_epoch"] == 2
    assert summary["recovery_s"] >= 0.0
    # the survivor holds its journaled width under the NEW epoch; the
    # dead job's lanes are free (2 lanes — not enough for parked C)
    assert alloc2.running_jobs() == {"aaaa0001": 2}
    assert alloc2.grant_epoch("aaaa0001") == 2
    assert alloc2.pending_jobs() == ["cccc0003"]
    assert "cccc0003" in sched2._parked
    # the requeue is budget-free: restart count untouched, epoch reset
    queued = {}
    while len(sched2.queue):
        t = sched2.queue.pop(timeout=0)
        queued[t.job_id] = t
    assert sorted(queued) == ["bbbb0002", "dddd0004"]
    assert queued["bbbb0002"].restarts == 1
    assert queued["bbbb0002"].grant_epoch == 0
    assert queued["bbbb0002"].elapsed_time_s == -1.0

    # fencing through the real handler: the pre-crash child's /job ask
    # with epoch 1 is rejected 409; the relayed epoch 2 passes
    task_a.grant_epoch = 1
    with pytest.raises(StaleGrantError) as exc:
        sched2._h_job(_req(task_a.to_dict()))
    assert exc.value.status_code == 409
    task_a.grant_epoch = 2
    assert sched2._h_job(_req(task_a.to_dict())) == {"ok": True}

    # the state file reflects the adopted grant's new epoch
    doc = read_json(os.path.join(sdir, "scheduler.state.json"))
    assert doc["tasks"]["aaaa0001"]["phase"] == "granted"
    assert doc["tasks"]["aaaa0001"]["epoch"] == 2
    assert sched2.recoveries == 1


def test_deployment_build_allocator_replays_prior_journal(tmp_path):
    """build_allocator with a journal_dir: a fresh boot journals; a
    second boot over the same directory REPLAYS it instead of starting
    empty — the deployment-level wiring behind --control-durable."""
    from kubeml_tpu.control.deployment import build_allocator

    d = str(tmp_path / "control")
    a1 = build_allocator(4, journal_dir=d)
    a1.submit("j1", lanes=2)
    a1._journal.close()
    a2 = build_allocator(4, journal_dir=d)
    assert a2.running_jobs() == {"j1": 2}
    assert a2.grant_epoch("j1") == 1
    assert build_allocator(0, journal_dir=d) is None  # cluster mode off


# ---------------------------------------------------------- PS recovery


def test_ps_recovery_readopts_live_children_drops_dead(tmp_path):
    """A restarted PS rebuilt from its ps.jobs.json manifest: a child
    answering /health on its recorded URL is re-adopted (registry
    entry, adopted pid, never double-started); a dead child is dropped
    for the scheduler sweep to requeue; a zero-replica fleet entry is
    left for cold start."""
    from kubeml_tpu.control.ps import ParameterServer
    from tools.check_metrics import parse_exposition

    child = JsonService(port=0)                 # stands in for a live
    port = child.start()                        # jobserver child
    try:
        sdir = str(tmp_path / "ps")
        os.makedirs(sdir)
        atomic_write_json(os.path.join(sdir, "ps.jobs.json"), {"jobs": {
            "live0001": {"task": _task("live0001").to_dict(),
                         "url": f"http://127.0.0.1:{port}",
                         "pid": os.getpid(), "partition": None},
            "dead0002": {"task": _task("dead0002").to_dict(),
                         "url": "http://127.0.0.1:9",
                         "pid": 999999, "partition": None},
        }})
        atomic_write_json(os.path.join(sdir, "ps.fleets.json"),
                          {"fleets": {"gpt-nano": {"stamp": None,
                                                   "replicas": 0}}})
        ps = ParameterServer(port=0, standalone_jobs=True, state_dir=sdir)
        summary = ps.recover()
    finally:
        child.stop()

    assert summary["adopted"] == ["live0001"]
    assert summary["dropped"] == ["dead0002"]
    assert summary["fleets"] == {}              # zero replicas: cold start
    assert summary["recovery_s"] >= 0.0
    assert ps.recoveries == 1
    assert "live0001" in ps.jobs and "dead0002" not in ps.jobs
    assert ps.jobs["live0001"].adopted_pid == os.getpid()
    # the recovery landed in the control-plane metric families
    fams = parse_exposition(ps.metrics.exposition())
    samples = {(n, tuple(sorted(lab.items()))): v
               for f in fams.values() for n, lab, v in f["samples"]}
    assert samples[("kubeml_control_recoveries_total",
                    (("role", "ps"),))] == 1.0
    assert samples[("kubeml_control_recovery_seconds_count",
                    (("role", "ps"),))] == 1.0
    # the re-persisted manifest keeps only the adopted survivor
    doc = read_json(os.path.join(sdir, "ps.jobs.json"))
    assert sorted(doc["jobs"]) == ["live0001"]


# ------------------------------------------------- jobserver callbacks


def test_jobserver_retry_is_bounded_and_seeded(monkeypatch):
    """The jobserver's control-plane callbacks retry through a restart
    window with jittered exponential backoff from a job-id-seeded RNG:
    the schedule replays identically run to run, and after the bounded
    attempts the loss is surrendered to the control plane's backstops."""
    import kubeml_tpu.train.jobserver as jobserver_mod

    def run_once(fail_first: int, attempts: int = 5):
        js = jobserver_mod.JobServer("retry001")
        calls, delays = [], []

        def fake_post(method, url, body=None):
            calls.append(url)
            if len(calls) <= fail_first:
                raise KubeMLException("control plane mid-restart", 503)
            return {"ok": True}

        monkeypatch.setattr(jobserver_mod, "http_json", fake_post)
        monkeypatch.setattr(jobserver_mod.time, "sleep", delays.append)
        ok = js._post_with_retry("probe", "http://ps/preempted/retry001",
                                 {"epoch": 1}, attempts=attempts)
        return ok, calls, delays

    ok, calls, delays = run_once(fail_first=2)
    assert ok is True and len(calls) == 3 and len(delays) == 2
    # full jitter stays inside [delay/2, delay] of the doubling ladder
    for d, base in zip(delays, (0.05, 0.1)):
        assert base * 0.5 <= d <= base
    # seeded: an identical rerun replays the exact same schedule
    assert run_once(fail_first=2)[2] == delays

    ok, calls, delays = run_once(fail_first=99, attempts=4)
    assert ok is False and len(calls) == 4 and len(delays) == 3


def test_jobserver_update_adopts_regrant_epoch():
    """PS /update/{job} relaying a recovered scheduler's re-grant: the
    child adopts the new fencing epoch so its next /job ask is not
    fenced as a stale pre-crash grant."""
    import kubeml_tpu.train.jobserver as jobserver_mod

    js = jobserver_mod.JobServer("epoch001")
    task = _task("epoch001")
    task.grant_epoch = 1
    js._job = types.SimpleNamespace(task=task)
    assert js._h_update(_req({"parallelism": 3, "grant_epoch": 4})) \
        == {"ok": True}
    assert task.grant_epoch == 4
    assert js._next_parallelism == 3
    assert js._update_event.is_set()
    # no epoch in the body leaves the grant untouched
    js._h_update(_req({"parallelism": 2}))
    assert task.grant_epoch == 4


# ------------------------------------------------------- observability


def test_control_flapping_health_rule():
    """Repeated recoveries inside one sample window mean the control
    plane is crash-looping — the rule goes critical on the delta, not
    the lifetime total (one clean recovery never fires it)."""
    from kubeml_tpu.control.health import HealthEvaluator

    ev = HealthEvaluator(clock=FakeClock(0.0))
    base = {"job_id": CLUSTER_JOB_ID, "cluster_pool_lanes": 4,
            "cluster_lanes_in_use": 2, "cluster_queue_depth": 0,
            "cluster_oldest_wait_s": 0.0, "cluster_fencing_epoch": 2,
            "cluster_recoveries_total": 1}
    assert ev.observe(dict(base)) == []        # one recovery: healthy
    assert ev.verdict(CLUSTER_JOB_ID)["state"] == "healthy"
    fired = ev.observe(dict(base, cluster_recoveries_total=3,
                            cluster_fencing_epoch=4))
    assert [r["rule"] for r in fired] == ["control_flapping"]
    assert "flapping" in fired[0]["detail"]
    assert ev.verdict(CLUSTER_JOB_ID)["state"] == "critical"
    # a training sample carries no cluster fields and cannot fire it
    ev2 = HealthEvaluator(clock=FakeClock(0.0))
    ev2.observe({"job_id": "train1", "train_loss": 0.5})
    assert ev2.verdict("train1")["state"] == "healthy"


def test_control_metrics_families_and_exposition(tmp_path):
    """update_cluster mirrors the journaled lifetime counters into the
    kubeml_control_* families by delta (replays never double-count),
    sets the fencing-epoch gauge, and folds a pushed recovery duration
    into the per-role histogram; the result passes the lint."""
    from kubeml_tpu.metrics.prom import MetricsRegistry
    from tools.check_metrics import parse_exposition, validate_exposition

    clock = FakeClock(0.0)
    alloc, _ = _journaled(tmp_path, clock)
    alloc.submit("j1", tenant="a", lanes=2)
    recovered = _twin(tmp_path, clock)
    recovered.mark_recovered()
    recovered.regrant("j1")
    with pytest.raises(StaleGrantError):
        recovered.fence_check("j1", 1)

    reg = MetricsRegistry()
    reg.update_cluster(recovered.snapshot(now=clock.t))
    text = reg.exposition()
    assert validate_exposition(text) == []

    def flat(t):
        return {(n, tuple(sorted(lab.items()))): v
                for f in parse_exposition(t).values()
                for n, lab, v in f["samples"]}

    samples = flat(text)
    assert samples[("kubeml_control_recoveries_total",
                    (("role", "allocator"),))] == 1.0
    assert samples[("kubeml_control_fencing_rejections_total",
                    (("role", "allocator"),))] == 1.0
    assert samples[("kubeml_control_fencing_epoch",
                    (("pool", "shared"),))] == 2.0
    assert samples[("kubeml_control_journal_records_total",
                    (("role", "allocator"),))] >= 4.0
    # replaying the same snapshot advances nothing
    reg.update_cluster(recovered.snapshot(now=clock.t))
    assert flat(reg.exposition())[
        ("kubeml_control_recoveries_total",
         (("role", "allocator"),))] == 1.0
    # a scheduler push stamps its recovery duration onto the snapshot
    snap = recovered.snapshot(now=clock.t)
    snap["control_recovery_s"] = 0.25
    snap["control_role"] = "scheduler"
    reg.update_cluster(snap)
    samples = flat(reg.exposition())
    assert samples[("kubeml_control_recoveries_total",
                    (("role", "scheduler"),))] == 1.0
    assert samples[("kubeml_control_recovery_seconds_count",
                    (("role", "scheduler"),))] == 1.0


def test_top_renders_control_line():
    """`kubeml top` shows the control-plane line when the durability
    layer is active, and keeps the pane quiet when it is off."""
    from kubeml_tpu.cli.main import _render_top

    latest = {"cluster_pool_lanes": 8, "cluster_lanes_in_use": 6,
              "cluster_running_jobs": 2, "cluster_queue_depth": 0,
              "cluster_oldest_wait_s": 0.0,
              "cluster_fencing_epoch": 3, "cluster_recoveries_total": 2,
              "cluster_journal_records_total": 20,
              "cluster_journal_compactions_total": 3,
              "cluster_journal_torn_drops_total": 1,
              "cluster_fencing_rejections_total": 2}
    out = _render_top({"id": "cluster", "state": "healthy",
                       "reasons": [], "latest": latest})
    assert "control: epoch 3" in out
    assert "recoveries 2" in out
    assert "journal 20 rec/3 compactions" in out
    assert "torn 1" in out and "fence rejects 2" in out
    # durability off: no journal records, no recoveries, no line
    quiet = _render_top({"id": "cluster", "state": "healthy",
                         "reasons": [],
                         "latest": {"cluster_pool_lanes": 8,
                                    "cluster_lanes_in_use": 6,
                                    "cluster_running_jobs": 2,
                                    "cluster_queue_depth": 0,
                                    "cluster_oldest_wait_s": 0.0}})
    assert "control:" not in quiet


# ------------------------------------------------------ lint self-test


def test_fault_lint_covers_control_kinds(tmp_path):
    """tools/check_fault_tests.py's fourth contract: every CONTROL_KINDS
    entry must be asserted by quoted name under tests/ — proven against
    a synthetic repo missing one, and green on the real repo (this very
    file carries the quoted assertions)."""
    from tools.check_fault_tests import (control_kinds, main,
                                         unasserted_control_kinds)

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    faults_py = os.path.join(repo, "kubeml_tpu", "faults.py")
    assert control_kinds(faults_py) == list(CONTROL_KINDS)
    assert unasserted_control_kinds(
        faults_py, os.path.join(repo, "tests")) == []
    assert main(["check_fault_tests.py"]) == 0

    fake = tmp_path / "repo"
    (fake / "tests").mkdir(parents=True)
    (fake / "kubeml_tpu").mkdir()
    (fake / "kubeml_tpu" / "faults.py").write_text(
        'SERVE_KINDS = ()\nFLEET_KINDS = ()\n'
        'CONTROL_KINDS = ("control_crash", "control_torn_write")\n')
    (fake / "tests" / "test_c.py").write_text(
        'def test_c():\n    assert "control_crash"\n')
    missing = unasserted_control_kinds(
        str(fake / "kubeml_tpu" / "faults.py"), str(fake / "tests"))
    assert missing == ["control_torn_write"]
    assert main(["x", str(fake / "tests")]) == 1
    (fake / "tests" / "test_t.py").write_text(
        'def test_t():\n    assert "control_torn_write"\n')
    assert main(["x", str(fake / "tests")]) == 0


# ----------------------------------------------------------- bench arm


def test_bench_control_chaos_arm_pins():
    """The self-asserting control_chaos arm: the crashed run converges
    to the uncrashed history exactly — zero lost jobs/streams, every
    injected fault fired once, and the folded weights bit-identical."""
    import bench

    arm = bench._measure_control_chaos_arm()
    assert arm["weights_bit_identical"] is True
    assert arm["lost_jobs"] == 0 and arm["lost_streams"] == 0
    assert arm["recoveries"] == 2
    assert arm["fencing_rejections"] == 2
    assert arm["torn_tail_drops"] == 1
    assert arm["fencing_epoch_final"] == 3
    assert arm["journal_records"] == 20
    assert arm["journal_compactions"] == 3
    assert arm["max_lanes_in_use"] <= arm["pool_lanes"]
    assert all(s >= 0.0 for s in arm["recovery_s"])
