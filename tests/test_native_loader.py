"""Native (C++) round assembler vs the pure-numpy reference path.

The native library is a fast path with identical outputs; every test here
asserts bit-equality against the Python assembly for ragged plans
(short final docs, partial batches, uneven worker shards).
"""

import numpy as np
import pytest

from kubeml_tpu import native
from kubeml_tpu.data.loader import RoundLoader, prefetch_rounds
from kubeml_tpu.data.registry import DatasetRegistry
from kubeml_tpu.models.base import KubeDataset

pytestmark = pytest.mark.skipif(not native.available(),
                                reason="native library unavailable")


class _PlainDataset(KubeDataset):
    dataset = "nat"


@pytest.fixture()
def handle(tmp_path):
    reg = DatasetRegistry(root=str(tmp_path / "ds"))
    rng = np.random.RandomState(0)
    # ragged on purpose: 330 train samples -> last doc is short (330 = 5*64+10)
    x = rng.rand(330, 6, 4).astype(np.float32)
    y = rng.randint(0, 5, 330).astype(np.int64)
    xt = rng.rand(90, 6, 4).astype(np.float32)
    yt = rng.randint(0, 5, 90).astype(np.int64)
    return reg.create("nat", x, y, xt, yt)


def _collect(loader, n_workers, k, batch, epoch=0):
    plan = loader.plan(n_workers, k, batch)
    return list(loader.epoch_rounds(plan, epoch))


@pytest.mark.parametrize("n_workers,k,batch", [
    (3, 2, 16), (5, -1, 32), (2, 4, 8), (1, 1, 64), (4, 3, 10)])
def test_native_rounds_match_python(handle, n_workers, k, batch):
    ds = _PlainDataset()
    nat = RoundLoader(handle, ds, n_lanes=2, seed=7, use_native=True)
    ref = RoundLoader(handle, ds, n_lanes=2, seed=7, use_native=False)
    assert nat._native_train, "native path not active"
    got = _collect(nat, n_workers, k, batch)
    want = _collect(ref, n_workers, k, batch)
    assert len(got) == len(want) and len(got) > 0
    for g, w in zip(got, want):
        np.testing.assert_array_equal(g.batch["x"], w.batch["x"])
        np.testing.assert_array_equal(g.batch["y"], w.batch["y"])
        np.testing.assert_array_equal(g.sample_mask, w.sample_mask)
        np.testing.assert_array_equal(g.step_mask, w.step_mask)
        np.testing.assert_array_equal(g.worker_mask, w.worker_mask)
        np.testing.assert_array_equal(g.rngs, w.rngs)
        assert g.round_index == w.round_index


def test_native_eval_matches_python(handle):
    ds = _PlainDataset()
    nat = RoundLoader(handle, ds, n_lanes=2, seed=1, use_native=True)
    ref = RoundLoader(handle, ds, n_lanes=2, seed=1, use_native=False)
    bg, mg = nat.eval_batches(3, 16)
    bw, mw = ref.eval_batches(3, 16)
    np.testing.assert_array_equal(bg["x"], bw["x"])
    np.testing.assert_array_equal(bg["y"], bw["y"])
    np.testing.assert_array_equal(mg, mw)


def test_custom_transform_falls_back(handle):
    class Scaled(_PlainDataset):
        def transform_train(self, data, labels):
            return {"x": data * 2.0, "y": labels}

    loader = RoundLoader(handle, Scaled(), n_lanes=2, use_native=True)
    assert not loader._native_train          # hook present -> numpy path
    assert loader._native_eval               # test hook untouched
    rb = next(iter(loader.epoch_rounds(loader.plan(2, 2, 16), 0)))
    raw, _ = handle.doc_range("train", 0, 1)
    np.testing.assert_allclose(rb.batch["x"][0, 0, 0], raw[0] * 2.0)


def test_prefetch_preserves_sequence(handle):
    ds = _PlainDataset()
    loader = RoundLoader(handle, ds, n_lanes=2, seed=3)
    plan = loader.plan(3, 2, 16)
    direct = list(loader.epoch_rounds(plan, 1))
    fetched = list(prefetch_rounds(loader.epoch_rounds(plan, 1), depth=2))
    assert len(direct) == len(fetched)
    for d, f in zip(direct, fetched):
        np.testing.assert_array_equal(d.batch["x"], f.batch["x"])
        np.testing.assert_array_equal(d.rngs, f.rngs)


def test_prefetch_propagates_errors():
    def gen():
        yield from ()
        raise RuntimeError("assembly failed")

    with pytest.raises(RuntimeError, match="assembly failed"):
        list(prefetch_rounds(gen()))


def test_assemble_round_cycle_pads():
    # 5 samples cycled into 2 steps x 4 slots: [0,1,2,3,4,0,1,2]
    x = np.arange(5, dtype=np.float32).reshape(5, 1)
    y = np.arange(5, dtype=np.int64)
    xo, yo, sm, stm, wm = native.assemble_round(
        x, y, np.array([0]), np.array([0]), np.array([5]), np.array([2]),
        W=2, S=2, B=4)
    np.testing.assert_array_equal(
        xo[0].ravel(), [0, 1, 2, 3, 4, 0, 1, 2])
    np.testing.assert_array_equal(
        yo[0].ravel(), [0, 1, 2, 3, 4, 0, 1, 2])
    np.testing.assert_array_equal(sm[0].ravel(), [1, 1, 1, 1, 1, 0, 0, 0])
    np.testing.assert_array_equal(stm, [[1, 1], [0, 0]])
    np.testing.assert_array_equal(wm, [1, 0])
    assert not xo[1].any() and not yo[1].any()
