"""GPT (decoder-only LM): shapes, causality, learning, SP parity, generation."""

import jax
import jax.numpy as jnp
import numpy as np

from kubeml_tpu.models import get_builtin
from kubeml_tpu.models.gpt import GPTMini, GPTModule, GPTMoEMini
from kubeml_tpu.parallel.kavg import KAvgEngine

VOCAB = 64
T = 16


class TinyGPT(GPTMini):
    """Test-sized geometry (the registered gpt-mini is ~6M params)."""

    def build(self):
        return GPTModule(vocab_size=VOCAB, max_len=32, hidden=32, layers=2,
                         heads=2, ffn=64, dropout=0.0)


def make_lm_task(rng, n):
    """Learnable LM data: ascending token runs, x[t+1] = x[t] + 1 with
    wraparound inside [1, VOCAB)."""
    start = rng.randint(1, VOCAB - 1, size=(n, 1))
    seq = (start + np.arange(T)[None, :] - 1) % (VOCAB - 1) + 1
    return seq.astype(np.int32)


def test_gpt_registered():
    assert get_builtin("gpt-mini") is GPTMini


def test_gpt_forward_shapes():
    model = TinyGPT()
    x = jnp.ones((2, T), jnp.int32)
    variables = model.init_variables(jax.random.PRNGKey(0), {"x": x})
    logits = model.module.apply(variables, x, train=False)
    assert logits.shape == (2, T, VOCAB)
    assert logits.dtype == jnp.float32


def test_gpt_causality():
    """Perturbing token t must leave logits at positions < t unchanged."""
    model = TinyGPT()
    rng = np.random.RandomState(0)
    x = rng.randint(1, VOCAB, size=(2, T)).astype(np.int32)
    variables = model.init_variables(jax.random.PRNGKey(0),
                                     {"x": jnp.asarray(x)})
    base = np.asarray(model.module.apply(variables, jnp.asarray(x),
                                         train=False))
    x2 = x.copy()
    x2[:, 10] = (x2[:, 10] % (VOCAB - 1)) + 1  # change token 10
    out = np.asarray(model.module.apply(variables, jnp.asarray(x2),
                                        train=False))
    np.testing.assert_allclose(out[:, :10], base[:, :10], rtol=1e-5,
                               atol=1e-5)
    assert np.abs(out[:, 10:] - base[:, 10:]).max() > 1e-4


def test_gpt_learns(mesh8):
    rng = np.random.RandomState(0)
    model = TinyGPT()
    W, S, B = 8, 2, 8
    x = make_lm_task(rng, W * S * B).reshape(W, S, B, T)
    variables = model.init_variables(jax.random.PRNGKey(0),
                                     {"x": jnp.asarray(x[0, 0])})
    engine = KAvgEngine(mesh8, model.loss, model.metrics,
                        model.configure_optimizers, donate=False)
    batch = {"x": jnp.asarray(x)}
    masks = dict(sample_mask=np.ones((W, S, B)), step_mask=np.ones((W, S)),
                 worker_mask=np.ones(W))
    first = last = None
    for _ in range(8):
        rngs = rng.randint(0, 2**31, size=(W, S, 2)).astype(np.uint32)
        variables, stats = engine.train_round(
            variables, batch, rngs=rngs, lr=3e-3, epoch=0, **masks)
        last = stats.loss_sum.sum() / stats.step_count.sum()
        if first is None:
            first = last
    assert last < first, (first, last)
    out = engine.eval_round(variables, batch, masks["sample_mask"])
    assert out["accuracy"] > 2.0 / VOCAB  # far above chance


def test_gpt_seq_parallel_ring_matches_dense():
    """Causal ring attention over the seq axis equals the dense forward,
    including ragged padding crossing shard boundaries."""
    from kubeml_tpu.parallel.mesh import make_mesh

    model = TinyGPT()
    rng = np.random.RandomState(0)
    B, Tsp = 2, 32  # 8 tokens per shard on a 4-way seq mesh
    x = rng.randint(1, VOCAB, size=(B, Tsp)).astype(np.int32)
    x[0, 20:] = 0
    variables = model.init_variables(jax.random.PRNGKey(0), {"x": x})

    dense = model.module.apply(variables, x, train=False)
    mesh = make_mesh(n_data=2, n_seq=4)
    sp = model.forward_seq_parallel(variables, x, mesh)
    assert sp.shape == (B, Tsp, VOCAB)
    # raw per-token logits over the vocab accumulate more bf16 noise
    # than BERT's pooled classifier outputs; diffs are structureless
    # (~0.05 uniformly, incl. pre-padding positions) = numeric, not
    # semantic
    np.testing.assert_allclose(np.asarray(sp), np.asarray(dense),
                               rtol=5e-2, atol=6e-2)


def test_gpt_seq_parallel_ulysses_matches_dense():
    from kubeml_tpu.parallel.mesh import make_mesh

    model = TinyGPT()
    rng = np.random.RandomState(1)
    B, Tsp = 2, 32
    x = rng.randint(1, VOCAB, size=(B, Tsp)).astype(np.int32)
    x[0, 20:] = 0
    x[1, 5:9] = 0  # interior pads
    variables = model.init_variables(jax.random.PRNGKey(0), {"x": x})

    dense = model.module.apply(variables, x, train=False)
    mesh = make_mesh(n_data=4, n_seq=2)  # 2 heads % 2 == 0
    sp = model.forward_seq_parallel(variables, x, mesh, impl="ulysses")
    assert sp.shape == (B, Tsp, VOCAB)
    # raw per-token logits over the vocab accumulate more bf16 noise
    # than BERT's pooled classifier outputs; diffs are structureless
    # (~0.05 uniformly, incl. pre-padding positions) = numeric, not
    # semantic
    np.testing.assert_allclose(np.asarray(sp), np.asarray(dense),
                               rtol=5e-2, atol=6e-2)


def test_gpt_generate():
    """Greedy generation: prompt preserved, window filled with real
    tokens, fixed shape, and repeated calls reuse one executable."""
    model = TinyGPT()
    rng = np.random.RandomState(0)
    prompts = rng.randint(1, VOCAB, size=(3, 6)).astype(np.int32)
    prompts[2, 4:] = 0  # ragged prompt: row 2 has 4 real tokens
    variables = model.init_variables(jax.random.PRNGKey(0),
                                     {"x": jnp.asarray(prompts)})
    out = model.infer(variables, prompts, max_new_tokens=8)
    assert out.shape == (3, 14)
    np.testing.assert_array_equal(out[:2, :6], prompts[:2])
    np.testing.assert_array_equal(out[2, :4], prompts[2, :4])
    assert (out[:2, 6:] != 0).all()      # generation never emits PAD_ID
    assert (out[2, 4:12] != 0).all()     # ragged row grew from its length


def test_gpt_generate_interior_and_all_pad():
    """Interior pads stay part of the prompt (nothing overwritten);
    an all-pad row generates unconditioned from position 0."""
    model = TinyGPT()
    prompts = np.array([[5, 0, 7, 0, 9],
                        [0, 0, 0, 0, 0]], np.int32)
    variables = model.init_variables(jax.random.PRNGKey(0),
                                     {"x": jnp.asarray(prompts)})
    out = model.infer(variables, prompts, max_new_tokens=4)
    assert out.shape == (2, 9)
    np.testing.assert_array_equal(out[0, :5], prompts[0])  # incl. token 9
    assert (out[0, 5:] != 0).all()
    assert (out[1, :4] != 0).all()  # all-pad row filled from position 0


def test_gpt_cached_generate_matches_infer():
    """The KV-cache scan decode must produce exactly infer()'s greedy
    continuation for full-length prompts (same conditioning, positions)."""
    model = TinyGPT()
    rng = np.random.RandomState(0)
    prompts = rng.randint(1, VOCAB, size=(3, 8)).astype(np.int32)
    variables = model.init_variables(jax.random.PRNGKey(0),
                                     {"x": jnp.asarray(prompts)})
    # compare against the re-forward path directly: infer() itself
    # delegates full-length prompts to generate(), so going through it
    # would be tautological
    slow = model._infer_reforward(variables, prompts, max_new_tokens=6)
    fast = model.generate(variables, prompts, max_new_tokens=6)
    assert fast.shape == (3, 14)
    np.testing.assert_array_equal(fast, slow)
    np.testing.assert_array_equal(model.infer(variables, prompts,
                                              max_new_tokens=6), fast)


def test_gpt_cached_generate_sampling_and_clip():
    model = TinyGPT()  # max_len 32
    rng = np.random.RandomState(1)
    prompts = rng.randint(1, VOCAB, size=(2, 30)).astype(np.int32)
    variables = model.init_variables(jax.random.PRNGKey(0),
                                     {"x": jnp.asarray(prompts)})
    out = model.generate(variables, prompts, max_new_tokens=10,
                         temperature=1.0, seed=7)
    assert out.shape == (2, 32)  # clipped to max_len
    assert (out[:, 30:] != 0).all()  # sampled tokens are never PAD
    np.testing.assert_array_equal(out[:, :30], prompts)
    # different seeds give different samples (overwhelmingly likely)
    out2 = model.generate(variables, prompts, max_new_tokens=10,
                          temperature=1.0, seed=8)
    assert (out[:, 30:] != out2[:, 30:]).any()


def test_gpt_infer_empty_prompt():
    """Width-0 prompts produce an unconditioned continuation via the
    re-forward path (generate() requires >= 1 column and says so)."""
    import pytest
    model = TinyGPT()
    empty = np.zeros((2, 0), np.int32)
    variables = model.init_variables(jax.random.PRNGKey(0),
                                     {"x": jnp.ones((2, 4), jnp.int32)})
    out = model.infer(variables, empty, max_new_tokens=4)
    assert out.shape == (2, 4)
    assert (out != 0).all()
    with pytest.raises(ValueError):
        model.generate(variables, empty, max_new_tokens=4)


def test_gpt_infer_rejects_overlong_prompt():
    """Prompts longer than max_len must raise, not come back silently
    truncated with zero generated tokens (serving-path data loss)."""
    import pytest
    model = TinyGPT()
    variables = model.init_variables(jax.random.PRNGKey(0),
                                     {"x": jnp.ones((2, 4), jnp.int32)})
    overlong = np.ones((2, model.module.max_len + 1), np.int32)
    with pytest.raises(ValueError, match="max_len"):
        model.infer(variables, overlong, max_new_tokens=4)


class TinyMoE(GPTMoEMini):
    def build(self):
        return GPTModule(vocab_size=VOCAB, max_len=32, hidden=32, layers=2,
                         heads=2, ffn=32, dropout=0.0, n_experts=4,
                         ep_mesh=self.ep_mesh)


def test_gpt_moe_registered_and_shapes():
    assert get_builtin("gpt-moe-mini") is GPTMoEMini
    model = TinyMoE()
    x = jnp.ones((2, T), jnp.int32)
    variables = model.init_variables(jax.random.PRNGKey(0), {"x": x})
    # expert-stacked FFN weights exist with the expert dim leading
    moe = variables["params"]["layer_0"]["moe"]
    assert moe["wi"].shape == (4, 32, 32)
    logits = model.module.apply(variables, x, train=False)
    assert logits.shape == (2, T, VOCAB)


def test_gpt_moe_loss_includes_aux():
    model = TinyMoE()
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randint(1, VOCAB, size=(4, T)).astype(np.int32))
    variables = model.init_variables(jax.random.PRNGKey(0), {"x": x})
    key = jax.random.key_data(jax.random.PRNGKey(1))
    per_ex, _ = model.loss(variables, {"x": x}, key, None)
    model.aux_coef = 0.0
    per_ex0, _ = model.loss(variables, {"x": x}, key, None)
    # the load-balance aux term contributes (>= 1 by Cauchy-Schwarz for
    # the balanced case; > 0 always with a real router)
    assert float((per_ex - per_ex0).min()) > 0.0


def test_gpt_moe_learns(mesh8):
    rng = np.random.RandomState(0)
    model = TinyMoE()
    W, S, B = 8, 2, 8
    x = make_lm_task(rng, W * S * B).reshape(W, S, B, T)
    variables = model.init_variables(jax.random.PRNGKey(0),
                                     {"x": jnp.asarray(x[0, 0])})
    engine = KAvgEngine(mesh8, model.loss, model.metrics,
                        model.configure_optimizers, donate=False)
    batch = {"x": jnp.asarray(x)}
    masks = dict(sample_mask=np.ones((W, S, B)), step_mask=np.ones((W, S)),
                 worker_mask=np.ones(W))
    first = last = None
    for _ in range(8):
        rngs = rng.randint(0, 2**31, size=(W, S, 2)).astype(np.uint32)
        variables, stats = engine.train_round(
            variables, batch, rngs=rngs, lr=3e-3, epoch=0, **masks)
        last = stats.loss_sum.sum() / stats.step_count.sum()
        if first is None:
            first = last
    assert last < first, (first, last)


def test_gpt_moe_ep_sharded_matches_unsharded():
    """The same variables forward identically whether the experts run
    replicated or sharded over the mesh `expert` axis (GSPMD inserts the
    dispatch/return all-to-alls)."""
    from kubeml_tpu.parallel.mesh import make_mesh

    mesh = make_mesh(n_data=2, n_expert=4)
    plain = TinyMoE()
    sharded = TinyMoE(ep_mesh=mesh)
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randint(1, VOCAB, size=(4, T)).astype(np.int32))
    variables = plain.init_variables(jax.random.PRNGKey(0), {"x": x})
    base = plain.module.apply(variables, x, train=False)
    out = jax.jit(lambda v, x: sharded.module.apply(v, x, train=False))(
        variables, x)
    # same structureless bf16-residual noise as the SP parity tests
    np.testing.assert_allclose(np.asarray(out), np.asarray(base),
                               rtol=5e-2, atol=6e-2)


def test_gpt_pipelined_matches_dense():
    """The GPipe-pipelined decoder trunk (stage axis, microbatched)
    equals the dense forward for full-length prompts."""
    from kubeml_tpu.parallel.mesh import make_mesh

    model = TinyGPT()  # 2 layers -> 2 stages
    rng = np.random.RandomState(0)
    B, Tp = 8, 16
    x = rng.randint(1, VOCAB, size=(B, Tp)).astype(np.int32)
    variables = model.init_variables(jax.random.PRNGKey(0), {"x": x})
    dense = model.module.apply(variables, x, train=False)
    mesh = make_mesh(n_data=4, n_stage=2)
    out = model.forward_pipelined(variables, jnp.asarray(x), mesh,
                                  microbatches=4)
    assert out.shape == (B, Tp, VOCAB)
    np.testing.assert_allclose(np.asarray(out), np.asarray(dense),
                               rtol=5e-2, atol=6e-2)


def test_gpt_pipelined_guards():
    import pytest
    from kubeml_tpu.parallel.mesh import make_mesh

    model = TinyGPT()
    mesh = make_mesh(n_data=4, n_stage=2)
    x = np.ones((8, 16), np.int32)
    variables = model.init_variables(jax.random.PRNGKey(0), {"x": x})
    padded = x.copy(); padded[0, 10:] = 0
    with pytest.raises(ValueError, match="pad-free"):
        model.forward_pipelined(variables, padded, mesh)
    with pytest.raises(ValueError, match="microbatches"):
        model.forward_pipelined(variables, x[:6], mesh, microbatches=4)
    with pytest.raises(ValueError, match="max_len"):
        model.forward_pipelined(variables, np.ones((8, 40), np.int32), mesh)


class BigCapMoE(GPTMoEMini):
    """4 experts with capacity_factor=4.0 (= E): no token is ever
    dropped, so per-shard routing equals global routing EXACTLY and the
    SP forward can be compared against the dense forward."""

    def build(self):
        return GPTModule(vocab_size=VOCAB, max_len=32, hidden=32, layers=2,
                         heads=2, ffn=32, dropout=0.0, n_experts=4,
                         capacity_factor=4.0)


def test_gpt_moe_seq_parallel_matches_dense():
    """Round 2's SP x MoE exclusion, lifted: with no capacity overflow,
    the per-shard-routed seq-parallel forward equals the dense one."""
    from kubeml_tpu.parallel.mesh import make_mesh

    model = BigCapMoE()
    rng = np.random.RandomState(0)
    B, Tsp = 2, 32
    x = rng.randint(1, VOCAB, size=(B, Tsp)).astype(np.int32)
    x[0, 20:] = 0  # ragged padding crossing shard boundaries
    variables = model.init_variables(jax.random.PRNGKey(0), {"x": x})

    dense = model.module.apply(variables, x, train=False)
    mesh = make_mesh(n_data=2, n_seq=4)
    sp = model.forward_seq_parallel(variables, x, mesh)
    assert sp.shape == (B, Tsp, VOCAB)
    np.testing.assert_allclose(np.asarray(sp), np.asarray(dense),
                               rtol=5e-2, atol=6e-2)


def test_gpt_moe_seq_parallel_default_capacity_runs():
    """Default capacity (drops possible): per-shard routing is the
    documented semantics — finite forward, correct shape."""
    from kubeml_tpu.parallel.mesh import make_mesh

    model = TinyMoE()
    rng = np.random.RandomState(1)
    x = rng.randint(1, VOCAB, size=(2, 32)).astype(np.int32)
    variables = model.init_variables(jax.random.PRNGKey(0), {"x": x})
    sp = model.forward_seq_parallel(
        variables, x, make_mesh(n_data=2, n_seq=4))
    assert sp.shape == (2, 32, VOCAB)
    assert np.isfinite(np.asarray(sp)).all()


def test_gpt_moe_seq_parallel_rejects_ep_mesh():
    import pytest

    from kubeml_tpu.parallel.mesh import make_mesh

    model = TinyMoE()
    model.ep_mesh = make_mesh(n_data=2, n_expert=4)
    with pytest.raises(ValueError, match="replicated experts"):
        model.enable_seq_parallel("ring")
    with pytest.raises(ValueError, match="replicated experts"):
        model.forward_seq_parallel(None, None,
                                   make_mesh(n_data=2, n_seq=4))


def test_gpt_moe_trains_seq_parallel():
    """The vma-checked SP round trains the MoE (per-shard routing,
    psum-averaged aux): weight/loss parity with the pure-DP round at
    overflow-free capacity."""
    from tests.test_parallel_tp_sp import _lm_sp_batch, _sp_train_compare

    _sp_train_compare(BigCapMoE, _lm_sp_batch, "ring")
