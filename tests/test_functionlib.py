"""Function registry: deploy-by-file parity with `kubeml fn create`."""

import numpy as np
import pytest

from kubeml_tpu.api.errors import FunctionNotFoundError, InvalidArgsError
from kubeml_tpu.train.functionlib import FunctionRegistry

USER_FN = '''
import flax.linen as nn
import jax.numpy as jnp
from kubeml_tpu.models.base import ClassifierModel, KubeDataset


class TinyModule(nn.Module):
    @nn.compact
    def __call__(self, x, train=False):
        return nn.Dense(3)(x.reshape((x.shape[0], -1)))


class TinyModel(ClassifierModel):
    name = "tiny"

    def build(self):
        return TinyModule()


class TinyData(KubeDataset):
    dataset = "toy"

    def transform_train(self, data, labels):
        return {"x": data * 2.0, "y": labels}
'''


def test_create_resolve_delete(tmp_path, tmp_home):
    reg = FunctionRegistry()
    src = tmp_path / "fn.py"
    src.write_text(USER_FN)
    reg.create("tiny", str(src))
    assert reg.list() == ["tiny"]
    model_cls, dataset_cls = reg.resolve("tiny")
    assert model_cls.name == "tiny"
    ds = dataset_cls()
    out = ds.transform_train(np.ones((2, 2)), np.zeros(2))
    np.testing.assert_array_equal(out["x"], 2 * np.ones((2, 2)))
    reg.delete("tiny")
    with pytest.raises(FunctionNotFoundError):
        reg.resolve("tiny")


def test_builtin_fallback(tmp_home):
    reg = FunctionRegistry()
    model_cls, _ = reg.resolve("mlp")
    assert model_cls.name == "mlp"


def test_rejects_non_model_file(tmp_path, tmp_home):
    reg = FunctionRegistry()
    src = tmp_path / "bad.py"
    src.write_text("x = 1\n")
    with pytest.raises(InvalidArgsError):
        reg.create("bad", str(src))


def test_rejects_duplicate_and_oversize(tmp_path, tmp_home):
    reg = FunctionRegistry()
    src = tmp_path / "fn.py"
    src.write_text(USER_FN)
    reg.create("tiny", str(src))
    with pytest.raises(InvalidArgsError):
        reg.create("tiny", str(src))
    big = tmp_path / "big.py"
    big.write_text(USER_FN + "#" + "x" * 300_000)
    with pytest.raises(InvalidArgsError):
        reg.create("big", str(big))
