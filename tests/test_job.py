"""TrainJob end-to-end: epoch loop, history, checkpoint, callbacks,
dynamic parallelism, goal accuracy, stop."""

import jax
import numpy as np
import pytest

from kubeml_tpu.api.errors import KubeMLException
from kubeml_tpu.api.types import TrainOptions, TrainRequest, TrainTask
from kubeml_tpu.data.registry import DatasetRegistry
from kubeml_tpu.models import get_builtin
from kubeml_tpu.models.base import KubeDataset
from kubeml_tpu.train.checkpoint import load_checkpoint
from kubeml_tpu.train.history import HistoryStore
from kubeml_tpu.train.job import JobCallbacks, TrainJob


class ToyDataset(KubeDataset):
    dataset = "blobs"


def make_blobs(reg, n_train=800, n_test=200, dim=8, classes=4, seed=0):
    """Linearly separable blobs: class c centered at one-hot(c)*3."""
    rng = np.random.RandomState(seed)

    def split(n):
        y = rng.randint(0, classes, n).astype(np.int32)
        # noisy enough that accuracy stays < 100% for a few epochs (the
        # default goal_accuracy=100 early-stop is reference parity)
        x = rng.randn(n, dim).astype(np.float32) * 2.0
        x[np.arange(n), y % dim] += 3.0
        return x, y

    xtr, ytr = split(n_train)
    xte, yte = split(n_test)
    return reg.create("blobs", xtr, ytr, xte, yte)


def make_task(job_id="testjob1", epochs=3, parallelism=2, k=2, batch=32,
              lr=0.1, static=True, validate_every=1, goal=100.0,
              engine="kavg"):
    req = TrainRequest(
        model_type="mlp", batch_size=batch, epochs=epochs, dataset="blobs",
        lr=lr, options=TrainOptions(
            default_parallelism=parallelism, static_parallelism=static,
            validate_every=validate_every, k=k, goal_accuracy=goal,
            engine=engine))
    return TrainTask(job_id=job_id, parameters=req, parallelism=parallelism)


@pytest.fixture()
def setup(tmp_path, tmp_home, mesh8):
    reg = DatasetRegistry()
    make_blobs(reg)
    store = HistoryStore()
    model = get_builtin("mlp")(hidden=16, num_classes=4)
    return reg, store, model, mesh8


def test_job_trains_and_persists(setup):
    reg, store, model, mesh = setup
    job = TrainJob(make_task(), model, ToyDataset(), mesh,
                   registry=reg, history_store=store)
    record = job.train()
    assert len(record.data.train_loss) == 3
    assert record.data.train_loss[-1] < record.data.train_loss[0]
    assert record.data.accuracy[-1] > 60.0
    assert record.data.parallelism == [2, 2, 2]
    # history persisted
    assert store.get("testjob1").data.accuracy == record.data.accuracy
    # checkpoint persisted and loadable
    variables, manifest = load_checkpoint("testjob1")
    assert manifest["model"] == "mlp"
    preds = model.infer(variables, np.zeros((4, 8), np.float32))
    assert preds.shape == (4,)


def test_goal_accuracy_early_stop(setup):
    reg, store, model, mesh = setup
    job = TrainJob(make_task(epochs=20, goal=50.0), model, ToyDataset(),
                   mesh, registry=reg, history_store=store)
    record = job.train()
    assert len(record.data.train_loss) < 20  # stopped early


def test_stop_signal(setup):
    reg, store, model, mesh = setup
    task = make_task(epochs=50)
    job = TrainJob(task, model, ToyDataset(), mesh, registry=reg,
                   history_store=store)
    calls = []

    def publish(m):
        calls.append(m)
        if len(calls) == 2:
            job.stop()

    job.callbacks = JobCallbacks(publish_metrics=publish)
    record = job.train()
    assert len(record.data.train_loss) == 2


def test_dynamic_parallelism_callback(setup):
    reg, store, model, mesh = setup
    asked = []

    def request_parallelism(task):
        asked.append(task.parallelism)
        return task.parallelism + 1  # scheduler scales up every epoch

    job = TrainJob(make_task(epochs=3, static=False), model, ToyDataset(),
                   mesh, registry=reg,
                   callbacks=JobCallbacks(request_parallelism=request_parallelism))
    record = job.train()
    assert record.data.parallelism == [2, 3, 4]
    assert asked == [2, 3]  # not asked after final epoch


def test_validate_every_cadence(setup):
    reg, store, model, mesh = setup
    job = TrainJob(make_task(epochs=4, validate_every=2), model,
                   ToyDataset(), mesh, registry=reg)
    record = job.train()
    acc = record.data.accuracy
    assert np.isnan(acc[0]) and not np.isnan(acc[1])
    assert not np.isnan(acc[3])


def test_failure_reports_exit_err(setup):
    reg, store, model, mesh = setup
    task = make_task()
    task.parameters.dataset = "missing"
    finished = []
    job = TrainJob(task, model, ToyDataset(), mesh, registry=reg,
                   callbacks=JobCallbacks(
                       on_finish=lambda jid, err: finished.append((jid, err))))
    with pytest.raises(Exception):
        job.train()
    assert finished and finished[0][1] is not None
    assert task.state == "failed"


def test_checkpoint_every_and_warm_start(setup, monkeypatch):
    reg, store, model, mesh = setup
    # epoch-cadence checkpointing: every epoch must produce a checkpoint
    # save in addition to the final one
    import kubeml_tpu.train.checkpoint as ckpt_mod
    saved = []
    real_save = ckpt_mod.save_checkpoint
    monkeypatch.setattr(
        ckpt_mod, "save_checkpoint",
        lambda jid, v, m, root=None: saved.append(m)
        or real_save(jid, v, m, root=root))
    task = make_task(job_id="ckptjob1", epochs=2)
    task.parameters.options.checkpoint_every = 1
    TrainJob(task, model, ToyDataset(), mesh, registry=reg,
             history_store=store).train()
    # saves are async latest-wins, so intermediate epochs may be elided
    # under write pressure; the durable contract is: at least one save
    # happened, the last one captured the final epoch, and the redundant
    # final save was skipped (the epoch-2 periodic save covers it)
    assert saved and saved[-1].get("epoch") == 2
    assert all(m.get("epoch") is not None for m in saved)
    variables, manifest = load_checkpoint("ckptjob1")
    assert manifest["function"] == "mlp"
    assert manifest["epoch"] == 2

    # warm start: the resumed job's first-epoch loss must be ~ the donor's
    # last loss, well below a cold start's first-epoch loss
    cold = TrainJob(make_task(job_id="coldjob1", epochs=1),
                    get_builtin("mlp")(hidden=16, num_classes=4),
                    ToyDataset(), mesh, registry=reg, history_store=store)
    cold_rec = cold.train()

    warm_task = make_task(job_id="warmjob1", epochs=1)
    warm_task.parameters.resume_from = "ckptjob1"
    warm = TrainJob(warm_task,
                    get_builtin("mlp")(hidden=16, num_classes=4),
                    ToyDataset(), mesh, registry=reg, history_store=store)
    warm_rec = warm.train()
    assert warm_rec.data.train_loss[0] < cold_rec.data.train_loss[0]


def test_resume_from_self_continues_job(setup):
    """Crash-recovery resume (resume_from == own job id): the job
    restores completed-epoch history, epoch index, and the negotiated
    parallelism from the mid-job checkpoint manifest, then runs ONLY
    the remaining epochs — one continuous history (the contract the PS
    watchdog's checkpoint-based restart builds on)."""
    from kubeml_tpu.train.checkpoint import save_checkpoint

    reg, store, model, mesh = setup
    first = TrainJob(make_task(job_id="resumejob1", epochs=2),
                     model, ToyDataset(), mesh, registry=reg,
                     history_store=store)
    rec1 = first.train()

    # re-publish the checkpoint as crash-time state: a mid-job manifest
    # claiming 2 epochs done and N=5 negotiated for the next epoch
    variables, manifest = load_checkpoint("resumejob1")
    crafted = dict(manifest, epoch=2, history=rec1.data.to_dict(),
                   parallelism=5)
    crafted.pop("completed", None)  # mid-job state, not a finished one
    save_checkpoint("resumejob1", variables, crafted)

    task = make_task(job_id="resumejob1", epochs=4)
    task.parameters.resume_from = "resumejob1"
    job2 = TrainJob(task, get_builtin("mlp")(hidden=16, num_classes=4),
                    ToyDataset(), mesh, registry=reg, history_store=store)
    rec2 = job2.train()

    assert job2._start_epoch == 2
    assert len(rec2.data.train_loss) == 4
    # restored epochs preserved verbatim; remaining epochs ran at the
    # manifest's carried-over parallelism, not the task default
    assert rec2.data.train_loss[:2] == rec1.data.train_loss
    assert rec2.data.parallelism == [2, 2, 5, 5]
    # training actually continued from the checkpoint weights
    assert rec2.data.train_loss[2] < rec1.data.train_loss[0]


def test_resume_from_self_completed_job_retrains_nothing(setup):
    """A process killed between its final checkpoint and its /finish
    notification leaves a manifest stamped completed=True; the restart
    must resume straight into completion — full history, zero epochs
    retrained — not rerun the job from its last epoch count."""
    import json
    import os

    from kubeml_tpu.api.const import kubeml_home

    reg, store, model, mesh = setup
    first = TrainJob(make_task(job_id="donejob1", epochs=2), model,
                     ToyDataset(), mesh, registry=reg, history_store=store)
    rec1 = first.train()
    with open(os.path.join(kubeml_home(), "models", "donejob1",
                           "manifest.json")) as f:
        assert json.load(f)["completed"] is True

    task = make_task(job_id="donejob1", epochs=2)
    task.parameters.resume_from = "donejob1"
    job2 = TrainJob(task, get_builtin("mlp")(hidden=16, num_classes=4),
                    ToyDataset(), mesh, registry=reg, history_store=store)
    rec2 = job2.train()
    assert job2._start_epoch == 2  # loop skipped entirely
    assert rec2.data.train_loss == rec1.data.train_loss
    assert rec2.data.accuracy == rec1.data.accuracy


def test_job_shuffle_option(setup):
    """options.shuffle reaches the RoundLoader (job path of the loader
    regression tests): epoch document order differs between epochs and
    the job still converges."""
    reg, store, model, mesh = setup
    task = make_task(job_id="shufjob1", epochs=2)
    task.parameters.options.shuffle = True
    job = TrainJob(task, model, ToyDataset(), mesh, registry=reg,
                   history_store=store)
    record = job.train()
    assert job._loader.shuffle is True
    assert len(record.data.train_loss) == 2
    assert record.data.train_loss[-1] < record.data.train_loss[0]
    # same job without shuffle keeps the parity default
    job2 = TrainJob(make_task(job_id="noshuf1", epochs=1), model,
                    ToyDataset(), mesh, registry=reg, history_store=store)
    job2.train()
    assert job2._loader.shuffle is False


def test_final_save_survives_periodic_failure(setup, monkeypatch):
    """A transient periodic-save failure with no later successful save
    must not abort the job: the final synchronous save is the
    remediation (ADVICE r1), and the published checkpoint holds the end
    state."""
    import kubeml_tpu.train.checkpoint as ckpt_mod
    reg, store, model, mesh = setup
    real_save = ckpt_mod.save_checkpoint
    calls = {"n": 0}

    def flaky(jid, v, m, root=None):
        calls["n"] += 1
        if m.get("epoch") is not None:  # every periodic save fails
            raise OSError("disk full")
        return real_save(jid, v, m, root=root)

    monkeypatch.setattr(ckpt_mod, "save_checkpoint", flaky)
    task = make_task(job_id="flakyckpt1", epochs=2)
    task.parameters.options.checkpoint_every = 2  # only the LAST epoch,
    # so no later periodic success supersedes the failure
    record = TrainJob(task, model, ToyDataset(), mesh, registry=reg,
                      history_store=store).train()
    assert len(record.data.train_loss) == 2
    variables, manifest = load_checkpoint("flakyckpt1")
    assert manifest["model"] == "mlp"
    # the final (sync) save won: only it stamps completed=True (periodic
    # saves never do — and all of them failed here anyway)
    assert manifest.get("completed") is True
    assert manifest.get("epoch") == 2
    # the periodic attempt ran (and failed) through the async writer;
    # the final save goes through job.py's direct import, unpatched
    assert calls["n"] >= 1


def test_warm_start_function_mismatch_rejected(setup):
    reg, store, model, mesh = setup
    donor = TrainJob(make_task(job_id="donor1", epochs=1), model,
                     ToyDataset(), mesh, registry=reg, history_store=store)
    donor.train()

    task = make_task(job_id="mismatch1", epochs=1)
    task.parameters.model_type = "lenet"
    task.parameters.resume_from = "donor1"
    bad = TrainJob(task, get_builtin("lenet")(), ToyDataset(), mesh,
                   registry=reg, history_store=store)
    with pytest.raises(Exception, match="holds function"):
        bad.train()


def test_straggler_tolerance_under_fault_injection(setup):
    """Random worker loss every round: the job must finish, learn, and
    average only over survivors (reference semantics util.go:144-166)."""
    from kubeml_tpu.utils.chaos import WorkerLossInjector

    reg, store, model, mesh = setup
    chaos = WorkerLossInjector(p=0.4, seed=7)
    job = TrainJob(make_task(job_id="chaosjob1", epochs=3, parallelism=4),
                   model, ToyDataset(), mesh, registry=reg,
                   history_store=store, round_hook=chaos)
    record = job.train()
    assert chaos.degraded_rounds > 0 and chaos.workers_lost > 0
    assert len(record.data.train_loss) == 3
    assert record.data.train_loss[-1] < record.data.train_loss[0]
    assert np.isfinite(record.data.train_loss).all()
    assert record.data.accuracy[-1] > 50.0


def test_syncdp_engine_job(setup):
    """options.engine='syncdp' trains through the product path: per-step
    gradient averaging, persistent optimizer state, same history/
    checkpoint/validate surface as kavg."""
    reg, store, model, mesh = setup
    job = TrainJob(make_task(job_id="syncjob1", engine="syncdp", lr=0.05),
                   model, ToyDataset(), mesh, registry=reg,
                   history_store=store)
    record = job.train()
    assert len(record.data.train_loss) == 3
    assert record.data.train_loss[-1] < record.data.train_loss[0]
    assert np.isfinite(record.data.train_loss).all()
    assert record.data.accuracy[-1] > 60.0
    # checkpoint works off the syncdp state's variables view
    variables, manifest = load_checkpoint("syncjob1")
    preds = model.infer(variables, np.zeros((4, 8), np.float32))
    assert preds.shape == (4,)


def test_syncdp_straggler_tolerance(setup):
    """Worker loss under syncdp: the lost worker's samples drop out of
    the global batch (mask), the job still finishes and learns."""
    from kubeml_tpu.utils.chaos import WorkerLossInjector

    reg, store, model, mesh = setup
    chaos = WorkerLossInjector(p=0.4, seed=7)
    job = TrainJob(make_task(job_id="syncchaos1", epochs=3, parallelism=4,
                             engine="syncdp", lr=0.05),
                   model, ToyDataset(), mesh, registry=reg,
                   history_store=store, round_hook=chaos)
    record = job.train()
    assert chaos.degraded_rounds > 0 and chaos.workers_lost > 0
    assert record.data.train_loss[-1] < record.data.train_loss[0]
    assert np.isfinite(record.data.train_loss).all()


def test_unknown_engine_rejected(setup):
    reg, store, model, mesh = setup
    job = TrainJob(make_task(job_id="badengine1", engine="sgd"),
                   model, ToyDataset(), mesh, registry=reg,
                   history_store=store)
    with pytest.raises(Exception, match="unknown training engine"):
        job.train()


def test_all_workers_lost_aborts(setup):
    """Zero survivors in a round is the job-abort path (job.go:188-193)."""
    reg, store, model, mesh = setup

    def kill_all(rb):
        import dataclasses as dc
        return dc.replace(rb, worker_mask=np.zeros_like(rb.worker_mask))

    job = TrainJob(make_task(job_id="deadjob1", epochs=2), model,
                   ToyDataset(), mesh, registry=reg, history_store=store,
                   round_hook=kill_all)
    with pytest.raises(Exception, match="no workers contributed"):
        job.train()
    assert job.exit_err is not None


# ------------------------------------------- job-level TP / SP (net-new)


def make_token_task(reg, name="toktask", n_train=256, n_test=64, T=16,
                    vocab=1000, seed=0):
    """Learnable text classification: label = first token > vocab/2."""
    rng = np.random.RandomState(seed)

    def split(n):
        x = rng.randint(1, vocab, size=(n, T)).astype(np.int32)
        y = (x[:, 0] > vocab // 2).astype(np.int32)
        return x, y

    xtr, ytr = split(n_train)
    xte, yte = split(n_test)
    return reg.create(name, xtr, ytr, xte, yte)


class TokenDataset(KubeDataset):
    dataset = "toktask"


def test_job_tensor_parallel_bert(tmp_home, mesh8):
    """A DP x TP job: --tensor-parallel 2 carves the 8-device mesh into
    data=4 x model=2, Megatron-shards the variables, trains AND
    validates (VERDICT r1 item 3's done criterion at the job layer)."""
    from kubeml_tpu.parallel.mesh import MODEL_AXIS, data_axis_size

    reg = DatasetRegistry()
    make_token_task(reg)
    store = HistoryStore()
    model = get_builtin("bert-tiny")()
    task = make_task(job_id="tpjob1", epochs=2, parallelism=4, k=1,
                     batch=16, lr=1e-3)
    task.parameters.model_type = "bert-tiny"
    task.parameters.dataset = "toktask"
    task.parameters.options.n_model = 2
    job = TrainJob(task, model, TokenDataset(), mesh8, registry=reg,
                   history_store=store)
    record = job.train()
    assert data_axis_size(job.mesh) == 4
    assert job.mesh.shape[MODEL_AXIS] == 2
    # variables actually carry model-axis shardings
    specs = [v.sharding.spec for v in
             jax.tree_util.tree_leaves(job.variables)
             if hasattr(v, "sharding")]
    assert any(MODEL_AXIS in str(s) for s in specs)
    assert record.data.train_loss[-1] < record.data.train_loss[0]
    assert record.data.accuracy[-1] == record.data.accuracy[-1]  # validated


def test_job_seq_parallel_gpt(tmp_home, mesh8):
    """A DP x SP job: --seq-parallel 2 trains the causal LM with ring
    attention inside the engine round; loss falls and validation runs
    (VERDICT r1 item 4 at the job layer)."""
    from kubeml_tpu.parallel.mesh import SEQ_AXIS, data_axis_size
    from tests.test_models_gpt import TinyGPT

    class LMDataset(KubeDataset):
        dataset = "lmtask"

        def transform_train(self, data, labels):
            return {"x": data}

        transform_test = transform_train

    reg = DatasetRegistry()
    rng = np.random.RandomState(0)

    def lm_split(n, T=32):
        start = rng.randint(1, 63, size=(n, 1))
        seq = (start + np.arange(T)[None, :] - 1) % 63 + 1
        return seq.astype(np.int32), np.zeros(n, np.int32)

    xtr, ytr = lm_split(256)
    xte, yte = lm_split(64)
    reg.create("lmtask", xtr, ytr, xte, yte)

    store = HistoryStore()
    task = make_task(job_id="spjob1", epochs=2, parallelism=4, k=1,
                     batch=16, lr=3e-3)
    task.parameters.model_type = "gpt-mini"
    task.parameters.dataset = "lmtask"
    task.parameters.options.n_seq = 2
    job = TrainJob(task, TinyGPT(), LMDataset(), mesh8, registry=reg,
                   history_store=store)
    record = job.train()
    assert data_axis_size(job.mesh) == 4
    assert job.mesh.shape[SEQ_AXIS] == 2
    assert job.model.module.seq_axis == SEQ_AXIS
    assert record.data.train_loss[-1] < record.data.train_loss[0]
    assert record.data.accuracy[-1] == record.data.accuracy[-1]


def test_job_seq_and_expert_parallel_moe(tmp_home, mesh8):
    """SP x EP at the job surface (round 4, the matrix's last
    exclusion): --seq-parallel 2 --expert-parallel 2 carves
    data=2 x seq=2 x expert=2 and trains the MoE trunk with experts
    sharded over the expert axis inside the fully-manual round — the
    vma backward assembles the expert-weight gradients exactly as it
    does manual TP's."""
    from kubeml_tpu.parallel.mesh import (EXPERT_AXIS, SEQ_AXIS,
                                          data_axis_size)

    class LMDataset(KubeDataset):
        dataset = "lmtask"

        def transform_train(self, data, labels):
            return {"x": data}

        transform_test = transform_train

    reg = DatasetRegistry()
    rng = np.random.RandomState(0)

    def lm_split(n, T=32):
        start = rng.randint(1, 63, size=(n, 1))
        seq = (start + np.arange(T)[None, :] - 1) % 63 + 1
        return seq.astype(np.int32), np.zeros(n, np.int32)

    xtr, ytr = lm_split(256)
    xte, yte = lm_split(64)
    reg.create("lmtask", xtr, ytr, xte, yte)

    from tests.test_models_gpt import TinyMoE

    store = HistoryStore()
    task = make_task(job_id="spepjob1", epochs=2, parallelism=2, k=1,
                     batch=16, lr=3e-3)
    task.parameters.model_type = "gpt-moe-mini"
    task.parameters.dataset = "lmtask"
    task.parameters.options.n_seq = 2
    task.parameters.options.n_expert = 2
    job = TrainJob(task, TinyMoE(), LMDataset(), mesh8, registry=reg,
                   history_store=store)
    record = job.train()
    assert data_axis_size(job.mesh) == 2
    assert job.mesh.shape[SEQ_AXIS] == 2
    assert job.mesh.shape[EXPERT_AXIS] == 2
    assert job.model.module.seq_axis == SEQ_AXIS
    assert job.model.module.ep_axis == EXPERT_AXIS
    assert record.data.train_loss[-1] < record.data.train_loss[0]
    assert record.data.accuracy[-1] == record.data.accuracy[-1]


def test_job_expert_parallel_alone_rejects_non_moe(tmp_home, mesh8):
    """Round 5 lifts the EP-requires-SP restriction: --expert-parallel
    alone now routes to the GSPMD ep_mesh path, so a function without
    experts gets the model-surface rejection (as a 400), not a
    requires-seq-parallel error."""
    reg = DatasetRegistry()
    make_blobs(reg)
    task = make_task(job_id="eponly1", epochs=1)
    task.parameters.options.n_expert = 2
    job = TrainJob(task, get_builtin("mlp")(hidden=16, num_classes=4),
                   ToyDataset(), mesh8, registry=reg)
    with pytest.raises(KubeMLException, match="no experts to shard") as ei:
        job.train()
    assert ei.value.status_code == 400


def test_job_expert_parallel_rejects_non_moe(tmp_home, mesh8):
    """--expert-parallel on a function without experts fails with the
    model-surface message, not a trace-time explosion."""
    from tests.test_models_gpt import TinyGPT

    class LMDataset(KubeDataset):
        dataset = "lmtask2"

        def transform_train(self, data, labels):
            return {"x": data}

        transform_test = transform_train

    reg = DatasetRegistry()
    rng = np.random.RandomState(0)
    x = rng.randint(1, 63, size=(64, 32)).astype(np.int32)
    reg.create("lmtask2", x, np.zeros(64, np.int32), x[:16],
               np.zeros(16, np.int32))
    task = make_task(job_id="epbad1", epochs=1, parallelism=2, k=1,
                     batch=16)
    task.parameters.model_type = "gpt-mini"
    task.parameters.dataset = "lmtask2"
    task.parameters.options.n_seq = 2
    task.parameters.options.n_expert = 2
    job = TrainJob(task, TinyGPT(), LMDataset(), mesh8, registry=reg)
    with pytest.raises(KubeMLException, match="no experts to shard"):
        job.train()


def test_job_tensor_and_seq_parallel_combined(tmp_home, mesh8):
    """Round 2's exclusion cleared at the job surface: --tensor-parallel 2
    --seq-parallel 2 carves data=2 x model=2 x seq=2 and trains the
    fully-manual round (Megatron psums + KV ring in one program)."""
    from kubeml_tpu.parallel.mesh import (MODEL_AXIS, SEQ_AXIS,
                                          data_axis_size)

    reg = DatasetRegistry()
    make_token_task(reg)
    store = HistoryStore()
    model = get_builtin("bert-tiny")()
    task = make_task(job_id="tpspjob1", epochs=2, parallelism=2, k=1,
                     batch=16, lr=1e-3)
    task.parameters.model_type = "bert-tiny"
    task.parameters.dataset = "toktask"
    task.parameters.options.n_model = 2
    task.parameters.options.n_seq = 2
    job = TrainJob(task, model, TokenDataset(), mesh8, registry=reg,
                   history_store=store)
    record = job.train()
    assert data_axis_size(job.mesh) == 2
    assert job.mesh.shape[MODEL_AXIS] == 2
    assert job.mesh.shape[SEQ_AXIS] == 2
    assert job.model.module.tp_axis == MODEL_AXIS
    assert job.model.module.seq_axis == SEQ_AXIS
    assert record.data.train_loss[-1] < record.data.train_loss[0]
    assert record.data.accuracy[-1] == record.data.accuracy[-1]  # validated


def test_job_parallelism_option_validation(setup):
    """Clear 400s for every unsupported TP/SP combination."""
    from kubeml_tpu.api.errors import KubeMLException
    reg, store, model, mesh = setup

    def expect_400(mutate, m=None, match=""):
        task = make_task(job_id="badopt1", epochs=1)
        mutate(task.parameters.options)
        job = TrainJob(task, m or get_builtin("mlp")(hidden=16,
                                                     num_classes=4),
                       ToyDataset(), mesh, registry=reg,
                       history_store=store)
        with pytest.raises(KubeMLException) as ei:
            job.train()
        assert ei.value.status_code == 400
        assert match in str(ei.value.message)

    # TP on a model with no rules
    expect_400(lambda o: setattr(o, "n_model", 2), match="tensor-parallel")
    # manual TP on a model without a tp_axis module
    def manual_on_mlp(o):
        o.n_model = 2
        o.tp_impl = "manual"
    expect_400(manual_on_mlp, match="manual tensor parallelism")
    # manual TP on MoE: curated 400, not a trace-time 500 (the module
    # HAS a tp_axis field but the expert FFNs reject the split)
    expect_400(manual_on_mlp, m=get_builtin("gpt-moe-mini")(),
               match="expert")
    # TP + SP combined runs manual TP, which requires ring (not ulysses)
    def both_ulysses(o):
        o.n_model = 2
        o.n_seq = 2
        o.seq_impl = "ulysses"
    expect_400(both_ulysses, m=get_builtin("bert-tiny")(), match="ring")
    # unknown tp_impl
    def bad_impl(o):
        o.n_model = 2
        o.tp_impl = "magic"
    expect_400(bad_impl, m=get_builtin("bert-tiny")(), match="tp_impl")
    # syncdp + TP
    def sync_tp(o):
        o.engine = "syncdp"
        o.n_model = 2
    expect_400(sync_tp, m=get_builtin("bert-tiny")(), match="kavg")
    # indivisible device count: 8 devices, factor 3
    expect_400(lambda o: setattr(o, "n_model", 3),
               m=get_builtin("bert-tiny")(), match="divisible")
    # SP on a model with no seq support
    expect_400(lambda o: setattr(o, "n_seq", 2), match="sequence")


def test_max_parallelism_caps_scheduler_growth(setup):
    """options.max_parallelism stops the reference policy's unbounded
    worker accretion (policy.go:75-90 floor-clamps at 1 only), binds
    from epoch 1, and rejects negative values."""
    from kubeml_tpu.api.errors import KubeMLException
    reg, store, model, mesh = setup
    task = make_task(job_id="capjob1", epochs=4, static=False)
    task.parameters.options.max_parallelism = 3

    job = TrainJob(task, model, ToyDataset(), mesh, registry=reg,
                   callbacks=JobCallbacks(
                       request_parallelism=lambda t: t.parallelism + 1))
    record = job.train()
    assert record.data.parallelism == [2, 3, 3, 3]

    # the cap binds on the INITIAL parallelism too
    over = make_task(job_id="capjob2", epochs=1, parallelism=8)
    over.parameters.options.max_parallelism = 3
    rec2 = TrainJob(over, model, ToyDataset(), mesh,
                    registry=reg).train()
    assert rec2.data.parallelism == [3]

    bad = make_task(job_id="capjob3", epochs=1)
    bad.parameters.options.max_parallelism = -2
    with pytest.raises(KubeMLException) as ei:
        TrainJob(bad, model, ToyDataset(), mesh, registry=reg).train()
    assert ei.value.status_code == 400


def test_elastic_shape_pinning_single_program(setup):
    """Recompile-free elastic N: with a max_parallelism cap, every ±1
    the policy takes reuses ONE compiled round program (W pinned at the
    lane-padded cap, N expressed through the worker mask) and ONE eval
    program — the fix for the 20-200 s per-±1 recompiles that dominated
    the round-4 autoscale trajectories."""
    from kubeml_tpu.parallel.mesh import make_mesh
    reg, store, model, _ = setup
    # a 1-lane mesh so lane padding can't mask the effect: without the
    # pin, W would track N exactly and every ±1 would be a new program
    mesh1 = make_mesh(n_data=1, devices=jax.devices()[:1])
    schedule = iter([3, 4, 3, 2, 4])
    task = make_task(job_id="elastic1", epochs=6, static=False)
    task.parameters.options.max_parallelism = 4
    job = TrainJob(task, model, ToyDataset(), mesh1, registry=reg,
                   history_store=store,
                   callbacks=JobCallbacks(
                       request_parallelism=lambda t: next(schedule, None)))
    record = job.train()
    assert record.data.parallelism == [2, 3, 4, 3, 2, 4]
    # one train program, one eval program — across FIVE parallelism moves
    assert len(job._engine._train_cache) == 1
    assert len(job._engine._eval_cache) == 1
    # pinned W is the lane-padded cap; training still converges
    assert job._loader.w_floor == 4
    assert record.data.accuracy[-1] > 60.0


def test_elastic_uncapped_grow_only_shapes(setup):
    """Without a cap, W is a grow-only high-water mark: scale-downs
    never reshape (no recompile), only crossing a new maximum does."""
    from kubeml_tpu.parallel.mesh import make_mesh
    reg, store, model, _ = setup
    mesh1 = make_mesh(n_data=1, devices=jax.devices()[:1])
    schedule = iter([4, 2, 4, 3])
    task = make_task(job_id="elastic2", epochs=5, static=False)
    job = TrainJob(task, model, ToyDataset(), mesh1, registry=reg,
                   callbacks=JobCallbacks(
                       request_parallelism=lambda t: next(schedule, None)))
    record = job.train()
    assert record.data.parallelism == [2, 4, 2, 4, 3]
    # two shapes ever: W=2 (start) and W=4 (first growth); the 4->2->4
    # moves reuse the W=4 program
    assert len(job._engine._train_cache) == 2
    assert job._loader.w_floor == 4


def test_policy_elapsed_excludes_compile(setup):
    """The duration reported to the throughput policy subtracts compile
    spikes (RoundStats.compiled), falling back to the cross-epoch EMA
    when every round of an epoch compiled (1-round epochs)."""
    reg, store, model, mesh = setup
    job = TrainJob(make_task(), model, ToyDataset(), mesh, registry=reg)
    # epoch 1: no steady sample yet — a steady dispatch is ~0 (async
    # dispatch is ms), so the whole spike counts as compile; otherwise
    # the policy's prev==0.0 branch would record a compile-inflated
    # reference time and grant every later epoch a spurious +1
    job._note_round_times([(5.0, 1, True, "kavg.train")])
    assert job._compile_overhead_s == 5.0
    # steady dispatches establish the EMA, normalized PER ROUND first:
    # a 2-round grouped dispatch at 0.04s is a 0.02s/round sample
    job._note_round_times([(0.04, 2, False, "kavg.train_multi"),
                           (0.04, 1, False, "kavg.train")])
    assert job._compile_overhead_s == 0.0
    assert abs(job._steady_round_ema - 0.03) < 1e-9
    # mixed epoch: spike minus the would-have-been steady cost of the
    # ROUNDS the compiling dispatch carried (2 here)
    job._note_round_times([(4.0, 2, True, "kavg.train_multi"),
                           (0.03, 1, False, "kavg.train")])
    assert abs(job._compile_overhead_s - (4.0 - 2 * 0.03)) < 1e-6
    # all-compiled epoch: the EMA stands in for the steady estimate
    job._note_round_times([(2.0, 1, True, "kavg.train")])
    assert abs(job._compile_overhead_s - (2.0 - job._steady_round_ema)) \
        < 1e-6


def test_loader_shape_floors(setup):
    """RoundLoader w_floor/s_floor semantics: pinned W, grow-only
    high-water, and S tracking N for sparse averaging (k=-1)."""
    from kubeml_tpu.data.loader import RoundLoader
    reg, store, model, mesh = setup
    handle = reg.get("blobs")
    ld = RoundLoader(handle, ToyDataset(), n_lanes=1, w_floor=8)
    rb = next(iter(ld.epoch_rounds(ld.plan(2, 2, 32), epoch=0)))
    assert rb.batch["x"].shape[0] == 8          # W pinned at the floor
    assert rb.worker_mask.sum() == 2            # N through the mask only
    s_at_k2 = rb.batch["x"].shape[1]
    # a later smaller plan keeps the shape (grow-only)
    rb2 = next(iter(ld.epoch_rounds(ld.plan(1, 2, 32), epoch=1)))
    assert rb2.batch["x"].shape[:2] == (8, s_at_k2)
    # sparse averaging: S tracks N (no high-water) so the pinned shape
    # never pays whole-shard masked compute at the cap
    ld2 = RoundLoader(handle, ToyDataset(), n_lanes=1, w_floor=4)
    s1 = next(iter(ld2.epoch_rounds(ld2.plan(1, -1, 32),
                                    epoch=0))).batch["x"].shape[1]
    s4 = next(iter(ld2.epoch_rounds(ld2.plan(4, -1, 32),
                                    epoch=1))).batch["x"].shape[1]
    assert s4 < s1


def _lm_registry(name="pplm", n_train=128, n_test=32, T=16, seed=0):
    """Tiny learnable LM dataset (ascending token runs) + its dataset
    class, for the pipeline/expert job-surface tests."""
    class LMDataset(KubeDataset):
        dataset = name

        def transform_train(self, data, labels):
            return {"x": data}

        transform_test = transform_train

    reg = DatasetRegistry()
    rng = np.random.RandomState(seed)

    def split(n):
        start = rng.randint(1, 63, size=(n, 1))
        seq = (start + np.arange(T)[None, :] - 1) % 63 + 1
        return seq.astype(np.int32), np.zeros(n, np.int32)

    if name not in [d.name for d in reg.list()]:
        reg.create(name, *split(n_train), *split(n_test))
    return reg, LMDataset()


def test_job_pipeline_parallel_matches_dense(tmp_home):
    """--pipeline-parallel at the job surface (round 5): data=4 x
    stage=2 trains the GPT trunk through the GPipe body inside the
    fully-manual round, and the merged history MATCHES the unpipelined
    job on an equal-lane mesh (same seed, same plan, dropout 0) —
    GPipe through the TrainJob is semantics-preserving, not just
    convergent."""
    import jax as _jax

    from kubeml_tpu.parallel.mesh import (STAGE_AXIS, data_axis_size,
                                          make_mesh)
    from tests.test_models_gpt import TinyGPT

    def run(n_stage, job_id):
        reg, ds = _lm_registry()
        task = make_task(job_id=job_id, epochs=2, parallelism=2, k=1,
                         batch=8, lr=3e-3)
        task.parameters.model_type = "gpt-mini"
        task.parameters.dataset = "pplm"
        task.parameters.options.n_stage = n_stage
        mesh = make_mesh(n_data=4, n_stage=n_stage)
        job = TrainJob(task, TinyGPT(), ds, mesh, registry=reg)
        return job, job.train()

    pp_job, pp_rec = run(2, "ppjob1")
    assert data_axis_size(pp_job.mesh) == 4
    assert pp_job.mesh.shape[STAGE_AXIS] == 2
    assert pp_job.model._pp_microbatches == 4  # auto: 2 x stages
    dense_job, dense_rec = run(1, "ppjob2")
    # TinyGPT is dropout-0 and the plans/rngs are seed-identical, so
    # the two jobs differ only by pipelined vs dense trunk execution
    np.testing.assert_allclose(pp_rec.data.train_loss,
                               dense_rec.data.train_loss,
                               rtol=2e-3, atol=2e-3)
    np.testing.assert_allclose(pp_rec.data.accuracy,
                               dense_rec.data.accuracy,
                               rtol=2e-2, atol=0.5)
    assert pp_rec.data.train_loss[-1] < pp_rec.data.train_loss[0]


def test_job_pipeline_parallel_with_experts(tmp_home, mesh8):
    """PP x EP at the job surface: --pipeline-parallel 2
    --expert-parallel 2 carves data=2 x stage=2 x expert=2; the MoE
    trunk pipelines with experts sharded over the expert axis
    (ep_partial_ffn inside the same manual round)."""
    from kubeml_tpu.parallel.mesh import (EXPERT_AXIS, STAGE_AXIS,
                                          data_axis_size)
    from tests.test_models_gpt import TinyMoE

    reg, ds = _lm_registry()
    task = make_task(job_id="ppepjob1", epochs=2, parallelism=2, k=1,
                     batch=8, lr=3e-3)
    task.parameters.model_type = "gpt-moe-mini"
    task.parameters.dataset = "pplm"
    task.parameters.options.n_stage = 2
    task.parameters.options.n_expert = 2
    job = TrainJob(task, TinyMoE(), ds, mesh8, registry=reg)
    record = job.train()
    assert data_axis_size(job.mesh) == 2
    assert job.mesh.shape[STAGE_AXIS] == 2
    assert job.mesh.shape[EXPERT_AXIS] == 2
    assert job.model.module.ep_axis == EXPERT_AXIS
    assert record.data.train_loss[-1] < record.data.train_loss[0]


def test_job_dp_ep_gspmd_matches_replicated(tmp_home):
    """Plain DP x EP (round 5, no SP/PP required): --expert-parallel 2
    alone takes the GSPMD ep_mesh route — inner axes stay Auto and XLA
    materializes the token all-to-alls inside each DP lane — and the
    history matches the replicated-expert job on an equal-lane mesh."""
    from kubeml_tpu.parallel.mesh import (EXPERT_AXIS, data_axis_size,
                                          make_mesh)
    from tests.test_models_gpt import TinyMoE

    def run(n_expert, job_id):
        reg, ds = _lm_registry()
        task = make_task(job_id=job_id, epochs=2, parallelism=2, k=1,
                         batch=8, lr=3e-3)
        task.parameters.model_type = "gpt-moe-mini"
        task.parameters.dataset = "pplm"
        task.parameters.options.n_expert = n_expert
        mesh = make_mesh(n_data=4, n_expert=n_expert)
        job = TrainJob(task, TinyMoE(), ds, mesh, registry=reg)
        return job, job.train()

    ep_job, ep_rec = run(2, "dpepjob1")
    assert data_axis_size(ep_job.mesh) == 4
    assert ep_job.mesh.shape[EXPERT_AXIS] == 2
    assert ep_job.model.module.ep_mesh is ep_job.mesh
    _, dense_rec = run(1, "dpepjob2")
    np.testing.assert_allclose(ep_rec.data.train_loss,
                               dense_rec.data.train_loss,
                               rtol=2e-3, atol=2e-3)
    assert ep_rec.data.train_loss[-1] < ep_rec.data.train_loss[0]


def test_job_pipeline_parallel_misconfigs(tmp_home, mesh8):
    """PP misconfigs fail as 400s at the job surface, not trace-time
    explosions: unsupported family, SP/TP composition, indivisible
    microbatches, indivisible layers."""
    from tests.test_models_gpt import TinyGPT

    def expect_400(mutate, model=None, dataset=None, match=""):
        reg, ds = _lm_registry()
        if model is None:
            make_blobs(reg)
            model, ds = get_builtin("mlp")(hidden=16, num_classes=4), \
                ToyDataset()
            dsname = "blobs"
        else:
            dsname = "pplm"
        task = make_task(job_id="ppbad", epochs=1, parallelism=2, k=1,
                         batch=8)
        task.parameters.dataset = dsname
        mutate(task.parameters.options, task.parameters)
        job = TrainJob(task, model, ds or dataset, mesh8, registry=reg)
        with pytest.raises(KubeMLException, match=match) as ei:
            job.train()
        assert ei.value.status_code == 400

    # family without a pipelineable trunk
    expect_400(lambda o, r: setattr(o, "n_stage", 2),
               match="does not support pipeline")
    # PP + SP rejected up front
    def pp_sp(o, r):
        o.n_stage = 2
        o.n_seq = 2
    expect_400(pp_sp, model=TinyGPT(), match="composes with")
    # microbatches must divide the batch
    def bad_mb(o, r):
        o.n_stage = 2
        o.pp_microbatches = 3
    expect_400(bad_mb, model=TinyGPT(), match="microbatches")
    # layers must split over the stage axis (TinyGPT has 2 layers)
    def bad_layers(o, r):
        o.n_stage = 4
    expect_400(bad_layers, model=TinyGPT(), match="layers")
    # syncdp cannot host the manual pipeline round
    def pp_sync(o, r):
        o.n_stage = 2
        o.engine = "syncdp"
    expect_400(pp_sync, model=TinyGPT(), match="kavg")


def test_job_rounds_per_dispatch_matches_ungrouped(setup):
    """--rounds-per-dispatch R trains IDENTICALLY to per-round dispatch
    (merges preserved between rounds; tail rounds dispatch singly) —
    the option exists to amortize submission overhead, never to change
    math."""
    reg, store, model, mesh = setup

    def run(job_id, rpd):
        task = make_task(job_id=job_id, epochs=2, parallelism=3, k=2,
                         batch=32)
        task.parameters.options.rounds_per_dispatch = rpd
        m = get_builtin("mlp")(hidden=16, num_classes=4)
        job = TrainJob(task, m, ToyDataset(), mesh, registry=reg)
        return job.train()

    # parallelism 3 on 800 samples / b32 / k2: several rounds per epoch
    # with a non-multiple tail for the grouped arm
    plain = run("rpd1", 1)
    grouped = run("rpd2", 3)
    np.testing.assert_allclose(grouped.data.train_loss,
                               plain.data.train_loss, rtol=1e-5,
                               atol=1e-6)
    np.testing.assert_allclose(grouped.data.accuracy, plain.data.accuracy,
                               rtol=1e-5, atol=1e-5)


def test_job_fsdp_matches_replicated_syncdp(setup):
    """--fsdp (ZeRO-3) at the job surface: parameters + optimizer state
    shard over the data axis inside the syncdp engine, and the history
    MATCHES the replicated-parameter syncdp job — FSDP is a layout, not
    a math change. kavg + fsdp rejects as 400 (weight-average semantics
    preclude parameter sharding)."""
    reg, store, model, mesh = setup

    def run(job_id, fsdp):
        task = make_task(job_id=job_id, epochs=2, engine="syncdp",
                         lr=0.05)
        task.parameters.options.fsdp = fsdp
        m = get_builtin("mlp")(hidden=16, num_classes=4)
        job = TrainJob(task, m, ToyDataset(), mesh, registry=reg)
        return job, job.train()

    job, rec = run("fsdpjob1", True)
    # the params really live sharded: dim-0-divisible leaves carry a
    # data-axis sharding in the engine state
    import jax as _jax
    from jax.sharding import PartitionSpec as _P
    sharded = [
        l for l in _jax.tree_util.tree_leaves(
            job._sync_state["params"])
        if hasattr(l, "sharding")
        and l.sharding.spec == _P("data")]
    assert sharded, "no parameter leaf is data-sharded under fsdp"
    _, rec0 = run("fsdpjob0", False)
    np.testing.assert_allclose(rec.data.train_loss, rec0.data.train_loss,
                               rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(rec.data.accuracy, rec0.data.accuracy,
                               rtol=1e-5, atol=1e-5)

    bad = make_task(job_id="fsdpbad1", epochs=1)  # kavg engine
    bad.parameters.options.fsdp = True
    with pytest.raises(KubeMLException, match="syncdp") as ei:
        TrainJob(bad, get_builtin("mlp")(hidden=16, num_classes=4),
                 ToyDataset(), mesh, registry=reg).train()
    assert ei.value.status_code == 400


def test_job_pipeline_parallel_bert_matches_dense(tmp_home):
    """--pipeline-parallel on the BERT family (round 5 extension): the
    encoder trunk pipelines through the job and the history matches the
    unpipelined job on an equal-lane mesh."""
    from kubeml_tpu.models.bert import BertModule, BertTiny
    from kubeml_tpu.parallel.mesh import STAGE_AXIS, make_mesh

    class TinyBert(BertTiny):
        num_classes = 2

        def build(self):
            return BertModule(vocab_size=1000, max_len=16, hidden=32,
                              layers=2, heads=2, ffn=64, dropout=0.0,
                              num_classes=2)

    def run(n_stage, job_id):
        reg = DatasetRegistry()
        if "toktask" not in [d.name for d in reg.list()]:
            make_token_task(reg)
        task = make_task(job_id=job_id, epochs=2, parallelism=2, k=1,
                         batch=8, lr=1e-3)
        task.parameters.model_type = "bert-tiny"
        task.parameters.dataset = "toktask"
        task.parameters.options.n_stage = n_stage
        mesh = make_mesh(n_data=4, n_stage=n_stage)
        job = TrainJob(task, TinyBert(), TokenDataset(), mesh,
                       registry=reg)
        return job, job.train()

    pp_job, pp_rec = run(2, "bertpp1")
    assert pp_job.mesh.shape[STAGE_AXIS] == 2
    _, dense_rec = run(1, "bertpp2")
    np.testing.assert_allclose(pp_rec.data.train_loss,
                               dense_rec.data.train_loss,
                               rtol=2e-3, atol=2e-3)
    np.testing.assert_allclose(pp_rec.data.accuracy,
                               dense_rec.data.accuracy,
                               rtol=2e-2, atol=0.5)


def test_enable_compile_cache_repoints_per_home(monkeypatch, tmp_path):
    """enable_compile_cache follows $KUBEML_TPU_HOME (test isolation:
    each home gets its own cache dir, not first-caller-wins) and
    honors the KUBEML_COMPILE_CACHE=0 opt-out."""
    from kubeml_tpu.utils import env as env_mod

    monkeypatch.setenv("KUBEML_TPU_HOME", str(tmp_path / "h1"))
    monkeypatch.delenv("KUBEML_COMPILE_CACHE", raising=False)
    assert env_mod.enable_compile_cache() is True
    assert jax.config.jax_compilation_cache_dir == \
        str(tmp_path / "h1" / "compile_cache")
    monkeypatch.setenv("KUBEML_TPU_HOME", str(tmp_path / "h2"))
    assert env_mod.enable_compile_cache() is True
    assert jax.config.jax_compilation_cache_dir == \
        str(tmp_path / "h2" / "compile_cache")
    monkeypatch.setenv("KUBEML_COMPILE_CACHE", "0")
    assert env_mod.enable_compile_cache() is False
