"""Test bootstrap: force an 8-device virtual CPU mesh.

The container's sitecustomize registers a TPU backend and eagerly
initializes JAX at interpreter start — before conftest runs — so plain env
vars are too late. Instead we clear the initialized backends and retarget
JAX at 8 virtual CPU devices, which is the supported path for testing
multi-chip sharding without hardware.
"""

import os
import sys

# repo root on sys.path so `import kubeml_tpu` works without installation
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from kubeml_tpu.testing import ensure_virtual_cpu_devices  # noqa: E402

ensure_virtual_cpu_devices(8)

# Cost-ledger XLA capture OFF by default in the suite: the extra AOT
# lower+compile per program per engine instance adds ~50% wall time to
# the engine-heavy files (measured on test_serving.py) and would blow
# the tier-1 budget. The capture path itself stays covered by
# tests/test_cost_ledger.py, which opts back in explicitly
# (CostLedger(capture_enabled=True) in the canonical budget inventory,
# KUBEML_COST_LEDGER=1 in its subprocess/engine tests). Everything
# else the ledger does — analytic records, dispatch attribution,
# snapshots, reconciliation of closed forms — is env-independent and
# still exercised by every engine test.
os.environ.setdefault("KUBEML_COST_LEDGER", "0")

import pytest  # noqa: E402

# ---------------------------------------------------------------- test tiers
#
# Smoke tier (`pytest -m "not slow"`) must stay under ~3 minutes so it is
# usable as the inner-loop check; the full tier runs everything (CI).
# Slowness is a measured property, not a design one, so it is maintained
# HERE as a list of node ids (measured with --durations=0 on the 8-device
# CPU mesh) instead of decorators scattered across files. Every subsystem
# keeps at least one fast test in the smoke tier.
SLOW_TESTS = {
    # model learning / convergence (tens of seconds each)
    "test_models_text.py::test_text_model_learns[lstm-0.01]",
    "test_models_text.py::test_text_model_learns[bert-tiny-0.001]",
    "test_models_vision.py::test_resnet18_engine_round",
    "test_models_vision.py::test_forward_shapes[resnet18-32]",
    "test_models_vision.py::test_forward_shapes[resnet50-64]",
    "test_models_vision.py::test_forward_shapes[resnet32-32]",
    "test_models_vision.py::test_forward_shapes[vgg11-32]",
    "test_models_lenet.py::test_lenet_learns",
    "test_models_gpt.py::test_gpt_learns",
    "test_models_gpt.py::test_gpt_moe_learns",
    "test_models_gpt.py::test_gpt_forward_shapes",
    "test_models_gpt.py::test_gpt_cached_generate_matches_infer",
    "test_models_gpt.py::test_gpt_cached_generate_sampling_and_clip",
    "test_models_gpt.py::test_gpt_seq_parallel_ring_matches_dense",
    "test_models_gpt.py::test_gpt_seq_parallel_ulysses_matches_dense",
    "test_models_gpt.py::test_gpt_moe_loss_includes_aux",
    "test_models_gpt.py::test_gpt_causality",
    "test_models_text.py::test_forward_shapes[bert-tiny-2]",
    "test_models_text.py::test_forward_shapes[lstm-4]",
    "test_models_text.py::test_bert_seq_parallel_matches_dense",
    "test_parallel_tp_sp.py::test_gpt_tp_forward_matches_replicated",
    "test_models_gpt.py::test_gpt_moe_ep_sharded_matches_unsharded",
    "test_models_gpt.py::test_gpt_moe_seq_parallel_matches_dense",
    "test_models_gpt.py::test_gpt_moe_seq_parallel_default_capacity_runs",
    "test_models_gpt.py::test_gpt_moe_trains_seq_parallel",
    "test_models_gpt.py::test_gpt_pipelined_matches_dense",
    "test_models_text.py::test_bert_max_len_guard",
    # experiment harness grids
    "test_experiments.py::test_baseline_text_grids_run[bert]",
    "test_experiments.py::test_baseline_text_grids_run[lstm]",
    "test_experiments.py::test_bench_text_engine_arm_runs",
    "test_experiments.py::test_bench_text_generate_arm_runs",
    "test_experiments.py::test_single_node_baseline_arm",
    # examples (full end-to-end function runs)
    "test_examples.py::test_gpt_example_trains_end_to_end",
    "test_examples.py::test_lenet_example_trains_end_to_end",
    "test_examples.py::test_two_jobs_run_concurrently",
    # parallelism equivalence / convergence
    "test_parallel_tp_sp.py::test_kavg_trains_tp_sharded_variables",
    "test_parallel_tp_sp.py::test_kavg_trains_tp_sharded_gpt",
    "test_parallel_tp_sp.py::test_kavg_trains_seq_parallel_bert_ring",
    "test_parallel_tp_sp.py::test_kavg_trains_seq_parallel_gpt_ring",
    "test_parallel_tp_sp.py::test_kavg_trains_seq_parallel_gpt_ulysses",
    "test_job.py::test_job_tensor_parallel_bert",
    "test_job.py::test_job_seq_parallel_gpt",
    "test_control_plane.py::test_tensor_parallel_job_through_controller",
    "test_parallel_tp_sp.py::test_ring_attention_grads_match",
    "test_parallel_tp_sp.py::test_ulysses_grads_match",
    "test_parallel_tp_sp.py::test_ring_attention_matches_full",
    "test_parallel_tp_sp.py::test_bert_tp_forward_matches_replicated",
    "test_parallel_pp_ep.py::test_moe_training_converges",
    "test_parallel_pp_ep.py::test_moe_sharded_matches_unsharded",
    "test_parallel_pp_ep.py::test_moe_matches_per_token_reference",
    "test_parallel_pp_ep.py::test_pipeline_grads_match",
    "test_parallel_pp_ep.py::test_moe_grads_finite",
    "test_parallel_pp_ep.py::test_pipeline_training_converges",
    "test_parallel_pp_ep.py::test_pipeline_aux_matches_sequential",
    "test_parallel_pp_ep.py::test_moe_trunk_pipelines",
    "test_parallel_pp_ep.py::test_moe_trunk_pipelines_expert_sharded",
    "test_parallel_pp_ep.py::test_moe_pipeline_rejects_indivisible_experts",
    # manual TP (round 3): every engine/grad/forward-parity test compiles
    # multi-axis shard_map programs (tens of seconds each on the CPU
    # mesh); the init-shapes check stays as the smoke-tier representative
    "test_manual_tp.py::"
    "test_bert_manual_tp_forward_matches_dense[float32-1e-05-1e-05]",
    "test_manual_tp.py::"
    "test_bert_manual_tp_forward_matches_dense[bfloat16-0.05-0.02]",
    "test_manual_tp.py::"
    "test_gpt_manual_tp_forward_matches_dense[float32-1e-05-1e-05]",
    "test_manual_tp.py::"
    "test_gpt_manual_tp_forward_matches_dense[bfloat16-0.05-0.02]",
    "test_manual_tp.py::test_manual_tp_grads_match_dense",
    "test_manual_tp.py::test_kavg_trains_manual_tp_bert",
    "test_manual_tp.py::test_kavg_trains_tp_sp_combined",
    "test_manual_tp.py::test_kavg_trains_tp_sp_combined_gpt",
    "test_manual_tp.py::test_kavg_manual_tp_compressed_merge",
    "test_manual_tp.py::test_kavg_sp_compressed_merge",
    "test_manual_tp.py::test_manual_tp_rejects_indivisible_heads",
    "test_manual_tp.py::test_manual_tp_init_matches_dense_shapes",
    "test_job.py::test_job_tensor_and_seq_parallel_combined",
    # round-3 re-tier (smoke measured 375s vs the <180s contract after
    # the new suites landed; durations re-measured on this machine) —
    # every file below keeps at least one fast test in the smoke tier
    "test_models_gpt.py::test_gpt_generate",
    "test_models_gpt.py::test_gpt_moe_registered_and_shapes",
    "test_models_gpt.py::test_gpt_generate_interior_and_all_pad",
    "test_models_gpt.py::test_gpt_infer_empty_prompt",
    "test_models_gpt.py::test_gpt_pipelined_guards",
    "test_parallel_tp_sp.py::test_sp_loss_handles_padding_across_shards",
    "test_parallel_tp_sp.py::test_ring_attention_causal",
    "test_parallel_tp_sp.py::test_ring_attention_causal_with_padding",
    "test_parallel_tp_sp.py::test_ulysses_causal_with_padding",
    "test_control_plane.py::test_end_to_end_train_infer",
    "test_control_plane.py::test_task_stop_via_controller",
    "test_control_plane.py::test_infer_cache_invalidates_on_new_checkpoint",
    "test_experiments.py::test_grid_sweep_live",
    "test_job.py::test_max_parallelism_caps_scheduler_growth",
    "test_job.py::test_job_shuffle_option",
    "test_job.py::test_dynamic_parallelism_callback",
    "test_job.py::test_warm_start_function_mismatch_rejected",
    "test_pallas_flash.py::test_ring_flash_matches_full",
    "test_pallas_flash.py::test_flash_grads_all_pad_row_match_reference",
    "test_pallas_flash.py::"
    "test_ring_flash_causal_noncontiguous_layout_poisons",
    "test_models_text.py::test_bert_seq_parallel_ulysses_matches_dense",
    "test_parallel_pp_ep.py::test_pipeline_matches_sequential",
    "test_syncdp.py::test_syncdp_matches_single_stream[True]",
    "test_syncdp.py::test_fsdp_matches_single_stream",
    "test_models_text.py::test_bert_padding_invariance",
    "test_models_gpt.py::test_gpt_infer_rejects_overlong_prompt",
    # distributed / deployment / control-plane long paths
    "test_distributed.py::test_kavg_round_over_multislice_mesh",
    "test_distributed_multiprocess.py::"
    "test_two_process_cluster_runs_kavg_round",
    "test_distributed_multiprocess.py::"
    "test_two_process_result_matches_single_process",
    "test_distributed_multiprocess.py::"
    "test_checkpoint_written_by_coordinator",
    "test_distributed_multiprocess.py::"
    "test_full_job_runs_across_two_processes",
    "test_distributed_multiprocess.py::"
    "test_full_job_matches_single_process",
    "test_role_deployment.py::test_split_role_processes_train",
    "test_distributed_multiprocess.py::"
    "test_job_survives_rank_death_via_supervisor_restart",
    "test_standalone_jobs.py::test_standalone_stop",
    "test_standalone_jobs.py::test_standalone_train_updates_and_infer",
    "test_standalone_jobs.py::test_dual_standalone_jobs_with_partitions",
    "test_standalone_jobs.py::test_crashed_job_process_releases_partition",
    "test_standalone_jobs.py::test_crashed_job_restarts_from_checkpoint",
    "test_standalone_jobs.py::test_restart_budget_exhausted_fails_job",
    "test_standalone_jobs.py::"
    "test_two_crashes_two_restarts_continuous_history",
    "test_standalone_jobs.py::"
    "test_sigterm_preemption_reschedules_without_budget",
    # elastic degraded mode: the per-round sweep runs 7 crash+resume job
    # pairs; the single-point preempt/resume tests stay in the smoke
    # tier as the fast representatives
    "test_elastic.py::test_crash_at_every_round_resumes_bit_identical",
    # donation-aliasing regression needs a larger slab and 4 repeat
    # trials (the corruption is allocator-timing dependent)
    "test_elastic.py::test_resume_survives_buffer_donation",
    "test_pallas_flash.py::"
    "test_ulysses_flash_training_round_matches_reference",
    "test_control_plane.py::test_dynamic_parallelism_through_scheduler",
    "test_control_plane.py::test_metrics_exposition_and_clearing",
    "test_control_plane.py::test_mid_job_inference",
    "test_cli.py::test_cli_full_flow",
    "test_job.py::test_checkpoint_every_and_warm_start",
    "test_job.py::test_job_seq_and_expert_parallel_moe",
    # round-5 job-level parity arms (70-160 s each: two full jobs per
    # test); the PP/EP surface keeps fast smoke representatives in
    # test_job_pipeline_parallel_misconfigs (~0 s: 400s fire before any
    # compile) + the elastic/fsdp/rounds-per-dispatch tests (5-8 s)
    "test_job.py::test_job_pipeline_parallel_matches_dense",
    "test_job.py::test_job_pipeline_parallel_with_experts",
    "test_job.py::test_job_pipeline_parallel_bert_matches_dense",
    "test_job.py::test_job_dp_ep_gspmd_matches_replicated",
    "test_parallel_pp_ep.py::test_kavg_sp_ep_round_matches_sp_only",
    "test_parallel_pp_ep.py::test_ep_alltoall_ffn_matches_dense",
    "test_parallel_pp_ep.py::test_moe_pipeline_alltoall_matches_replicated",
    "test_pallas_flash.py::test_flash_grads_match_reference",
    "test_pallas_flash.py::"
    "test_ring_flash_grads_match_dense_ring_causal_ragged",
    "test_pallas_flash.py::test_ring_flash_training_round_matches_dense",
    "test_pallas_flash.py::test_ring_flash_causal",
    "test_pallas_flash.py::test_ring_flash_causal_with_padding",
}


# Nightly tier (round 4): the full tier was outgrowing CI's 45-minute
# cap (~39 min measured). These are the heaviest tests whose coverage
# is REPRESENTED by a faster sibling that stays in the CI tier — each
# entry names its stand-in. CI runs `-m "not nightly"`; the nightly
# workflow (and any local `pytest tests/`... with `-m ""`) runs all.
# Nightly tests are also slow-marked, so the smoke tier is unaffected.
NIGHTLY_TESTS = {
    # job-level TP+SP / SP carving: stood in for by
    # test_job_seq_and_expert_parallel_moe (seq+expert carving, same
    # code path) + the engine-level combined tests in test_manual_tp
    "test_job.py::test_job_tensor_and_seq_parallel_combined",
    "test_job.py::test_job_seq_parallel_gpt",
    # vision engine convergence: bench.py measures the same round on
    # hardware every round; test_lenet_learns keeps a convergence run
    "test_models_vision.py::test_resnet18_engine_round",
    # resnet50 forward shape: resnet18/32/vgg11 shape tests remain
    "test_models_vision.py::test_forward_shapes[resnet50-64]",
    # flash-ring grads: the causal+ragged superset case and the full
    # training-round parity stay in the CI tier
    "test_pallas_flash.py::test_ring_flash_grads_match_dense_ring",
    "test_pallas_flash.py::test_ring_flash_grads_match_dense_ring_causal",
    # function-registry end-to-end: the lenet example test keeps the
    # registry path; GPT training is covered by test_gpt_learns
    "test_examples.py::test_gpt_example_trains_end_to_end",
    # TP through the full control plane: control-plane train covered by
    # test_end_to_end_train_infer, TP job by test_job_tensor_parallel_bert
    "test_control_plane.py::test_tensor_parallel_job_through_controller",
    # text sweep harness: the lstm grid arm stays
    "test_experiments.py::test_baseline_text_grids_run[bert]",
    # manual-TP suite: grads-match + bert training + tp_sp_combined
    # (bert) remain; the gpt combined variant and the TP compressed
    # merge (sp compressed merge remains) move out
    "test_manual_tp.py::test_kavg_trains_tp_sp_combined_gpt",
    "test_manual_tp.py::test_kavg_manual_tp_compressed_merge",
    # SP x MoE training: the replicated-expert SP round runs as the
    # reference arm INSIDE test_kavg_sp_ep_round_matches_sp_only
    "test_models_gpt.py::test_gpt_moe_trains_seq_parallel",
    # chained two-crash supervised recovery: the one-crash supervised
    # test (test_job_survives_rank_death_via_supervisor_restart) keeps
    # the crash->supervisor-restart->resume path in the CI tier
    "test_distributed_multiprocess.py::"
    "test_two_crashes_two_supervised_restarts",
}


def pytest_collection_modifyitems(config, items):
    matched = set()
    for item in items:
        # node id relative to tests/: "<file>::<name>[<param>]"
        nodeid = item.nodeid.split("/")[-1]
        if nodeid in SLOW_TESTS or nodeid in NIGHTLY_TESTS:
            matched.add(nodeid)
            item.add_marker(pytest.mark.slow)
        if nodeid in NIGHTLY_TESTS:
            item.add_marker(pytest.mark.nightly)
    # a stale entry (renamed/removed test) would silently put a slow
    # test back into the smoke tier — make it a collection error instead.
    # Only enforced on whole-file collection (no ::nodeid selection, no
    # -k narrowing); partial selections legitimately match a subset.
    if config.option.keyword or any("::" in a for a in config.args):
        return
    collected_files = {item.nodeid.split("/")[-1].split("::")[0]
                       for item in items}
    stale = {t for t in (SLOW_TESTS | NIGHTLY_TESTS) - matched
             if t.split("::")[0] in collected_files}
    if stale:
        raise pytest.UsageError(
            f"SLOW_TESTS/NIGHTLY_TESTS entries match no collected test: "
            f"{sorted(stale)}")


@pytest.fixture(scope="session")
def mesh8():
    from kubeml_tpu.parallel.mesh import make_mesh
    return make_mesh(n_data=8)


@pytest.fixture(scope="session")
def mesh4x2():
    from kubeml_tpu.parallel.mesh import make_mesh
    return make_mesh(n_data=4, n_model=2)


@pytest.fixture()
def tmp_home(tmp_path, monkeypatch):
    """Isolated KUBEML_TPU_HOME per test."""
    monkeypatch.setenv("KUBEML_TPU_HOME", str(tmp_path / "kubeml_home"))
    return tmp_path / "kubeml_home"
