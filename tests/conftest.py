"""Test bootstrap: force an 8-device virtual CPU mesh.

The container's sitecustomize registers a TPU backend and eagerly
initializes JAX at interpreter start — before conftest runs — so plain env
vars are too late. Instead we clear the initialized backends and retarget
JAX at 8 virtual CPU devices, which is the supported path for testing
multi-chip sharding without hardware.
"""

import os
import sys

# repo root on sys.path so `import kubeml_tpu` works without installation
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from kubeml_tpu.testing import ensure_virtual_cpu_devices  # noqa: E402

ensure_virtual_cpu_devices(8)

import pytest  # noqa: E402


@pytest.fixture(scope="session")
def mesh8():
    from kubeml_tpu.parallel.mesh import make_mesh
    return make_mesh(n_data=8)


@pytest.fixture(scope="session")
def mesh4x2():
    from kubeml_tpu.parallel.mesh import make_mesh
    return make_mesh(n_data=4, n_model=2)


@pytest.fixture()
def tmp_home(tmp_path, monkeypatch):
    """Isolated KUBEML_TPU_HOME per test."""
    monkeypatch.setenv("KUBEML_TPU_HOME", str(tmp_path / "kubeml_home"))
    return tmp_path / "kubeml_home"
