"""Pipeline (stage axis) and expert (MoE) parallelism tests.

Runs on the 8-virtual-CPU-device mesh from conftest. Correctness is
checked against unpipelined / per-token dense references.
"""

import jax
from kubeml_tpu import compat
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from kubeml_tpu.parallel.ep import init_moe_params, make_dispatch, moe_apply
from kubeml_tpu.parallel.mesh import make_mesh
from kubeml_tpu.parallel.pp import (pipeline_apply, sequential_apply,
                                    stack_stage_params)


def _dense_stage(params, x):
    return jnp.tanh(x @ params["w"] + params["b"])


def _make_stages(rng, n, f):
    ps = []
    for i in range(n):
        kw, rng = jax.random.split(rng)
        ps.append({"w": jax.random.normal(kw, (f, f)) / np.sqrt(f),
                   "b": jnp.full((f,), 0.01 * i)})
    return stack_stage_params(ps)


@pytest.fixture(scope="module")
def pp_mesh():
    return make_mesh(n_data=2, n_stage=4)


def test_pipeline_matches_sequential(pp_mesh):
    rng = jax.random.PRNGKey(0)
    stages = _make_stages(rng, 4, 8)
    x = jax.random.normal(jax.random.PRNGKey(1), (6, 3, 8))  # M=6 microbatches
    got = pipeline_apply(_dense_stage, stages, x, pp_mesh)
    want = sequential_apply(_dense_stage, stages, x)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-5)


def test_pipeline_grads_match(pp_mesh):
    stages = _make_stages(jax.random.PRNGKey(2), 4, 8)
    x = jax.random.normal(jax.random.PRNGKey(3), (5, 2, 8))
    tgt = jax.random.normal(jax.random.PRNGKey(4), (5, 2, 8))

    def loss_pp(p):
        return jnp.mean((pipeline_apply(_dense_stage, p, x, pp_mesh) - tgt) ** 2)

    def loss_seq(p):
        return jnp.mean((sequential_apply(_dense_stage, p, x) - tgt) ** 2)

    g_pp = jax.grad(loss_pp)(stages)
    g_seq = jax.grad(loss_seq)(stages)
    for a, b in zip(jax.tree_util.tree_leaves(g_pp),
                    jax.tree_util.tree_leaves(g_seq)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-5)


def test_pipeline_jits_under_mesh(pp_mesh):
    stages = _make_stages(jax.random.PRNGKey(5), 4, 8)
    x = jax.random.normal(jax.random.PRNGKey(6), (8, 2, 8))
    f = jax.jit(lambda p, x: pipeline_apply(_dense_stage, p, x, pp_mesh))
    got = f(stages, x)
    want = sequential_apply(_dense_stage, stages, x)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-5)


# ------------------------------------------------------------------ MoE / EP

def test_dispatch_top1_routes_to_argmax():
    logits = jnp.array([[2.0, 0.0, -1.0],
                        [0.0, 3.0, 0.0],
                        [0.0, 0.1, 4.0],
                        [5.0, 0.0, 0.0]])
    dispatch, combine, _ = make_dispatch(logits, capacity=2, k=1)
    probs = jax.nn.softmax(logits, axis=-1)
    for tok, exp in enumerate([0, 1, 2, 0]):
        assert float(dispatch[tok, exp].sum()) == 1.0
        np.testing.assert_allclose(float(combine[tok, exp].sum()),
                                   float(probs[tok, exp]), rtol=1e-6)
    # each token routed exactly once
    np.testing.assert_allclose(np.asarray(dispatch.sum(axis=(1, 2))),
                               np.ones(4))


def test_dispatch_capacity_drops_overflow():
    # all four tokens prefer expert 0; capacity 2 keeps the first two
    logits = jnp.tile(jnp.array([[5.0, 0.0]]), (4, 1))
    dispatch, _, _ = make_dispatch(logits, capacity=2, k=1)
    kept = np.asarray(dispatch[:, 0].sum(axis=-1))
    np.testing.assert_allclose(kept, [1, 1, 0, 0])


def test_dispatch_top2_uses_distinct_experts():
    logits = jnp.array([[1.0, 0.5, -2.0]] * 3)
    dispatch, _, _ = make_dispatch(logits, capacity=4, k=2)
    per_tok = np.asarray(dispatch.sum(axis=2))  # [T, E]
    np.testing.assert_allclose(per_tok[:, 0], 1)
    np.testing.assert_allclose(per_tok[:, 1], 1)
    np.testing.assert_allclose(per_tok[:, 2], 0)


def test_moe_matches_per_token_reference():
    d, ff, e, t = 6, 12, 4, 16
    params = init_moe_params(jax.random.PRNGKey(0), d, ff, e)
    x = jax.random.normal(jax.random.PRNGKey(1), (t, d))
    # huge capacity => nothing dropped => exact per-token semantics
    y, _ = moe_apply(params, x, mesh=None, k=1, capacity_factor=float(e))

    probs = jax.nn.softmax(x @ params["router"], axis=-1)
    choice = jnp.argmax(probs, axis=-1)
    want = []
    for i in range(t):
        ei = int(choice[i])
        h = jax.nn.gelu(x[i] @ params["wi"][ei] + params["bi"][ei])
        want.append((h @ params["wo"][ei] + params["bo"][ei]) *
                    probs[i, ei])
    np.testing.assert_allclose(np.asarray(y), np.asarray(jnp.stack(want)),
                               rtol=1e-4, atol=1e-5)


def test_moe_sharded_matches_unsharded():
    mesh = make_mesh(n_data=2, n_expert=4)
    d, ff, e, t = 8, 16, 4, 32
    params = init_moe_params(jax.random.PRNGKey(2), d, ff, e)
    x = jax.random.normal(jax.random.PRNGKey(3), (t, d))

    y_plain, aux_plain = moe_apply(params, x, mesh=None, k=2)
    f = jax.jit(lambda p, x: moe_apply(p, x, mesh=mesh, k=2))
    y_shard, aux_shard = f(params, x)
    np.testing.assert_allclose(np.asarray(y_shard), np.asarray(y_plain),
                               rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(float(aux_shard), float(aux_plain), rtol=1e-5)


def test_moe_grads_finite():
    d, ff, e, t = 6, 12, 4, 16
    params = init_moe_params(jax.random.PRNGKey(4), d, ff, e)
    x = jax.random.normal(jax.random.PRNGKey(5), (t, d))

    def loss(p):
        y, aux = moe_apply(p, x, k=2)
        return jnp.mean(y ** 2) + 0.01 * aux

    g = jax.grad(loss)(params)
    for leaf in jax.tree_util.tree_leaves(g):
        assert np.isfinite(np.asarray(leaf)).all()


def test_pipeline_training_converges():
    """GPipe is trainable end-to-end: grads through the ppermute ring
    train a stacked-stage trunk to fit a fixed regression target."""
    mesh = make_mesh(n_data=1, n_stage=4)
    rng = np.random.RandomState(0)
    feat, P_, M, B = 8, 4, 8, 4
    stages = stack_stage_params([
        {"w": jnp.asarray(rng.randn(feat, feat) / np.sqrt(feat),
                          jnp.float32),
         "b": jnp.zeros((feat,), jnp.float32)} for _ in range(P_)])

    def stage_fn(p, a):
        return jnp.tanh(a @ p["w"] + p["b"])

    x = jnp.asarray(rng.randn(M, B, feat), jnp.float32)
    target = jnp.asarray(np.tanh(rng.randn(M, B, feat)), jnp.float32)

    def loss_fn(stages):
        y = pipeline_apply(stage_fn, stages, x, mesh)
        return jnp.mean((y - target) ** 2)

    tx = optax.adam(3e-2)
    opt = tx.init(stages)

    @jax.jit
    def step(stages, opt):
        loss, grads = jax.value_and_grad(loss_fn)(stages)
        updates, opt = tx.update(grads, opt, stages)
        return optax.apply_updates(stages, updates), opt, loss

    l0 = float(loss_fn(stages))
    for _ in range(60):
        stages, opt, loss = step(stages, opt)
    assert float(loss) < l0 * 0.5, (l0, float(loss))


def test_moe_training_converges():
    """The sharded MoE block is trainable: router + experts fit a
    classification toy under the aux load-balancing loss."""
    mesh = make_mesh(n_data=1, n_expert=4)
    rng = np.random.RandomState(0)
    T, D = 64, 8
    x = jnp.asarray(rng.randn(T, D), jnp.float32)
    head_w = jnp.asarray(rng.randn(D, 4) * 0.1, jnp.float32)
    y = jnp.asarray(rng.randint(0, 4, T))

    params = init_moe_params(jax.random.PRNGKey(0), d_model=D, d_ff=16,
                             n_experts=4)
    params = dict(params, head=head_w)

    def loss_fn(params):
        moe_p = {k: v for k, v in params.items() if k != "head"}
        h, aux = moe_apply(moe_p, x, mesh, k=2)
        logits = (x + h) @ params["head"]
        ce = optax.softmax_cross_entropy_with_integer_labels(logits, y)
        return ce.mean() + 0.01 * aux

    tx = optax.adam(1e-2)
    opt = tx.init(params)

    @jax.jit
    def step(params, opt):
        loss, grads = jax.value_and_grad(loss_fn)(params)
        updates, opt = tx.update(grads, opt, params)
        return optax.apply_updates(params, updates), opt, loss

    l0 = float(loss_fn(params))
    for _ in range(80):
        params, opt, loss = step(params, opt)
    assert float(loss) < l0 * 0.7, (l0, float(loss))


def test_pipeline_aux_matches_sequential():
    """has_aux: per-stage scalar outputs accumulate over REAL
    (stage, microbatch) pairs only — fill/drain garbage ticks masked —
    and equal the sequential reference exactly."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from kubeml_tpu.parallel.mesh import make_mesh
    from kubeml_tpu.parallel.pp import (pipeline_apply, sequential_apply,
                                        stack_stage_params)

    rng = np.random.RandomState(0)
    P_, M, B, F = 4, 6, 2, 8
    mesh = make_mesh(n_data=1, n_stage=P_)
    stages = stack_stage_params([
        {"w": jnp.asarray(rng.randn(F, F).astype(np.float32) / 3)}
        for _ in range(P_)])
    x = jnp.asarray(rng.randn(M, B, F).astype(np.float32))

    def stage_fn(p, a):
        out = jnp.tanh(a @ p["w"])
        return out, (out ** 2).sum()  # nonzero aux per real tick

    ys_ref, aux_ref = sequential_apply(stage_fn, stages, x, has_aux=True)
    ys, aux = pipeline_apply(stage_fn, stages, x, mesh, has_aux=True)
    np.testing.assert_allclose(np.asarray(ys), np.asarray(ys_ref),
                               rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(float(aux), float(aux_ref), rtol=1e-5)
    # grads flow through the aux path too
    g = jax.grad(lambda s: pipeline_apply(
        stage_fn, s, x, mesh, has_aux=True)[1])(stages)
    g_ref = jax.grad(lambda s: sequential_apply(
        stage_fn, s, x, has_aux=True)[1])(stages)
    np.testing.assert_allclose(np.asarray(g["w"]), np.asarray(g_ref["w"]),
                               rtol=1e-4, atol=1e-5)


def test_moe_trunk_pipelines():
    """Pipelined MoE (round 2, lifting the r1 restriction): the MoE
    decoder trunk rides the stage pipeline with per-microbatch routing
    capacity, equal to the per-microbatch sequential reference, with
    the load-balance aux accumulated."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from kubeml_tpu.parallel.mesh import make_mesh
    from tests.test_models_gpt import TinyMoE, make_lm_task

    model = TinyMoE()
    rng = np.random.RandomState(0)
    B, T, M = 8, 16, 4
    x = make_lm_task(rng, B)[:, :T]
    variables = model.init_variables(jax.random.PRNGKey(0),
                                     {"x": jnp.asarray(x)})
    mesh = make_mesh(n_data=4, n_stage=2)
    logits, aux = model.forward_pipelined(variables, jnp.asarray(x), mesh,
                                          microbatches=M)
    assert logits.shape == (B, T, 64)
    assert np.isfinite(np.asarray(logits)).all()
    assert float(aux) > 0.0  # load-balance loss accumulated

    # per-microbatch sequential reference: same capacity semantics by
    # construction -> near-exact parity (bf16 noise only)
    from kubeml_tpu.models.gpt import DecoderBlock
    module = model.module
    block = DecoderBlock(module.hidden, module.heads, module.ffn, 0.0,
                         module.dtype, n_experts=module.n_experts,
                         moe_k=module.moe_k,
                         capacity_factor=module.capacity_factor)
    params = variables["params"]
    emb = params["tok_embed"]["embedding"].astype(module.dtype)
    h = emb[jnp.asarray(x)] + params["pos_embed"]["embedding"][
        jnp.arange(T)].astype(module.dtype)[None]
    h = h.reshape(M, B // M, T, module.hidden)

    outs, aux_ref = [], 0.0
    for mb in range(M):
        a = h[mb]
        ones = jnp.ones(a.shape[:2], jnp.float32)
        for l in range(module.layers):
            a, st = block.apply({"params": params[f"layer_{l}"]}, a,
                                ones, False, mutable=["intermediates"])
            # match the pipeline's carry dtype (activations ride the
            # ring in the module compute dtype)
            a = a.astype(module.dtype)
            aux_ref += float(sum(jax.tree_util.tree_leaves(st)))
        outs.append(a)
    hr = jnp.stack(outs).reshape(B, T, module.hidden)
    import flax.linen as nn
    hr = nn.LayerNorm(dtype=jnp.float32).apply(
        {"params": params["LayerNorm_0"]}, hr)
    ref_logits = (hr.astype(module.dtype) @ emb.T).astype(jnp.float32)

    np.testing.assert_allclose(np.asarray(logits), np.asarray(ref_logits),
                               rtol=5e-2, atol=5e-2)
    np.testing.assert_allclose(float(aux),
                               aux_ref / (module.layers * M), rtol=1e-3)


def test_moe_trunk_pipelines_expert_sharded():
    """PP x EP (round 3, lifting the r2 restriction): the pipelined MoE
    trunk with experts sharded over the mesh expert axis (manual
    ep_partial_ffn psum inside the stage shard_map) equals the
    replicated-expert pipeline bit-for-bit up to bf16 psum noise."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from kubeml_tpu.parallel.mesh import make_mesh
    from tests.test_models_gpt import TinyMoE, make_lm_task

    model = TinyMoE()
    rng = np.random.RandomState(0)
    B, T, M = 8, 16, 4
    x = make_lm_task(rng, B)[:, :T]
    variables = model.init_variables(jax.random.PRNGKey(0),
                                     {"x": jnp.asarray(x)})

    rep_mesh = make_mesh(n_data=4, n_stage=2)
    ref_logits, ref_aux = model.forward_pipelined(
        variables, jnp.asarray(x), rep_mesh, microbatches=M)

    ep_model = TinyMoE()  # fresh instance: the pp cache keys on mesh
    ep_mesh = make_mesh(n_data=2, n_stage=2, n_expert=2)
    logits, aux = ep_model.forward_pipelined(
        variables, jnp.asarray(x), ep_mesh, microbatches=M)

    assert logits.shape == ref_logits.shape
    np.testing.assert_allclose(np.asarray(logits), np.asarray(ref_logits),
                               rtol=5e-2, atol=5e-2)
    np.testing.assert_allclose(float(aux), float(ref_aux), rtol=1e-3)


def test_ep_alltoall_ffn_matches_dense():
    """Token-sharded expert dispatch (VERDICT r3 item 7): inside a
    4-way manual expert axis, ep_alltoall_ffn — local routing, two
    tiled all_to_alls moving slot payloads to the experts and back —
    equals the dense full-expert math applied per token shard."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import PartitionSpec as P

    from kubeml_tpu.parallel.ep import route_tokens
    from kubeml_tpu.parallel.manual import ep_alltoall_ffn
    from kubeml_tpu.parallel.mesh import EXPERT_AXIS, make_mesh

    rng = np.random.RandomState(5)
    n, Tl, d, f, E = 4, 16, 8, 16, 8
    x = jnp.asarray(rng.randn(n * Tl, d).astype(np.float32))
    mask = np.ones(n * Tl, np.float32)
    mask[10:14] = 0.0  # pad tokens inside shard 0
    mask = jnp.asarray(mask)
    router = jnp.asarray(rng.randn(d, E).astype(np.float32) * 0.3)
    wi = jnp.asarray(rng.randn(E, d, f).astype(np.float32) * 0.2)
    bi = jnp.asarray(rng.randn(E, f).astype(np.float32) * 0.1)
    wo = jnp.asarray(rng.randn(E, f, d).astype(np.float32) * 0.2)
    bo = jnp.asarray(rng.randn(E, d).astype(np.float32) * 0.1)
    mesh = make_mesh(n_data=1, n_expert=n)

    def body(x_l, m_l, router, wi, bi, wo, bo):
        disp, comb, _ = route_tokens(router, x_l, k=2,
                                     capacity_factor=2.0, token_mask=m_l)
        return ep_alltoall_ffn(wi, bi, wo, bo, disp, comb, x_l,
                               EXPERT_AXIS, dtype=jnp.float32)

    y = jax.jit(compat.shard_map(
        body, mesh=mesh,
        in_specs=(P(EXPERT_AXIS), P(EXPERT_AXIS), P(), P(), P(), P(), P()),
        out_specs=P(EXPERT_AXIS), check_vma=False))(
        x, mask, router, wi, bi, wo, bo)

    # dense reference: the same local routing + FULL expert set, one
    # token shard at a time
    refs = []
    for i in range(n):
        x_l = x[i * Tl:(i + 1) * Tl]
        disp, comb, _ = route_tokens(router, x_l, k=2, capacity_factor=2.0,
                                     token_mask=mask[i * Tl:(i + 1) * Tl])
        ein = jnp.einsum("tec,td->ecd", disp, x_l)
        hh = jax.nn.gelu(jnp.einsum("ecd,edf->ecf", ein, wi)
                         + bi[:, None, :])
        out = jnp.einsum("ecf,efd->ecd", hh, wo) + bo[:, None, :]
        refs.append(jnp.einsum("tec,ecd->td", comb, out))
    ref = jnp.concatenate(refs, axis=0)
    np.testing.assert_allclose(np.asarray(y), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


def test_moe_pipeline_alltoall_matches_replicated():
    """Model-level: the pipelined expert-sharded MoE trunk with
    ep_impl='alltoall' (token-sharded dispatch) equals the replicated-
    token ep_partial_ffn path at overflow-free capacity — per-shard
    routing changes the slot GROUPING, not the combine, when nothing
    drops."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from kubeml_tpu.parallel.mesh import make_mesh
    from tests.test_models_gpt import TinyMoE, make_lm_task

    rng = np.random.RandomState(0)
    B, T, M = 8, 16, 4

    class RoomyMoE(TinyMoE):
        # capacity 4x: no expert overflows under either grouping, so
        # the two dispatch strategies must agree exactly
        def build(self):
            m = super().build()
            return m.clone(capacity_factor=4.0)

    x = make_lm_task(rng, B)[:, :T]
    model = RoomyMoE()
    variables = model.init_variables(jax.random.PRNGKey(0),
                                     {"x": jnp.asarray(x)})
    ep_mesh = make_mesh(n_data=2, n_stage=2, n_expert=2)
    ref_logits, _ = model.forward_pipelined(
        variables, jnp.asarray(x), ep_mesh, microbatches=M)

    # SAME model instance: the pp cache keys on the module config, so
    # the clone must compile a fresh program, not reuse the replicated
    # path's (regression guard for the cache-key fix)
    model._module = model.module.clone(ep_impl="alltoall")
    logits, _ = model.forward_pipelined(
        variables, jnp.asarray(x), ep_mesh, microbatches=M)
    np.testing.assert_allclose(np.asarray(logits), np.asarray(ref_logits),
                               rtol=5e-2, atol=5e-2)


def test_kavg_sp_ep_round_matches_sp_only():
    """One K-avg SP training round with experts ALSO sharded over a
    2-way expert axis (SP x EP — round 4's last matrix cell) produces
    the same merged variables as the SP-only round with replicated
    experts: routing runs on expert-replicated tokens, ep_partial_ffn's
    psum assembles the identical FFN output, and the vma backward psums
    each lane's partial expert-weight grads."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    from kubeml_tpu.parallel.kavg import KAvgEngine
    from kubeml_tpu.parallel.mesh import make_mesh
    from tests.test_models_gpt import VOCAB, TinyMoE

    rng = np.random.RandomState(4)
    W, S, B, T = 2, 2, 4, 32
    start = rng.randint(1, VOCAB - 1, size=(W * S * B, 1))
    x = ((start + np.arange(T)[None, :] - 1) % (VOCAB - 1) + 1) \
        .astype(np.int32).reshape(W, S, B, T)
    batch = {"x": jnp.asarray(x)}
    masks = dict(sample_mask=np.ones((W, S, B), np.float32),
                 step_mask=np.ones((W, S), np.float32),
                 worker_mask=np.ones(W, np.float32))
    rngs = rng.randint(0, 2**31, size=(W, S, 2)).astype(np.uint32)

    model0 = TinyMoE()
    variables = model0.init_variables(jax.random.PRNGKey(0),
                                      {"x": jnp.asarray(x[0, 0])})

    def run(mesh, enable_ep):
        model = TinyMoE()
        model._module = model.module.clone(dropout=0.0)
        model.enable_seq_parallel("ring")
        if enable_ep:
            model.enable_expert_parallel()
        eng = KAvgEngine(mesh, model.loss, model.metrics,
                         lambda lr, e: optax.sgd(lr), donate=False,
                         batch_seq_dims=model.seq_batch_dims)
        out, stats = eng.train_round(variables, batch, rngs=rngs,
                                     lr=1e-2, epoch=0, **masks)
        return out, float(np.asarray(stats.loss_sum).sum())

    ref, loss_ref = run(
        make_mesh(n_data=2, n_seq=2, devices=jax.devices()[:4]), False)
    ep, loss_ep = run(
        make_mesh(n_data=2, n_seq=2, n_expert=2), True)

    assert abs(loss_ref - loss_ep) < 1e-3 * max(1.0, abs(loss_ref))
    for a, b in zip(jax.tree_util.tree_leaves(ref),
                    jax.tree_util.tree_leaves(ep)):
        np.testing.assert_allclose(np.asarray(b), np.asarray(a),
                                   rtol=5e-3, atol=5e-4)


def test_moe_pipeline_rejects_indivisible_experts():
    import jax
    import jax.numpy as jnp
    import numpy as np
    import pytest

    from kubeml_tpu.parallel.mesh import make_mesh
    from kubeml_tpu.models.gpt import GPTModule, GPTMoEMini
    from tests.test_models_gpt import make_lm_task

    class ThreeExpertMoE(GPTMoEMini):
        def build(self):
            return GPTModule(vocab_size=64, max_len=32, hidden=32,
                             layers=2, heads=2, ffn=32, dropout=0.0,
                             n_experts=3)

    model = ThreeExpertMoE()
    rng = np.random.RandomState(0)
    x = make_lm_task(rng, 4)[:, :16]
    variables = model.init_variables(jax.random.PRNGKey(0),
                                     {"x": jnp.asarray(x)})
    mesh = make_mesh(n_data=2, n_stage=2, n_expert=2)
    with pytest.raises(ValueError, match="experts do not divide"):
        model.forward_pipelined(variables, jnp.asarray(x), mesh,
                                microbatches=2)
