"""Split-role deployment: each control-plane role in its own OS process
(`kubeml serve --role ...`), wired together by URLs — the reference's
one-binary-per-role Kubernetes layout (ml/cmd/ml/main.go:60-156), on
plain processes."""

import json
import os
import subprocess
import sys
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from kubeml_tpu.utils.env import find_free_port

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _wait_health(url, proc, timeout=90):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if proc.poll() is not None:
            raise AssertionError(
                f"role process died with {proc.returncode}")
        try:
            urllib.request.urlopen(url + "/health", timeout=2)
            return
        except (urllib.error.URLError, OSError):
            time.sleep(0.3)
    raise AssertionError(f"{url} never became healthy")


def test_split_role_processes_train(tmp_home, tmp_path):
    env = dict(os.environ)
    from kubeml_tpu.testing import virtual_cpu_env
    env.update({
        "KUBEML_TPU_HOME": os.environ["KUBEML_TPU_HOME"],
        "PYTHONPATH": REPO + os.pathsep + env.get("PYTHONPATH", ""),
        # force the virtual CPU backend in the children (the PS trains)
        **virtual_cpu_env(8),
    })
    ports = {r: find_free_port() for r in
             ("storage", "ps", "scheduler", "controller")}
    urls = {r: f"http://127.0.0.1:{p}" for r, p in ports.items()}

    def serve(role, *extra):
        return subprocess.Popen(
            [sys.executable, "-m", "kubeml_tpu.cli.main", "serve",
             "--role", role, "--port", str(ports[role]), *extra],
            env=env, cwd=REPO)

    roles = ("storage", "ps", "scheduler", "controller")
    procs = [serve("storage"),
             serve("ps", "--scheduler-url", urls["scheduler"]),
             serve("scheduler", "--ps-url", urls["ps"]),
             serve("controller", "--scheduler-url", urls["scheduler"],
                   "--ps-url", urls["ps"],
                   "--storage-url", urls["storage"])]
    try:
        for r, p in zip(roles, procs):
            _wait_health(urls[r], p)

        from kubeml_tpu.api.types import TrainOptions, TrainRequest
        from kubeml_tpu.control.client import KubemlClient
        from tests.test_control_plane import wait_history, write_blob_files

        client = KubemlClient(urls["controller"])
        paths = write_blob_files(tmp_path)
        client.v1().datasets().create("blobs", paths["xtr"], paths["ytr"],
                                      paths["xte"], paths["yte"])
        req = TrainRequest(model_type="mlp", batch_size=32, epochs=2,
                           dataset="blobs", lr=0.1,
                           options=TrainOptions(default_parallelism=2,
                                                static_parallelism=True,
                                                k=2))
        job_id = client.v1().networks().train(req)
        history = wait_history(client, job_id, timeout=240)
        assert len(history.data.train_loss) == 2

        # inference against the PS process's checkpoint, via the controller
        x = np.load(paths["xte"])[:3]
        preds = client.v1().networks().infer(job_id, x.tolist())
        assert len(preds) == 3
    finally:
        for p in procs:
            p.terminate()
        for p in procs:
            try:
                p.wait(10)
            except subprocess.TimeoutExpired:
                p.kill()
