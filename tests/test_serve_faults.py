"""Serving-plane fault-tolerance tests (ServeFaultPlan + recovery paths).

The contracts pinned here:

  * poisoned-stream isolation — a non-finite logit burst (on-device
    guard) or a step-poisoning request (service bisection) terminates
    ONLY the offending stream; every co-batched neighbour finishes
    bit-identical to a clean run, and the program inventory stays at
    exactly two compiles
  * per-request deadlines — deadline_ms validates at admission (400),
    sheds when infeasible against the backlog (429), reaps expired
    streams in the queue AND in slots, and every release restores the
    page free list exactly
  * supervised recovery — a dead or wedged serving loop is detected by
    the watchdog, the engine is rebuilt, and in-flight streams resume
    mid-generation with bit-identical continuations (per-(seed, pos)
    sampling keys); the recovered pager passes its invariant audit
  * graceful drain — admission flips to 503 + Retry-After, in-flight
    streams finish within the grace budget, stragglers force-release
    with an attributable error
  * every injection is coordinate-driven (tools/check_fault_tests.py
    lints this file, and its serve-kind coverage check rides along)
"""

import numpy as np
import pytest

pytestmark = [pytest.mark.serving, pytest.mark.faults]


def _nano():
    import jax

    from kubeml_tpu.models import get_builtin
    model = get_builtin("gpt-nano")()
    module = model.module
    variables = model.init_variables(
        jax.random.PRNGKey(0),
        {"x": np.ones((1, module.max_len), np.int32)})
    return model, module, variables


def _drive(engine, limit=10_000):
    finished = []
    while engine.active():
        finished.extend(engine.step())
        limit -= 1
        assert limit > 0, "engine failed to drain"
    return finished


def _clean_tokens(module, variables, specs):
    """Reference run: the same request specs on a fault-free engine."""
    from kubeml_tpu.serve.engine import DecodeEngine
    from kubeml_tpu.serve.slots import GenerateRequest

    engine = DecodeEngine(module, variables, slots=4, page=4)
    reqs = [GenerateRequest(list(p), max_new_tokens=n, temperature=t,
                            seed=s) for p, n, t, s in specs]
    for r in reqs:
        engine.attach(r)
    _drive(engine)
    assert all(r.outcome == "ok" for r in reqs)
    return [r.tokens for r in reqs]


SPECS = [([5, 6, 7], 6, 0.0, 0),
         ([9, 10, 11, 12], 6, 0.7, 1),
         ([3, 4], 6, 1.3, 7)]


# ------------------------------------------------------ poisoned streams

def test_nan_guard_isolates_poisoned_stream_bit_identically():
    """serve_nan_logits raises the poison lane for ONE slot: only that
    request errors, neighbours match a clean run token-for-token, and
    the isolation costs zero extra compiles."""
    from kubeml_tpu.faults import ServeFaultPlan
    from kubeml_tpu.serve.engine import DecodeEngine
    from kubeml_tpu.serve.slots import GenerateRequest

    _model, module, variables = _nano()
    clean = _clean_tokens(module, variables, SPECS)

    plan = ServeFaultPlan.parse([{"kind": "serve_nan_logits", "slot": 1}])
    engine = DecodeEngine(module, variables, slots=4, page=4,
                          fault_plan=plan)
    reqs = [GenerateRequest(list(p), max_new_tokens=n, temperature=t,
                            seed=s) for p, n, t, s in SPECS]
    for r in reqs:
        engine.attach(r)          # attach order == slot order
    _drive(engine)

    assert plan.injected["serve_nan_logits"] == 1
    assert reqs[1].outcome == "error"
    assert "poisoned and isolated" in reqs[1].error
    assert "non-finite logits" in reqs[1].error
    # blast radius is exactly one slot: survivors are bit-identical
    assert reqs[0].outcome == "ok" and reqs[2].outcome == "ok"
    assert reqs[0].tokens == clean[0]
    assert reqs[2].tokens == clean[2]
    # the guard is data in the decode program, not a third program
    assert engine.stats["compiles"] == 1
    assert engine.stats["prefill_compiles"] == 1
    assert engine.stats["poisoned"] == 1


def test_bisection_quarantines_step_poisoning_request():
    """serve_step_crash is rid-sticky: the service's bisection retries
    the failed step with suspect lanes masked, converges on the
    poisoning request, quarantines it, and every survivor finishes
    bit-identical — no engine restart, no recompile."""
    from kubeml_tpu.faults import ServeFaultPlan
    from kubeml_tpu.serve.engine import DecodeEngine
    from kubeml_tpu.serve.service import ServeService

    _model, module, variables = _nano()
    clean = _clean_tokens(module, variables, SPECS)

    plan = ServeFaultPlan.parse([{"kind": "serve_step_crash", "slot": 0}])
    engine = DecodeEngine(module, variables, slots=4, page=4,
                          fault_plan=plan)
    svc = ServeService("crash-m", engine, supervise=False).start()
    try:
        reqs = [svc.submit(list(p), max_new_tokens=n, temperature=t,
                           seed=s) for p, n, t, s in SPECS]
        for r in reqs:
            assert r.wait(120), "request never reached a terminal state"
    finally:
        svc.stop()

    assert plan.injected["serve_step_crash"] >= 1
    # the first submission binds slot 0 and is the quarantined poisoner
    assert reqs[0].outcome == "error"
    assert "serve_step_crash" in reqs[0].error
    assert "quarantined" in reqs[0].error
    assert reqs[1].outcome == "ok" and reqs[2].outcome == "ok"
    assert reqs[1].tokens == clean[1]
    assert reqs[2].tokens == clean[2]
    # isolation, not restart: same engine, same two compiled programs
    assert svc.engine is engine
    assert svc.restarts_total == 0
    assert svc.poisoned_total == 1
    assert engine.stats["compiles"] == 1
    assert engine.stats["prefill_compiles"] == 1


def test_crash_event_is_rid_sticky_not_slot_sticky():
    from kubeml_tpu.faults import ServeFaultPlan

    plan = ServeFaultPlan.parse(
        [{"kind": "serve_step_crash", "step": 5, "slot": 2}])
    plan.check_crash(4, [(2, "aaaa")])          # before its step: quiet
    with pytest.raises(RuntimeError) as ei:
        plan.check_crash(5, [(2, "aaaa"), (0, "bbbb")])
    assert "serve_step_crash" in str(ei.value)
    plan.check_crash(7, [(0, "bbbb")])          # bound rid masked: quiet
    with pytest.raises(RuntimeError):
        plan.check_crash(9, [(1, "aaaa")])      # follows the rid, not slot


def test_serve_fault_plan_parse_and_once_only_nan():
    from kubeml_tpu.faults import ServeFaultPlan

    plan = ServeFaultPlan.parse(
        '{"events": [{"kind": "serve_nan_logits", "step": 3, "slot": 1}]}')
    assert plan.has("serve_nan_logits")
    assert plan.nan_hits(2, [0, 1]) == set()    # wrong step
    assert plan.nan_hits(3, [0]) == set()       # target absent: unconsumed
    assert plan.nan_hits(3, [0, 1]) == {1}
    assert plan.nan_hits(3, [0, 1]) == set()    # once per event
    with pytest.raises(ValueError):
        ServeFaultPlan.parse([{"kind": "bogus"}])
    with pytest.raises(ValueError):
        ServeFaultPlan.parse({"events": 3})


# -------------------------------------------------------------- deadlines

def test_deadline_reaps_slot_and_restores_free_list():
    """An expired stream releases with the terminal `deadline` outcome,
    carries its partial tokens to the client, and gives every KV page
    back — the free list is exactly restored."""
    from kubeml_tpu.serve.engine import DecodeEngine
    from kubeml_tpu.serve.slots import GenerateRequest

    _model, module, variables = _nano()
    clk = {"t": 0.0}
    engine = DecodeEngine(module, variables, slots=2, page=8,
                          prefix_cache=False, clock=lambda: clk["t"])
    req = GenerateRequest([5, 6, 7], max_new_tokens=32, deadline_ms=50)
    req.deadline_at = 0.05
    assert engine.pager.in_use == 0
    engine.attach(req)
    engine.step()
    assert req.outcome is None and len(req.tokens) >= 1
    clk["t"] = 0.2
    finished = engine.step()
    assert finished == [req]
    assert req.outcome == "deadline"
    assert "deadline of 50ms exceeded" in req.error
    assert engine.stats["deadline_expired"] == 1
    assert engine.active() == 0
    assert engine.pager.in_use == 0          # free list exactly restored
    assert engine.pager.check_invariants() == []
    # the flight record for the reaping step counts it
    assert engine.flight.snapshot()[-1]["deadlines"] == 1
    # the closing event carries the partial tokens the client paid for
    evs = []
    while not req.events.empty():
        evs.append(req.events.get_nowait())
    assert evs[-1].get("deadline") is True
    assert evs[-1]["tokens"] == req.tokens and req.tokens


def test_deadline_validates_at_admission_and_sheds_infeasible():
    from kubeml_tpu.models.base import InferenceInputError
    from kubeml_tpu.serve.engine import DecodeEngine
    from kubeml_tpu.serve.service import ServeService
    from kubeml_tpu.serve.slots import ServeSaturated

    _model, module, variables = _nano()
    engine = DecodeEngine(module, variables, slots=2, page=8)
    svc = ServeService("dl-m", engine, supervise=False)  # loop not started

    for bad in (0, -5, float("nan"), float("inf"), "soon"):
        with pytest.raises(InferenceInputError):
            svc.submit([5, 6], max_new_tokens=2, deadline_ms=bad)

    # a generous deadline admits fine against an empty backlog...
    ok = svc.submit(list(range(2, 42)), max_new_tokens=4,
                    deadline_ms=10_000)
    assert ok.deadline_at is not None
    # ...but now ~39 queued prompt tokens (~0.15s at the drain rate)
    # make a 100ms deadline a guaranteed expiry: shed at the door
    with pytest.raises(ServeSaturated) as ei:
        svc.submit([5, 6], max_new_tokens=4, deadline_ms=100)
    assert "infeasible" in str(ei.value)
    assert ei.value.status_code == 429
    assert ei.value.retry_after_s > 1.0
    assert svc.rejected_total == 1


def test_queue_deadline_expires_before_slot_frees():
    """With one slot held by a (fault-slowed) stream, a queued request
    whose deadline lapses is reaped by the service sweep — it never
    waits on capacity it cannot get in time."""
    from kubeml_tpu.faults import ServeFaultPlan
    from kubeml_tpu.serve.engine import DecodeEngine
    from kubeml_tpu.serve.service import ServeService

    _model, module, variables = _nano()
    plan = ServeFaultPlan.parse(
        [{"kind": "serve_slow_step", "duration_s": 0.02}])
    engine = DecodeEngine(module, variables, slots=1, page=8,
                          fault_plan=plan)
    svc = ServeService("q-m", engine, supervise=False).start()
    try:
        a = svc.submit([5, 6, 7], max_new_tokens=6)
        b = svc.submit([9, 10], max_new_tokens=4, deadline_ms=30)
        assert b.wait(60) and a.wait(60)
    finally:
        svc.stop()
    assert plan.injected["serve_slow_step"] >= 1
    assert a.outcome == "ok"
    assert b.outcome == "deadline"
    assert "before a slot was free" in b.error
    assert svc.deadline_total == 1


# ----------------------------------------------------- supervised recovery

def test_wedge_recovery_resumes_streams_bit_identically():
    """serve_loop_wedge freezes the serving loop mid-burst; the watchdog
    detects the stale beat, rebuilds the engine, and the resumed streams
    finish with EXACTLY the tokens of an uninterrupted run."""
    from kubeml_tpu.faults import ServeFaultPlan
    from kubeml_tpu.serve.engine import DecodeEngine
    from kubeml_tpu.serve.service import ServeService
    from kubeml_tpu.utils.trace import Tracer

    _model, module, variables = _nano()
    clean = _clean_tokens(module, variables, SPECS)

    plan = ServeFaultPlan.parse([{"kind": "serve_loop_wedge", "step": 2}])
    engine = DecodeEngine(module, variables, slots=4, page=4,
                          fault_plan=plan)
    tracer = Tracer()
    svc = ServeService("wedge-m", engine, tracer=tracer,
                       wedge_timeout_s=0.2, watchdog_interval_s=0.05)
    svc.start()
    try:
        reqs = [svc.submit(list(p), max_new_tokens=n, temperature=t,
                           seed=s) for p, n, t, s in SPECS]
        for r in reqs:
            assert r.wait(120), "stream never resumed after the wedge"
    finally:
        svc.stop()

    assert plan.injected["serve_loop_wedge"] == 1
    assert all(r.outcome == "ok" for r in reqs)
    assert [r.tokens for r in reqs] == clean
    assert svc.restarts_total == 1
    assert svc.engine is not engine            # rebuilt, not resuscitated
    svc.engine.check_pager()                   # recovered pager is sound
    restarts = [e for e in tracer.events() if e["name"] == "engine_restart"]
    assert len(restarts) == 1 and restarts[0]["name"] == "engine_restart"
    assert "wedged" in restarts[0]["args"]["reason"]
    assert restarts[0]["args"]["resumed"] >= 1
    # the old engine's black box rode into the trace before the swap
    snaps = [e for e in tracer.events() if e["name"] == "flight_snapshot"]
    assert any(str(s["args"].get("reason", "")).startswith(
        "engine_restart:") for s in snaps)


@pytest.mark.filterwarnings(
    "ignore::pytest.PytestUnhandledThreadExceptionWarning")
def test_dead_loop_thread_recovery_resumes_bit_identically():
    """A loop thread that dies outright (uncaught exception outside the
    step) is detected by the watchdog and replaced; in-flight streams
    continue bit-identically."""
    from kubeml_tpu.serve.engine import DecodeEngine
    from kubeml_tpu.serve.service import ServeService

    _model, module, variables = _nano()
    clean = _clean_tokens(module, variables, SPECS)

    engine = DecodeEngine(module, variables, slots=4, page=4)
    svc = ServeService("dead-m", engine, wedge_timeout_s=5.0,
                       watchdog_interval_s=0.05)
    orig_publish = svc._publish
    state = {"killed": False}

    def bomb():
        if not state["killed"] and svc._inflight > 0:
            state["killed"] = True
            raise RuntimeError("injected loop death")
        orig_publish()

    svc._publish = bomb
    svc.start()
    try:
        reqs = [svc.submit(list(p), max_new_tokens=n, temperature=t,
                           seed=s) for p, n, t, s in SPECS]
        for r in reqs:
            assert r.wait(120), "stream never resumed after loop death"
    finally:
        svc.stop()

    assert state["killed"]
    assert svc.restarts_total == 1
    assert all(r.outcome == "ok" for r in reqs)
    assert [r.tokens for r in reqs] == clean
    svc.engine.check_pager()


# ----------------------------------------------------------- graceful drain

def test_drain_closes_admission_and_finishes_in_flight():
    from kubeml_tpu.serve.engine import DecodeEngine
    from kubeml_tpu.serve.service import ServeService
    from kubeml_tpu.serve.slots import ServeDraining
    from kubeml_tpu.utils.trace import Tracer

    _model, module, variables = _nano()
    engine = DecodeEngine(module, variables, slots=2, page=8)
    tracer = Tracer()
    svc = ServeService("drain-m", engine, tracer=tracer,
                       supervise=False).start()
    try:
        a = svc.submit([5, 6, 7], max_new_tokens=4)
        assert svc.drain(grace_s=60.0) is True
        assert a.outcome == "ok"               # in-flight stream finished
        with pytest.raises(ServeDraining) as ei:
            svc.submit([9, 10], max_new_tokens=2)
        assert ei.value.status_code == 503
        assert ei.value.retry_after_s >= 1.0
        assert "another replica" in str(ei.value)
    finally:
        svc.stop()
    drains = [e for e in tracer.events() if e["name"] == "drain"]
    assert len(drains) == 1 and drains[0]["name"] == "drain"
    assert drains[0]["args"]["grace_s"] == 60.0


def test_drain_force_releases_streams_past_grace_budget():
    from kubeml_tpu.faults import ServeFaultPlan
    from kubeml_tpu.serve.engine import DecodeEngine
    from kubeml_tpu.serve.service import ServeService

    _model, module, variables = _nano()
    plan = ServeFaultPlan.parse(
        [{"kind": "serve_slow_step", "duration_s": 0.05}])
    engine = DecodeEngine(module, variables, slots=2, page=8,
                          fault_plan=plan)
    svc = ServeService("force-m", engine, supervise=False).start()
    r = svc.submit([5, 6], max_new_tokens=32)
    # ~31 decode rounds at 50ms each vastly outlast a 150ms budget
    svc.stop(grace_s=0.15)
    assert r.wait(60)
    assert r.outcome == "error"
    assert "grace budget exhausted" in r.error


# -------------------------------------------------- stall guard + pager

def test_stalled_stream_guard_cancels_and_frees_pages():
    """events_iter's stall timeout CANCELS the request (not just the
    HTTP thread walking away), so the next engine step reaps the slot
    and the page free list is fully restored."""
    from kubeml_tpu.serve.engine import DecodeEngine
    from kubeml_tpu.serve.slots import GenerateRequest

    _model, module, variables = _nano()
    engine = DecodeEngine(module, variables, slots=2, page=8,
                          prefix_cache=False)
    req = GenerateRequest([5, 6, 7], max_new_tokens=8)
    engine.attach(req)
    engine.step()
    assert engine.pager.in_use > 0
    evs = list(req.events_iter(timeout=0.05))
    assert any("stream stalled" in str(e.get("error", "")) for e in evs)
    assert req.cancelled
    engine.step()                              # loop reaps the cancel
    assert req.outcome == "cancelled"
    assert engine.active() == 0
    assert engine.pager.in_use == 0
    assert engine.pager.check_invariants() == []


def test_pager_invariant_audit_strict_and_production_postures():
    from kubeml_tpu.serve.engine import DecodeEngine

    _model, module, variables = _nano()
    strict = DecodeEngine(module, variables, slots=2, page=8)
    assert strict.pager.check_invariants() == []
    strict.check_pager()                       # healthy: no-op
    # simulate a leaked release path: a referenced page vanishes from
    # the refcount map without returning to any list
    pid = strict.pager.alloc()
    del strict.pager._refs[pid]
    problems = strict.pager.check_invariants()
    assert problems and any("conservation" in p for p in problems)
    with pytest.raises(AssertionError, match="pager invariants"):
        strict.check_pager()

    prod = DecodeEngine(module, variables, slots=2, page=8,
                        strict_pager=False)
    pid = prod.pager.alloc()
    del prod.pager._refs[pid]
    prod.check_pager()                         # logs + counts, no raise
    assert prod.stats["page_leaks"] == 1


# ----------------------------------------------------------- observability

def test_fault_metric_families_and_deadline_outcome():
    from kubeml_tpu.metrics.prom import MetricsRegistry
    from tools.check_metrics import validate_exposition

    reg = MetricsRegistry()
    reg.note_serve_engine_restart("m")
    reg.note_serve_poisoned("m")
    reg.note_serve_page_leaks("m", 2)
    reg.observe_serve_request("m", "deadline")
    expo = reg.exposition()
    assert "# TYPE kubeml_serve_engine_restarts_total counter" in expo
    assert "# TYPE kubeml_serve_poisoned_requests_total counter" in expo
    assert "# TYPE kubeml_serve_page_leaks_total counter" in expo
    assert 'kubeml_serve_engine_restarts_total{model="m"} 1' in expo
    assert 'kubeml_serve_page_leaks_total{model="m"} 2' in expo
    assert 'outcome="deadline"' in expo
    assert validate_exposition(expo) == []
    reg.clear_serve("m")
    assert 'model="m"' not in reg.exposition()


def test_serve_crash_loop_health_rule():
    """Critical when restarts grew by 2+ within the window; one restart
    is recovery working; a lone high sample has no in-window delta."""
    from kubeml_tpu.control.health import HealthEvaluator

    ev = HealthEvaluator()
    assert not [f for f in ev.observe(
        {"job_id": "serve:m", "serve_engine_restarts": 0})
        if f["rule"] == "serve_crash_loop"]
    fired = [f for f in ev.observe(
        {"job_id": "serve:m", "serve_engine_restarts": 2})
        if f["rule"] == "serve_crash_loop"]
    assert fired and fired[0]["severity"] == "critical"
    assert "crash-looping" in fired[0]["detail"]

    single = HealthEvaluator()
    assert not [f for f in single.observe(
        {"job_id": "serve:n", "serve_engine_restarts": 0})
        if f["rule"] == "serve_crash_loop"]
    assert not [f for f in single.observe(
        {"job_id": "serve:n", "serve_engine_restarts": 1})
        if f["rule"] == "serve_crash_loop"]

    lone = HealthEvaluator()
    assert not [f for f in lone.observe(
        {"job_id": "serve:o", "serve_engine_restarts": 7})
        if f["rule"] == "serve_crash_loop"]


def test_top_renders_serve_faults_line():
    from kubeml_tpu.cli.main import _render_top

    latest = {"serve_active_slots": 1, "serve_slot_cap": 2,
              "serve_queue_depth": 0, "serve_queue_cap": 4,
              "serve_kv_page_utilization": 0.25,
              "serve_ttft_p50": 0.030, "serve_ttft_p99": 0.090,
              "serve_rejected_total": 0,
              "serve_prefill_backlog_tokens": 0,
              "serve_prefix_hit_pct": 50.0,
              "serve_engine_restarts": 1,
              "serve_poisoned_total": 2,
              "serve_deadline_total": 3}
    out = _render_top({"id": "serve:m", "state": "healthy", "reasons": [],
                       "latest": latest})
    assert "serve faults: restarts 1  poisoned 2  deadline 3" in out
    # a replica predating the fault telemetry renders without the line
    del latest["serve_engine_restarts"]
    out = _render_top({"id": "serve:m", "state": "healthy", "reasons": [],
                       "latest": latest})
    assert "serve faults" not in out


# ------------------------------------------------------------------- lint

def test_fault_lint_serve_kind_coverage_passes_on_this_repo():
    import tools.check_fault_tests as lint
    assert lint.main(["check_fault_tests"]) == 0


def test_fault_lint_serve_kind_coverage_self_test(tmp_path):
    """The coverage check parses SERVE_KINDS from the declaration site,
    demands the QUOTED kind on an assert line, and fails loudly when a
    kind has no test."""
    import tools.check_fault_tests as lint

    root = tmp_path
    (root / "kubeml_tpu").mkdir()
    (root / "tests").mkdir()
    faults = root / "kubeml_tpu" / "faults.py"
    faults.write_text('SERVE_KINDS = ("zz_boom", "zz_hang")\n'
                      'FLEET_KINDS = ()\n'
                      'CONTROL_KINDS = ()\n')
    tests_dir = str(root / "tests")

    assert lint.serve_kinds(str(faults)) == ["zz_boom", "zz_hang"]
    assert lint.unasserted_serve_kinds(str(faults), tests_dir) == \
        ["zz_boom", "zz_hang"]
    assert lint.main(["x", tests_dir]) == 1

    # a mention in a plan spec (no assert) does NOT count as coverage
    t = root / "tests" / "test_zz.py"
    t.write_text('plan = [{"kind": "zz_boom"}]\nkinds = ["zz_hang"]\n')
    assert lint.unasserted_serve_kinds(str(faults), tests_dir) == \
        ["zz_boom", "zz_hang"]

    t.write_text('kinds = ["zz_boom", "zz_hang"]\n'
                 'assert "zz_boom" in kinds\n'
                 'assert "zz_hang" in kinds\n')
    assert lint.unasserted_serve_kinds(str(faults), tests_dir) == []
    assert lint.main(["x", tests_dir]) == 0

    # a miswired tuple (faults.py refactor) fails loudly, not silently
    faults.write_text("RENAMED = ()\n")
    with pytest.raises(SystemExit):
        lint.serve_kinds(str(faults))
