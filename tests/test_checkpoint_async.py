"""AsyncCheckpointer: latest-wins, donation safety, drain, error surfacing."""

import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kubeml_tpu.train.checkpoint import (AsyncCheckpointer, load_checkpoint,
                                         save_checkpoint)


def test_async_save_and_wait(tmp_path):
    ck = AsyncCheckpointer(root=str(tmp_path))
    v = {"params": {"w": jnp.arange(4.0)}}
    ck.save("j1", v, {"model": "m"})
    ck.wait()
    loaded, manifest = load_checkpoint("j1", root=str(tmp_path))
    np.testing.assert_array_equal(loaded["params"]["w"], np.arange(4.0))
    assert manifest["model"] == "m"


def test_async_snapshot_survives_donation(tmp_path):
    """save() must snapshot before returning: deleting the source buffers
    right after (what engine donation does on the next round) must not
    corrupt the written checkpoint."""
    # gate the worker so deletion definitely happens before the write
    import kubeml_tpu.train.checkpoint as ckpt_mod
    gate = threading.Event()
    real = ckpt_mod.save_checkpoint

    def gated(jid, variables, manifest, root=None):
        gate.wait(5)
        return real(jid, variables, manifest, root=root)

    ckpt_mod.save_checkpoint = gated
    try:
        ck = AsyncCheckpointer(root=str(tmp_path))
        w = jnp.arange(8.0)
        ck.save("j2", {"params": {"w": w}}, {})
        w.delete()  # simulate donation of the live buffer
        gate.set()
        ck.wait()
    finally:
        ckpt_mod.save_checkpoint = real
    loaded, _ = load_checkpoint("j2", root=str(tmp_path))
    np.testing.assert_array_equal(loaded["params"]["w"], np.arange(8.0))


def test_async_latest_wins(tmp_path):
    """Saves queued faster than the writer drains collapse to the newest."""
    import kubeml_tpu.train.checkpoint as ckpt_mod
    written = []
    gate = threading.Event()
    real = ckpt_mod.save_checkpoint

    def slow(jid, variables, manifest, root=None):
        gate.wait(5)
        written.append(manifest.get("epoch"))
        return real(jid, variables, manifest, root=root)

    ckpt_mod.save_checkpoint = slow
    try:
        ck = AsyncCheckpointer(root=str(tmp_path))
        for e in range(5):
            ck.save("j3", {"params": {"w": jnp.full(2, float(e))}},
                    {"epoch": e})
        gate.set()
        ck.wait()
    finally:
        ckpt_mod.save_checkpoint = real
    # the first dequeued save may be any early epoch (races with the
    # enqueue loop), but the LAST write is always the newest snapshot
    assert written[-1] == 4
    loaded, manifest = load_checkpoint("j3", root=str(tmp_path))
    assert manifest["epoch"] == 4
    np.testing.assert_array_equal(loaded["params"]["w"], np.full(2, 4.0))


def test_async_error_superseded_by_later_success(tmp_path):
    """A transient save failure must NOT fail the job when a newer save
    for the same job published a durable checkpoint."""
    import kubeml_tpu.train.checkpoint as ckpt_mod
    real = ckpt_mod.save_checkpoint
    calls = {"n": 0}

    def flaky(jid, variables, manifest, root=None):
        calls["n"] += 1
        if calls["n"] == 1:
            raise OSError("transient disk error")
        return real(jid, variables, manifest, root=root)

    ckpt_mod.save_checkpoint = flaky
    try:
        ck = AsyncCheckpointer(root=str(tmp_path))
        ck.save("j6", {"params": {"w": jnp.zeros(2)}}, {"epoch": 1})
        # ensure the failing write fully ran before the next save so it
        # is not collapsed away by latest-wins
        while calls["n"] < 1:
            time.sleep(0.01)
        ck.save("j6", {"params": {"w": jnp.ones(2)}}, {"epoch": 2})
        ck.wait()  # must not raise: epoch-2 save succeeded
        ck.close()
    finally:
        ckpt_mod.save_checkpoint = real
    loaded, manifest = load_checkpoint("j6", root=str(tmp_path))
    assert manifest["epoch"] == 2
    np.testing.assert_array_equal(loaded["params"]["w"], np.ones(2))


def test_async_close_stops_worker_and_rejects_saves(tmp_path):
    ck = AsyncCheckpointer(root=str(tmp_path))
    ck.save("j7", {"params": {"w": jnp.zeros(2)}}, {})
    ck.close()
    assert ck._thread is None  # worker joined
    load_checkpoint("j7", root=str(tmp_path))  # drained before stopping
    with pytest.raises(RuntimeError):
        ck.save("j8", {"params": {"w": jnp.zeros(2)}}, {})
    ck.close()  # idempotent


def test_async_error_surfaces_on_wait(tmp_path):
    target = tmp_path / "not-a-dir"
    target.write_text("file blocks the models root")
    ck = AsyncCheckpointer(root=str(target))
    ck.save("j4", {"params": {"w": jnp.zeros(1)}}, {})
    with pytest.raises(Exception):
        ck.wait()
    # the error is consumed; a subsequent good save works
    ck.root = str(tmp_path)
    ck.save("j5", {"params": {"w": jnp.zeros(1)}}, {})
    ck.wait()
    load_checkpoint("j5", root=str(tmp_path))


def test_mid_publish_crash_falls_back_to_previous(tmp_path):
    """save_checkpoint publishes via two renames (current -> .old, then
    tmp -> current); a SIGKILL landing between them must not lose ALL
    recovery state — loads and the watchdog's saved_at probe fall back
    to the intact .old checkpoint (at most one epoch of state lost)."""
    import os
    import shutil

    from kubeml_tpu.train.checkpoint import (checkpoint_saved_at,
                                             delete_checkpoint)

    root = str(tmp_path)
    save_checkpoint("jx", {"params": {"w": jnp.arange(3.0)}},
                    {"model": "m", "epoch": 1}, root=root)
    # simulate the crash window: the current dir was renamed aside and
    # the new one never landed
    os.rename(os.path.join(root, "jx"), os.path.join(root, "jx.old"))

    assert checkpoint_saved_at("jx", root=root) is not None
    loaded, manifest = load_checkpoint("jx", root=root)
    assert manifest["epoch"] == 1
    np.testing.assert_array_equal(
        np.asarray(loaded["params"]["w"]), np.arange(3.0))

    # the next successful save supersedes the fallback...
    save_checkpoint("jx", {"params": {"w": jnp.arange(3.0) + 1}},
                    {"model": "m", "epoch": 2}, root=root)
    _, manifest = load_checkpoint("jx", root=root)
    assert manifest["epoch"] == 2
    # ...and delete removes every variant incl. leftovers
    shutil.copytree(os.path.join(root, "jx"), os.path.join(root, "jx.tmp"))
    delete_checkpoint("jx", root=root)
    assert not any(os.path.exists(os.path.join(root, p))
                   for p in ("jx", "jx.old", "jx.tmp"))
