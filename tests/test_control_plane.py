"""Full-stack control-plane test: client -> controller -> scheduler -> PS
-> job -> history/metrics/infer, all over real HTTP on localhost."""

import time

import numpy as np
import pytest
import urllib.request

from kubeml_tpu.api.errors import KubeMLException
from kubeml_tpu.api.types import TrainOptions, TrainRequest
from kubeml_tpu.control.client import KubemlClient
from kubeml_tpu.control.deployment import start_deployment


@pytest.fixture()
def stack(tmp_path, tmp_home, mesh8):
    dep = start_deployment(mesh=mesh8)
    client = KubemlClient(dep.controller_url)
    yield dep, client, tmp_path
    dep.stop()


def write_blob_files(tmp_path, n_train=600, n_test=120, dim=8, classes=3):
    rng = np.random.RandomState(0)

    def split(n):
        y = rng.randint(0, classes, n).astype(np.int32)
        x = rng.randn(n, dim).astype(np.float32) * 1.5
        x[np.arange(n), y * 2] += 3.0
        return x, y

    paths = {}
    for name, arr in zip(("xtr", "ytr", "xte", "yte"),
                         [a for s in (split(n_train), split(n_test))
                          for a in s]):
        p = tmp_path / f"{name}.npy"
        np.save(p, arr)
        paths[name] = str(p)
    return paths


def wait_history(client, job_id, timeout=120):
    deadline = time.time() + timeout
    while time.time() < deadline:
        try:
            return client.v1().histories().get(job_id)
        except KubeMLException:
            time.sleep(0.3)
    raise TimeoutError(f"no history for {job_id}")


def test_end_to_end_train_infer(stack):
    dep, client, tmp_path = stack
    paths = write_blob_files(tmp_path)

    # dataset upload through the controller (multipart proxy)
    summary = client.v1().datasets().create(
        "blobs", paths["xtr"], paths["ytr"], paths["xte"], paths["yte"])
    assert summary.train_set_size == 600
    assert [s.name for s in client.v1().datasets().list()] == ["blobs"]

    # train via the public API
    req = TrainRequest(model_type="mlp", batch_size=32, epochs=3,
                       dataset="blobs", lr=0.1,
                       options=TrainOptions(default_parallelism=2,
                                            static_parallelism=True, k=2))
    job_id = client.v1().networks().train(req)
    assert len(job_id) == 8

    history = wait_history(client, job_id)
    assert len(history.data.train_loss) == 3
    assert history.data.parallelism == [2, 2, 2]

    # inference on the checkpointed model through the public API
    x = np.load(paths["xte"])[:5]
    preds = client.v1().networks().infer(job_id, x.tolist())
    assert len(preds) == 5

    # task list empty after completion
    assert client.v1().tasks().list() == []


def test_dynamic_parallelism_through_scheduler(stack):
    dep, client, tmp_path = stack
    paths = write_blob_files(tmp_path)
    client.v1().datasets().create(
        "blobs", paths["xtr"], paths["ytr"], paths["xte"], paths["yte"])
    req = TrainRequest(model_type="mlp", batch_size=32, epochs=4,
                       dataset="blobs", lr=0.1,
                       options=TrainOptions(default_parallelism=2,
                                            static_parallelism=False, k=-1))
    job_id = client.v1().networks().train(req)
    history = wait_history(client, job_id)
    # epoch 2 must ask the scheduler: second policy call always +1
    assert history.data.parallelism[0] == 2
    assert history.data.parallelism[1] == 3


def test_metrics_exposition_and_clearing(stack):
    dep, client, tmp_path = stack
    paths = write_blob_files(tmp_path)
    client.v1().datasets().create(
        "blobs", paths["xtr"], paths["ytr"], paths["xte"], paths["yte"])
    # enough epochs that per-job gauges stay visible for several seconds
    # between the first publish and the finish-time clear
    req = TrainRequest(model_type="mlp", batch_size=16, epochs=10,
                       dataset="blobs", lr=0.1,
                       options=TrainOptions(default_parallelism=2,
                                            static_parallelism=True, k=1))
    job_id = client.v1().networks().train(req)
    # during the run, gauges should appear
    seen_series = False
    for _ in range(100):
        text = urllib.request.urlopen(dep.ps.url + "/metrics").read().decode()
        if f'kubeml_job_train_loss{{jobid="{job_id}"}}' in text:
            seen_series = True
            break
        time.sleep(0.2)
    wait_history(client, job_id)
    assert seen_series, "per-job gauges never appeared on /metrics"
    dep.ps.wait_for_job(job_id)
    text = urllib.request.urlopen(dep.ps.url + "/metrics").read().decode()
    assert f'jobid="{job_id}"' not in text  # cleared at finish


def test_task_stop_via_controller(stack):
    dep, client, tmp_path = stack
    paths = write_blob_files(tmp_path, n_train=6000)
    client.v1().datasets().create(
        "blobs", paths["xtr"], paths["ytr"], paths["xte"], paths["yte"])
    req = TrainRequest(model_type="mlp", batch_size=16, epochs=50,
                       dataset="blobs", lr=0.01,
                       options=TrainOptions(default_parallelism=2,
                                            static_parallelism=True, k=1))
    job_id = client.v1().networks().train(req)
    # wait until running then stop
    for _ in range(100):
        tasks = client.v1().tasks().list()
        if any(t.job_id == job_id for t in tasks):
            break
        time.sleep(0.2)
    client.v1().tasks().stop(job_id)
    history = wait_history(client, job_id)
    assert len(history.data.train_loss) < 50


def test_mid_job_inference(stack):
    """The reference serves inference on a LIVE job's weights
    (scheduler/api.go:119-162). Default checkpoint cadence (auto:
    every validated epoch) makes /infer answer while the job is still
    running — and again after it finishes."""
    dep, client, tmp_path = stack
    paths = write_blob_files(tmp_path, n_train=4000)
    client.v1().datasets().create(
        "blobs", paths["xtr"], paths["ytr"], paths["xte"], paths["yte"])
    req = TrainRequest(model_type="mlp", batch_size=16, epochs=40,
                       dataset="blobs", lr=0.01,
                       options=TrainOptions(default_parallelism=2,
                                            static_parallelism=True, k=1))
    job_id = client.v1().networks().train(req)
    x = np.load(paths["xte"])[:3].tolist()

    # The hard regression guard: the FIRST published checkpoint must be a
    # periodic (auto-cadence) one — its manifest carries `epoch`. If the
    # auto cadence breaks, the first checkpoint to appear is the final
    # save (epoch absent) and this fails regardless of timing races.
    from kubeml_tpu.train.checkpoint import load_checkpoint
    manifest = None
    deadline = time.time() + 180
    while time.time() < deadline:
        try:
            _, manifest = load_checkpoint(job_id)
            break
        except Exception:
            time.sleep(0.05)
    assert manifest is not None, "no checkpoint ever published"
    assert manifest.get("epoch") is not None, \
        "first published checkpoint was not a periodic auto-cadence save"

    # and the product surface serves it mid-run
    preds = client.v1().networks().infer(job_id, x)
    assert len(preds) == 3
    if any(t.job_id == job_id for t in client.v1().tasks().list()):
        pass  # genuinely observed mid-run (the common case)
    client.v1().tasks().stop(job_id)
    wait_history(client, job_id)
    dep.ps.wait_for_job(job_id)
    post = client.v1().networks().infer(job_id, x)  # post-run still works
    assert len(post) == 3


def test_error_envelope_on_bad_requests(stack):
    dep, client, tmp_path = stack
    # missing dataset -> scheduler accepts, job fails; infer on unknown model
    with pytest.raises(KubeMLException) as ei:
        client.v1().networks().infer("nonexist1", [[1.0]])
    assert ei.value.status_code == 404
    with pytest.raises(KubeMLException) as ei:
        client.v1().histories().get("nonexist1")
    assert ei.value.status_code == 404
    with pytest.raises(KubeMLException) as ei:
        client.v1().datasets().delete("nonexist1")
    assert ei.value.status_code == 404


def test_infer_cache_invalidates_on_new_checkpoint(stack):
    """Repeated inference hits the PS cache; a re-written checkpoint
    (same job id) invalidates it."""
    dep, client, tmp_path = stack
    paths = write_blob_files(tmp_path)
    client.v1().datasets().create(
        "blobs", paths["xtr"], paths["ytr"], paths["xte"], paths["yte"])
    req = TrainRequest(model_type="mlp", batch_size=32, epochs=1,
                       dataset="blobs", lr=0.1,
                       options=TrainOptions(default_parallelism=2,
                                            static_parallelism=True, k=2))
    job_id = client.v1().networks().train(req)
    wait_history(client, job_id)

    x = np.load(paths["xte"])[:4].tolist()
    p1 = client.v1().networks().infer(job_id, x)
    assert job_id in dep.ps._infer_cache
    p2 = client.v1().networks().infer(job_id, x)
    assert p1 == p2

    # overwrite the checkpoint with different weights -> cache must miss
    # and the NEW weights must be served
    from kubeml_tpu.train.checkpoint import (checkpoint_saved_at,
                                             load_checkpoint,
                                             save_checkpoint)
    import jax
    variables, manifest = load_checkpoint(job_id)
    zeroed = jax.tree_util.tree_map(lambda a: np.asarray(a) * 0.0, variables)
    save_checkpoint(job_id, zeroed, manifest)
    p3 = client.v1().networks().infer(job_id, x)
    # all-zero weights predict class 0 everywhere — different model served
    assert p3 == [0] * len(x)
    assert dep.ps._infer_cache[job_id][0] == checkpoint_saved_at(job_id)


def test_tensor_parallel_job_through_controller(stack):
    """VERDICT r1 item 3's done criterion: a DP x TP bert-tiny job
    submitted through the public API (the `kubeml train -f bert-tiny
    --tensor-parallel 2` path) trains and validates."""
    dep, client, tmp_path = stack
    rng = np.random.RandomState(0)

    def split(n, T=16, vocab=1000):
        x = rng.randint(1, vocab, size=(n, T)).astype(np.int32)
        y = (x[:, 0] > vocab // 2).astype(np.int32)
        return x, y

    paths = {}
    for name, arr in zip(("xtr", "ytr", "xte", "yte"),
                         [a for s in (split(256), split(64)) for a in s]):
        p = tmp_path / f"tok_{name}.npy"
        np.save(p, arr)
        paths[name] = str(p)
    client.v1().datasets().create("toks", paths["xtr"], paths["ytr"],
                                  paths["xte"], paths["yte"])
    req = TrainRequest(model_type="bert-tiny", batch_size=16, epochs=2,
                       dataset="toks", lr=1e-3,
                       options=TrainOptions(default_parallelism=4,
                                            static_parallelism=True, k=1,
                                            n_model=2))
    job_id = client.v1().networks().train(req)
    history = wait_history(client, job_id, timeout=300)
    assert len(history.data.train_loss) == 2
    assert history.data.train_loss[-1] < history.data.train_loss[0]
    # validated every epoch; accuracy recorded
    assert history.data.accuracy[-1] == history.data.accuracy[-1]
    # and the checkpointed model serves inference through the public API
    x = np.load(paths["xte"])[:4]
    preds = client.v1().networks().infer(job_id, x.tolist())
    assert len(preds) == 4


def test_infer_batcher_groups_and_scatters():
    """InferBatcher: concurrent same-shape submissions are served by
    ONE stacked run (padded to a pow-2 bucket), each caller getting
    exactly its own slice; failures propagate to every member; a lone
    request still works."""
    import threading

    import numpy as np

    from kubeml_tpu.control.ps import InferBatcher

    b = InferBatcher(window_s=0.05, max_batch=64)
    calls = []

    def run(stacked):
        calls.append(len(stacked))
        return stacked.sum(axis=1)  # per-row reduction: slices checkable

    # sparse traffic: the very first request serves IMMEDIATELY (no
    # window tax when there is nothing to batch with) — and primes the
    # dense-traffic detector for the concurrent burst below
    lone = b.submit(("m", (3,), "f"), np.ones((2, 3)), run)
    np.testing.assert_array_equal(lone, [3.0, 3.0])
    assert calls == [2]

    results = {}
    errs = []

    def client(i):
        arr = np.full((2, 3), float(i))
        try:
            results[i] = b.submit(("m", (3,), "f"), arr, run)
        except Exception as e:  # pragma: no cover - failure surfaces
            errs.append(e)

    threads = [threading.Thread(target=client, args=(i,))
               for i in range(5)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errs
    # dense burst: one stacked call, padded 10 -> 16
    assert calls == [2, 16]
    for i in range(5):
        np.testing.assert_array_equal(results[i], [3.0 * i, 3.0 * i])

    # batched failure reaches every member
    def boom(stacked):
        raise RuntimeError("kernel exploded")

    failures = []

    def bad_client():
        try:
            b.submit(("x", (3,), "f"), np.ones((1, 3)), boom)
        except RuntimeError as e:
            failures.append(str(e))

    threads = [threading.Thread(target=bad_client) for _ in range(3)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert failures == ["kernel exploded"] * 3


def test_concurrent_infer_through_ps(stack):
    """8 concurrent /infer clients against the PS micro-batcher return
    the SAME predictions the single-stream path computes — serving
    depth (VERDICT r4 weak #6) without correctness drift."""
    import threading

    from kubeml_tpu.control.httpd import http_json

    dep, client, tmp_path = stack
    paths = write_blob_files(tmp_path)
    client.v1().datasets().create(
        "blobsinf", paths["xtr"], paths["ytr"], paths["xte"],
        paths["yte"])
    req = TrainRequest(model_type="mlp", batch_size=32, epochs=1,
                       dataset="blobsinf", lr=0.1,
                       options=TrainOptions(default_parallelism=2,
                                            static_parallelism=True, k=2))
    job_id = client.v1().networks().train(req)
    wait_history(client, job_id)

    url = f"{dep.ps.url}/infer"
    xq = np.load(paths["xte"])[:8]
    expect = http_json("POST", url, {"model_id": job_id,
                                     "data": xq.tolist()})["predictions"]
    outs = [None] * 8

    def worker(i):
        outs[i] = http_json("POST", url, {
            "model_id": job_id, "data": xq.tolist()})["predictions"]

    threads = [threading.Thread(target=worker, args=(i,))
               for i in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert all(o == expect for o in outs)


def test_trace_propagation_end_to_end(stack):
    """ISSUE 3 acceptance: one `kubeml train` run yields a merged Chrome
    trace where the client-minted trace id appears on client, scheduler,
    PS and job spans, round spans nest under epoch spans, and the
    document is fetchable through PS /trace?id=, the controller proxy,
    and `kubeml trace --id`."""
    dep, client, tmp_path = stack
    paths = write_blob_files(tmp_path)
    client.v1().datasets().create(
        "blobs", paths["xtr"], paths["ytr"], paths["xte"], paths["yte"])
    req = TrainRequest(model_type="mlp", batch_size=32, epochs=2,
                       dataset="blobs", lr=0.1,
                       options=TrainOptions(default_parallelism=2,
                                            static_parallelism=True, k=2))
    trace_id = "cafe0123feedbeef"
    job_id = client.v1().networks().train(req, trace_id=trace_id)
    wait_history(client, job_id)
    dep.ps.wait_for_job(job_id)

    doc = client.v1().traces().get(job_id)  # controller -> PS merge
    assert doc["metadata"]["trace_ids"] == [trace_id]
    # all four processes contributed a trace file (threaded stack: four
    # sinks in one OS process, one file per role)
    roles = {s.split("-")[0] for s in doc["metadata"]["sources"]}
    assert {"client", "scheduler", "ps", "job"} <= roles
    spans = [e for e in doc["traceEvents"] if e.get("ph") == "X"]
    assert all(e["args"]["trace_id"] == trace_id for e in spans)
    names = {e["name"] for e in spans}
    assert {"client.train", "scheduler.enqueue", "ps.start_task",
            "epoch", "round", "dispatch"} <= names
    epochs = sorted(e["args"]["epoch"] for e in spans
                    if e["name"] == "epoch")
    assert epochs == [0, 1]
    rounds = [e for e in spans if e["name"] == "round"]
    assert rounds and all(e["args"]["parent"] == "epoch" for e in rounds)

    # PS endpoint directly
    from kubeml_tpu.control.httpd import http_json
    direct = http_json("GET", f"{dep.ps.url}/trace?id={job_id}")
    assert direct["metadata"]["trace_ids"] == [trace_id]
    with pytest.raises(KubeMLException) as ei:
        client.v1().traces().get("nosuchjob")
    assert ei.value.status_code == 404

    # CLI fetch writes the same Perfetto-loadable document
    import json
    from kubeml_tpu.cli.main import main as cli_main
    out = tmp_path / "trace.json"
    cli_main(["--controller", dep.controller_url, "trace",
              "--id", job_id, "-o", str(out)])
    assert json.loads(out.read_text())["metadata"]["trace_ids"] == \
        [trace_id]


def test_service_metrics_exposition(stack):
    """Every control-plane service serves a lint-clean /metrics with
    per-endpoint HTTP request counters; the PS additionally serves the
    three job phase histogram families with valid cumulative buckets
    (fed here over the real wire path, POST /metrics/{jobId}) plus the
    health-stat and runtime-introspection families."""
    from kubeml_tpu.api.types import MetricUpdate
    from kubeml_tpu.control.httpd import http_json
    from tools.check_metrics import parse_exposition, validate_exposition

    dep, client, tmp_path = stack
    http_json("POST", f"{dep.ps.url}/metrics/metricprobe", MetricUpdate(
        job_id="metricprobe", validation_loss=0.5, accuracy=0.9,
        train_loss=0.4, parallelism=2, epoch_duration=1.0,
        phase_times={"dispatch": [0.01, 0.2], "data_wait": [0.001],
                     "device_drain": [0.05]},
        grad_norms=[0.5, 0.7], update_ratios=[1e-3, 2e-3],
        worker_losses=[0.41, 0.39], loss_spread=0.01,
        jit_compiles=3, hbm_peak_bytes=1 << 20,
        hbm_in_use_bytes=1 << 19, trace_events_dropped=0).to_dict())

    ps_text = urllib.request.urlopen(dep.ps.url + "/metrics").read().decode()
    assert validate_exposition(ps_text) == []
    fams = parse_exposition(ps_text)
    for fam, n in (("kubeml_job_dispatch_seconds", 2),
                   ("kubeml_job_data_wait_seconds", 1),
                   ("kubeml_job_merge_seconds", 1)):
        assert fams[fam]["type"] == "histogram"
        counts = [v for name, labels, v in fams[fam]["samples"]
                  if name == fam + "_count"
                  and labels["jobid"] == "metricprobe"]
        assert counts == [n], fam

    # per-worker stats are LABELLED series (the lint's cardinality guard
    # rejects indexed family names), runtime counters come from the
    # update's cumulative values
    grads = {labels["worker"]: v for name, labels, v
             in fams["kubeml_job_worker_grad_norm"]["samples"]
             if labels["jobid"] == "metricprobe"}
    assert grads == {"0": 0.5, "1": 0.7}
    assert fams["kubeml_jit_compiles_total"]["type"] == "counter"
    compiles = [v for name, labels, v
                in fams["kubeml_jit_compiles_total"]["samples"]
                if labels["jobid"] == "metricprobe"]
    assert compiles == [3]
    hbm = {labels["kind"]: v for name, labels, v
           in fams["kubeml_device_hbm_bytes"]["samples"]
           if labels["jobid"] == "metricprobe"}
    assert hbm == {"peak": float(1 << 20), "in_use": float(1 << 19)}
    states = {labels["state"]: v for name, labels, v
              in fams["kubeml_job_health"]["samples"]
              if labels["jobid"] == "metricprobe"}
    assert sum(states.values()) == 1.0  # one-hot state vector
    dep.ps.metrics.clear_job("metricprobe")

    # middleware counters, labeled by route pattern. The middleware
    # records a request *after* replying (so latency covers the full
    # handler), which means a scrape issued right after the POST can
    # race its increment — poll briefly instead of asserting one-shot.
    deadline = time.monotonic() + 5.0
    while True:
        reqs = {(labels["method"], labels["endpoint"]): v
                for name, labels, v
                in fams["kubeml_http_requests_total"]["samples"]
                if labels["service"] == "ps" and labels["status"] == "200"}
        if ("POST", "/metrics/{jobId}") in reqs:
            break
        if time.monotonic() > deadline:
            raise AssertionError(
                f"POST /metrics/{{jobId}} never hit the counter: {reqs}")
        time.sleep(0.05)
        fams = parse_exposition(
            urllib.request.urlopen(dep.ps.url + "/metrics").read().decode())
    assert reqs[("POST", "/metrics/{jobId}")] >= 1
    assert "kubeml_http_request_duration_seconds" in fams

    # scheduler and controller serve the default middleware exposition
    # (prime each with one request first: the middleware records a
    # request after replying, so a cold scrape is legitimately empty)
    for svc in (dep.scheduler, dep.controller):
        urllib.request.urlopen(svc.url + "/health").read()
        text = urllib.request.urlopen(svc.url + "/metrics").read().decode()
        assert validate_exposition(text) == []
        svc_fams = parse_exposition(text)
        samples = svc_fams["kubeml_http_requests_total"]["samples"]
        assert {labels["service"] for _, labels, _ in samples} \
            == {svc.name}

    # the jobserver (standalone-mode child) is a JsonService too and
    # must stay scraper-clean — it is the one service the deployment
    # fixture does not start, so probe a bare instance directly
    from kubeml_tpu.train.jobserver import JobServer
    js = JobServer("metricprobe", ps_url=dep.ps.url, port=0)
    js.start()
    try:
        urllib.request.urlopen(js.url + "/health").read()
        text = urllib.request.urlopen(js.url + "/metrics").read().decode()
        assert validate_exposition(text) == []
        samples = parse_exposition(text)[
            "kubeml_http_requests_total"]["samples"]
        assert {labels["service"] for _, labels, _ in samples} == {"job"}
    finally:
        js.stop()


def test_train_options_wire_roundtrip_round5_fields():
    """The round-5 TrainOptions fields survive the REST wire format
    (to_dict/from_dict) — a field that serializes but doesn't parse
    would silently train with defaults on the far side."""
    from kubeml_tpu.api.types import TrainOptions

    opts = TrainOptions(default_parallelism=3, n_stage=2,
                        pp_microbatches=6, fsdp=True,
                        rounds_per_dispatch=4, n_expert=2,
                        max_parallelism=8, max_restarts=2)
    rt = TrainOptions.from_dict(opts.to_dict())
    assert rt == opts


def test_health_telemetry_wire_roundtrip():
    """TrainOptions.train_stats and the health/runtime MetricUpdate
    fields survive to_dict/from_dict — a field that serializes but
    doesn't parse would silently publish defaults (and the PS would
    evaluate health on nothing)."""
    from kubeml_tpu.api.types import MetricUpdate, TrainOptions

    opts = TrainOptions(default_parallelism=2, train_stats=False)
    assert TrainOptions.from_dict(opts.to_dict()) == opts
    assert TrainOptions.from_dict({}).train_stats is True  # default on

    m = MetricUpdate(
        job_id="wire", validation_loss=0.5, accuracy=0.9, train_loss=0.4,
        parallelism=2, epoch_duration=1.0,
        grad_norms=[0.5, 0.7], update_ratios=[1e-3, 2e-3],
        worker_losses=[0.41, 0.39], loss_spread=0.01,
        jit_compiles=3, hbm_peak_bytes=1 << 20,
        hbm_in_use_bytes=1 << 19, trace_events_dropped=2)
    rt = MetricUpdate.from_dict(m.to_dict())
    assert rt == m
    assert rt.grad_norms == [0.5, 0.7]
    assert rt.trace_events_dropped == 2
