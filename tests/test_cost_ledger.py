"""Analytic cost-ledger tests (kubeml_tpu/metrics/ledger.py).

The contracts pinned here are the ones the ledger is built around:

  * determinism — the canonical program inventory produces a
    byte-identical snapshot JSON in two FRESH processes (identical HLO
    yields bit-identical cost analysis; the budget gate depends on it)
  * fallback — when a backend exposes no cost analysis the caller's
    closed-form estimate stands in, tagged source="fallback"
  * replay — `totals == dispatches x per-dispatch cost` holds exactly
    for stable programs, tampering raises, and recaptures (shape
    changes) exempt a program from the global invariant
  * reconciliation — the serve engine's `pager.decode_kv` record
    equals `KVPageSlab.decode_bytes_per_token` EXACTLY, so the paged
    attention proxy and the ledger can never drift apart
  * the budget gate itself — tools/check_cost_budgets.py passes
    against the committed tools/cost_budgets.json and FAILS loudly on
    a perturbed budget, an unbudgeted program, and a stale entry
  * plumbing — per-program storm attribution, delta-advanced
    kubeml_cost_* counters, and the MetricUpdate wire round-trip
"""

import json
import os
import subprocess
import sys

import pytest

pytestmark = pytest.mark.cost

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_TOOLS = os.path.join(_REPO_ROOT, "tools")


def _gate():
    """Import tools/check_cost_budgets.py as a module."""
    if _TOOLS not in sys.path:
        sys.path.insert(0, _TOOLS)
    import check_cost_budgets
    return check_cost_budgets


# -------------------------------------------------------- determinism

_CANONICAL_SNIPPET = """
import sys
sys.path.insert(0, {tools!r})
import check_cost_budgets
from kubeml_tpu.metrics.ledger import snapshot_to_json
ledger = check_cost_budgets.build_canonical_ledger()
for name in ledger.programs():
    ledger.note_dispatch(name, 3, samples=8, tokens=4)
print(snapshot_to_json(ledger.snapshot()))
"""


def test_snapshot_bit_identical_across_two_fresh_processes():
    """Two cold processes compiling the same canonical inventory emit
    byte-identical snapshot JSON — the determinism contract that makes
    per-program cost a CI-gateable number rather than a profile."""
    env = dict(os.environ, JAX_PLATFORMS="cpu", KUBEML_COST_LEDGER="1")
    code = _CANONICAL_SNIPPET.format(tools=_TOOLS)
    outs = []
    for _ in range(2):
        r = subprocess.run([sys.executable, "-c", code], cwd=_REPO_ROOT,
                           env=env, capture_output=True, text=True,
                           timeout=300)
        assert r.returncode == 0, r.stderr
        outs.append(r.stdout)
    assert outs[0] == outs[1]
    snap = json.loads(outs[0])
    assert snap, "canonical ledger produced no programs"
    for entry in snap.values():
        assert entry["dispatches"] == 3
        assert entry["flops_total"] == 3 * entry["flops"]
        assert entry["hbm_bytes_total"] == 3 * entry["hbm_bytes"]


# ----------------------------------------------------------- fallback

def test_fallback_when_backend_has_no_cost_analysis(monkeypatch):
    """With XLA's analysis unavailable, the caller's closed form stands
    in and is tagged so budgets/reconciliation treat it correctly."""
    import jax.numpy as jnp

    from kubeml_tpu.metrics import ledger as ledger_mod

    monkeypatch.setattr(ledger_mod, "extract_xla_cost",
                        lambda *a, **k: None)
    led = ledger_mod.CostLedger(capture_enabled=True)
    rec = led.capture("fb.prog", "train", lambda x: x, jnp.zeros((2,)),
                      fallback={"flops": 12.0, "hbm_bytes": 34.0,
                                "transcendentals": 5.0})
    assert rec.source == "fallback"
    assert (rec.flops, rec.hbm_bytes, rec.transcendentals) == (12.0, 34.0, 5.0)
    # totals still attribute off the fallback record
    led.note_dispatch("fb.prog", 4, samples=16)
    assert led.totals("fb.prog")["flops_total"] == 48.0
    led.replay_check()


def test_env_gate_disables_xla_capture(monkeypatch):
    """KUBEML_COST_LEDGER=0 skips the extra AOT compile entirely and
    uses the fallback path (source=fallback, no XLA call)."""
    from kubeml_tpu.metrics import ledger as ledger_mod

    monkeypatch.setenv("KUBEML_COST_LEDGER", "0")

    def _boom(*a, **k):  # must not be reached when gated off
        raise AssertionError("extract_xla_cost called despite gate")

    monkeypatch.setattr(ledger_mod, "extract_xla_cost", _boom)
    led = ledger_mod.CostLedger()
    rec = led.capture("gated.prog", "serve", None,
                      fallback={"hbm_bytes": 7.0})
    assert rec.source == "fallback" and rec.hbm_bytes == 7.0


# -------------------------------------------------------------- replay

def test_replay_invariant_tamper_and_recapture_exemption():
    from kubeml_tpu.metrics.ledger import (CostLedger,
                                           CostReconciliationError)

    led = CostLedger()
    led.capture_analytic("a", "kernel", flops=10.0, hbm_bytes=100.0)
    led.note_dispatch("a", 7)
    led.replay_check()

    # tampering with a total breaks the invariant loudly
    led._totals["a"]["flops_total"] += 1.0
    with pytest.raises(CostReconciliationError, match="replay mismatch"):
        led.replay_check()
    led._totals["a"]["flops_total"] -= 1.0
    led.replay_check()

    # a recapture (shape change → new per-dispatch cost) makes the
    # global invariant per-segment; the replay check must skip it
    led.capture_analytic("a", "kernel", flops=20.0, hbm_bytes=100.0)
    led.note_dispatch("a", 1)
    assert led.totals("a")["recaptures"] == 1
    led.replay_check()  # mixed-record totals, but exempted


def test_reconcile_exact_and_tolerant():
    from kubeml_tpu.metrics.ledger import (CostLedger,
                                           CostReconciliationError)

    led = CostLedger()
    led.capture_analytic("p", "serve", hbm_bytes=1000.0)
    led.reconcile("p", "hbm_bytes", 1000.0, tolerance=0.0)
    with pytest.raises(CostReconciliationError):
        led.reconcile("p", "hbm_bytes", 1001.0, tolerance=0.0)
    led.reconcile("p", "hbm_bytes", 1100.0, tolerance=0.2)
    with pytest.raises(CostReconciliationError):
        led.reconcile("p", "hbm_bytes", 2000.0, tolerance=0.2)
    with pytest.raises(CostReconciliationError, match="no record"):
        led.reconcile("missing", "hbm_bytes", 1.0)


# ---------------------------------------------------- serve reconcile

def test_decode_engine_kv_record_reconciles_exactly(monkeypatch):
    """The engine's pager.decode_kv record IS the slab's
    decode_bytes_per_token — the acceptance-criterion reconciliation,
    checked at the engine level (not just the canonical inventory).
    Capture is forced ON (the suite defaults it off for speed) so this
    is also the one in-suite drive of `_ledger_capture`'s XLA path,
    including its decode-bytes-vs-proxy tolerance sanity check."""
    import jax
    import numpy as np

    monkeypatch.setenv("KUBEML_COST_LEDGER", "1")

    from kubeml_tpu.models import get_builtin
    from kubeml_tpu.serve.engine import DecodeEngine
    from kubeml_tpu.serve.slots import GenerateRequest

    model = get_builtin("gpt-nano")()
    module = model.module
    variables = model.init_variables(
        jax.random.PRNGKey(0),
        {"x": np.ones((1, module.max_len), np.int32)})
    engine = DecodeEngine(module, variables, slots=4, page=4)

    rec = engine.ledger.record("pager.decode_kv")
    assert rec is not None and rec.source == "analytic"
    assert rec.hbm_bytes == float(engine.slab.decode_bytes_per_token)
    assert rec.plane == "serve"

    # drive one request: serve-plane tokens attribute, replay holds
    engine.attach(GenerateRequest([5, 6, 7], max_new_tokens=4))
    guard = 10_000
    while engine.active():
        engine.step()
        guard -= 1
        assert guard > 0
    engine.ledger.replay_check()
    dec = engine.ledger.record("serve.decode")
    assert dec is not None and dec.source == "xla"
    att = engine.ledger.attributed()
    assert att["serve"]["tokens"] > 0
    assert att["serve"]["bytes_per_token"] > 0.0


# ---------------------------------------------------------- budget gate

def test_budget_gate_passes_committed_and_fails_perturbed():
    """The regression gate's self-test: the committed budgets pass,
    and a deliberately broken budget file produces every violation
    class (exceeded-exact, unbudgeted, stale, source mismatch)."""
    gate = _gate()
    with open(gate.DEFAULT_BUDGETS) as f:
        budgets = json.load(f)
    assert gate.check(budgets) == []

    perturbed = json.loads(json.dumps(budgets))  # deep copy
    progs = perturbed["programs"]
    # exceeded: an analytic program's bytes are exact — off by one fails
    assert progs["pager.decode_kv"]["source"] == "analytic"
    progs["pager.decode_kv"]["hbm_bytes"] += 1.0
    # source mismatch: lint.train is compiler-derived
    progs["lint.train"]["source"] = "analytic"
    # unbudgeted: drop a canonical program from the file
    del progs["merge.monolithic"]
    # stale: budget an entry no canonical program produces
    progs["ghost.prog"] = {"plane": "train", "source": "analytic",
                           "flops": 1.0, "hbm_bytes": 1.0,
                           "transcendentals": 0.0}
    problems = "\n".join(gate.check(perturbed))
    assert "pager.decode_kv.hbm_bytes" in problems
    assert "lint.train.source" in problems
    assert "merge.monolithic: unbudgeted" in problems
    assert "ghost.prog: stale" in problems


def test_budget_gate_cli_passes_in_suite():
    """tier-1 wiring: the gate script itself exits 0 against the
    committed file, run exactly as CI would run it."""
    r = subprocess.run(
        [sys.executable, os.path.join(_TOOLS, "check_cost_budgets.py")],
        cwd=_REPO_ROOT, env=dict(os.environ, JAX_PLATFORMS="cpu"),
        capture_output=True, text=True, timeout=300)
    assert r.returncode == 0, r.stdout + r.stderr
    assert "cost budgets OK" in r.stdout


# ------------------------------------------------------- merge helpers

def test_merge_snapshots_and_attribution():
    from kubeml_tpu.metrics.ledger import (attributed_from_snapshot,
                                           merge_cost_snapshots)

    a = {"p": {"program": "p", "plane": "serve", "flops": 2.0,
               "hbm_bytes": 10.0, "source": "analytic", "dispatches": 3,
               "flops_total": 6.0, "hbm_bytes_total": 30.0,
               "transcendentals_total": 0.0, "samples": 0, "tokens": 12,
               "recaptures": 0}}
    b = json.loads(json.dumps(a))
    b["p"].update(dispatches=1, flops_total=2.0, hbm_bytes_total=10.0,
                  tokens=4)
    merged = merge_cost_snapshots([a, b, {}])
    assert merged["p"]["dispatches"] == 4
    assert merged["p"]["flops_total"] == 8.0
    assert merged["p"]["tokens"] == 16
    assert merged["p"]["flops"] == 2.0  # record from first snapshot

    att = attributed_from_snapshot(merged)
    assert att["serve"]["flops_per_token"] == 8.0 / 16
    assert att["serve"]["bytes_per_token"] == 40.0 / 16


# ------------------------------------------------- storm attribution

def test_recompile_storm_names_the_guilty_program():
    from kubeml_tpu.metrics.runtime import JitCompileTracker

    t = JitCompileTracker()
    # program "healthy" dispatches without compiling; "churny" hits the
    # storm threshold — attribution must separate them
    for _ in range(20):
        t.note(False, program="healthy")
    for _ in range(3):
        t.note(True, 0.1, program="churny")
    assert t.storms_by_program.get("churny") == 1
    assert "healthy" not in t.storms_by_program
    assert t.storm


# --------------------------------------------------------- prom wiring

def test_update_cost_delta_advances_counters():
    """kubeml_cost_* counters advance by snapshot deltas per owner:
    repeats are no-ops, dips (engine restart resets a ledger) are
    absorbed, and two owners sum into one (program, plane) series."""
    from kubeml_tpu.metrics.prom import MetricsRegistry

    reg = MetricsRegistry()

    def snap(flops, disp):
        return {"p": {"plane": "serve", "flops_total": flops,
                      "hbm_bytes_total": 2 * flops, "dispatches": disp}}

    key = ("p", "serve")
    reg.update_cost("job-1", snap(100.0, 2))
    assert reg.cost_flops_total.value(key) == 100.0
    assert reg.cost_dispatches_total.value(key) == 2.0
    reg.update_cost("job-1", snap(100.0, 2))   # repeat: no-op
    assert reg.cost_flops_total.value(key) == 100.0
    reg.update_cost("job-1", snap(150.0, 3))   # advance by delta
    assert reg.cost_flops_total.value(key) == 150.0
    reg.update_cost("job-1", snap(40.0, 1))    # restart dip: absorbed
    assert reg.cost_flops_total.value(key) == 150.0
    reg.update_cost("serve:m", snap(60.0, 1))  # second owner sums
    assert reg.cost_flops_total.value(key) == 210.0
    assert reg.cost_hbm_bytes_total.value(key) == 420.0

    # clear_job drops only the seen baseline; counters are PS-lifetime
    reg.clear_job("job-1")
    assert reg.cost_flops_total.value(key) == 210.0
    assert not [k for k in reg._cost_seen if k[0] == "job-1"]
    assert [k for k in reg._cost_seen if k[0] == "serve:m"]

    # the families are part of the exposition (metrics lint surface)
    text = reg.exposition()
    assert "kubeml_cost_flops_total" in text
    assert "kubeml_cost_dispatches_total" in text


# ----------------------------------------------------------- wire types

def test_metric_update_cost_programs_roundtrip():
    from kubeml_tpu.api.types import MetricUpdate

    snap = {"kavg.train": {"program": "kavg.train", "plane": "train",
                           "flops": 5.0, "hbm_bytes": 9.0,
                           "dispatches": 2, "flops_total": 10.0,
                           "hbm_bytes_total": 18.0, "samples": 64,
                           "tokens": 0, "recaptures": 0,
                           "transcendentals": 0.0,
                           "transcendentals_total": 0.0,
                           "source": "xla"}}
    m = MetricUpdate(job_id="j", validation_loss=0.1, accuracy=0.9,
                     train_loss=0.2, parallelism=2, epoch_duration=1.0,
                     cost_programs=snap)
    d = json.loads(json.dumps(m.to_dict()))  # through the JSON wire
    m2 = MetricUpdate.from_dict(d)
    assert m2.cost_programs == snap
    # absent on the wire (old sender) → empty dict, not None
    del d["cost_programs"]
    assert MetricUpdate.from_dict(d).cost_programs == {}
