"""SLO-plane unit tests: mergeable latency sketches
(kubeml_tpu/metrics/sketch.py), the multi-window burn-rate engine
(kubeml_tpu/serve/slo.py), and the wiring around them.

The contracts pinned here:

  * sketch identity — merging per-replica sketches equals sketching
    the POOLED samples, bucket for bucket (exact state equality, in
    any merge order); this is what makes fleet p99 the p99 of the
    fleet, not of the worst replica
  * sketch accuracy — every quantile of seeded data is within the
    configured relative error of the sorted-list answer
  * windowed expiry — sub-windows age out as a pure function of an
    injectable clock: deterministic under a fake clock, empty after
    window_s of silence (the property that made the autoscaler's
    stale-p99 `inflight > 0` guard unnecessary)
  * burn engine — burn = bad_fraction / (1 - target) per window; an
    alert needs BOTH the fast and slow windows above 1.0, onsets are
    counted once, and recovery clears
  * wiring — the slo_burn health rule fires on the multi-window
    condition only, the kubeml_serve_slo_* Prometheus families pass
    the metrics lint and clear with the model, `kubeml top` renders
    the slo line, and tools/check_serve_spans.py lints
    FLEET_SPAN_KINDS with the same quoted-name rule (self-tested on
    synthetic trees, including one WITHOUT fleet.py — the engine-only
    lint fixtures must keep passing)
"""

import random

import pytest

pytestmark = [pytest.mark.serving, pytest.mark.slo]


# ------------------------------------------------------------- sketches


def test_sketch_merge_equals_pooled_exactly():
    """The satellite identity: merge(per-part sketches) == sketch of
    the pooled samples, as exact bucket-state equality, regardless of
    partition or merge order."""
    from kubeml_tpu.metrics.sketch import QuantileSketch

    rng = random.Random(42)
    samples = [rng.lognormvariate(-3.0, 1.0) for _ in range(4000)]
    pooled = QuantileSketch()
    parts = [QuantileSketch() for _ in range(3)]
    for i, v in enumerate(samples):
        pooled.add(v)
        parts[i % 3].add(v)
    forward = QuantileSketch()
    for p in parts:
        forward.merge(p)
    backward = QuantileSketch()
    for p in reversed(parts):
        backward.merge(p)
    assert forward.state() == pooled.state()
    assert backward.state() == pooled.state()
    assert forward.count == len(samples)
    # and therefore every quantile agrees exactly, not approximately
    for q in (0.0, 0.25, 0.5, 0.9, 0.99, 1.0):
        assert forward.quantile(q) == pooled.quantile(q)


def test_sketch_quantiles_within_relative_error_of_sorted_list():
    """Accuracy contract vs the sorted-list percentile the sketch
    replaced: every quantile is within alpha relative error of the
    exact order statistic."""
    from kubeml_tpu.metrics.sketch import QuantileSketch

    alpha = 0.01
    rng = random.Random(7)
    samples = sorted(rng.uniform(0.0005, 3.0) for _ in range(5000))
    sk = QuantileSketch(alpha=alpha)
    for v in samples:
        sk.add(v)
    for q in (0.01, 0.1, 0.25, 0.5, 0.75, 0.9, 0.95, 0.99, 0.999):
        exact = samples[int(q * (len(samples) - 1))]
        est = sk.quantile(q)
        assert abs(est - exact) <= alpha * exact * 1.0001, (q, est,
                                                           exact)


def test_sketch_edge_cases_and_state_round_trip():
    from kubeml_tpu.metrics.sketch import QuantileSketch

    sk = QuantileSketch()
    assert sk.quantile(0.5) == 0.0          # empty
    sk.add(-1.0)
    sk.add(0.0)
    sk.add(0.02)
    assert sk.count == 3
    assert sk.quantile(0.0) == 0.0          # clamped zero bucket
    assert abs(sk.quantile(1.0) - 0.02) <= 0.01 * 0.02
    # JSON round trip preserves the exact bucket state
    import json
    st = json.loads(json.dumps(sk.state()))
    clone = QuantileSketch.from_state(st)
    assert clone.state() == sk.state()
    assert clone.quantile(1.0) == sk.quantile(1.0)
    # guard rails
    with pytest.raises(ValueError):
        sk.quantile(1.5)
    with pytest.raises(ValueError):
        QuantileSketch(alpha=0.0)
    with pytest.raises(ValueError):
        sk.merge(QuantileSketch(alpha=0.02))


def test_windowed_sketch_expiry_is_deterministic_under_fake_clock():
    """Sub-windows age out as a pure function of the clock: samples
    survive exactly window_s, partial expiry drops only the old
    sub-windows, and two identically-fed rings agree state-for-state."""
    from kubeml_tpu.metrics.sketch import WindowedSketch

    t = [0.0]
    mk = lambda: WindowedSketch(window_s=60.0, subwindows=6,  # noqa: E731
                                clock=lambda: t[0])
    a, b = mk(), mk()
    for w in (a, b):
        w.add(0.010)                      # tick 0
    t[0] = 55.0
    for w in (a, b):
        w.add(0.020)                      # tick 5
    assert a.count == 2
    assert a.state() == b.state()         # deterministic
    t[0] = 59.9                           # everything still live
    assert a.count == 2
    t[0] = 60.0                           # tick 6: tick 0 expires
    assert a.count == 1
    assert a.quantile(1.0) == pytest.approx(0.020, rel=0.011)
    t[0] = 115.0                          # tick 11: tick 5 expires too
    assert a.count == 0
    assert a.quantile(0.99) == 0.0        # idle window drains to empty
    assert a.state() == b.state()


# ---------------------------------------------------------- burn engine


def test_slo_engine_burn_math_and_multi_window_alert():
    from kubeml_tpu.serve.slo import SLOEngine

    e = SLOEngine(0.05, 0.01, target=0.99, fast_ticks=2, slow_ticks=6)
    assert e.burn_fast == 0.0 and e.burn_slow == 0.0   # no traffic
    assert e.attainment == 1.0
    # 2% bad at a 1% budget: burn 2.0 in any window that saw it —
    # both windows contain the same single tick, so the alert fires
    # at onset immediately
    assert e.tick(98, 2) is True
    assert e.alerting
    assert e.alerts_total == 1
    assert e.burn_fast == pytest.approx(2.0)
    assert e.burn_slow == pytest.approx(2.0)
    assert e.attainment == pytest.approx(0.98)
    # recovery: clean ticks push the bad tick out of the FAST window
    # first — burn_slow stays elevated but the alert clears (no
    # flapping on long memory)
    e.tick(100, 0)
    e.tick(100, 0)
    assert e.burn_fast == 0.0
    assert e.burn_slow > 0.0
    assert not e.alerting
    # re-onset counts again
    onsets = [e.tick(0, 50) for _ in range(2)]
    assert onsets.count(True) == 1 and e.alerts_total == 2
    assert e.good_total == 298 and e.bad_total == 102

    with pytest.raises(ValueError):
        SLOEngine(0.05, 0.01, target=1.0)
    with pytest.raises(ValueError):
        SLOEngine(0.05, 0.01, fast_ticks=3, slow_ticks=2)


def test_slo_engine_tick_onset_ordering_and_classify():
    from kubeml_tpu.serve.slo import SLOEngine

    e = SLOEngine(0.05, 0.01, target=0.9, fast_ticks=2, slow_ticks=4)
    onsets = [e.tick(0, 5) for _ in range(4)]
    # the alert ONSET is reported exactly once while the condition holds
    assert onsets == [True, False, False, False]
    assert e.alerts_total == 1
    assert e.snapshot_fields()["serve_slo_alerts_total"] == 1
    assert set(e.snapshot_fields()) == {
        "serve_slo_target", "serve_slo_attainment",
        "serve_slo_burn_fast", "serve_slo_burn_slow",
        "serve_slo_good_total", "serve_slo_bad_total",
        "serve_slo_alerts_total"}

    # classification: ok within both objectives is good; a disabled
    # objective (<= 0) never disqualifies; errors are always bad
    assert e.classify("ok", ttft=0.04, tpot=0.005)
    assert not e.classify("ok", ttft=0.06, tpot=0.005)
    assert not e.classify("ok", ttft=0.04, tpot=0.02)
    assert not e.classify("error", ttft=0.01, tpot=0.001)
    assert not e.classify("deadline", ttft=0.01, tpot=0.001)
    relaxed = SLOEngine(0.0, 0.0)
    assert relaxed.classify("ok", ttft=99.0, tpot=99.0)


# ---------------------------------------------------------------- wiring


def test_slo_burn_health_rule_needs_both_windows():
    """slo_burn fires only when BOTH burn windows exceed 1.0; samples
    without serve_slo_* fields (training jobs, solo serve) never
    fire."""
    from kubeml_tpu.control.health import HealthEvaluator

    ev = HealthEvaluator()
    base = {"job_id": "serve:m", "serve_slo_target": 0.99,
            "serve_slo_attainment": 0.97}
    # fast spike alone: no page
    assert not [f for f in ev.observe(dict(
        base, serve_slo_burn_fast=3.0, serve_slo_burn_slow=0.4))
        if f["rule"] == "slo_burn"]
    # both windows burning: warning with the numbers in the detail
    fired = [f for f in ev.observe(dict(
        base, serve_slo_burn_fast=3.0, serve_slo_burn_slow=1.5))
        if f["rule"] == "slo_burn"]
    assert fired and fired[0]["severity"] == "warning"
    assert "fast 3x" in fired[0]["detail"]
    assert "slow 1.5x" in fired[0]["detail"]
    assert "0.97" in fired[0]["detail"]
    # recovery clears on the next sample
    assert not [f for f in ev.observe(dict(
        base, serve_slo_burn_fast=0.0, serve_slo_burn_slow=1.5))
        if f["rule"] == "slo_burn"]

    solo = HealthEvaluator()
    assert not [f for f in solo.observe(
        {"job_id": "train-1", "train_loss": 0.5})
        if f["rule"] == "slo_burn"]


def test_slo_metric_families_pass_lint_and_clear():
    """The kubeml_serve_slo_* families: gauges mirror the snapshot
    (burn windows via the `window` label), counters advance by delta
    across republishes, the exposition is lint-clean, and clear_serve
    removes every series."""
    from kubeml_tpu.metrics.prom import MetricsRegistry
    from tools.check_metrics import validate_exposition

    reg = MetricsRegistry()
    snap = {"fleet_replicas": 2, "serve_slo_target": 0.99,
            "serve_slo_attainment": 0.985,
            "serve_slo_burn_fast": 1.5, "serve_slo_burn_slow": 1.2,
            "serve_slo_good_total": 197, "serve_slo_bad_total": 3,
            "serve_slo_alerts_total": 1}
    reg.update_fleet("m1", snap)
    reg.update_fleet("m1", snap)          # republish: no double count
    text = reg.exposition()
    assert ('kubeml_serve_slo_attainment{model="m1"} 0.985') in text
    assert ('kubeml_serve_slo_burn_rate'
            '{model="m1",window="fast"} 1.5') in text
    assert ('kubeml_serve_slo_burn_rate'
            '{model="m1",window="slow"} 1.2') in text
    assert 'kubeml_serve_slo_good_total{model="m1"} 197' in text
    assert 'kubeml_serve_slo_bad_total{model="m1"} 3' in text
    assert 'kubeml_serve_slo_burn_alerts_total{model="m1"} 1' in text
    assert validate_exposition(text) == []
    # counters advance by DELTA from the cumulative snapshot
    reg.update_fleet("m1", dict(snap, serve_slo_good_total=250,
                                serve_slo_bad_total=4))
    text = reg.exposition()
    assert 'kubeml_serve_slo_good_total{model="m1"} 250' in text
    assert 'kubeml_serve_slo_bad_total{model="m1"} 4' in text
    reg.clear_serve("m1")
    assert 'model="m1"' not in reg.exposition()


def test_top_renders_slo_line():
    from kubeml_tpu.cli.main import _render_top

    doc = {"id": "serve:m1", "state": "healthy", "reasons": [],
           "latest": {"serve_active_slots": 1, "serve_slot_cap": 8,
                      "serve_queue_depth": 0, "serve_queue_cap": 16,
                      "serve_kv_page_utilization": 0.25,
                      "serve_rejected_total": 0,
                      "serve_slo_target": 0.99,
                      "serve_slo_attainment": 0.985,
                      "serve_slo_burn_fast": 1.5,
                      "serve_slo_burn_slow": 1.23,
                      "serve_slo_good_total": 197,
                      "serve_slo_bad_total": 3}}
    out = _render_top(doc)
    assert "slo: attainment 98.5% (target 99%)" in out
    assert "burn fast 1.50 slow 1.23" in out
    assert "good/bad 197/3" in out
    # snapshots without the SLO plane render no slo line
    del doc["latest"]["serve_slo_attainment"]
    assert "slo:" not in _render_top(doc)


# ----------------------------------------------------------- span lint


def _write_tree(root, engine_kinds, fleet_kinds, asserted):
    """Synthetic repo tree for the span lint: registries + one test
    file asserting `asserted` quoted."""
    serve = root / "kubeml_tpu" / "serve"
    serve.mkdir(parents=True)
    engine_tuple = ", ".join(f'"{k}"' for k in engine_kinds)
    (serve / "engine.py").write_text(
        f"SERVE_SPAN_KINDS = ({engine_tuple},)\n")
    if fleet_kinds is not None:
        fleet_tuple = ", ".join(f'"{k}"' for k in fleet_kinds)
        (serve / "fleet.py").write_text(
            f"FLEET_SPAN_KINDS = ({fleet_tuple},)\n")
    tests = root / "tests"
    tests.mkdir()
    lines = ["def test_kinds():"]
    lines += [f'    assert "{k}" in kinds()' for k in asserted]
    lines += ["", "", "def kinds():", "    return []"]
    (tests / "test_spans.py").write_text("\n".join(lines) + "\n")


def test_serve_span_lint_covers_fleet_kinds(tmp_path):
    """The extended lint: a FLEET_SPAN_KINDS entry without a quoted
    assert fails; asserting it passes; a tree WITHOUT fleet.py (the
    engine-only self-test fixtures) checks just the engine registry."""
    from tools import check_serve_spans as lint

    covered = tmp_path / "covered"
    covered.mkdir()
    _write_tree(covered, ["alpha"], ["route_x", "migrate_x"],
                ["alpha", "route_x", "migrate_x"])
    assert lint.main(["check_serve_spans.py", str(covered)]) == 0

    naked = tmp_path / "naked"
    naked.mkdir()
    _write_tree(naked, ["alpha"], ["route_x", "migrate_x"],
                ["alpha", "route_x"])        # migrate_x unasserted
    assert lint.main(["check_serve_spans.py", str(naked)]) == 1
    assert lint.unasserted_fleet_kinds(
        str(naked / "kubeml_tpu" / "serve" / "fleet.py"),
        str(naked / "tests")) == ["migrate_x"]

    engine_only = tmp_path / "engine_only"
    engine_only.mkdir()
    _write_tree(engine_only, ["alpha"], None, ["alpha"])
    assert lint.main(["check_serve_spans.py", str(engine_only)]) == 0

    # fleet.py present but the tuple missing: the lint is miswired
    broken = tmp_path / "broken"
    broken.mkdir()
    _write_tree(broken, ["alpha"], None, ["alpha"])
    (broken / "kubeml_tpu" / "serve" / "fleet.py").write_text(
        "VNODES = 32\n")
    assert lint.main(["check_serve_spans.py", str(broken)]) == 1


def test_fleet_span_registry_matches_design():
    """The eight cross-replica kinds from the design doc, pinned so a
    rename shows up here AND in the per-kind behavioural asserts."""
    from kubeml_tpu.serve.fleet import FLEET_SPAN_KINDS

    assert set(FLEET_SPAN_KINDS) == {
        "route", "affine_hit", "spill", "retry", "cold_start_wait",
        "migrate", "hedge", "probe"}
