"""Fleet failure-domain tests (serve/fleet.py supervise_once + the
FleetFaultPlan kinds in kubeml_tpu/faults.py).

The contracts pinned here:

  * fault plan — FleetFaultPlan parses the same shapes as
    ServeFaultPlan (JSON string / dict / list), rejects unknown kinds,
    fires each event ONCE, resolves wildcard replicas to the lowest
    live index, and keeps an untargetable event armed
  * crash failover — a killed replica is ejected from the hash ring
    and its in-flight streams live-migrate onto survivors via the
    re-prefill path, finishing TOKEN-FOR-TOKEN identical to a solo
    unfaulted engine (the (seed, pos) sampling keys make the
    continuation exact); the replacement replica earns its vnodes back
    through half-open probes ("probe_rejoin")
  * wedge — watchdog restarts beyond the budget read as crash-looping
    and eject; slow — a planted serve_slow_step straggler drives the
    hedged retry of a QUEUED stream onto a peer ("hedge")
  * edge cases — all-replicas-ejected fails fast with a 503 whose
    Retry-After reflects probation (no spin against an empty ring);
    stale sticky sessions pointing at an ejected replica re-resolve
    through the ring; the per-stream migration budget turns the N+1th
    move into a clean terminal error
  * telemetry — per-replica prefix deltas re-baseline across a replica
    restart epoch (never negative, totals monotone), the new
    kubeml_serve_fleet_* counter families pass the metrics lint, the
    fleet_degraded health rule fires on an in-window ejection, and
    `kubeml top` renders the fleet-faults line
  * lint — tools/check_fault_tests.py FLEET_KINDS coverage rule passes
    on this repo and behaves on synthetic trees (every injection here
    is coordinate-driven; the lint scans this file too)
"""

import time

import numpy as np
import pytest

pytestmark = [pytest.mark.serving, pytest.mark.faults]


@pytest.fixture(scope="module")
def nano():
    import jax

    from kubeml_tpu.models import get_builtin
    model = get_builtin("gpt-nano")()
    module = model.module
    variables = model.init_variables(
        jax.random.PRNGKey(0),
        {"x": np.ones((1, module.max_len), np.int32)})
    return model, module, variables


def _factory(module, variables, *, slots=2, page=4, max_queue=2):
    from kubeml_tpu.serve.engine import DecodeEngine
    from kubeml_tpu.serve.service import ServeService

    def make(index):
        engine = DecodeEngine(module, variables, slots=slots, page=page)
        return ServeService("fleet-m", engine, max_queue=max_queue,
                            supervise=False)
    return make


def _fleet(module, variables, **kw):
    from kubeml_tpu.serve.fleet import ServeFleet
    kw.setdefault("autoscale_interval_s", 0.0)   # tests drive ticks
    kw.setdefault("page_tokens", 4)
    factory_kw = {k: kw.pop(k) for k in ("slots", "max_queue")
                  if k in kw}
    return ServeFleet("fleet-m", _factory(module, variables,
                                          **factory_kw), **kw)


def _solo_tokens(module, variables, prompt, n_new, *, page=4):
    """Reference decode: the same request alone on a fresh engine."""
    from kubeml_tpu.serve.engine import DecodeEngine
    from kubeml_tpu.serve.slots import GenerateRequest

    engine = DecodeEngine(module, variables, slots=2, page=page)
    req = GenerateRequest(list(prompt), max_new_tokens=n_new)
    engine.attach(req)
    limit = 10_000
    while engine.active():
        engine.step()
        limit -= 1
        assert limit > 0, "solo engine failed to drain"
    assert req.outcome == "ok"
    return req.tokens


def _owned_prompts(fleet, owner, count, n_tokens=5):
    """Prompts whose routing digest lands on replica `owner`."""
    from kubeml_tpu.serve.pager import routing_digest
    out = []
    for base in range(3, 4000):
        p = [(base + j) % 97 + 1 for j in range(n_tokens)]
        with fleet._lock:
            if fleet._ring_owner(
                    routing_digest(p, fleet.page_tokens)) == owner:
                out.append(p)
        if len(out) == count:
            return out
    raise AssertionError(f"no {count} prompts owned by {owner}")


def _wait(pred, timeout_s=30.0, tick=0.01):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(tick)
    return False


# ------------------------------------------------------------ fault plan

def test_fleet_fault_plan_parse_fire_once_and_wildcard():
    """FleetFaultPlan shares ServeFaultPlan's parse contract; fire() is
    once-only, wildcard replicas resolve to the lowest live index, and
    an event with no live target stays armed."""
    from kubeml_tpu.faults import FLEET_KINDS, FleetFaultPlan

    assert "fleet_replica_crash" in FLEET_KINDS
    assert "fleet_replica_wedge" in FLEET_KINDS
    assert "fleet_replica_slow" in FLEET_KINDS

    plan = FleetFaultPlan.parse(
        '{"events": [{"kind": "fleet_replica_crash", "tick": 2},'
        ' {"kind": "fleet_replica_slow", "replica": 7,'
        '  "duration_s": 0.5}]}')
    assert plan is FleetFaultPlan.parse(plan)       # idempotent
    # tick 1: nothing due
    assert plan.fire(1, [0, 1]) == []
    # tick 2: the crash fires, wildcard replica -> lowest live index
    fired = plan.fire(2, [3, 1])
    assert [(k, r) for k, r, _e in fired] == [("fleet_replica_crash", 1)]
    assert plan.injected["fleet_replica_crash"] == 1
    # once-only: tick 2 again delivers nothing
    assert plan.fire(2, [1, 3]) == []
    # the slow event targets replica 7: stays armed while 7 is absent
    assert plan.fire(3, [1, 3]) == []
    assert plan.injected["fleet_replica_slow"] == 0
    fired = plan.fire(4, [1, 7])
    assert [(k, r) for k, r, _e in fired] == [("fleet_replica_slow", 7)]
    assert fired[0][2].duration_s == 0.5
    assert plan.injected["fleet_replica_slow"] == 1
    assert plan.injected["fleet_replica_wedge"] == 0

    # list / dict forms parse too; unknown kinds fail loudly
    assert FleetFaultPlan.parse(
        [{"kind": "fleet_replica_wedge"}]).has("fleet_replica_wedge")
    with pytest.raises(ValueError):
        FleetFaultPlan.parse([{"kind": "replica_crash"}])
    with pytest.raises(ValueError):
        FleetFaultPlan.parse('{"events": 3}')


# ------------------------------------------- crash -> eject -> migrate

def test_crash_failover_migrates_streams_and_probation_rejoins(nano):
    """The full failure-domain cycle: a deterministic
    fleet_replica_crash kills replica 0 mid-decode; supervise_once
    ejects it, live-migrates its in-flight streams onto the survivor
    (bit-identical continuation via re-prefill), spawns a probationary
    replacement, and later graduates it back onto the ring."""
    _model, module, variables = nano
    fleet = _fleet(module, variables, replicas_min=2, replicas_max=2,
                   probe_requests=1, slots=2, max_queue=4,
                   fault_plan=[{"kind": "fleet_replica_crash",
                                "replica": 0}])
    fleet.start()
    try:
        victim = fleet._replicas[0]
        prompts = _owned_prompts(fleet, 0, 3)
        reqs = [fleet.submit(p, max_new_tokens=8) for p in prompts]
        assert all(r.fleet_replica == 0 for r in reqs)
        # let the victim get mid-decode so the kill lands on live work
        assert _wait(lambda: victim.engine.active() >= 1)
        actions = fleet.supervise_once()
        assert "eject" in actions and "failover_migrate" in actions
        for p, r in zip(prompts, reqs):
            assert r.wait(120) and r.outcome == "ok", (r.outcome, r.error)
            assert r.migrations >= 1 and r.fleet_replica != 0
            np.testing.assert_array_equal(
                r.tokens, _solo_tokens(module, variables, p, 8))
        snap = fleet.snapshot()
        assert snap["fleet_ejections_total"] == 1
        assert snap["fleet_failovers_total"] == 1
        assert snap["fleet_migrated_streams_total"] >= 3
        assert snap["fleet_probation"] == 1      # replacement, half-open
        assert fleet.path_counts["eject"] == 1
        assert fleet.path_counts["failover_migrate"] >= 3
        assert fleet.fault_plan.injected["fleet_replica_crash"] == 1

        # probation: the next submit is routed as a half-open probe;
        # serving it to "ok" earns the vnodes back on the next tick
        r = fleet.submit(prompts[0], max_new_tokens=4)
        assert r.wait(120) and r.outcome == "ok"
        np.testing.assert_array_equal(
            r.tokens, _solo_tokens(module, variables, prompts[0], 4))
        assert snap["fleet_probes_total"] + 1 == \
            fleet.snapshot()["fleet_probes_total"]
        actions = fleet.supervise_once()
        assert "probe_rejoin" in actions
        assert fleet.path_counts["probe_rejoin"] == 1
        snap = fleet.snapshot()
        assert snap["fleet_probation"] == 0
        assert snap["fleet_replicas"] == 2       # ring repopulated
    finally:
        fleet.stop(grace_s=0.0)


def test_wedge_blows_restart_budget_and_ejects(nano):
    """fleet_replica_wedge drives real watchdog-path restarts past the
    budget; the supervisor reads the replica as crash-looping and
    ejects it (no migration needed when nothing is in flight)."""
    _model, module, variables = nano
    fleet = _fleet(module, variables, replicas_min=2, replicas_max=2,
                   replica_restart_budget=1, probe_requests=1,
                   fault_plan=[{"kind": "fleet_replica_wedge",
                                "replica": 1}])
    fleet.start()
    try:
        r = fleet.submit([5, 6, 7, 8], max_new_tokens=4)
        assert r.wait(120) and r.outcome == "ok"
        actions = fleet.supervise_once()
        assert actions == ["eject"]
        assert fleet.fault_plan.injected["fleet_replica_wedge"] == 1
        snap = fleet.snapshot()
        assert snap["fleet_ejections_total"] == 1
        assert snap["fleet_failovers_total"] == 0    # nothing in flight
        assert 1 not in fleet._replicas or 1 in fleet._probation
    finally:
        fleet.stop(grace_s=0.0)


def test_slow_replica_straggler_is_hedged(nano):
    """fleet_replica_slow plants serve_slow_step on the victim; a
    stream stuck QUEUED behind the straggler past hedge_after_s is
    stolen and re-admitted on a peer ("hedge") and still finishes
    bit-identical to a solo engine; the steal leaves a "hedge" instant
    with the stitch pointer on the fleet timeline."""
    from kubeml_tpu.utils.trace import Tracer

    _model, module, variables = nano
    tracer = Tracer()
    fleet = _fleet(module, variables, replicas_min=2, replicas_max=2,
                   hedge_after_s=0.05, slots=2, max_queue=4,
                   tracer=tracer,
                   fault_plan=[{"kind": "fleet_replica_slow",
                                "replica": 0, "duration_s": 0.2}])
    fleet.start()
    try:
        fleet.supervise_once()       # delivers the slow-step plant
        assert fleet.fault_plan.injected["fleet_replica_slow"] == 1
        prompts = _owned_prompts(fleet, 0, 5)
        reqs = [fleet.submit(p, max_new_tokens=12) for p in prompts]
        assert all(r.fleet_replica == 0 for r in reqs)
        assert _wait(lambda: "hedge" in fleet.supervise_once(),
                     timeout_s=60.0, tick=0.05), "no hedge fired"
        assert fleet.path_counts["hedge"] >= 1
        assert fleet.snapshot()["fleet_hedges_total"] >= 1
        hedge_evs = [e for e in tracer.events() if e["name"] == "hedge"]
        assert hedge_evs, 'no "hedge" instant on the fleet timeline'
        assert hedge_evs[0]["args"]["resumed_from"] == 0
        assert hedge_evs[0]["args"]["replica"] != 0
        assert hedge_evs[0]["args"]["parent"] == "generate"
        hedged = 0
        for p, r in zip(prompts, reqs):
            assert r.wait(180) and r.outcome == "ok", (r.outcome, r.error)
            hedged += int(r.fleet_replica != 0)
            np.testing.assert_array_equal(
                r.tokens, _solo_tokens(module, variables, p, 12))
        assert hedged >= 1
    finally:
        fleet.stop(grace_s=0.0)


@pytest.mark.slo
def test_crash_migration_preserves_trace_and_merges_one_tree(nano,
                                                            tmp_path):
    """Satellite: live migration must NOT lose the request's trace.
    The ejected replica's buffered spans are flushed at eject time, the
    re-submitted stream keeps its original trace_id, the fleet stamps a
    "migrate" instant with resumed_from=<dead replica>, and the merged
    trace document carries ONE connected tree per request with spans
    from BOTH the dead and the surviving replica. The probationary
    replacement's half-open traffic leaves a "probe" instant on the
    same timeline."""
    import json

    from kubeml_tpu.serve.engine import DecodeEngine
    from kubeml_tpu.serve.fleet import ServeFleet
    from kubeml_tpu.serve.service import ServeService
    from kubeml_tpu.utils.trace import (Tracer, TraceSink,
                                        merge_job_trace)

    _model, module, variables = nano
    home = str(tmp_path)

    def make(index):
        engine = DecodeEngine(module, variables, slots=2, page=4)
        return ServeService(
            "fleet-m", engine, max_queue=4, supervise=False,
            tracer=Tracer(),
            trace_sink=TraceSink("fleet-m", f"serve-r{index}",
                                 home=home))

    fleet_tracer = Tracer()
    fleet = ServeFleet(
        "fleet-m", make, autoscale_interval_s=0.0, page_tokens=4,
        replicas_min=2, replicas_max=2, probe_requests=1,
        tracer=fleet_tracer,
        trace_sink=TraceSink("fleet-m", "fleet", home=home),
        fault_plan=[{"kind": "fleet_replica_crash", "replica": 0}])
    fleet.start()
    try:
        victim = fleet._replicas[0]
        prompts = _owned_prompts(fleet, 0, 2)
        tids = [f"t-mig-{i}" for i in range(len(prompts))]
        reqs = [fleet.submit(p, max_new_tokens=8, trace_id=t)
                for p, t in zip(prompts, tids)]
        assert all(r.fleet_replica == 0 for r in reqs)
        assert _wait(lambda: victim.engine.active() >= 1)
        actions = fleet.supervise_once()
        assert "eject" in actions and "failover_migrate" in actions
        for r in reqs:
            assert r.wait(120) and r.outcome == "ok", (r.outcome,
                                                       r.error)
            assert r.fleet_replica != 0

        # the fleet's own timeline: a "migrate" instant per stream,
        # carrying the ORIGINAL trace_id and the stitch pointer
        for r, tid in zip(reqs, tids):
            (mig,) = [e for e in fleet_tracer.events()
                      if e["name"] == "migrate"
                      and e["args"].get("trace_id") == tid]
            assert mig["args"]["resumed_from"] == 0
            assert mig["args"]["replica"] == r.fleet_replica
            assert mig["args"]["parent"] == "generate"

        # probation: the replacement's half-open probe rides the same
        # span plumbing
        rp = fleet.submit(prompts[0], max_new_tokens=2,
                          trace_id="t-probe")
        assert rp.wait(120) and rp.outcome == "ok"
        probe = [e for e in fleet_tracer.events()
                 if e["name"] == "probe"
                 and e["args"].get("trace_id") == "t-probe"]
        assert probe, 'no "probe" instant on the fleet timeline'
        assert probe[0]["args"]["parent"] == "generate"

        # flush every surviving writer and merge: the dead replica's
        # file was already forced out by the eject path
        for svc in fleet.replicas():
            svc.flush_trace()
        fleet._flush_trace(force=True)
        merged = merge_job_trace("fleet-m", home=home)
        events = merged["traceEvents"]

        # the dead replica's sink holds the first half of each tree
        dead = [e for e in events
                if e.get("args", {}).get("trace_id") in tids]
        assert dead, "migrated requests left no merged events"
        for tid in tids:
            evs = [e for e in events
                   if e.get("args", {}).get("trace_id") == tid]
            names = {e["name"] for e in evs}
            # spans from the DEAD replica (admission on replica 0
            # happened before the kill)...
            assert "queue_wait" in names or "admit" in names
            # ...and from the SURVIVOR (the request went terminal
            # there, emitting the tree's root)
            assert "generate" in names
            assert "finish" in names
            assert "migrate" in names and "route" in names
            # one connected tree: exactly one root, everything else
            # parented to it
            roots = [e for e in evs if e["name"] == "generate"]
            assert len(roots) == 1
            for e in evs:
                assert e["name"] == "generate" \
                    or e["args"].get("parent") == "generate", e

        # both halves really came from different replica sink files
        import glob
        import os
        r0_files = glob.glob(os.path.join(
            home, "**", "serve-r0-*.trace.json"), recursive=True)
        assert len(r0_files) == 1
        with open(r0_files[0]) as f:
            r0_events = json.load(f)["traceEvents"]
        assert any(e.get("args", {}).get("trace_id") in tids
                   for e in r0_events)
        survivor_files = glob.glob(os.path.join(
            home, "**", "serve-r1-*.trace.json"), recursive=True)
        assert len(survivor_files) == 1
        with open(survivor_files[0]) as f:
            r1_events = json.load(f)["traceEvents"]
        assert any(e.get("args", {}).get("trace_id") in tids
                   for e in r1_events)
    finally:
        fleet.stop(grace_s=0.0)


# ------------------------------------------------------------ edge cases

def test_all_replicas_ejected_fails_fast_with_probation_retry_after(nano):
    """Satellite: when the LAST replica is ejected the router must not
    spin retry-once against an empty ring — submit fails fast 503 with
    a probation-aware Retry-After once the replacement's probe quota is
    spoken for, and the ring heals through the normal rejoin path."""
    from kubeml_tpu.serve.slots import ServeDraining

    _model, module, variables = nano
    fleet = _fleet(module, variables, replicas_min=1, replicas_max=1,
                   probe_requests=1,
                   fault_plan=[{"kind": "fleet_replica_crash"}])
    fleet.start()
    try:
        r = fleet.submit([5, 6, 7], max_new_tokens=3)
        assert r.wait(120) and r.outcome == "ok"
        actions = fleet.supervise_once()
        assert "eject" in actions
        snap = fleet.snapshot()
        assert snap["fleet_replicas"] == 0       # ring is empty
        assert snap["fleet_probation"] == 1      # replacement half-open

        # the replacement's single probe slot takes one stream...
        probe = fleet.submit([5, 6, 7], max_new_tokens=3)
        assert probe.wait(120) and probe.outcome == "ok"
        # ...and with probe quota exhausted, submit fails FAST: 503
        with pytest.raises(ServeDraining) as exc:
            fleet.submit([9, 10, 11], max_new_tokens=3)
        assert "all replicas ejected" in str(exc.value)
        assert exc.value.retry_after_s >= 1.0
        # the reaped probe graduates the replacement; service resumes
        assert "probe_rejoin" in fleet.supervise_once()
        r = fleet.submit([9, 10, 11], max_new_tokens=3)
        assert r.wait(120) and r.outcome == "ok"
    finally:
        fleet.stop(grace_s=0.0)


def test_stale_session_remaps_through_ring_after_ejection(nano):
    """Satellite: a sticky session pointing at an ejected replica is a
    stale LRU entry, not an error — the next submit with that session
    re-resolves through the ring onto a live replica."""
    _model, module, variables = nano
    fleet = _fleet(module, variables, replicas_min=2, replicas_max=2,
                   probe_requests=1,
                   fault_plan=[{"kind": "fleet_replica_crash",
                                "replica": 0}])
    fleet.start()
    try:
        prompt = _owned_prompts(fleet, 0, 1)[0]
        r = fleet.submit(prompt, max_new_tokens=3, session="s1")
        assert r.wait(120) and r.outcome == "ok"
        assert r.fleet_replica == 0
        assert "eject" in fleet.supervise_once()
        # ejection purges sessions; simulate the worst case anyway: a
        # stale entry that somehow still names the dead replica
        with fleet._lock:
            assert "s1" not in fleet._sessions   # purged on eject
            fleet._sessions["s1"] = 0
        r = fleet.submit(prompt, max_new_tokens=3, session="s1")
        assert r.wait(120) and r.outcome == "ok"
        assert r.fleet_replica != 0
        with fleet._lock:
            assert fleet._sessions["s1"] == r.fleet_replica
    finally:
        fleet.stop(grace_s=0.0)


def test_migration_budget_exhausts_into_clean_terminal_error(nano):
    """A stream that has already moved MIGRATION_BUDGET times is NOT
    re-prefilled again on the next ejection — it finishes with a
    terminal error naming the budget, instead of ping-ponging KV work
    across a flapping fleet forever."""
    from kubeml_tpu.serve.fleet import MIGRATION_BUDGET

    _model, module, variables = nano
    fleet = _fleet(module, variables, replicas_min=2, replicas_max=2,
                   probe_requests=1, slots=2, max_queue=4,
                   fault_plan=[{"kind": "fleet_replica_crash",
                                "replica": 0}])
    fleet.start()
    try:
        victim = fleet._replicas[0]
        doomed, fine = _owned_prompts(fleet, 0, 2)
        r_doomed = fleet.submit(doomed, max_new_tokens=16)
        r_fine = fleet.submit(fine, max_new_tokens=16)
        assert _wait(lambda: victim.engine.active() >= 1)
        r_doomed.migrations = MIGRATION_BUDGET      # already moved N times
        actions = fleet.supervise_once()
        assert "eject" in actions
        assert r_doomed.wait(120) and r_doomed.outcome == "error"
        assert "migration budget exhausted" in r_doomed.error
        # its neighbour still migrates and finishes bit-identically
        assert r_fine.wait(120) and r_fine.outcome == "ok"
        np.testing.assert_array_equal(
            r_fine.tokens, _solo_tokens(module, variables, fine, 16))
    finally:
        fleet.stop(grace_s=0.0)


# ------------------------------------------------------------- telemetry

def test_prefix_deltas_rebaseline_across_replica_restart_epoch(nano):
    """Satellite: a watchdog-rebuilt engine restarts its prefix
    counters at zero; the fleet snapshot must re-baseline per replica
    EPOCH instead of publishing negative deltas or double-counting."""
    _model, module, variables = nano
    fleet = _fleet(module, variables, replicas_min=1, replicas_max=1)
    fleet.start()
    try:
        # silence background publishes so only OUR snapshot calls
        # consume the deltas (deterministic cursors)
        for svc in fleet.replicas():
            svc.health_cb = None
        r = fleet.submit([5, 6, 7, 8, 9], max_new_tokens=3)
        assert r.wait(120) and r.outcome == "ok"
        fleet.snapshot()                      # absorb the first round
        r = fleet.submit([5, 6, 7, 8, 9], max_new_tokens=3)
        assert r.wait(120) and r.outcome == "ok"
        snap1 = fleet.snapshot()
        assert snap1["fleet_replica_prefix_hits"]["0"] >= 1
        total1 = fleet._retired["prefix_hits"] + sum(
            int(e.stats["prefix_hits"]) for _i, e in fleet.engines())

        svc = fleet._replicas[0]
        assert svc.force_restart("test epoch bump") == 1
        # the rebuilt engine's counters are back at zero: without the
        # epoch re-baseline this snapshot would publish NEGATIVE deltas
        r = fleet.submit([5, 6, 7, 8, 9], max_new_tokens=3)
        assert r.wait(120) and r.outcome == "ok"
        snap2 = fleet.snapshot()
        for d in list(snap2["fleet_replica_prefix_hits"].values()) + \
                list(snap2["fleet_replica_prefix_misses"].values()):
            assert d >= 0, snap2
        total2 = fleet._retired["prefix_hits"] + sum(
            int(e.stats["prefix_hits"]) for _i, e in fleet.engines())
        assert total2 >= total1      # lifetime totals stay monotone
    finally:
        fleet.stop(grace_s=0.0)


def test_fleet_fault_counter_families_pass_metrics_lint():
    """The five new kubeml_serve_fleet_* families advance by delta from
    the snapshot, survive a republish, render a lint-clean exposition,
    and clear with the model."""
    from kubeml_tpu.metrics.prom import MetricsRegistry
    from tools.check_metrics import validate_exposition

    reg = MetricsRegistry()
    snap = {"fleet_replicas": 2, "fleet_ejections_total": 1,
            "fleet_failovers_total": 1,
            "fleet_migrated_streams_total": 3,
            "fleet_probes_total": 2, "fleet_hedges_total": 1}
    reg.update_fleet("m1", snap)
    reg.update_fleet("m1", snap)      # republish: no double count
    text = reg.exposition()
    assert 'kubeml_serve_fleet_ejections_total{model="m1"} 1' in text
    assert 'kubeml_serve_fleet_failovers_total{model="m1"} 1' in text
    assert ('kubeml_serve_fleet_migrated_streams_total'
            '{model="m1"} 3') in text
    assert 'kubeml_serve_fleet_probes_total{model="m1"} 2' in text
    assert 'kubeml_serve_fleet_hedges_total{model="m1"} 1' in text
    assert validate_exposition(text) == []
    reg.clear_serve("m1")
    assert 'model="m1"' not in reg.exposition()


def test_fleet_degraded_health_rule_fires_on_in_window_ejection():
    """Warning when fleet_ejections_total grew within the window; a
    steady republish has no in-window delta; solo-serve samples carry
    no fleet_* fields and never fire."""
    from kubeml_tpu.control.health import HealthEvaluator

    ev = HealthEvaluator()
    assert not [f for f in ev.observe(
        {"job_id": "serve:m", "fleet_ejections_total": 0})
        if f["rule"] == "fleet_degraded"]
    fired = [f for f in ev.observe(
        {"job_id": "serve:m", "fleet_ejections_total": 1,
         "fleet_migrated_streams_total": 3, "fleet_probation": 1})
        if f["rule"] == "fleet_degraded"]
    assert fired and fired[0]["severity"] == "warning"
    assert "ejected within the sample window" in fired[0]["detail"]
    assert "fleet is degraded" in fired[0]["detail"]

    solo = HealthEvaluator()
    assert not [f for f in solo.observe(
        {"job_id": "serve:n", "serve_active_slots": 1})
        if f["rule"] == "fleet_degraded"]


def test_top_renders_fleet_faults_line():
    from kubeml_tpu.cli.main import _render_top

    doc = {"id": "serve:m1", "state": "healthy", "reasons": [],
           "latest": {"serve_active_slots": 1, "serve_slot_cap": 8,
                      "serve_queue_depth": 0, "serve_queue_cap": 16,
                      "serve_kv_page_utilization": 0.25,
                      "serve_rejected_total": 0,
                      "fleet_replicas": 3, "fleet_replicas_min": 1,
                      "fleet_replicas_max": 4, "fleet_draining": 0,
                      "fleet_spills_total": 0,
                      "fleet_router_retries_total": 0,
                      "fleet_cold_starts_total": 0,
                      "fleet_grows_total": 0, "fleet_shrinks_total": 0,
                      "fleet_scale_to_zero_total": 0,
                      "fleet_ejections_total": 1,
                      "fleet_failovers_total": 1,
                      "fleet_migrated_streams_total": 4,
                      "fleet_probes_total": 2, "fleet_hedges_total": 1,
                      "fleet_probation": 1}}
    out = _render_top(doc)
    assert "fleet faults: ejections 1" in out
    assert "failovers 1" in out and "migrated 4" in out
    assert "probes 2" in out and "hedges 1" in out
    assert "probation 1" in out
    # an old snapshot without the fault fields renders no faults line
    del doc["latest"]["fleet_ejections_total"]
    assert "fleet faults:" not in _render_top(doc)


# ------------------------------------------------------------------ lint

def test_fault_lint_fleet_kind_coverage_passes_on_this_repo():
    import tools.check_fault_tests as lint
    assert lint.main(["check_fault_tests"]) == 0


def test_fault_lint_fleet_kind_coverage_self_test(tmp_path):
    """The FLEET_KINDS coverage rule parses the declaration site,
    demands the QUOTED kind on an assert line, and fails loudly when
    the tuple goes missing in a refactor."""
    import tools.check_fault_tests as lint

    root = tmp_path
    (root / "kubeml_tpu").mkdir()
    (root / "tests").mkdir()
    faults = root / "kubeml_tpu" / "faults.py"
    faults.write_text('SERVE_KINDS = ()\n'
                      'FLEET_KINDS = ("zz_boom", "zz_wedge")\n'
                      'CONTROL_KINDS = ()\n')
    tests_dir = str(root / "tests")

    assert lint.fleet_kinds(str(faults)) == ["zz_boom", "zz_wedge"]
    assert lint.unasserted_fleet_kinds(str(faults), tests_dir) == \
        ["zz_boom", "zz_wedge"]
    assert lint.main(["x", tests_dir]) == 1

    # a mention in a plan spec (no assert) does NOT count as coverage
    t = root / "tests" / "test_zz.py"
    t.write_text('plan = [{"kind": "zz_boom"}]\nkinds = ["zz_wedge"]\n')
    assert lint.unasserted_fleet_kinds(str(faults), tests_dir) == \
        ["zz_boom", "zz_wedge"]

    t.write_text('kinds = ["zz_boom", "zz_wedge"]\n'
                 'assert "zz_boom" in kinds\n'
                 'assert "zz_wedge" in kinds\n')
    assert lint.unasserted_fleet_kinds(str(faults), tests_dir) == []
    assert lint.main(["x", tests_dir]) == 0

    # a miswired tuple (faults.py refactor) fails loudly, not silently
    faults.write_text('SERVE_KINDS = ()\n')
    with pytest.raises(SystemExit):
        lint.fleet_kinds(str(faults))


def test_fleet_path_lint_covers_the_fault_paths():
    """The four failure-domain paths are FLEET_PATH_VARIANTS entries,
    so tools/check_fleet_paths.py now demands a quoted-name identity
    test for each — this file is that coverage."""
    import os

    import tools.check_fleet_paths as lint

    root = os.path.dirname(os.path.dirname(os.path.abspath(
        lint.__file__)))
    names = lint.path_variants(
        os.path.join(root, "kubeml_tpu", "serve", "fleet.py"))
    assert {"eject", "failover_migrate", "probe_rejoin",
            "hedge"} <= set(names)
    assert lint.main(["check_fleet_paths"]) == 0
