"""Throughput policy: exact parity with ml/pkg/scheduler/policy.go."""

from kubeml_tpu.api.types import TrainOptions, TrainRequest, TrainTask
from kubeml_tpu.control.policy import ThroughputBasedPolicy


def make_task(parallelism, elapsed, requested=5):
    req = TrainRequest("m", 32, 5, "d", 0.1,
                       options=TrainOptions(default_parallelism=requested))
    return TrainTask(job_id="job1", parameters=req, parallelism=parallelism,
                     elapsed_time_s=elapsed)


def test_first_call_returns_requested_parallelism():
    pol = ThroughputBasedPolicy()
    p, is_new = pol.calculate_parallelism(make_task(0, -1, requested=3))
    assert (p, is_new) == (3, True)


def test_second_call_always_scales_up():
    pol = ThroughputBasedPolicy()
    pol.calculate_parallelism(make_task(0, -1))
    p, is_new = pol.calculate_parallelism(make_task(5, 100.0))
    assert (p, is_new) == (6, False)


def test_faster_epoch_scales_up():
    pol = ThroughputBasedPolicy()
    pol.calculate_parallelism(make_task(0, -1))
    pol.calculate_parallelism(make_task(5, 100.0))   # sets ref time 100
    p, _ = pol.calculate_parallelism(make_task(6, 104.0))  # <= 105
    assert p == 7


def test_much_slower_epoch_scales_down():
    pol = ThroughputBasedPolicy()
    pol.calculate_parallelism(make_task(0, -1))
    pol.calculate_parallelism(make_task(5, 100.0))
    p, _ = pol.calculate_parallelism(make_task(6, 121.0))  # >= 120
    assert p == 5


def test_between_thresholds_keeps_parallelism_and_reference_time():
    pol = ThroughputBasedPolicy()
    pol.calculate_parallelism(make_task(0, -1))
    pol.calculate_parallelism(make_task(5, 100.0))
    p, _ = pol.calculate_parallelism(make_task(6, 110.0))  # in between
    assert p == 6
    # the reference time must STILL be 100 (not refreshed on keep):
    # 104 <= 100*1.05 -> scale up
    p, _ = pol.calculate_parallelism(make_task(6, 104.0))
    assert p == 7


def test_scale_down_clamped_at_one():
    pol = ThroughputBasedPolicy()
    pol.calculate_parallelism(make_task(0, -1))
    pol.calculate_parallelism(make_task(1, 100.0))
    p, _ = pol.calculate_parallelism(make_task(1, 500.0))
    assert p == 1


def test_task_finished_clears_state():
    pol = ThroughputBasedPolicy()
    pol.calculate_parallelism(make_task(0, -1, requested=4))
    pol.task_finished("job1")
    p, is_new = pol.calculate_parallelism(make_task(0, -1, requested=4))
    assert (p, is_new) == (4, True)
