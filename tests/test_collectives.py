"""ring_psum: the ppermute ring all-reduce used for compressed merges on
meshes whose inner axes stay Auto (a partially-manual sub-f32 lax.psum
is a fatal partitioner miscompile — parallel/collectives.py)."""

import jax
from kubeml_tpu import compat
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from kubeml_tpu.parallel.collectives import ring_psum
from kubeml_tpu.parallel.mesh import DATA_AXIS, make_mesh


def run_ring(mesh, x, wire_dtype, **shmap_kw):
    return jax.jit(compat.shard_map(
        lambda v: ring_psum(v, DATA_AXIS, wire_dtype), mesh=mesh,
        in_specs=P(DATA_AXIS), out_specs=P(), check_vma=False,
        **shmap_kw))(jnp.asarray(x))


@pytest.mark.parametrize("n", [37, 64, 1])  # incl. padding + degenerate
def test_ring_matches_psum_f32(mesh8, n):
    x = np.random.RandomState(0).randn(8, n).astype(np.float32)
    out = run_ring(mesh8, x, jnp.float32)
    np.testing.assert_allclose(np.asarray(out)[0], x.sum(axis=0),
                               rtol=1e-5, atol=1e-6)


def test_ring_bf16_wire_tolerance(mesh8):
    x = np.random.RandomState(1).randn(8, 257).astype(np.float32)
    out = run_ring(mesh8, x, jnp.bfloat16)
    np.testing.assert_allclose(np.asarray(out, np.float32)[0],
                               x.sum(axis=0), rtol=5e-2, atol=5e-2)
    # really compressed: not bit-equal to the f32 reduction
    assert not np.allclose(np.asarray(out, np.float32)[0], x.sum(axis=0),
                           rtol=1e-6, atol=0)


def test_ring_on_partially_manual_mesh(mesh4x2):
    """THE case the builtin cannot do: bf16 wire, data manual, model
    Auto. A direct sub-f32 psum here kills the process."""
    x = np.random.RandomState(2).randn(4, 100).astype(np.float32)
    out = run_ring(mesh4x2, x, jnp.bfloat16,
                   axis_names={DATA_AXIS})
    np.testing.assert_allclose(np.asarray(out, np.float32)[0],
                               x.sum(axis=0), rtol=5e-2, atol=5e-2)


def test_ring_single_lane_passthrough():
    mesh = make_mesh(n_data=1, devices=jax.devices()[:1])
    x = np.random.RandomState(3).randn(1, 16).astype(np.float32)
    out = run_ring(mesh, x, jnp.bfloat16)
    np.testing.assert_array_equal(np.asarray(out), x)


@pytest.mark.parametrize("wire", [jnp.bfloat16, jnp.float32])
def test_ring_lane_identity(mesh8, wire):
    """The replicated out-spec contract: EVERY lane must hold the
    bit-identical reduced value, including the 1/D chunk each rank owns
    (which, pre-fix, the owner kept in unrounded f32 while everyone
    else stored the wire-rounded copy)."""
    x = np.random.RandomState(5).randn(8, 193).astype(np.float32)
    per_lane = jax.jit(compat.shard_map(
        lambda v: ring_psum(v, DATA_AXIS, wire)[None],
        mesh=mesh8, in_specs=P(DATA_AXIS), out_specs=P(DATA_AXIS),
        check_vma=False))(jnp.asarray(x))
    out = np.asarray(per_lane, np.float32)            # [8, 193]
    for lane in range(1, 8):
        np.testing.assert_array_equal(out[lane], out[0])


def test_ring_multidim_leaves(mesh8):
    """Weight-shaped (non-flat) leaves reduce correctly through the
    flatten/pad path."""
    x = np.random.RandomState(4).randn(8, 3, 5, 2).astype(np.float32)
    out = run_ring(mesh8, x, jnp.float32)
    np.testing.assert_allclose(np.asarray(out)[0], x.sum(axis=0),
                               rtol=1e-5, atol=1e-6)
