"""Unit tests for shard-assignment math — parity checked against the
reference semantics (python/kubeml/kubeml/util.py:46-81)."""

import math

import pytest

from kubeml_tpu.data.sharding import (
    split_minibatches, get_subset_period, plan_epoch)


class TestSplitMinibatches:
    def test_even_split(self):
        parts = split_minibatches(range(12), 4)
        assert parts == [range(0, 3), range(3, 6), range(6, 9), range(9, 12)]

    def test_uneven_split_first_workers_get_extra(self):
        parts = split_minibatches(range(10), 4)
        assert [len(p) for p in parts] == [3, 3, 2, 2]
        assert parts[0] == range(0, 3)
        assert parts[-1] == range(8, 10)

    def test_more_workers_than_docs(self):
        parts = split_minibatches(range(2), 5)
        assert [len(p) for p in parts] == [1, 1, 0, 0, 0]

    def test_covers_all_docs_disjoint(self):
        for n_docs in (1, 7, 64, 100):
            for n in (1, 2, 3, 5, 8):
                parts = split_minibatches(range(n_docs), n)
                flat = [i for p in parts for i in p]
                assert flat == list(range(n_docs))


class TestSubsetPeriod:
    def test_sparse_avg_whole_shard(self):
        assert get_subset_period(-1, 128, range(5, 25)) == 20

    def test_k_batches_to_docs(self):
        # K=8 batches of 128 samples = 1024 samples = 16 docs of 64
        assert get_subset_period(8, 128, range(0, 100)) == 16
        # ceil: 3 batches of 50 = 150 samples -> ceil(150/64) = 3 docs
        assert get_subset_period(3, 50, range(0, 100)) == 3


class TestPlanEpoch:
    def test_single_worker_sparse(self):
        plan = plan_epoch(num_samples=640, n_workers=1, k=-1, batch_size=64)
        assert len(plan.rounds) == 1
        c = plan.rounds[0].chunks[0]
        assert (c.doc_start, c.doc_end) == (0, 10)
        assert c.num_samples == 640 and c.num_steps == 10

    def test_total_samples_conserved(self):
        for n_samples in (640, 1000, 50000):
            for n in (1, 2, 5, 8):
                for k in (-1, 4, 16):
                    plan = plan_epoch(n_samples, n, k, 32)
                    assert plan.total_samples == n_samples, (n_samples, n, k)

    def test_ragged_workers_masked(self):
        # 10 docs over 4 workers: shards of 3,3,2,2 docs; K=1 batch of 64
        # => period 1 doc => worker 0/1 have 3 rounds, workers 2/3 have 2
        plan = plan_epoch(640, 4, 1, 64)
        assert len(plan.rounds) == 3
        last = plan.rounds[2]
        assert [c.active for c in last.chunks] == [True, True, False, False]
        assert last.active_workers == 2

    def test_partial_final_batch(self):
        # 100 samples, 1 worker, batch 64 -> 2 docs (64 + 36), 2 steps
        plan = plan_epoch(100, 1, -1, 64)
        c = plan.rounds[0].chunks[0]
        assert c.num_samples == 100 and c.num_steps == 2

    def test_steps_match_reference_loader_counts(self):
        # reference: per chunk, DataLoader(len=ceil(chunk_samples/batch))
        plan = plan_epoch(1000, 3, 2, 32)
        for r in plan.rounds:
            for c in r.chunks:
                assert c.num_steps == math.ceil(c.num_samples / 32)

    def test_invalid_args(self):
        with pytest.raises(ValueError):
            plan_epoch(100, 0, 1, 32)
        with pytest.raises(ValueError):
            plan_epoch(100, 1, 1, 0)
